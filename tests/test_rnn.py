"""RNN family tests: NumPy parity, masking, grads, custom-cell fallback.

Reference test model: test/legacy_test/test_rnn_nets.py and
test_rnn_cells.py (NumPy step references, multi-layer/bidirect sweeps).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _sig(x):
    return 1.0 / (1.0 + np.exp(-x))


def np_simple_step(x, h, w_ih, w_hh, b_ih, b_hh, act="tanh"):
    z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    return np.tanh(z) if act == "tanh" else np.maximum(z, 0)


def np_lstm_step(x, h, c, w_ih, w_hh, b_ih, b_hh, w_ho=None):
    g = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    H = g.shape[-1] // 4
    i, f, gg, o = g[:, :H], g[:, H:2*H], g[:, 2*H:3*H], g[:, 3*H:]
    c2 = _sig(f) * c + _sig(i) * np.tanh(gg)
    h2 = _sig(o) * np.tanh(c2)
    if w_ho is not None:
        h2 = h2 @ w_ho
    return h2, c2


def np_gru_step(x, h, w_ih, w_hh, b_ih, b_hh):
    xg = x @ w_ih.T + b_ih
    hg = h @ w_hh.T + b_hh
    H = h.shape[-1]
    r = _sig(xg[:, :H] + hg[:, :H])
    z = _sig(xg[:, H:2*H] + hg[:, H:2*H])
    c = np.tanh(xg[:, 2*H:] + r * hg[:, 2*H:])
    return (h - c) * z + c


def _cell_weights(cell):
    return [np.asarray(p.numpy()) for p in
            (cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh)]


class TestCells:
    def test_simple_cell_parity(self):
        rng = np.random.default_rng(0)
        cell = nn.SimpleRNNCell(8, 12)
        x = rng.standard_normal((4, 8)).astype("float32")
        h = rng.standard_normal((4, 12)).astype("float32")
        y, h2 = cell(paddle.to_tensor(x), paddle.to_tensor(h))
        ref = np_simple_step(x, h, *_cell_weights(cell))
        np.testing.assert_allclose(y.numpy(), ref, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(h2.numpy(), ref, rtol=1e-5, atol=1e-5)

    def test_simple_cell_relu(self):
        rng = np.random.default_rng(1)
        cell = nn.SimpleRNNCell(8, 12, activation="relu")
        x = rng.standard_normal((4, 8)).astype("float32")
        h = rng.standard_normal((4, 12)).astype("float32")
        y, _ = cell(paddle.to_tensor(x), paddle.to_tensor(h))
        ref = np_simple_step(x, h, *_cell_weights(cell), act="relu")
        np.testing.assert_allclose(y.numpy(), ref, rtol=1e-5, atol=1e-5)

    def test_lstm_cell_parity(self):
        rng = np.random.default_rng(2)
        cell = nn.LSTMCell(8, 12)
        x = rng.standard_normal((4, 8)).astype("float32")
        h = rng.standard_normal((4, 12)).astype("float32")
        c = rng.standard_normal((4, 12)).astype("float32")
        y, (h2, c2) = cell(paddle.to_tensor(x),
                           (paddle.to_tensor(h), paddle.to_tensor(c)))
        rh, rc = np_lstm_step(x, h, c, *_cell_weights(cell))
        np.testing.assert_allclose(y.numpy(), rh, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(h2.numpy(), rh, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(c2.numpy(), rc, rtol=1e-5, atol=1e-5)

    def test_gru_cell_parity(self):
        rng = np.random.default_rng(3)
        cell = nn.GRUCell(8, 12)
        x = rng.standard_normal((4, 8)).astype("float32")
        h = rng.standard_normal((4, 12)).astype("float32")
        y, h2 = cell(paddle.to_tensor(x), paddle.to_tensor(h))
        ref = np_gru_step(x, h, *_cell_weights(cell))
        np.testing.assert_allclose(y.numpy(), ref, rtol=1e-5, atol=1e-5)

    def test_cell_default_states(self):
        cell = nn.LSTMCell(8, 12)
        x = paddle.to_tensor(np.zeros((4, 8), "float32"))
        y, (h, c) = cell(x)
        assert y.shape == [4, 12] and h.shape == [4, 12] and c.shape == [4, 12]

    def test_lstm_cell_proj(self):
        rng = np.random.default_rng(4)
        cell = nn.LSTMCell(8, 12, proj_size=5)
        x = rng.standard_normal((4, 8)).astype("float32")
        h = rng.standard_normal((4, 5)).astype("float32")
        c = rng.standard_normal((4, 12)).astype("float32")
        y, (h2, c2) = cell(paddle.to_tensor(x),
                           (paddle.to_tensor(h), paddle.to_tensor(c)))
        w = _cell_weights(cell) + [np.asarray(cell.weight_ho.numpy())]
        rh, rc = np_lstm_step(x, h, c, *w)
        assert y.shape == [4, 5]
        np.testing.assert_allclose(y.numpy(), rh, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(c2.numpy(), rc, rtol=1e-5, atol=1e-5)

    def test_bad_args(self):
        with pytest.raises(ValueError):
            nn.SimpleRNNCell(4, 0)
        with pytest.raises(ValueError):
            nn.SimpleRNNCell(4, 8, activation="gelu")
        with pytest.raises(ValueError):
            nn.LSTMCell(4, 8, proj_size=8)


def _np_unroll(kind, x, states, weights, reverse=False, seq_len=None):
    """NumPy reference loop over [B, T, I]."""
    B, T, _ = x.shape
    order = range(T - 1, -1, -1) if reverse else range(T)
    outs = [None] * T
    for t in order:
        if kind == "lstm":
            h, c = np_lstm_step(x[:, t], states[0], states[1], *weights)
            new = (h, c)
        elif kind == "gru":
            new = (np_gru_step(x[:, t], states[0], *weights),)
        else:
            new = (np_simple_step(x[:, t], states[0], *weights),)
        if seq_len is not None:
            m = (t < seq_len).astype(x.dtype)[:, None]
            new = tuple(m * n + (1 - m) * o for n, o in zip(new, states))
        states = new
        outs[t] = new[0]
    return np.stack(outs, axis=1), states


class TestRNNWrapper:
    @pytest.mark.parametrize("kind", ["simple", "lstm", "gru"])
    def test_parity(self, kind):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((3, 7, 8)).astype("float32")
        if kind == "lstm":
            cell = nn.LSTMCell(8, 10)
            st = (np.zeros((3, 10), "float32"), np.zeros((3, 10), "float32"))
        elif kind == "gru":
            cell = nn.GRUCell(8, 10)
            st = (np.zeros((3, 10), "float32"),)
        else:
            cell = nn.SimpleRNNCell(8, 10)
            st = (np.zeros((3, 10), "float32"),)
        layer = nn.RNN(cell)
        out, fin = layer(paddle.to_tensor(x))
        ref_out, ref_fin = _np_unroll(kind, x, st, _cell_weights(cell))
        np.testing.assert_allclose(out.numpy(), ref_out, rtol=1e-4, atol=1e-4)
        fin_h = fin[0] if kind == "lstm" else fin
        np.testing.assert_allclose(fin_h.numpy(), ref_fin[0],
                                   rtol=1e-4, atol=1e-4)

    def test_reverse(self):
        rng = np.random.default_rng(6)
        x = rng.standard_normal((3, 7, 8)).astype("float32")
        cell = nn.GRUCell(8, 10)
        layer = nn.RNN(cell, is_reverse=True)
        out, fin = layer(paddle.to_tensor(x))
        ref_out, ref_fin = _np_unroll(
            "gru", x, (np.zeros((3, 10), "float32"),),
            _cell_weights(cell), reverse=True)
        np.testing.assert_allclose(out.numpy(), ref_out, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(fin.numpy(), ref_fin[0],
                                   rtol=1e-4, atol=1e-4)

    def test_sequence_length_masking(self):
        rng = np.random.default_rng(7)
        x = rng.standard_normal((3, 7, 8)).astype("float32")
        seq = np.array([7, 3, 5], "int32")
        cell = nn.LSTMCell(8, 10)
        layer = nn.RNN(cell)
        out, fin = layer(paddle.to_tensor(x),
                         sequence_length=paddle.to_tensor(seq))
        st = (np.zeros((3, 10), "float32"), np.zeros((3, 10), "float32"))
        ref_out, ref_fin = _np_unroll("lstm", x, st, _cell_weights(cell),
                                      seq_len=seq)
        np.testing.assert_allclose(fin[0].numpy(), ref_fin[0],
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(fin[1].numpy(), ref_fin[1],
                                   rtol=1e-4, atol=1e-4)

    def test_time_major(self):
        rng = np.random.default_rng(8)
        x = rng.standard_normal((7, 3, 8)).astype("float32")
        cell = nn.GRUCell(8, 10)
        out, fin = nn.RNN(cell, time_major=True)(paddle.to_tensor(x))
        ref_out, _ = _np_unroll("gru", np.swapaxes(x, 0, 1),
                                (np.zeros((3, 10), "float32"),),
                                _cell_weights(cell))
        np.testing.assert_allclose(out.numpy(),
                                   np.swapaxes(ref_out, 0, 1),
                                   rtol=1e-4, atol=1e-4)

    def test_birnn_concat(self):
        rng = np.random.default_rng(9)
        x = rng.standard_normal((3, 5, 8)).astype("float32")
        cf, cb = nn.GRUCell(8, 6), nn.GRUCell(8, 6)
        out, (sf, sb) = nn.BiRNN(cf, cb)(paddle.to_tensor(x))
        assert out.shape == [3, 5, 12]
        fw, _ = _np_unroll("gru", x, (np.zeros((3, 6), "float32"),),
                           _cell_weights(cf))
        bw, _ = _np_unroll("gru", x, (np.zeros((3, 6), "float32"),),
                           _cell_weights(cb), reverse=True)
        np.testing.assert_allclose(out.numpy(),
                                   np.concatenate([fw, bw], -1),
                                   rtol=1e-4, atol=1e-4)


class TestGrads:
    def test_lstm_fd_grad(self):
        """FD check of d(sum(out))/d(weight_ih) through the fused scan."""
        rng = np.random.default_rng(10)
        x = rng.standard_normal((2, 5, 4)).astype("float64").astype("float32")
        cell = nn.LSTMCell(4, 6)
        layer = nn.RNN(cell)

        def loss_for(w_val):
            saved = cell.weight_ih._value
            cell.weight_ih._value = paddle.to_tensor(w_val)._value
            out, _ = layer(paddle.to_tensor(x))
            val = float(out.sum().numpy())
            cell.weight_ih._value = saved
            return val

        out, _ = layer(paddle.to_tensor(x))
        loss = out.sum()
        loss.backward()
        g = np.asarray(cell.weight_ih.grad.numpy())

        w0 = np.asarray(cell.weight_ih.numpy())
        eps = 1e-2
        for idx in [(0, 0), (3, 2), (11, 1)]:
            wp = w0.copy(); wp[idx] += eps
            wm = w0.copy(); wm[idx] -= eps
            fd = (loss_for(wp) - loss_for(wm)) / (2 * eps)
            np.testing.assert_allclose(g[idx], fd, rtol=2e-2, atol=2e-2)

    def test_gru_grad_flows_to_input(self):
        rng = np.random.default_rng(11)
        x = paddle.to_tensor(
            rng.standard_normal((2, 5, 4)).astype("float32"))
        x.stop_gradient = False
        out, _ = nn.GRU(4, 6)(x)
        out.sum().backward()
        assert x.grad is not None
        assert np.isfinite(x.grad.numpy()).all()
        assert np.abs(x.grad.numpy()).sum() > 0


class TestMultiLayer:
    def test_stacked_lstm_matches_manual(self):
        rng = np.random.default_rng(12)
        x = rng.standard_normal((3, 6, 8)).astype("float32")
        net = nn.LSTM(8, 10, num_layers=2)
        net.eval()
        out, (h, c) = net(paddle.to_tensor(x))
        # layer 0 then layer 1, via the per-layer cells
        c0 = net[0].cell
        c1 = net[1].cell
        o1, s1 = _np_unroll("lstm", x,
                            (np.zeros((3, 10), "float32"),) * 2,
                            _cell_weights(c0))
        o2, s2 = _np_unroll("lstm", o1,
                            (np.zeros((3, 10), "float32"),) * 2,
                            _cell_weights(c1))
        np.testing.assert_allclose(out.numpy(), o2, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(h.numpy()[0], s1[0], rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(h.numpy()[1], s2[0], rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(c.numpy()[1], s2[1], rtol=1e-4, atol=1e-4)

    def test_bidirect_state_layout(self):
        x = paddle.to_tensor(np.zeros((3, 6, 8), "float32"))
        net = nn.SimpleRNN(8, 10, num_layers=2, direction="bidirect")
        out, h = net(x)
        assert out.shape == [3, 6, 20]
        assert h.shape == [4, 3, 10]  # L*D = 4

    def test_initial_states_roundtrip(self):
        rng = np.random.default_rng(13)
        x = paddle.to_tensor(rng.standard_normal((3, 6, 8)).astype("float32"))
        h0 = paddle.to_tensor(rng.standard_normal((2, 3, 10)).astype("float32"))
        c0 = paddle.to_tensor(rng.standard_normal((2, 3, 10)).astype("float32"))
        net = nn.LSTM(8, 10, num_layers=2)
        out, (h, c) = net(x, (h0, c0))
        assert h.shape == [2, 3, 10] and c.shape == [2, 3, 10]

    def test_param_aliases(self):
        net = nn.LSTM(8, 10, num_layers=2, direction="bidirectional")
        assert net.weight_ih_l0 is net[0].cell_fw.weight_ih
        assert net.weight_hh_l0_reverse is net[0].cell_bw.weight_hh
        assert net.bias_ih_l1 is net[1].cell_fw.bias_ih
        # aliases are the same objects, not copies, and not duplicated in
        # state_dict
        sd = net.state_dict()
        assert not any(k.startswith("weight_ih_l") for k in sd)

    def test_param_aliases_proj_and_no_bias(self):
        # proj_size adds weight_ho to parameters(); aliases must not shift
        net = nn.LSTM(8, 10, num_layers=2, proj_size=4)
        assert net.weight_ih_l1 is net[1].cell.weight_ih
        assert net.weight_ih_l1.shape == [40, 4]
        # bias attr False still creates (frozen) bias params; aliases skip
        # them without misaligning the rest
        net2 = nn.LSTM(8, 10, num_layers=2, bias_ih_attr=False)
        assert not hasattr(net2, "bias_ih_l0")
        assert net2.bias_hh_l0 is net2[0].cell.bias_hh
        assert net2.weight_ih_l1 is net2[1].cell.weight_ih

    def test_lstm_cell_proj_frozen_hh(self):
        cell = nn.LSTMCell(8, 12, proj_size=5, weight_hh_attr=False)
        assert cell.weight_ho is not None and cell.weight_ho.stop_gradient
        x = paddle.to_tensor(np.zeros((2, 8), "float32"))
        y, _ = cell(x)
        assert y.shape == [2, 5]

    def test_masked_outputs_unmasked_states_masked(self):
        """Step outputs stay raw past seq_len; only states freeze — and the
        fused-scan path must agree with the eager loop."""
        from paddle_tpu.nn.layer.rnn import _rnn_eager_loop

        rng = np.random.default_rng(20)
        x = rng.standard_normal((2, 5, 4)).astype("float32")
        seq = np.array([5, 2], "int32")
        cell = nn.GRUCell(4, 6)
        out_s, fin_s = nn.RNN(cell)(paddle.to_tensor(x),
                                    sequence_length=paddle.to_tensor(seq))
        out_e, fin_e = _rnn_eager_loop(
            cell, paddle.to_tensor(x), cell.get_initial_states(
                paddle.to_tensor(x)), paddle.to_tensor(seq), False, False, {})
        np.testing.assert_allclose(out_s.numpy(), out_e.numpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(fin_s.numpy(), fin_e.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_dropout_between_layers_trains_differently(self):
        rng = np.random.default_rng(14)
        x = paddle.to_tensor(rng.standard_normal((3, 6, 8)).astype("float32"))
        net = nn.GRU(8, 10, num_layers=2, dropout=0.5)
        net.train()
        a = net(x)[0].numpy()
        b = net(x)[0].numpy()
        assert not np.allclose(a, b)  # dropout resamples across calls
        net.eval()
        c = net(x)[0].numpy()
        d = net(x)[0].numpy()
        np.testing.assert_allclose(c, d)

    def test_proj_lstm_net(self):
        x = paddle.to_tensor(np.zeros((3, 6, 8), "float32"))
        net = nn.LSTM(8, 10, num_layers=2, proj_size=4)
        out, (h, c) = net(x)
        assert out.shape == [3, 6, 4]
        assert h.shape == [2, 3, 4] and c.shape == [2, 3, 10]


class _DoubleCell(nn.RNNCellBase):
    """Custom user cell: traced into the fused scan via module-state swap."""

    def __init__(self, size):
        super().__init__()
        self.lin = nn.Linear(size, size)
        self.hidden_size = size
        self.input_size = size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, x, states=None):
        if states is None:
            states = self.get_initial_states(x, self.state_shape)
        h = self.lin(x) + states * 0.5
        return h, h


class TestCustomCell:
    def test_custom_cell_scan(self):
        rng = np.random.default_rng(15)
        x = rng.standard_normal((2, 5, 4)).astype("float32")
        cell = _DoubleCell(4)
        out, fin = nn.RNN(cell)(paddle.to_tensor(x))
        w = np.asarray(cell.lin.weight.numpy())
        b = np.asarray(cell.lin.bias.numpy())
        h = np.zeros((2, 4), "float32")
        for t in range(5):
            h = x[:, t] @ w + b + h * 0.5
        np.testing.assert_allclose(fin.numpy(), h, rtol=1e-4, atol=1e-4)

    def test_custom_cell_grad(self):
        rng = np.random.default_rng(16)
        x = paddle.to_tensor(rng.standard_normal((2, 5, 4)).astype("float32"))
        cell = _DoubleCell(4)
        out, _ = nn.RNN(cell)(x)
        out.sum().backward()
        assert cell.lin.weight.grad is not None
        assert np.abs(cell.lin.weight.grad.numpy()).sum() > 0


class TestSeq2SeqSmoke:
    def test_encoder_decoder_trains(self):
        """Tiny GRU encoder-decoder: loss decreases over a few steps."""
        rng = np.random.default_rng(17)
        vocab, hidden, B, T = 12, 16, 4, 6
        emb = nn.Embedding(vocab, hidden)
        enc = nn.GRU(hidden, hidden)
        dec = nn.GRU(hidden, hidden)
        head = nn.Linear(hidden, vocab)
        params = (list(emb.parameters()) + list(enc.parameters())
                  + list(dec.parameters()) + list(head.parameters()))
        opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=params)
        src = paddle.to_tensor(rng.integers(0, vocab, (B, T)).astype("int64"))
        tgt = paddle.to_tensor(rng.integers(0, vocab, (B, T)).astype("int64"))
        losses = []
        for _ in range(8):
            _, h = enc(emb(src))
            out, _ = dec(emb(tgt), h)
            logits = head(out)
            loss = paddle.nn.functional.cross_entropy(
                logits.reshape([-1, vocab]), tgt.reshape([-1]))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.8, losses


class TestRNNUnderTrace:
    def test_lstm_lowers_to_scan_not_unroll(self):
        """Under to_static / compiled train steps the RNN must lower to ONE
        lax.scan per (layer, direction) — never an unrolled per-step
        trace (64 steps here would mean hundreds of dot_generals)."""
        import jax

        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lstm = nn.LSTM(8, 16, num_layers=2)
                self.fc = nn.Linear(16, 4)

            def forward(self, x):
                out, _ = self.lstm(x)
                return self.fc(out[:, -1])

        net = Net()
        x = paddle.to_tensor(
            np.random.default_rng(0).standard_normal((2, 64, 8))
            .astype("float32"))
        params = {k: p._value for k, p in net.named_parameters()}

        def pure(xv):
            from paddle_tpu.jit import functional_call

            out, _ = functional_call(net, params, {}, [xv])
            return out

        jaxpr = str(jax.make_jaxpr(pure)(x._value))
        assert jaxpr.count("scan[") >= 2
        assert jaxpr.count("dot_general") < 64

        # and to_static output parity with eager
        eager = net(x).numpy()
        snet = paddle.jit.to_static(Net())
        snet.set_state_dict(net.state_dict())
        np.testing.assert_allclose(snet(x).numpy(), eager, rtol=1e-4,
                                   atol=1e-5)
