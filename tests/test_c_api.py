"""C inference API (native/c_api.cc — reference analog:
paddle/fluid/inference/capi_exp/pd_inference_api.h, the paddle_inference_c
library C/Go deployments link against).

Two integration levels:
- ctypes inside this process (attach-to-running-interpreter path),
- a standalone C program compiled at test time (embed-an-interpreter path).
"""

import ctypes
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB = os.path.join(_REPO, "native", "libpaddle_tpu_c.so")


def _build_lib():
    if not os.path.exists(_LIB):
        subprocess.run(["make", "-C", os.path.join(_REPO, "native"),
                        "c_api"], check=True, capture_output=True)
    return _LIB


def _save_tiny_model(tmp_path):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=32)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = np.random.RandomState(0).randint(0, 64, (2, 8)).astype(np.int32)
    ref = m(paddle.to_tensor(ids)).numpy()
    prefix = os.path.join(str(tmp_path), "gpt")
    paddle.jit.save(m, prefix,
                    input_spec=[paddle.jit.InputSpec([2, 8], "int32")])
    return prefix + ".pdmodel", ids, ref


def test_c_api_ctypes_roundtrip(tmp_path):
    lib = ctypes.CDLL(_build_lib())
    lib.PD_ConfigCreate.restype = ctypes.c_void_p
    lib.PD_PredictorCreate.restype = ctypes.c_void_p
    lib.PD_PredictorCreate.argtypes = [ctypes.c_void_p]
    lib.PD_ConfigSetModel.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p]
    lib.PD_PredictorGetInputNum.restype = ctypes.c_size_t
    lib.PD_PredictorGetInputNum.argtypes = [ctypes.c_void_p]
    lib.PD_PredictorGetInputNameByIndex.restype = ctypes.c_char_p
    lib.PD_PredictorGetInputNameByIndex.argtypes = [ctypes.c_void_p,
                                                    ctypes.c_int]
    lib.PD_PredictorGetInputHandle.restype = ctypes.c_void_p
    lib.PD_PredictorGetInputHandle.argtypes = [ctypes.c_void_p,
                                               ctypes.c_char_p]
    lib.PD_TensorReshape.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                     ctypes.POINTER(ctypes.c_int32)]
    lib.PD_TensorCopyFromCpuInt32.argtypes = [ctypes.c_void_p,
                                              ctypes.c_void_p]
    lib.PD_PredictorRun.restype = ctypes.c_int32
    lib.PD_PredictorRun.argtypes = [ctypes.c_void_p]
    lib.PD_PredictorGetOutputNum.restype = ctypes.c_size_t
    lib.PD_PredictorGetOutputNum.argtypes = [ctypes.c_void_p]
    lib.PD_PredictorGetOutputNameByIndex.restype = ctypes.c_char_p
    lib.PD_PredictorGetOutputNameByIndex.argtypes = [ctypes.c_void_p,
                                                     ctypes.c_int]
    lib.PD_PredictorGetOutputHandle.restype = ctypes.c_void_p
    lib.PD_PredictorGetOutputHandle.argtypes = [ctypes.c_void_p,
                                                ctypes.c_char_p]
    lib.PD_TensorGetNumDims.restype = ctypes.c_size_t
    lib.PD_TensorGetNumDims.argtypes = [ctypes.c_void_p]
    lib.PD_TensorGetShape.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_int32)]
    lib.PD_TensorCopyToCpuFloat.argtypes = [ctypes.c_void_p,
                                            ctypes.c_void_p]
    lib.PD_TensorDestroy.argtypes = [ctypes.c_void_p]
    lib.PD_PredictorDestroy.argtypes = [ctypes.c_void_p]
    lib.PD_ConfigDestroy.argtypes = [ctypes.c_void_p]

    model_path, ids, ref = _save_tiny_model(tmp_path)

    cfg = lib.PD_ConfigCreate()
    lib.PD_ConfigSetModel(cfg, model_path.encode(), b"")
    pred = lib.PD_PredictorCreate(cfg)
    assert pred, "PD_PredictorCreate failed"

    n_in = lib.PD_PredictorGetInputNum(pred)
    assert n_in == 1
    name = lib.PD_PredictorGetInputNameByIndex(pred, 0)
    h = lib.PD_PredictorGetInputHandle(pred, name)
    shape = (ctypes.c_int32 * 2)(2, 8)
    lib.PD_TensorReshape(h, 2, shape)
    buf = np.ascontiguousarray(ids)
    lib.PD_TensorCopyFromCpuInt32(h, buf.ctypes.data_as(ctypes.c_void_p))

    assert lib.PD_PredictorRun(pred) == 1

    assert lib.PD_PredictorGetOutputNum(pred) == 1
    oname = lib.PD_PredictorGetOutputNameByIndex(pred, 0)
    oh = lib.PD_PredictorGetOutputHandle(pred, oname)
    nd = lib.PD_TensorGetNumDims(oh)
    oshape = (ctypes.c_int32 * nd)()
    lib.PD_TensorGetShape(oh, oshape)
    assert list(oshape) == list(ref.shape), (list(oshape), ref.shape)
    out = np.empty(ref.shape, np.float32)
    lib.PD_TensorCopyToCpuFloat(oh, out.ctypes.data_as(ctypes.c_void_p))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    lib.PD_TensorDestroy(h)
    lib.PD_TensorDestroy(oh)
    lib.PD_PredictorDestroy(pred)
    lib.PD_ConfigDestroy(cfg)


_C_MAIN = r"""
#include <stdio.h>
#include <stdint.h>
#include <stdlib.h>

typedef int32_t PD_Bool;
typedef struct PD_Config PD_Config;
typedef struct PD_Predictor PD_Predictor;
typedef struct PD_Tensor PD_Tensor;

extern PD_Config* PD_ConfigCreate(void);
extern void PD_ConfigSetModel(PD_Config*, const char*, const char*);
extern PD_Predictor* PD_PredictorCreate(PD_Config*);
extern const char* PD_PredictorGetInputNameByIndex(PD_Predictor*, int);
extern PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor*, const char*);
extern void PD_TensorReshape(PD_Tensor*, size_t, const int32_t*);
extern void PD_TensorCopyFromCpuInt32(PD_Tensor*, const int32_t*);
extern PD_Bool PD_PredictorRun(PD_Predictor*);
extern const char* PD_PredictorGetOutputNameByIndex(PD_Predictor*, int);
extern PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor*, const char*);
extern size_t PD_TensorGetNumDims(PD_Tensor*);
extern void PD_TensorGetShape(PD_Tensor*, int32_t*);
extern void PD_TensorCopyToCpuFloat(PD_Tensor*, float*);

int main(int argc, char** argv) {
  PD_Config* cfg = PD_ConfigCreate();
  PD_ConfigSetModel(cfg, argv[1], "");
  PD_Predictor* pred = PD_PredictorCreate(cfg);
  if (!pred) { fprintf(stderr, "create failed\n"); return 1; }
  PD_Tensor* h = PD_PredictorGetInputHandle(
      pred, PD_PredictorGetInputNameByIndex(pred, 0));
  int32_t shape[2] = {2, 8};
  PD_TensorReshape(h, 2, shape);
  int32_t ids[16];
  for (int i = 0; i < 16; ++i) ids[i] = (i * 7) % 64;
  PD_TensorCopyFromCpuInt32(h, ids);
  if (!PD_PredictorRun(pred)) { fprintf(stderr, "run failed\n"); return 2; }
  PD_Tensor* oh = PD_PredictorGetOutputHandle(
      pred, PD_PredictorGetOutputNameByIndex(pred, 0));
  size_t nd = PD_TensorGetNumDims(oh);
  int32_t oshape[8];
  PD_TensorGetShape(oh, oshape);
  size_t n = 1;
  for (size_t i = 0; i < nd; ++i) n *= (size_t)oshape[i];
  float* out = (float*)malloc(n * sizeof(float));
  PD_TensorCopyToCpuFloat(oh, out);
  double s = 0;
  for (size_t i = 0; i < n; ++i) s += out[i];
  printf("C_API_OK ndims=%zu n=%zu checksum=%.4f\n", nd, n, s);
  return 0;
}
"""


def test_c_api_standalone_program(tmp_path):
    """Compile a real C program against the lib and run it — exercises the
    embed-an-interpreter path a C/Go deployment would take."""
    lib = _build_lib()
    model_path, ids, ref = _save_tiny_model(tmp_path)
    src = tmp_path / "main.c"
    src.write_text(_C_MAIN)
    exe = tmp_path / "capi_demo"
    subprocess.run(
        ["gcc", str(src), "-o", str(exe), f"-L{os.path.dirname(lib)}",
         "-lpaddle_tpu_c", f"-Wl,-rpath,{os.path.dirname(lib)}"],
        check=True, capture_output=True, text=True)
    # keep pre-existing PYTHONPATH entries EXCEPT the axon sitecustomize:
    # it force-sets jax_platforms=axon programmatically, which would point
    # the embedded interpreter at the TPU tunnel and ignore JAX_PLATFORMS
    extra = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
             if p and ".axon_site" not in p]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.pathsep.join([_REPO] + extra)}
    r = subprocess.run([str(exe), model_path], capture_output=True,
                       text=True, timeout=300, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "C_API_OK" in r.stdout, r.stdout
    assert f"n={ref.size}" in r.stdout, (r.stdout, ref.size)
