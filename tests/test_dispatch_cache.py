"""Eager dispatch cache (framework/core.py run_op).

Reference bar: everything above the kernel must be microsecond-scale per op
(SURVEY §3.1 hot-loop note; the reference generates C++ ad_func entry points,
eager_gen.py). Here the analog is one cached compiled program per
(op, attrs, avals, grad) signature — these tests assert reuse, attr-change
separation, fallback for unjittable ops, and numeric parity with the
uncached path.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.core import (
    clear_dispatch_cache,
    dispatch_cache_stats,
    run_op,
)


@pytest.fixture(autouse=True)
def _fresh_cache():
    clear_dispatch_cache()
    yield
    clear_dispatch_cache()


def test_repeat_op_hits_cache():
    x = paddle.to_tensor(np.ones((8, 8), np.float32))
    y = paddle.to_tensor(np.ones((8, 8), np.float32))
    _ = paddle.add(x, y)
    base = dispatch_cache_stats()
    for _ in range(5):
        _ = paddle.add(x, y)
    s = dispatch_cache_stats()
    assert s["hits"] >= base["hits"] + 5
    assert s["misses"] == base["misses"]


def test_attr_change_keys_separately():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    a0 = paddle.sum(x, axis=0)
    a1 = paddle.sum(x, axis=1)
    assert a0.shape == [4] and a1.shape == [3]
    np.testing.assert_allclose(a0.numpy(), x.numpy().sum(0))
    np.testing.assert_allclose(a1.numpy(), x.numpy().sum(1))
    # repeat both: each should hit its own entry
    h0 = dispatch_cache_stats()["hits"]
    _ = paddle.sum(x, axis=0)
    _ = paddle.sum(x, axis=1)
    assert dispatch_cache_stats()["hits"] >= h0 + 2


def test_shape_change_keys_separately():
    a = paddle.to_tensor(np.ones((4, 4), np.float32))
    b = paddle.to_tensor(np.ones((2, 2), np.float32))
    _ = paddle.exp(a)
    m = dispatch_cache_stats()["misses"]
    _ = paddle.exp(b)  # different aval -> new entry
    assert dispatch_cache_stats()["misses"] == m + 1


def test_grad_path_cached_and_correct():
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((16, 16)).astype(np.float32)
    wv = rng.standard_normal((16, 16)).astype(np.float32)
    x = paddle.to_tensor(xv)
    w = paddle.to_tensor(wv, stop_gradient=False)

    def step():
        y = paddle.matmul(x, w)
        loss = paddle.sum(y * y)
        loss.backward()
        g = np.array(w.grad.numpy())
        w.clear_grad()
        return g

    g1 = step()
    hits_before = dispatch_cache_stats()["hits"]
    g2 = step()
    assert dispatch_cache_stats()["hits"] > hits_before
    np.testing.assert_allclose(g1, g2, rtol=1e-5)
    # numpy oracle: d/dw sum((xw)^2) = 2 x^T (x w)
    oracle = 2.0 * xv.T @ (xv @ wv)
    np.testing.assert_allclose(g1, oracle, rtol=1e-3, atol=1e-3)


def test_unjittable_op_falls_back():
    import warnings

    x = paddle.to_tensor(np.array([1.0, -2.0, 3.0], np.float32))

    def host_round_trip(a):
        # np.asarray on a tracer raises -> not jittable, must fall back
        return paddle.framework.core.jnp.asarray(np.asarray(a) * 2.0)

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = run_op("host_round_trip", host_round_trip, [x])
    np.testing.assert_allclose(out.numpy(), [2.0, -4.0, 6.0])
    # blacklisting is announced once, with the op name
    assert any("host_round_trip" in str(w.message) for w in caught)
    # second call: bypassed (blacklisted), still correct, and the op is
    # visible by NAME in the stats so the regression is findable
    b = dispatch_cache_stats()["bypass"]
    out2 = run_op("host_round_trip", host_round_trip, [x])
    np.testing.assert_allclose(out2.numpy(), [2.0, -4.0, 6.0])
    stats = dispatch_cache_stats()
    assert stats["bypass"] > b
    assert "host_round_trip" in stats["uncacheable_ops"]
    assert stats["bypassed_ops"].get("host_round_trip", 0) >= 1


def test_inplace_and_hooks_still_work():
    x = paddle.to_tensor(np.zeros((4,), np.float32), stop_gradient=False)
    seen = []
    y = x * 2.0
    y.register_hook(lambda g: seen.append(np.array(g.numpy())))
    y.sum().backward()
    assert seen and np.allclose(seen[0], 1.0)
    assert np.allclose(np.array(x.grad.numpy()), 2.0)


def test_weak_vs_strong_scalar_keys_separately():
    # jax.jit retraces on weak_type; one shared cache entry would apply the
    # bwd treedef of one trace to the residuals of the other (silent wrong
    # grads) — so weak and strong scalars must key separately.
    import jax.numpy as jnp

    x = paddle.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
    weak = paddle.framework.core.Tensor(jnp.asarray(2.0))      # weak f32
    strong = paddle.framework.core.Tensor(jnp.float32(2.0))    # strong f32

    def go(s):
        y = x * s
        y.sum().backward()
        g = np.array(x.grad.numpy())
        x.clear_grad()
        return g

    g_w = go(weak)
    m = dispatch_cache_stats()["misses"]
    g_s = go(strong)
    assert dispatch_cache_stats()["misses"] == m + 1  # distinct entry
    np.testing.assert_allclose(g_w, g_s)
    np.testing.assert_allclose(g_w, 2.0)


def test_int_vs_float_attr_keys_separately():
    x = paddle.to_tensor(np.ones((4,), np.int32))
    two_i = 2
    two_f = 2.0
    a = run_op("scale_attr", lambda v, s=two_i: v * s, [x])
    b = run_op("scale_attr", lambda v, s=two_f: v * s, [x])
    assert a.dtype == np.int32
    np.testing.assert_allclose(a.numpy(), 2)
    np.testing.assert_allclose(b.numpy(), 2.0)


def test_multi_output_op_cached():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32))
    for _ in range(2):
        top, idx = paddle.topk(x, k=3)
        np.testing.assert_allclose(top.numpy(), [5.0, 4.0, 3.0])
        np.testing.assert_allclose(idx.numpy(), [5, 4, 3])
