"""paddle.distributed.rpc over the TCP worker server (reference:
python/paddle/distributed/rpc/rpc.py — init_rpc/rpc_sync/rpc_async/
worker-info surface)."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu.distributed.rpc as rpc


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _double(x):
    return 2 * x


def _add(a, b=0):
    return a + b


def _boom():
    raise ValueError("remote failure")


class TestSingleWorker:
    def setup_method(self, m):
        rpc.init_rpc("worker0", rank=0, world_size=1,
                     master_endpoint=f"127.0.0.1:{_free_port()}")

    def teardown_method(self, m):
        rpc.shutdown()

    def test_sync_async_and_infos(self):
        assert rpc.rpc_sync("worker0", _double, args=(21,)) == 42
        assert rpc.rpc_sync("worker0", _add, args=(1,), kwargs={"b": 2}) == 3
        fut = rpc.rpc_async("worker0", _double, args=(5,))
        assert fut.wait() == 10
        # numpy payloads round-trip
        arr = np.arange(6).reshape(2, 3)
        out = rpc.rpc_sync("worker0", _double, args=(arr,))
        np.testing.assert_array_equal(out, 2 * arr)

        me = rpc.get_current_worker_info()
        assert me.name == "worker0" and me.rank == 0
        assert rpc.get_worker_info("worker0") == me
        assert rpc.get_all_worker_infos() == [me]

    def test_remote_exception_reraises(self):
        with pytest.raises(ValueError, match="remote failure"):
            rpc.rpc_sync("worker0", _boom)

    def test_unknown_worker(self):
        with pytest.raises(ValueError, match="unknown rpc worker"):
            rpc.rpc_sync("nobody", _double, args=(1,))


def test_requires_init():
    with pytest.raises(RuntimeError, match="not initialized"):
        rpc.rpc_sync("worker0", _double, args=(1,))


_WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import paddle_tpu.distributed.rpc as rpc

    def mul3(x):
        return 3 * x

    rank = int(sys.argv[1])
    rpc.init_rpc(f"w{{rank}}", rank=rank, world_size=2,
                 master_endpoint=sys.argv[2])
    if rank == 0:
        # call INTO the other process
        out = rpc.rpc_sync("w1", mul3, args=(14,))
        assert out == 42, out
        infos = rpc.get_all_worker_infos()
        assert [w.name for w in infos] == ["w0", "w1"], infos
        print("RPC_OK", out)
    rpc.shutdown()
""")


def test_two_processes(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=repo))
    ep = f"127.0.0.1:{_free_port()}"
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo}
    procs = [subprocess.Popen([sys.executable, str(script), str(r), ep],
                              stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                              text=True, env=env)
             for r in (0, 1)]
    outs = [p.communicate(timeout=120) for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert "RPC_OK 42" in outs[0][0], outs


def _slow(seconds):
    import time
    time.sleep(seconds)
    return "done"


class TestTimeouts:
    def setup_method(self, m):
        rpc.init_rpc("w0", rank=0, world_size=1,
                     master_endpoint=f"127.0.0.1:{_free_port()}")

    def teardown_method(self, m):
        rpc.shutdown()

    def test_call_timeout_raises(self):
        with pytest.raises(Exception) as ei:
            rpc.rpc_sync("w0", _slow, args=(2,), timeout=0.5)
        assert "timed out" in str(ei.value).lower() or isinstance(
            ei.value, (TimeoutError, OSError)), ei.value

    def test_async_timeout_surfaces_in_future(self):
        fut = rpc.rpc_async("w0", _slow, args=(2,), timeout=0.5)
        with pytest.raises(Exception) as ei:
            fut.wait()
        assert "timed out" in str(ei.value).lower() or isinstance(
            ei.value, (TimeoutError, OSError)), ei.value

    def test_fast_call_within_timeout(self):
        assert rpc.rpc_sync("w0", _double, args=(4,), timeout=30) == 8
