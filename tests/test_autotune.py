"""Pallas block-size autotune cache (reference:
paddle/phi/kernels/autotune/cache.h AutoTuneCache + auto_tune_base.h
candidate measurement)."""

import os
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import autotune


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_DIR", str(tmp_path))
    monkeypatch.delenv("PADDLE_TPU_PALLAS_INTERPRET", raising=False)
    autotune.clear_cache()
    yield
    autotune.clear_cache()


def test_disabled_returns_default(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
    calls = []
    out = autotune.pick_block_sizes("k", 512, 512, (128, 128),
                                    lambda bq, bk: calls.append((bq, bk)))
    assert out == (128, 128) and not calls


def test_measures_once_and_caches(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    timings = {(128, 128): 0.004, (128, 256): 0.001, (256, 128): 0.003,
               (256, 256): 0.002, (128, 512): 0.005, (256, 512): 0.006}
    calls = []

    def run_with(bq, bk):
        import time

        calls.append((bq, bk))
        time.sleep(timings.get((bq, bk), 0.01))

    best = autotune.pick_block_sizes("flash_fwd", 512, 512, (128, 128),
                                     run_with, reps=1)
    assert best == (128, 256), best
    assert calls, "no candidates measured"

    # second call: cache hit, no measuring
    calls.clear()
    again = autotune.pick_block_sizes("flash_fwd", 512, 512, (128, 128),
                                      run_with, reps=1)
    assert again == (128, 256) and not calls

    # survives across process state (disk cache)
    autotune._memory.clear()
    autotune._disk_loaded[0] = False
    third = autotune.pick_block_sizes("flash_fwd", 512, 512, (128, 128),
                                      run_with, reps=1)
    assert third == (128, 256) and not calls


def test_tracer_inputs_use_cache_only(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    calls = []
    out = autotune.pick_block_sizes("k2", 256, 256, (128, 128),
                                    lambda bq, bk: calls.append(1),
                                    allow_measure=False)
    assert out == (128, 128) and not calls  # no cache -> default, no measure


def test_failing_candidates_skipped(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")

    def run_with(bq, bk):
        if (bq, bk) != (128, 128):
            raise RuntimeError("mosaic rejects this tiling")

    best = autotune.pick_block_sizes("k3", 1024, 1024, (128, 128),
                                     run_with, reps=1)
    assert best == (128, 128)


def test_flash_entry_consults_tuner(monkeypatch):
    """flash_attention_fwd routes through the tuner: a pre-seeded cache
    winner changes the block shape _fwd actually receives."""
    from paddle_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    # force tuning on despite interpret mode so the cache lookup runs
    monkeypatch.setattr(autotune, "autotune_enabled", lambda: True)

    B, S, H, D = 1, 512, 2, 32
    # seed the winner for this exact signature
    key = (f"flash_fwd|{autotune._device_kind()}|{S}|{S}|"
           f"{B}|{H}|{H}|{D}|float32|True")
    autotune._memory[key] = [256, 256]
    autotune._disk_loaded[0] = True

    seen = []
    orig_fwd = fa._fwd

    def spy(q, k, v, scale, causal, sq, skv, bq=None, bk=None, safe=None):
        seen.append((bq, bk))
        return orig_fwd(q, k, v, scale, causal, sq, skv, bq=bq, bk=bk,
                        safe=safe)

    monkeypatch.setattr(fa, "_fwd", spy)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    out = fa.flash_attention_fwd(q, q, q, causal=True)
    assert out.shape == q.shape and bool(jnp.isfinite(out).all())
    assert (256, 256) in seen, f"tuned blocks not used: {seen}"


def test_flash_entry_default_under_interpret(monkeypatch):
    """Interpret mode (tuning off) still runs correctly on defaults."""
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 256, 2, 32)), jnp.float32)
    out = flash_attention_fwd(q, q, q, causal=True)
    assert out.shape == q.shape and bool(jnp.isfinite(out).all())


class TestSetConfig:
    """incubate.autotune.set_config error semantics (reference:
    python/paddle/incubate/autotune.py — warn + fall back, never raise)."""

    def test_bad_path_warns_and_defaults(self, monkeypatch):
        import warnings
        import paddle_tpu.incubate as incubate

        monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            incubate.autotune.set_config("/nonexistent/autotune.json")
        assert any("cannot load" in str(x.message) for x in w)
        assert os.environ["PADDLE_TPU_AUTOTUNE"] == "1"

    def test_non_dict_json_warns_and_defaults(self, tmp_path, monkeypatch):
        import warnings
        import paddle_tpu.incubate as incubate

        p = tmp_path / "cfg.json"
        p.write_text("[1, 2, 3]")
        monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            incubate.autotune.set_config(str(p))
        assert any("expects" in str(x.message) for x in w)
        assert os.environ["PADDLE_TPU_AUTOTUNE"] == "1"

    def test_dict_without_kernel_leaves_autotune_untouched(self, monkeypatch):
        import paddle_tpu.incubate as incubate

        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        incubate.autotune.set_config({"layout": {"enable": True}})
        assert os.environ["PADDLE_TPU_AUTOTUNE"] == "0"

    def test_kernel_enable_false(self, monkeypatch):
        import paddle_tpu.incubate as incubate

        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
        incubate.autotune.set_config({"kernel": {"enable": False}})
        assert os.environ["PADDLE_TPU_AUTOTUNE"] == "0"
