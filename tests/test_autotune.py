"""Pallas block-size autotune cache (reference:
paddle/phi/kernels/autotune/cache.h AutoTuneCache + auto_tune_base.h
candidate measurement)."""

import os
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import autotune


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_DIR", str(tmp_path))
    monkeypatch.delenv("PADDLE_TPU_PALLAS_INTERPRET", raising=False)
    autotune.clear_cache()
    yield
    autotune.clear_cache()


def test_disabled_returns_default(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
    calls = []
    out = autotune.pick_block_sizes("k", 512, 512, (128, 128),
                                    lambda bq, bk: calls.append((bq, bk)))
    assert out == (128, 128) and not calls


def test_measures_once_and_caches(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    timings = {(128, 128): 0.004, (128, 256): 0.001, (256, 128): 0.003,
               (256, 256): 0.002, (128, 512): 0.005, (256, 512): 0.006}
    calls = []

    def run_with(bq, bk):
        import time

        calls.append((bq, bk))
        time.sleep(timings.get((bq, bk), 0.01))

    best = autotune.pick_block_sizes("flash_fwd", 512, 512, (128, 128),
                                     run_with, reps=1)
    assert best == (128, 256), best
    assert calls, "no candidates measured"

    # second call: cache hit, no measuring
    calls.clear()
    again = autotune.pick_block_sizes("flash_fwd", 512, 512, (128, 128),
                                      run_with, reps=1)
    assert again == (128, 256) and not calls

    # survives across process state (disk cache)
    autotune._memory.clear()
    autotune._disk_loaded[0] = False
    third = autotune.pick_block_sizes("flash_fwd", 512, 512, (128, 128),
                                      run_with, reps=1)
    assert third == (128, 256) and not calls


def test_tracer_inputs_use_cache_only(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    calls = []
    out = autotune.pick_block_sizes("k2", 256, 256, (128, 128),
                                    lambda bq, bk: calls.append(1),
                                    allow_measure=False)
    assert out == (128, 128) and not calls  # no cache -> default, no measure


def test_failing_candidates_skipped(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")

    def run_with(bq, bk):
        if (bq, bk) != (128, 128):
            raise RuntimeError("mosaic rejects this tiling")

    best = autotune.pick_block_sizes("k3", 1024, 1024, (128, 128),
                                     run_with, reps=1)
    assert best == (128, 128)


def test_flash_entry_consults_tuner(monkeypatch):
    """flash_attention_fwd routes through the tuner: a pre-seeded cache
    winner changes the block shape _fwd actually receives."""
    from paddle_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    # force tuning on despite interpret mode so the cache lookup runs
    monkeypatch.setattr(autotune, "autotune_enabled", lambda: True)

    B, S, H, D = 1, 512, 2, 32
    # seed the winner for this exact signature (device + jaxlib keyed)
    key = (f"flash_fwd|{autotune._device_kind()}|{autotune._jaxlib_version()}"
           f"|{S}|{S}|{B}|{H}|{H}|{D}|float32|True")
    autotune._memory[key] = [256, 256]
    autotune._disk_loaded[0] = True

    seen = []
    orig_fwd = fa._fwd

    def spy(q, k, v, scale, causal, sq, skv, bq=None, bk=None, safe=None):
        seen.append((bq, bk))
        return orig_fwd(q, k, v, scale, causal, sq, skv, bq=bq, bk=bk,
                        safe=safe)

    monkeypatch.setattr(fa, "_fwd", spy)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    out = fa.flash_attention_fwd(q, q, q, causal=True)
    assert out.shape == q.shape and bool(jnp.isfinite(out).all())
    assert (256, 256) in seen, f"tuned blocks not used: {seen}"


def test_flash_entry_default_under_interpret(monkeypatch):
    """Interpret mode (tuning off) still runs correctly on defaults."""
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 256, 2, 32)), jnp.float32)
    out = flash_attention_fwd(q, q, q, causal=True)
    assert out.shape == q.shape and bool(jnp.isfinite(out).all())


def test_jaxlib_version_in_disk_key(monkeypatch):
    """A jaxlib upgrade must invalidate tuned winners: the cache key embeds
    the jaxlib version, so a winner stored under the old version misses."""
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    autotune.pick_block_sizes("kver", 256, 256, (128, 128),
                              lambda bq, bk: None, reps=1)
    (key,) = [k for k in autotune._memory if k.startswith("kver|")]
    assert f"|{autotune._jaxlib_version()}|" in key

    # same signature under a different jaxlib version: cache miss
    monkeypatch.setattr(autotune, "_jaxlib_version", lambda: "9.9.9")
    calls = []
    autotune.pick_block_sizes("kver", 256, 256, (128, 128),
                              lambda bq, bk: calls.append(1), reps=1)
    assert calls, "stale winner survived a jaxlib upgrade"


def test_trace_miss_counts_fallback_and_warns_once(monkeypatch):
    """PADDLE_TPU_AUTOTUNE=1 + jit trace + cache miss used to silently run
    defaults; now it counts pallas_autotune_fallbacks_total{kernel=} and
    warns ONCE naming the key."""
    import warnings

    from paddle_tpu.observability.metrics import reset_default_registry

    reg = reset_default_registry()
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = autotune.pick_block_sizes("kfb", 256, 256, (128, 128),
                                        lambda bq, bk: None,
                                        allow_measure=False)
        again = autotune.pick_block_sizes("kfb", 256, 256, (128, 128),
                                          lambda bq, bk: None,
                                          allow_measure=False)
    assert out == (128, 128) and again == (128, 128)
    hits = [x for x in w if "kfb" in str(x.message)]
    assert len(hits) == 1, "fallback warning must fire once per key"
    assert "PADDLE_TPU_AUTOTUNE" in str(hits[0].message)
    ctr = reg.get("pallas_autotune_fallbacks_total")
    assert ctr is not None and ctr.value(kernel="kfb") == 2
    tiles = autotune.chosen_tiles()
    assert tiles["kfb"]["source"] == "default"
    assert tiles["kfb"]["fallbacks"] == 2


def test_hit_and_miss_counters(monkeypatch):
    from paddle_tpu.observability.metrics import reset_default_registry

    reg = reset_default_registry()
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    autotune.pick_block_sizes("khm", 256, 256, (128, 128),
                              lambda bq, bk: None, reps=1)
    autotune.pick_block_sizes("khm", 256, 256, (128, 128),
                              lambda bq, bk: None, reps=1)
    assert reg.get("pallas_autotune_misses_total").value(kernel="khm") == 1
    assert reg.get("pallas_autotune_hits_total").value(kernel="khm") == 1
    assert autotune.chosen_tiles()["khm"]["source"] == "tuned"


def test_custom_candidates_override_grid(monkeypatch):
    """Kernels with a non-attention tunable (fused norm row block, dense
    decode page tile) pass their own candidate list."""
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
    seen = []

    def run_with(bq, bk):
        seen.append((bq, bk))

    best = autotune.pick_block_sizes(
        "kcand", 512, 384, (64, 384), run_with, reps=1,
        candidates=[(64, 384), (128, 384)])
    assert set(seen) == {(64, 384), (128, 384)}
    assert best in {(64, 384), (128, 384)}


def test_disabled_still_records_default_tile(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
    out = autotune.pick_block_sizes("kdef", 512, 512, (256, 512),
                                    lambda bq, bk: None)
    assert out == (256, 512)
    rec = autotune.chosen_tiles()["kdef"]
    assert rec == {"bq": 256, "bk": 512, "source": "default"}


def test_all_pallas_kernels_consult_tuner(monkeypatch):
    """Acceptance: every Pallas kernel entry lands a tile in the registry —
    flash, flashmask, varlen, dense+paged decode, fused norm, fused rope."""
    import jax.numpy as jnp

    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.ops.pallas.decode_attention import (
        dense_decode_attention, paged_decode_attention)
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd
    from paddle_tpu.ops.pallas.fused_norm import layer_norm_fwd, rms_norm_fwd
    from paddle_tpu.ops.pallas.fused_rope import apply_fused_rope
    from paddle_tpu.ops.pallas.masked_flash import (
        flashmask_attention_fwd, varlen_flash_attention_fwd)

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 64, 2, 32)), jnp.float32)
    flash_attention_fwd(q, q, q, causal=True)
    idx = jnp.full((1, 1, 64, 1), 64, jnp.int32)
    flashmask_attention_fwd(q, q, q, idx, causal=True)
    qp = jnp.asarray(rng.standard_normal((48, 2, 32)), jnp.float32)
    cu = jnp.asarray([0, 20, 48], jnp.int32)
    varlen_flash_attention_fwd(qp, qp, qp, cu, cu, 0.17, causal=True)
    qd = jnp.asarray(rng.standard_normal((2, 4, 32)), jnp.float32)
    dense = jnp.asarray(rng.standard_normal((2, 2, 64, 32)), jnp.float32)
    dense_decode_attention(qd, dense, dense, jnp.asarray([5, 9], jnp.int32))
    paged = jnp.asarray(rng.standard_normal((4, 2, 8, 32)), jnp.float32)
    tables = jnp.asarray([[1, 2], [3, -1]], jnp.int32)
    paged_decode_attention(qd, paged, paged, tables,
                           jnp.asarray([10, 5], jnp.int32))
    paged_q = (paged * 16).astype(jnp.int8)
    scales = jnp.ones((4, 2), jnp.float32) / 16
    paged_decode_attention(qd, paged_q, paged_q, tables,
                           jnp.asarray([10, 5], jnp.int32),
                           kv_scales=(scales, scales))
    x = jnp.asarray(rng.standard_normal((2, 40, 96)), jnp.float32)
    rms_norm_fwd(x, None)
    layer_norm_fwd(x, None, None)
    c = jnp.cos(jnp.ones((1, 64, 16), jnp.float32))
    s = jnp.sin(jnp.ones((1, 64, 16), jnp.float32))
    apply_fused_rope((q,), c, s)
    from paddle_tpu.ops.pallas.grouped_gemm import grouped_matmul

    grouped_matmul(jnp.asarray(rng.standard_normal((16, 8)), jnp.float32),
                   jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32),
                   jnp.asarray([8, 4], jnp.int32))

    tiles = autotune.chosen_tiles()
    for kernel in ("flash_fwd", "flashmask_fwd", "varlen_fwd",
                   "decode_dense", "decode_paged", "decode_paged_q8",
                   "fused_rms_norm", "fused_layer_norm", "fused_rope",
                   "grouped_gemm"):
        assert kernel in tiles, (kernel, sorted(tiles))
        assert tiles[kernel]["bq"] > 0 and tiles[kernel]["bk"] > 0


class TestSetConfig:
    """incubate.autotune.set_config error semantics (reference:
    python/paddle/incubate/autotune.py — warn + fall back, never raise)."""

    def test_bad_path_warns_and_defaults(self, monkeypatch):
        import warnings
        import paddle_tpu.incubate as incubate

        monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            incubate.autotune.set_config("/nonexistent/autotune.json")
        assert any("cannot load" in str(x.message) for x in w)
        assert os.environ["PADDLE_TPU_AUTOTUNE"] == "1"

    def test_non_dict_json_warns_and_defaults(self, tmp_path, monkeypatch):
        import warnings
        import paddle_tpu.incubate as incubate

        p = tmp_path / "cfg.json"
        p.write_text("[1, 2, 3]")
        monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            incubate.autotune.set_config(str(p))
        assert any("expects" in str(x.message) for x in w)
        assert os.environ["PADDLE_TPU_AUTOTUNE"] == "1"

    def test_dict_without_kernel_leaves_autotune_untouched(self, monkeypatch):
        import paddle_tpu.incubate as incubate

        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        incubate.autotune.set_config({"layout": {"enable": True}})
        assert os.environ["PADDLE_TPU_AUTOTUNE"] == "0"

    def test_kernel_enable_false(self, monkeypatch):
        import paddle_tpu.incubate as incubate

        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
        incubate.autotune.set_config({"kernel": {"enable": False}})
        assert os.environ["PADDLE_TPU_AUTOTUNE"] == "0"
