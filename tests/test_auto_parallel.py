"""Auto-parallel (semi-auto) API + distributed checkpoint tests.

Reference patterns: test/auto_parallel/ (shard_tensor/reshard unit tests,
semi-auto e2e) and the checkpoint save/load-with-reshard tests.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.env.set_global_mesh(None)
    dist.auto_parallel.set_mesh(None)


def _mesh2d():
    return dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])


class TestPlacements:
    def test_shard_tensor_sharding_and_value(self):
        mesh = _mesh2d()
        x = np.arange(32, dtype=np.float32).reshape(8, 4)
        d = dist.shard_tensor(paddle.to_tensor(x), mesh,
                              [dist.Shard(0), dist.Replicate()])
        assert "x" in str(d._value.sharding.spec)
        assert d.placements == [dist.Shard(0), dist.Replicate()]
        assert d.process_mesh == mesh
        np.testing.assert_allclose(d.numpy(), x)

    def test_reshard_changes_layout_not_value(self):
        mesh = _mesh2d()
        x = np.random.RandomState(0).rand(8, 16).astype(np.float32)
        d = dist.shard_tensor(paddle.to_tensor(x), mesh,
                              [dist.Shard(0), dist.Shard(1)])
        r = dist.reshard(d, mesh, [dist.Replicate(), dist.Shard(0)])
        np.testing.assert_allclose(r.numpy(), x)
        assert r.placements[0].is_replicate()

    def test_placement_predicates(self):
        assert dist.Shard(1).is_shard(1) and not dist.Shard(1).is_shard(0)
        assert dist.Replicate().is_replicate()
        assert dist.Partial().is_partial()
        assert dist.Shard(0) == dist.Shard(0) != dist.Shard(1)

    def test_wrong_placement_count_raises(self):
        with pytest.raises(ValueError):
            dist.shard_tensor(paddle.to_tensor(np.zeros((4, 4), np.float32)),
                              _mesh2d(), [dist.Shard(0)])

    def test_dtensor_from_fn(self):
        mesh = _mesh2d()
        d = dist.dtensor_from_fn(paddle.ones, mesh,
                                 [dist.Replicate(), dist.Replicate()], [4, 4])
        np.testing.assert_allclose(d.numpy(), np.ones((4, 4)))


class TestEagerSemiAuto:
    def test_eager_ops_on_dist_tensors(self):
        mesh = _mesh2d()
        x = np.random.RandomState(0).rand(8, 4).astype(np.float32)
        d = dist.shard_tensor(paddle.to_tensor(x), mesh,
                              [dist.Shard(0), dist.Replicate()])
        out = (d * 2 + 1).numpy()
        np.testing.assert_allclose(out, x * 2 + 1, rtol=1e-6)

    def test_training_with_sharded_weight(self):
        """Dygraph semi-auto: ops between dist tensors run distributed
        (reference: dygraph DistTensor path through generated dist branch)."""
        mesh = _mesh2d()
        paddle.seed(0)
        lin = nn.Linear(4, 4)
        lin.weight._value = dist.shard_tensor(
            lin.weight, mesh, [dist.Shard(0), dist.Shard(1)])._value
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.rand(8, 4).astype(np.float32))
        Y = paddle.to_tensor(rng.rand(8, 4).astype(np.float32))
        losses = []
        for _ in range(8):
            loss = F.mse_loss(lin(X), Y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_shard_layer(self):
        mesh = _mesh2d()
        paddle.seed(0)

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 4)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        m = MLP()

        def shard_fn(name, layer, mesh):
            if isinstance(layer, nn.Linear):
                layer.weight._value = dist.shard_tensor(
                    layer.weight, mesh, [dist.Replicate(), dist.Shard(1)])._value

        dist.shard_layer(m, mesh, shard_fn)
        assert "y" in str(m.fc1.weight._value.sharding.spec)
        out = m(paddle.to_tensor(np.random.RandomState(1).rand(8, 4).astype(np.float32)))
        assert out.shape == [8, 4]

    def test_get_set_mesh(self):
        mesh = _mesh2d()
        dist.auto_parallel.set_mesh(mesh)
        assert dist.auto_parallel.get_mesh() is mesh


class TestDistributedCheckpoint:
    def test_save_load_reshard(self, tmp_path):
        """Save under one mesh config, load under another — the reference's
        reshard-on-load contract (load_state_dict.py:476)."""
        mesh = _mesh2d()
        rng = np.random.RandomState(0)
        w = rng.randn(8, 16).astype(np.float32)
        b = rng.randn(16).astype(np.float32)
        sd = {
            "w": dist.shard_tensor(paddle.to_tensor(w), mesh,
                                   [dist.Shard(0), dist.Shard(1)]),
            "b": dist.shard_tensor(paddle.to_tensor(b), mesh,
                                   [dist.Replicate(), dist.Shard(0)]),
            "scalar": paddle.to_tensor(np.float32(3.5)),
        }
        path = str(tmp_path / "ckpt")
        dist.checkpoint.save_state_dict(sd, path)

        mesh2 = dist.ProcessMesh(list(range(8)), dim_names=["p"])
        tgt = {
            "w": dist.shard_tensor(paddle.to_tensor(np.zeros_like(w)), mesh2,
                                   [dist.Shard(1)]),
            "b": dist.shard_tensor(paddle.to_tensor(np.zeros_like(b)), mesh2,
                                   [dist.Shard(0)]),
            "scalar": paddle.to_tensor(np.float32(0)),
        }
        dist.checkpoint.load_state_dict(tgt, path)
        np.testing.assert_allclose(tgt["w"].numpy(), w)
        np.testing.assert_allclose(tgt["b"].numpy(), b)
        assert float(tgt["scalar"].numpy()) == 3.5

    def test_model_state_dict_round_trip(self, tmp_path):
        """Whole-model save/load through the sharded checkpoint."""
        paddle.seed(0)
        mesh = _mesh2d()
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8))
        for p_ in m.parameters():
            if p_._value.ndim == 2:
                p_._value = dist.shard_tensor(
                    p_, mesh, [dist.Replicate(), dist.Shard(1)])._value
        ref = {k: v.numpy().copy() for k, v in m.state_dict().items()}
        path = str(tmp_path / "model_ckpt")
        dist.checkpoint.save_state_dict(m.state_dict(), path)
        for p_ in m.parameters():
            p_.set_value(paddle.to_tensor(np.zeros(p_.shape, np.float32)))
        dist.checkpoint.load_state_dict(m.state_dict(), path)
        for k, v in m.state_dict().items():
            np.testing.assert_allclose(v.numpy(), ref[k], err_msg=k)

    def test_missing_key_raises(self, tmp_path):
        sd = {"a": paddle.to_tensor(np.ones((2, 2), np.float32))}
        path = str(tmp_path / "c")
        dist.checkpoint.save_state_dict(sd, path)
        with pytest.raises(KeyError):
            dist.checkpoint.load_state_dict(
                {"missing": paddle.to_tensor(np.zeros((2, 2), np.float32))}, path)
