"""SOT graph-break capture (reference:
python/paddle/jit/sot/translate.py:97-106 — compiled subgraphs around
BreakGraphError instead of whole-frame eager fallback)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.core import dispatch_cache_stats
from paddle_tpu.jit import to_static
from paddle_tpu.jit.sot import SOTCapture


def _t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


class TestSOTCapture:
    def test_branch_function_correct_both_paths(self):
        def f(x):
            y = paddle.tanh(x) * 2.0
            if float(y.sum()) > 0:  # graph break
                z = y + 1.0
            else:
                z = y - 1.0
            return z * 3.0

        cap = SOTCapture(f)
        xp = _t([0.5, 0.5])
        xn = _t([-0.5, -0.5])
        np.testing.assert_allclose(cap(xp).numpy(), f(xp).numpy(), rtol=1e-6)
        np.testing.assert_allclose(cap(xn).numpy(), f(xn).numpy(), rtol=1e-6)
        # second calls replay compiled segments (no new record runs)
        r0 = cap.stats["record_runs"]
        np.testing.assert_allclose(cap(xp).numpy(), f(xp).numpy(), rtol=1e-6)
        np.testing.assert_allclose(cap(xn).numpy(), f(xn).numpy(), rtol=1e-6)
        assert cap.stats["record_runs"] == r0
        assert cap.stats["replay_runs"] >= 2
        # one break => 2 segments per replay
        assert cap.stats["segments_run"] >= 4

    def test_majority_of_ops_run_compiled(self):
        """VERDICT criterion: a model with one dynamic branch executes >50%
        of its ops inside compiled segments (2 sot_segment dispatches vs the
        ~12 per-op dispatches the eager fallback would pay)."""
        def f(x):
            h = x
            for _ in range(5):
                h = paddle.tanh(h) + 0.1 * h  # 3 ops per iteration
            if bool((h.sum() > 0.0)):  # break
                h = h * 2.0
            for _ in range(5):
                h = paddle.sin(h) * 0.9
            return h.sum()

        cap = SOTCapture(f)
        x = _t([0.3, 0.4])
        ref = float(f(x).numpy())
        _ = cap(x)  # record
        from paddle_tpu.framework.core import clear_dispatch_cache

        clear_dispatch_cache()
        out = cap(x)  # replay
        stats = dispatch_cache_stats()  # read BEFORE any further eager ops
        np.testing.assert_allclose(float(out.numpy()), ref, rtol=1e-5)
        total = stats["hits"] + stats["misses"] + stats["bypass"]
        assert cap.stats["segments_run"] >= 2
        # >50% compiled: the ~23 recorded ops execute inside 2 compiled
        # segment dispatches per replay
        n_ops = sum(len(seg.ops) for seg in _walk_segments(cap))
        assert n_ops >= 20
        assert total <= n_ops / 2, (stats, n_ops)

    def test_int_loop_guard(self):
        def f(x, n):
            h = x
            for _ in range(int(n)):  # int graph break
                h = h * 2.0
            return h

        cap = SOTCapture(f)
        x = _t([1.0])
        n2 = paddle.to_tensor(np.asarray(2, np.int32))
        n3 = paddle.to_tensor(np.asarray(3, np.int32))
        np.testing.assert_allclose(cap(x, n2).numpy(), [4.0])
        np.testing.assert_allclose(cap(x, n3).numpy(), [8.0])  # new path
        np.testing.assert_allclose(cap(x, n2).numpy(), [4.0])  # replay
        np.testing.assert_allclose(cap(x, n3).numpy(), [8.0])
        assert cap.stats["record_runs"] == 2

    def test_gradients_flow_through_segments(self):
        paddle.seed(0)
        lin = nn.Linear(4, 4)

        def f(x):
            h = lin(x)
            if float(h.sum()) > -1e9:  # always true; still a break
                h = paddle.tanh(h)
            return h.sum()

        cap = SOTCapture(f)
        x = paddle.to_tensor(np.ones((2, 4), np.float32), stop_gradient=False)
        _ = cap(x)  # record
        loss = cap(x)  # replay through compiled segments
        loss.backward()
        assert lin.weight.grad is not None, "param grads lost in segments"
        assert x.grad is not None
        # reference grads from plain eager
        lin.weight.clear_grad()
        x2 = paddle.to_tensor(np.ones((2, 4), np.float32),
                              stop_gradient=False)
        f(x2).backward()
        np.testing.assert_allclose(np.asarray(x.grad.numpy()),
                                   np.asarray(x2.grad.numpy()), rtol=1e-5)

    def test_weight_updates_visible_to_replay(self):
        paddle.seed(0)
        lin = nn.Linear(2, 2)

        def f(x):
            h = lin(x)
            if float(h.sum()) > -1e9:
                h = h + 0.0
            return h

        cap = SOTCapture(f)
        x = _t([[1.0, 1.0]])
        _ = cap(x)
        before = cap(x).numpy().copy()
        with paddle.no_grad():
            lin.weight.set_value(lin.weight.numpy() * 2.0)
        after = cap(x).numpy()
        assert not np.allclose(before, after), "stale weights in replay"

    def test_to_static_routes_to_sot(self):
        @to_static
        def f(x):
            y = paddle.exp(x)
            if float(y.sum()) > 1.0:  # breaks the whole-frame trace
                return y * 2.0
            return y * 0.5

        x = _t([0.5, 0.5])
        out1 = f(x)  # whole-frame jit fails -> SOT capture records
        out2 = f(x)  # replay
        ref = np.exp([0.5, 0.5]) * 2.0
        np.testing.assert_allclose(out1.numpy(), ref, rtol=1e-5)
        np.testing.assert_allclose(out2.numpy(), ref, rtol=1e-5)
        assert f._sot_fallen_back[0]
        assert f._sot_capture[0] is not None
        assert f._sot_capture[0].stats["replay_runs"] >= 1

    def test_to_static_layer_routes_to_sot(self):
        paddle.seed(0)

        class Dyn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)
                if float(h.mean()) > -1e9:
                    h = paddle.nn.functional.relu(h)
                return h

        m = to_static(Dyn())
        x = _t(np.ones((2, 4)))
        out1 = m(x)
        out2 = m(x)
        np.testing.assert_allclose(out1.numpy(), out2.numpy(), rtol=1e-6)
        cap = m.forward._sot_capture[0][True]  # keyed by training mode
        assert cap.stats["replay_runs"] >= 1
        # switching to eval records a separate capture (train-mode graphs
        # must not replay in eval)
        m.eval()
        out3 = m(x)
        assert True in m.forward._sot_capture[0]
        np.testing.assert_allclose(out3.numpy(), out1.numpy(), rtol=1e-6)

    def test_numpy_sync_is_guarded(self):
        def f(x):
            y = paddle.tanh(x)
            if y.numpy().sum() > 0:  # .numpy() escape must be guarded
                return y + 1.0
            return y - 1.0

        cap = SOTCapture(f)
        xp, xn = _t([0.5, 0.5]), _t([-0.5, -0.5])
        np.testing.assert_allclose(cap(xp).numpy(), f(xp).numpy(), rtol=1e-6)
        np.testing.assert_allclose(cap(xn).numpy(), f(xn).numpy(), rtol=1e-6)
        np.testing.assert_allclose(cap(xn).numpy(), f(xn).numpy(), rtol=1e-6)

    def test_item_comparison_guard_survives_value_drift(self):
        """`if t.item() > c:` guards on the branch OUTCOME, so replays keep
        working while the underlying value changes (training loop pattern)."""
        def f(x):
            y = paddle.tanh(x)
            if y.sum().item() > 0:
                return y * 2.0
            return y * -1.0

        cap = SOTCapture(f)
        rng = np.random.default_rng(0)
        for i in range(20):
            x = _t(np.abs(rng.normal(size=(3,))) + 0.1)  # always positive
            np.testing.assert_allclose(cap(x).numpy(), f(x).numpy(),
                                       rtol=1e-5)
        assert not cap.disabled
        assert cap.stats["record_runs"] == 1  # one record, 19 replays
        assert cap.stats["replay_runs"] == 19
        # the other branch still records + replays
        xneg = _t([-1.0, -1.0, -1.0])
        np.testing.assert_allclose(cap(xneg).numpy(), f(xneg).numpy(),
                                   rtol=1e-5)
        assert cap.stats["record_runs"] == 2

    def test_continuous_guard_disables_instead_of_rerecording_forever(self):
        def f(x):
            v = float(x.sum())  # continuous guard: every input differs
            return x * v

        cap = SOTCapture(f)
        rng = np.random.default_rng(0)
        for _ in range(40):
            x = _t(rng.normal(size=(3,)))
            np.testing.assert_allclose(cap(x).numpy(), f(x).numpy(),
                                       rtol=1e-5)
        assert cap.disabled  # safety valve fired; still correct throughout

    def test_nested_jit_output_falls_back_safely(self):
        inner = to_static(lambda x: x * 2.0)
        _ = inner(_t([1.0]))  # compile the inner (bypasses run_op)

        def f(x):
            h = inner(x)  # tensor produced outside run_op
            if float(h.sum()) > 0:
                return h + 1.0
            return h - 1.0

        cap = SOTCapture(f)
        x = _t([1.0])
        np.testing.assert_allclose(cap(x).numpy(), f(x).numpy(), rtol=1e-6)
        assert cap.disabled  # unreplayable -> permanent eager, not wrong
        x2 = _t([3.0])
        np.testing.assert_allclose(cap(x2).numpy(), f(x2).numpy(), rtol=1e-6)


def _walk_segments(cap):
    out = []

    def walk(node):
        if node is None:
            return
        if node.segment is not None:
            out.append(node.segment)
        for c in node.children.values():
            walk(c)

    for root in cap.roots.values():
        walk(root)
    return out


class TestSOTRng:
    def test_dropout_resamples_across_replays(self):
        """VERDICT r3 #6: RNG must not freeze in captured segments — two
        replays of one captured frame draw different dropout masks."""
        def f(x):
            y = nn.functional.dropout(x, 0.5, training=True)
            if y.sum().item() > -1e9:  # graph break (always true branch)
                z = y * 2.0
            else:
                z = y - 1.0
            return z

        cap = SOTCapture(f)
        x = _t(np.ones((8, 32)))
        a = cap(x).numpy()  # record run
        b = cap(x).numpy()  # replay 1
        c = cap(x).numpy()  # replay 2
        assert cap.stats["replay_runs"] >= 2
        # masks differ call-to-call (P[identical] ~ 2^-256)
        assert not np.allclose(b, c)
        assert not np.allclose(a, b)
        # but each call is a valid dropout output: zeros or 4.0 (=1/0.5*2)
        for arr in (a, b, c):
            vals = np.unique(np.round(arr, 5))
            assert set(vals).issubset({0.0, 4.0}), vals

    def test_rng_follows_global_seed_in_replay(self):
        def f(x):
            y = nn.functional.dropout(x, 0.5, training=True)
            if y.sum().item() > -1e9:
                z = y * 1.0
            else:
                z = y - 1.0
            return z

        cap = SOTCapture(f)
        x = _t(np.ones((4, 16)))
        cap(x)  # record
        paddle.seed(1234)
        a = cap(x).numpy()
        paddle.seed(1234)
        b = cap(x).numpy()
        np.testing.assert_allclose(a, b)  # same seed => same replay mask

    def test_eval_mode_capture_deterministic(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)
                self.drop = nn.Dropout(0.5)

            def forward(self, x):
                y = self.drop(self.fc(x))
                if y.sum().item() > -1e9:
                    return y * 2.0
                return y

        net = Net()
        net.eval()
        cap = SOTCapture(net.forward)
        x = _t(np.ones((2, 8)))
        a = cap(x).numpy()
        b = cap(x).numpy()
        np.testing.assert_allclose(a, b)  # eval: dropout is identity


class TestSOTEdgeCases:
    def test_returned_item_scalar_not_baked(self):
        """A frame returning t.item() must rebuild the scalar from the
        recorded source at replay, not return the record-time value."""
        def f(x):
            s = (x * x).sum()
            if x.sum().item() > -1e9:  # break so capture engages
                s = s + 0.0
            return s.item()

        cap = SOTCapture(f)
        a = _t([1.0, 2.0])
        b = _t([3.0, 4.0])
        assert abs(cap(a) - 5.0) < 1e-5
        assert abs(cap(b) - 25.0) < 1e-5  # replay with different data
        assert cap.stats["replay_runs"] >= 1

    def test_ndarray_arg_keyed_by_content(self):
        """Large ndarray args must key the trace by content, not repr."""
        def f(x, table):
            y = x * 1.0
            if float(y.sum()) > -1e9:
                y = y + float(np.asarray(table).sum())
            return y

        cap = SOTCapture(f)
        big1 = np.zeros(2000, np.float32)
        big2 = np.zeros(2000, np.float32)
        big2[1000] = 5.0  # same truncated repr, different content
        x = _t([1.0])
        r1 = cap(x, big1).numpy()
        r2 = cap(x, big2).numpy()
        np.testing.assert_allclose(r1, [1.0])
        np.testing.assert_allclose(r2, [6.0])

    def test_constant_tensor_guard(self):
        """Branching on a host-constant tensor must replay, not crash."""
        def f(x):
            if paddle.to_tensor(True):
                return x * 2.0
            return x

        cap = SOTCapture(f)
        x = _t([1.5])
        np.testing.assert_allclose(cap(x).numpy(), [3.0])
        np.testing.assert_allclose(cap(x).numpy(), [3.0])  # replay
