"""Conv-model training under AMP O2 — the production TPU recipe.

Round-4 regression: `preferred_element_type=f32` in the conv forward broke
JAX's conv transpose rule under bf16 (`conv_general_dilated(bf16 lhs, f32
cotangent)`), so no conv model could train under O2 and the ResNet-50
hardware bench rung died. Reference keeps conv on the AMP low-precision
white list (python/paddle/amp/amp_lists.py:33-105); these tests pin the
whole train step, not just the functional.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt

import jax


@pytest.fixture(autouse=True)
def _clear_mesh():
    yield
    dist.env.set_global_mesh(None)


def test_resnet18_train_step_amp_o2():
    from paddle_tpu.vision.models import resnet18

    paddle.seed(0)
    model = resnet18()
    optimizer = opt.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=model.parameters())
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    step = dist.DistributedTrainStep(
        model, lambda lg, lb: F.cross_entropy(lg, lb), optimizer, mesh=mesh,
        amp_level="O2", amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    img = paddle.to_tensor(rng.normal(size=(2, 3, 32, 32)).astype(np.float32))
    lab = paddle.to_tensor(rng.integers(0, 1000, (2, 1)))
    losses = [float(step(img, lab)) for _ in range(3)]
    assert all(np.isfinite(v) for v in losses), losses
    assert losses[-1] < losses[0], losses


def test_unet_train_step_amp_o2():
    """UNet has conv, conv_transpose (upsample path), groupnorm and attention
    — the full diffusion stack under O2."""
    from paddle_tpu.models import UNetModel, unet_tiny

    paddle.seed(0)
    cfg = unet_tiny()
    model = UNetModel(cfg)
    mse = nn.MSELoss()
    optimizer = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    rng = np.random.default_rng(1)
    noise = paddle.to_tensor(rng.normal(size=(2, 3, 8, 8)).astype(np.float32))
    t = paddle.to_tensor(np.array([10, 20]))
    ctx = paddle.to_tensor(np.zeros((2, 4, cfg.context_dim), np.float32))
    step = dist.DistributedTrainStep(
        model, lambda pred, target: mse(pred, target), optimizer, mesh=mesh,
        amp_level="O2", amp_dtype="bfloat16")
    noisy = paddle.to_tensor(
        rng.normal(size=(2, 3, 8, 8)).astype(np.float32))
    losses = [float(step([noisy, t, ctx], noise)) for _ in range(3)]
    assert all(np.isfinite(v) for v in losses), losses
    assert losses[-1] < losses[0], losses


def test_conv_transpose_bf16_grad():
    """Direct functional pin: transpose-conv backward in pure bf16."""
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(1, 4, 8, 8)).astype(np.float32)
    ).astype("bfloat16")
    w = paddle.to_tensor(
        np.random.default_rng(1).normal(size=(4, 6, 3, 3)).astype(np.float32)
    ).astype("bfloat16")
    x.stop_gradient = False
    w.stop_gradient = False
    out = F.conv2d_transpose(x, w, stride=2, padding=1)
    assert out.dtype == x.dtype
    out.sum().backward()
    assert tuple(x.grad.shape) == (1, 4, 8, 8)
    assert tuple(w.grad.shape) == (4, 6, 3, 3)
    assert np.isfinite(x.grad.astype("float32").numpy()).all()
