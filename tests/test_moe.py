"""MoE / expert-parallel tests.

Oracle pattern follows the reference's OpTest + hybrid-parallel parity tests
(test/collective/fleet/...): dense-dispatch MoE vs an explicit per-token
python loop, and the expert-parallel path vs the replicated run.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.distributed.models.moe import (
    ExpertFFN,
    GShardGate,
    MoELayer,
    NaiveGate,
    SwitchGate,
)


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    dist.env.set_global_mesh(None)


def _ref_moe(x, gate_w, gate_b, w1, b1, w2, b2, topk, normalize=True):
    """Per-token loop oracle: out[t] = sum_j w_j * FFN_{e_j}(x[t])."""
    import jax

    T, M = x.shape
    logits = x @ gate_w + gate_b
    probs = np.asarray(jax.nn.softmax(logits.astype(np.float32), axis=-1))
    out = np.zeros_like(x)
    for t in range(T):
        idx = np.argsort(-probs[t])[:topk]
        w = probs[t][idx]
        if normalize:
            w = w / max(w.sum(), 1e-9)
        for j, e in enumerate(idx):
            h = np.asarray(jax.nn.gelu(x[t] @ w1[e] + b1[e][0]))
            out[t] += w[j] * (h @ w2[e] + b2[e][0])
    return out


class TestMoENumerics:
    def test_naive_gate_matches_loop_oracle(self):
        paddle.seed(0)
        E, M, H, T = 4, 16, 32, 24
        layer = MoELayer(M, ExpertFFN(E, M, H), gate={"type": "naive", "top_k": 2})
        layer.eval()
        rng = np.random.RandomState(0)
        x = rng.randn(T, M).astype(np.float32)
        got = layer(paddle.to_tensor(x)).numpy()
        ref = _ref_moe(
            x,
            np.asarray(layer.gate.gate.weight._value),
            np.asarray(layer.gate.gate.bias._value),
            np.asarray(layer.experts.w1._value), np.asarray(layer.experts.b1._value),
            np.asarray(layer.experts.w2._value), np.asarray(layer.experts.b2._value),
            topk=2,
        )
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_list_experts_match_stacked(self):
        """Reference-parity list-of-experts path == stacked ExpertFFN path
        when weights are copied across."""
        paddle.seed(1)
        E, M, H = 4, 8, 16
        stacked = MoELayer(M, ExpertFFN(E, M, H), gate={"type": "naive", "top_k": 2})
        stacked.eval()

        class Expert(nn.Layer):
            def __init__(self, e):
                super().__init__()
                self.fc1 = nn.Linear(M, H)
                self.fc2 = nn.Linear(H, M)
                self.fc1.weight.set_value(stacked.experts.w1[e])
                self.fc1.bias.set_value(stacked.experts.b1[e].reshape([H]))
                self.fc2.weight.set_value(stacked.experts.w2[e])
                self.fc2.bias.set_value(stacked.experts.b2[e].reshape([M]))

            def forward(self, x):
                return self.fc2(F.gelu(self.fc1(x), approximate=True))

        listed = MoELayer(M, [Expert(e) for e in range(E)],
                          gate=stacked.gate)
        listed.eval()
        x = paddle.to_tensor(np.random.RandomState(2).randn(12, M).astype(np.float32))
        np.testing.assert_allclose(stacked(x).numpy(), listed(x).numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_switch_capacity_drops_overflow(self):
        """Tokens beyond expert capacity produce zero rows (reference
        gshard_gate.py capacity pruning semantics)."""
        paddle.seed(0)
        M = 8
        gate = SwitchGate(M, num_expert=2, capacity=(0.5, 0.5))
        # force every token to expert 0
        gate.gate.weight.set_value(paddle.to_tensor(
            np.zeros((M, 2), np.float32)))
        gate.gate.bias.set_value(paddle.to_tensor(np.array([10.0, -10.0], np.float32)))
        layer = MoELayer(M, ExpertFFN(2, M, 16), gate=gate)
        layer.eval()
        T = 8
        x = paddle.to_tensor(np.random.RandomState(3).randn(T, M).astype(np.float32))
        out = layer(x).numpy()
        cap = gate.capacity(T)  # ceil(0.5 * 8 / 2) = 2
        nonzero_rows = (np.abs(out) > 1e-7).any(axis=-1).sum()
        assert nonzero_rows == cap

    def test_gshard_gate_l_aux_and_grads(self):
        paddle.seed(0)
        E, M, H = 4, 8, 16
        layer = MoELayer(M, ExpertFFN(E, M, H), gate={"type": "gshard", "top_k": 2})
        x = paddle.to_tensor(np.random.RandomState(4).randn(16, M).astype(np.float32))
        out = layer(x)
        assert layer.l_aux is not None
        (out.sum() + layer.l_aux).backward()
        assert float(np.abs(np.asarray(layer.experts.w1.grad._value)).sum()) > 0
        assert layer.gate.gate.weight.grad is not None


class TestExpertParallel:
    def test_ep_sharded_train_step(self):
        """Experts sharded over the dp axis (the reference's moe_group=data
        group), whole step jitted over the mesh."""
        paddle.seed(0)
        mesh = dist.build_mesh(dp=4, mp=2)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.moe = MoELayer(8, ExpertFFN(4, 8, 16, ep_axis="dp"),
                                    gate={"type": "naive", "top_k": 2},
                                    ep_axis="dp")

            def forward(self, x):
                return self.moe(x)

        net = Net()
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
        step = dist.DistributedTrainStep(net, F.mse_loss, opt, mesh=mesh)
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        losses = [float(step(X, y).numpy()) for _ in range(8)]
        assert losses[-1] < losses[0]
        sh = step.params["moe.experts.w1"].sharding
        assert "dp" in str(sh.spec)


class TestFusedMoE:
    def test_fused_moe_matches_oracle(self):
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(5)
        E, M, H, T = 4, 8, 16, 12
        x = rng.randn(T, M).astype(np.float32) * 0.5
        gw = rng.randn(M, E).astype(np.float32) * 0.1
        w1 = rng.randn(E, M, 2 * H).astype(np.float32) * 0.1
        w2 = rng.randn(E, H, M).astype(np.float32) * 0.1
        got = IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                           paddle.to_tensor(w1), paddle.to_tensor(w2),
                           moe_topk=2).numpy()

        import jax
        logits = x @ gw
        probs = np.asarray(jax.nn.softmax(logits.astype(np.float32), axis=-1))
        ref = np.zeros_like(x)
        for t in range(T):
            idx = np.argsort(-probs[t])[:2]
            w = probs[t][idx]
            w = w / w.sum()
            for j, e in enumerate(idx):
                h = x[t] @ w1[e]
                u, g = h[:H], h[H:]
                h = np.asarray(jax.nn.silu(u)) * g
                ref[t] += w[j] * (h @ w2[e])
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_fused_moe_group_routing(self):
        """group_moe: per-group softmax + top-1 per group vs a numpy oracle."""
        import paddle_tpu.incubate.nn.functional as IF
        import jax

        rng = np.random.RandomState(7)
        E, M, H, T, K = 4, 8, 16, 12, 2
        x = rng.randn(T, M).astype(np.float32) * 0.5
        gw = rng.randn(M, E).astype(np.float32) * 0.5
        w1 = rng.randn(E, M, 2 * H).astype(np.float32) * 0.1
        w2 = rng.randn(E, H, M).astype(np.float32) * 0.1
        got = IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                           paddle.to_tensor(w1), paddle.to_tensor(w2),
                           moe_topk=K, group_moe=True).numpy()

        Eg = E // K
        logits = (x @ gw).reshape(T, K, Eg)
        gp = np.asarray(jax.nn.softmax(logits.astype(np.float32), axis=-1))
        ref = np.zeros_like(x)
        for t in range(T):
            sel = [(g, int(np.argmax(gp[t, g]))) for g in range(K)]
            w = np.asarray([gp[t, g, e] for g, e in sel])
            w = w / w.sum()  # norm_topk_prob default True
            for wj, (g, e) in zip(w, sel):
                eid = g * Eg + e
                h = x[t] @ w1[eid]
                u, gg = h[:H], h[H:]
                h = np.asarray(jax.nn.silu(u)) * gg
                ref[t] += wj * (h @ w2[eid])
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
        with pytest.raises(ValueError):
            IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                         paddle.to_tensor(w1), paddle.to_tensor(w2),
                         moe_topk=3, group_moe=True)

    def test_fused_moe_weight_only_int8(self):
        """weight_only_int8: int8 expert weights + per-out-channel scales
        reproduce the fp32 MoE within quantization error (reference cutlass
        weight-only grouped GEMM path)."""
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(7)
        E, M, H, T = 4, 8, 16, 12
        x = rng.randn(T, M).astype(np.float32) * 0.5
        gw = rng.randn(M, E).astype(np.float32) * 0.1
        w1 = rng.randn(E, M, 2 * H).astype(np.float32) * 0.1
        w2 = rng.randn(E, H, M).astype(np.float32) * 0.1

        def quant(w):
            scale = np.abs(w).max(axis=1) / 127.0  # [E, out]
            q = np.clip(np.round(w / scale[:, None, :]), -128, 127).astype(np.int8)
            return q, scale.astype(np.float32)

        q1, s1 = quant(w1)
        q2, s2 = quant(w2)
        ref = IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                           paddle.to_tensor(w1), paddle.to_tensor(w2),
                           moe_topk=2).numpy()
        got = IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                           paddle.to_tensor(q1), paddle.to_tensor(q2),
                           ffn1_scale=paddle.to_tensor(s1),
                           ffn2_scale=paddle.to_tensor(s2),
                           quant_method="weight_only_int8",
                           moe_topk=2).numpy()
        assert np.abs(got - ref).max() < 0.05 * np.abs(ref).max() + 1e-3


class TestGlobalScatterGather:
    def test_round_trip(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.utils import global_gather, global_scatter

        # the [src*dst*k, ...] stacked view needs the group size explicit —
        # alltoall_single now rejects shapes it cannot interpret instead of
        # silently returning the input
        grp = dist.new_group(list(range(4)))
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(16, 1))
        cnt = paddle.to_tensor(np.full((4,), 4, np.int64))
        s = global_scatter(x, cnt, cnt, group=grp)
        assert not np.allclose(s.numpy(), x.numpy())  # exchange happened
        g = global_gather(s, cnt, cnt, group=grp)
        np.testing.assert_allclose(g.numpy(), x.numpy())
