"""MoE / expert-parallel tests.

Oracle pattern follows the reference's OpTest + hybrid-parallel parity tests
(test/collective/fleet/...): dense-dispatch MoE vs an explicit per-token
python loop, and the expert-parallel path vs the replicated run.
"""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.distributed.models.moe import (
    ExpertFFN,
    GShardGate,
    MoELayer,
    NaiveGate,
    SwitchGate,
)


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    dist.env.set_global_mesh(None)


def _ref_moe(x, gate_w, gate_b, w1, b1, w2, b2, topk, normalize=True):
    """Per-token loop oracle: out[t] = sum_j w_j * FFN_{e_j}(x[t])."""
    import jax

    T, M = x.shape
    logits = x @ gate_w + gate_b
    probs = np.asarray(jax.nn.softmax(logits.astype(np.float32), axis=-1))
    out = np.zeros_like(x)
    for t in range(T):
        idx = np.argsort(-probs[t])[:topk]
        w = probs[t][idx]
        if normalize:
            w = w / max(w.sum(), 1e-9)
        for j, e in enumerate(idx):
            h = np.asarray(jax.nn.gelu(x[t] @ w1[e] + b1[e][0]))
            out[t] += w[j] * (h @ w2[e] + b2[e][0])
    return out


class TestMoENumerics:
    def test_naive_gate_matches_loop_oracle(self):
        paddle.seed(0)
        E, M, H, T = 4, 16, 32, 24
        layer = MoELayer(M, ExpertFFN(E, M, H), gate={"type": "naive", "top_k": 2})
        layer.eval()
        rng = np.random.RandomState(0)
        x = rng.randn(T, M).astype(np.float32)
        got = layer(paddle.to_tensor(x)).numpy()
        ref = _ref_moe(
            x,
            np.asarray(layer.gate.gate.weight._value),
            np.asarray(layer.gate.gate.bias._value),
            np.asarray(layer.experts.w1._value), np.asarray(layer.experts.b1._value),
            np.asarray(layer.experts.w2._value), np.asarray(layer.experts.b2._value),
            topk=2,
        )
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_list_experts_match_stacked(self):
        """Reference-parity list-of-experts path == stacked ExpertFFN path
        when weights are copied across."""
        paddle.seed(1)
        E, M, H = 4, 8, 16
        stacked = MoELayer(M, ExpertFFN(E, M, H), gate={"type": "naive", "top_k": 2})
        stacked.eval()

        class Expert(nn.Layer):
            def __init__(self, e):
                super().__init__()
                self.fc1 = nn.Linear(M, H)
                self.fc2 = nn.Linear(H, M)
                self.fc1.weight.set_value(stacked.experts.w1[e])
                self.fc1.bias.set_value(stacked.experts.b1[e].reshape([H]))
                self.fc2.weight.set_value(stacked.experts.w2[e])
                self.fc2.bias.set_value(stacked.experts.b2[e].reshape([M]))

            def forward(self, x):
                return self.fc2(F.gelu(self.fc1(x), approximate=True))

        listed = MoELayer(M, [Expert(e) for e in range(E)],
                          gate=stacked.gate)
        listed.eval()
        x = paddle.to_tensor(np.random.RandomState(2).randn(12, M).astype(np.float32))
        np.testing.assert_allclose(stacked(x).numpy(), listed(x).numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_switch_capacity_drops_overflow(self):
        """Tokens beyond expert capacity produce zero rows (reference
        gshard_gate.py capacity pruning semantics)."""
        paddle.seed(0)
        M = 8
        gate = SwitchGate(M, num_expert=2, capacity=(0.5, 0.5))
        # force every token to expert 0
        gate.gate.weight.set_value(paddle.to_tensor(
            np.zeros((M, 2), np.float32)))
        gate.gate.bias.set_value(paddle.to_tensor(np.array([10.0, -10.0], np.float32)))
        layer = MoELayer(M, ExpertFFN(2, M, 16), gate=gate)
        layer.eval()
        T = 8
        x = paddle.to_tensor(np.random.RandomState(3).randn(T, M).astype(np.float32))
        out = layer(x).numpy()
        cap = gate.capacity(T)  # ceil(0.5 * 8 / 2) = 2
        nonzero_rows = (np.abs(out) > 1e-7).any(axis=-1).sum()
        assert nonzero_rows == cap

    def test_gshard_gate_l_aux_and_grads(self):
        paddle.seed(0)
        E, M, H = 4, 8, 16
        layer = MoELayer(M, ExpertFFN(E, M, H), gate={"type": "gshard", "top_k": 2})
        x = paddle.to_tensor(np.random.RandomState(4).randn(16, M).astype(np.float32))
        out = layer(x)
        assert layer.l_aux is not None
        (out.sum() + layer.l_aux).backward()
        assert float(np.abs(np.asarray(layer.experts.w1.grad._value)).sum()) > 0
        assert layer.gate.gate.weight.grad is not None


class TestExpertParallel:
    def test_ep_sharded_train_step(self):
        """Experts sharded over the dp axis (the reference's moe_group=data
        group), whole step jitted over the mesh."""
        paddle.seed(0)
        mesh = dist.build_mesh(dp=4, mp=2)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.moe = MoELayer(8, ExpertFFN(4, 8, 16, ep_axis="dp"),
                                    gate={"type": "naive", "top_k": 2},
                                    ep_axis="dp")

            def forward(self, x):
                return self.moe(x)

        net = Net()
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
        step = dist.DistributedTrainStep(net, F.mse_loss, opt, mesh=mesh)
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        losses = [float(step(X, y).numpy()) for _ in range(8)]
        assert losses[-1] < losses[0]
        sh = step.params["moe.experts.w1"].sharding
        assert "dp" in str(sh.spec)


class TestFusedMoE:
    def test_fused_moe_matches_oracle(self):
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(5)
        E, M, H, T = 4, 8, 16, 12
        x = rng.randn(T, M).astype(np.float32) * 0.5
        gw = rng.randn(M, E).astype(np.float32) * 0.1
        w1 = rng.randn(E, M, 2 * H).astype(np.float32) * 0.1
        w2 = rng.randn(E, H, M).astype(np.float32) * 0.1
        got = IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                           paddle.to_tensor(w1), paddle.to_tensor(w2),
                           moe_topk=2).numpy()

        import jax
        logits = x @ gw
        probs = np.asarray(jax.nn.softmax(logits.astype(np.float32), axis=-1))
        ref = np.zeros_like(x)
        for t in range(T):
            idx = np.argsort(-probs[t])[:2]
            w = probs[t][idx]
            w = w / w.sum()
            for j, e in enumerate(idx):
                h = x[t] @ w1[e]
                u, g = h[:H], h[H:]
                h = np.asarray(jax.nn.silu(u)) * g
                ref[t] += w[j] * (h @ w2[e])
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_fused_moe_group_routing(self):
        """group_moe: per-group softmax + top-1 per group vs a numpy oracle."""
        import paddle_tpu.incubate.nn.functional as IF
        import jax

        rng = np.random.RandomState(7)
        E, M, H, T, K = 4, 8, 16, 12, 2
        x = rng.randn(T, M).astype(np.float32) * 0.5
        gw = rng.randn(M, E).astype(np.float32) * 0.5
        w1 = rng.randn(E, M, 2 * H).astype(np.float32) * 0.1
        w2 = rng.randn(E, H, M).astype(np.float32) * 0.1
        got = IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                           paddle.to_tensor(w1), paddle.to_tensor(w2),
                           moe_topk=K, group_moe=True).numpy()

        Eg = E // K
        logits = (x @ gw).reshape(T, K, Eg)
        gp = np.asarray(jax.nn.softmax(logits.astype(np.float32), axis=-1))
        ref = np.zeros_like(x)
        for t in range(T):
            sel = [(g, int(np.argmax(gp[t, g]))) for g in range(K)]
            w = np.asarray([gp[t, g, e] for g, e in sel])
            w = w / w.sum()  # norm_topk_prob default True
            for wj, (g, e) in zip(w, sel):
                eid = g * Eg + e
                h = x[t] @ w1[eid]
                u, gg = h[:H], h[H:]
                h = np.asarray(jax.nn.silu(u)) * gg
                ref[t] += wj * (h @ w2[eid])
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
        with pytest.raises(ValueError):
            IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                         paddle.to_tensor(w1), paddle.to_tensor(w2),
                         moe_topk=3, group_moe=True)

    def test_fused_moe_weight_only_int8(self):
        """weight_only_int8: int8 expert weights + per-out-channel scales
        reproduce the fp32 MoE within quantization error (reference cutlass
        weight-only grouped GEMM path)."""
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(7)
        E, M, H, T = 4, 8, 16, 12
        x = rng.randn(T, M).astype(np.float32) * 0.5
        gw = rng.randn(M, E).astype(np.float32) * 0.1
        w1 = rng.randn(E, M, 2 * H).astype(np.float32) * 0.1
        w2 = rng.randn(E, H, M).astype(np.float32) * 0.1

        def quant(w):
            scale = np.abs(w).max(axis=1) / 127.0  # [E, out]
            q = np.clip(np.round(w / scale[:, None, :]), -128, 127).astype(np.int8)
            return q, scale.astype(np.float32)

        q1, s1 = quant(w1)
        q2, s2 = quant(w2)
        ref = IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                           paddle.to_tensor(w1), paddle.to_tensor(w2),
                           moe_topk=2).numpy()
        got = IF.fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                           paddle.to_tensor(q1), paddle.to_tensor(q2),
                           ffn1_scale=paddle.to_tensor(s1),
                           ffn2_scale=paddle.to_tensor(s2),
                           quant_method="weight_only_int8",
                           moe_topk=2).numpy()
        assert np.abs(got - ref).max() < 0.05 * np.abs(ref).max() + 1e-3


class TestGroupedGemm:
    """ops/pallas/grouped_gemm.py under the interpreter (the CUDA-vs-NumPy
    OpTest pattern): ragged forward semantics + VJP exactness."""

    def test_ragged_forward_and_dead_tiles(self, pallas_interpret_unless_hw):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.grouped_gemm import (grouped_matmul,
                                                        row_stride)

        rng = np.random.RandomState(0)
        E, K, N = 4, 16, 24
        sizes = np.array([5, 0, 8, 3], np.int32)
        R = row_stride(8)
        lhs = np.zeros((E * R, K), np.float32)
        for e in range(E):
            lhs[e * R:e * R + sizes[e]] = rng.randn(sizes[e], K)
        rhs = rng.randn(E, K, N).astype(np.float32)
        out = np.asarray(grouped_matmul(jnp.asarray(lhs), jnp.asarray(rhs),
                                        jnp.asarray(sizes)))
        ref = np.stack([lhs.reshape(E, R, K)[e] @ rhs[e]
                        for e in range(E)]).reshape(E * R, N)
        # live groups exact; the all-dead group's tiles are ZERO (skipped
        # tiles write zeros, never garbage)
        np.testing.assert_array_equal(out, ref)
        assert not out[R:2 * R].any()

    def test_vjp_matches_masked_einsum(self, pallas_interpret_unless_hw):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.grouped_gemm import grouped_matmul

        rng = np.random.RandomState(1)
        E, K, N, R, bm = 4, 16, 24, 8, 8
        sizes = np.array([5, 0, 8, 3], np.int32)
        lhs = rng.randn(E * R, K).astype(np.float32)  # garbage in dead rows
        rhs = rng.randn(E, K, N).astype(np.float32)
        co = rng.randn(E * R, N).astype(np.float32)
        computed = np.minimum(-(-sizes // bm) * bm, R)
        mask = (np.arange(R)[None, :] < computed[:, None]).reshape(E * R)

        def f(l, r):
            return (grouped_matmul(l, r, jnp.asarray(sizes)) * co).sum()

        def fref(l, r):
            o = jnp.einsum("erk,ekn->ern", l.reshape(E, R, K),
                           r).reshape(E * R, N)
            o = jnp.where(jnp.asarray(mask)[:, None], o, 0.0)
            return (o * co).sum()

        g = jax.grad(f, (0, 1))(jnp.asarray(lhs), jnp.asarray(rhs))
        gr = jax.grad(fref, (0, 1))(jnp.asarray(lhs), jnp.asarray(rhs))
        np.testing.assert_array_equal(np.asarray(g[0]), np.asarray(gr[0]))
        np.testing.assert_array_equal(np.asarray(g[1]), np.asarray(gr[1]))

    def test_autotune_consult_recorded(self, pallas_interpret_unless_hw):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas import autotune
        from paddle_tpu.ops.pallas.grouped_gemm import grouped_matmul

        rng = np.random.RandomState(2)
        grouped_matmul(jnp.asarray(rng.randn(16, 8).astype(np.float32)),
                       jnp.asarray(rng.randn(2, 8, 16).astype(np.float32)),
                       jnp.asarray(np.array([8, 4], np.int32)))
        rec = autotune.chosen_tiles().get("grouped_gemm")
        assert rec is not None and rec["source"] in (
            "default", "tuned", "measured", "fixed")


def _moe_with_grads(gate_cfg, fast, x, seed=7, E=4, M=16, H=32,
                    train=False, capacity=None):
    """(out, {param grads}) for one fresh seeded layer; fast/dense toggled
    via the captured-at-trace env (fresh dispatch per call)."""
    os.environ["PADDLE_TPU_MOE_FAST"] = "1" if fast else "0"
    from paddle_tpu.framework.core import clear_dispatch_cache

    clear_dispatch_cache()
    paddle.seed(seed)
    cfg = dict(gate_cfg)
    if capacity is not None:
        cfg["capacity"] = capacity
    layer = MoELayer(M, ExpertFFN(E, M, H), gate=cfg)
    layer.train() if train else layer.eval()
    xt = paddle.to_tensor(x)
    out = layer(xt)
    (out.sum() + layer.l_aux).backward()
    grads = {
        "w1": np.asarray(layer.experts.w1.grad._value),
        "w2": np.asarray(layer.experts.w2.grad._value),
        "gate_w": np.asarray(layer.gate.gate.weight.grad._value),
    }
    return out.numpy(), grads, float(np.asarray(layer.l_aux._value))


class TestFastPathParity:
    """Sorted-dispatch fast path vs the dense einsum oracle
    (PADDLE_TPU_MOE_FAST flipped either way): values + grads + l_aux.
    rtol=0; the tiny atol absorbs the one-FMA difference between XLA's
    fused einsum contraction and the explicit weighted sum (the products
    and routing are bit-identical — pinpointed in ISSUE-14 review)."""

    ATOL = 2e-6

    @pytest.fixture(autouse=True)
    def _restore_toggle(self):
        prev = os.environ.get("PADDLE_TPU_MOE_FAST")
        yield
        if prev is None:
            os.environ.pop("PADDLE_TPU_MOE_FAST", None)
        else:
            os.environ["PADDLE_TPU_MOE_FAST"] = prev

    @pytest.mark.parametrize("gate_cfg", [
        {"type": "naive", "top_k": 2},
        {"type": "gshard", "top_k": 2},
        {"type": "switch", "top_k": 1},
    ], ids=["naive_top2", "gshard_top2", "switch_top1"])
    def test_values_grads_laux_match_dense(self, gate_cfg):
        x = np.random.RandomState(0).randn(24, 16).astype(np.float32)
        out_d, g_d, l_d = _moe_with_grads(gate_cfg, fast=False, x=x)
        out_f, g_f, l_f = _moe_with_grads(gate_cfg, fast=True, x=x)
        np.testing.assert_allclose(out_f, out_d, rtol=0, atol=self.ATOL)
        assert l_f == l_d
        for k in g_d:
            np.testing.assert_allclose(g_f[k], g_d[k], rtol=0,
                                       atol=self.ATOL)

    def test_capacity_drop_parity(self):
        """Forced overflow (cap < routed tokens): the fast path's positional
        drop mask keeps exactly the rows the dense one-hot pruning keeps."""
        x = np.random.RandomState(1).randn(16, 16).astype(np.float32)
        cfg = {"type": "switch", "top_k": 1}
        out_d, g_d, _ = _moe_with_grads(cfg, fast=False, x=x,
                                        capacity=(0.5, 0.5))
        out_f, g_f, _ = _moe_with_grads(cfg, fast=True, x=x,
                                        capacity=(0.5, 0.5))
        np.testing.assert_allclose(out_f, out_d, rtol=0, atol=self.ATOL)
        nz = (np.abs(out_f) > 1e-7).any(-1).sum()
        assert 0 < nz < 16  # drops actually happened
        for k in g_d:
            np.testing.assert_allclose(g_f[k], g_d[k], rtol=0,
                                       atol=self.ATOL)

    def test_bf16_parity(self):
        import jax.numpy as jnp

        x32 = np.random.RandomState(2).randn(16, 16).astype(np.float32)
        for fast in (False, True):
            os.environ["PADDLE_TPU_MOE_FAST"] = "1" if fast else "0"
            from paddle_tpu.framework.core import clear_dispatch_cache

            clear_dispatch_cache()
            paddle.seed(3)
            layer = MoELayer(16, ExpertFFN(4, 16, 32),
                             gate={"type": "naive", "top_k": 2})
            layer.eval()
            x = paddle.to_tensor(x32).astype("bfloat16")
            out = layer(x)
            res = np.asarray(out.astype("float32").numpy())
            if fast:
                np.testing.assert_allclose(res, ref, rtol=0, atol=0.1)
            else:
                ref = res
        os.environ.pop("PADDLE_TPU_MOE_FAST", None)

    def test_kernel_path_parity(self, pallas_interpret_unless_hw):
        """One parity case with the Pallas grouped GEMM actually live
        (interpret mode) instead of the CPU einsum fallback."""
        from paddle_tpu.ops.pallas.grouped_gemm import kernel_usable

        assert kernel_usable()
        x = np.random.RandomState(3).randn(24, 16).astype(np.float32)
        cfg = {"type": "gshard", "top_k": 2}
        out_d, g_d, _ = _moe_with_grads(cfg, fast=False, x=x)
        out_f, g_f, _ = _moe_with_grads(cfg, fast=True, x=x)
        np.testing.assert_allclose(out_f, out_d, rtol=0, atol=self.ATOL)
        for k in g_d:
            np.testing.assert_allclose(g_f[k], g_d[k], rtol=0,
                                       atol=self.ATOL)


class TestGateAuxLoss:
    """ISSUE-14 satellite pin: the load-balance aux loss comes from
    PRE-capacity-drop router stats — post-drop stats are biased toward
    already-overflowed experts (the overflow is what the drop removed)."""

    @pytest.mark.parametrize("gtype,topk", [("switch", 1), ("gshard", 2)])
    def test_l_aux_invariant_to_capacity(self, gtype, topk):
        x = np.random.RandomState(4).randn(32, 8).astype(np.float32)
        vals = []
        for cap in ((0.25, 0.25), (10.0, 10.0)):
            paddle.seed(5)
            layer = MoELayer(8, ExpertFFN(4, 8, 16),
                             gate={"type": gtype, "top_k": topk,
                                   "capacity": cap})
            layer.eval()
            layer(paddle.to_tensor(x))
            vals.append(float(np.asarray(layer.l_aux._value)))
        assert vals[0] == vals[1]


class TestExpertParallelFast:
    """ep-sharded fast path on the 8-device CPU mesh: parity with the dense
    oracle through a jitted DistributedTrainStep, a2a chunk overlap
    schedule on, and the a2a accounting visible to the observability
    registry + comm_task observers."""

    def _losses(self, fast, chunks, steps=2):
        os.environ["PADDLE_TPU_MOE_FAST"] = "1" if fast else "0"
        os.environ["PADDLE_TPU_MOE_A2A_CHUNKS"] = str(chunks)
        from paddle_tpu.framework.core import clear_dispatch_cache

        clear_dispatch_cache()
        paddle.seed(0)
        mesh = dist.build_mesh(ep=4, mp=2)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.moe = MoELayer(8, ExpertFFN(4, 8, 16, ep_axis="ep"),
                                    gate={"type": "naive", "top_k": 2},
                                    ep_axis="ep")

            def forward(self, x):
                return self.moe(x)

        net = Net()
        opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                     parameters=net.parameters())
        step = dist.DistributedTrainStep(net, F.mse_loss, opt, mesh=mesh,
                                         batch_axes=("dp", "ep"))
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        losses = [float(step(X, y).numpy()) for _ in range(steps)]
        sh = step.params["moe.experts.w1"].sharding
        return losses, str(sh.spec)

    @pytest.fixture(autouse=True)
    def _restore(self):
        prev = {k: os.environ.get(k) for k in
                ("PADDLE_TPU_MOE_FAST", "PADDLE_TPU_MOE_A2A_CHUNKS")}
        yield
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        dist.env.set_global_mesh(None)

    def test_ep_fast_matches_dense_with_overlap_on(self):
        from paddle_tpu.distributed import comm_watchdog
        from paddle_tpu.observability.metrics import default_registry

        dense, _ = self._losses(fast=False, chunks=2)
        seen = []
        obs = comm_watchdog.add_task_observer(
            lambda desc, t0, t1, kind: seen.append((desc, kind)))
        try:
            reg = default_registry()
            base = reg.snapshot()
            fast, spec = self._losses(fast=True, chunks=2)
            delta = reg.delta(base)
        finally:
            comm_watchdog.remove_task_observer(obs)
        for a, b in zip(dense, fast):
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-6)
        assert "ep" in spec  # expert weights actually sharded on ep
        # a2a accounting: counters + kind="a2a" intervals per executed step
        assert delta.get("collective_bytes_total{op=all_to_all}", 0) > 0
        assert delta.get("collective_calls_total{op=all_to_all}", 0) >= 2
        assert any(kind == "a2a" for _d, kind in seen)

    def test_emit_step_anchoring_follows_schedule(self):
        """Chunked records land behind now (covered by the open compute
        span); unchunked ones land ahead of it (counted exposed) — the
        instrument-side half of the PADDLE_TPU_MOE_A2A_CHUNKS A/B."""
        import time

        from paddle_tpu.distributed import comm_watchdog, moe_comm

        seen = []
        obs = comm_watchdog.add_task_observer(
            lambda d, t0, t1, k: seen.append((d, t0, t1, k)))
        try:
            now = time.perf_counter_ns()
            moe_comm.emit_step(
                ({"desc": "a", "bytes": 10 ** 9, "calls": 2,
                  "overlapped": True},
                 {"desc": "b", "bytes": 10 ** 9, "calls": 2,
                  "overlapped": False}), floor_ns=now)
        finally:
            comm_watchdog.remove_task_observer(obs)
        (da, a0, a1, ka), (db, b0, b1, kb) = seen
        assert ka == kb == "a2a" and "[est]" in da
        assert a0 >= now and a1 <= time.perf_counter_ns()  # floored, behind
        assert b0 >= now and b1 > b0 and b1 > a1           # ahead: exposed

    @pytest.mark.slow
    def test_ep_fast_chunks_off_parity(self):
        """chunks=1 (overlap schedule off) must be numerically identical
        to chunks=2 — chunking only re-tiles, never re-routes."""
        one, _ = self._losses(fast=True, chunks=1)
        two, _ = self._losses(fast=True, chunks=2)
        np.testing.assert_allclose(one, two, rtol=0, atol=1e-6)


class TestGlobalScatterGather:
    def test_round_trip(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.utils import global_gather, global_scatter

        # the [src*dst*k, ...] stacked view needs the group size explicit —
        # alltoall_single now rejects shapes it cannot interpret instead of
        # silently returning the input
        grp = dist.new_group(list(range(4)))
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(16, 1))
        cnt = paddle.to_tensor(np.full((4,), 4, np.int64))
        s = global_scatter(x, cnt, cnt, group=grp)
        assert not np.allclose(s.numpy(), x.numpy())  # exchange happened
        g = global_gather(s, cnt, cnt, group=grp)
        np.testing.assert_allclose(g.numpy(), x.numpy())
