"""amp.debugging nan/inf checker + operator stats + device memory stats
(reference: python/paddle/amp/debugging.py:56,321;
paddle/fluid/eager/nan_inf_utils.cc; paddle/phi/core/memory/stats.cc)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.amp.debugging import (
    DebugMode,
    NumericError,
    TensorCheckerConfig,
    check_numerics,
    collect_operator_stats,
    disable_tensor_checker,
    enable_tensor_checker,
    operator_stats,
)


def test_tensor_checker_aborts_on_nan():
    cfg = TensorCheckerConfig(
        enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT)
    enable_tensor_checker(cfg)
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(NumericError, match="divide"):
            _ = x / paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    finally:
        disable_tensor_checker()
    # hook uninstalled: the same op no longer raises
    bad = x / paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    assert not np.isfinite(bad.numpy()).all()


def test_tensor_checker_warn_mode_and_skip_list():
    cfg = TensorCheckerConfig(
        enable=True, debug_mode=DebugMode.CHECK_NAN_INF,
        skipped_op_list={"divide"})
    enable_tensor_checker(cfg)
    try:
        x = paddle.to_tensor(np.array([1.0], np.float32))
        z = paddle.to_tensor(np.array([0.0], np.float32))
        _ = x / z  # skipped op: no warning, no raise
        with pytest.warns(UserWarning, match="log"):
            _ = paddle.log(z - 1.0)
    finally:
        disable_tensor_checker()


def test_check_numerics():
    t = paddle.to_tensor(np.array([1.0, np.nan, np.inf, 0.0], np.float32))
    with pytest.raises(NumericError):
        check_numerics(t, "op", "t")
    n_nan, n_inf, n_zero = check_numerics(
        t, "op", "t", debug_mode=DebugMode.CHECK_NAN_INF)
    assert (int(n_nan), int(n_inf), int(n_zero)) == (1, 1, 1)


def test_collect_operator_stats(capsys):
    with collect_operator_stats():
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        _ = paddle.matmul(x, x)
        _ = x + x
        stats = operator_stats()
    assert "matmul" in stats
    assert any("float32" in dt for dt in stats["matmul"])
    out = capsys.readouterr().out
    assert "op list" in out and "matmul" in out


def test_device_memory_stats():
    import paddle_tpu.device as device

    base = device.memory_allocated()
    x = paddle.to_tensor(np.ones((256, 256), np.float32))
    allocated = device.memory_allocated()
    assert allocated >= base
    assert device.max_memory_allocated() >= allocated
    stats = device.memory_stats()
    assert "bytes_in_use" in stats and "peak_bytes_in_use" in stats
    device.reset_max_memory_allocated()
    assert device.max_memory_allocated() <= device.memory_allocated() + 1
    del x
