"""Ring attention / context parallelism tests.

The reference has no ring attention (SURVEY §5.7) — the oracle is dense
attention on the full sequence; the ring result over a sep-sharded mesh must
match it exactly (same online-softmax math as flash attention).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn.functional as F
from paddle_tpu.parallel.ring import ring_attention_spmd


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    dist.env.set_global_mesh(None)


def _dense(q, k, v, causal):
    D = q.shape[-1]
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H:
        k = np.repeat(k, H // Hkv, axis=2)
        v = np.repeat(v, H // Hkv, axis=2)
    S = q.shape[1]
    logits = np.einsum("bihd,bjhd->bhij", q, k) / np.sqrt(D)
    if causal:
        m = np.tril(np.ones((S, S), bool))
        logits = np.where(m, logits, -1e30)
    p = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    return np.einsum("bhij,bjhd->bihd", p, v)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        mesh = dist.build_mesh(sep=4)
        rng = np.random.RandomState(0)
        B, S, H, D = 2, 32, 4, 8
        q, k, v = (rng.randn(B, S, H, D).astype(np.float32) for _ in range(3))
        out = F.ring_flash_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                     paddle.to_tensor(v), causal=causal)
        np.testing.assert_allclose(out.numpy(), _dense(q, k, v, causal),
                                   rtol=1e-5, atol=1e-5)

    def test_gqa(self):
        mesh = dist.build_mesh(sep=8)
        rng = np.random.RandomState(1)
        B, S, H, Hkv, D = 1, 64, 8, 2, 16
        q = rng.randn(B, S, H, D).astype(np.float32)
        k = rng.randn(B, S, Hkv, D).astype(np.float32)
        v = rng.randn(B, S, Hkv, D).astype(np.float32)
        out = ring_attention_spmd(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                  mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), _dense(q, k, v, True),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_dense(self):
        mesh = dist.build_mesh(sep=4)
        rng = np.random.RandomState(2)
        B, S, H, D = 1, 16, 2, 8
        q, k, v = (jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
                   for _ in range(3))

        def ring_loss(q, k, v):
            return ring_attention_spmd(q, k, v, mesh, causal=True).sum()

        def dense_loss(q, k, v):
            logits = jnp.einsum("bihd,bjhd->bhij", q, k) / np.sqrt(D)
            m = jnp.tril(jnp.ones((S, S), bool))
            logits = jnp.where(m, logits, -1e30)
            p = jax.nn.softmax(logits, -1)
            return jnp.einsum("bhij,bjhd->bihd", p, v).sum()

        g1 = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_fallback_without_mesh(self):
        rng = np.random.RandomState(3)
        B, S, H, D = 1, 8, 2, 4
        q, k, v = (rng.randn(B, S, H, D).astype(np.float32) for _ in range(3))
        out = F.ring_flash_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                     paddle.to_tensor(v), causal=True)
        np.testing.assert_allclose(out.numpy(), _dense(q, k, v, True),
                                   rtol=1e-5, atol=1e-5)


class TestContextParallelGPT:
    def test_gpt_cp_trains_and_matches_dense_loss(self):
        """GPT with context_parallel over sep=4 (+dp=2): first-step loss must
        equal the replicated no-CP run (exact attention), and training must
        make progress — the hybrid_parallel parity-test pattern."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainingCriterion

        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, size=(2, 32)).astype(np.int32)
        labels = rng.randint(0, 128, size=(2, 32)).astype(np.int32)

        def run(cp):
            paddle.seed(11)
            dist.env.set_global_mesh(None)
            cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                            num_heads=4, max_position_embeddings=64,
                            context_parallel=cp)
            mesh = dist.build_mesh(dp=2, sep=4) if cp else dist.build_mesh(dp=2)
            model = GPTForCausalLM(cfg)
            crit = GPTPretrainingCriterion(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=model.parameters())
            step = dist.DistributedTrainStep(
                model, lambda logits, y: crit(logits, y), opt, mesh=mesh)
            return [float(step(paddle.to_tensor(ids), paddle.to_tensor(labels)).numpy())
                    for _ in range(5)]

        cp_losses = run(True)
        ref_losses = run(False)
        np.testing.assert_allclose(cp_losses[0], ref_losses[0], rtol=1e-4)
        assert cp_losses[-1] < cp_losses[0]
