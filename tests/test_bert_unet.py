"""BERT + diffusion UNet model families (BASELINE.md configs: "BERT-base /
ERNIE-1.0 pretraining (fleet data-parallel only)" and "Stable Diffusion
UNet: conv + cross-attn")."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.models import (
    BertForPretraining,
    BertForSequenceClassification,
    BertModel,
    BertPretrainingCriterion,
    UNetModel,
    bert_tiny,
    unet_tiny,
)


class TestBert:
    def test_model_shapes_and_mask(self):
        paddle.seed(0)
        cfg = bert_tiny()
        m = BertModel(cfg)
        m.eval()
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 16)))
        tt = paddle.to_tensor((rng.random((2, 16)) > 0.5).astype(np.int32))
        am = np.ones((2, 16), np.int32)
        am[1, 8:] = 0  # padding on lane 1
        seq, pooled = m(ids, tt, paddle.to_tensor(am))
        assert tuple(seq.shape) == (2, 16, cfg.hidden_size)
        assert tuple(pooled.shape) == (2, cfg.hidden_size)
        # masked positions must not influence lane 1's pooled output
        ids2 = ids.numpy().copy()
        ids2[1, 8:] = (ids2[1, 8:] + 7) % cfg.vocab_size
        _, pooled2 = m(paddle.to_tensor(ids2), tt, paddle.to_tensor(am))
        np.testing.assert_allclose(pooled.numpy()[1], pooled2.numpy()[1],
                                   atol=1e-5)

    def test_pretraining_loss_decreases(self):
        paddle.seed(0)
        cfg = bert_tiny()
        model = BertForPretraining(cfg)
        crit = BertPretrainingCriterion(cfg)
        model.train()
        o = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
        rng = np.random.default_rng(1)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 32)))
        mpos = paddle.to_tensor(rng.integers(0, 32, (4, 6)))
        mlab = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 6)))
        nsp = paddle.to_tensor(rng.integers(0, 2, (4,)))
        losses = []
        for _ in range(6):
            mlm, nspl = model(ids, masked_positions=mpos)
            loss = crit(mlm, nspl, mlab, nsp)
            loss.backward()
            o.step(); o.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses
        # MLM head gathers masked slots only: [B, M, V], not [B, S, V]
        assert tuple(mlm.shape) == (4, 6, cfg.vocab_size)

    def test_mlm_ignore_index(self):
        cfg = bert_tiny()
        crit = BertPretrainingCriterion(cfg)
        mlm = paddle.to_tensor(np.zeros((1, 3, cfg.vocab_size), np.float32))
        nsp = paddle.to_tensor(np.zeros((1, 2), np.float32))
        lab_all = paddle.to_tensor(np.array([[1, 2, 3]]))
        lab_ign = paddle.to_tensor(np.array([[1, -100, -100]]))
        nl = paddle.to_tensor(np.array([0]))
        l1 = float(crit(mlm, nsp, lab_all, nl).numpy())
        l2 = float(crit(mlm, nsp, lab_ign, nl).numpy())
        np.testing.assert_allclose(l1, l2, rtol=1e-6)  # uniform logits

    def test_sequence_classification_dp_trains(self):
        """BERT fine-tuning through the compiled DP step (the BASELINE
        fleet-data-parallel config)."""
        paddle.seed(0)
        cfg = bert_tiny()
        model = BertForSequenceClassification(cfg, num_classes=2)
        ce = nn.CrossEntropyLoss()
        model.train()
        mesh = dist.build_mesh(dp=4)
        step = dist.DistributedTrainStep(
            model, lambda lg, lb: ce(lg, lb),
            opt.AdamW(learning_rate=5e-4, parameters=model.parameters()),
            mesh=mesh)
        rng = np.random.default_rng(2)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (8, 16)))
        y = paddle.to_tensor(rng.integers(0, 2, (8,)))
        losses = [float(step(ids, y)) for _ in range(5)]
        dist.env.set_global_mesh(None)
        assert losses[-1] < losses[0], losses


class TestUNet:
    def test_forward_shape_and_context(self):
        paddle.seed(0)
        cfg = unet_tiny()
        m = UNetModel(cfg)
        m.eval()
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(2, 3, 16, 16)).astype(np.float32))
        t = paddle.to_tensor(np.array([3, 500]))
        ctx = paddle.to_tensor(rng.normal(size=(2, 5, cfg.context_dim))
                               .astype(np.float32))
        out = m(x, t, ctx)
        assert tuple(out.shape) == (2, 3, 16, 16)
        assert np.isfinite(out.numpy()).all()
        # cross-attention context actually conditions the output
        ctx2 = paddle.to_tensor(rng.normal(size=(2, 5, cfg.context_dim))
                                .astype(np.float32))
        out2 = m(x, t, ctx2)
        assert np.abs(out.numpy() - out2.numpy()).max() > 1e-6

    def test_denoising_trains(self):
        paddle.seed(0)
        cfg = unet_tiny()
        m = UNetModel(cfg)
        m.train()
        mse = nn.MSELoss()
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        rng = np.random.default_rng(1)
        clean = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        noise = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        noisy = paddle.to_tensor(clean + 0.5 * noise)
        t = paddle.to_tensor(np.array([10, 20]))
        ctx = paddle.to_tensor(np.zeros((2, 4, cfg.context_dim), np.float32))
        losses = []
        for _ in range(5):
            pred = m(noisy, t, ctx)
            loss = mse(pred, paddle.to_tensor(noise))
            loss.backward()
            o.step(); o.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses
