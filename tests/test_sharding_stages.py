"""GroupSharded stage 1/2/3 internals (reference:
python/paddle/distributed/fleet/meta_parallel/sharding/group_sharded_stage2.py:47,
group_sharded_stage3.py:85, group_sharded_optimizer_stage2.py:53; API
python/paddle/distributed/sharding/group_sharded.py:50).

The TPU formulation: stage 1 shards optimizer states over the `sharding`
axis; stage 2 additionally reduce-scatters grads to their owner shard and
computes the update sharded (then all-gathers fresh params); stage 3 shards
the parameters themselves (FSDP). Asserts numeric parity across stages plus
the per-device footprint reductions each stage buys, and the host-offload
placement of optimizer states.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist

H, B = 256, 32


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(H, H)
        self.l2 = nn.Linear(H, H)
        self.l3 = nn.Linear(H, 8)

    def forward(self, x):
        h = nn.functional.relu(self.l1(x))
        h = nn.functional.relu(self.l2(h))
        return self.l3(h)


def _build(stage, offload=False):
    paddle.seed(0)
    mesh = dist.build_mesh(sharding=4)
    model = _MLP()
    crit = nn.MSELoss()
    optimizer = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = dist.DistributedTrainStep(
        model, lambda o, y: crit(o, y), optimizer, mesh=mesh,
        sharding_stage=stage, offload=offload)
    return model, step


def _data():
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(np.asarray(rng.normal(size=(B, H)), np.float32))
    y = paddle.to_tensor(np.asarray(rng.normal(size=(B, 8)), np.float32))
    return x, y


def _run(stage, steps=4, offload=False):
    _, step = _build(stage, offload)
    x, y = _data()
    losses = [float(step(x, y)) for _ in range(steps)]
    dist.env.set_global_mesh(None)
    return losses, step


def _dev0_bytes(tree_leaves):
    """Bytes resident on device 0 for the given arrays."""
    total = 0
    for a in tree_leaves:
        for s in a.addressable_shards:
            if s.device == jax.devices()[0]:
                total += np.dtype(a.dtype).itemsize * int(np.prod(s.data.shape))
    return total


def test_stage_parity():
    """All sharding stages follow the stage-0 loss trajectory exactly
    (reference parity: dygraph_group_sharded_stage2/3 tests)."""
    ref, _ = _run(0)
    for stage in (1, 2, 3):
        got, _ = _run(stage)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5), stage
    assert ref[-1] < ref[0]


def test_optimizer_state_sharded_per_device():
    """Stages 1+ hold 1/N of the moments per device (ZeRO-1)."""
    _, s0 = _run(0, steps=1)[1]._state, _run(0, steps=1)
    # rebuild cleanly to inspect placements
    losses0, step0 = _run(0, steps=1)
    losses1, step1 = _run(1, steps=1)
    leaves = lambda st: [v for d in st.opt_states.values()
                         for v in d.values() if hasattr(v, "addressable_shards")]
    b0, b1 = _dev0_bytes(leaves(step0)), _dev0_bytes(leaves(step1))
    assert b1 <= b0 / 2, (b0, b1)


def test_stage3_params_sharded_per_device():
    """Stage 3 shards the parameters themselves (FSDP)."""
    _, step0 = _run(0, steps=1)
    _, step3 = _run(3, steps=1)
    p0 = _dev0_bytes(step0.params.values())
    p3 = _dev0_bytes(step3.params.values())
    assert p3 <= p0 / 2, (p0, p3)


def test_stage2_sharded_update_in_program():
    """Stage 2's compiled step reduce-scatters grads and computes the
    update on the owner shard — visible as a smaller temp footprint (and a
    reduce-scatter op) vs stage 0 on the same mesh."""
    _, step0 = _run(0, steps=1)
    _, step2 = _run(2, steps=1)

    def temp_bytes(step):
        x, y = _data()
        raw = lambda t: t._value
        batch = {"inputs": [raw(x)], "labels": [raw(y)]}
        lowered = step._compiled.lower(
            step.params, step.opt_states, step.buffers,
            jax.random.PRNGKey(0), jnp.asarray(1, jnp.int32),
            jnp.asarray(1e-3, jnp.float32), batch)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    t0, t2 = temp_bytes(step0), temp_bytes(step2)
    assert t2 < t0, (t0, t2)


def test_offload_states_stay_on_host():
    """offload=True keeps optimizer states in pinned host memory across
    steps (reference: GroupSharded offload=True moving moments to CPU)."""
    losses, step = _run(2, steps=3, offload=True)
    ref, _ = _run(2, steps=3)
    np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=1e-5)
    kinds = {
        v.sharding.memory_kind
        for d in step.opt_states.values()
        for v in d.values() if hasattr(v, "sharding")
    }
    assert kinds == {"pinned_host"}, kinds


def test_group_sharded_parallel_plumbs_stage():
    """group_sharded_parallel('p_g_os') must select a distinct stage-3 path
    in DistributedTrainStep (reference group_sharded.py:50)."""
    paddle.seed(0)
    mesh = dist.build_mesh(sharding=4)
    model = _MLP()
    optimizer = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    model, optimizer, _ = dist.group_sharded_parallel(
        model, optimizer, "p_g_os", offload=False)
    crit = nn.MSELoss()
    step = dist.DistributedTrainStep(
        model, lambda o, y: crit(o, y), optimizer, mesh=mesh)
    assert step.sharding_stage == 3
    x, y = _data()
    l = [float(step(x, y)) for _ in range(2)]
    dist.env.set_global_mesh(None)
    assert all(np.isfinite(v) for v in l)
    # stage-3 placement: params sharded
    p3 = _dev0_bytes(step.params.values())
    full = sum(np.dtype(v.dtype).itemsize * int(np.prod(v.shape))
               for v in step.params.values())
    assert p3 <= full / 2
