"""GroupSharded stage 1/2/3 internals (reference:
python/paddle/distributed/fleet/meta_parallel/sharding/group_sharded_stage2.py:47,
group_sharded_stage3.py:85, group_sharded_optimizer_stage2.py:53; API
python/paddle/distributed/sharding/group_sharded.py:50).

The TPU formulation: stage 1 shards optimizer states over the `sharding`
axis; stage 2 additionally reduce-scatters grads to their owner shard and
computes the update sharded (then all-gathers fresh params); stage 3 shards
the parameters themselves (FSDP). Asserts numeric parity across stages plus
the per-device footprint reductions each stage buys, and the host-offload
placement of optimizer states.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist

H, B = 256, 32


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(H, H)
        self.l2 = nn.Linear(H, H)
        self.l3 = nn.Linear(H, 8)

    def forward(self, x):
        h = nn.functional.relu(self.l1(x))
        h = nn.functional.relu(self.l2(h))
        return self.l3(h)


def _build(stage, offload=False):
    paddle.seed(0)
    mesh = dist.build_mesh(sharding=4)
    model = _MLP()
    crit = nn.MSELoss()
    optimizer = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = dist.DistributedTrainStep(
        model, lambda o, y: crit(o, y), optimizer, mesh=mesh,
        sharding_stage=stage, offload=offload)
    return model, step


def _data():
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(np.asarray(rng.normal(size=(B, H)), np.float32))
    y = paddle.to_tensor(np.asarray(rng.normal(size=(B, 8)), np.float32))
    return x, y


def _run(stage, steps=4, offload=False):
    _, step = _build(stage, offload)
    x, y = _data()
    losses = [float(step(x, y)) for _ in range(steps)]
    dist.env.set_global_mesh(None)
    return losses, step


def _dev0_bytes(tree_leaves):
    """Bytes resident on device 0 for the given arrays."""
    total = 0
    for a in tree_leaves:
        for s in a.addressable_shards:
            if s.device == jax.devices()[0]:
                total += np.dtype(a.dtype).itemsize * int(np.prod(s.data.shape))
    return total


def test_stage_parity():
    """All sharding stages follow the stage-0 loss trajectory exactly
    (reference parity: dygraph_group_sharded_stage2/3 tests)."""
    ref, _ = _run(0)
    for stage in (1, 2, 3):
        got, _ = _run(stage)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5), stage
    assert ref[-1] < ref[0]


def test_optimizer_state_sharded_per_device():
    """Stages 1+ hold 1/N of the moments per device (ZeRO-1)."""
    _, s0 = _run(0, steps=1)[1]._state, _run(0, steps=1)
    # rebuild cleanly to inspect placements
    losses0, step0 = _run(0, steps=1)
    losses1, step1 = _run(1, steps=1)
    leaves = lambda st: [v for d in st.opt_states.values()
                         for v in d.values() if hasattr(v, "addressable_shards")]
    b0, b1 = _dev0_bytes(leaves(step0)), _dev0_bytes(leaves(step1))
    assert b1 <= b0 / 2, (b0, b1)


def test_stage3_params_sharded_per_device():
    """Stage 3 shards the parameters themselves (FSDP)."""
    _, step0 = _run(0, steps=1)
    _, step3 = _run(3, steps=1)
    p0 = _dev0_bytes(step0.params.values())
    p3 = _dev0_bytes(step3.params.values())
    assert p3 <= p0 / 2, (p0, p3)


def test_stage2_sharded_update_in_program():
    """Stage 2's compiled step reduce-scatters grads and computes the
    update on the owner shard — visible as a smaller temp footprint (and a
    reduce-scatter op) vs stage 0 on the same mesh."""
    _, step0 = _run(0, steps=1)
    _, step2 = _run(2, steps=1)

    def temp_bytes(step):
        x, y = _data()
        raw = lambda t: t._value
        batch = {"inputs": [raw(x)], "labels": [raw(y)]}
        lowered = step._compiled.lower(
            step.params, step.opt_states, step.buffers,
            jax.random.PRNGKey(0), jnp.asarray(1, jnp.int32),
            jnp.asarray(1e-3, jnp.float32), batch)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    t0, t2 = temp_bytes(step0), temp_bytes(step2)
    assert t2 < t0, (t0, t2)


def test_offload_states_stay_on_host():
    """offload=True keeps optimizer states in host memory across steps
    (reference: GroupSharded offload=True moving moments to CPU). The
    memory kind is per-platform: pinned_host on TPU, unpinned_host on the
    CPU backend (where host==device memory, the same code path runs as a
    no-op placement)."""
    from paddle_tpu.distributed.train_step import host_memory_kind

    losses, step = _run(2, steps=3, offload=True)
    ref, _ = _run(2, steps=3)
    np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=1e-5)
    kinds = {
        v.sharding.memory_kind
        for d in step.opt_states.values()
        for v in d.values() if hasattr(v, "sharding")
    }
    assert kinds == {host_memory_kind(step.mesh)}, kinds


def test_offload_streaming_vs_move_barrier_parity():
    """The comm_overlap streaming path (in-program per-param device_puts)
    and the legacy host-side move barrier must produce the same training
    trajectory — they only relocate WHERE the transfers are issued."""
    paddle.seed(0)
    mesh = dist.build_mesh(sharding=4)
    x, y = _data()

    def run(overlap):
        paddle.seed(0)
        model = _MLP()
        crit = nn.MSELoss()
        optimizer = opt.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = dist.DistributedTrainStep(
            model, lambda o, t: crit(o, t), optimizer, mesh=mesh,
            sharding_stage=2, offload=True, comm_overlap=overlap)
        losses = [float(step(x, y)) for _ in range(3)]
        dist.env.set_global_mesh(None)
        return losses, step

    on, step_on = run(True)
    off, step_off = run(False)
    np.testing.assert_allclose(on, off, rtol=0, atol=0)
    assert step_on._offload_streaming()
    assert not step_off._offload_streaming()  # knob off -> move barrier


def test_grad_bucket_tags_keep_stage2_parity():
    """In-backward reduce-scatter bucket tags (comm_overlap, stage 2) are
    identities on the primals and only constrain cotangent placement —
    the loss trajectory must be unchanged, and the plan must actually
    cover the sharded params in reverse topological order."""
    x, y = _data()

    def run(overlap, bucket_mb=None):
        paddle.seed(0)
        model = _MLP()
        crit = nn.MSELoss()
        optimizer = opt.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        mesh = dist.build_mesh(sharding=4)
        step = dist.DistributedTrainStep(
            model, lambda o, t: crit(o, t), optimizer, mesh=mesh,
            sharding_stage=2, comm_overlap=overlap)
        if bucket_mb is not None:
            import os as _os
            _os.environ["PADDLE_TPU_RS_BUCKET_MB"] = str(bucket_mb)
        try:
            losses = [float(step(x, y)) for _ in range(3)]
            plan = step._grad_bucket_plan()
        finally:
            if bucket_mb is not None:
                del _os.environ["PADDLE_TPU_RS_BUCKET_MB"]
        dist.env.set_global_mesh(None)
        return losses, plan, step

    on, plan_on, step_on = run(True)
    off, plan_off, _ = run(False)
    np.testing.assert_allclose(on, off, rtol=0, atol=0)
    assert plan_off == []
    tagged = [n for names, _ in plan_on for n in names]
    sharded = [n for n in step_on._state.params
               if step_on._update_spec(n) != step_on._param_spec(n)]
    assert sorted(tagged) == sorted(sharded)
    # reverse topological order: last-registered param's grad arrives first
    assert tagged == list(reversed([n for n in step_on._state.params
                                    if n in set(tagged)]))
    # a tiny bucket cap splits the plan into more buckets, same coverage
    _, plan_small, _ = run(True, bucket_mb=1e-4)
    assert len(plan_small) > len(plan_on)
    assert sorted(n for names, _ in plan_small for n in names) == \
        sorted(tagged)


def test_h2d_pipelined_behind_inflight_step():
    """When the previous step's program is still executing at input-
    placement time, the h2d window is recorded as overlapped comm (the
    train_step/prev_step_inflight compute span) — the T3 'tracked
    overlap' signal the schedule work optimizes."""
    from paddle_tpu import observability as obs

    paddle.seed(0)
    mesh = dist.build_mesh(sharding=4)
    model = _MLP()
    crit = nn.MSELoss()
    optimizer = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = dist.DistributedTrainStep(
        model, lambda o, t: crit(o, t), optimizer, mesh=mesh,
        sharding_stage=2, comm_overlap=True)
    x, y = _data()
    _ = float(step(x, y))  # compile

    class _Inflight:  # deterministic "previous step still executing"
        def is_ready(self):
            return False

    tl = obs.enable_step_timeline()
    try:
        step._inflight = _Inflight()
        tl.step_begin(0)
        _ = step(x, y)
        rec = tl.step_end()
    finally:
        tl.uninstall()
        dist.env.set_global_mesh(None)
    names = [s["name"] for s in rec["spans"]]
    assert any(n.endswith("prev_step_inflight") for n in names), names
    # and the h2d comm interval is credited as covered
    assert rec["overlap"]["covered_s"] > 0
    assert rec["overlap_fraction"] > 0

    # knob off: the same window is exposed comm
    model_off = _MLP()
    step_off = dist.DistributedTrainStep(
        model_off, lambda o, t: crit(o, t),
        opt.AdamW(learning_rate=1e-3, parameters=model_off.parameters()),
        mesh=mesh, sharding_stage=0, comm_overlap=False)
    tl = obs.enable_step_timeline()
    try:
        step_off._inflight = _Inflight()
        tl.step_begin(1)
        _ = step_off(x, y)
        rec_off = tl.step_end()
    finally:
        tl.uninstall()
        dist.env.set_global_mesh(None)
    assert not any(n.endswith("prev_step_inflight")
                   for n in (s["name"] for s in rec_off["spans"]))


def test_group_sharded_parallel_plumbs_stage():
    """group_sharded_parallel('p_g_os') must select a distinct stage-3 path
    in DistributedTrainStep (reference group_sharded.py:50)."""
    paddle.seed(0)
    mesh = dist.build_mesh(sharding=4)
    model = _MLP()
    optimizer = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    model, optimizer, _ = dist.group_sharded_parallel(
        model, optimizer, "p_g_os", offload=False)
    crit = nn.MSELoss()
    step = dist.DistributedTrainStep(
        model, lambda o, y: crit(o, y), optimizer, mesh=mesh)
    assert step.sharding_stage == 3
    x, y = _data()
    l = [float(step(x, y)) for _ in range(2)]
    dist.env.set_global_mesh(None)
    assert all(np.isfinite(v) for v in l)
    # stage-3 placement: params sharded
    p3 = _dev0_bytes(step.params.values())
    full = sum(np.dtype(v.dtype).itemsize * int(np.prod(v.shape))
               for v in step.params.values())
    assert p3 <= full / 2
