"""Mesh planner tests (docs/PLANNER.md): analytic+measured hybrid cost
model, canonical MeshPlan layout artifact, elastic plan adoption.

The measured halves run on the virtual 8-device CPU mesh — the same
fixture the auto-tuner tests sweep — so analytic-vs-measured ranking
agreement is exercised end to end without hardware.
"""

import json
import os

import numpy as np

import jax

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.auto_tuner import tune
from paddle_tpu.distributed.planner import (
    CostModel,
    MeshPlan,
    SpecLayout,
    analytic_plan,
    measured_overlap_fraction,
    plan_and_tune,
    rank_candidates,
    shortlist,
)

MODEL_CFG = {"hidden_size": 64, "num_layers": 2, "num_heads": 4,
             "vocab_size": 1024, "seq_length": 32}


def _cfg(dp=1, mp=1, pp=1, sh=1, mbs=1, stage=1, gbs=8, rc=False):
    return {"dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
            "sharding_degree": sh, "sharding_stage": stage,
            "micro_batch_size": mbs, "use_recompute": rc,
            "global_batch_size": gbs}


def _tcfg(**kw):
    base = {"num_devices": 8, "global_batch_size": 8,
            "model_cfg": dict(MODEL_CFG)}
    base.update(kw)
    return base


# --------------------------------------------------------------------------- #
# cost model
# --------------------------------------------------------------------------- #


class TestCostModel:
    def test_more_mp_less_compute_more_comm(self):
        """mp splits the model: per-device compute drops, comm rises — the
        activations start riding the mp axis 4x per layer per microbatch.
        Byte monotonicity needs a production shape (on toy models the
        param-gradient volume shrinks faster than the activation volume
        grows; the launch-latency term still makes comm_s monotonic there,
        which is exactly the latency-bound-regime claim)."""
        cm = CostModel()
        big = _tcfg(global_batch_size=32,
                    model_cfg={"hidden_size": 2048, "num_layers": 24,
                               "num_heads": 16, "vocab_size": 50304,
                               "seq_length": 2048})
        a = cm.predict(big, _cfg(dp=2, mp=1))
        b = cm.predict(big, _cfg(dp=2, mp=2))
        assert b["compute_s"] < a["compute_s"]
        assert (sum(b["comm_bytes_by_axis"].values())
                > sum(a["comm_bytes_by_axis"].values()))
        assert "mp_allreduce" in b["comm_bytes_by_axis"]
        assert "mp_allreduce" not in a["comm_bytes_by_axis"]
        # latency-bound regime: comm seconds stay monotonic in mp even on
        # the tiny fixture, via the per-collective launch term
        tiny = _tcfg()
        assert (cm.predict(tiny, _cfg(dp=2, mp=2))["comm_s"]
                > cm.predict(tiny, _cfg(dp=2, mp=1))["comm_s"])

    def test_pp_bubble_shrinks_with_more_microbatches(self):
        cm = CostModel()
        t = _tcfg()
        few = cm.predict(t, _cfg(dp=2, pp=2, mbs=2))   # n_micro = 2
        many = cm.predict(t, _cfg(dp=2, pp=2, mbs=1))  # n_micro = 4
        assert few["n_micro"] == 2 and many["n_micro"] == 4
        assert many["bubble_s"] < few["bubble_s"]
        assert cm.predict(t, _cfg(dp=8))["bubble_s"] == 0.0

    def test_recompute_multiplier_and_memory(self):
        cm = CostModel()
        t = _tcfg()
        plain = cm.predict(t, _cfg(dp=8, rc=False))
        rc = cm.predict(t, _cfg(dp=8, rc=True))
        # 4/3 on the FLOPs leg; recompute also shrinks resident activations
        assert rc["mem_estimate_bytes"] < plain["mem_estimate_bytes"]
        # over-cap configs are reported, not silently ranked as feasible
        capped = dict(t, max_mem_usage_bytes=1)
        assert cm.predict(capped, _cfg(dp=8))["mem_ok"] is False
        assert cm.predict(t, _cfg(dp=8))["mem_ok"] is True

    def test_ep_a2a_term_monotone_and_gated(self):
        """ISSUE-14: the MoE dispatch/combine a2a volume term. Dense models
        never see it; under ep it grows with (ep-1)/ep (byte volume) and
        with the chunk schedule (launch-latency alpha regime)."""
        cm = CostModel()
        moe_cfg = _tcfg(model_cfg=dict(MODEL_CFG, moe_num_experts=8,
                                       moe_top_k=2))
        dense = cm.predict(_tcfg(), _cfg(dp=8))
        assert "ep_a2a" not in dense["comm_s_by_axis"]
        no_ep = cm.predict(moe_cfg, dict(_cfg(dp=8), ep_degree=1))
        assert "ep_a2a" not in no_ep["comm_s_by_axis"]
        prev = 0.0
        for ep in (2, 4, 8):
            bd = cm.predict(moe_cfg, dict(_cfg(dp=8 // ep), ep_degree=ep))
            cur = bd["comm_s_by_axis"]["ep_a2a"]
            assert cur > prev
            assert bd["comm_bytes_by_axis"]["ep_a2a"] > 0
            prev = cur
        # latency-bound regime: more chunks = more launches = more alpha
        few = CostModel(a2a_chunks=1).predict(
            moe_cfg, dict(_cfg(dp=2), ep_degree=4))
        many = CostModel(a2a_chunks=4).predict(
            moe_cfg, dict(_cfg(dp=2), ep_degree=4))
        assert many["comm_s_by_axis"]["ep_a2a"] > few["comm_s_by_axis"]["ep_a2a"]
        assert (many["comm_bytes_by_axis"]["ep_a2a"]
                == few["comm_bytes_by_axis"]["ep_a2a"])

    def test_ep_grid_gated_on_moe_and_pruned_by_experts(self):
        """The candidate grid only grows an ep dimension for MoE models,
        and ep must divide the expert count."""
        ranked, _pruned = rank_candidates(_tcfg(mp_degree=[1],
                                                pp_degree=[1],
                                                sharding_degree=[1]))
        assert all(cfg.get("ep_degree", 1) == 1 for cfg, _bd in ranked)
        moe = _tcfg(model_cfg=dict(MODEL_CFG, moe_num_experts=4,
                                   moe_top_k=2),
                    mp_degree=[1], pp_degree=[1], sharding_degree=[1])
        ranked, pruned = rank_candidates(moe)
        eps = {cfg.get("ep_degree", 1) for cfg, _bd in ranked}
        assert {1, 2, 4} <= eps and 8 not in eps  # 8 !| 4 experts
        assert any("moe_num_experts" in r for _c, n, r in pruned
                   if n == "prune_by_ep")

    def test_overlap_discount_from_step_timeline(self, tmp_path):
        """The measured half: overlap_fraction from step-timeline JSONL
        discounts exposed comm; no history means all comm exposed."""
        p = str(tmp_path / "steps.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"step": 0, "overlap": {
                "fraction": 0.5, "comm_s": 2.0, "covered_s": 1.0,
                "exposed_s": 1.0}}) + "\n")
            f.write(json.dumps({"step": 1, "overlap": {
                "fraction": 0.5, "comm_s": 2.0, "covered_s": 1.0,
                "exposed_s": 1.0}}) + "\n")
        frac, src = measured_overlap_fraction(p)
        assert frac == 0.5 and "step_timeline" in src
        t = _tcfg()
        cold = CostModel().predict(t, _cfg(dp=8))
        warm = CostModel(overlap_paths=p).predict(t, _cfg(dp=8))
        assert cold["overlap_fraction"] == 0.0
        assert warm["overlap_fraction"] == 0.5
        assert warm["exposed_comm_s"] == cold["exposed_comm_s"] * 0.5
        assert warm["total_s"] < cold["total_s"]

    def test_overlap_from_bench_perf_lines(self, tmp_path):
        p = str(tmp_path / "bench.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"metric": "mfu_x", "value": 0.5,
                                "overlap_fraction": 0.8}) + "\n")
            # 1.0 in a bare perf line is the ZERO-comm sentinel (cpu_smoke /
            # single-device runs) — taking it as evidence would make the
            # planner rank pod meshes as if collectives were free
            f.write(json.dumps({"metric": "mfu_smoke", "value": 0.5,
                                "overlap_fraction": 1.0}) + "\n")
        frac, src = measured_overlap_fraction(p)
        assert frac == 0.8 and "bench_lines:1" in src
        sentinel_only = str(tmp_path / "smoke.jsonl")
        with open(sentinel_only, "w") as f:
            f.write(json.dumps({"metric": "mfu_smoke",
                                "overlap_fraction": 1.0}) + "\n")
        assert measured_overlap_fraction(sentinel_only) == (None, None)
        assert measured_overlap_fraction(
            str(tmp_path / "missing.jsonl")) == (None, None)


# --------------------------------------------------------------------------- #
# ranking + shortlist
# --------------------------------------------------------------------------- #

GRID = {"mp_degree": [1, 2], "pp_degree": [1], "sharding_degree": [1, 2],
        "micro_batch_size": [1, 2]}


class TestPlannerRanking:
    def test_shortlist_is_sorted_topk_and_prunes_are_named(self):
        t = _tcfg(**dict(GRID, pp_degree=[1, 2]))
        ranked, pruned = rank_candidates(t)
        assert len(ranked) > 5
        totals = [bd["total_s"] for _c, bd in ranked]
        assert totals == sorted(totals)
        sl = shortlist(t, top_k=5)
        assert len(sl) == 5
        assert [c["dp_degree"] for c, _ in sl] == \
            [c["dp_degree"] for c, _ in ranked[:5]]
        assert pruned, "grid should have infeasible points"
        assert all(rule.startswith("prune_by_") for _c, rule, _r in pruned)

    def test_hybrid_shortlist_agrees_with_full_measurement(self):
        """Acceptance, on the 8-device CPU mesh with a gpt tuner fixture:
        plan_and_tune times only the K=5 shortlist of the N>5 feasible
        grid points, records predicted-vs-measured error per trial, and —
        measuring the analytically-rejected remainder the old way — the
        measured-best of the FULL grid sits inside the analytic top-K
        (the planner would not have pruned away the winner)."""
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

        # one-layer fixture: trial cost is XLA compiles, not math, and
        # mesh-ranking behavior is layer-count-independent here (pp=[1])
        small = {"hidden_size": 32, "num_layers": 1, "num_heads": 2,
                 "vocab_size": 256, "seq_length": 16}
        cfg_model = GPTConfig(vocab_size=small["vocab_size"],
                              hidden_size=small["hidden_size"],
                              num_layers=1, num_heads=2,
                              max_position_embeddings=32)
        crit = GPTPretrainingCriterion(cfg_model)
        builder = lambda c: GPTForCausalLM(cfg_model)
        loss = lambda lg, lb: crit(lg, lb)
        optb = lambda m: opt.AdamW(learning_rate=1e-3,
                                   parameters=m.parameters())
        t = _tcfg(**GRID, model_cfg=small)
        ranked, _ = rank_candidates(t)
        n_candidates = len(ranked)
        assert n_candidates > 5, "grid too small to make top-K meaningful"

        plan, best, rec = plan_and_tune(
            builder, loss, optb, t, top_k=5,
            devices=jax.devices(), steps=1)
        measured = [h for h in rec.history if h.get("step_time")]
        assert len(measured) == 5 < n_candidates
        for h in measured:
            assert h["predicted_step_time"] > 0
            assert "prediction_error_pct" in h
        skipped = [h for h in rec.history
                   if str(h.get("pruned", "")).startswith("analytic rank")]
        assert len(skipped) == n_candidates - 5
        assert best is not None
        assert plan.source == "measured"
        assert plan.measured_step_time_s == best["step_time"]
        assert plan.num_devices == 8

        # the old exhaustive way, over just the rejected remainder
        rest = dict(t, candidates=[dict(c) for c, _bd in ranked[5:]])
        _b2, rec2 = tune(builder, loss, optb, rest,
                         devices=jax.devices(), steps=1)
        all_measured = measured + [h for h in rec2.history
                                   if h.get("step_time")]
        assert len(all_measured) == n_candidates
        key = lambda c: (c["dp_degree"], c["mp_degree"], c["pp_degree"],
                         c["sharding_degree"], c["micro_batch_size"])
        best_overall = min(all_measured, key=lambda h: h["step_time"])
        top_k_keys = {key(c) for c, _bd in ranked[:5]}
        assert key(best_overall) in top_k_keys, (
            f"measured best {key(best_overall)} not in analytic top-5 "
            f"{sorted(top_k_keys)}")


# --------------------------------------------------------------------------- #
# MeshPlan artifact
# --------------------------------------------------------------------------- #


class TestMeshPlan:
    def test_json_round_trip_lossless(self, tmp_path):
        plan = analytic_plan(_tcfg(**GRID))
        p = str(tmp_path / "mesh_plan.json")
        plan.save(p)
        loaded = MeshPlan.load(p)
        assert loaded == plan
        assert loaded.to_dict() == plan.to_dict()
        # a second save/load cycle is byte-stable
        loaded.save(p)
        assert MeshPlan.load(p) == plan

    def test_partition_specs_and_mesh(self):
        from jax.sharding import PartitionSpec as P

        plan = analytic_plan(_tcfg(**GRID))
        specs = plan.partition_specs()
        assert specs["vocab_embedding"] == P("mp", None)
        assert specs["column_parallel"] == P(None, "mp")
        assert specs["row_parallel"] == P("mp", None)
        mesh = plan.build_mesh(devices=jax.devices()[:plan.num_devices])
        assert int(np.prod(list(mesh.shape.values()))) == plan.num_devices
        assert dist.env.mesh_shape(mesh) == plan.mesh
        dist.env.set_global_mesh(None)

    def test_stage3_layouts_fold_fsdp_axis(self):
        from jax.sharding import PartitionSpec as P

        sl = SpecLayout(fsdp=True)
        assert sl.vocab_embedding() == P("mp", "sharding")
        assert sl.column_parallel() == P("sharding", "mp")
        assert sl.row_parallel() == P("mp", "sharding")
        assert sl.norm() == P("sharding")
        assert sl.activations() == P(("dp", "sharding"), None, None)
        # stage-3 candidate round-trips its stage through the artifact
        plan = MeshPlan.from_candidate(
            _cfg(dp=2, sh=4, stage=3), CostModel().predict(
                _tcfg(), _cfg(dp=2, sh=4, stage=3)))
        assert plan.sharding_stage == 3
        assert plan.partition_specs()["column_parallel"] == P("sharding", "mp")
        assert plan.tuner_candidate()["sharding_stage"] == 3

    def test_ep_layout_round_trip(self, tmp_path):
        """ISSUE-14: an ep>1 candidate round-trips through the MeshPlan
        artifact — mesh axis, expert_stacked layout, tuner candidate, and
        the materialized mesh all carry ep."""
        from jax.sharding import PartitionSpec as P

        cfg = dict(_cfg(dp=2, mp=1), ep_degree=4)
        moe_cfg = _tcfg(model_cfg=dict(MODEL_CFG, moe_num_experts=8,
                                       moe_top_k=2))
        plan = MeshPlan.from_candidate(
            cfg, CostModel().predict(moe_cfg, cfg),
            model_cfg=moe_cfg["model_cfg"])
        assert plan.mesh["ep"] == 4 and plan.num_devices == 8
        assert plan.partition_specs()["expert_stacked"] == P("ep", None)
        assert plan.tuner_candidate()["ep_degree"] == 4
        p = str(tmp_path / "mesh_plan.json")
        plan.save(p)
        loaded = MeshPlan.load(p)
        assert loaded == plan
        mesh = loaded.build_mesh(devices=jax.devices()[:8])
        assert dist.env.mesh_shape(mesh) == loaded.mesh
        assert "xep4" in loaded.describe()
        dist.env.set_global_mesh(None)
        # a pre-ep plan file (no "ep" key) still loads and builds
        d = loaded.to_dict()
        d["mesh"] = {k: v for k, v in d["mesh"].items() if k != "ep"}
        d["num_devices"] = 2
        old = MeshPlan.from_dict(d)
        assert old.tuner_candidate()["ep_degree"] == 1
        mesh = old.build_mesh(devices=jax.devices()[:2])
        assert dist.env.mesh_shape(mesh)["ep"] == 1
        dist.env.set_global_mesh(None)

    def test_infeasible_grid_raises(self):
        # 7 devices, grid that cannot factorize onto heads=4/layers=2
        t = _tcfg(num_devices=7, mp_degree=[7], pp_degree=[7],
                  sharding_degree=[1], dp_degree=[1])
        try:
            analytic_plan(t)
        except ValueError as e:
            assert "no feasible mesh candidate" in str(e)
        else:
            raise AssertionError("expected ValueError")


# --------------------------------------------------------------------------- #
# elastic plan adoption
# --------------------------------------------------------------------------- #


class TestElasticAdoption:
    def test_restart_with_changed_device_count_adopts_replanned_mesh(
            self, tmp_path):
        """Extends the reshard-on-load story: a job planned for 8 devices
        checkpoints; the 'pod' comes back with 4. The trainer re-plans
        analytically, persists the new MeshPlan next to the checkpoint,
        and restore reshards the state onto the mesh built from the new
        plan — the job MIGRATED to a re-tuned mesh, not just survived."""
        ckpt = str(tmp_path / "ckpt")
        pcfg = _tcfg(mp_degree=[1], pp_degree=[1], sharding_degree=[1])
        w = np.random.RandomState(0).randn(8, 16).astype(np.float32)

        def make_state(value):
            def on_plan(plan):
                mesh = dist.ProcessMesh(list(range(plan.num_devices)),
                                        dim_names=["p"])
                state["w"] = dist.shard_tensor(
                    paddle.to_tensor(value.copy()), mesh, [dist.Shard(0)])
            return on_plan

        state = {}
        t1 = dist.ResilientTrainer(
            lambda step: 0.0, lambda: state, ckpt, save_every=1,
            async_save=False, planner_cfg=pcfg, plan_devices=8,
            on_plan=make_state(w))
        t1.run(1)
        plan_file = os.path.join(ckpt, "mesh_plan.json")
        assert os.path.exists(plan_file)
        assert t1.plan_changed  # no plan existed: first plan counts
        assert t1.plan.num_devices == 8
        assert MeshPlan.load(plan_file).mesh["dp"] == 8

        # "restart" with half the devices: re-plan + reshard-on-load
        state = {}
        t2 = dist.ResilientTrainer(
            lambda step: 0.0, lambda: state, ckpt, save_every=100,
            async_save=False, planner_cfg=pcfg, plan_devices=4,
            on_plan=make_state(np.zeros_like(w)))
        res = t2.run(2)
        assert t2.plan_changed
        assert t2.plan.num_devices == 4
        assert t2.plan.mesh["dp"] == 4
        assert res["resumed_from"] == 0
        np.testing.assert_allclose(state["w"].numpy(), w)
        assert MeshPlan.load(plan_file).num_devices == 4

        # third run, same device count: adopt WITHOUT re-planning
        state = {}
        t3 = dist.ResilientTrainer(
            lambda step: 0.0, lambda: state, ckpt, save_every=100,
            async_save=False, planner_cfg=pcfg, plan_devices=4,
            on_plan=make_state(np.zeros_like(w)))
        t3._adopt_plan()
        assert not t3.plan_changed
        assert t3.plan.num_devices == 4

    def test_plan_path_without_planner_cfg_keeps_stale_plan(self, tmp_path):
        plan = analytic_plan(_tcfg(mp_degree=[1], pp_degree=[1],
                                   sharding_degree=[1]))
        p = str(tmp_path / "mesh_plan.json")
        plan.save(p)
        t = dist.ResilientTrainer(
            lambda step: 0.0, lambda: {}, str(tmp_path / "ckpt"),
            plan_path=p, plan_devices=4)
        t._adopt_plan()
        assert t.plan.num_devices == 8  # stale but surfaced, not re-planned
        assert not t.plan_changed


# --------------------------------------------------------------------------- #
# planner observability
# --------------------------------------------------------------------------- #


class TestPlannerMetrics:
    def test_counters_flow_through_registry(self):
        from paddle_tpu.observability.metrics import default_registry

        reg = default_registry()
        base = reg.snapshot()
        rank_candidates(_tcfg(**dict(GRID, pp_degree=[1, 2])))
        delta = reg.delta(base)
        assert any(k.startswith("planner_candidates_total")
                   for k in delta), delta
        assert any(k.startswith("planner_pruned_total") for k in delta)
