"""Auto-tuner tests (reference: python/paddle/distributed/auto_tuner/ —
tuner.py AutoTuner, prune.py static+history pruning, recorder.py)."""

import numpy as np

import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.auto_tuner import (
    AutoTuner,
    Recorder,
    default_candidates,
    tune,
)
from paddle_tpu.distributed.auto_tuner.tuner import (
    estimate_memory_bytes,
    prune_by_memory,
    prune_by_mp,
    prune_by_pp,
)

MODEL_CFG = {"hidden_size": 64, "num_layers": 4, "num_heads": 4,
             "vocab_size": 1024, "seq_length": 32}


def test_candidates_and_static_pruning():
    tuner_cfg = {
        "num_devices": 8,
        "global_batch_size": 8,
        "model_cfg": MODEL_CFG,
        "micro_batch_size": [1, 2],
    }
    cands = default_candidates(tuner_cfg)
    assert all(
        c["dp_degree"] * c["mp_degree"] * c["pp_degree"] * c["sharding_degree"] == 8
        for c in cands)
    # mp=8 cannot divide num_heads=4 -> pruned
    bad = dict(cands[0], mp_degree=8, dp_degree=1, pp_degree=1,
               sharding_degree=1)
    assert prune_by_mp(tuner_cfg, bad) is not None
    # pp=8 cannot divide num_layers=4 -> pruned
    bad_pp = dict(cands[0], pp_degree=8, dp_degree=1, mp_degree=1,
                  sharding_degree=1)
    assert prune_by_pp(tuner_cfg, bad_pp) is not None

    tuner = AutoTuner(tuner_cfg)
    seen = []
    while True:
        c = tuner.search_once()
        if c is None:
            break
        seen.append(c)
        tuner.add_cfg(dict(c))
    assert seen, "no surviving candidates"
    assert tuner.pruned, "nothing was pruned"
    # every survivor obeys the divisibility laws
    for c in seen:
        assert MODEL_CFG["num_heads"] % c["mp_degree"] == 0
        assert MODEL_CFG["num_layers"] % c["pp_degree"] == 0


def test_memory_pruning_and_history():
    tuner_cfg = {
        "num_devices": 8,
        "global_batch_size": 8,
        "model_cfg": dict(MODEL_CFG, hidden_size=4096, num_layers=32),
        "max_mem_usage_bytes": int(1e9),  # 1 GB cap: big configs must die
        "micro_batch_size": [1],
    }
    full = {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sharding_stage": 1,
            "micro_batch_size": 1, "use_recompute": True,
            "global_batch_size": 8}
    assert prune_by_memory(tuner_cfg, full) is not None
    # history pruning: a config >= a known-OOM estimate is skipped
    from paddle_tpu.distributed.auto_tuner.tuner import prune_by_history

    hist = [{"error": "oom",
             "mem_estimate": estimate_memory_bytes(tuner_cfg, full) - 1}]
    assert prune_by_history(tuner_cfg, full, hist) is not None


def test_recorder_best_and_csv(tmp_path):
    r = Recorder()
    r.add_cfg(dp_degree=8, step_time=0.5)
    r.add_cfg(dp_degree=4, step_time=0.2)
    r.add_cfg(dp_degree=2, step_time=None, error="oom")
    best, err = r.get_best()
    assert not err and best["dp_degree"] == 4
    p = str(tmp_path / "history.csv")
    r.store_history(p)
    loaded, missing = r.load_history(p)
    assert not missing and len(loaded) == 3


def test_load_history_restores_types(tmp_path):
    """csv.DictReader returns all-string rows; load_history must coerce
    them back or prune_by_history's numeric comparison on loaded history
    raises TypeError (float vs str)."""
    from paddle_tpu.distributed.auto_tuner.tuner import prune_by_history

    r = Recorder()
    r.add_cfg(dp_degree=8, mp_degree=1, pp_degree=1, sharding_degree=1,
              sharding_stage=1, micro_batch_size=1, use_recompute=True,
              global_batch_size=8, step_time=0.25, mem_estimate=1.5e9,
              error=None)
    r.add_cfg(dp_degree=4, mp_degree=2, pp_degree=1, sharding_degree=1,
              sharding_stage=1, micro_batch_size=2, use_recompute=False,
              global_batch_size=8, step_time=None, mem_estimate=3.5e9,
              error="oom")
    p = str(tmp_path / "history.csv")
    r.store_history(p)
    loaded, missing = r.load_history(p)
    assert not missing
    ok = next(h for h in loaded if h["error"] is None)
    oom = next(h for h in loaded if h["error"] == "oom")
    assert ok["step_time"] == 0.25 and isinstance(ok["step_time"], float)
    assert ok["dp_degree"] == 8 and isinstance(ok["dp_degree"], int)
    assert ok["use_recompute"] is True and oom["use_recompute"] is False
    assert oom["step_time"] is None  # error=None/"" round-trips to None
    assert isinstance(oom["mem_estimate"], float)
    # the regression: history loaded from disk feeds the pruner directly
    tuner_cfg = {"num_devices": 8, "model_cfg": MODEL_CFG}
    big = {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1,
           "sharding_degree": 1, "sharding_stage": 1, "micro_batch_size": 4,
           "use_recompute": False, "global_batch_size": 8}
    prune_by_history(tuner_cfg, big, loaded)  # must not raise TypeError


def test_memory_estimate_matches_placement():
    """Pin the per-device formulas to the actual DistributedTrainStep
    placement: body split by mp*pp (+sharding at stage 3), the vocab
    embedding split by mp only (it lives on ONE pipeline stage; stage 3
    adds the sharding split on its free dim), optimizer states
    sharding-split at every stage >= 1."""
    model = {"hidden_size": 64, "num_layers": 4, "vocab_size": 1024,
             "seq_length": 32}
    tuner_cfg = {"model_cfg": model}
    h, L, vocab, seq = 64, 4, 1024, 32
    body, emb = 12 * L * h * h, vocab * h

    def cfg(mp, pp, sh, stage, mbs=2, rc=False):
        return {"dp_degree": 1, "mp_degree": mp, "pp_degree": pp,
                "sharding_degree": sh, "sharding_stage": stage,
                "micro_batch_size": mbs, "use_recompute": rc,
                "global_batch_size": 8}

    # stage 1, mp=2 pp=2 sh=2: emb NOT divided by pp, states /sh
    got = estimate_memory_bytes(tuner_cfg, cfg(2, 2, 2, 1))
    want = (2 * (body / 4 + emb / 2)          # bf16 params
            + 12 * (body / 4 + emb / 2) / 2   # f32 master+moments, ZeRO-1
            + 2 * seq * h * 16 * (L // 2) / 2)  # activations
    assert got == want
    # stage 3, mp=2 pp=1 sh=2: params AND states take the fsdp split;
    # the embedding is divided by mp and sharding, never by pp
    got3 = estimate_memory_bytes(tuner_cfg, cfg(2, 1, 2, 3))
    want3 = (14 * (body / 4 + emb / 4)
             + 2 * seq * h * 16 * L / 2)
    assert got3 == want3
    # more pp must not shrink the embedding term: pp=4 halves the body
    # vs pp=2 but the owning stage still holds vocab*h/mp
    e2 = estimate_memory_bytes(tuner_cfg, cfg(1, 2, 1, 1, rc=True))
    e4 = estimate_memory_bytes(tuner_cfg, cfg(1, 4, 1, 1, rc=True))
    assert (e2 - e4) == (2 + 12) * (body / 2 - body / 4)


def test_tune_records_pruned_and_restores_caller_mesh():
    """tune() must (a) leave the caller's global mesh exactly as it found
    it — even when model_builder raises mid-trial — and (b) surface the
    pruned configs + reasons in the Recorder history so shortlist reports
    show why configs were skipped."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import env as _env
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    cfg_model = GPTConfig(vocab_size=MODEL_CFG["vocab_size"],
                          hidden_size=MODEL_CFG["hidden_size"],
                          num_layers=1, num_heads=4,
                          max_position_embeddings=64)
    crit = GPTPretrainingCriterion(cfg_model)
    tuner_cfg = {
        "num_devices": 4,
        "global_batch_size": 8,
        "model_cfg": dict(MODEL_CFG, num_layers=1),
        # pp=2 does not divide num_layers=1 -> pruned with a reason
        "mp_degree": [1], "pp_degree": [1, 2], "sharding_degree": [1],
        "dp_degree": [2, 4], "micro_batch_size": [1, 2],
    }
    calls = {"n": 0}

    def flaky_builder(c):
        calls["n"] += 1
        if calls["n"] == 1:  # first trial dies inside model_builder
            raise RuntimeError("injected model_builder failure")
        return GPTForCausalLM(cfg_model)

    prior = dist.build_mesh(dp=2, sharding=2,
                            devices=__import__("jax").devices()[:4])
    try:
        best, rec = tune(
            flaky_builder, lambda lg, lb: crit(lg, lb),
            lambda m: opt.AdamW(learning_rate=1e-3,
                                parameters=m.parameters()),
            tuner_cfg, devices=__import__("jax").devices()[:4], steps=1)
        assert _env.get_global_mesh() is prior, \
            "tune() must restore the caller's global mesh"
        failed = [h for h in rec.history if h.get("error")]
        assert failed and failed[0]["error"] == "RuntimeError"
        assert best is not None and best.get("step_time")
        pruned = [h for h in rec.history if h.get("pruned")]
        assert pruned and any("pp 2 does not divide" in h["pruned"]
                              for h in pruned)
    finally:
        _env.set_global_mesh(None)


def test_tune_measures_and_picks_best():
    """End-to-end sweep on the 8-device CPU mesh over a restricted grid —
    each trial builds a real DistributedTrainStep (reference: subprocess
    trials with timeout, tuner.py + launch integration)."""
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion, GPTConfig

    cfg_model = GPTConfig(vocab_size=MODEL_CFG["vocab_size"],
                          hidden_size=MODEL_CFG["hidden_size"],
                          num_layers=2, num_heads=4,
                          max_position_embeddings=64)
    crit = GPTPretrainingCriterion(cfg_model)

    tuner_cfg = {
        "num_devices": 4,
        "global_batch_size": 8,
        "model_cfg": dict(MODEL_CFG, num_layers=2),
        # restricted grid: 3 feasible points
        "mp_degree": [1, 2],
        "pp_degree": [1],
        "sharding_degree": [1, 2],
        "dp_degree": [1, 2, 4],
        "micro_batch_size": [2],
    }

    best, rec = tune(
        lambda c: GPTForCausalLM(cfg_model),
        lambda lg, lb: crit(lg, lb),
        lambda m: opt.AdamW(learning_rate=1e-3, parameters=m.parameters()),
        tuner_cfg, devices=jax.devices()[:4], steps=1)
    assert best is not None and best["step_time"] > 0
    measured = [h for h in rec.history if h.get("step_time")]
    assert len(measured) >= 2, rec.history
    assert all(np.isfinite(h["loss"]) for h in measured)
    assert best["step_time"] == min(h["step_time"] for h in measured)
