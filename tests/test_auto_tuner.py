"""Auto-tuner tests (reference: python/paddle/distributed/auto_tuner/ —
tuner.py AutoTuner, prune.py static+history pruning, recorder.py)."""

import numpy as np

import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.auto_tuner import (
    AutoTuner,
    Recorder,
    default_candidates,
    tune,
)
from paddle_tpu.distributed.auto_tuner.tuner import (
    estimate_memory_bytes,
    prune_by_memory,
    prune_by_mp,
    prune_by_pp,
)

MODEL_CFG = {"hidden_size": 64, "num_layers": 4, "num_heads": 4,
             "vocab_size": 1024, "seq_length": 32}


def test_candidates_and_static_pruning():
    tuner_cfg = {
        "num_devices": 8,
        "global_batch_size": 8,
        "model_cfg": MODEL_CFG,
        "micro_batch_size": [1, 2],
    }
    cands = default_candidates(tuner_cfg)
    assert all(
        c["dp_degree"] * c["mp_degree"] * c["pp_degree"] * c["sharding_degree"] == 8
        for c in cands)
    # mp=8 cannot divide num_heads=4 -> pruned
    bad = dict(cands[0], mp_degree=8, dp_degree=1, pp_degree=1,
               sharding_degree=1)
    assert prune_by_mp(tuner_cfg, bad) is not None
    # pp=8 cannot divide num_layers=4 -> pruned
    bad_pp = dict(cands[0], pp_degree=8, dp_degree=1, mp_degree=1,
                  sharding_degree=1)
    assert prune_by_pp(tuner_cfg, bad_pp) is not None

    tuner = AutoTuner(tuner_cfg)
    seen = []
    while True:
        c = tuner.search_once()
        if c is None:
            break
        seen.append(c)
        tuner.add_cfg(dict(c))
    assert seen, "no surviving candidates"
    assert tuner.pruned, "nothing was pruned"
    # every survivor obeys the divisibility laws
    for c in seen:
        assert MODEL_CFG["num_heads"] % c["mp_degree"] == 0
        assert MODEL_CFG["num_layers"] % c["pp_degree"] == 0


def test_memory_pruning_and_history():
    tuner_cfg = {
        "num_devices": 8,
        "global_batch_size": 8,
        "model_cfg": dict(MODEL_CFG, hidden_size=4096, num_layers=32),
        "max_mem_usage_bytes": int(1e9),  # 1 GB cap: big configs must die
        "micro_batch_size": [1],
    }
    full = {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sharding_stage": 1,
            "micro_batch_size": 1, "use_recompute": True,
            "global_batch_size": 8}
    assert prune_by_memory(tuner_cfg, full) is not None
    # history pruning: a config >= a known-OOM estimate is skipped
    from paddle_tpu.distributed.auto_tuner.tuner import prune_by_history

    hist = [{"error": "oom",
             "mem_estimate": estimate_memory_bytes(tuner_cfg, full) - 1}]
    assert prune_by_history(tuner_cfg, full, hist) is not None


def test_recorder_best_and_csv(tmp_path):
    r = Recorder()
    r.add_cfg(dp_degree=8, step_time=0.5)
    r.add_cfg(dp_degree=4, step_time=0.2)
    r.add_cfg(dp_degree=2, step_time=None, error="oom")
    best, err = r.get_best()
    assert not err and best["dp_degree"] == 4
    p = str(tmp_path / "history.csv")
    r.store_history(p)
    loaded, missing = r.load_history(p)
    assert not missing and len(loaded) == 3


def test_tune_measures_and_picks_best():
    """End-to-end sweep on the 8-device CPU mesh over a restricted grid —
    each trial builds a real DistributedTrainStep (reference: subprocess
    trials with timeout, tuner.py + launch integration)."""
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion, GPTConfig

    cfg_model = GPTConfig(vocab_size=MODEL_CFG["vocab_size"],
                          hidden_size=MODEL_CFG["hidden_size"],
                          num_layers=2, num_heads=4,
                          max_position_embeddings=64)
    crit = GPTPretrainingCriterion(cfg_model)

    tuner_cfg = {
        "num_devices": 4,
        "global_batch_size": 8,
        "model_cfg": dict(MODEL_CFG, num_layers=2),
        # restricted grid: 3 feasible points
        "mp_degree": [1, 2],
        "pp_degree": [1],
        "sharding_degree": [1, 2],
        "dp_degree": [1, 2, 4],
        "micro_batch_size": [2],
    }

    best, rec = tune(
        lambda c: GPTForCausalLM(cfg_model),
        lambda lg, lb: crit(lg, lb),
        lambda m: opt.AdamW(learning_rate=1e-3, parameters=m.parameters()),
        tuner_cfg, devices=jax.devices()[:4], steps=1)
    assert best is not None and best["step_time"] > 0
    measured = [h for h in rec.history if h.get("step_time")]
    assert len(measured) >= 2, rec.history
    assert all(np.isfinite(h["loss"]) for h in measured)
    assert best["step_time"] == min(h["step_time"] for h in measured)
