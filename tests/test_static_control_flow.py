"""Static control flow: cond / while_loop / gradients (reference:
python/paddle/static/nn/control_flow.py:723,1313, base/backward.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


class TestCond:
    def test_reference_docstring_example(self):
        a = paddle.full([1], 1.0)
        b = paddle.full([1], 2.0)
        out = static.nn.cond(a < b, lambda: a + b, lambda: a * b)
        np.testing.assert_allclose(out.numpy(), [3.0])
        out = static.nn.cond(a > b, lambda: a + b, lambda: a * b)
        np.testing.assert_allclose(out.numpy(), [2.0])

    def test_nest_outputs(self):
        a = paddle.full([2], 1.0)
        r = static.nn.cond(a.sum() > 0,
                           lambda: (a + 1, [a * 2, a * 3]),
                           lambda: (a - 1, [a * 4, a * 5]))
        y, (p, q) = r
        np.testing.assert_allclose(y.numpy(), [2.0, 2.0])
        np.testing.assert_allclose(q.numpy(), [3.0, 3.0])

    def test_mismatched_branches_raise(self):
        a = paddle.full([2], 1.0)
        with pytest.raises(ValueError):
            static.nn.cond(a.sum() > 0, lambda: a,
                           lambda: paddle.full([3], 1.0))

    def test_in_program_with_feeds(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            y = static.nn.fc(x, 3, activation="relu")
            z = static.nn.cond(y.sum() < 1e9, lambda: y * 2.0,
                               lambda: y - 1.0)
        exe = static.Executor()
        fx = np.random.default_rng(0).standard_normal((5, 4)).astype("float32")
        (zv,) = exe.run(prog, feed={"x": fx}, fetch_list=[z])
        assert zv.shape == (5, 3)

    def test_device_side_predicate_in_program(self):
        """The branch taken depends on the FED value, proving lax.cond
        compiled into the program (not a baked build-time branch)."""
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [1], "float32")
            z = static.nn.cond(x.sum() > 0, lambda: x * 10.0,
                               lambda: x * 100.0)
        exe = static.Executor()
        (a,) = exe.run(prog, feed={"x": np.array([2.0], "float32")},
                       fetch_list=[z])
        (b,) = exe.run(prog, feed={"x": np.array([-2.0], "float32")},
                       fetch_list=[z])
        np.testing.assert_allclose(a, [20.0])
        np.testing.assert_allclose(b, [-200.0])


class TestWhileLoop:
    def test_reference_docstring_example(self):
        i = paddle.full(shape=[1], fill_value=0, dtype="int32")
        ten = paddle.full(shape=[1], fill_value=10, dtype="int32")
        (out,) = static.nn.while_loop(lambda i: i < ten,
                                      lambda i: [i + 1], [i])
        np.testing.assert_allclose(out.numpy(), [10])

    def test_multi_var(self):
        i = paddle.full([1], 0.0)
        acc = paddle.full([1], 0.0)
        iN, accN = static.nn.while_loop(
            lambda i, acc: i < 5.0, lambda i, acc: [i + 1.0, acc + i],
            [i, acc])
        np.testing.assert_allclose(accN.numpy(), [10.0])

    def test_fed_trip_count_in_program(self):
        prog = static.Program()
        with static.program_guard(prog):
            n = static.data("n", [1], "float32")
            i0 = paddle.full([1], 0.0)
            a0 = paddle.full([1], 0.0)
            _, accN = static.nn.while_loop(
                lambda i, a: i < n, lambda i, a: [i + 1.0, a + i], [i0, a0])
        exe = static.Executor()
        (v5,) = exe.run(prog, feed={"n": np.array([5.0], "float32")},
                        fetch_list=[accN])
        (v3,) = exe.run(prog, feed={"n": np.array([3.0], "float32")},
                        fetch_list=[accN])
        np.testing.assert_allclose(v5, [10.0])
        np.testing.assert_allclose(v3, [3.0])

    def test_bad_body_raises(self):
        i = paddle.full([1], 0.0)
        with pytest.raises(ValueError):
            static.nn.while_loop(lambda i: i < 3.0,
                                 lambda i: [paddle.full([2], 0.0)], [i])
        with pytest.raises(ValueError):
            static.nn.while_loop(lambda i: i < 3.0, lambda i: [i + 1], [])


class TestStaticGradients:
    def test_gradients_fetchable(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 2], "float32")
            x.stop_gradient = False
            y = (x * x).sum()
            (gx,) = static.gradients(y, x)
        feed = np.array([[1.0, 2.0], [3.0, 4.0]], "float32")
        (gv,) = static.Executor().run(prog, feed={"x": feed},
                                      fetch_list=[gx])
        np.testing.assert_allclose(gv, 2 * feed)

    def test_gradients_through_param(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [3, 4], "float32")
            x.stop_gradient = False
            y = static.nn.fc(x, 2)
            (gx,) = static.gradients(y.sum(), x)
        feed = np.ones((3, 4), "float32")
        (gv,) = static.Executor().run(prog, feed={"x": feed},
                                      fetch_list=[gx])
        w = np.asarray(prog.all_parameters()[0].numpy())
        np.testing.assert_allclose(gv, np.tile(w.sum(1), (3, 1)), rtol=1e-5)


class TestStagedSideEffects:
    """Print/Assert/py_func: run-time side effects inside compiled programs
    (reference control_flow.py:2215 Print, :59 Assert; static/nn py_func) —
    the dy2static AST-semantics gap from round-3 VERDICT §2.4."""

    def test_print_fires_at_run_not_build(self, capfd):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2], "float32")
            y = static.Print(x * 2, message="stagedprint:")
            z = y + 1
        build_out = capfd.readouterr().out
        assert "stagedprint:" not in build_out  # build must not print
        exe = static.Executor()
        (zv,) = exe.run(prog, feed={"x": np.array([1., 2.], np.float32)},
                        fetch_list=[z])
        np.testing.assert_allclose(zv, [3., 5.])
        import jax

        jax.effects_barrier()
        run_out = capfd.readouterr().out
        assert "stagedprint:" in run_out and "[2. 4.]" in run_out.replace(
            "2.0", "2.")

    def test_assert_checks_fed_values(self):
        prog = static.Program()
        with static.program_guard(prog):
            a = static.data("a", [1], "float32")
            static.Assert(a > 0, data=[a])
            out = a * 3
        exe = static.Executor()
        (ov,) = exe.run(prog, feed={"a": np.array([2.], np.float32)},
                        fetch_list=[out])
        np.testing.assert_allclose(ov, [6.])
        with pytest.raises(Exception):  # JaxRuntimeError from the callback
            exe.run(prog, feed={"a": np.array([-1.], np.float32)},
                    fetch_list=[out])

    def test_py_func_forward_and_custom_backward(self):
        """backward_func receives (x, out, dout) — the reference contract
        (static/nn/common.py py_func)."""
        def np_cube(x):
            return x ** 3

        def np_cube_bwd(x, y, dy):
            assert y.shape == x.shape  # forward output IS passed
            return dy * 3 * x ** 2

        xin = paddle.to_tensor(np.array([1., 2., 3.], np.float32),
                               stop_gradient=False)
        proto = paddle.to_tensor(np.zeros(3, np.float32))
        out = static.nn.py_func(np_cube, xin, proto,
                                backward_func=np_cube_bwd)
        np.testing.assert_allclose(out.numpy(), [1., 8., 27.])
        out.sum().backward()
        np.testing.assert_allclose(xin.grad.numpy(), [3., 12., 27.])

    def test_py_func_skip_vars_in_backward(self):
        def np_cube(x):
            return x ** 3

        def np_cube_bwd_no_out(x, dy):  # out skipped
            return dy * 3 * x ** 2

        xin = paddle.to_tensor(np.array([2.], np.float32),
                               stop_gradient=False)
        proto = paddle.to_tensor(np.zeros(1, np.float32))
        out = static.nn.py_func(np_cube, xin, proto,
                                backward_func=np_cube_bwd_no_out,
                                skip_vars_in_backward_input=[proto])
        out.sum().backward()
        np.testing.assert_allclose(xin.grad.numpy(), [12.])

    def test_py_func_in_program(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("xpf", [3], "float32")
            proto = paddle.zeros([3])
            y = static.nn.py_func(lambda v: v + 10.0, x, proto)
        (yv,) = static.Executor().run(
            prog, feed={"xpf": np.array([1., 2., 3.], np.float32)},
            fetch_list=[y])
        np.testing.assert_allclose(yv, [11., 12., 13.])
