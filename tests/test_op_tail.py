"""Round-4 op-tail: vision.ops, geometric, nn.quant, nn.utils, pooling
tail, loss tail, tensor tail, _C_ops surface, fused softmax-mask.

Reference model: per-op forward parity vs NumPy + grad smoke
(test/legacy_test op tests for the corresponding kernels)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision import ops as V

rng = np.random.default_rng(42)


class TestVisionOps:
    def test_nms(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60],
                          [0, 0, 5, 5]], np.float32)
        scores = np.array([0.9, 0.8, 0.7, 0.6], np.float32)
        keep = V.nms(paddle.to_tensor(boxes), 0.5, paddle.to_tensor(scores))
        assert list(keep.numpy()) == [0, 2, 3]

    def test_nms_categories(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1], np.int64)
        keep = V.nms(paddle.to_tensor(boxes), 0.5,
                     paddle.to_tensor(scores),
                     paddle.to_tensor(cats), [0, 1])
        assert len(keep.numpy()) == 2  # different classes: both kept

    def test_roi_align_constant(self):
        x = paddle.to_tensor(np.full((2, 3, 16, 16), 5.0, np.float32))
        rois = paddle.to_tensor(np.array(
            [[0, 0, 8, 8], [4, 4, 12, 12], [0, 0, 16, 16]], np.float32))
        bn = paddle.to_tensor(np.array([2, 1], np.int32))
        out = V.roi_align(x, rois, bn, 4)
        assert out.shape == [3, 3, 4, 4]
        np.testing.assert_allclose(out.numpy(), 5.0, rtol=1e-5)

    def test_roi_align_grad(self):
        x = paddle.to_tensor(rng.standard_normal(
            (1, 2, 8, 8)).astype("float32"), stop_gradient=False)
        rois = paddle.to_tensor(np.array([[1, 1, 6, 6]], np.float32))
        bn = paddle.to_tensor(np.array([1], np.int32))
        V.roi_align(x, rois, bn, 2).sum().backward()
        assert x.grad is not None and np.abs(x.grad.numpy()).sum() > 0

    def test_roi_pool(self):
        x = paddle.to_tensor(np.full((1, 2, 8, 8), 3.0, np.float32))
        rois = paddle.to_tensor(np.array([[0, 0, 7, 7]], np.float32))
        bn = paddle.to_tensor(np.array([1], np.int32))
        out = V.roi_pool(x, rois, bn, 2)
        np.testing.assert_allclose(out.numpy(), 3.0, rtol=1e-6)

    def test_psroi_pool(self):
        x = paddle.to_tensor(rng.random((1, 8, 12, 12)).astype("float32"))
        out = V.psroi_pool(x, paddle.to_tensor(
            np.array([[0, 0, 12, 12]], np.float32)),
            paddle.to_tensor(np.array([1], np.int32)), 2)
        assert out.shape == [1, 2, 2, 2]
        with pytest.raises(ValueError):
            V.psroi_pool(paddle.to_tensor(np.zeros((1, 7, 4, 4), "float32")),
                         paddle.to_tensor(np.array([[0, 0, 4, 4]],
                                                   np.float32)),
                         paddle.to_tensor(np.array([1], np.int32)), 2)

    def test_box_coder_roundtrip(self):
        pb = paddle.to_tensor(np.array([[0, 0, 10, 10], [5, 5, 20, 20]],
                                       np.float32))
        tb = paddle.to_tensor(np.array([[1, 1, 9, 9], [6, 6, 18, 18]],
                                       np.float32))
        var = [0.1, 0.1, 0.2, 0.2]
        enc = V.box_coder(pb, var, tb)
        dec = V.box_coder(pb, var, paddle.to_tensor(enc.numpy()),
                          code_type="decode_center_size")
        d = dec.numpy()
        np.testing.assert_allclose(d[0, 0], [1, 1, 9, 9], atol=1e-4)
        np.testing.assert_allclose(d[1, 1], [6, 6, 18, 18], atol=1e-4)

    def test_deform_conv_zero_offset_is_conv(self):
        x = paddle.to_tensor(rng.standard_normal((2, 4, 8, 8))
                             .astype("float32"))
        w = paddle.to_tensor(
            rng.standard_normal((6, 4, 3, 3)).astype("float32") * 0.1)
        off = paddle.to_tensor(np.zeros((2, 18, 8, 8), np.float32))
        y = V.deform_conv2d(x, off, w, padding=1)
        ref = F.conv2d(x, w, padding=1)
        np.testing.assert_allclose(y.numpy(), ref.numpy(), rtol=1e-4,
                                   atol=1e-4)

    def test_deform_conv_layer_and_grad(self):
        layer = V.DeformConv2D(4, 6, 3, padding=1)
        x = paddle.to_tensor(rng.standard_normal((1, 4, 6, 6))
                             .astype("float32"))
        off = paddle.to_tensor(
            rng.standard_normal((1, 18, 6, 6)).astype("float32") * 0.1)
        out = layer(x, off)
        out.sum().backward()
        assert layer.weight.grad is not None

    def test_yolo_box_shapes(self):
        x = paddle.to_tensor(rng.standard_normal(
            (2, 3 * 7, 4, 4)).astype("float32"))
        img = paddle.to_tensor(np.full((2, 2), 64, np.int32))
        b, s = V.yolo_box(x, img, [10, 13, 16, 30, 33, 23], 2, 0.01, 16)
        assert b.shape == [2, 48, 4] and s.shape == [2, 48, 2]

    def test_yolo_loss_finite_and_grad(self):
        x = paddle.to_tensor(rng.standard_normal(
            (2, 3 * 7, 4, 4)).astype("float32") * 0.1, stop_gradient=False)
        gt = paddle.to_tensor(
            np.array([[[0.5, 0.5, 0.3, 0.4]], [[0.2, 0.3, 0.1, 0.2]]],
                     np.float32))
        gl = paddle.to_tensor(np.zeros((2, 1), np.int64))
        loss = V.yolo_loss(x, gt, gl, [10, 13, 16, 30, 33, 23], [0, 1, 2],
                           2, 0.5, 16)
        assert np.isfinite(loss.numpy()).all()
        loss.sum().backward()
        assert x.grad is not None

    def test_prior_box(self):
        inp = paddle.to_tensor(np.zeros((1, 3, 4, 4), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
        b, v = V.prior_box(inp, img, min_sizes=[8.0], aspect_ratios=[2.0],
                           flip=True, clip=True)
        assert b.shape == [4, 4, 3, 4]
        assert (b.numpy() >= 0).all() and (b.numpy() <= 1).all()

    def test_matrix_nms(self):
        bb = paddle.to_tensor(np.array(
            [[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
            np.float32))
        sc = paddle.to_tensor(np.array(
            [[[0.1, 0.1, 0.1], [0.9, 0.8, 0.7]]], np.float32))
        out, num = V.matrix_nms(bb, sc, 0.3, 0.0, 10, 5,
                                background_label=0)
        assert out.shape[1] == 6 and int(num.numpy()[0]) == out.shape[0]

    def test_generate_proposals(self):
        H = W = 4
        A = 3
        scores = paddle.to_tensor(rng.random((1, A, H, W)).astype("float32"))
        deltas = paddle.to_tensor(
            rng.standard_normal((1, A * 4, H, W)).astype("float32") * 0.1)
        img = paddle.to_tensor(np.array([[64, 64]], np.float32))
        a = (rng.random((H * W * A, 4)) * 32).astype("float32")
        a[:, 2:] = a[:, :2] + 8  # well-formed boxes
        anchors = paddle.to_tensor(a)
        var = paddle.to_tensor(np.ones((H * W * A, 4), np.float32))
        rois, probs, n = V.generate_proposals(
            scores, deltas, img, anchors, var, min_size=1.0,
            return_rois_num=True)
        assert rois.shape[1] == 4 and int(n.numpy()[0]) == rois.shape[0]

    def test_distribute_fpn_proposals(self):
        rois = paddle.to_tensor(np.array(
            [[0, 0, 10, 10], [0, 0, 100, 100], [0, 0, 224, 224]],
            np.float32))
        multi, restore = V.distribute_fpn_proposals(rois, 2, 5, 4, 224)
        assert len(multi) == 4
        assert sum(m.shape[0] for m in multi) == 3
        assert sorted(restore.numpy().ravel().tolist()) == [0, 1, 2]


class TestGeometric:
    def test_segment_ops(self):
        G = paddle.geometric
        data = paddle.to_tensor(np.array(
            [[1., 2., 3.], [3., 2., 1.], [4., 5., 6.]], np.float32))
        ids = paddle.to_tensor(np.array([0, 0, 1], np.int32))
        np.testing.assert_allclose(G.segment_sum(data, ids).numpy(),
                                   [[4, 4, 4], [4, 5, 6]])
        np.testing.assert_allclose(G.segment_mean(data, ids).numpy(),
                                   [[2, 2, 2], [4, 5, 6]])
        np.testing.assert_allclose(G.segment_min(data, ids).numpy(),
                                   [[1, 2, 1], [4, 5, 6]])
        np.testing.assert_allclose(G.segment_max(data, ids).numpy(),
                                   [[3, 2, 3], [4, 5, 6]])

    def test_send_u_recv_reference_example(self):
        G = paddle.geometric
        x = paddle.to_tensor(np.array([[0, 2, 3], [1, 4, 5], [2, 6, 7]],
                                      np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int32))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int32))
        out = G.send_u_recv(x, src, dst, "sum")
        np.testing.assert_allclose(out.numpy(),
                                   [[0, 2, 3], [2, 8, 10], [1, 4, 5]])

    def test_send_u_recv_grad(self):
        G = paddle.geometric
        x = paddle.to_tensor(np.ones((3, 3), np.float32),
                             stop_gradient=False)
        src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int32))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int32))
        G.send_u_recv(x, src, dst, "sum").sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   [[2, 2, 2], [1, 1, 1], [1, 1, 1]])

    def test_send_ue_recv_and_uv(self):
        G = paddle.geometric
        x = paddle.to_tensor(np.array([[0, 2, 3], [1, 4, 5], [2, 6, 7]],
                                      np.float32))
        y = paddle.to_tensor(np.ones((4, 3), np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int32))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int32))
        out = G.send_ue_recv(x, y, src, dst, "add", "sum")
        np.testing.assert_allclose(out.numpy(),
                                   [[1, 3, 4], [4, 10, 12], [2, 5, 6]])
        assert G.send_uv(x, x, src, dst, "mul").shape == [4, 3]

    def test_reindex_and_sample(self):
        G = paddle.geometric
        xs = paddle.to_tensor(np.array([0, 5, 8, 9], np.int64))
        nbs = paddle.to_tensor(np.array([8, 9, 0, 4, 7, 6, 7], np.int64))
        cnt = paddle.to_tensor(np.array([2, 3, 1, 1], np.int64))
        rs, rd, mp = G.reindex_graph(xs, nbs, cnt)
        assert list(mp.numpy()[:4]) == [0, 5, 8, 9]
        assert rd.numpy().tolist() == [0, 0, 1, 1, 1, 2, 3]
        row = paddle.to_tensor(np.array([1, 2, 0, 2, 0, 1], np.int64))
        colptr = paddle.to_tensor(np.array([0, 2, 4, 6], np.int64))
        nb, c = G.sample_neighbors(
            row, colptr, paddle.to_tensor(np.array([0, 2], np.int64)),
            sample_size=1)
        assert list(c.numpy()) == [1, 1]


class TestQuantOps:
    def test_int8_roundtrip(self):
        from paddle_tpu.nn.quant import weight_dequantize, weight_quantize

        w = rng.standard_normal((64, 32)).astype("float32")
        q, s = weight_quantize(paddle.to_tensor(w))
        assert q.shape == [32, 64] and s.shape == [32]
        assert str(q.numpy().dtype) == "int8"
        wd = weight_dequantize(q, s, out_dtype="float32")
        assert np.abs(wd.numpy() - w).max() / np.abs(w).max() < 0.02

    def test_weight_only_linear(self):
        from paddle_tpu.nn.quant import weight_only_linear, weight_quantize

        w = rng.standard_normal((64, 32)).astype("float32")
        x = rng.standard_normal((4, 64)).astype("float32")
        ref = x @ w
        q, s = weight_quantize(paddle.to_tensor(w))
        y = weight_only_linear(paddle.to_tensor(x), q, weight_scale=s)
        assert np.abs(y.numpy() - ref).max() / np.abs(ref).max() < 0.03
        q4, s4 = weight_quantize(paddle.to_tensor(w),
                                 algo="weight_only_int4")
        assert q4.shape == [32, 32]  # packed nibbles
        y4 = weight_only_linear(paddle.to_tensor(x), q4, weight_scale=s4,
                                weight_dtype="int4")
        assert np.abs(y4.numpy() - ref).max() / np.abs(ref).max() < 0.2

    def test_llm_int8_outliers(self):
        from paddle_tpu.nn.quant import llm_int8_linear, weight_quantize

        w = rng.standard_normal((64, 32)).astype("float32")
        x = rng.standard_normal((4, 64)).astype("float32")
        x[:, 5] *= 50
        q, s = weight_quantize(paddle.to_tensor(w), algo="llm.int8")
        y = llm_int8_linear(paddle.to_tensor(x), q, weight_scale=s,
                            threshold=6.0)
        ref = x @ w
        assert np.abs(y.numpy() - ref).max() / np.abs(ref).max() < 0.05


class TestNNUtils:
    def test_weight_norm_roundtrip(self):
        from paddle_tpu.nn.utils import remove_weight_norm, weight_norm

        lin = paddle.nn.Linear(8, 6)
        x = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"))
        ref = lin(x).numpy()
        weight_norm(lin, dim=0)
        np.testing.assert_allclose(lin(x).numpy(), ref, rtol=1e-5)
        lin(x).sum().backward()
        assert lin.weight_g.grad is not None
        remove_weight_norm(lin)
        np.testing.assert_allclose(lin(x).numpy(), ref, rtol=1e-5)
        assert "weight_g" not in dict(lin.named_parameters())

    def test_spectral_norm_unit_sv(self):
        from paddle_tpu.nn.utils import spectral_norm

        lin = paddle.nn.Linear(8, 6)
        with paddle.no_grad():
            lin.weight.set_value(lin.weight.numpy() * 10)
        spectral_norm(lin, n_power_iterations=5)
        lin.train()
        x = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"))
        for _ in range(5):
            lin(x)
        sv = np.linalg.svd(lin.weight.numpy(), compute_uv=False).max()
        assert abs(sv - 1.0) < 0.05

    def test_vector_roundtrip_and_clip(self):
        from paddle_tpu.nn.utils import (clip_grad_norm_, clip_grad_value_,
                                         parameters_to_vector,
                                         vector_to_parameters)

        lin = paddle.nn.Linear(3, 2)
        vec = parameters_to_vector(lin.parameters())
        assert vec.shape == [8]
        vector_to_parameters(paddle.to_tensor(np.zeros(8, np.float32)),
                             lin.parameters())
        assert np.abs(lin.weight.numpy()).sum() == 0
        p = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
        (p * paddle.to_tensor(np.array([3., 4., 0., 0.],
                                       np.float32))).sum().backward()
        total = clip_grad_norm_([p], 1.0)
        np.testing.assert_allclose(float(total.numpy()), 5.0, rtol=1e-4)
        np.testing.assert_allclose(np.linalg.norm(p.grad.numpy()), 1.0,
                                   rtol=1e-3)
        clip_grad_value_([p], 0.1)
        assert np.abs(p.grad.numpy()).max() <= 0.1 + 1e-6


class TestPoolingTail:
    def test_max_pool_mask_and_unpool(self):
        x = rng.standard_normal((2, 3, 8, 8)).astype("float32")
        xt = paddle.to_tensor(x)
        out, mask = F.max_pool2d(xt, 2, 2, return_mask=True)
        flat = x.reshape(2, 3, -1)
        for b in range(2):
            for c in range(3):
                np.testing.assert_allclose(
                    flat[b, c][mask.numpy()[b, c].ravel()],
                    out.numpy()[b, c].ravel(), rtol=1e-6)
        un = F.max_unpool2d(out, mask, 2, 2)
        assert un.shape == [2, 3, 8, 8]

    def test_negative_input_padded_pool(self):
        x = paddle.to_tensor(
            -np.abs(rng.standard_normal((2, 3, 8, 8))).astype("float32")
            - 1.0)
        on, _ = F.max_pool2d(x, 3, 2, padding=1, return_mask=True)
        ref = F.max_pool2d(x, 3, 2, padding=1)
        np.testing.assert_allclose(on.numpy(), ref.numpy(), rtol=1e-6)

    def test_unpool_1d_3d(self):
        x1 = paddle.to_tensor(rng.standard_normal((2, 3, 10))
                              .astype("float32"))
        o1, m1 = F.max_pool1d(x1, 2, 2, return_mask=True)
        assert F.max_unpool1d(o1, m1, 2, 2).shape == [2, 3, 10]
        x3 = paddle.to_tensor(rng.standard_normal((1, 2, 4, 4, 4))
                              .astype("float32"))
        o3, m3 = F.max_pool3d(x3, 2, 2, return_mask=True)
        assert F.max_unpool3d(o3, m3, 2, 2).shape == [1, 2, 4, 4, 4]

    def test_lp_pool(self):
        c = paddle.to_tensor(np.full((1, 1, 4, 4), 2.0, np.float32))
        np.testing.assert_allclose(F.lp_pool2d(c, 2, 2, 2).numpy(), 4.0,
                                   rtol=1e-5)
        c1 = paddle.to_tensor(np.full((1, 1, 4), 2.0, np.float32))
        np.testing.assert_allclose(
            F.lp_pool1d(c1, 1, 2, 2).numpy(), 4.0, rtol=1e-5)

    def test_fractional_pool(self):
        x = rng.standard_normal((2, 3, 8, 8)).astype("float32")
        xt = paddle.to_tensor(x)
        out = F.fractional_max_pool2d(xt, 3, random_u=0.3)
        assert out.shape == [2, 3, 3, 3]
        out2, mask = F.fractional_max_pool2d(xt, 3, random_u=0.3,
                                             return_mask=True)
        flat = x.reshape(2, 3, -1)
        for b in range(2):
            for c in range(3):
                np.testing.assert_allclose(
                    flat[b, c][mask.numpy()[b, c].ravel()],
                    out2.numpy()[b, c].ravel(), rtol=1e-6)


class TestLossTail:
    def test_hsigmoid_default_tree(self):
        inp = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"),
                               stop_gradient=False)
        lab = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))
        w = paddle.to_tensor(
            rng.standard_normal((3, 8)).astype("float32") * 0.1,
            stop_gradient=False)
        loss = F.hsigmoid_loss(inp, lab, 4, w)
        assert loss.shape == [4, 1] and np.isfinite(loss.numpy()).all()
        loss.sum().backward()
        assert inp.grad is not None and w.grad is not None

    def test_margin_ce_degenerates_to_plain_ce(self):
        import jax
        import jax.numpy as jnp

        logits = paddle.to_tensor(
            rng.standard_normal((6, 10)).astype("float32") * 0.1)
        lab = paddle.to_tensor(rng.integers(0, 10, (6,)).astype("int64"))
        mce = F.margin_cross_entropy(logits, lab, margin1=1.0, margin2=0.0,
                                     margin3=0.0, scale=1.0,
                                     reduction="mean")
        ref = float(jnp.mean(-jax.nn.log_softmax(logits.numpy())[
            np.arange(6), lab.numpy()]))
        np.testing.assert_allclose(float(mce.numpy()), ref, rtol=1e-4)
        loss, sm = F.margin_cross_entropy(logits, lab, return_softmax=True)
        assert sm.shape == [6, 10]

    def test_class_center_sample(self):
        lab = paddle.to_tensor(np.array([1, 5, 5, 7], np.int64))
        new_lab, sampled = F.class_center_sample(lab, 10, 6)
        s = sampled.numpy()
        assert {1, 5, 7}.issubset(set(s.tolist())) and len(s) == 6
        for orig, nl in zip([1, 5, 5, 7], new_lab.numpy()):
            assert s[nl] == orig

    def test_rrelu(self):
        xa = paddle.to_tensor(np.full((1000,), -1.0, np.float32))
        ev = F.rrelu(xa, training=False)
        np.testing.assert_allclose(ev.numpy(), -(1 / 8 + 1 / 3) / 2,
                                   rtol=1e-5)
        s = -F.rrelu(xa, training=True).numpy()
        assert (s >= 1 / 8 - 1e-6).all() and (s <= 1 / 3 + 1e-6).all()
        assert s.std() > 0.01


class TestTensorTail:
    def test_indices_and_complex(self):
        assert paddle.tril_indices(4, 4, 0).numpy().shape == (2, 10)
        assert paddle.triu_indices(3, 3, 1).numpy().shape == (2, 3)
        c = paddle.complex(paddle.to_tensor([1.0]), paddle.to_tensor([2.0]))
        assert "complex" in str(c.dtype)

    def test_fill_diagonal(self):
        x = paddle.to_tensor(np.zeros((3, 3), np.float32))
        x.fill_diagonal_(5.0)
        np.testing.assert_allclose(x.numpy(), np.eye(3) * 5)
        y = paddle.to_tensor(np.zeros((4, 4), np.float32))
        o = paddle.fill_diagonal_tensor(
            y, paddle.to_tensor(np.array([1., 2., 3., 4.], np.float32)))
        np.testing.assert_allclose(np.diag(o.numpy()), [1, 2, 3, 4])

    def test_reduce_as(self):
        big = paddle.to_tensor(rng.standard_normal((2, 3, 4))
                               .astype("float32"))
        tgt = paddle.to_tensor(np.zeros((3, 1), np.float32))
        r = paddle.reduce_as(big, tgt)
        np.testing.assert_allclose(
            r.numpy(), big.numpy().sum(0).sum(-1, keepdims=True), rtol=1e-5)

    def test_edit_distance(self):
        ed, n = paddle.edit_distance(
            paddle.to_tensor(np.array([[1, 2, 3]], np.int64)),
            paddle.to_tensor(np.array([[1, 3, 3]], np.int64)),
            normalized=False)
        np.testing.assert_allclose(ed.numpy(), [[1.0]])
        assert int(n.numpy()[0]) == 1

    def test_clip_by_norm_svdvals_gamma(self):
        cb = paddle.clip_by_norm(
            paddle.to_tensor(np.array([3.0, 4.0], np.float32)), 1.0)
        np.testing.assert_allclose(np.linalg.norm(cb.numpy()), 1.0,
                                   rtol=1e-5)
        sv = paddle.linalg.svdvals(paddle.to_tensor(
            np.diag([3., 2., 1.]).astype("float32")))
        np.testing.assert_allclose(sv.numpy(), [3, 2, 1], rtol=1e-5)
        g = paddle.standard_gamma(
            paddle.to_tensor(np.full((2000,), 2.0, np.float32)))
        assert abs(g.numpy().mean() - 2.0) < 0.3


class TestSoftmaxMaskFuse:
    def test_fused_softmax_mask(self):
        import jax

        x = rng.standard_normal((2, 2, 4, 4)).astype("float32")
        m = np.where(rng.random((2, 1, 4, 4)) > 0.5, 0.0,
                     -1e9).astype("float32")
        out = paddle.incubate.softmax_mask_fuse(
            paddle.to_tensor(x), paddle.to_tensor(m))
        ref = np.asarray(jax.nn.softmax(x + m, axis=-1))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)

    def test_fused_softmax_mask_upper_triangle(self):
        x = rng.standard_normal((1, 2, 5, 5)).astype("float32")
        out = paddle.incubate.softmax_mask_fuse_upper_triangle(
            paddle.to_tensor(x)).numpy()
        # rows sum to 1; strictly-upper entries are 0
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)
        assert np.abs(np.triu(out[0, 0], 1)).max() < 1e-6


class TestCOpsSurface:
    def test_audit_tool_passes(self):
        import subprocess
        import sys

        r = subprocess.run(
            [sys.executable, "tools/op_audit.py"], capture_output=True,
            text=True, cwd="/root/repo",
            env={"PYTHONPATH": "/root/repo", "JAX_PLATFORMS": "cpu",
                 "PATH": "/usr/bin:/bin:/opt/venv/bin"})
        assert r.returncode == 0, r.stdout + r.stderr
        assert "resolution: 9" in r.stdout  # >= 90%

    def test_optimizer_kernels(self):
        import paddle_tpu._C_ops as C

        p = paddle.to_tensor(np.ones(4, np.float32))
        g = paddle.to_tensor(np.full(4, 0.5, np.float32))
        C.sgd_(p, paddle.to_tensor(np.float32(0.1)), g)
        np.testing.assert_allclose(p.numpy(), 0.95)
        m1 = paddle.to_tensor(np.zeros(4, np.float32))
        m2 = paddle.to_tensor(np.zeros(4, np.float32))
        b1 = paddle.to_tensor(np.float32(1.0))
        b2 = paddle.to_tensor(np.float32(1.0))
        C.adam_(p, g, paddle.to_tensor(np.float32(0.1)), m1, m2, b1, b2)
        assert np.isfinite(p.numpy()).all()
        np.testing.assert_allclose(b1.numpy(), 0.9, rtol=1e-6)

    def test_misc_kernels(self):
        import paddle_tpu._C_ops as C

        out = C.hinge_loss(
            paddle.to_tensor(np.array([0.5, -0.5], np.float32)),
            paddle.to_tensor(np.array([1.0, 1.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [0.5, 1.5])
        al = C.ctc_align(paddle.to_tensor(
            np.array([[1, 1, 0, 2, 2, 0, 3]], np.int32)))
        np.testing.assert_allclose(al.numpy(), [[1, 2, 3]])
        cnt = C.number_count(
            paddle.to_tensor(np.array([0, 1, 1, 2], np.int64)), 4)
        np.testing.assert_allclose(cnt.numpy(), [1, 2, 1, 0])
        mi, _ = C.bipartite_match(paddle.to_tensor(
            np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)))
        np.testing.assert_allclose(mi.numpy(), [[0, 1]])
        d = C.dirichlet(paddle.to_tensor(
            np.array([1.0, 2.0, 3.0], np.float32)))
        np.testing.assert_allclose(d.numpy().sum(), 1.0, rtol=1e-5)

    def test_warprnnt_lattice(self):
        import paddle_tpu._C_ops as C

        r = C.warprnnt(
            paddle.to_tensor(rng.standard_normal((1, 5, 3, 4))
                             .astype("float32")),
            paddle.to_tensor(np.array([[1, 2]], np.int32)),
            paddle.to_tensor(np.array([5], np.int32)),
            paddle.to_tensor(np.array([2], np.int32)))
        assert np.isfinite(r.numpy()).all() and float(r.numpy()) > 0

    def test_fake_quant_family(self):
        import paddle_tpu._C_ops as C

        x = paddle.to_tensor(rng.standard_normal((4, 4)).astype("float32"))
        q, s = C.fake_quantize_abs_max(x)
        assert np.abs(q.numpy()).max() <= 127
        dq, s2 = C.fake_quantize_dequantize_abs_max(x)
        assert np.abs(dq.numpy() - x.numpy()).max() < 0.05
        qc, sc = C.fake_channel_wise_quantize_abs_max(x)
        assert sc.shape == [4]


class TestTextDatasets:
    def test_uci_housing_local(self, tmp_path):
        import paddle_tpu.text.datasets as TD

        rng2 = np.random.default_rng(0)
        raw = np.concatenate([rng2.random((500, 13)),
                              rng2.random((500, 1)) * 50], axis=1)
        f = tmp_path / "housing.data"
        np.savetxt(f, raw)
        train = TD.UCIHousing(data_file=str(f), mode="train")
        test = TD.UCIHousing(data_file=str(f), mode="test")
        assert len(train) == 406 and len(test) == 94
        x, y = train[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_wmt14_pairs(self, tmp_path):
        import paddle_tpu.text.datasets as TD

        (tmp_path / "s.en").write_text("hello world\nfoo bar baz\n")
        (tmp_path / "t.fr").write_text("bonjour monde\nfu barre base\n")
        ds = TD.WMT14(src_file=str(tmp_path / "s.en"),
                      trg_file=str(tmp_path / "t.fr"))
        assert len(ds) == 2
        src, trg, nxt = ds[0]
        assert trg[0] == ds.trg_dict["<s>"] and nxt[-1] == ds.trg_dict["<e>"]
        assert len(trg) == len(nxt)

    def test_imikolov_ngram(self, tmp_path):
        import tarfile

        import paddle_tpu.text.datasets as TD

        data = tmp_path / "data"
        data.mkdir()
        (data / "ptb.train.txt").write_text(
            "the cat sat\nthe dog sat\n" * 30)
        (data / "ptb.valid.txt").write_text("the cat sat\n")
        tar = tmp_path / "simple-examples.tgz"
        with tarfile.open(tar, "w:gz") as tf:
            tf.add(data / "ptb.train.txt", "simple-examples/data/ptb.train.txt")
            tf.add(data / "ptb.valid.txt", "simple-examples/data/ptb.valid.txt")
        ds = TD.Imikolov(data_file=str(tar), data_type="NGRAM",
                         window_size=3, mode="train", min_word_freq=10)
        assert len(ds) > 0
        assert all(g.shape == (3,) for g in [ds[0], ds[1]])

    def test_download_refused(self):
        import paddle_tpu.text.datasets as TD

        with pytest.raises(RuntimeError):
            TD.Imdb(download=True)
        with pytest.raises(RuntimeError):
            TD.UCIHousing()


class TestNamespaceBatch:
    def test_regularizer_applies_before_clip(self):
        from paddle_tpu import regularizer

        lin = paddle.nn.Linear(
            4, 4, weight_attr=paddle.ParamAttr(
                regularizer=regularizer.L2Decay(0.5)))
        w0 = lin.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        loss = lin(paddle.to_tensor(np.zeros((2, 4), np.float32))).sum()
        loss.backward()
        opt.step()
        np.testing.assert_allclose(lin.weight.numpy(),
                                   w0 * (1 - 0.1 * 0.5), rtol=1e-5)
        g = regularizer.L1Decay(0.3)(
            paddle.to_tensor(np.array([2.0, -3.0], np.float32)))
        np.testing.assert_allclose(g.numpy(), [0.3, -0.3])

    def test_reader_decorators(self):
        r = lambda: iter(range(10))  # noqa: E731
        assert [b for b in paddle.batch(r, 3)()][0] == [0, 1, 2]
        assert len([b for b in paddle.batch(r, 3, drop_last=True)()]) == 3
        assert sorted(x for x in paddle.reader.shuffle(r, 5)()) == \
            list(range(10))
        comp = [x for x in paddle.reader.compose(
            lambda: iter([1, 2]), lambda: iter([(3, 4), (5, 6)]))()]
        assert comp == [(1, 3, 4), (2, 5, 6)]

    def test_version_and_misc(self):
        assert paddle.__version__ == paddle.version.full_version
        assert paddle.in_dynamic_mode() is True
        paddle.disable_signal_handler()
        assert paddle.sysconfig.get_include().endswith("native")

    def test_histogramdd_cauchy_geometric(self):
        h, edges = paddle.histogramdd(
            paddle.to_tensor(rng.standard_normal((100, 2))
                             .astype("float32")), bins=4)
        assert h.shape == [4, 4] and len(edges) == 2
        assert float(h.numpy().sum()) == 100
        t = paddle.to_tensor(np.zeros(1000, np.float32))
        t.geometric_(0.5)
        assert t.numpy().min() >= 1 and 1.5 < t.numpy().mean() < 2.5

    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny(scale=1):\n    'doc'\n    return scale * 2\n")
        assert paddle.hub.list(str(tmp_path)) == ["tiny"]
        assert paddle.hub.load(str(tmp_path), "tiny", scale=3) == 6
        with pytest.raises(RuntimeError):
            paddle.hub.load("org/repo", "m", source="github")
