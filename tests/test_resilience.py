"""Fault tolerance end to end: crash-safe checkpoint commit protocol,
auto-resume, elastic status transitions, watchdog post-mortems, and the
fault-injection harness itself (docs/RESILIENCE.md).

The headline test is kill-during-save under the real launcher: a worker is
SIGKILL'd (os._exit) between writing a checkpoint's metadata and its COMMIT
marker, the pod respawns, and training resumes from the last committed step
with no manual cleanup."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import comm_watchdog
from paddle_tpu.distributed.checkpoint import (
    COMMIT_FILE,
    CheckpointCorruptError,
    CheckpointManager,
    Metadata,
    latest_checkpoint,
    load_state_dict,
    validate_checkpoint,
)
from paddle_tpu.distributed.checkpoint.metadata import metadata_path
from paddle_tpu.distributed.faults import FAULT_EXIT_CODE, FaultInjected
from paddle_tpu.distributed.resilience import ResilientTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sd(val=0.0, n=6):
    return {"w": paddle.to_tensor(np.full((n,), val, np.float32))}


# --------------------------------------------------------------------------- #
# commit protocol
# --------------------------------------------------------------------------- #

class TestCommitProtocol:
    def test_commit_layout(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_sd(1.0), 7)
        path = mgr.path_for(7)
        assert os.path.isfile(os.path.join(path, COMMIT_FILE))
        assert not os.path.isdir(path + ".tmp")
        meta = Metadata.load(metadata_path(path))
        assert meta.file_checksums  # file-level crc recorded
        for entries in meta.state_dict_metadata.values():
            assert all(m.checksum.startswith("crc32:") for m in entries)
        ok, reason = validate_checkpoint(path)
        assert ok, reason

    def test_interrupted_save_is_skipped_and_swept(self, tmp_path,
                                                   fault_injector):
        """(a) save dies between metadata and COMMIT: discovery resumes from
        the previous commit; the partial needs no manual cleanup."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_sd(1.0), 1)
        mgr.save(_sd(2.0), 2)
        fault_injector.arm("ckpt.before_commit", "exc")
        with pytest.raises(FaultInjected):
            mgr.save(_sd(3.0), 3)
        fault_injector.disarm()
        # the partial save left a .tmp (shards + metadata, no COMMIT)
        assert os.path.isdir(mgr.path_for(3) + ".tmp")
        assert not os.path.isdir(mgr.path_for(3))
        info = latest_checkpoint(str(tmp_path))
        assert info.step == 2
        tgt = _sd(0.0)
        load_state_dict(tgt, info.path)
        assert float(tgt["w"].numpy()[0]) == 2.0
        # next save sweeps the stale tmp as a side effect of rotation
        mgr.save(_sd(4.0), 4)
        assert not os.path.isdir(mgr.path_for(3) + ".tmp")
        assert latest_checkpoint(str(tmp_path)).step == 4

    def test_mid_save_failure_leaves_no_metadata(self, tmp_path,
                                                 fault_injector):
        mgr = CheckpointManager(str(tmp_path))
        fault_injector.arm("ckpt.mid_save", "exc")
        with pytest.raises(FaultInjected):
            mgr.save(_sd(1.0), 1)
        fault_injector.disarm()
        assert latest_checkpoint(str(tmp_path)) is None

    def test_checksum_mismatch_names_file(self, tmp_path, fault_injector):
        """(b) a bit-flipped shard raises a clear error naming the file and
        is never loaded silently."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_sd(1.0), 1)
        mgr.save(_sd(2.0), 2)
        bad = fault_injector.corrupt(mgr.path_for(2))
        with pytest.raises(CheckpointCorruptError) as ei:
            load_state_dict(_sd(0.0), mgr.path_for(2))
        assert os.path.basename(bad) in str(ei.value)
        # discovery falls back past the corruption
        assert latest_checkpoint(str(tmp_path)).step == 1

    def test_truncated_shard_detected(self, tmp_path, fault_injector):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_sd(5.0), 1)
        fault_injector.truncate(mgr.path_for(1), frac=0.3)
        with pytest.raises(CheckpointCorruptError):
            load_state_dict(_sd(0.0), mgr.path_for(1))
        assert latest_checkpoint(str(tmp_path)) is None

    def test_restore_latest_rolls_back_partial_load(self, tmp_path,
                                                    monkeypatch):
        """A corruption hit on a LATER shard (multi-file checkpoints) aborts
        the in-place load mid-loop; restore_latest must roll the mutated
        tensors back so 'no valid checkpoint' really means untouched live
        state, not a silent half-restored mix (graftlint-era review find)."""
        from paddle_tpu.distributed.checkpoint import manager as mgr_mod

        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_sd(7.0), 1)

        def half_load_then_die(state_dict, path, **kw):
            # emulate the multi-file failure mode: first tensor mutated,
            # then a later shard file turns out corrupt
            state_dict["w"]._value = state_dict["w"]._value * 0.0
            raise CheckpointCorruptError("later shard crc mismatch")

        monkeypatch.setattr(mgr_mod, "load_state_dict", half_load_then_die)
        live = _sd(3.0)
        assert mgr.restore_latest(live) is None
        np.testing.assert_array_equal(np.asarray(live["w"].numpy()),
                                      np.full((6,), 3.0, np.float32))

    def test_restore_latest_rolls_back_on_key_mismatch(self, tmp_path):
        """A live state_dict key absent from the checkpoint raises KeyError
        mid-load (schema change between save and resume); the error must
        propagate — it is NOT corruption — but only after the rollback."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_sd(7.0), 1)
        live = _sd(3.0)
        live["brand_new_param"] = paddle.to_tensor(
            np.full((2,), 5.0, np.float32))
        with pytest.raises(KeyError):
            mgr.restore_latest(live)
        # dict order put "w" first: it was overwritten with 7.0 before the
        # KeyError — the rollback must have undone that
        np.testing.assert_array_equal(np.asarray(live["w"].numpy()),
                                      np.full((6,), 3.0, np.float32))

    def test_keep_last_n_rotation(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
        for s in range(1, 6):
            mgr.save(_sd(float(s)), s)
        steps = sorted(d for d in os.listdir(str(tmp_path)))
        assert steps == ["step_4", "step_5"]

    def test_async_save_snapshots_at_call_time(self, tmp_path):
        """Double-buffered save: mutations after save() must not leak into
        the checkpoint (device→host snapshot happens on the caller)."""
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        sd = _sd(3.0)
        mgr.save(sd, 1)
        sd["w"].set_value(paddle.to_tensor(np.full((6,), 99.0, np.float32)))
        mgr.wait()
        tgt = _sd(0.0)
        assert mgr.restore_latest(tgt) == 1
        assert float(tgt["w"].numpy()[0]) == 3.0

    def test_async_failure_surfaces_on_wait(self, tmp_path, fault_injector):
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        fault_injector.arm("ckpt.before_commit", "exc")
        mgr.save(_sd(1.0), 1)
        with pytest.raises(FaultInjected):
            mgr.wait()
        fault_injector.disarm()
        assert latest_checkpoint(str(tmp_path)) is None

    def test_overwrite_preserves_unrelated_files(self, tmp_path):
        """Re-saving into an existing checkpoint dir must not delete files a
        user keeps alongside it (the pre-hardening save wrote in place)."""
        from paddle_tpu.distributed.checkpoint import save_state_dict

        path = str(tmp_path / "ckpt")
        save_state_dict(_sd(1.0), path)
        keep = os.path.join(path, "notes.txt")
        with open(keep, "w") as f:
            f.write("user data")
        save_state_dict(_sd(2.0), path)
        assert open(keep).read() == "user data"
        ok, reason = validate_checkpoint(path)
        assert ok, reason
        tgt = _sd(0.0)
        load_state_dict(tgt, path)
        assert float(tgt["w"].numpy()[0]) == 2.0

    def test_legacy_checkpoint_without_checksums_loads(self, tmp_path):
        """Pre-hardening checkpoints carry no checksums; they must still
        load (nothing to verify against) rather than be rejected."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_sd(8.0), 1)
        path = mgr.path_for(1)
        meta = Metadata.load(metadata_path(path))
        meta.file_checksums = {}
        for entries in meta.state_dict_metadata.values():
            for m in entries:
                m.checksum = ""
        meta.save(metadata_path(path))
        tgt = _sd(0.0)
        load_state_dict(tgt, path)
        assert float(tgt["w"].numpy()[0]) == 8.0

    def test_resume_under_different_sharding(self, tmp_path):
        """Checkpoint written under one mesh config restores under another —
        the reshard-on-load path the trainer relies on after an elastic
        reconfiguration."""
        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
        w = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        sd = {"w": dist.shard_tensor(paddle.to_tensor(w), mesh,
                                     [dist.Shard(0), dist.Shard(1)])}
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(sd, 3)
        mesh2 = dist.ProcessMesh(list(range(8)), dim_names=["p"])
        tgt = {"w": dist.shard_tensor(paddle.to_tensor(np.zeros_like(w)),
                                      mesh2, [dist.Shard(1)])}
        assert mgr.restore_latest(tgt) == 3
        np.testing.assert_allclose(tgt["w"].numpy(), w)


# --------------------------------------------------------------------------- #
# elastic transitions (fake store)
# --------------------------------------------------------------------------- #

class FakeStore:
    """Dict-backed stand-in for the native TCPStore (tryget contract)."""

    def __init__(self):
        self.d = {}

    def set(self, k, v):
        self.d[k] = v if isinstance(v, bytes) else str(v).encode()

    def tryget(self, k):
        return self.d.get(k)

    def add(self, k, amount):
        cur = int(self.d.get(k, b"0")) + int(amount)
        self.d[k] = str(cur).encode()
        return cur

    def delete_key(self, k):
        self.d.pop(k, None)


class TestElasticTransitions:
    def test_ok_hold_restart_on_rejoin(self):
        """(c) OK → HOLD on missed heartbeat → RESTART on rejoin → OK."""
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)

        store = FakeStore()
        m = ElasticManager(store=store, job_id="j", np_range="2:2", rank=0,
                           timeout=5.0)
        m.heartbeat()
        store.set("j/heartbeat/1", str(time.time()))
        assert m.watch() == ElasticStatus.OK
        # rank 1 misses its heartbeat: below min_np → HOLD
        store.set("j/heartbeat/1", str(time.time() - 60))
        assert m.watch() == ElasticStatus.HOLD
        assert m.watch() == ElasticStatus.HOLD  # stable while down
        # rank 1 rejoins: one RESTART to re-form the groups, then OK
        store.set("j/heartbeat/1", str(time.time()))
        assert m.watch() == ElasticStatus.RESTART
        assert m.watch() == ElasticStatus.OK

    def test_initial_fillup_is_not_a_reform(self):
        """Job start passes through HOLD while workers come up; reaching
        full strength the first time is OK, not a membership change."""
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)

        store = FakeStore()
        m = ElasticManager(store=store, job_id="j2", np_range="2:2", rank=0,
                           timeout=5.0)
        assert m.watch() == ElasticStatus.HOLD
        m.heartbeat()
        store.set("j2/heartbeat/1", str(time.time()))
        assert m.watch() == ElasticStatus.OK

    def test_shrink_within_band_signals_one_reform(self):
        """2:4 band: losing a node while still runnable must yield exactly
        one reform-flagged RESTART per survivor; the steady partial band
        keeps reporting plain (scale-up) RESTARTs that must NOT read as
        reforms — exiting on those would livelock the trainer."""
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)

        store = FakeStore()
        m = ElasticManager(store=store, job_id="jb", np_range="2:4", rank=0,
                           timeout=5.0)
        m.heartbeat()
        for r in (1, 2):
            store.set(f"jb/heartbeat/{r}", str(time.time()))
        assert m.watch() == ElasticStatus.RESTART  # 3/4: can still scale up
        assert not m.last_restart_was_reform
        assert m.watch() == ElasticStatus.RESTART  # steady state
        assert not m.last_restart_was_reform
        # node 2 dies; 2 alive >= min_np: survivors get ONE reform signal
        store.set("jb/heartbeat/2", str(time.time() - 60))
        assert m.watch() == ElasticStatus.RESTART
        assert m.last_restart_was_reform
        assert m.watch() == ElasticStatus.RESTART
        assert not m.last_restart_was_reform

    def test_completed_wins(self):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)

        store = FakeStore()
        m = ElasticManager(store=store, job_id="j3", np_range="1:1", rank=0)
        m.heartbeat()
        assert m.watch() == ElasticStatus.OK
        m.complete()
        assert m.watch() == ElasticStatus.COMPLETED


# --------------------------------------------------------------------------- #
# resilient trainer
# --------------------------------------------------------------------------- #

class TestResilientTrainer:
    def test_resume_in_process(self, tmp_path, monkeypatch):
        ckpt = str(tmp_path / "ck")
        w = paddle.to_tensor(np.zeros(4, np.float32))

        def step_fn(i):
            w.set_value(paddle.to_tensor(w.numpy() + 1.0))
            return float(w.numpy()[0])

        out = ResilientTrainer(step_fn, {"w": w}, ckpt, save_every=2,
                               async_save=False).run(5)
        assert out["resumed_from"] is None and out["last_loss"] == 5.0

        # "restart": fresh tensors, same checkpoint dir
        monkeypatch.setenv("PADDLE_RESTART_COUNT", "1")
        w2 = paddle.to_tensor(np.zeros(4, np.float32))
        ran = []

        def step_fn2(i):
            ran.append(i)
            w2.set_value(paddle.to_tensor(w2.numpy() + 1.0))
            return float(w2.numpy()[0])

        out2 = ResilientTrainer(step_fn2, {"w": w2}, ckpt, save_every=2,
                                async_save=False).run(8)
        assert out2["resumed_from"] == 4  # final save of run 1
        assert ran == [5, 6, 7]
        assert float(w2.numpy()[0]) == 8.0

    def test_hold_times_out(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        m = ElasticManager(store=FakeStore(), job_id="jh", np_range="2:2",
                           rank=0, timeout=5.0)
        t = ResilientTrainer(lambda i: 0.0, _sd(), str(tmp_path), elastic=m,
                             hold_poll=0.05, hold_timeout=0.3,
                             async_save=False)
        with pytest.raises(RuntimeError, match="hold timed out"):
            t.run(1)

    def test_watchdog_stall_spills_report(self, tmp_path):
        """A step stalled past its comm_task deadline lands in the spill
        file (the post-mortem the launcher dumps on worker death)."""
        report_file = str(tmp_path / "wd.report")
        comm_watchdog.disable()
        assert comm_watchdog.enable(timeout_seconds=5.0,
                                    report_file=report_file)
        try:
            with comm_watchdog.comm_task("stalled_step/3", 0.15):
                time.sleep(0.5)
            assert comm_watchdog.timeout_count() >= 1
            deadline = time.time() + 3
            content = ""
            while time.time() < deadline and "stalled_step/3" not in content:
                if os.path.exists(report_file):
                    content = open(report_file).read()
                time.sleep(0.05)
            assert "stalled_step/3" in content
            assert "exceeded" in content
        finally:
            comm_watchdog.disable()


# --------------------------------------------------------------------------- #
# launcher integration (forked workers)
# --------------------------------------------------------------------------- #

def _run_launch(tmp_path, extra_args, script_body, extra_env=None,
                timeout=240):
    script = os.path.join(str(tmp_path), "train.py")
    with open(script, "w") as f:
        f.write(script_body)
    env = {
        "PYTHONPATH": REPO,
        "PATH": os.environ.get("PATH", ""),
        "HOME": os.environ.get("HOME", "/root"),
        "JAX_PLATFORMS": "cpu",
        "CKPT_DIR": os.path.join(str(tmp_path), "ckpts"),
    }
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         f"--log_dir={tmp_path}/log", *extra_args, script],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=str(tmp_path))
    return proc


RESILIENT_TRAIN = """
import os, sys
import numpy as np
restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
if restart == 0:
    # fault only on the first life: die on the SECOND checkpoint, after
    # metadata is written but before the COMMIT marker
    os.environ["PADDLE_FAULT_INJECT"] = "ckpt.before_commit:kill@2"
import paddle_tpu as paddle
from paddle_tpu.distributed.resilience import ResilientTrainer

w = paddle.to_tensor(np.zeros(4, np.float32))
sd = {"w": w}
def step_fn(i):
    w.set_value(paddle.to_tensor(w.numpy() + 1.0))
    return float(w.numpy()[0])
t = ResilientTrainer(step_fn, sd, os.environ["CKPT_DIR"], save_every=2,
                     async_save=False)
out = t.run(6)
print("RESUMED_FROM", out["resumed_from"], flush=True)
print("FINAL", float(w.numpy()[0]), flush=True)
"""


def test_kill_during_save_resumes_from_last_commit(tmp_path):
    """ACCEPTANCE: worker SIGKILL'd mid-save (metadata written, COMMIT
    absent) → launcher respawns → training auto-resumes from the last
    committed step, with no manual cleanup of the torn checkpoint."""
    proc = _run_launch(tmp_path, ["--max_restart=2"], RESILIENT_TRAIN)
    assert proc.returncode == 0, proc.stderr[-3000:]
    # first life died with the injected fault's exit code
    assert f"pod failed (exit {FAULT_EXIT_CODE})" in proc.stderr
    logs = os.listdir(os.path.join(str(tmp_path), "log"))
    r1 = [l for l in logs if l.endswith(".r1")][0]
    out = open(os.path.join(str(tmp_path), "log", r1)).read()
    # run 1 committed step 1 (w=2), died committing step 3; run 2 resumed
    # from step 1 and trained steps 2..5 → w = 6
    assert "RESUMED_FROM 1" in out, out
    assert "FINAL 6.0" in out, out
    # the torn save was swept by the resumed run's own rotation
    ckpts = sorted(os.listdir(os.path.join(str(tmp_path), "ckpts")))
    assert all(not d.endswith(".tmp") for d in ckpts), ckpts
    info = latest_checkpoint(os.path.join(str(tmp_path), "ckpts"))
    assert info is not None and info.step == 5


def test_launcher_dumps_watchdog_report(tmp_path):
    """On worker death the launcher folds the comm-watchdog spill file into
    the worker log and its own stderr (post-mortem for hang restarts)."""
    proc = _run_launch(tmp_path, [], """
import os, sys
with open(os.environ["PADDLE_WD_REPORT_FILE"], "w") as f:
    f.write("[watchdog] task 1 'train_step/7' exceeded 500ms (9000ms elapsed)\\n")
sys.exit(3)
""")
    assert proc.returncode == 3
    assert "comm-watchdog post-mortem for worker 0" in proc.stderr
    assert "train_step/7" in proc.stderr
    log0 = open(os.path.join(str(tmp_path), "log", "workerlog.0.r0")).read()
    assert "comm-watchdog post-mortem" in log0


@pytest.mark.slow
def test_hang_recovery_end_to_end(tmp_path):
    """Forks real workers: a step wedges past the watchdog deadline, the
    spill thread's FatalError line trips the launcher's LogWatcher, the pod
    is torn down and respawned, and training resumes from the last commit."""
    proc = _run_launch(tmp_path, ["--max_restart=2"], """
import os, sys
import numpy as np
restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
if restart == 0:
    # 4th step (step index 3, after the step-1 commit) hangs for 120s
    os.environ["PADDLE_FAULT_INJECT"] = "trainer.before_step:sleep:120@4"
import paddle_tpu as paddle
from paddle_tpu.distributed.resilience import ResilientTrainer

w = paddle.to_tensor(np.zeros(4, np.float32))
def step_fn(i):
    w.set_value(paddle.to_tensor(w.numpy() + 1.0))
    return float(w.numpy()[0])
t = ResilientTrainer(step_fn, {"w": w}, os.environ["CKPT_DIR"], save_every=2,
                     async_save=False, step_timeout=1.0)
out = t.run(6)
print("RESUMED_FROM", out["resumed_from"], flush=True)
print("FINAL", float(w.numpy()[0]), flush=True)
""", timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "comm-watchdog post-mortem" in proc.stderr
    logs = os.listdir(os.path.join(str(tmp_path), "log"))
    r1 = [l for l in logs if l.endswith(".r1") and not l.endswith(".wd")][0]
    out = open(os.path.join(str(tmp_path), "log", r1)).read()
    assert "RESUMED_FROM 1" in out, out
    assert "FINAL 6.0" in out, out


# --------------------------------------------------------------------------- #
# store backoff + harness self-test
# --------------------------------------------------------------------------- #

def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_store_connect_backoff_rescues_late_bind():
    """Workers racing the master's bind at pod (re)start: the client retries
    with backoff until the server appears instead of dying on the first
    ECONNREFUSED."""
    import threading

    from paddle_tpu.distributed.store import TCPStore

    port = _free_port()
    server_holder = {}

    def bind_late():
        time.sleep(0.7)
        server_holder["srv"] = TCPStore("127.0.0.1", port, is_master=True)

    t = threading.Thread(target=bind_late, daemon=True)
    t.start()
    client = TCPStore("127.0.0.1", port, is_master=False, timeout=10)
    try:
        client.set("k", b"v")
        assert client.get("k") == b"v"
    finally:
        client.close()
        t.join()
        server_holder["srv"].close()


def test_store_connect_gives_up_after_deadline():
    from paddle_tpu.distributed.store import TCPStore

    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="attempt"):
        TCPStore("127.0.0.1", _free_port(), is_master=False, timeout=0.8)
    assert time.monotonic() - t0 < 10


def test_fault_inject_cli_self_test(tmp_path):
    """The harness verifies its own corruption round-trip
    (`tools/fault_inject.py --self-test`)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fault_inject.py"),
         "--self-test"],
        env={"PYTHONPATH": REPO, "PATH": os.environ.get("PATH", ""),
             "HOME": os.environ.get("HOME", "/root"),
             "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=180, cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "self-test passed" in proc.stdout
