"""nn.Layer / layers / functional tests with NumPy (and analytic) oracles
(reference test model: test/legacy_test op tests + imperative layer tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def check(t, ref, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(t.numpy(), np.float64), ref, rtol=rtol, atol=atol)


class TestLayerBase:
    def test_registration_and_traversal(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
        assert len(net.parameters()) == 4
        assert len(net.sublayers()) == 2

    def test_state_dict_roundtrip(self, tmp_path):
        net = nn.Linear(3, 3)
        sd = net.state_dict()
        assert set(sd.keys()) == {"weight", "bias"}
        paddle.save(sd, str(tmp_path / "m.pdparams"))
        net2 = nn.Linear(3, 3)
        missing, unexpected = net2.set_state_dict(paddle.load(str(tmp_path / "m.pdparams")))
        assert missing == [] and unexpected == []
        np.testing.assert_array_equal(net2.weight.numpy(), net.weight.numpy())

    def test_train_eval_mode(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        assert net.training
        net.eval()
        assert not net[1].training
        x = paddle.ones([4, 2])
        out1, out2 = net(x), net(x)
        np.testing.assert_array_equal(out1.numpy(), out2.numpy())  # no dropout in eval

    def test_forward_hooks(self):
        net = nn.Linear(2, 2)
        calls = []
        h = net.register_forward_post_hook(lambda l, i, o: calls.append("post"))
        net(paddle.ones([1, 2]))
        assert calls == ["post"]
        h.remove()
        net(paddle.ones([1, 2]))
        assert calls == ["post"]

    def test_layer_to_dtype(self):
        net = nn.Linear(2, 2)
        net.to(dtype="bfloat16")
        assert str(net.weight.dtype) == "bfloat16"


class TestCoreLayers:
    def setup_method(self, _):
        self.rng = np.random.RandomState(0)
        paddle.seed(0)

    def test_linear_matches_numpy(self):
        x = self.rng.rand(5, 3).astype(np.float32)
        layer = nn.Linear(3, 4)
        out = layer(paddle.to_tensor(x))
        ref = x @ layer.weight.numpy() + layer.bias.numpy()
        check(out, ref)

    def test_embedding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        ids = paddle.to_tensor([[1, 2], [0, 3]], dtype="int32")
        out = emb(ids)
        assert out.shape == [2, 2, 4]
        np.testing.assert_array_equal(out.numpy()[1, 0], np.zeros(4))  # padding row

    def test_conv2d_matches_torch_formula(self):
        import torch
        import torch.nn.functional as tF

        x = self.rng.rand(2, 3, 8, 8).astype(np.float32)
        conv = nn.Conv2D(3, 5, 3, stride=2, padding=1)
        out = conv(paddle.to_tensor(x))
        ref = tF.conv2d(
            torch.tensor(x), torch.tensor(conv.weight.numpy()),
            torch.tensor(conv.bias.numpy()), stride=2, padding=1,
        ).numpy()
        check(out, ref, rtol=1e-3, atol=1e-4)

    def test_conv2d_transpose(self):
        import torch
        import torch.nn.functional as tF

        x = self.rng.rand(2, 4, 5, 5).astype(np.float32)
        conv = nn.Conv2DTranspose(4, 3, 3, stride=2, padding=1, output_padding=1)
        out = conv(paddle.to_tensor(x))
        ref = tF.conv_transpose2d(
            torch.tensor(x), torch.tensor(conv.weight.numpy()),
            torch.tensor(conv.bias.numpy()), stride=2, padding=1, output_padding=1,
        ).numpy()
        assert out.shape == list(ref.shape)
        check(out, ref, rtol=1e-3, atol=1e-4)

    def test_depthwise_conv(self):
        import torch
        import torch.nn.functional as tF

        x = self.rng.rand(1, 4, 6, 6).astype(np.float32)
        conv = nn.Conv2D(4, 4, 3, groups=4, padding=1)
        out = conv(paddle.to_tensor(x))
        ref = tF.conv2d(torch.tensor(x), torch.tensor(conv.weight.numpy()),
                        torch.tensor(conv.bias.numpy()), padding=1, groups=4).numpy()
        check(out, ref, rtol=1e-3, atol=1e-4)

    def test_batchnorm_train_and_eval(self):
        bn = nn.BatchNorm2D(3)
        x = self.rng.rand(4, 3, 5, 5).astype(np.float32) * 2 + 1
        out = bn(paddle.to_tensor(x))
        # training: normalized by batch stats
        np.testing.assert_allclose(out.numpy().mean(axis=(0, 2, 3)), 0, atol=1e-5)
        np.testing.assert_allclose(out.numpy().std(axis=(0, 2, 3)), 1, atol=1e-2)
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), 0)
        bn.eval()
        out_eval = bn(paddle.to_tensor(x))
        assert out_eval.shape == [4, 3, 5, 5]

    def test_layernorm(self):
        ln = nn.LayerNorm(6)
        x = self.rng.rand(2, 4, 6).astype(np.float32)
        out = ln(paddle.to_tensor(x))
        m = x.mean(-1, keepdims=True)
        v = x.var(-1, keepdims=True)
        ref = (x - m) / np.sqrt(v + 1e-5) * ln.weight.numpy() + ln.bias.numpy()
        check(out, ref, rtol=1e-3, atol=1e-4)

    def test_rmsnorm(self):
        rms = nn.RMSNorm(8)
        x = self.rng.rand(3, 8).astype(np.float32)
        out = rms(paddle.to_tensor(x))
        ref = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6) * rms.weight.numpy()
        check(out, ref, rtol=1e-4)

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        x = self.rng.rand(2, 4, 3, 3).astype(np.float32)
        out = gn(paddle.to_tensor(x))
        xr = x.reshape(2, 2, 2, 3, 3)
        m = xr.mean(axis=(2, 3, 4), keepdims=True)
        v = xr.var(axis=(2, 3, 4), keepdims=True)
        ref = ((xr - m) / np.sqrt(v + 1e-5)).reshape(2, 4, 3, 3)
        check(out, ref, rtol=1e-3, atol=1e-4)

    def test_pooling(self):
        x = self.rng.rand(1, 2, 4, 4).astype(np.float32)
        mp = nn.MaxPool2D(2)(paddle.to_tensor(x))
        ap = nn.AvgPool2D(2)(paddle.to_tensor(x))
        ref_max = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
        ref_avg = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
        check(mp, ref_max)
        check(ap, ref_avg)
        gap = nn.AdaptiveAvgPool2D(1)(paddle.to_tensor(x))
        check(gap, x.mean(axis=(2, 3), keepdims=True))

    def test_activations(self):
        x = self.rng.randn(4, 5).astype(np.float32)
        t = paddle.to_tensor(x)
        check(F.relu(t), np.maximum(x, 0))
        check(F.gelu(t), 0.5 * x * (1 + np.vectorize(np.math.erf if hasattr(np, "math") else __import__("math").erf)(x / np.sqrt(2))), rtol=1e-3, atol=1e-4)
        check(F.silu(t), x / (1 + np.exp(-x)), rtol=1e-4)
        check(F.leaky_relu(t, 0.1), np.where(x > 0, x, 0.1 * x))
        sm = F.softmax(t, axis=-1).numpy()
        np.testing.assert_allclose(sm.sum(-1), 1, rtol=1e-5)

    def test_dropout_train_scales(self):
        paddle.seed(7)
        x = paddle.ones([1000])
        out = F.dropout(x, p=0.5, training=True)
        kept = out.numpy()[out.numpy() != 0]
        np.testing.assert_allclose(kept, 2.0, rtol=1e-6)  # upscale_in_train
        assert 300 < (out.numpy() == 0).sum() < 700


class TestLosses:
    def setup_method(self, _):
        self.rng = np.random.RandomState(1)

    def test_cross_entropy(self):
        logits = self.rng.randn(4, 5).astype(np.float32)
        labels = np.array([0, 2, 4, 1])
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels, dtype="int32"))
        # numpy oracle
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        check(loss, ref, rtol=1e-4)

    def test_cross_entropy_ignore_index(self):
        logits = self.rng.randn(4, 5).astype(np.float32)
        labels = np.array([0, -100, 4, -100])
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels, dtype="int32"), ignore_index=-100)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[[0, 2], [0, 4]]).mean()
        check(loss, ref, rtol=1e-4)

    def test_soft_label_and_smoothing(self):
        logits = self.rng.randn(3, 4).astype(np.float32)
        soft = np.full((3, 4), 0.25, np.float32)
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(soft), soft_label=True)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        logp = np.log(e / e.sum(-1, keepdims=True))
        check(loss, -(soft * logp).sum(-1).mean(), rtol=1e-4)

    def test_mse_l1(self):
        a = self.rng.rand(3, 4).astype(np.float32)
        b = self.rng.rand(3, 4).astype(np.float32)
        check(F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b)), ((a - b) ** 2).mean(), rtol=1e-5)
        check(F.l1_loss(paddle.to_tensor(a), paddle.to_tensor(b)), np.abs(a - b).mean(), rtol=1e-5)

    def test_bce_with_logits(self):
        z = self.rng.randn(6).astype(np.float32)
        y = (self.rng.rand(6) > 0.5).astype(np.float32)
        loss = F.binary_cross_entropy_with_logits(paddle.to_tensor(z), paddle.to_tensor(y))
        p = 1 / (1 + np.exp(-z))
        ref = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        check(loss, ref, rtol=1e-4)

    def test_grad_through_loss(self):
        layer = nn.Linear(3, 2)
        x = paddle.to_tensor(self.rng.rand(4, 3).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 0, 1]), dtype="int32")
        loss = F.cross_entropy(layer(x), y)
        loss.backward()
        assert layer.weight.grad is not None
        assert layer.weight.grad.shape == [3, 2]


class TestAttention:
    def test_sdpa_matches_reference(self):
        rng = np.random.RandomState(2)
        q = rng.rand(2, 5, 3, 8).astype(np.float32)  # [B,S,H,D]
        k = rng.rand(2, 5, 3, 8).astype(np.float32)
        v = rng.rand(2, 5, 3, 8).astype(np.float32)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v)
        )
        # numpy oracle
        qh = q.transpose(0, 2, 1, 3)
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        s = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(8)
        e = np.exp(s - s.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = (p @ vh).transpose(0, 2, 1, 3)
        check(out, ref, rtol=1e-3, atol=1e-4)

    def test_causal_masking(self):
        rng = np.random.RandomState(3)
        q = rng.rand(1, 4, 1, 4).astype(np.float32)
        k = rng.rand(1, 4, 1, 4).astype(np.float32)
        v = rng.rand(1, 4, 1, 4).astype(np.float32)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v), is_causal=True
        )
        # row 0 attends only to col 0 -> equals v[0]
        np.testing.assert_allclose(out.numpy()[0, 0, 0], v[0, 0, 0], rtol=1e-4)

    def test_multihead_attention_layer(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 6, 16])
        out = mha(x)
        assert out.shape == [2, 6, 16]

    def test_transformer_encoder(self):
        enc_layer = nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0)
        enc = nn.TransformerEncoder(enc_layer, 2)
        out = enc(paddle.randn([2, 5, 16]))
        assert out.shape == [2, 5, 16]

    def test_flashmask_causal_equiv(self):
        """flashmask with trivial indices == plain causal attention."""
        rng = np.random.RandomState(4)
        B, S, H, D = 1, 6, 2, 4
        q = paddle.to_tensor(rng.rand(B, S, H, D).astype(np.float32))
        k = paddle.to_tensor(rng.rand(B, S, H, D).astype(np.float32))
        v = paddle.to_tensor(rng.rand(B, S, H, D).astype(np.float32))
        # start index S for every column: nothing extra masked beyond causal
        idx = paddle.full([B, 1, S, 1], S, dtype="int32")
        out_fm = F.flashmask_attention(q, k, v, idx, causal=True)
        out_ref = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        check(out_fm, out_ref.numpy(), rtol=1e-4, atol=1e-5)


class TestOptimizers:
    def _train(self, opt_cls, **kw):
        paddle.seed(0)
        rng = np.random.RandomState(0)
        net = nn.Linear(4, 1)
        X = paddle.to_tensor(rng.rand(32, 4).astype(np.float32))
        w_true = np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
        y = paddle.to_tensor(rng.rand(32, 4).astype(np.float32) @ w_true)
        X = paddle.to_tensor(rng.rand(32, 4).astype(np.float32))
        y = paddle.matmul(X, paddle.to_tensor(w_true))
        opt = opt_cls(parameters=net.parameters(), **kw)
        first = None
        for i in range(60):
            loss = F.mse_loss(net(X), y)
            if first is None:
                first = float(loss.numpy())
            loss.backward()
            opt.step()
            opt.clear_grad()
        return first, float(loss.numpy())

    @pytest.mark.parametrize("cls,kw", [
        ("SGD", {"learning_rate": 0.1}),
        ("Momentum", {"learning_rate": 0.1, "momentum": 0.9}),
        ("Adam", {"learning_rate": 0.05}),
        ("AdamW", {"learning_rate": 0.05, "weight_decay": 0.01}),
        ("RMSProp", {"learning_rate": 0.01}),
        ("Lamb", {"learning_rate": 0.1}),
        ("NAdam", {"learning_rate": 0.05}),
        ("RAdam", {"learning_rate": 0.05}),
        ("Rprop", {"learning_rate": 0.001}),
        ("ASGD", {"learning_rate": 0.05, "batch_num": 2}),
    ])
    def test_optimizers_reduce_loss(self, cls, kw):
        first, last = self._train(getattr(paddle.optimizer, cls), **kw)
        assert last < first * 0.2, f"{cls}: {first} -> {last}"

    def test_adam_matches_reference_formula(self):
        p0 = np.array([1.0, 2.0], np.float32)
        g = np.array([0.1, -0.2], np.float32)
        p = paddle.to_tensor(p0.copy())
        p.stop_gradient = False
        param = paddle.framework.core.Parameter(p._value)
        param.grad = paddle.to_tensor(g)
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[param])
        opt.step()
        m = 0.1 * g
        v = 0.001 * g * g
        mh = m / (1 - 0.9)
        vh = v / (1 - 0.999)
        ref = p0 - 0.1 * mh / (np.sqrt(vh) + 1e-8)
        np.testing.assert_allclose(param.numpy(), ref, rtol=1e-5)

    def test_grad_clip_global_norm(self):
        net = nn.Linear(2, 2)
        clip = nn.ClipGradByGlobalNorm(0.1)
        opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=net.parameters(), grad_clip=clip)
        loss = (net(paddle.ones([1, 2])) * 100).sum()
        loss.backward()
        # apply clip manually to inspect
        pg = [(p, p.grad) for p in net.parameters() if p.grad is not None]
        clipped = clip(pg)
        total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in clipped))
        assert total <= 0.1 + 1e-5

    def test_lr_scheduler(self):
        sched = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[paddle.framework.core.Parameter(paddle.zeros([1])._value)])
        lrs = []
        for _ in range(5):
            lrs.append(opt.get_lr())
            sched.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)

    def test_cosine_warmup(self):
        cos = paddle.optimizer.lr.CosineAnnealingDecay(0.1, T_max=10)
        warm = paddle.optimizer.lr.LinearWarmup(cos, warmup_steps=5, start_lr=0.0, end_lr=0.1)
        vals = []
        for _ in range(8):
            vals.append(warm())
            warm.step()
        assert vals[0] == 0.0 and abs(vals[4] - 0.08) < 1e-6
        assert vals[6] < 0.1  # cosine decay began

    def test_lbfgs_solves_quadratic(self):
        """LBFGS (closure-based, strong-Wolfe) drives a linear least-squares
        problem to ~0 in a few outer steps (reference optimizer/lbfgs.py)."""
        paddle.seed(0)
        rng = np.random.default_rng(0)
        X = paddle.to_tensor(rng.normal(size=(32, 4)).astype(np.float32))
        W = rng.normal(size=(4, 1)).astype(np.float32)
        Y = paddle.to_tensor((X.numpy() @ W).astype(np.float32))
        m = paddle.nn.Linear(4, 1)
        mse = paddle.nn.MSELoss()
        o = paddle.optimizer.LBFGS(learning_rate=1.0, max_iter=10,
                                   line_search_fn="strong_wolfe",
                                   parameters=m.parameters())

        def closure():
            o.clear_grad()
            loss = mse(m(X), Y)
            loss.backward()
            return loss

        l0 = float(closure().numpy())
        for _ in range(3):
            loss = o.step(closure)
        assert float(loss.numpy()) < l0 * 1e-3

    def test_optimizer_state_dict(self):
        net = nn.Linear(2, 2)
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=net.parameters())
        loss = net(paddle.ones([1, 2])).sum()
        loss.backward()
        opt.step()
        sd = opt.state_dict()
        assert sd["_step_count"] == 1
        opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=net.parameters())
        opt2.set_state_dict(sd)
        assert opt2._step_count == 1


class TestAmp:
    def test_autocast_casts_matmul(self):
        with paddle.amp.auto_cast(dtype="bfloat16"):
            a = paddle.ones([4, 4])
            out = paddle.matmul(a, a)
        assert str(out.dtype) == "bfloat16"

    def test_autocast_keeps_blacklist_f32(self):
        with paddle.amp.auto_cast(dtype="bfloat16"):
            x = paddle.ones([4], dtype="bfloat16")
            out = paddle.nn.functional.softmax(x)
        assert out.dtype == np.float32

    def test_grad_scaler_noop_path(self):
        net = nn.Linear(2, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=1.0)
        loss = net(paddle.ones([3, 2])).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        assert net.weight.grad is None or True  # step ran without error

    def test_grad_scaler_skips_on_inf(self):
        net = nn.Linear(2, 1)
        w0 = net.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        loss = net(paddle.ones([1, 2])).sum()
        scaler.scale(loss).backward()
        net.weight.grad._value = net.weight.grad._value.at[0, 0].set(np.inf)
        scaler.step(opt)
        np.testing.assert_array_equal(net.weight.numpy(), w0)  # skipped
        assert scaler._scale < 4.0  # backed off

    def test_grad_scaler_no_double_unscale(self):
        # unscale_/clip/step pattern: step() must not divide grads by the
        # scale a second time (reference grad_scaler.py:354-373).
        net = nn.Linear(2, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        loss = net(paddle.ones([1, 2])).sum()
        scaler.scale(loss).backward()
        scaler.unscale_(opt)
        g_after_unscale = net.weight.grad.numpy().copy()
        scaler.step(opt)  # must NOT unscale again
        np.testing.assert_allclose(
            net.weight.grad.numpy(), g_after_unscale, rtol=1e-6)
        scaler.update()
        # a second explicit unscale_ before the next update() raises
        scaler.scale(net(paddle.ones([1, 2])).sum()).backward()
        scaler.unscale_(opt)
        with pytest.raises(RuntimeError):
            scaler.unscale_(opt)

    def test_decorate_o2(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.LayerNorm(2))
        paddle.amp.decorate(net, level="O2", dtype="bfloat16")
        assert str(net[0].weight.dtype) == "bfloat16"
        assert net[1].weight.dtype == np.float32  # norm stays f32
