"""Direct correctness coverage for ops/pallas/decode_attention.py — the
paged/dense decode kernels vs a numpy oracle under interpret mode (the
serving engines exercise them end-to-end; these pin the kernel contract
itself: GQA head groups, partially-filled final pages, -1 unused
block-table entries, and the `l == 0` zero-length-row guard in _finish)."""

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.ops.pallas.decode_attention import (
    dense_decode_attention,
    paged_decode_attention,
    paged_kv_write,
)


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret_unless_hw):
    pass


def _ref_attend(q_bh, keys, vals, L, scale):
    """One (row, head): softmax(q·K[:L]) @ V[:L] in f64-ish numpy."""
    if L == 0:
        return np.zeros_like(q_bh)
    s = keys[:L] @ q_bh * scale
    p = np.exp(s - s.max())
    p /= p.sum()
    return p @ vals[:L]


def _ref_paged(q, kc, vc, tables, lengths):
    B, H, D = q.shape
    _, Hkv, ps, _ = kc.shape
    P = tables.shape[1]
    S = P * ps
    g = H // Hkv
    kc, vc = np.asarray(kc), np.asarray(vc)
    out = np.zeros((B, H, D), np.float32)
    for b in range(B):
        keys = np.zeros((S, Hkv, D), np.float32)
        vals = np.zeros_like(keys)
        for j in range(P):
            t = int(tables[b, j])
            if t >= 0:
                keys[j * ps:(j + 1) * ps] = kc[t].transpose(1, 0, 2)
                vals[j * ps:(j + 1) * ps] = vc[t].transpose(1, 0, 2)
        for h in range(H):
            out[b, h] = _ref_attend(np.asarray(q)[b, h], keys[:, h // g],
                                    vals[:, h // g], int(lengths[b]),
                                    D ** -0.5)
    return out


def _make_case(B, H, Hkv, D, ps, P, lengths, seed=0, n_pages=None):
    """Random paged cache + per-row block tables covering `lengths` tokens;
    entries past each row's last page are -1."""
    rng = np.random.default_rng(seed)
    need = [-(-L // ps) if L else 0 for L in lengths]
    if n_pages is None:
        n_pages = 1 + sum(need)  # page 0 = null
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((n_pages, Hkv, ps, D)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((n_pages, Hkv, ps, D)), jnp.float32)
    tables = np.full((B, P), -1, np.int32)
    nxt = 1
    for b, m in enumerate(need):
        for j in range(m):
            tables[b, j] = nxt
            nxt += 1
    return q, kc, vc, jnp.asarray(tables), jnp.asarray(
        np.asarray(lengths, np.int32))


CASES = [
    # B, H, Hkv, D, ps, P, lengths
    (2, 4, 4, 32, 16, 4, [64, 32]),          # MHA, full pages
    (2, 4, 2, 32, 16, 4, [48, 16]),          # GQA head groups
    (3, 4, 1, 16, 8, 8, [13, 27, 5]),        # MQA, partial final pages
    (2, 2, 2, 16, 16, 2, [17, 31]),          # partial fill + -1 tail entries
]


@pytest.mark.parametrize("B,H,Hkv,D,ps,P,lengths", CASES)
def test_paged_decode_matches_reference(B, H, Hkv, D, ps, P, lengths):
    q, kc, vc, tables, lens = _make_case(B, H, Hkv, D, ps, P, lengths)
    out = paged_decode_attention(q, kc, vc, tables, lens)
    ref = _ref_paged(q, kc, vc, np.asarray(tables), np.asarray(lens))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=2e-5)


def test_zero_length_row_outputs_zeros():
    """The `l == 0` guard in _decode_kernel._finish: a row with no valid
    tokens (every page skipped) must return zeros, not NaN from 0/0."""
    q, kc, vc, tables, lens = _make_case(3, 4, 2, 16, 8, 4, [16, 0, 9])
    out = np.asarray(paged_decode_attention(q, kc, vc, tables, lens))
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))
    ref = _ref_paged(q, kc, vc, np.asarray(tables), np.asarray(lens))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=2e-5)


def test_unused_table_entries_are_skipped():
    """-1 entries (and whatever stale page ids would sit behind them) must
    not contribute: truncating a row's table to -1 changes nothing vs a
    shorter reference, even though the physical pages still hold data."""
    q, kc, vc, tables, lens = _make_case(1, 2, 2, 16, 8, 4, [16])
    tables = np.asarray(tables).copy()
    # leave garbage pages allocated beyond the valid range; table says -1
    out = paged_decode_attention(q, kc, vc, jnp.asarray(tables), lens)
    ref = _ref_paged(q, kc, vc, tables, np.asarray(lens))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=2e-5)


def test_dense_decode_matches_reference():
    rng = np.random.default_rng(3)
    B, H, Hkv, D, S = 2, 4, 2, 32, 64
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    lens = np.asarray([37, 64], np.int32)
    out = dense_decode_attention(q, kc, vc, jnp.asarray(lens))
    g = H // Hkv
    ref = np.zeros((B, H, D), np.float32)
    for b in range(B):
        for h in range(H):
            ref[b, h] = _ref_attend(
                np.asarray(q)[b, h],
                np.asarray(kc)[b, h // g], np.asarray(vc)[b, h // g],
                int(lens[b]), D ** -0.5)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=2e-5)


class TestPagedKvWrite:
    def test_write_lands_at_next_slot(self):
        B, Hkv, D, ps, P, n_pages = 2, 2, 8, 4, 4, 6
        kc = jnp.zeros((n_pages, Hkv, ps, D), jnp.float32)
        tables = np.full((B, P), -1, np.int32)
        tables[0, :2] = [1, 2]
        tables[1, :1] = [3]
        lengths = np.asarray([5, 2], np.int32)  # slots (page 2, 1), (page 3, 2)
        new = jnp.asarray(
            np.arange(B * Hkv * D, dtype=np.float32).reshape(B, Hkv, D) + 1.0)
        out = np.array(paged_kv_write(kc, new, jnp.asarray(tables),
                                      jnp.asarray(lengths)))
        np.testing.assert_array_equal(out[2, :, 1], np.asarray(new)[0])
        np.testing.assert_array_equal(out[3, :, 2], np.asarray(new)[1])
        # nothing else touched
        out[2, :, 1] = 0
        out[3, :, 2] = 0
        assert not out.any()

    def test_parked_rows_hit_null_page(self):
        """Rows whose table entry is -1 (inactive program rows) write page 0
        — the reserved null page — and corrupt nothing allocatable."""
        B, Hkv, D, ps, P, n_pages = 2, 1, 4, 4, 2, 4
        kc = jnp.zeros((n_pages, Hkv, ps, D), jnp.float32)
        tables = np.full((B, P), -1, np.int32)
        tables[0, 0] = 1
        lengths = np.asarray([1, 0], np.int32)
        new = jnp.ones((B, Hkv, D), jnp.float32)
        out = np.asarray(paged_kv_write(kc, new, jnp.asarray(tables),
                                        jnp.asarray(lengths)))
        assert out[1, :, 1].any()          # live row wrote its slot
        assert out[0, :, 0].any()          # parked row landed on null page
        assert not out[2:].any()           # no allocatable page touched
