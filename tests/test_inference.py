"""Inference/decode path tests: generation, MMHA, paged block attention,
FusedMultiTransformer, jit.save program export, inference.Predictor.

Oracle: dense attention / full-sequence forward (the reference's OpTest
pattern — kernel result vs straightforward computation)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as IF
from paddle_tpu.incubate.nn.layer import FusedMultiTransformer
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def _softmax(x):
    return np.asarray(jax.nn.softmax(jnp.asarray(x), -1))


class TestGenerate:
    def _model(self):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=64)
        return GPTForCausalLM(cfg)

    def test_greedy_cache_matches_nocache(self):
        m = self._model()
        ids = np.random.RandomState(0).randint(0, 128, (2, 8)).astype(np.int32)
        a = m.generate(ids, max_new_tokens=6, temperature=0.0).numpy()
        b = m.generate(ids, max_new_tokens=6, temperature=0.0,
                       use_cache=False).numpy()
        np.testing.assert_array_equal(a, b)
        assert a.shape == (2, 14)

    def test_llama_style_gqa_rope(self):
        paddle.seed(1)
        cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
                        num_kv_heads=2, norm_type="rmsnorm", activation="swiglu",
                        use_rope=True, max_position_embeddings=64,
                        tie_word_embeddings=False)
        m = GPTForCausalLM(cfg)
        ids = np.random.RandomState(1).randint(0, 96, (2, 6)).astype(np.int32)
        a = m.generate(ids, max_new_tokens=5, temperature=0.0).numpy()
        b = m.generate(ids, max_new_tokens=5, temperature=0.0,
                       use_cache=False).numpy()
        np.testing.assert_array_equal(a, b)

    def test_sampling_reproducible_and_eos_stop(self):
        m = self._model()
        ids = np.random.RandomState(2).randint(0, 128, (2, 4)).astype(np.int32)
        a = m.generate(ids, max_new_tokens=5, temperature=0.9, top_k=20,
                       top_p=0.9, seed=7).numpy()
        b = m.generate(ids, max_new_tokens=5, temperature=0.9, top_k=20,
                       top_p=0.9, seed=7).numpy()
        np.testing.assert_array_equal(a, b)
        # eos stop: pick the greedy first token as "eos" → generation stops
        g = m.generate(ids, max_new_tokens=8, temperature=0.0).numpy()
        eos = int(g[0, 4])
        e = m.generate(ids, max_new_tokens=8, temperature=0.0,
                       eos_token_id=eos).numpy()
        assert e.shape[1] <= g.shape[1]


class TestMMHA:
    def test_decode_steps_match_dense(self):
        rng = np.random.RandomState(0)
        B, H, D, Smax = 2, 2, 8, 16
        cache = np.zeros((2, B, H, Smax, D), np.float32)
        qs, ks, vs, outs = [], [], [], []
        for t in range(3):
            x = rng.randn(B, 3 * H * D).astype(np.float32)
            s = x.reshape(B, 3, H, D)
            qs.append(s[:, 0]); ks.append(s[:, 1]); vs.append(s[:, 2])
            out, cache_t = IF.masked_multihead_attention(
                paddle.to_tensor(x), paddle.to_tensor(cache),
                sequence_lengths=paddle.to_tensor(np.full((B,), t, np.int32)))
            cache = cache_t.numpy()
            outs.append(out.numpy())
        K = np.stack(ks, 2); V = np.stack(vs, 2)
        logits = np.einsum("bhd,bhtd->bht", qs[2], K) / np.sqrt(D)
        ref = np.einsum("bht,bhtd->bhd", _softmax(logits), V).reshape(B, H * D)
        np.testing.assert_allclose(outs[2], ref, rtol=1e-5, atol=1e-5)

    def test_quant_path_raises(self):
        with pytest.raises(NotImplementedError):
            IF.masked_multihead_attention(
                paddle.to_tensor(np.zeros((1, 48), np.float32)),
                paddle.to_tensor(np.zeros((2, 1, 2, 4, 8), np.float32)),
                out_scale=0.5)


class TestBlockAttention:
    def test_prefill_and_decode_match_dense(self):
        rng = np.random.RandomState(0)
        B, Hq, Hkv, D, bs = 2, 4, 2, 8, 4
        kc = np.zeros((8, Hkv, bs, D), np.float32)
        vc = np.zeros((8, Hkv, bs, D), np.float32)
        tables = np.array([[0, 1, -1, -1], [2, 3, -1, -1]], np.int32)
        S = 5
        qkv = rng.randn(B, S, (Hq + 2 * Hkv) * D).astype(np.float32)
        out, _, kc2, vc2 = IF.block_multihead_attention(
            paddle.to_tensor(qkv), paddle.to_tensor(kc), paddle.to_tensor(vc),
            paddle.to_tensor(np.full((B,), S, np.int32)),
            paddle.to_tensor(np.zeros((B,), np.int32)),
            paddle.to_tensor(np.full((B,), S, np.int32)),
            block_tables=paddle.to_tensor(tables), block_size=bs)
        q3 = qkv.reshape(B, S, Hq + 2 * Hkv, D)
        q, k, v = q3[:, :, :Hq], q3[:, :, Hq:Hq + Hkv], q3[:, :, Hq + Hkv:]
        kr, vr = np.repeat(k, 2, 2), np.repeat(v, 2, 2)
        logits = np.einsum("bshd,bthd->bhst", q, kr) / np.sqrt(D)
        logits = np.where(np.tril(np.ones((S, S), bool))[None, None], logits, -1e30)
        ref = np.einsum("bhst,bthd->bshd", _softmax(logits), vr).reshape(B, S, Hq * D)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)

        qkv_d = rng.randn(B, 1, (Hq + 2 * Hkv) * D).astype(np.float32)
        out_d, _, _, _ = IF.block_multihead_attention(
            paddle.to_tensor(qkv_d), kc2, vc2,
            paddle.to_tensor(np.zeros((B,), np.int32)),
            paddle.to_tensor(np.full((B,), S, np.int32)),
            paddle.to_tensor(np.ones((B,), np.int32)),
            block_tables=paddle.to_tensor(tables), block_size=bs)
        qd3 = qkv_d.reshape(B, 1, Hq + 2 * Hkv, D)
        qd = qd3[:, :, :Hq]
        k_all = np.concatenate([k, qd3[:, :, Hq:Hq + Hkv]], 1)
        v_all = np.concatenate([v, qd3[:, :, Hq + Hkv:]], 1)
        kr, vr = np.repeat(k_all, 2, 2), np.repeat(v_all, 2, 2)
        logits = np.einsum("bshd,bthd->bhst", qd, kr) / np.sqrt(D)
        ref_d = np.einsum("bhst,bthd->bshd", _softmax(logits), vr).reshape(B, 1, Hq * D)
        np.testing.assert_allclose(out_d.numpy(), ref_d, rtol=1e-5, atol=1e-5)

    def test_int8_kv_cache_quant(self):
        """int8 cache path: quantize-on-write, dequantize-on-read tracks the
        fp32 cache within quantization error (reference CacheKVInt8)."""
        rng = np.random.RandomState(3)
        B, Hq, Hkv, D, bs = 2, 4, 2, 8, 4
        S = 5
        qkv = rng.randn(B, S, (Hq + 2 * Hkv) * D).astype(np.float32)
        tables = np.array([[0, 1, -1, -1], [2, 3, -1, -1]], np.int32)
        args = dict(block_tables=paddle.to_tensor(tables), block_size=bs)
        enc = paddle.to_tensor(np.full((B,), S, np.int32))
        dec = paddle.to_tensor(np.zeros((B,), np.int32))
        this = paddle.to_tensor(np.full((B,), S, np.int32))

        # fp32 reference
        kc = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.float32))
        vc = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.float32))
        ref, _, _, _ = IF.block_multihead_attention(
            paddle.to_tensor(qkv), kc, vc, enc, dec, this, **args)

        # int8 cache: per-kv-head scales sized to the data range
        amax = np.abs(qkv).max()
        qs = np.full((Hkv,), 127.0 / amax, np.float32)
        dqs = (1.0 / qs).astype(np.float32)
        kc8 = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.int8))
        vc8 = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.int8))
        out, _, kc8b, _ = IF.block_multihead_attention(
            paddle.to_tensor(qkv), kc8, vc8, enc, dec, this,
            cache_k_quant_scales=paddle.to_tensor(qs),
            cache_v_quant_scales=paddle.to_tensor(qs),
            cache_k_dequant_scales=paddle.to_tensor(dqs),
            cache_v_dequant_scales=paddle.to_tensor(dqs), **args)
        assert str(kc8b.numpy().dtype) == "int8"
        assert np.abs(kc8b.numpy()).max() > 0  # writes actually quantized
        err = np.abs(out.numpy() - ref.numpy()).max()
        assert err < 0.05 * np.abs(ref.numpy()).max() + 1e-2, err

    def test_rope_fused_prefill_matches_manual(self):
        """rope_emb fuses rotary into q/k before the cache write
        (reference: fused_multi_transformer_op.cu.h:3097 decode loop)."""
        rng = np.random.RandomState(5)
        B, Hq, Hkv, D, bs = 2, 2, 2, 8, 4
        S, max_seq = 4, 16
        qkv = rng.randn(B, S, (Hq + 2 * Hkv) * D).astype(np.float32)
        tables = np.array([[0, 1, -1, -1], [2, 3, -1, -1]], np.int32)
        inv = 1.0 / (10000.0 ** (np.arange(0, D, 2) / D))
        ang = np.arange(max_seq)[:, None] * inv[None, :]     # [max_seq, D/2]
        rope = np.stack([np.cos(ang), np.sin(ang)])[:, None, :, None, :]
        rope = np.broadcast_to(rope, (2, B, max_seq, 1, D // 2)).astype(np.float32)
        kc = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.float32))
        vc = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.float32))
        out, _, kc2, _ = IF.block_multihead_attention(
            paddle.to_tensor(qkv), kc, vc,
            paddle.to_tensor(np.full((B,), S, np.int32)),
            paddle.to_tensor(np.zeros((B,), np.int32)),
            paddle.to_tensor(np.full((B,), S, np.int32)),
            block_tables=paddle.to_tensor(tables), block_size=bs,
            rope_emb=paddle.to_tensor(rope), use_neox_style=True)

        # manual: rotate q/k (neox half-split), then causal attention
        q3 = qkv.reshape(B, S, Hq + 2 * Hkv, D)
        q, k, v = q3[:, :, :Hq], q3[:, :, Hq:Hq + Hkv], q3[:, :, Hq + Hkv:]

        def rot(x):
            c = np.cos(ang)[None, :S, None, :]
            s_ = np.sin(ang)[None, :S, None, :]
            x1, x2 = x[..., :D // 2], x[..., D // 2:]
            return np.concatenate([x1 * c - x2 * s_, x2 * c + x1 * s_], -1)

        qr, kr_ = rot(q), rot(k)
        logits = np.einsum("bshd,bthd->bhst", qr, kr_) / np.sqrt(D)
        logits = np.where(np.tril(np.ones((S, S), bool))[None, None],
                          logits, -1e30)
        ref = np.einsum("bhst,bthd->bshd", _softmax(logits), v)
        np.testing.assert_allclose(
            out.numpy().reshape(B, S, Hq, D), ref, rtol=1e-5, atol=1e-5)
        # the CACHE must hold rotated keys (write-after-rope, like the fused
        # kernel) — page 0 slot 0 is batch 0 position 0
        np.testing.assert_allclose(kc2.numpy()[0, :, 0],
                                   kr_[0, 0], rtol=1e-5, atol=1e-5)

    def test_pre_cache_prefix_attended(self):
        """pre_key/value_cache: a shared prefix every query attends before
        the paged cache (reference pre_cache path)."""
        rng = np.random.RandomState(7)
        B, Hq, Hkv, D, bs, P = 2, 2, 2, 8, 4, 3
        S = 4
        qkv = rng.randn(B, S, (Hq + 2 * Hkv) * D).astype(np.float32)
        pre_k = rng.randn(B, Hkv, P, D).astype(np.float32)
        pre_v = rng.randn(B, Hkv, P, D).astype(np.float32)
        tables = np.array([[0, 1, -1, -1], [2, 3, -1, -1]], np.int32)
        kc = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.float32))
        vc = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.float32))
        out, _, _, _ = IF.block_multihead_attention(
            paddle.to_tensor(qkv), kc, vc,
            paddle.to_tensor(np.full((B,), S, np.int32)),
            paddle.to_tensor(np.zeros((B,), np.int32)),
            paddle.to_tensor(np.full((B,), S, np.int32)),
            block_tables=paddle.to_tensor(tables), block_size=bs,
            pre_key_cache=paddle.to_tensor(pre_k),
            pre_value_cache=paddle.to_tensor(pre_v))
        q3 = qkv.reshape(B, S, Hq + 2 * Hkv, D)
        q, k, v = q3[:, :, :Hq], q3[:, :, Hq:Hq + Hkv], q3[:, :, Hq + Hkv:]
        k_all = np.concatenate([np.moveaxis(pre_k, 1, 2), k], 1)  # [B,P+S,..]
        v_all = np.concatenate([np.moveaxis(pre_v, 1, 2), v], 1)
        logits = np.einsum("bshd,bthd->bhst", q, k_all) / np.sqrt(D)
        # prefix always visible; cache part causal
        keep = np.concatenate(
            [np.ones((S, P), bool), np.tril(np.ones((S, S), bool))], -1)
        logits = np.where(keep[None, None], logits, -1e30)
        ref = np.einsum("bhst,bthd->bshd", _softmax(logits), v_all)
        np.testing.assert_allclose(
            out.numpy().reshape(B, S, Hq, D), ref, rtol=1e-5, atol=1e-5)

    def test_pre_cache_decode_step(self):
        """Decode (S=1) with a prefix cache: new token attends prefix + all
        cached tokens + itself."""
        rng = np.random.RandomState(9)
        B, Hq, Hkv, D, bs, P = 1, 2, 2, 8, 4, 2
        S = 3
        tables = np.array([[0, 1, -1, -1]], np.int32)
        pre_k = rng.randn(B, Hkv, P, D).astype(np.float32)
        pre_v = rng.randn(B, Hkv, P, D).astype(np.float32)
        qkv = rng.randn(B, S, (Hq + 2 * Hkv) * D).astype(np.float32)
        kc = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.float32))
        vc = paddle.to_tensor(np.zeros((8, Hkv, bs, D), np.float32))
        _, _, kc2, vc2 = IF.block_multihead_attention(
            paddle.to_tensor(qkv), kc, vc,
            paddle.to_tensor(np.full((B,), S, np.int32)),
            paddle.to_tensor(np.zeros((B,), np.int32)),
            paddle.to_tensor(np.full((B,), S, np.int32)),
            block_tables=paddle.to_tensor(tables), block_size=bs,
            pre_key_cache=paddle.to_tensor(pre_k),
            pre_value_cache=paddle.to_tensor(pre_v))
        qkv_d = rng.randn(B, 1, (Hq + 2 * Hkv) * D).astype(np.float32)
        out_d, _, _, _ = IF.block_multihead_attention(
            paddle.to_tensor(qkv_d), kc2, vc2,
            paddle.to_tensor(np.zeros((B,), np.int32)),
            paddle.to_tensor(np.full((B,), S, np.int32)),
            paddle.to_tensor(np.ones((B,), np.int32)),
            block_tables=paddle.to_tensor(tables), block_size=bs,
            pre_key_cache=paddle.to_tensor(pre_k),
            pre_value_cache=paddle.to_tensor(pre_v))
        q3 = qkv.reshape(B, S, Hq + 2 * Hkv, D)
        qd3 = qkv_d.reshape(B, 1, Hq + 2 * Hkv, D)
        qd = qd3[:, :, :Hq]
        k_all = np.concatenate(
            [np.moveaxis(pre_k, 1, 2), q3[:, :, Hq:Hq + Hkv],
             qd3[:, :, Hq:Hq + Hkv]], 1)
        v_all = np.concatenate(
            [np.moveaxis(pre_v, 1, 2), q3[:, :, Hq + Hkv:],
             qd3[:, :, Hq + Hkv:]], 1)
        logits = np.einsum("bshd,bthd->bhst", qd, k_all) / np.sqrt(D)
        ref_d = np.einsum("bhst,bthd->bshd", _softmax(logits), v_all)
        np.testing.assert_allclose(
            out_d.numpy().reshape(B, 1, Hq, D), ref_d, rtol=1e-5, atol=1e-5)

    def test_blha_get_max_len(self):
        e, d = IF.blha_get_max_len(
            paddle.to_tensor(np.array([3, 9, 1], np.int32)),
            paddle.to_tensor(np.array([5, 2, 8], np.int32)))
        assert int(e.numpy()[0]) == 9 and int(d.numpy()[0]) == 8


class TestVarlenAttention:
    def test_masks_padding(self):
        rng = np.random.RandomState(1)
        B, H, S, D = 2, 2, 8, 4
        q = rng.randn(B, H, S, D).astype(np.float32)
        k = rng.randn(B, H, S, D).astype(np.float32)
        v = rng.randn(B, H, S, D).astype(np.float32)
        lens = np.array([8, 5], np.int32)
        out = IF.variable_length_memory_efficient_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(lens), paddle.to_tensor(lens)).numpy()
        # row 1 must ignore keys >= 5
        logits = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(D)
        logits[1, :, :, 5:] = -1e30
        ref = np.einsum("bhst,bhtd->bhsd", _softmax(logits), v)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


class TestFusedMultiTransformer:
    def test_cached_decode_matches_full(self):
        rng = np.random.RandomState(0)
        paddle.seed(3)
        B = 2
        fmt = FusedMultiTransformer(embed_dim=16, num_heads=2,
                                    dim_feedforward=32, num_layers=2)
        for p_ in fmt.parameters():
            p_.set_value(paddle.to_tensor(
                rng.randn(*p_.shape).astype(np.float32) * 0.05))
        src = rng.randn(B, 6, 16).astype(np.float32)
        full = fmt(paddle.to_tensor(src)).numpy()
        caches = fmt.init_caches(B, 8)
        _, caches = fmt(paddle.to_tensor(src[:, :5]), caches=caches, time_step=0)
        h2, _ = fmt(paddle.to_tensor(src[:, 5:6]), caches=caches, time_step=5)
        np.testing.assert_allclose(h2.numpy()[:, 0], full[:, 5],
                                   rtol=1e-5, atol=1e-5)

    def test_gqa_and_rmsnorm(self):
        rng = np.random.RandomState(1)
        fmt = FusedMultiTransformer(embed_dim=16, num_heads=4,
                                    dim_feedforward=32, num_layers=1,
                                    norm_type="rmsnorm", gqa_group_size=2)
        assert fmt.kv_heads == 2
        out = fmt(paddle.to_tensor(rng.randn(1, 4, 16).astype(np.float32)))
        assert out.shape == [1, 4, 16]


class TestSavedProgram:
    def test_jit_save_load_predictor(self, tmp_path):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                        num_heads=2, max_position_embeddings=32)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = np.random.RandomState(0).randint(0, 64, (2, 8)).astype(np.int32)
        ref = m(paddle.to_tensor(ids)).numpy()
        prefix = os.path.join(str(tmp_path), "gpt")
        paddle.jit.save(m, prefix,
                        input_spec=[paddle.jit.InputSpec([2, 8], "int32")])
        assert os.path.exists(prefix + ".pdmodel")
        tl = paddle.jit.load(prefix)
        np.testing.assert_allclose(tl(paddle.to_tensor(ids)).numpy(), ref,
                                   rtol=1e-6, atol=1e-6)
        config = paddle.inference.Config(prefix + ".pdmodel")
        pred = paddle.inference.create_predictor(config)
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(ids)
        outs = pred.run()
        np.testing.assert_allclose(outs[0], ref, rtol=1e-6, atol=1e-6)
