"""Quantized serving fast path (PADDLE_TPU_KV_QUANT / PADDLE_TPU_SERVE_W8):
the int8 BlockPool layout with per-(page, head) scales, the running-abs-max
paged_kv_write_q8 append, the dequant-fused Pallas decode kernel, and the
PagedServingEngine over all three.

Acceptance properties pinned here:
- quantized-vs-dense logit divergence under an explicit tolerance (the
  first decode step after an identical unquantized prefill isolates pure KV
  quantization error);
- BITWISE scheduling invariance of the quantized path itself — preemption/
  spill/resume and prefix sharing produce token-identical output because
  the int8 payload+scale update is a pure function of page history;
- prefix sharing + COW + preemption recovery all pass with kv_quant on;
- strictly more concurrency than the f32 pool at an equal HBM byte budget.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.inference.paged import BlockPool, PagedServingEngine
from paddle_tpu.models import GPTForCausalLM, gpt3_tiny
from paddle_tpu.observability.metrics import default_registry
from paddle_tpu.ops.pallas.decode_attention import (
    KV_QMAX,
    paged_decode_attention,
    paged_kv_write_q8,
)


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret_unless_hw):
    pass


def _model():
    paddle.seed(0)
    return GPTForCausalLM(gpt3_tiny())


def _counter(name, **labels):
    m = default_registry().get(name)
    return m.value(**labels) if m is not None else 0.0


def _drive(eng, prompts, temps=None, max_new=None, priorities=None):
    ids = [eng.add_request(
        p,
        max_new_tokens=5 if max_new is None else max_new[i],
        temperature=0.0 if temps is None else temps[i],
        priority=0 if priorities is None else priorities[i])
        for i, p in enumerate(prompts)]
    done = eng.run()
    by = {r.req_id: r for r in done}
    return [by[i] for i in ids]


def _quantize_ref(pages):
    """numpy oracle for the pool's per-(page, head) abs-max quantization."""
    absmax = np.abs(pages).max(axis=(2, 3))
    scale = absmax / KV_QMAX
    safe = np.where(scale == 0, 1.0, scale)
    q = np.clip(np.round(pages / safe[:, :, None, None]),
                -KV_QMAX, KV_QMAX).astype(np.int8)
    return q, scale.astype(np.float32)


# --------------------------------------------------------------------------- #
# quantized block pool
# --------------------------------------------------------------------------- #


class TestQuantBlockPool:
    def _pool(self, **kw):
        kw.setdefault("num_layers", 2)
        kw.setdefault("kv_heads", 2)
        kw.setdefault("head_dim", 4)
        kw.setdefault("page_size", 4)
        kw.setdefault("num_pages", 6)
        kw.setdefault("quantized", True)
        return BlockPool(**kw)

    def test_layout_and_byte_accounting(self):
        pool = self._pool()
        k, v = pool.kv[0]
        assert k.dtype == jnp.int8 and v.dtype == jnp.int8
        sk, sv = pool.scales[0]
        assert sk.shape == (6, 2) and sk.dtype == jnp.float32
        # payload 2*2*(2*4*4) + scales 2*2*2*4 per page
        assert pool.bytes_per_page == 2 * 2 * (2 * 4 * 4) + 2 * 2 * 2 * 4
        f32 = BlockPool.page_nbytes(2, 2, 4, 4, jnp.float32, False)
        assert f32 / pool.bytes_per_page > 3.0  # toy dims: scales loom large
        # at a realistic page shape the scale overhead amortizes to ~4x
        q = BlockPool.page_nbytes(12, 12, 64, 16, quantized=True)
        f = BlockPool.page_nbytes(12, 12, 64, 16, jnp.float32, False)
        assert f / q > 3.9

    def test_write_prompt_pages_quantizes_with_error_bound(self):
        pool = self._pool()
        pages = [pool.alloc(), pool.alloc()]
        rng = np.random.default_rng(0)
        stacked = rng.standard_normal((2, 2, 4, 4)).astype(np.float32) * 2.0
        n0 = _counter("serving_kv_quant_pages_total")
        pool.write_prompt_pages(pages, [True, True],
                                [jnp.asarray(stacked)] * 2,
                                [jnp.asarray(-stacked)] * 2)
        assert _counter("serving_kv_quant_pages_total") == n0 + 2
        k, _ = pool.kv[0]
        sk, _ = pool.scales[0]
        deq = (np.asarray(k[np.asarray(pages)], np.float32)
               * np.asarray(sk[np.asarray(pages)])[:, :, None, None])
        err_bound = np.asarray(sk[np.asarray(pages)])[:, :, None, None] / 2
        assert np.all(np.abs(deq - stacked) <= err_bound + 1e-7)
        # matches the numpy oracle bit-for-bit (determinism => sharing works)
        q_ref, s_ref = _quantize_ref(stacked)
        np.testing.assert_array_equal(np.asarray(k[np.asarray(pages)]), q_ref)
        np.testing.assert_allclose(np.asarray(sk[np.asarray(pages)]), s_ref,
                                   rtol=1e-6)

    def test_copy_page_carries_scales(self):
        pool = self._pool()
        src, dst = pool.alloc(), pool.alloc()
        pool.write_prompt_pages(
            [src], [True],
            [jnp.ones((1, 2, 4, 4)) * 3.0] * 2,
            [jnp.ones((1, 2, 4, 4)) * 5.0] * 2)
        pool.copy_page(src, dst)
        for li in range(2):
            k, v = pool.kv[li]
            sk, sv = pool.scales[li]
            np.testing.assert_array_equal(np.asarray(k[dst]),
                                          np.asarray(k[src]))
            np.testing.assert_array_equal(np.asarray(sk[dst]),
                                          np.asarray(sk[src]))
            np.testing.assert_array_equal(np.asarray(sv[dst]),
                                          np.asarray(sv[src]))

    def test_spill_restore_roundtrip_is_bitexact(self):
        pool = self._pool()
        pages = [pool.alloc(), pool.alloc()]
        rng = np.random.default_rng(3)
        stacked = rng.standard_normal((2, 2, 4, 4)).astype(np.float32)
        pool.write_prompt_pages(pages, [True, True],
                                [jnp.asarray(stacked)] * 2,
                                [jnp.asarray(2 * stacked)] * 2)
        before_k = np.asarray(pool.kv[0][0][np.asarray(pages)])
        before_s = np.asarray(pool.scales[0][0][np.asarray(pages)])
        host = pool.read_pages(pages)
        assert len(host[0]) == 4  # (k, v, k_scale, v_scale)
        for p in pages:
            pool.release(p)
        fresh = [pool.alloc(), pool.alloc()]
        pool.restore_pages(fresh, host, [0, 1])
        np.testing.assert_array_equal(
            np.asarray(pool.kv[0][0][np.asarray(fresh)]), before_k)
        np.testing.assert_array_equal(
            np.asarray(pool.scales[0][0][np.asarray(fresh)]), before_s)


# --------------------------------------------------------------------------- #
# quantized append + dequant-fused kernel
# --------------------------------------------------------------------------- #


class TestPagedKvWriteQ8:
    def test_append_dequantizes_to_row_within_bound(self):
        B, Hkv, D, ps = 2, 2, 8, 4
        cache = jnp.zeros((5, Hkv, ps, D), jnp.int8)
        scales = jnp.zeros((5, Hkv), jnp.float32)
        tables = jnp.asarray([[1, 2], [3, -1]], jnp.int32)
        lengths = jnp.asarray([5, 2], jnp.int32)  # -> (page 2, 1), (page 3, 2)
        new = jnp.asarray(
            np.random.default_rng(0).standard_normal((B, Hkv, D)),
            jnp.float32)
        cache, scales = paged_kv_write_q8(cache, scales, new, tables, lengths)
        deq = (np.asarray(cache, np.float32)
               * np.asarray(scales)[:, :, None, None])
        for b, (pg, sl) in enumerate([(2, 1), (3, 2)]):
            bound = np.asarray(scales)[pg][:, None] / 2
            assert np.all(np.abs(deq[pg, :, sl] - np.asarray(new)[b])
                          <= bound + 1e-7)

    def test_scale_grows_and_requantizes_prior_content(self):
        Hkv, D, ps = 1, 4, 4
        cache = jnp.zeros((2, Hkv, ps, D), jnp.int8)
        scales = jnp.zeros((2, Hkv), jnp.float32)
        tables = jnp.asarray([[1]], jnp.int32)
        small = jnp.full((1, Hkv, D), 0.5, jnp.float32)
        big = jnp.full((1, Hkv, D), 4.0, jnp.float32)
        cache, scales = paged_kv_write_q8(
            cache, scales, small, tables, jnp.asarray([0], jnp.int32))
        s0 = float(scales[1, 0])
        cache, scales = paged_kv_write_q8(
            cache, scales, big, tables, jnp.asarray([1], jnp.int32))
        s1 = float(scales[1, 0])
        assert s1 == pytest.approx(4.0 / KV_QMAX) and s1 > s0
        deq = np.asarray(cache, np.float32)[1, 0] * s1
        # slot 0 was requantized under the grown scale; one rounding step
        np.testing.assert_allclose(deq[0], 0.5, atol=s1 / 2 + 1e-7)
        np.testing.assert_allclose(deq[1], 4.0, atol=s1 / 2 + 1e-7)

    def test_unchanged_scale_append_is_bitexact_for_prior_slots(self):
        Hkv, D, ps = 1, 4, 4
        cache = jnp.zeros((2, Hkv, ps, D), jnp.int8)
        scales = jnp.zeros((2, Hkv), jnp.float32)
        tables = jnp.asarray([[1]], jnp.int32)
        big = jnp.full((1, Hkv, D), 4.0, jnp.float32)
        small = jnp.full((1, Hkv, D), 0.5, jnp.float32)
        cache, scales = paged_kv_write_q8(
            cache, scales, big, tables, jnp.asarray([0], jnp.int32))
        slot0 = np.asarray(cache)[1, 0, 0].copy()
        cache, scales = paged_kv_write_q8(
            cache, scales, small, tables, jnp.asarray([1], jnp.int32))
        np.testing.assert_array_equal(np.asarray(cache)[1, 0, 0], slot0)

    def test_slot0_write_ignores_stale_state_from_recycled_page(self):
        """A page popped back off the free list keeps its last tenant's
        payload AND scale (release() never clears device data). Slot 0 is
        always a page's first write, so the append must restart the running
        abs-max there — inheriting a big stale scale would quantize a
        small-magnitude row to a few int8 levels and make page content
        depend on which physical page the free list happened to return,
        breaking the bitwise scheduling invariance."""
        Hkv, D, ps = 1, 4, 4
        tables = jnp.asarray([[1]], jnp.int32)
        small = jnp.full((1, Hkv, D), 0.5, jnp.float32)
        recycled = paged_kv_write_q8(
            jnp.full((2, Hkv, ps, D), 111, jnp.int8),   # stale payload
            jnp.full((2, Hkv), 100.0, jnp.float32),     # stale big scale
            small, tables, jnp.asarray([0], jnp.int32))
        fresh = paged_kv_write_q8(
            jnp.zeros((2, Hkv, ps, D), jnp.int8),
            jnp.zeros((2, Hkv), jnp.float32),
            small, tables, jnp.asarray([0], jnp.int32))
        # written page identical regardless of the previous tenant
        np.testing.assert_array_equal(np.asarray(recycled[0])[1],
                                      np.asarray(fresh[0])[1])
        np.testing.assert_array_equal(np.asarray(recycled[1])[1],
                                      np.asarray(fresh[1])[1])
        assert float(recycled[1][1, 0]) == pytest.approx(0.5 / KV_QMAX)
        assert not np.asarray(recycled[0])[1, :, 1:].any()  # stale slots zeroed

    def test_parked_rows_hit_null_page(self):
        Hkv, D, ps = 1, 4, 4
        cache = jnp.zeros((3, Hkv, ps, D), jnp.int8)
        scales = jnp.zeros((3, Hkv), jnp.float32)
        tables = jnp.asarray([[1], [-1]], jnp.int32)
        new = jnp.ones((2, Hkv, D), jnp.float32)
        cache, scales = paged_kv_write_q8(
            cache, scales, new, tables, jnp.asarray([1, 0], jnp.int32))
        out = np.asarray(cache)
        assert out[1, :, 1].any()      # live row wrote its slot
        assert out[0, :, 0].any()      # parked row landed on null page
        assert not out[2:].any()       # no allocatable page touched


class TestDequantFusedKernel:
    def test_matches_dequantized_reference_kernel(self):
        """The fused kernel on (int8 payload, scales) equals the f32 kernel
        on the pre-dequantized cache — the dequant multiply is the only new
        op, applied to the identical page stream."""
        rng = np.random.default_rng(0)
        B, H, Hkv, D, ps, P = 2, 4, 2, 16, 8, 3
        n_pages = 1 + B * P
        q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
        pages = rng.standard_normal((n_pages, Hkv, ps, D)).astype(np.float32)
        qk, sk = _quantize_ref(pages)
        qv, sv = _quantize_ref(pages[::-1].copy())
        tables = np.full((B, P), -1, np.int32)
        tables[0, :3] = [1, 2, 3]
        tables[1, :2] = [4, 5]
        lengths = jnp.asarray([21, 13], jnp.int32)  # partial final pages
        fused = paged_decode_attention(
            q, jnp.asarray(qk), jnp.asarray(qv), jnp.asarray(tables),
            lengths, kv_scales=(jnp.asarray(sk), jnp.asarray(sv)))
        deq_k = qk.astype(np.float32) * sk[:, :, None, None]
        deq_v = qv.astype(np.float32) * sv[:, :, None, None]
        ref = paged_decode_attention(
            q, jnp.asarray(deq_k), jnp.asarray(deq_v), jnp.asarray(tables),
            lengths)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)

    def test_zero_length_row_is_finite(self):
        B, H, Hkv, D, ps, P = 2, 2, 2, 16, 8, 2
        q = jnp.ones((B, H, D), jnp.float32)
        cache = jnp.ones((3, Hkv, ps, D), jnp.int8)
        scales = jnp.ones((3, Hkv), jnp.float32)
        tables = jnp.asarray([[1, -1], [-1, -1]], jnp.int32)
        out = np.asarray(paged_decode_attention(
            q, cache, cache, tables, jnp.asarray([4, 0], jnp.int32),
            kv_scales=(scales, scales)))
        assert np.all(np.isfinite(out))
        np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))


# --------------------------------------------------------------------------- #
# quantized engine
# --------------------------------------------------------------------------- #


class TestQuantEngine:
    # explicit divergence tolerances the acceptance criteria pin: the first
    # decode tick after the identical (unquantized) prefill isolates pure KV
    # quantization error — observed ~1e-3 on gpt3_tiny, pinned at ~20x
    # margin; later ticks may accumulate one rounding step per scale growth
    FIRST_TICK_LOGIT_TOL = 0.02
    DRAIN_LOGIT_TOL = 0.05

    def test_logit_and_token_divergence_vs_full_precision(self):
        """Lockstep quantized-vs-f32 drive of the same mixed greedy/sampled
        workload: tick-0 logits (pure KV quant error after an identical
        prefill) pinned at 0.02, every tick's at 0.05, and the emitted
        token streams identical — int8 KV error stays under the argmax
        margins, and sampled rows share the (seed, arrival) key stream so
        divergence could only come from logit movement."""
        rng = np.random.default_rng(42)
        prompts = [rng.integers(1, 1000, 4 + i).astype(np.int32)
                   for i in range(4)]
        temps = [0.0, 0.7, 0.0, 0.0]
        engines = {
            quant: PagedServingEngine(_model(), max_batch_size=4,
                                      max_seq_len=64, page_size=16, seed=3,
                                      kv_quant=quant)
            for quant in (False, True)}
        for quant, eng in engines.items():
            for i, p in enumerate(prompts):
                eng.add_request(p, max_new_tokens=5, temperature=temps[i])
        diffs = []
        while engines[False].has_work() or engines[True].has_work():
            engines[False].step()
            engines[True].step()
            if engines[False].last_logits is not None:
                diffs.append(float(np.max(np.abs(
                    np.asarray(engines[False].last_logits)
                    - np.asarray(engines[True].last_logits)))))
        assert 0 < diffs[0] <= self.FIRST_TICK_LOGIT_TOL
        assert max(diffs) <= self.DRAIN_LOGIT_TOL
        toks = {q: [r.generated
                    for r in sorted(e.finished, key=lambda r: r.req_id)]
                for q, e in engines.items()}
        assert toks[True] == toks[False]

    def test_prefix_sharing_and_cow_under_kv_quant(self, monkeypatch):
        """Two identical prompts through the env toggle: pages share (hits),
        the first divergent write copies (COW), and both requests emit
        identical tokens — determinism makes shared int8 pages bit-equal."""
        monkeypatch.setenv("PADDLE_TPU_KV_QUANT", "1")
        hits0 = _counter("serving_prefix_hits_total")
        cow0 = _counter("serving_cow_copies_total")
        eng = PagedServingEngine(_model(), max_batch_size=4, max_seq_len=64,
                                 page_size=16, seed=3)
        assert eng.kv_quant  # captured from env at construction
        prompt = np.random.default_rng(1).integers(1, 1000, 10).astype(
            np.int32)
        eng.add_request(prompt, max_new_tokens=4)
        eng.add_request(prompt, max_new_tokens=4)
        done = sorted(eng.run(), key=lambda r: r.req_id)
        assert done[0].generated == done[1].generated
        assert _counter("serving_prefix_hits_total") > hits0
        assert _counter("serving_cow_copies_total") > cow0

    def test_preemption_recovery_is_bitwise_invariant(self):
        """The quantized path's scheduling invariance: an undersized pool
        that forces spill/resume produces BIT-IDENTICAL tokens to an ample
        pool — the int8 payload+scale update is a pure function of page
        history, and spill buffers round-trip exactly."""
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, 1000, 14).astype(np.int32)
                   for _ in range(4)]
        prios = [0, -1, -2, -3]

        def run(num_pages=None, watermark=None):
            eng = PagedServingEngine(
                _model(), max_batch_size=4, max_seq_len=64, page_size=16,
                seed=3, kv_quant=True, prefix_sharing=False,
                num_pages=num_pages, watermark_pages=watermark)
            return [r.generated for r in _drive(
                eng, prompts, max_new=[6] * 4, priorities=prios)]

        ample = run()
        pre0 = _counter("serving_preemptions_total")
        res0 = _counter("serving_resumes_total")
        starved = run(num_pages=6, watermark=0)
        assert _counter("serving_preemptions_total") > pre0
        assert _counter("serving_resumes_total") > res0
        assert starved == ample  # bitwise

    def test_more_concurrency_than_f32_at_equal_byte_budget(self):
        """The headline: at the SAME pool HBM bytes the int8 engine admits
        strictly more concurrent requests (~4x the pages)."""
        cfg = gpt3_tiny()
        budget = 13 * BlockPool.page_nbytes(
            cfg.num_layers, cfg.kv_heads, cfg.head_dim, 16)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, 1000, 30).astype(np.int32)
                   for _ in range(8)]
        peak = {}
        for quant in (False, True):
            eng = PagedServingEngine(_model(), max_batch_size=8,
                                     max_seq_len=64, page_size=16, seed=0,
                                     kv_quant=quant,
                                     kv_budget_bytes=budget)
            for p in prompts:
                eng.add_request(p, max_new_tokens=3)
            peak[quant] = 0
            while eng.has_work():
                eng.step()
                peak[quant] = max(peak[quant], eng.live_count)
        assert peak[True] == 8          # all rows live at once
        assert peak[True] > peak[False]  # strictly more than f32
        assert _counter("serving_kv_bytes_per_token") < 512

    def test_sub_two_page_byte_budget_raises(self):
        """A budget that cannot fit the null page plus one allocatable page
        must raise, not silently enlarge the pool past the requested bytes
        (which would falsify the equal-budget A/B)."""
        with pytest.raises(ValueError, match="kv_budget_bytes"):
            PagedServingEngine(_model(), max_batch_size=2, max_seq_len=32,
                               page_size=16, kv_budget_bytes=64)

    def test_num_pages_and_byte_budget_are_mutually_exclusive(self):
        """Passing both would let the page count silently override the byte
        budget — the other way an equal-budget A/B can quietly lie."""
        with pytest.raises(ValueError, match="not both"):
            PagedServingEngine(_model(), max_batch_size=2, max_seq_len=32,
                               page_size=16, num_pages=100,
                               kv_budget_bytes=200_000)

    def test_serve_w8_weight_bytes_drop_and_tokens_flow(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SERVE_W8", "1")
        model = _model()
        dense_bytes = sum(
            int(np.prod(p._value.shape)) * p._value.dtype.itemsize
            for _, p in model.named_parameters())
        eng = PagedServingEngine(model, max_batch_size=2, max_seq_len=64,
                                 page_size=16, seed=3, kv_quant=True)
        assert eng.serve_w8
        served = (sum(int(np.prod(v.shape)) * v.dtype.itemsize
                      for v in eng.params.values())
                  + sum(int(np.prod(v.shape)) * v.dtype.itemsize
                        for v in eng.buffers.values()))
        assert served < dense_bytes  # projection HBM dropped
        prompt = np.random.default_rng(2).integers(1, 1000, 8).astype(
            np.int32)
        eng.add_request(prompt, max_new_tokens=4)
        done = eng.run()
        assert len(done[0].generated) == 4


class TestKvDtypeFlowsFromModel:
    def test_bf16_model_gets_bf16_pages(self):
        """Satellite: the pool/prefill dtype follows the model instead of a
        hardcoded f32 — a bf16 model no longer silently pays 2x KV bytes."""
        model = _model()
        for _, p in model.named_parameters():
            p._value = p._value.astype(jnp.bfloat16)
        eng = PagedServingEngine(model, max_batch_size=2, max_seq_len=32,
                                 page_size=16)
        assert eng.kv_dtype == jnp.bfloat16
        assert eng.pool.kv[0][0].dtype == jnp.bfloat16
        assert eng.pool.bytes_per_token == eng.cfg.num_layers * 2 * \
            eng.cfg.kv_heads * eng.cfg.head_dim * 2

    def test_f32_model_unchanged(self):
        eng = PagedServingEngine(_model(), max_batch_size=2, max_seq_len=32,
                                 page_size=16)
        assert eng.kv_dtype == jnp.float32
        assert eng.pool.kv[0][0].dtype == jnp.float32


@pytest.mark.slow
class TestQuantDrainStress:
    def test_large_mixed_drain_under_pressure_quantized(self):
        """16 mixed greedy/sampled requests with shared prefixes through an
        undersized QUANTIZED pool: everything drains, output matches the
        ample-pool quantized run bitwise, and the quant series populate."""
        rng = np.random.default_rng(11)
        shared = rng.integers(1, 1000, 16).astype(np.int32)
        prompts, temps, max_new, prios = [], [], [], []
        for i in range(16):
            tail = rng.integers(1, 1000, 2 + i % 7).astype(np.int32)
            prompts.append(np.concatenate([shared, tail]) if i % 3 == 0
                           else rng.integers(1, 1000,
                                             3 + i % 9).astype(np.int32))
            temps.append(0.6 if i % 4 == 0 else 0.0)
            max_new.append(4 + i % 6)
            prios.append(-(i % 5))

        def run(**kw):
            eng = PagedServingEngine(_model(), max_batch_size=4,
                                     max_seq_len=64, page_size=16, seed=9,
                                     kv_quant=True, **kw)
            return [r.generated
                    for r in _drive(eng, prompts, temps, max_new, prios)]

        ample = run()
        starved = run(num_pages=8, watermark_pages=1)
        assert starved == ample
        assert _counter("serving_kv_quant_pages_total") > 0
