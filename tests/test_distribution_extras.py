"""Distribution-zoo tail + transform family tests — scipy.stats parity for
densities/statistics, autodiff-Jacobian parity for transform log-dets
(reference: test/distribution/test_distribution_beta.py etc.)."""

import numpy as np
import pytest
import scipy.stats as st

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _v(t):
    return np.asarray(t.numpy(), np.float64)


class TestZooDensities:
    def test_beta(self):
        d = D.Beta(2.0, 3.0)
        x = np.asarray([0.2, 0.5, 0.9], np.float32)
        np.testing.assert_allclose(_v(d.log_prob(paddle.to_tensor(x))),
                                   st.beta.logpdf(x, 2, 3), rtol=1e-5)
        np.testing.assert_allclose(float(_v(d.mean)), 2 / 5, rtol=1e-6)
        np.testing.assert_allclose(float(_v(d.entropy())),
                                   st.beta.entropy(2, 3), rtol=1e-5)

    def test_gamma(self):
        d = D.Gamma(3.0, 2.0)
        x = np.asarray([0.5, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(_v(d.log_prob(paddle.to_tensor(x))),
                                   st.gamma.logpdf(x, 3, scale=0.5),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(_v(d.entropy())),
                                   st.gamma.entropy(3, scale=0.5), rtol=1e-5)
        np.testing.assert_allclose(_v(d.cdf(paddle.to_tensor(x))),
                                   st.gamma.cdf(x, 3, scale=0.5), rtol=1e-5)

    def test_dirichlet(self):
        c = np.asarray([2.0, 3.0, 4.0], np.float32)
        d = D.Dirichlet(c)
        x = np.asarray([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(float(_v(d.log_prob(paddle.to_tensor(x)))),
                                   st.dirichlet.logpdf(x, c), rtol=1e-5)
        np.testing.assert_allclose(_v(d.mean), c / c.sum(), rtol=1e-6)
        np.testing.assert_allclose(float(_v(d.entropy())),
                                   st.dirichlet.entropy(c), rtol=1e-4,
                                   atol=1e-5)

    def test_laplace(self):
        d = D.Laplace(1.0, 2.0)
        x = np.asarray([-1.0, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(_v(d.log_prob(paddle.to_tensor(x))),
                                   st.laplace.logpdf(x, 1, 2), rtol=1e-5)
        np.testing.assert_allclose(_v(d.cdf(paddle.to_tensor(x))),
                                   st.laplace.cdf(x, 1, 2), rtol=1e-5)
        p = np.asarray([0.1, 0.5, 0.9], np.float32)
        np.testing.assert_allclose(_v(d.icdf(paddle.to_tensor(p))),
                                   st.laplace.ppf(p, 1, 2), rtol=1e-5)

    def test_lognormal(self):
        d = D.LogNormal(0.5, 0.8)
        x = np.asarray([0.5, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(
            _v(d.log_prob(paddle.to_tensor(x))),
            st.lognorm.logpdf(x, 0.8, scale=np.exp(0.5)), rtol=1e-5)
        np.testing.assert_allclose(
            float(_v(d.mean)), st.lognorm.mean(0.8, scale=np.exp(0.5)),
            rtol=1e-5)

    def test_multinomial(self):
        d = D.Multinomial(10, np.asarray([0.2, 0.3, 0.5], np.float32))
        x = np.asarray([2.0, 3.0, 5.0], np.float32)
        np.testing.assert_allclose(
            float(_v(d.log_prob(paddle.to_tensor(x)))),
            st.multinomial.logpmf([2, 3, 5], 10, [0.2, 0.3, 0.5]), rtol=1e-5)
        np.testing.assert_allclose(_v(d.mean), [2.0, 3.0, 5.0], rtol=1e-5)
        s = _v(d.sample((7,)))
        assert s.shape == (7, 3)
        np.testing.assert_allclose(s.sum(-1), 10.0)

    def test_geometric_gumbel_cauchy(self):
        g = D.Geometric(0.3)
        k = np.asarray([0.0, 2.0, 5.0], np.float32)
        np.testing.assert_allclose(_v(g.log_prob(paddle.to_tensor(k))),
                                   st.geom.logpmf(k + 1, 0.3), rtol=1e-5)
        gm = D.Gumbel(1.0, 2.0)
        x = np.asarray([-1.0, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(_v(gm.log_prob(paddle.to_tensor(x))),
                                   st.gumbel_r.logpdf(x, 1, 2), rtol=1e-5)
        c = D.Cauchy(0.5, 1.5)
        np.testing.assert_allclose(_v(c.log_prob(paddle.to_tensor(x))),
                                   st.cauchy.logpdf(x, 0.5, 1.5), rtol=1e-5)
        np.testing.assert_allclose(_v(c.cdf(paddle.to_tensor(x))),
                                   st.cauchy.cdf(x, 0.5, 1.5), rtol=1e-5)

    def test_poisson_studentt_binomial(self):
        p = D.Poisson(3.0)
        k = np.asarray([0.0, 2.0, 6.0], np.float32)
        np.testing.assert_allclose(_v(p.log_prob(paddle.to_tensor(k))),
                                   st.poisson.logpmf(k, 3.0), rtol=1e-5)
        t = D.StudentT(5.0, 1.0, 2.0)
        x = np.asarray([-1.0, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(_v(t.log_prob(paddle.to_tensor(x))),
                                   st.t.logpdf(x, 5, 1, 2), rtol=1e-5)
        b = D.Binomial(8, 0.4)
        np.testing.assert_allclose(_v(b.log_prob(paddle.to_tensor(k))),
                                   st.binom.logpmf(k, 8, 0.4), rtol=1e-5)

    def test_sample_moments(self):
        paddle.seed(7)
        for d, mean, std in [
            (D.Beta(2.0, 3.0), 0.4, np.sqrt(st.beta.var(2, 3))),
            (D.Gamma(3.0, 2.0), 1.5, np.sqrt(st.gamma.var(3, scale=0.5))),
            (D.Laplace(1.0, 2.0), 1.0, np.sqrt(8.0)),
            (D.Gumbel(1.0, 2.0), st.gumbel_r.mean(1, 2),
             st.gumbel_r.std(1, 2)),
        ]:
            s = _v(d.sample((20000,)))
            np.testing.assert_allclose(s.mean(), mean, atol=4 * std / 140)

    def test_rsample_gradients_flow(self):
        """Reparameterized sampling: d(sample.mean)/d(param) is nonzero."""
        paddle.seed(0)
        loc = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
        d = D.Laplace(loc, 1.0)
        s = d.rsample((64,))
        s.mean().backward()
        assert loc.grad is not None
        np.testing.assert_allclose(float(loc.grad.numpy()), 1.0, rtol=1e-4)

    def test_kl_new_pairs(self):
        # KL(p||q) >= 0, == 0 for identical, and matches a Monte-Carlo
        # estimate for Beta
        p, q = D.Beta(2.0, 3.0), D.Beta(3.0, 2.0)
        kl = float(_v(D.kl_divergence(p, q)))
        assert kl > 0
        assert abs(float(_v(D.kl_divergence(p, p)))) < 1e-6
        paddle.seed(1)
        x = _v(p.sample((40000,)))
        mc = (st.beta.logpdf(x, 2, 3) - st.beta.logpdf(x, 3, 2)).mean()
        np.testing.assert_allclose(kl, mc, rtol=0.08)
        for pair in [(D.Gamma(3.0, 2.0), D.Gamma(2.0, 1.0)),
                     (D.Laplace(0.0, 1.0), D.Laplace(1.0, 2.0)),
                     (D.Dirichlet(np.asarray([2.0, 3.0], np.float32)),
                      D.Dirichlet(np.asarray([1.0, 1.0], np.float32)))]:
            assert float(np.max(_v(D.kl_divergence(*pair)))) > 0


class TestTransforms:
    BIJ = None  # populated below

    @pytest.mark.parametrize("t,x", [
        (lambda: D.AffineTransform(1.0, 2.0), np.asarray([0.3, -1.2])),
        (lambda: D.ExpTransform(), np.asarray([0.3, -1.2])),
        (lambda: D.PowerTransform(2.0), np.asarray([0.5, 1.7])),
        (lambda: D.SigmoidTransform(), np.asarray([0.3, -1.2])),
        (lambda: D.TanhTransform(), np.asarray([0.3, -1.2])),
    ])
    def test_bijection_roundtrip_and_logdet(self, t, x):
        tr = t()
        x = x.astype(np.float32)
        y = tr.forward(paddle.to_tensor(x))
        back = _v(tr.inverse(y))
        np.testing.assert_allclose(back, x, rtol=1e-5, atol=1e-6)
        # log|dy/dx| vs autodiff
        ldj = _v(tr.forward_log_det_jacobian(paddle.to_tensor(x)))
        grad = jax.vmap(jax.grad(lambda v: tr._forward(v)))(jnp.asarray(x))
        np.testing.assert_allclose(ldj, np.log(np.abs(np.asarray(grad))),
                                   rtol=1e-5, atol=1e-6)
        ildj = _v(tr.inverse_log_det_jacobian(y))
        np.testing.assert_allclose(ildj, -ldj, rtol=1e-5, atol=1e-6)

    def test_chain(self):
        tr = D.ChainTransform([D.AffineTransform(0.5, 2.0),
                               D.ExpTransform()])
        x = np.asarray([0.1, -0.4], np.float32)
        y = _v(tr.forward(paddle.to_tensor(x)))
        np.testing.assert_allclose(y, np.exp(0.5 + 2.0 * x), rtol=1e-5)
        np.testing.assert_allclose(_v(tr.inverse(paddle.to_tensor(y))), x,
                                   rtol=1e-5)
        ldj = _v(tr.forward_log_det_jacobian(paddle.to_tensor(x)))
        grad = jax.vmap(jax.grad(lambda v: tr._forward(v)))(jnp.asarray(x))
        np.testing.assert_allclose(ldj, np.log(np.abs(np.asarray(grad))),
                                   rtol=1e-5)

    def test_stickbreaking(self):
        tr = D.StickBreakingTransform()
        x = np.asarray([0.3, -0.8, 1.1], np.float32)
        y = _v(tr.forward(paddle.to_tensor(x)))
        assert y.shape == (4,)
        assert (y > 0).all()
        np.testing.assert_allclose(y.sum(), 1.0, rtol=1e-6)
        np.testing.assert_allclose(_v(tr.inverse(paddle.to_tensor(y))), x,
                                   rtol=1e-4, atol=1e-5)
        # log-det vs autodiff jacobian of the first k outputs
        ldj = float(_v(tr.forward_log_det_jacobian(paddle.to_tensor(x))))
        J = jax.jacobian(lambda v: tr._forward(v)[:-1])(jnp.asarray(x))
        _, ref = np.linalg.slogdet(np.asarray(J, np.float64))
        np.testing.assert_allclose(ldj, ref, rtol=1e-4)

    def test_shapes_and_stack_reshape(self):
        tr = D.StickBreakingTransform()
        assert tr.forward_shape((5, 3)) == (5, 4)
        assert tr.inverse_shape((5, 4)) == (5, 3)
        rt = D.ReshapeTransform((6,), (2, 3))
        y = rt.forward(paddle.to_tensor(np.arange(6, dtype=np.float32)))
        assert tuple(y.shape) == (2, 3)
        stk = D.StackTransform([D.ExpTransform(), D.AffineTransform(0.0, 2.0)])
        x = np.asarray([[0.5, 1.0], [1.5, 2.0]], np.float32)
        y = _v(stk.forward(paddle.to_tensor(x)))
        np.testing.assert_allclose(y[0], np.exp(x[0]), rtol=1e-5)
        np.testing.assert_allclose(y[1], 2 * x[1], rtol=1e-5)

    def test_transformed_distribution_lognormal_parity(self):
        """TransformedDistribution(Normal, ExpTransform) == LogNormal."""
        td = D.TransformedDistribution(D.Normal(0.5, 0.8), D.ExpTransform())
        ln = D.LogNormal(0.5, 0.8)
        x = paddle.to_tensor(np.asarray([0.5, 1.0, 3.0], np.float32))
        np.testing.assert_allclose(_v(td.log_prob(x)), _v(ln.log_prob(x)),
                                   rtol=1e-5)
        paddle.seed(3)
        s = _v(td.sample((5000,)))
        assert s.shape == (5000,)
        assert (s > 0).all()


class TestIndependent:
    def test_log_prob_sums_event_dims(self):
        base = D.Normal(np.zeros((3, 4), np.float32),
                        np.ones((3, 4), np.float32))
        ind = D.Independent(base, 1)
        assert tuple(ind.batch_shape) == (3,)
        assert tuple(ind.event_shape) == (4,)
        x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
        lp = _v(ind.log_prob(paddle.to_tensor(x)))
        base_lp = _v(base.log_prob(paddle.to_tensor(x)))
        np.testing.assert_allclose(lp, base_lp.sum(-1), rtol=1e-6)
        np.testing.assert_allclose(_v(ind.entropy()),
                                   _v(base.entropy()).sum(-1), rtol=1e-6)
        paddle.seed(0)
        assert tuple(ind.sample((5,)).shape) == (5, 3, 4)
        with pytest.raises(ValueError):
            D.Independent(base, 3)


class TestMultivariateNormal:
    def test_scipy_parity_and_sampling(self):
        rng = np.random.default_rng(0)
        A = rng.normal(size=(3, 3)).astype(np.float32)
        cov = A @ A.T + 2 * np.eye(3, dtype=np.float32)
        loc = np.asarray([1.0, -0.5, 2.0], np.float32)
        d = D.MultivariateNormal(loc, covariance_matrix=cov)
        x = rng.normal(size=(5, 3)).astype(np.float32)
        np.testing.assert_allclose(
            _v(d.log_prob(paddle.to_tensor(x))),
            st.multivariate_normal.logpdf(x, loc, cov), rtol=1e-4)
        np.testing.assert_allclose(
            float(_v(d.entropy())),
            st.multivariate_normal.entropy(loc, cov), rtol=1e-5)
        np.testing.assert_allclose(_v(d.covariance_matrix), cov, rtol=1e-4)
        np.testing.assert_allclose(_v(d.variance), np.diag(cov), rtol=1e-4)
        paddle.seed(3)
        s = _v(d.sample((30000,)))
        np.testing.assert_allclose(s.mean(0), loc, atol=0.05)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.15)

    def test_precision_and_scale_tril_ctor(self):
        cov = np.asarray([[2.0, 0.5], [0.5, 1.0]], np.float32)
        d1 = D.MultivariateNormal(np.zeros(2, np.float32),
                                  covariance_matrix=cov)
        d2 = D.MultivariateNormal(np.zeros(2, np.float32),
                                  precision_matrix=np.linalg.inv(cov))
        d3 = D.MultivariateNormal(np.zeros(2, np.float32),
                                  scale_tril=np.linalg.cholesky(cov))
        x = paddle.to_tensor(np.asarray([0.3, -0.7], np.float32))
        for d in (d2, d3):
            np.testing.assert_allclose(_v(d.log_prob(x)),
                                       _v(d1.log_prob(x)), rtol=1e-4)
        with pytest.raises(ValueError):
            D.MultivariateNormal(np.zeros(2, np.float32))
