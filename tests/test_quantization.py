"""Direct tier-1 coverage for paddle_tpu/quantization/ — previously only
touched by the test_quant_audio_text.py smoke. Pins the weight-quantization
error bound, QuantizedLinear forward parity at int8 tolerance, PTQ convert
semantics, and the ptq_convert_for_serving pass the serving engines run
under PADDLE_TPU_SERVE_W8."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (
    PTQ,
    QuantizedLinear,
    fake_quant,
    ptq_convert_for_serving,
    quantize_weight,
)


class TestQuantizeWeight:
    def test_roundtrip_error_bounded_by_half_step(self):
        """Symmetric abs-max: |w - q*scale| <= scale/2 per element, scale =
        per-channel absmax / 127 — the rounding bound, channel by channel."""
        rng = np.random.default_rng(0)
        w = rng.standard_normal((32, 16)).astype(np.float32) * 3.0
        for axis in (0, 1):
            q, s = quantize_weight(w, axis=axis)
            qv, sv = np.asarray(q._value), np.asarray(s._value)
            assert qv.dtype == np.int8
            deq = qv.astype(np.float32) * sv
            assert np.all(np.abs(deq - w) <= sv / 2 + 1e-7)
            # per-channel: each channel's scale reflects ITS absmax
            red = 1 - axis
            np.testing.assert_allclose(
                np.squeeze(sv), np.abs(w).max(axis=red) / 127, rtol=1e-6)

    def test_zero_channel_is_safe(self):
        w = np.zeros((4, 3), np.float32)
        w[0] = [1.0, -2.0, 0.5]
        q, s = quantize_weight(w, axis=0)
        deq = np.asarray(q._value, np.float32) * np.asarray(s._value)
        np.testing.assert_allclose(deq, w, atol=2.0 / 127)
        assert np.all(np.isfinite(deq))

    def test_values_stay_in_int8_range(self):
        w = np.asarray([[-5.0, 5.0, 4.99, -4.99]], np.float32)
        q, _ = quantize_weight(w, axis=0)
        qv = np.asarray(q._value)
        assert qv.min() >= -128 and qv.max() <= 127


class TestQuantizedLinear:
    def test_forward_parity_at_int8_tolerance(self):
        paddle.seed(0)
        lin = nn.Linear(24, 12)
        ql = QuantizedLinear(lin)
        x = paddle.to_tensor(
            np.random.default_rng(1).standard_normal((5, 24)).astype(
                np.float32))
        y, yq = lin(x).numpy(), ql(x).numpy()
        # error budget: per-channel scale/2 rounding per weight, summed over
        # the 24-term contraction
        w = np.asarray(lin.weight._value)
        bound = (np.abs(x.numpy()).sum(-1, keepdims=True)
                 * (np.abs(w).max(0) / 127) / 2) + 1e-6
        assert np.all(np.abs(y - yq) <= bound)

    def test_bias_and_no_bias(self):
        paddle.seed(0)
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        for bias_attr in (None, False):
            lin = nn.Linear(8, 4, bias_attr=bias_attr)
            ql = QuantizedLinear(lin)
            np.testing.assert_allclose(ql(x).numpy(), lin(x).numpy(),
                                       atol=0.05)

    def test_int8_buffers_registered(self):
        ql = QuantizedLinear(nn.Linear(8, 4))
        bufs = dict(ql.named_buffers())
        assert str(bufs["weight_quant"]._value.dtype) == "int8"
        assert bufs["weight_scale"]._value.dtype == jnp.float32


class TestFakeQuantSTE:
    def test_gradient_is_identity(self):
        x = paddle.to_tensor(
            np.asarray([0.3, -1.2, 2.0], np.float32), stop_gradient=False)
        y = fake_quant(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(3), rtol=0)


class TestConvertPasses:
    def _mlp(self):
        paddle.seed(0)

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 16)
                self.fc2 = nn.Linear(16, 4)

            def forward(self, x):
                return self.fc2(nn.functional.relu(self.fc1(x)))

        return M()

    def test_ptq_convert_swaps_observed_linears(self):
        m = self._mlp()
        ptq = PTQ()
        ptq.quantize(m)
        m(paddle.to_tensor(np.ones((2, 8), np.float32)))  # calibrate
        ptq.convert(m)
        assert isinstance(m.fc1, QuantizedLinear)
        assert isinstance(m.fc2, QuantizedLinear)
        assert m.fc1.activation_scale > 0

    def test_serving_convert_is_idempotent(self):
        m = self._mlp()
        assert ptq_convert_for_serving(m) == 2
        first = m.fc1
        assert ptq_convert_for_serving(m) == 0  # second pass: no-op
        assert m.fc1 is first  # not re-wrapped / double-quantized

    def test_serving_convert_covers_gpt_projections_only(self):
        """On a built GPTForCausalLM the pass swaps every decoder projection
        (Column/RowParallelLinear) but leaves the embedding — and therefore
        the tied LM head — full precision."""
        from paddle_tpu.models import GPTForCausalLM, gpt3_tiny

        paddle.seed(0)
        m = GPTForCausalLM(gpt3_tiny())
        n = ptq_convert_for_serving(m)
        # 2 layers x (q, k, v, out, fc1, fc2) = 12 projections
        assert n == 12
        for layer in m.gpt.layers:
            assert isinstance(layer.self_attn.q_proj, QuantizedLinear)
            assert isinstance(layer.mlp.fc2, QuantizedLinear)
        assert m.gpt.embed_tokens.weight._value.dtype == jnp.float32
        # projection weight bytes dropped ~4x (int8 payload + f32 scales)
        qbytes = sum(
            int(np.prod(b._value.shape)) * b._value.dtype.itemsize
            for _, b in m.named_buffers())
        cfg = m.config
        f32_proj_bytes = 4 * cfg.num_layers * (
            4 * cfg.hidden_size * cfg.hidden_size
            + 2 * cfg.hidden_size * cfg.ffn_size)
        assert qbytes < f32_proj_bytes / 3.5
        # the converted model still runs a forward
        out = m(paddle.to_tensor(np.ones((1, 4), np.int64)))
        assert np.all(np.isfinite(out.numpy()))

    def test_serving_convert_skips_untied_lm_head(self):
        """The head is the projection most sensitive to weight rounding; a
        tied head rides the f32 embedding matmul, and the untied `lm_head`
        must be skipped by name so the full-precision-head contract is
        independent of tie_word_embeddings."""
        import dataclasses

        from paddle_tpu.models import GPTForCausalLM, gpt3_tiny

        paddle.seed(0)
        m = GPTForCausalLM(dataclasses.replace(gpt3_tiny(),
                                               tie_word_embeddings=False))
        assert ptq_convert_for_serving(m) == 12  # same 12, head excluded
        assert not isinstance(m.lm_head, QuantizedLinear)
        out = m(paddle.to_tensor(np.ones((1, 4), np.int64)))
        assert np.all(np.isfinite(out.numpy()))
