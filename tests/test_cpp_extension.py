"""Custom C++ op extension via XLA FFI (reference:
paddle/fluid/framework/custom_operator.cc + python/paddle/utils/
cpp_extension — PD_BUILD_OP / PD_BUILD_GRAD_OP analog)."""

import os
import shutil

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "native", "custom_op_example.cc")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain")


@pytest.fixture(scope="module")
def lib(tmp_path_factory):
    return cpp_extension.load(
        "paddle_tpu_custom_example", [SRC],
        build_directory=str(tmp_path_factory.mktemp("ext")))


def test_custom_op_forward(lib):
    axpby = cpp_extension.custom_op(lib, "Axpby")
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    y = paddle.to_tensor(np.ones(8, np.float32))
    out = axpby(x, y, a=np.float32(2.0), b=np.float32(3.0))
    np.testing.assert_allclose(out.numpy(), 2.0 * x.numpy() + 3.0)


def test_custom_op_backward(lib):
    scale = cpp_extension.custom_op(lib, "Scale")

    def axpby_grad(residuals, g, a, b):
        # backward composed from another custom C++ kernel
        return (scale(g, c=a), scale(g, c=b))

    axpby = cpp_extension.custom_op(lib, "Axpby", backward=axpby_grad)
    x = paddle.to_tensor(np.arange(8, dtype=np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones(8, np.float32), stop_gradient=False)
    out = axpby(x, y, a=np.float32(2.0), b=np.float32(3.0))
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 2.0)
    np.testing.assert_allclose(y.grad.numpy(), 3.0)


def test_custom_op_under_jit(lib):
    """Custom calls must survive jit tracing (the reference's static-graph
    custom-op path)."""
    import jax
    import jax.numpy as jnp

    axpby = cpp_extension.custom_op(lib, "Axpby", name="axpby_jit")

    @jax.jit
    def f(xv, yv):
        t = axpby(paddle.Tensor(xv), paddle.Tensor(yv),
                  a=np.float32(1.5), b=np.float32(0.5))
        return t._value + 1.0

    out = f(jnp.ones(4), jnp.ones(4))
    np.testing.assert_allclose(np.asarray(out), 3.0)


def test_build_cache_and_rebuild(lib, tmp_path):
    # same name returns the cached library object
    lib2 = cpp_extension.load("paddle_tpu_custom_example", [SRC])
    assert lib2 is lib
