"""Launcher restart + elastic manager (reference:
python/paddle/distributed/launch/controllers/collective.py:22-150,
launch/controllers/watcher.py, fleet/elastic/manager.py:125)."""

import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(tmp_path, extra_args, script_body):
    script = os.path.join(tmp_path, "train.py")
    with open(script, "w") as f:
        f.write(script_body)
    env = {
        "PYTHONPATH": REPO,
        "PATH": os.environ.get("PATH", ""),
        "HOME": os.environ.get("HOME", "/root"),
        "JAX_PLATFORMS": "cpu",
        "SENTINEL": os.path.join(tmp_path, "sentinel"),
    }
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         f"--log_dir={tmp_path}/log", *extra_args, script],
        env=env, capture_output=True, text=True, timeout=240, cwd=tmp_path)
    return proc


CRASH_ONCE = """
import os, sys
s = os.environ["SENTINEL"]
if not os.path.exists(s):
    open(s, "w").write("x")
    print("FatalError: injected first-run crash", flush=True)
    sys.exit(17)
print("restart_count=", os.environ.get("PADDLE_RESTART_COUNT"), flush=True)
print("OK", flush=True)
"""


def test_launcher_restarts_failed_pod(tmp_path):
    """Kill-one-child-and-observe-restart (VERDICT done-criterion): the
    first run exits 17; with --max_restart the pod respawns and succeeds."""
    proc = _run_launch(tmp_path, ["--max_restart=2"], CRASH_ONCE)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "restart 1/2" in proc.stderr
    assert "fatal log" in proc.stderr  # LogWatcher surfaced the error line
    logs = os.listdir(os.path.join(tmp_path, "log"))
    assert any(l.endswith(".r0") for l in logs)
    assert any(l.endswith(".r1") for l in logs)
    r1 = [l for l in logs if l.endswith(".r1")][0]
    out = open(os.path.join(tmp_path, "log", r1)).read()
    assert "restart_count= 1" in out and "OK" in out


def test_launcher_exhausts_restarts(tmp_path):
    proc = _run_launch(tmp_path, ["--max_restart=1"], """
import sys
sys.exit(9)
""")
    assert proc.returncode == 9
    assert "restarts exhausted" in proc.stderr


def test_launcher_no_restart_by_default(tmp_path):
    proc = _run_launch(tmp_path, [], """
import sys
sys.exit(5)
""")
    assert proc.returncode == 5
    assert "restart 1" not in proc.stderr


def test_nnodes_range_implies_restart(tmp_path):
    proc = _run_launch(tmp_path, ["--nnodes=1:2"], CRASH_ONCE)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "restart 1/3" in proc.stderr


def test_fatal_log_tears_down_hung_worker(tmp_path):
    """A worker that logs a fatal line but HANGS (the classic stuck-
    collective failure) must be torn down by the log watcher, not waited on
    forever (reference launch/controllers/watcher.py)."""
    t0 = time.time()
    proc = _run_launch(tmp_path, [], """
import time
print("FatalError: poisoned collective", flush=True)
time.sleep(120)
""")
    assert proc.returncode != 0
    assert time.time() - t0 < 60, "watcher did not tear down the hung worker"
    assert "fatal log" in proc.stderr


def test_elastic_manager_liveness():
    from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus
    from paddle_tpu.distributed.store import TCPStore

    srv = TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
    try:
        m0 = ElasticManager(store=srv, job_id="j", np_range="1:2", rank=0,
                            timeout=1.5)
        assert m0.enable
        # nothing has heartbeat yet -> below min -> HOLD
        assert m0.alive_nodes() == []
        assert m0.watch() == ElasticStatus.HOLD
        m0.heartbeat()
        assert m0.alive_nodes() == [0]
        assert m0.is_ready()
        # one node in a 1:2 range -> can still scale up -> RESTART signal
        assert m0.watch() == ElasticStatus.RESTART
        m1 = ElasticManager(store=srv, job_id="j", np_range="1:2", rank=1,
                            timeout=1.5)
        m1.heartbeat()
        assert sorted(m0.alive_nodes()) == [0, 1]
        assert m0.watch() == ElasticStatus.OK  # healthy full cluster
        # rank-1 death: heartbeat ages out -> back to RESTART
        time.sleep(1.6)
        m0.heartbeat()
        assert m0.alive_nodes() == [0]
        assert m0.watch() == ElasticStatus.RESTART
        m0.exit(completed=True)
        assert m0.alive_nodes() == []
        assert m0.watch() == ElasticStatus.COMPLETED
    finally:
        srv.close()
