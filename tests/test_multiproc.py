"""Multi-process correctness harness: spawn N real controller processes on
localhost over jax.distributed (CPU backend, one device each) + the native
TCPStore, and assert eager collective parity and DP train-step parity.

Reference analog: the spawn-on-localhost harness
test/legacy_test/test_parallel_dygraph_dataparallel.py:161
(start_local_trainers) driving per-rank bodies with NCCL over TCP rendezvous.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_world(world, timeout=300):
    coord, store = _free_port(), _free_port()
    procs = []
    for rank in range(world):
        env = {
            # PYTHONPATH override drops the axon sitecustomize so the CPU
            # backend initializes without the TPU tunnel
            "PYTHONPATH": REPO,
            "PATH": os.environ.get("PATH", ""),
            "HOME": os.environ.get("HOME", "/root"),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{coord}",
            "PADDLE_MASTER": f"127.0.0.1:{store}",
            "WORLD_SIZE": str(world),
            "RANK": str(rank),
        }
        procs.append(subprocess.Popen(
            [sys.executable, "-u", os.path.join(REPO, "tests", "multiproc_worker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


@pytest.mark.parametrize("world", [2, 4])
def test_multiprocess_collectives_and_dp_parity(world):
    procs, outs = _spawn_world(world)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{out[-4000:]}"
    # every rank converged on the same loss trajectory
    losses = {}
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        rec = json.loads(line)
        losses[rec["rank"]] = rec["losses"]
    assert set(losses) == set(range(world))
    ref = losses[0]
    for r in range(1, world):
        assert losses[r] == pytest.approx(ref, rel=1e-5)
