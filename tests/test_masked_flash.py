"""Interpret-mode parity for the flashmask / varlen / decode Pallas kernels
vs jnp oracles (reference OpTest pattern, test/legacy_test/op_test.py:418;
kernel analogs: paddle/phi/kernels/gpu/flash_attn_kernel.cu:832 flashmask and
varlen params, fusion/gpu/block_attn.h, masked_multihead_attention_kernel.cu).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.masked_flash import (
    flashmask_attention_fwd,
    varlen_flash_attention_fwd,
)
from paddle_tpu.ops.pallas.decode_attention import (
    dense_decode_attention,
    paged_decode_attention,
)


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret_unless_hw):
    pass


# --------------------------------------------------------------------------- #
# flashmask
# --------------------------------------------------------------------------- #


def _flashmask_keep_ref(idx, Sq, Sk, causal):
    """[B, Hm, Sq, Sk] keep-mask from startend_row_indices [B, Hm, Sk, n]."""
    B, Hm, _, n = idx.shape
    rows = np.arange(Sq)[:, None]  # query row
    idx = np.moveaxis(np.asarray(idx), 2, 3)  # [B, Hm, n, Sk]
    if causal:
        start = idx[:, :, 0][:, :, None, :]
        if n == 1:
            masked = rows[None, None] >= start
        else:
            end = idx[:, :, 1][:, :, None, :]
            masked = (rows[None, None] >= start) & (rows[None, None] < end)
    else:
        if n == 2:
            lts = idx[:, :, 0][:, :, None, :]
            ute = idx[:, :, 1][:, :, None, :]
            masked = (rows[None, None] >= lts) | (rows[None, None] < ute)
        else:
            lts = idx[:, :, 0][:, :, None, :]
            lte = idx[:, :, 1][:, :, None, :]
            uts = idx[:, :, 2][:, :, None, :]
            ute = idx[:, :, 3][:, :, None, :]
            masked = ((rows[None, None] >= lts) & (rows[None, None] < lte)) | (
                (rows[None, None] >= uts) & (rows[None, None] < ute)
            )
    keep = ~masked
    if causal:
        keep = keep & np.tril(np.ones((Sq, Sk), bool))[None, None]
    return keep


def _masked_ref(q, k, v, keep):
    """q [B,S,H,D], keep [B,Hm,Sq,Sk] -> [B,S,H,D]; rows w/ no kept key -> 0."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    Hm = keep.shape[1]
    if Hm != H:
        keep = jnp.repeat(jnp.asarray(keep), H // Hm, axis=1)
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) / np.sqrt(D)
    logits = jnp.where(keep, logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    # fully-masked rows: softmax of all -1e30 is uniform garbage; zero them
    any_keep = jnp.any(keep, axis=-1, keepdims=True)
    p = jnp.where(any_keep, p, 0.0)
    return jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v).astype(q.dtype)


def _causal_doc_mask_idx(rng, B, Hm, S, n):
    """Document-mask style indices for the causal encodings; a distinct doc
    boundary per (batch, mask-head) so the idx BlockSpec index_map is
    actually exercised."""
    idx = np.empty((B, Hm, S, n), np.int32)
    cols = np.arange(S)
    for b in range(B):
        for hm in range(Hm):
            # split S into 2 docs at a random boundary; attention per doc
            cut = int(rng.integers(S // 4, 3 * S // 4))
            # rows >= start masked: start = doc end boundary per column
            start = np.where(cols < cut, cut, S)
            idx[b, hm, :, 0] = start
            if n == 2:
                idx[b, hm, :, 1] = S  # mask [start, S)
    return jnp.asarray(idx)


FM_CASES = [
    # B, S, H, Hkv, Hm, D, causal, n
    (1, 128, 4, 4, 1, 64, True, 1),
    (1, 256, 4, 2, 1, 64, True, 2),   # GQA
    (2, 128, 4, 4, 4, 32, True, 2),   # per-head mask
    (1, 128, 2, 2, 1, 64, False, 2),
    (1, 100, 2, 2, 1, 32, False, 4),  # padding path
]


@pytest.mark.parametrize("B,S,H,Hkv,Hm,D,causal,n", FM_CASES)
def test_flashmask_parity(B, S, H, Hkv, Hm, D, causal, n):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, Hkv, D)), jnp.float32)
    if causal:
        idx = _causal_doc_mask_idx(rng, B, Hm, S, n)
    else:
        if n == 2:
            lts = rng.integers(S // 2, S, (B, Hm, S, 1))
            ute = rng.integers(0, S // 2, (B, Hm, S, 1))
            idx = jnp.asarray(np.concatenate([lts, ute], -1).astype(np.int32))
        else:
            lts = rng.integers(0, S // 2, (B, Hm, S, 1))
            lte = lts + rng.integers(0, S // 4, (B, Hm, S, 1))
            uts = rng.integers(S // 2, S, (B, Hm, S, 1))
            ute = uts + rng.integers(0, S // 4, (B, Hm, S, 1))
            idx = jnp.asarray(
                np.concatenate([lts, lte, uts, ute], -1).astype(np.int32))

    keep = _flashmask_keep_ref(np.asarray(idx), S, S, causal)
    out = flashmask_attention_fwd(q, k, v, idx, causal=causal)
    ref = _masked_ref(q, k, v, keep)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=2e-5)

    g = jnp.asarray(rng.standard_normal(out.shape), jnp.float32)
    gq, gk, gv = jax.grad(
        lambda a, b, c: (flashmask_attention_fwd(a, b, c, idx, causal=causal) * g).sum(),
        (0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(
        lambda a, b, c: (_masked_ref(a, b, c, keep) * g).sum(), (0, 1, 2))(q, k, v)
    np.testing.assert_allclose(gq, rq, rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(gk, rk, rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(gv, rv, rtol=1e-3, atol=2e-4)


def test_flashmask_functional_dispatch():
    """nn.functional.flashmask_attention routes to the kernel under interpret
    mode and matches its own jnp fallback path."""
    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F
    from paddle_tpu.nn.functional.flash_attention import sdp_kernel

    rng = np.random.default_rng(3)
    S = 128
    q = paddle.to_tensor(rng.standard_normal((1, S, 2, 32)).astype("float32"),
                         stop_gradient=False)
    k = paddle.to_tensor(rng.standard_normal((1, S, 2, 32)).astype("float32"))
    v = paddle.to_tensor(rng.standard_normal((1, S, 2, 32)).astype("float32"))
    idx = paddle.to_tensor(
        np.full((1, 1, S, 1), S, np.int32))  # nothing extra masked
    out = F.flashmask_attention(q, k, v, startend_row_indices=idx, causal=True)
    with sdp_kernel(enable_flash=False):
        ref = F.flashmask_attention(q, k, v, startend_row_indices=idx, causal=True)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4, atol=2e-5)
    out.sum().backward()
    assert np.isfinite(q.grad.numpy()).all()


# --------------------------------------------------------------------------- #
# varlen
# --------------------------------------------------------------------------- #


def _varlen_ref(q, k, v, cq, ck, scale, causal):
    Tq, H, D = q.shape
    Tk = k.shape[0]
    Hkv = k.shape[1]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=1)
        v = jnp.repeat(v, H // Hkv, axis=1)
    cq = np.asarray(cq)
    ck = np.asarray(ck)
    seg_q = np.cumsum(np.bincount(cq[1:-1], minlength=Tq))[:Tq]
    seg_k = np.cumsum(np.bincount(ck[1:-1], minlength=Tk))[:Tk]
    mask = seg_q[:, None] == seg_k[None, :]
    if causal:
        pos_q = np.arange(Tq) - cq[seg_q]
        pos_k = np.arange(Tk) - ck[seg_k]
        mask = mask & (pos_q[:, None] >= pos_k[None, :])
    logits = jnp.einsum("qhd,khd->hqk", q, k).astype(jnp.float32) * scale
    logits = jnp.where(jnp.asarray(mask)[None], logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return jnp.einsum("hqk,khd->qhd", p.astype(v.dtype), v).astype(q.dtype)


VL_CASES = [
    # q seqlens, k seqlens, H, Hkv, D, causal
    ([60, 68], None, 4, 4, 64, True),
    ([33, 50, 45], None, 4, 2, 32, True),   # GQA, unaligned boundaries
    ([100, 156], None, 2, 2, 64, False),
    ([7, 9, 11], None, 2, 1, 32, True),     # tiny, single block
    ([40, 60], [90, 30], 2, 2, 32, False),  # cross: q lens != k lens
]


@pytest.mark.parametrize("lens_q,lens_k,H,Hkv,D,causal", VL_CASES)
def test_varlen_parity(lens_q, lens_k, H, Hkv, D, causal):
    rng = np.random.default_rng(1)
    lens_k = lens_k or lens_q
    Tq, Tk = sum(lens_q), sum(lens_k)
    cq = np.concatenate([[0], np.cumsum(lens_q)]).astype(np.int32)
    ck = np.concatenate([[0], np.cumsum(lens_k)]).astype(np.int32)
    q = jnp.asarray(rng.standard_normal((Tq, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((Tk, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((Tk, Hkv, D)), jnp.float32)
    scale = 1.0 / np.sqrt(D)
    cq_j, ck_j = jnp.asarray(cq), jnp.asarray(ck)

    out = varlen_flash_attention_fwd(q, k, v, cq_j, ck_j, scale, causal=causal)
    ref = _varlen_ref(q, k, v, cq, ck, scale, causal)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=2e-5)

    g = jnp.asarray(rng.standard_normal(out.shape), jnp.float32)
    gq, gk, gv = jax.grad(
        lambda a, b, c: (varlen_flash_attention_fwd(
            a, b, c, cq_j, ck_j, scale, causal=causal) * g).sum(), (0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(
        lambda a, b, c: (_varlen_ref(a, b, c, cq, ck, scale, causal) * g).sum(),
        (0, 1, 2))(q, k, v)
    np.testing.assert_allclose(gq, rq, rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(gk, rk, rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(gv, rv, rtol=1e-3, atol=2e-4)


# --------------------------------------------------------------------------- #
# decode (dense MMHA-analog and paged)
# --------------------------------------------------------------------------- #


def _decode_ref(q, kc, vc, lengths):
    """q [B,H,D]; kc/vc [B,Hkv,S,D]; lengths [B] -> [B,H,D]."""
    B, H, D = q.shape
    Hkv, S = kc.shape[1], kc.shape[2]
    if Hkv != H:
        kc = jnp.repeat(kc, H // Hkv, axis=1)
        vc = jnp.repeat(vc, H // Hkv, axis=1)
    logits = jnp.einsum("bhd,bhsd->bhs", q, kc).astype(jnp.float32) / np.sqrt(D)
    keep = jnp.arange(S)[None, None, :] < jnp.asarray(lengths)[:, None, None]
    logits = jnp.where(keep, logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhs,bhsd->bhd", p.astype(vc.dtype), vc).astype(q.dtype)


@pytest.mark.parametrize("B,H,Hkv,D,S", [
    (2, 4, 4, 64, 256),
    (3, 8, 2, 64, 512),   # GQA
    (1, 4, 1, 128, 128),  # MQA
])
def test_dense_decode_parity(B, H, Hkv, D, S):
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    lens = jnp.asarray(rng.integers(1, S + 1, B).astype(np.int32))
    out = dense_decode_attention(q, kc, vc, lens)
    np.testing.assert_allclose(out, _decode_ref(q, kc, vc, lens),
                               rtol=1e-4, atol=2e-5)


@pytest.mark.parametrize("B,H,Hkv,D,ps,P", [
    (2, 4, 4, 64, 64, 4),
    (2, 8, 2, 64, 128, 3),  # GQA, non-pow2 page count
])
def test_paged_decode_parity(B, H, Hkv, D, ps, P):
    rng = np.random.default_rng(4)
    n_pages = B * P + 2
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((n_pages, Hkv, ps, D)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((n_pages, Hkv, ps, D)), jnp.float32)
    lens = rng.integers(1, ps * P + 1, B).astype(np.int32)
    # random non-overlapping physical pages; unused slots -1
    perm = rng.permutation(n_pages)[: B * P].reshape(B, P)
    used = (np.arange(P)[None] * ps) < lens[:, None]
    tables = np.where(used, perm, -1).astype(np.int32)

    out = paged_decode_attention(q, kc, vc, jnp.asarray(tables),
                                 jnp.asarray(lens))

    # oracle: gather each row's logical cache densely
    gk = np.zeros((B, Hkv, ps * P, D), np.float32)
    gv = np.zeros((B, Hkv, ps * P, D), np.float32)
    for b in range(B):
        for p in range(P):
            if tables[b, p] >= 0:
                gk[b, :, p * ps:(p + 1) * ps] = np.asarray(kc[tables[b, p]])
                gv[b, :, p * ps:(p + 1) * ps] = np.asarray(vc[tables[b, p]])
    ref = _decode_ref(q, jnp.asarray(gk), jnp.asarray(gv), jnp.asarray(lens))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=2e-5)


def test_flashmask_bf16_parity():
    """bf16 operands (the AMP O2 path — round-5 made the kernels feed the
    MXU native dtypes, so the casts are no longer no-ops under f32)."""
    rng = np.random.default_rng(7)
    B, S, H, D, n = 1, 128, 2, 32, 2
    qf = rng.standard_normal((B, S, H, D)).astype(np.float32) * 0.5
    kf = rng.standard_normal((B, S, H, D)).astype(np.float32) * 0.5
    vf = rng.standard_normal((B, S, H, D)).astype(np.float32) * 0.5
    idx = _causal_doc_mask_idx(rng, B, 1, S, n)
    q, k, v = (jnp.asarray(x, jnp.bfloat16) for x in (qf, kf, vf))
    keep = _flashmask_keep_ref(np.asarray(idx), S, S, True)
    out = flashmask_attention_fwd(q, k, v, idx, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = _masked_ref(jnp.asarray(np.asarray(q, np.float32)),
                      jnp.asarray(np.asarray(k, np.float32)),
                      jnp.asarray(np.asarray(v, np.float32)), keep)
    # bf16 tolerance: ~8 mantissa bits
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.06, atol=0.06)
    g = jnp.ones(out.shape, jnp.bfloat16)
    gq, gk, gv = jax.grad(
        lambda a, b, c: (flashmask_attention_fwd(a, b, c, idx, causal=True)
                         .astype(jnp.float32) * g.astype(jnp.float32)).sum(),
        (0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(
        lambda a, b, c: (_masked_ref(a, b, c, keep)).sum(), (0, 1, 2))(
        jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf))
    for got, want, name in ((gq, rq, "dq"), (gk, rk, "dk"), (gv, rv, "dv")):
        d = np.abs(np.asarray(got, np.float32) - np.asarray(want)).max()
        assert d < 0.08, (name, d)
