"""Unified telemetry layer tests: metrics registry semantics, exporter
golden formats, span nesting + chrome-trace round-trip, StepTimeline
stitching, chained-hook coexistence with the graftlint runtime, flight
recorder post-mortems (incl. dump-on-injected-crash through the fault
harness), and the Model.fit acceptance run where the step-timeline JSONL
sync counts must agree with the graftlint runtime report."""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.distributed import comm_watchdog
from paddle_tpu.framework import core
from paddle_tpu.observability import flight, metrics, spans


@pytest.fixture
def registry():
    reg = metrics.reset_default_registry()
    yield reg
    metrics.reset_default_registry()


@pytest.fixture
def recorder():
    rec = flight.reset_recorder()
    yield rec
    flight.reset_recorder()
    flight.uninstall_crash_handlers()


@pytest.fixture
def timeline(registry):
    tl = obs.enable_step_timeline()
    yield tl
    tl.uninstall()


# --------------------------------------------------------------------------- #
# registry semantics
# --------------------------------------------------------------------------- #


class TestRegistry:
    def test_counter_labels_and_monotonicity(self, registry):
        c = registry.counter("req_total", "requests", ("op",))
        c.inc(op="a")
        c.inc(2.5, op="a")
        c.inc(op="b")
        assert c.value(op="a") == 3.5
        assert c.value(op="b") == 1.0
        with pytest.raises(ValueError):
            c.inc(-1, op="a")
        with pytest.raises(ValueError):
            c.inc(op="a", extra="nope")  # undeclared label

    def test_gauge_set_inc_dec(self, registry):
        g = registry.gauge("depth")
        g.set(4)
        g.inc()
        g.dec(2)
        assert g.value() == 3.0

    def test_histogram_buckets_sum_count_mean(self, registry):
        h = registry.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 5
        assert h.sum() == pytest.approx(56.05)
        assert h.mean() == pytest.approx(56.05 / 5)
        sample = [s for s in registry.collect() if s["metric"] == "lat"][0]
        # per-bucket (non-cumulative) counts as collected
        assert sample["buckets"] == {"0.1": 1, "1.0": 2, "10.0": 1}
        assert sample["count"] == 5  # 50.0 overflows to +Inf only

    def test_redeclare_same_family_ok_mismatch_rejected(self, registry):
        c1 = registry.counter("x_total", "x", ("op",))
        c2 = registry.counter("x_total", "x", ("op",))
        assert c1 is c2
        with pytest.raises(ValueError):
            registry.gauge("x_total")
        with pytest.raises(ValueError):
            registry.counter("x_total", labelnames=("other",))
        h1 = registry.histogram("h", buckets=(1.0, 2.0))
        assert registry.histogram("h", buckets=(2.0, 1.0)) is h1  # same set
        with pytest.raises(ValueError):
            registry.histogram("h", buckets=(0.5, 2.0))

    def test_snapshot_delta(self, registry):
        c = registry.counter("n_total")
        g = registry.gauge("g")
        c.inc(3)
        g.set(7)
        snap = registry.snapshot()
        c.inc(2)
        g.set(1)
        d = registry.delta(snap)
        assert d["n_total"] == 2
        assert d["g"] == 1  # gauges report current value, not a diff


# --------------------------------------------------------------------------- #
# exporters (golden formats)
# --------------------------------------------------------------------------- #


class TestExporters:
    def _fill(self, reg):
        c = reg.counter("rpc_total", "rpc calls", ("op",))
        c.inc(3, op="all_reduce")
        g = reg.gauge("queue_depth")
        g.set(2)
        h = reg.histogram("step_seconds", "per-step", buckets=(0.5, 2.0))
        h.observe(0.25)
        h.observe(1.0)
        h.observe(9.0)

    def test_prometheus_text_golden(self, registry):
        self._fill(registry)
        assert registry.prometheus_text() == (
            "# HELP rpc_total rpc calls\n"
            "# TYPE rpc_total counter\n"
            'rpc_total{op="all_reduce"} 3\n'
            "# TYPE queue_depth gauge\n"
            "queue_depth 2\n"
            "# HELP step_seconds per-step\n"
            "# TYPE step_seconds histogram\n"
            'step_seconds_bucket{le="0.5"} 1\n'
            'step_seconds_bucket{le="2"} 2\n'      # cumulative
            'step_seconds_bucket{le="+Inf"} 3\n'
            "step_seconds_sum 10.25\n"
            "step_seconds_count 3\n"
        )

    def test_jsonl_events_golden(self, registry, tmp_path):
        self._fill(registry)
        lines = registry.jsonl_events(ts=0)
        docs = [json.loads(ln) for ln in lines]
        assert docs[0] == {"ts": 0, "metric": "rpc_total", "type": "counter",
                           "labels": {"op": "all_reduce"}, "value": 3}
        hist = [d for d in docs if d["metric"] == "step_seconds"][0]
        assert hist["count"] == 3 and hist["sum"] == 10.25
        assert hist["buckets"] == {"0.5": 1, "2.0": 1}
        # file export appends parseable lines
        path = tmp_path / "m.jsonl"
        registry.export_jsonl(str(path), ts=0)
        registry.export_jsonl(str(path), ts=1)
        on_disk = [json.loads(ln) for ln in path.read_text().splitlines()]
        assert len(on_disk) == 2 * len(docs)


# --------------------------------------------------------------------------- #
# spans
# --------------------------------------------------------------------------- #


class TestSpans:
    def test_nesting_paths_and_decorator(self, timeline):
        @obs.span("inner_fn")
        def work():
            return 1

        timeline.step_begin(0)
        with obs.span("fwd"):
            with obs.span("attn"):
                pass
            work()
        rec = timeline.step_end()
        names = [(s["name"], s["depth"]) for s in rec["spans"]]
        # children close before parents (exit order)
        assert ("fwd/attn", 1) in names
        assert ("fwd/inner_fn", 1) in names
        assert ("fwd", 0) in names
        assert all(s["dur_s"] >= 0 for s in rec["spans"])

    def test_chrome_trace_round_trip(self, tmp_path):
        from paddle_tpu.profiler import Profiler

        p = Profiler()
        p.start()
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        with obs.span("obs_step"):
            with obs.span("obs_fwd"):
                _ = (x + x).sum()
        p.stop()
        path = str(tmp_path / "trace.json")
        p.export(path)
        doc = json.load(open(path))
        byname = {e["name"]: e for e in doc["traceEvents"]}
        assert byname["obs_step"]["cat"] == "observability"
        assert "obs_step/obs_fwd" in byname
        # spans share the timeline with op dispatch events
        assert any(e["cat"] == "operator" for e in doc["traceEvents"])


# --------------------------------------------------------------------------- #
# StepTimeline stitching
# --------------------------------------------------------------------------- #


class TestStepTimeline:
    def test_stitches_syncs_comm_tasks_dispatch(self, timeline):
        x = paddle.to_tensor(np.ones((8,), np.float32))
        timeline.step_begin(7)
        with obs.span("fwd"):
            y = (x * 2.0).sum()
        with comm_watchdog.comm_task("allreduce/7"):
            time.sleep(0.01)
        _ = float(y)      # sync 1
        _ = y.numpy()     # sync 2
        rec = timeline.step_end(extra={"loss": 1.0})

        assert rec["step"] == 7 and rec["loss"] == 1.0
        assert rec["host_syncs"] == 2
        assert rec["sync_kinds"] == {"float": 1, "array": 1}
        assert [t["desc"] for t in rec["comm_tasks"]] == ["allreduce/7"]
        assert rec["comm_tasks"][0]["dur_s"] >= 0.01
        # ops ran through the eager dispatch cache during the step
        d = rec["dispatch"]
        assert d["hits"] + d["misses"] + d["bypass"] >= 2
        assert rec["dur_s"] > 0
        assert timeline.records[-1] is rec

    def test_interstep_syncs_and_totals(self, timeline):
        x = paddle.to_tensor(np.ones((2,), np.float32))
        timeline.step_begin(0)
        _ = float(x.sum())
        timeline.step_end()
        _ = float(x.sum())  # between steps
        timeline.step_begin(1)
        timeline.step_end()
        assert timeline.interstep_syncs == 1
        assert timeline.total_host_syncs() == 2

    def test_total_syncs_survive_ring_eviction(self, registry):
        tl = spans.StepTimeline(keep=2).install()
        try:
            x = paddle.to_tensor(np.ones((2,), np.float32))
            for i in range(5):
                tl.step_begin(i)
                _ = float(x.sum())
                tl.step_end()
        finally:
            tl.uninstall()
        assert len(tl.records) == 2  # ring evicted steps 0-2...
        assert tl.total_host_syncs() == 5  # ...but the total kept counting

    def test_jsonl_output(self, registry, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        tl = obs.enable_step_timeline(jsonl_path=path)
        try:
            for i in range(3):
                tl.step_begin(i)
                tl.step_end()
        finally:
            tl.uninstall()
        recs = [json.loads(ln) for ln in open(path)]
        assert [r["step"] for r in recs] == [0, 1, 2]

    def test_fleet_summary_over_store(self, registry):
        class FakeStore:
            def __init__(self):
                self.kv = {}

            def set(self, k, v):
                self.kv[k] = v.encode() if isinstance(v, str) else v

            def tryget(self, k):
                return self.kv.get(k)

        store = FakeStore()
        base = {"sync_kinds": {}, "comm_tasks": [], "spans": [],
                "dispatch": {"hits": 4, "misses": 1, "bypass": 0},
                "t_wall": 0.0}
        obs.publish_step_record(
            store, 0, {**base, "step": 3, "dur_s": 0.10, "host_syncs": 1})
        obs.publish_step_record(
            store, 1, {**base, "step": 3, "dur_s": 0.30, "host_syncs": 2,
                       "comm_tasks": [{"desc": "ar", "dur_s": 0.05}]})
        s = obs.fleet_step_summary(store, world_size=2, step=3)
        assert s["ranks"] == 2 and s["step"] == 3
        assert s["step_time_s"]["max"] == 0.30
        assert s["step_time_s"]["mean"] == pytest.approx(0.20)
        assert s["straggler_rank"] == 1
        assert s["host_syncs"] == 3
        assert s["comm_task_s"] == pytest.approx(0.05)
        assert s["dispatch"]["hits"] == 8

    def test_fleet_summary_times_out_on_missing_rank(self, registry):
        class EmptyStore:
            def tryget(self, k):
                return None

        with pytest.raises(TimeoutError):
            obs.fleet_step_summary(EmptyStore(), world_size=1, step=0,
                                   timeout=0.05)


# --------------------------------------------------------------------------- #
# chained hooks + graftlint runtime coexistence
# --------------------------------------------------------------------------- #


class TestChainedHooks:
    def test_set_returns_previous_base(self):
        prev0 = core.set_sync_observer(None)
        try:
            a = lambda k, t: None  # noqa: E731
            assert core.set_sync_observer(a) is None
            assert core.set_sync_observer(None) is a
        finally:
            core.set_sync_observer(prev0)

    def test_add_remove_compose_with_base(self):
        seen = []
        prev0 = core.set_sync_observer(lambda k, t: seen.append(("base", k)))
        obs_fn = core.add_sync_observer(lambda k, t: seen.append(("chain", k)))
        try:
            x = paddle.to_tensor(np.ones((2,), np.float32))
            _ = float(x.sum())
            assert ("base", "float") in seen and ("chain", "float") in seen
        finally:
            core.remove_sync_observer(obs_fn)
            core.set_sync_observer(prev0)

    def test_interceptor_chain_composes_with_base(self):
        calls = []
        prev0 = core.set_op_input_interceptor(None)
        icp = core.add_op_input_interceptor(
            lambda name, values: calls.append(name) or values)
        try:
            x = paddle.to_tensor(np.ones((2,), np.float32))
            _ = x + x
            assert "add" in calls
        finally:
            core.remove_op_input_interceptor(icp)
            core.set_op_input_interceptor(prev0)

    def test_graftlint_runtime_and_timeline_coexist(self, registry):
        """GRAFTLINT_RUNTIME=1 semantics + telemetry together: the runtime
        check still raises on an in-trace sync, the timeline still counts
        every sync, and uninstalling either leaves the other working."""
        from tools.graftlint import runtime as rt

        rt.install_runtime_checks("raise")
        tl = obs.enable_step_timeline()
        rt.reset_runtime_events()
        try:
            x = paddle.to_tensor(np.ones((3,), np.float32))
            tl.step_begin(0)
            _ = float(x.sum())  # eager sync: allowed, counted by both
            rec = tl.step_end()
            assert rec["host_syncs"] == 1
            assert rt.runtime_report()["host_syncs_total"] == 1

            with pytest.raises(rt.HostSyncInTraceError):
                with core.tracing_guard(True):
                    x.numpy()
            assert len(rt.runtime_report()["host_syncs_in_trace"]) == 1

            # removing the runtime checks must not detach the timeline
            rt.uninstall_runtime_checks()
            tl.step_begin(1)
            _ = float(x.sum())
            assert tl.step_end()["host_syncs"] == 1
        finally:
            rt.uninstall_runtime_checks()
            rt.reset_runtime_events()
            tl.uninstall()


# --------------------------------------------------------------------------- #
# watchdog report: peek vs drain
# --------------------------------------------------------------------------- #


class TestWatchdogReport:
    def test_peek_is_non_destructive_drain_consumes_once(self):
        comm_watchdog.disable()
        if not comm_watchdog.enable(timeout_seconds=5.0):
            pytest.skip("native watchdog unavailable")
        try:
            with comm_watchdog.comm_task("stuck/1", 0.1):
                time.sleep(0.4)
            deadline = time.time() + 3
            while time.time() < deadline and not comm_watchdog.peek_report():
                time.sleep(0.05)
            first_peek = comm_watchdog.peek_report()
            assert "stuck/1" in first_peek
            # peek again: unchanged (non-destructive)
            assert comm_watchdog.peek_report() == first_peek
            # drain hands out the text once...
            assert "stuck/1" in comm_watchdog.drain_report()
            assert comm_watchdog.drain_report() == ""
            # ...but peek still sees the retained history
            assert "stuck/1" in comm_watchdog.peek_report()

            events = comm_watchdog.report_events()
            assert events and events[0]["desc"] == "stuck/1"
            assert events[0]["timeout_ms"] == 100
            assert events[0]["elapsed_ms"] >= 100
        finally:
            comm_watchdog.disable()


# --------------------------------------------------------------------------- #
# flight recorder
# --------------------------------------------------------------------------- #


class TestFlightRecorder:
    def test_ring_bounded_and_dump_contents(self, registry, recorder,
                                            tmp_path):
        rec = flight.FlightRecorder(capacity=3)
        for i in range(5):
            rec.record_step({"step": i, "dur_s": 0.01, "host_syncs": 0})
        assert [s["step"] for s in rec.steps] == [2, 3, 4]
        rec.note("checkpoint_save", step=4)
        registry.counter("c_total").inc(2)
        path = str(tmp_path / "fl.json")
        out = rec.dump(path, reason="unit test")
        assert out == path
        doc = json.loads(open(path).read().splitlines()[-1])
        assert doc["reason"] == "unit test"
        assert [s["step"] for s in doc["steps"]] == [2, 3, 4]
        assert doc["events"][0]["kind"] == "checkpoint_save"
        assert doc["metric_deltas"]["c_total"] == 2
        assert "watchdog_report" in doc and "dispatch_cache" in doc

    def test_timeline_feeds_default_recorder(self, registry, recorder):
        tl = obs.enable_step_timeline()
        try:
            tl.step_begin(11)
            tl.step_end()
        finally:
            tl.uninstall()
        assert [s["step"] for s in recorder.steps] == [11]

    def test_dump_on_injected_crash(self, registry, recorder, tmp_path,
                                    monkeypatch, fault_injector):
        """The acceptance path: ResilientTrainer + armed fault point → the
        flight recorder post-mortem lands on disk with the dying step."""
        from paddle_tpu.distributed.faults import FaultInjected
        from paddle_tpu.distributed.resilience import ResilientTrainer

        fl_path = str(tmp_path / "worker.flight")
        monkeypatch.setenv("PADDLE_FLIGHT_FILE", fl_path)
        tl = obs.enable_step_timeline()
        w = paddle.to_tensor(np.zeros(4, np.float32))

        def step_fn(i):
            w.set_value(paddle.to_tensor(w.numpy() + 1.0))
            return float(w.numpy()[0])

        fault_injector.arm("trainer.before_step", "exc", nth=3)
        try:
            with pytest.raises(FaultInjected):
                ResilientTrainer(step_fn, {"w": w}, str(tmp_path / "ck"),
                                 save_every=2, async_save=False).run(6)
        finally:
            fault_injector.disarm()
            tl.uninstall()
            flight.uninstall_crash_handlers()
        doc = json.loads(open(fl_path).read().splitlines()[-1])
        assert "trainer crash at step 2" in doc["reason"]
        steps = [s["step"] for s in doc["steps"]]
        assert steps[-1] == 2  # the aborted step made it into the ring
        assert doc["steps"][-1].get("aborted") is True
        kinds = [e["kind"] for e in doc["events"]]
        assert "trainer_start" in kinds and "checkpoint_save" in kinds
        # trainer metrics made it into the dump's delta window
        assert any(k.startswith("trainer_step_seconds")
                   for k in doc["metric_deltas"])

    def test_sigterm_handler_chains_and_uninstalls(self, registry, recorder,
                                                   tmp_path):
        import signal

        calls = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: calls.append(s))
        try:
            path = str(tmp_path / "sig.flight")
            flight.install_crash_handlers(path)
            os.kill(os.getpid(), signal.SIGTERM)
            # give the interpreter a bytecode boundary to run the handler
            time.sleep(0.01)
            assert calls == [signal.SIGTERM]  # previous handler still ran
            doc = json.loads(open(path).read().splitlines()[-1])
            assert doc["reason"] == "SIGTERM"
        finally:
            flight.uninstall_crash_handlers()
            signal.signal(signal.SIGTERM, prev)


# --------------------------------------------------------------------------- #
# acceptance: Model.fit telemetry agrees with the graftlint runtime
# --------------------------------------------------------------------------- #


class TestFitTelemetry:
    def test_fit_jsonl_sync_counts_match_graftlint_runtime(self, registry,
                                                           tmp_path):
        """Single-process Model.fit with telemetry enabled: the JSONL step
        timeline's host-sync counts must agree with the graftlint runtime
        report for the same run — two independent observers on one chained
        hook, so a disagreement means a dropped observer."""
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        from tools.graftlint import runtime as rt

        net = nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(opt.SGD(learning_rate=0.01,
                              parameters=net.parameters()),
                      nn.MSELoss())
        rng = np.random.default_rng(0)
        data = [(rng.random((2, 4), np.float32).astype(np.float32),
                 rng.random((2, 2)).astype(np.float32)) for _ in range(6)]

        path = str(tmp_path / "fit_steps.jsonl")
        tl = obs.enable_step_timeline(jsonl_path=path)
        rt.install_runtime_checks("raise")  # fit must not sync under traces
        rt.reset_runtime_events()
        try:
            model.fit(data, epochs=1, log_freq=2, verbose=0)
        finally:
            rt.uninstall_runtime_checks()
            tl.uninstall()

        recs = [json.loads(ln) for ln in open(path)]
        assert len(recs) == 6
        # the loss scalar syncs exactly at log boundaries (steps 0, 2, 4)
        assert [r["host_syncs"] for r in recs] == [1, 0, 1, 0, 1, 0]
        assert [r["loss_synced"] for r in recs] == \
            [True, False, True, False, True, False]
        # per-step counts + between-step syncs (the epoch-mean float) must
        # equal what the graftlint runtime observer saw on the same run
        rep = rt.runtime_report()
        assert rep["host_syncs_in_trace"] == []
        total_from_timeline = (sum(r["host_syncs"] for r in recs)
                               + tl.interstep_syncs)
        assert total_from_timeline == rep["host_syncs_total"]
        assert tl.total_host_syncs() == rep["host_syncs_total"]

        # the registry's view agrees with the timeline's sync accounting
        assert registry.get("hapi_loss_sync_total").value() == 4  # 3 logs + epoch mean
        assert registry.get("hapi_train_steps_total").value() == 6
        assert registry.get("hapi_train_step_seconds").count() == 6
        # fit's spans are in the step records (inner spans exit first, so
        # the compiled-step compute span precedes the fit wrapper)
        names = [s["name"] for s in recs[0]["spans"]]
        assert "fit/train_batch" in names
        assert "fit/train_batch/train_step/compiled" in names
        rt.reset_runtime_events()


# --------------------------------------------------------------------------- #
# comm/compute overlap (ROADMAP item 2: the T3-style tracked-overlap metric)
# --------------------------------------------------------------------------- #


def _ct(start_s, dur_s, desc="rs", kind="comm"):
    return {"desc": desc, "kind": kind, "start_ns": int(start_s * 1e9),
            "dur_s": dur_s}


def _sp(start_s, dur_s, kind="compute", name="bwd"):
    rec = {"name": name, "depth": 0, "start_ns": int(start_s * 1e9),
           "dur_s": dur_s}
    if kind is not None:
        rec["attrs"] = {"kind": kind}
    return rec


class TestOverlapStats:
    def test_disjoint_comm_fully_exposed(self):
        ov = spans.overlap_stats([_ct(0.0, 0.1)], [_sp(0.2, 0.1)])
        assert ov["fraction"] == 0.0
        assert ov["comm_s"] == pytest.approx(0.1)
        assert ov["exposed_s"] == pytest.approx(0.1)
        assert ov["covered_s"] == 0.0

    def test_fully_covered_comm(self):
        ov = spans.overlap_stats([_ct(0.1, 0.1)], [_sp(0.0, 0.5)])
        assert ov["fraction"] == 1.0
        assert ov["exposed_s"] == 0.0

    def test_partial_overlap_exact_interval_math(self):
        # comm [0, 0.4); compute [0.3, 0.6) -> covered 0.1 of 0.4
        ov = spans.overlap_stats([_ct(0.0, 0.4)], [_sp(0.3, 0.3)])
        assert ov["fraction"] == pytest.approx(0.25)
        assert ov["covered_s"] == pytest.approx(0.1)
        assert ov["exposed_s"] == pytest.approx(0.3)

    def test_zero_comm_step_reports_one(self):
        ov = spans.overlap_stats([], [_sp(0.0, 1.0)])
        assert ov == {"fraction": 1.0, "comm_s": 0.0, "covered_s": 0.0,
                      "exposed_s": 0.0}

    def test_union_not_pairwise_sum(self):
        # two overlapping comm intervals: union is 0.3, not 0.4; two
        # overlapping compute spans covering [0.0, 0.25) -> covered 0.25
        comm = [_ct(0.0, 0.2), _ct(0.1, 0.2)]
        compute = [_sp(0.0, 0.15), _sp(0.1, 0.15)]
        ov = spans.overlap_stats(comm, compute)
        assert ov["comm_s"] == pytest.approx(0.3)
        assert ov["covered_s"] == pytest.approx(0.25)
        assert ov["fraction"] == pytest.approx(0.25 / 0.3)

    def test_step_kind_and_untagged_spans_excluded(self):
        # a deadline-only "step" region is not comm; an untagged (driver)
        # span wrapping everything is not compute
        comm = [_ct(0.0, 1.0, desc="train_step/3", kind="step"),
                _ct(0.2, 0.1)]
        compute = [_sp(0.0, 1.0, kind=None, name="fit/train_batch")]
        ov = spans.overlap_stats(comm, compute)
        assert ov["comm_s"] == pytest.approx(0.1)
        assert ov["fraction"] == 0.0

    def test_a2a_kind_joins_comm_union(self):
        # MoE all-to-all intervals (kind="a2a", ISSUE-14) are comm for the
        # overlap accounting; "step" stays excluded beside them
        comm = [_ct(0.0, 0.2, desc="moe/a2a/epx4[est]", kind="a2a"),
                _ct(0.1, 0.2),
                _ct(0.0, 1.0, desc="train_step/1", kind="step")]
        ov = spans.overlap_stats(comm, [_sp(0.0, 0.15)])
        assert ov["comm_s"] == pytest.approx(0.3)
        assert ov["covered_s"] == pytest.approx(0.15)
        assert "a2a" in spans.COMM_KINDS and "step" not in spans.COMM_KINDS

    def test_multi_interval_sweep(self):
        comm = [_ct(0.0, 0.1), _ct(0.2, 0.1), _ct(0.4, 0.1)]
        compute = [_sp(0.05, 0.2), _sp(0.45, 0.2)]
        ov = spans.overlap_stats(comm, compute)
        # covered: [0.05,0.1)=0.05 + [0.2,0.25)=0.05 + [0.45,0.5)=0.05
        assert ov["covered_s"] == pytest.approx(0.15)
        assert ov["fraction"] == pytest.approx(0.5)


class TestOverlapTimeline:
    def test_record_carries_overlap_and_metrics(self, timeline, registry):
        timeline.step_begin(0)
        with comm_watchdog.comm_task("rs/grads"):
            with obs.span("update", kind="compute"):
                time.sleep(0.01)
        rec = timeline.step_end()
        assert rec["overlap_fraction"] == rec["overlap"]["fraction"]
        assert rec["overlap"]["comm_s"] >= 0.01
        # the comm region is covered by the concurrent compute span
        assert rec["overlap_fraction"] > 0.5
        assert registry.get("step_overlap_fraction").value() == \
            rec["overlap_fraction"]
        assert registry.get("comm_overlapped_seconds_total").value() == \
            pytest.approx(rec["overlap"]["covered_s"])

    def test_exposed_comm_counted(self, timeline, registry):
        timeline.step_begin(1)
        with comm_watchdog.comm_task("allgather/params"):
            time.sleep(0.01)
        rec = timeline.step_end()
        assert rec["overlap_fraction"] == 0.0
        assert registry.get("comm_exposed_seconds_total").value() == \
            pytest.approx(rec["overlap"]["exposed_s"])

    def test_overlap_fraction_in_every_jsonl_record(self, registry,
                                                    tmp_path):
        path = str(tmp_path / "steps.jsonl")
        tl = obs.enable_step_timeline(jsonl_path=path)
        try:
            for i in range(3):
                tl.step_begin(i)
                if i == 1:
                    with comm_watchdog.comm_task("ar"):
                        time.sleep(0.002)
                tl.step_end()
        finally:
            tl.uninstall()
        recs = [json.loads(ln) for ln in open(path)]
        assert all("overlap_fraction" in r and "overlap" in r for r in recs)
        assert recs[0]["overlap_fraction"] == 1.0  # zero-comm step
        assert recs[1]["overlap_fraction"] == 0.0  # exposed comm

    def test_flight_records_carry_overlap(self, timeline, recorder,
                                          tmp_path):
        timeline.step_begin(5)
        timeline.step_end()
        path = recorder.dump(path=str(tmp_path / "flight.json"),
                             reason="test")
        doc = json.loads(open(path).read().strip().splitlines()[-1])
        steps = doc["steps"]
        assert steps and all("overlap_fraction" in r for r in steps)

    def test_fleet_summary_aggregates_overlap(self, registry):
        class FakeStore:
            def __init__(self):
                self.kv = {}

            def set(self, k, v):
                self.kv[k] = v.encode() if isinstance(v, str) else v

            def tryget(self, k):
                return self.kv.get(k)

        store = FakeStore()
        base = {"sync_kinds": {}, "comm_tasks": [], "spans": [],
                "dispatch": {"hits": 0, "misses": 0, "bypass": 0},
                "t_wall": 0.0, "host_syncs": 0}
        obs.publish_step_record(store, 0, {
            **base, "step": 1, "dur_s": 0.2,
            "overlap": {"fraction": 1.0, "comm_s": 0.1, "covered_s": 0.1,
                        "exposed_s": 0.0}})
        obs.publish_step_record(store, 1, {
            **base, "step": 1, "dur_s": 0.2,
            "overlap": {"fraction": 0.0, "comm_s": 0.1, "covered_s": 0.0,
                        "exposed_s": 0.1}})
        s = obs.fleet_step_summary(store, world_size=2, step=1)
        assert s["overlap"]["fraction"] == pytest.approx(0.5)
        assert s["overlap"]["comm_s"] == pytest.approx(0.2)
        assert s["overlap"]["exposed_s"] == pytest.approx(0.1)

    def test_comm_task_start_offset_relative_to_step(self, timeline):
        timeline.step_begin(0)
        time.sleep(0.005)
        with comm_watchdog.comm_task("late"):
            pass
        rec = timeline.step_end()
        assert rec["comm_tasks"][0]["start_ns"] >= 4_000_000
