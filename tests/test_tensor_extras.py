"""Tensor-op tail + generated in-place variants (reference:
python/paddle/tensor/ math/manipulation/linalg exports; `<op>_` family)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestExtras:
    def test_math_tail(self):
        x = _t(np.array([0.5, 1.5], np.float32))
        np.testing.assert_allclose(paddle.negative(x).numpy(), [-0.5, -1.5])
        np.testing.assert_allclose(paddle.positive(x).numpy(), x.numpy())
        s = paddle.add_n([x, x, x])
        np.testing.assert_allclose(s.numpy(), 3 * x.numpy())
        np.testing.assert_allclose(
            paddle.sgn(_t(np.array([-3.0, 0.0, 2.0], np.float32))).numpy(),
            [-1.0, 0.0, 1.0])

    def test_special_functions(self):
        import math

        x = _t(np.array([2.0, 3.0], np.float32))
        # gammaln(n) = log((n-1)!)
        np.testing.assert_allclose(paddle.gammaln(x).numpy(),
                                   [0.0, math.log(2.0)], atol=1e-5)
        s = paddle.sinc(_t(np.array([0.0, 0.5], np.float32)))
        np.testing.assert_allclose(s.numpy(), [1.0, 2 / np.pi], rtol=1e-5)
        assert bool(paddle.signbit(_t(np.array([-1.0], np.float32))).numpy()[0])

    def test_complex_family(self):
        pairs = _t(np.array([[1.0, 2.0], [3.0, -1.0]], np.float32))
        c = paddle.as_complex(pairs)
        assert paddle.is_complex(c)
        np.testing.assert_allclose(paddle.as_real(c).numpy(), pairs.numpy())
        p = paddle.polar(_t(np.array([1.0], np.float32)),
                         _t(np.array([np.pi / 2], np.float32)))
        np.testing.assert_allclose(np.imag(p.numpy()), [1.0], atol=1e-6)
        assert paddle.is_floating_point(pairs)
        assert paddle.is_integer(_t(np.array([1, 2])))

    def test_manipulation_tail(self):
        t = _t(np.arange(12, dtype=np.float32).reshape(3, 4))
        assert paddle.shape(t).numpy().tolist() == [3, 4]
        assert int(paddle.rank(t).numpy()) == 2
        assert paddle.broadcast_shape([3, 1], [1, 4]) == [3, 4]
        np.testing.assert_allclose(
            paddle.matrix_transpose(t).numpy(), t.numpy().T)
        np.testing.assert_allclose(
            paddle.reverse(t, axis=0).numpy(), t.numpy()[::-1])
        parts = paddle.tensor_split(_t(np.arange(10)), [3, 7])
        assert [p.shape[0] for p in parts] == [3, 4, 3]
        un = paddle.unflatten(_t(np.arange(12)), 0, [3, 4])
        assert tuple(un.shape) == (3, 4)
        pieces = paddle.unstack(t, axis=1)
        assert len(pieces) == 4 and tuple(pieces[0].shape) == (3,)

    def test_scatter_family(self):
        t = _t(np.zeros((3, 3), np.float32))
        out = paddle.index_fill(t, _t(np.array([0, 2])), 0, 5.0)
        np.testing.assert_allclose(out.numpy()[:, 0], [5, 0, 5])
        sel = paddle.select_scatter(t, _t(np.ones(3, np.float32)), 0, 1)
        np.testing.assert_allclose(sel.numpy()[1], 1.0)
        sl = paddle.slice_scatter(t, _t(np.ones((3, 1), np.float32)),
                                  axes=[1], starts=[2], ends=[3], strides=[1])
        np.testing.assert_allclose(sl.numpy()[:, 2], 1.0)
        snd = paddle.scatter_nd(_t(np.array([[0], [2]])),
                                _t(np.array([1.0, 3.0], np.float32)), [4])
        np.testing.assert_allclose(snd.numpy(), [1, 0, 3, 0])
        ms = paddle.masked_scatter(
            t, _t(np.eye(3, dtype=bool)),
            _t(np.array([7.0, 8.0, 9.0], np.float32)))
        np.testing.assert_allclose(np.diag(ms.numpy()), [7, 8, 9])

    def test_diag_family(self):
        d = paddle.diag_embed(_t(np.array([1.0, 2.0], np.float32)))
        np.testing.assert_allclose(d.numpy(), np.diag([1.0, 2.0]))
        t = _t(np.arange(9, dtype=np.float32).reshape(3, 3))
        np.testing.assert_allclose(paddle.diagonal(t).numpy(), [0, 4, 8])
        ds = paddle.diagonal_scatter(t, _t(np.zeros(3, np.float32)))
        np.testing.assert_allclose(np.diag(ds.numpy()), 0.0)

    def test_linalg_tail(self):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(3, 3)).astype(np.float32)
        spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        L = np.linalg.cholesky(spd)
        inv = paddle.cholesky_inverse(_t(L))
        np.testing.assert_allclose(inv.numpy(), np.linalg.inv(spd),
                                   rtol=1e-3, atol=1e-4)
        ms = [rng.normal(size=(4, 4)).astype(np.float32) for _ in range(3)]
        md = paddle.multi_dot([_t(m) for m in ms])
        np.testing.assert_allclose(md.numpy(), ms[0] @ ms[1] @ ms[2],
                                   rtol=1e-4, atol=1e-4)
        x = rng.normal(size=(5, 3)).astype(np.float32)
        y = rng.normal(size=(4, 3)).astype(np.float32)
        cd = paddle.cdist(_t(x), _t(y))
        ref = np.sqrt(((x[:, None] - y[None]) ** 2).sum(-1))
        np.testing.assert_allclose(cd.numpy(), ref, rtol=1e-4, atol=1e-5)
        v = paddle.vander(_t(np.array([1.0, 2.0, 3.0], np.float32)))
        np.testing.assert_allclose(v.numpy(), np.vander([1.0, 2.0, 3.0]))
        bd = paddle.block_diag([_t(np.ones((2, 2), np.float32)),
                                _t(np.full((1, 1), 5.0, np.float32))])
        assert tuple(bd.shape) == (3, 3) and bd.numpy()[2, 2] == 5

    def test_trapezoid_and_logcumsumexp(self):
        y = _t(np.array([1.0, 2.0, 3.0], np.float32))
        np.testing.assert_allclose(float(paddle.trapezoid(y).numpy()), 4.0)
        ct = paddle.cumulative_trapezoid(y)
        np.testing.assert_allclose(ct.numpy(), [1.5, 4.0])
        lse = paddle.logcumsumexp(_t(np.zeros(3, np.float32)))
        np.testing.assert_allclose(lse.numpy(), np.log([1, 2, 3]), rtol=1e-5)

    def test_isin_and_predicates(self):
        x = _t(np.array([1, 2, 3, 4]))
        np.testing.assert_array_equal(
            paddle.isin(x, _t(np.array([2, 4]))).numpy(),
            [False, True, False, True])
        inf = _t(np.array([np.inf, -np.inf, 1.0], np.float32))
        np.testing.assert_array_equal(paddle.isposinf(inf).numpy(),
                                      [True, False, False])
        np.testing.assert_array_equal(paddle.isneginf(inf).numpy(),
                                      [False, True, False])

    def test_inplace_variants(self):
        x = _t(np.ones(3, np.float32))
        y = paddle.exp_(x)
        assert y is x
        np.testing.assert_allclose(x.numpy(), np.e, rtol=1e-6)
        z = _t(np.array([-2.0, 5.0], np.float32))
        paddle.clip_(z, min=0.0, max=1.0)
        np.testing.assert_allclose(z.numpy(), [0.0, 1.0])
        # in-place participates in autograd via the snapshot mechanism
        a = _t(np.ones(2, np.float32))
        a.stop_gradient = False
        b = a * 2.0
        paddle.add_(b, _t(np.ones(2, np.float32)))
        b.sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), 2.0)
        # in-place on a leaf requiring grad is rejected (reference error)
        leaf = _t(np.ones(2, np.float32))
        leaf.stop_gradient = False
        with pytest.raises(RuntimeError, match="leaf"):
            paddle.exp_(leaf)

    def test_top_p_sampling(self):
        paddle.seed(0)
        logits = _t(np.array([[0.0, 0.0, 10.0]], np.float32))
        vals, ids = paddle.top_p_sampling(
            logits, _t(np.array([0.5], np.float32)))
        assert int(ids.numpy()[0, 0]) == 2
        assert float(vals.numpy()[0, 0]) > 0.9

    def test_take_and_combinations(self):
        t = _t(np.arange(6, dtype=np.float32).reshape(2, 3))
        np.testing.assert_allclose(
            paddle.take(t, _t(np.array([0, 4]))).numpy(), [0.0, 4.0])
        c = paddle.combinations(_t(np.array([1, 2, 3])), 2)
        assert tuple(c.shape) == (3, 2)
        # mode="raise" bounds-checks eagerly instead of silently wrapping
        with pytest.raises(IndexError):
            paddle.take(t, _t(np.array([0, 99])))

    def test_frexp_and_cast(self):
        m, e = paddle.frexp(_t(np.array([4.0], np.float32)))
        np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), 4.0)
        assert "int32" in str(paddle.cast(_t(np.ones(2, np.float32)),
                                          "int32")._value.dtype)
