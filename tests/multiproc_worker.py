"""Worker body for tests/test_multiproc.py — runs under jax.distributed with
N controller processes on localhost (reference analog: the per-rank body of
test/legacy_test/test_parallel_dygraph_dataparallel.py:30 workers).

Asserts eager cross-process collectives, TCPStore p2p, and DP train-step
parity between the global dp=N mesh and a process-local single-device run.
Exits 0 on success; any assertion failure propagates as a nonzero exit.
"""

import json
import os
import sys

import numpy as np


def main():
    rank = int(os.environ["RANK"])
    world = int(os.environ["WORLD_SIZE"])

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    dist.init_parallel_env()
    import jax

    assert jax.process_count() == world, jax.process_count()
    assert dist.get_rank() == rank

    # --- all_reduce sum / max ------------------------------------------- #
    t = paddle.to_tensor(np.full((4,), rank + 1.0, np.float32))
    dist.all_reduce(t)
    np.testing.assert_allclose(t.numpy(), world * (world + 1) / 2.0)
    t2 = paddle.to_tensor(np.full((2,), float(rank), np.float32))
    dist.all_reduce(t2, op=dist.ReduceOp.MAX)
    np.testing.assert_allclose(t2.numpy(), world - 1.0)

    # --- all_gather ------------------------------------------------------ #
    lst = []
    dist.all_gather(lst, paddle.to_tensor(np.asarray([rank], np.int32)))
    assert [int(x.numpy()[0]) for x in lst] == list(range(world))

    # --- broadcast (tensor + object, variable-size payloads) ------------- #
    b = paddle.to_tensor(np.full((3,), float(rank), np.float32))
    dist.broadcast(b, src=1)
    np.testing.assert_allclose(b.numpy(), 1.0)
    objs = [{"rank": rank, "blob": "x" * (5 * (rank + 1))}]
    dist.broadcast_object_list(objs, src=0)
    assert objs[0]["rank"] == 0

    gathered = []
    dist.all_gather_object(gathered, {"r": rank, "pad": "y" * (10 * (rank + 1))})
    assert [o["r"] for o in gathered] == list(range(world))

    # --- alltoall_single -------------------------------------------------- #
    a = paddle.to_tensor(np.full((world, 2), float(rank), np.float32))
    out = paddle.to_tensor(np.zeros((world, 2), np.float32))
    dist.alltoall_single(out, a)
    np.testing.assert_allclose(
        out.numpy(), np.arange(world, dtype=np.float32)[:, None]
        * np.ones((1, 2), np.float32))

    # --- p2p over the native TCPStore ------------------------------------ #
    if world >= 2:
        if rank == 0:
            dist.send(paddle.to_tensor(np.arange(5.0, dtype=np.float32)), dst=1)
        elif rank == 1:
            r = paddle.to_tensor(np.zeros(5, np.float32))
            dist.recv(r, src=0)
            np.testing.assert_allclose(r.numpy(), np.arange(5.0))
    dist.barrier()

    # --- DP train-step parity: global dp=world mesh vs local run --------- #
    def run(mesh):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(), nn.Linear(16, 4))
        crit = nn.MSELoss()
        step = dist.DistributedTrainStep(
            model, lambda o, y: crit(o, y),
            opt.AdamW(learning_rate=1e-2, parameters=model.parameters()),
            mesh=mesh)
        rng = np.random.default_rng(3)
        x = paddle.to_tensor(np.asarray(rng.normal(size=(8, 16)), np.float32))
        y = paddle.to_tensor(np.asarray(rng.normal(size=(8, 4)), np.float32))
        out = [float(step(x, y)) for _ in range(3)]
        dist.env.set_global_mesh(None)
        return out

    global_losses = run(dist.build_mesh(dp=world))
    local_losses = run(dist.build_mesh(dp=1, devices=jax.local_devices()))
    np.testing.assert_allclose(global_losses, local_losses,
                               rtol=2e-4, atol=1e-5)

    # --- eager hybrid-optimizer clip over an mp=world topology ----------- #
    # reference parity: _HybridParallelClipGrad must reduce TP-sharded sq
    # sums over the mp group while counting replicated params exactly once,
    # so the per-rank update equals the single-device full-tensor clip.
    from paddle_tpu.distributed import fleet

    strat = fleet.DistributedStrategy()
    strat.hybrid_configs = {"dp_degree": 1, "mp_degree": world}
    fleet.init(is_collective=True, strategy=strat)
    from paddle_tpu.framework.core import Parameter
    import jax.numpy as jnp
    wd = Parameter(jnp.zeros(4, jnp.float32))
    wd.is_distributed = True  # rank-distinct shard of a TP weight
    wr = Parameter(jnp.zeros(2, jnp.float32))
    g_d = np.arange(4, dtype=np.float32) + 4.0 * rank
    g_r = np.asarray([6.0, 8.0], np.float32)
    wd.grad = paddle.to_tensor(g_d.copy())
    wr.grad = paddle.to_tensor(g_r.copy())
    inner = opt.SGD(learning_rate=1.0, parameters=[wd, wr],
                    grad_clip=nn.ClipGradByGlobalNorm(1.0))
    hpo = fleet.distributed_optimizer(inner)
    hpo.step()
    full_d = np.concatenate([np.arange(4, dtype=np.float32) + 4.0 * r
                             for r in range(world)])
    gn = np.sqrt((full_d ** 2).sum() + (g_r ** 2).sum())
    scale = 1.0 / max(gn, 1.0)
    np.testing.assert_allclose(wd.numpy(), -g_d * scale, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(wr.numpy(), -g_r * scale, rtol=1e-5, atol=1e-6)
    dist.env.set_global_mesh(None)

    # --- ragged MoE global_scatter/gather (capacity-padded exchange) ------ #
    if world == 2:
        from paddle_tpu.distributed.utils.moe_utils import (global_gather,
                                                            global_scatter)

        # 1 local expert per rank; rank0 sends [2 to e0, 1 to e1],
        # rank1 sends [1 to e0, 2 to e1] — ragged on purpose
        local_counts = {0: np.asarray([2, 1]), 1: np.asarray([1, 2])}
        global_counts = {0: np.asarray([2, 1]), 1: np.asarray([1, 2])}
        lc = local_counts[rank]
        gc = global_counts[rank]
        vals = (np.arange(lc.sum(), dtype=np.float32)[:, None]
                + 100.0 * rank) * np.ones((1, 4), np.float32)
        x_moe = paddle.to_tensor(vals)
        y = global_scatter(x_moe, paddle.to_tensor(lc.astype(np.int64)),
                           paddle.to_tensor(gc.astype(np.int64)))
        assert y.shape[0] == int(gc.sum()), (rank, y.shape)
        # receive layout: block (src_rank r): rank r's tokens for MY expert
        if rank == 0:
            # from r0: values [0, 1]; from r1: value [100]
            expect = np.asarray([[0.0] * 4, [1.0] * 4, [100.0] * 4],
                                np.float32)
        else:
            # from r0: value [2]; from r1: values [101, 102]
            expect = np.asarray([[2.0] * 4, [101.0] * 4, [102.0] * 4],
                                np.float32)
        np.testing.assert_allclose(np.asarray(y.numpy()), expect, rtol=1e-6)
        # gather is the exact inverse
        back = global_gather(y, paddle.to_tensor(lc.astype(np.int64)),
                             paddle.to_tensor(gc.astype(np.int64)))
        np.testing.assert_allclose(np.asarray(back.numpy()), vals, rtol=1e-6)

    # --- hybrid dp x mp: the mp group is a SUBGROUP of the world, so the
    # distributed clip's reduction rides allreduce_value_group ------------- #
    if world >= 4 and world % 2 == 0:
        mp_deg = world // 2
        strat2 = fleet.DistributedStrategy()
        strat2.hybrid_configs = {"dp_degree": 2, "mp_degree": mp_deg}
        fleet.init(is_collective=True, strategy=strat2)
        hcg = fleet.get_hybrid_communicate_group()
        mp_rank = hcg.get_model_parallel_rank()
        wd2 = Parameter(jnp.zeros(4, jnp.float32))
        wd2.is_distributed = True
        wr2 = Parameter(jnp.zeros(2, jnp.float32))
        g_d2 = np.arange(4, dtype=np.float32) + 4.0 * mp_rank
        wd2.grad = paddle.to_tensor(g_d2.copy())
        wr2.grad = paddle.to_tensor(g_r.copy())
        inner2 = opt.SGD(learning_rate=1.0, parameters=[wd2, wr2],
                         grad_clip=nn.ClipGradByGlobalNorm(1.0))
        hpo2 = fleet.distributed_optimizer(inner2)
        hpo2.step()
        full2 = np.concatenate([np.arange(4, dtype=np.float32) + 4.0 * r
                                for r in range(mp_deg)])
        gn2 = np.sqrt((full2 ** 2).sum() + (g_r ** 2).sum())
        s2 = 1.0 / max(gn2, 1.0)
        np.testing.assert_allclose(wd2.numpy(), -g_d2 * s2,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(wr2.numpy(), -g_r * s2,
                                   rtol=1e-5, atol=1e-6)
        dist.env.set_global_mesh(None)

    print(json.dumps({"rank": rank, "losses": global_losses}), flush=True)


if __name__ == "__main__":
    main()
