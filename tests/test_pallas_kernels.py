"""Pallas kernel correctness vs jnp oracle, run on CPU through the Pallas
interpreter (the reference's CUDA-kernel-vs-NumPy OpTest pattern,
test/legacy_test/op_test.py:418)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret_unless_hw):
    pass


def _ref(q, k, v, causal, scale=None):
    B, Sq, H, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    s = scale or 1.0 / np.sqrt(D)
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * s
    if causal:
        m = jnp.tril(jnp.ones((Sq, Skv), bool), k=Skv - Sq)
        logits = jnp.where(m[None, None], logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v).astype(q.dtype)


CASES = [
    (2, 128, 128, 4, 4, 64, True),
    (1, 256, 256, 4, 2, 64, True),  # GQA
    (1, 100, 100, 2, 2, 32, False),  # padding path
    (1, 128, 256, 2, 1, 64, False),  # MQA, cross lengths
    (1, 64, 128, 2, 2, 32, True),  # causal bottom-right alignment (decode-like)
    (1, 1, 96, 2, 2, 32, True),  # single-query decode sees whole cache
]


@pytest.mark.parametrize("B,Sq,Skv,H,Hkv,D,causal", CASES)
def test_flash_attention_fwd_bwd_parity(B, Sq, Skv, H, Hkv, D, causal):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Sq, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Skv, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Skv, Hkv, D)), jnp.float32)
    out = flash_attention_fwd(q, k, v, causal=causal)
    np.testing.assert_allclose(out, _ref(q, k, v, causal), rtol=1e-4, atol=2e-5)

    g = jnp.asarray(rng.standard_normal(out.shape), jnp.float32)
    gq, gk, gv = jax.grad(
        lambda a, b, c: (flash_attention_fwd(a, b, c, causal=causal) * g).sum(),
        (0, 1, 2),
    )(q, k, v)
    rq, rk, rv = jax.grad(
        lambda a, b, c: (_ref(a, b, c, causal) * g).sum(), (0, 1, 2)
    )(q, k, v)
    np.testing.assert_allclose(gq, rq, rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(gk, rk, rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(gv, rv, rtol=1e-3, atol=2e-4)


def test_functional_dispatch_uses_kernel():
    """scaled_dot_product_attention routes to the Pallas kernel under
    interpret mode and matches the jnp fallback."""
    import importlib

    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F

    fa_mod = importlib.import_module("paddle_tpu.nn.functional.flash_attention")

    rng = np.random.default_rng(1)
    q = paddle.to_tensor(rng.standard_normal((1, 64, 2, 32)).astype("float32"),
                         stop_gradient=False)
    k = paddle.to_tensor(rng.standard_normal((1, 64, 2, 32)).astype("float32"))
    v = paddle.to_tensor(rng.standard_normal((1, 64, 2, 32)).astype("float32"))
    assert fa_mod._use_pallas_kernel()
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    ref = _ref(q.value, k.value, v.value, True)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=1e-4, atol=2e-5)
    # tape backward works through the custom-vjp kernel
    out.sum().backward()
    assert q.grad is not None and np.isfinite(q.grad.numpy()).all()


class TestKeyBiasPath:
    """Padding-mask attention rides the Pallas kernel as a fused additive
    key bias (round-5: BERT's [B,1,1,S] masks forced the S^2 composite)."""

    def _data(self, B=2, S=96, H=2, D=32, seed=0):
        rng = np.random.default_rng(seed)
        q = paddle.to_tensor(rng.normal(size=(B, S, H, D)).astype(np.float32))
        k = paddle.to_tensor(rng.normal(size=(B, S, H, D)).astype(np.float32))
        v = paddle.to_tensor(rng.normal(size=(B, S, H, D)).astype(np.float32))
        return q, k, v

    def test_bool_padding_mask_matches_composite(
            self, pallas_interpret_unless_hw):
        import jax.numpy as jnp

        from paddle_tpu.nn.functional.flash_attention import _ref_attention

        q, k, v = self._data()
        B, S = q.shape[0], q.shape[1]
        lens = np.array([64, 96])
        keep = (np.arange(S)[None, :] < lens[:, None])
        mask = paddle.to_tensor(keep[:, None, None, :])
        q.stop_gradient = False
        k.stop_gradient = False
        v.stop_gradient = False
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=mask,
                                             is_causal=False)
        out.sum().backward()
        ref = _ref_attention(jnp.asarray(q.numpy()), jnp.asarray(k.numpy()),
                             jnp.asarray(v.numpy()),
                             mask=jnp.asarray(keep[:, None, None, :]),
                             causal=False)
        err = np.abs(out.numpy() - np.asarray(ref)).max()
        assert err < 2e-5, err
        # BACKWARD parity: grads must match jax.grad of the composite — a
        # bias-wiring regression in the bwd kernels stays finite but wrong
        def composite_loss(qq, kk, vv):
            return _ref_attention(
                qq, kk, vv, mask=jnp.asarray(keep[:, None, None, :]),
                causal=False).sum()

        gq, gk, gv = jax.grad(composite_loss, argnums=(0, 1, 2))(
            jnp.asarray(q.numpy()), jnp.asarray(k.numpy()),
            jnp.asarray(v.numpy()))
        for got, want, name in ((q.grad, gq, "dq"), (k.grad, gk, "dk"),
                                (v.grad, gv, "dv")):
            d = np.abs(got.numpy() - np.asarray(want)).max()
            assert d < 5e-3, (name, d)

    def test_additive_float_mask_matches_composite(
            self, pallas_interpret_unless_hw):
        import jax.numpy as jnp

        from paddle_tpu.nn.functional.flash_attention import _ref_attention

        q, k, v = self._data(seed=3)
        B, S = q.shape[0], q.shape[1]
        bias = np.random.default_rng(4).normal(size=(B, 1, 1, S)) \
            .astype(np.float32)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=paddle.to_tensor(bias), is_causal=False)
        ref = _ref_attention(jnp.asarray(q.numpy()), jnp.asarray(k.numpy()),
                             jnp.asarray(v.numpy()),
                             mask=jnp.asarray(bias), causal=False)
        err = np.abs(out.numpy() - np.asarray(ref)).max()
        assert err < 2e-5, err

    def test_causal_plus_padding(self, pallas_interpret_unless_hw):
        import jax.numpy as jnp

        from paddle_tpu.nn.functional.flash_attention import _ref_attention

        q, k, v = self._data(seed=5)
        B, S = q.shape[0], q.shape[1]
        keep = (np.arange(S)[None, :] < 80).repeat(B, 0)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=paddle.to_tensor(keep[:, None, None, :]),
            is_causal=True)
        full = np.tril(np.ones((S, S), bool))[None, None] \
            & keep[:, None, None, :]
        ref = _ref_attention(jnp.asarray(q.numpy()), jnp.asarray(k.numpy()),
                             jnp.asarray(v.numpy()),
                             mask=jnp.asarray(full), causal=False)
        err = np.abs(out.numpy() - np.asarray(ref)).max()
        assert err < 2e-5, err

    def test_full_2d_mask_still_uses_composite(self):
        """A general [B,1,Sq,Skv] mask is NOT a key-padding mask and must
        keep the exact composite path — checked by VALUE, so a loosened
        key_padding detection cannot mis-route it undetected."""
        from paddle_tpu.nn.functional.flash_attention import _ref_attention

        q, k, v = self._data(S=32)
        S = q.shape[1]
        m = np.random.default_rng(6).random((2, 1, S, S)) > 0.3
        m |= np.eye(S, dtype=bool)[None, None]  # no fully-masked rows
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=paddle.to_tensor(m), is_causal=False)
        ref = _ref_attention(jnp.asarray(q.numpy()), jnp.asarray(k.numpy()),
                             jnp.asarray(v.numpy()), mask=jnp.asarray(m),
                             causal=False)
        err = np.abs(out.numpy() - np.asarray(ref)).max()
        assert err < 2e-5, err


class TestSafeSoftmaxToggle:
    """ADVICE r5: PADDLE_TPU_FLASH_SAFE_SOFTMAX used to be re-read at
    backward TRACE time, so flipping it between forward and backward
    silently corrupted gradients (the two kernels disagree on the lse
    convention). The mode is now captured at forward trace time and rides
    the custom-VJP static args."""

    def _qkv(self, seed=0, B=1, S=64, H=2, D=32):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        return q, k, v

    def _grads(self, q, k, v):
        out, vjp = jax.vjp(
            lambda a, b, c: flash_attention_fwd(a, b, c, causal=True),
            q, k, v)
        return out, vjp(jnp.ones_like(out))

    @pytest.mark.parametrize("fwd_mode", ["0", "1"])
    def test_env_flip_between_fwd_and_bwd_is_inert(self, monkeypatch,
                                                   fwd_mode):
        q, k, v = self._qkv()
        bwd_mode = "1" if fwd_mode == "0" else "0"
        # reference: both passes in the forward's mode
        monkeypatch.setenv("PADDLE_TPU_FLASH_SAFE_SOFTMAX", fwd_mode)
        ref_out, ref_grads = self._grads(q, k, v)
        # toggled run: vjp built under fwd_mode, env flipped before the
        # backward trace executes
        monkeypatch.setenv("PADDLE_TPU_FLASH_SAFE_SOFTMAX", fwd_mode)
        out, vjp = jax.vjp(
            lambda a, b, c: flash_attention_fwd(a, b, c, causal=True),
            q, k, v)
        monkeypatch.setenv("PADDLE_TPU_FLASH_SAFE_SOFTMAX", bwd_mode)
        grads = vjp(jnp.ones_like(out))
        np.testing.assert_allclose(out, ref_out, rtol=1e-5, atol=1e-6)
        for g, rg in zip(grads, ref_grads):
            np.testing.assert_allclose(g, rg, rtol=1e-4, atol=1e-5,
                                       err_msg="backward followed the env "
                                       "var, not the forward's mode")

    def test_fast_mode_gates_ds_at_clamp(self):
        """Where the fast forward SATURATED (all logits >= _CLAMP), the
        clamp is flat so dq and dk must be exactly zero; dv (which sees the
        saturated equal weights) stays finite — the safe kernel is the
        oracle for it."""
        rng = np.random.default_rng(1)
        B, S, H, D = 1, 16, 1, 32
        # logits = (q @ k^T) * scale, driven far above _CLAMP=60 everywhere
        q = jnp.asarray(100.0 * np.abs(rng.standard_normal((B, S, H, D))),
                        jnp.float32)
        k = jnp.asarray(np.abs(rng.standard_normal((B, S, H, D))),
                        jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        logits = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(D)
        assert logits.min() > 60.0  # every entry saturates

        os.environ.pop("PADDLE_TPU_FLASH_SAFE_SOFTMAX", None)
        out, (gq, gk, gv) = (
            lambda o, vjp: (o, vjp(jnp.ones_like(o))))(*jax.vjp(
                lambda a, b, c: flash_attention_fwd(a, b, c, causal=False),
                q, k, v))
        assert np.all(np.isfinite(out))
        np.testing.assert_allclose(gq, np.zeros_like(gq), atol=1e-6)
        np.testing.assert_allclose(gk, np.zeros_like(gk), atol=1e-6)
        assert np.all(np.isfinite(gv)) and np.abs(gv).max() > 0
