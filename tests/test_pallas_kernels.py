"""Pallas kernel correctness vs jnp oracle, run on CPU through the Pallas
interpreter (the reference's CUDA-kernel-vs-NumPy OpTest pattern,
test/legacy_test/op_test.py:418)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret_unless_hw):
    pass


def _ref(q, k, v, causal, scale=None):
    B, Sq, H, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    s = scale or 1.0 / np.sqrt(D)
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * s
    if causal:
        m = jnp.tril(jnp.ones((Sq, Skv), bool), k=Skv - Sq)
        logits = jnp.where(m[None, None], logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v).astype(q.dtype)


CASES = [
    (2, 128, 128, 4, 4, 64, True),
    (1, 256, 256, 4, 2, 64, True),  # GQA
    (1, 100, 100, 2, 2, 32, False),  # padding path
    (1, 128, 256, 2, 1, 64, False),  # MQA, cross lengths
    (1, 64, 128, 2, 2, 32, True),  # causal bottom-right alignment (decode-like)
    (1, 1, 96, 2, 2, 32, True),  # single-query decode sees whole cache
]


@pytest.mark.parametrize("B,Sq,Skv,H,Hkv,D,causal", CASES)
def test_flash_attention_fwd_bwd_parity(B, Sq, Skv, H, Hkv, D, causal):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Sq, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Skv, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Skv, Hkv, D)), jnp.float32)
    out = flash_attention_fwd(q, k, v, causal=causal)
    np.testing.assert_allclose(out, _ref(q, k, v, causal), rtol=1e-4, atol=2e-5)

    g = jnp.asarray(rng.standard_normal(out.shape), jnp.float32)
    gq, gk, gv = jax.grad(
        lambda a, b, c: (flash_attention_fwd(a, b, c, causal=causal) * g).sum(),
        (0, 1, 2),
    )(q, k, v)
    rq, rk, rv = jax.grad(
        lambda a, b, c: (_ref(a, b, c, causal) * g).sum(), (0, 1, 2)
    )(q, k, v)
    np.testing.assert_allclose(gq, rq, rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(gk, rk, rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(gv, rv, rtol=1e-3, atol=2e-4)


def test_functional_dispatch_uses_kernel():
    """scaled_dot_product_attention routes to the Pallas kernel under
    interpret mode and matches the jnp fallback."""
    import importlib

    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F

    fa_mod = importlib.import_module("paddle_tpu.nn.functional.flash_attention")

    rng = np.random.default_rng(1)
    q = paddle.to_tensor(rng.standard_normal((1, 64, 2, 32)).astype("float32"),
                         stop_gradient=False)
    k = paddle.to_tensor(rng.standard_normal((1, 64, 2, 32)).astype("float32"))
    v = paddle.to_tensor(rng.standard_normal((1, 64, 2, 32)).astype("float32"))
    assert fa_mod._use_pallas_kernel()
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    ref = _ref(q.value, k.value, v.value, True)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=1e-4, atol=2e-5)
    # tape backward works through the custom-vjp kernel
    out.sum().backward()
    assert q.grad is not None and np.isfinite(q.grad.numpy()).all()
