"""nn.functional tail (reference: python/paddle/nn/functional/ vision.py
grid_sample/affine_grid, loss.py tail, common.py sequence_mask/zeropad2d,
extension.py temporal_shift/gather_tree, qkvpacked flash wrappers)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestSpatialTransformer:
    def test_identity_affine_grid_sample(self):
        x = _t(np.random.default_rng(0).standard_normal((2, 3, 5, 7))
               .astype(np.float32))
        theta = _t(np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                           (2, 1, 1)))
        grid = F.affine_grid(theta, [2, 3, 5, 7])
        out = F.grid_sample(x, grid)
        np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-5)

    def test_shift_and_padding_modes(self):
        x = _t(np.arange(12, dtype=np.float32).reshape(1, 1, 3, 4))
        # shift one pixel right in grid space = sample one pixel to the right
        theta = _t(np.array([[[1.0, 0, 2.0 / 3.0], [0, 1.0, 0]]], np.float32))
        grid = F.affine_grid(theta, [1, 1, 3, 4])
        border = F.grid_sample(x, grid, padding_mode="border").numpy()
        np.testing.assert_allclose(border[..., :-1], x.numpy()[..., 1:],
                                   atol=1e-4)
        zeros = F.grid_sample(x, grid, padding_mode="zeros").numpy()
        np.testing.assert_allclose(zeros[..., -1], 0.0, atol=1e-4)
        nearest = F.grid_sample(x, grid, mode="nearest").numpy()
        assert np.isfinite(nearest).all()

    def test_grid_sample_grad(self):
        x = _t(np.ones((1, 1, 4, 4), np.float32))
        x.stop_gradient = False
        theta = _t(np.array([[[1.0, 0, 0.1], [0, 1.0, -0.1]]], np.float32))
        grid = F.affine_grid(theta, [1, 1, 4, 4])
        F.grid_sample(x, grid).sum().backward()
        assert x.grad is not None and np.isfinite(x.grad.numpy()).all()


class TestCommonTail:
    def test_sequence_mask_and_zeropad(self):
        sm = F.sequence_mask(_t(np.array([1, 3])), maxlen=4)
        np.testing.assert_array_equal(sm.numpy(),
                                      [[1, 0, 0, 0], [1, 1, 1, 0]])
        zp = F.zeropad2d(_t(np.ones((1, 1, 2, 2), np.float32)), [1, 0, 0, 2])
        assert tuple(zp.shape) == (1, 1, 4, 3)
        assert float(zp.numpy().sum()) == 4.0

    def test_pairwise_distance(self):
        d = F.pairwise_distance(_t(np.zeros((2, 3), np.float32)),
                                _t(np.ones((2, 3), np.float32)))
        np.testing.assert_allclose(d.numpy(), np.sqrt(3), rtol=1e-4)

    def test_temporal_shift(self):
        x = _t(np.random.default_rng(0).standard_normal((4, 8, 2, 2))
               .astype(np.float32))  # N=2 segments of T=2
        out = F.temporal_shift(x, seg_num=2, shift_ratio=0.25)
        assert tuple(out.shape) == (4, 8, 2, 2)
        v = x.numpy().reshape(2, 2, 8, 2, 2)
        o = out.numpy().reshape(2, 2, 8, 2, 2)
        # first fold shifted backward: o[:, t, :2] == v[:, t+1, :2]
        np.testing.assert_allclose(o[:, 0, :2], v[:, 1, :2])
        np.testing.assert_allclose(o[:, 1, :2], 0.0)
        # untouched tail channels identical
        np.testing.assert_allclose(o[:, :, 4:], v[:, :, 4:])

    def test_gather_tree(self):
        # T=2, B=1, K=2; beam 0 at t=1 came from parent 1
        ids = _t(np.array([[[2, 5]], [[6, 1]]]))
        parents = _t(np.array([[[0, 0]], [[1, 0]]]))
        out = F.gather_tree(ids, parents).numpy()
        # final beam 0: path = ids[0][parent chain] -> t0 from parent 1 (=5)
        np.testing.assert_array_equal(out[:, 0, 0], [5, 6])
        np.testing.assert_array_equal(out[:, 0, 1], [2, 1])


class TestLossTail:
    def test_gaussian_and_poisson_nll(self):
        z = _t(np.zeros(4, np.float32))
        one = _t(np.ones(4, np.float32))
        np.testing.assert_allclose(
            float(F.gaussian_nll_loss(z, z, one).numpy()), 0.0, atol=1e-6)
        # poisson log-input: exp(x) - y*x at x=0,y=1 -> 1
        np.testing.assert_allclose(
            float(F.poisson_nll_loss(z, one).numpy()), 1.0, atol=1e-6)

    def test_margin_losses(self):
        x = _t(np.array([10.0, -10.0], np.float32))
        y = _t(np.array([1.0, -1.0], np.float32))
        assert float(F.soft_margin_loss(x, y).numpy()) < 1e-3
        ml = F.multi_label_soft_margin_loss(
            _t(np.array([[10.0, -10.0]], np.float32)),
            _t(np.array([[1.0, 0.0]], np.float32)))
        assert float(ml.numpy()) < 1e-3
        tl = F.triplet_margin_with_distance_loss(
            _t(np.zeros((2, 3), np.float32)),
            _t(np.zeros((2, 3), np.float32)),
            _t(np.full((2, 3), 10.0, np.float32)), margin=1.0)
        np.testing.assert_allclose(float(tl.numpy()), 0.0, atol=1e-5)

    def test_dice_and_npair(self):
        probs = _t(np.array([[[0.9, 0.1], [0.2, 0.8]]], np.float32))
        labels = _t(np.array([[[0], [1]]]))
        d = F.dice_loss(probs, labels)
        assert 0 <= float(d.numpy()) < 0.3
        a = _t(np.eye(4, 8, dtype=np.float32))
        y = _t(np.arange(4))
        n = F.npair_loss(a, a, y)
        assert np.isfinite(float(n.numpy()))


class TestQKVPacked:
    def test_qkvpacked_matches_unpacked(self):
        rng = np.random.default_rng(0)
        qkv = rng.standard_normal((2, 16, 3, 2, 8)).astype(np.float32)
        out, _ = F.flash_attn_qkvpacked(_t(qkv), causal=True)
        ref, _ = F.flash_attention(_t(qkv[:, :, 0]), _t(qkv[:, :, 1]),
                                   _t(qkv[:, :, 2]), causal=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-6)

    def test_varlen_qkvpacked(self):
        rng = np.random.default_rng(1)
        qkv = rng.standard_normal((20, 3, 2, 8)).astype(np.float32)
        cu = _t(np.array([0, 8, 20], np.int32))
        out, _ = F.flash_attn_varlen_qkvpacked(
            _t(qkv), cu, cu, 12, 12, 8 ** -0.5, causal=True,
            varlen_padded=False)
        assert tuple(out.shape) == (20, 2, 8)
        assert np.isfinite(out.numpy()).all()
        # the reference's padded default is a different memory layout:
        # reading it as packed would silently misalign, so it must raise
        with pytest.raises(NotImplementedError, match="varlen_padded"):
            F.flash_attn_varlen_qkvpacked(_t(qkv), cu, cu, 12, 12, 8 ** -0.5)
