"""Fused RMSNorm/LayerNorm and fused RoPE Pallas kernels vs the lax
composites, run on CPU through the Pallas interpreter (the reference's
CUDA-kernel-vs-NumPy OpTest pattern, test/legacy_test/op_test.py:418), plus
the PADDLE_TPU_FUSED_NORM / PADDLE_TPU_FUSED_ROPE A/B toggles proven through
the llama model's loss and gradients."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret_unless_hw):
    pass


# per-dtype tolerances: (fwd rtol/atol, grad rtol/atol). bf16 carries ~8
# mantissa bits; both sides compute f32 stats so disagreement is cast noise.
_TOLS = {
    "float32": (2e-6, 1e-4),
    "bfloat16": (2e-2, 2e-2),
}


def _f32(x):
    return np.asarray(x, np.float32)


# --------------------------------------------------------------------------- #
# norm kernels vs lax oracles
# --------------------------------------------------------------------------- #


def _ref_rms(a, w, eps):
    x32 = a.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    out = x32 * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * w.astype(jnp.float32)
    return out.astype(a.dtype)


def _ref_ln(a, w, b, eps):
    x32 = a.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * w.astype(jnp.float32)
    if b is not None:
        out = out + b.astype(jnp.float32)
    return out.astype(a.dtype)


# odd rows force padded row tiles; N=96 pads lanes up to 128; N=300 is a
# non-multiple wide row (pads to 384). Two shapes, not more — tier-1 wall
# time is budgeted and each combo runs a fwd + two VJPs.
_NORM_SHAPES = [((2, 100, 96), 96), ((1, 33, 300), 300)]


class TestFusedNorm:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("shape,n", _NORM_SHAPES)
    @pytest.mark.parametrize("has_w", [True, False])
    def test_rms_fwd_vjp_parity(self, dtype, shape, n, has_w):
        from paddle_tpu.ops.pallas.fused_norm import rms_norm_fwd

        ftol, gtol = _TOLS[dtype]
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal(shape), jnp.dtype(dtype))
        w = (jnp.asarray(rng.standard_normal(n), jnp.dtype(dtype))
             if has_w else None)
        eps = 1e-6
        out = rms_norm_fwd(x, w, eps)
        np.testing.assert_allclose(
            _f32(out), _f32(_ref_rms(x, w, eps)), rtol=ftol, atol=ftol * 4)

        g = jnp.asarray(rng.standard_normal(shape), jnp.dtype(dtype))

        def loss(fn):
            def inner(a, *rest):
                ww = rest[0] if rest else None
                return (fn(a, ww).astype(jnp.float32)
                        * g.astype(jnp.float32)).sum()
            return inner

        args = (x, w) if has_w else (x,)
        argnums = (0, 1) if has_w else (0,)
        got = jax.grad(loss(lambda a, ww: rms_norm_fwd(a, ww, eps)),
                       argnums)(*args)
        ref = jax.grad(loss(lambda a, ww: _ref_rms(a, ww, eps)),
                       argnums)(*args)
        for gg, rr in zip(got, ref):
            np.testing.assert_allclose(_f32(gg), _f32(rr), rtol=gtol,
                                       atol=gtol * 8)

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("shape,n", _NORM_SHAPES)
    @pytest.mark.parametrize("affine", [True, False])
    def test_layer_norm_fwd_vjp_parity(self, dtype, shape, n, affine):
        from paddle_tpu.ops.pallas.fused_norm import layer_norm_fwd

        ftol, gtol = _TOLS[dtype]
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal(shape), jnp.dtype(dtype))
        w = b = None
        if affine:
            w = jnp.asarray(rng.standard_normal(n), jnp.dtype(dtype))
            b = jnp.asarray(rng.standard_normal(n), jnp.dtype(dtype))
        eps = 1e-5
        out = layer_norm_fwd(x, w, b, eps)
        np.testing.assert_allclose(
            _f32(out), _f32(_ref_ln(x, w, b, eps)), rtol=ftol, atol=ftol * 8)

        g = jnp.asarray(rng.standard_normal(shape), jnp.dtype(dtype))
        args = (x, w, b) if affine else (x,)
        argnums = (0, 1, 2) if affine else (0,)

        def wrap(fn):
            def inner(a, *rest):
                ww, bb = (rest + (None, None))[:2]
                return (fn(a, ww, bb).astype(jnp.float32)
                        * g.astype(jnp.float32)).sum()
            return inner

        got = jax.grad(wrap(lambda a, ww, bb: layer_norm_fwd(a, ww, bb, eps)),
                       argnums)(*args)
        ref = jax.grad(wrap(lambda a, ww, bb: _ref_ln(a, ww, bb, eps)),
                       argnums)(*args)
        for gg, rr in zip(got, ref):
            np.testing.assert_allclose(_f32(gg), _f32(rr), rtol=gtol,
                                       atol=gtol * 8)

    def test_layer_norm_mean_dominated_no_cancellation(self):
        """Variance must be the two-pass (x-mean)^2 form: the one-pass
        E[x^2]-E[x]^2 cancels catastrophically in f32 when |mean| >> std
        (both moments ~1e8, their difference below f32 resolution), blowing
        rstd up to ~1/sqrt(eps). N=96 also exercises the padded-lane mask
        in the centered sum (zeros would contribute mean^2 each)."""
        from paddle_tpu.ops.pallas.fused_norm import layer_norm_fwd

        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.standard_normal((4, 96)) + 1e4, jnp.float32)
        out = layer_norm_fwd(x, None, None, 1e-5)
        ref = _ref_ln(x, None, None, 1e-5)
        # outputs are ~N(0,1); centered-in-f32 noise is ~1e4 * eps(f32)
        np.testing.assert_allclose(_f32(out), _f32(ref), rtol=0, atol=5e-3)
        assert float(jnp.max(jnp.abs(out))) < 10.0


# --------------------------------------------------------------------------- #
# rope kernel vs the composite pairing math
# --------------------------------------------------------------------------- #


def _ref_rope(x, c, s, neox):
    cc = c[:, :, None, :].astype(jnp.float32)
    ss = s[:, :, None, :].astype(jnp.float32)
    xf = x.astype(jnp.float32)
    if neox:
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate([x1 * cc - x2 * ss, x2 * cc + x1 * ss], axis=-1)
    else:
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        out = jnp.stack([x1 * cc - x2 * ss, x2 * cc + x1 * ss],
                        axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def _tables(s_len, d, batched=None):
    pos = (jnp.arange(s_len, dtype=jnp.float32)[None]
           if batched is None else batched.astype(jnp.float32))
    inv = 1.0 / (10000.0 ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    fr = pos[..., None] * inv[None, None]
    return jnp.cos(fr), jnp.sin(fr)


class TestFusedRope:
    # odd S=100/37 force padded sequence tiles; GQA k has fewer heads
    CASES = [(2, 100, 4, 2, 32), (2, 37, 4, 4, 64)]

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("B,S,H,Hkv,D", CASES)
    @pytest.mark.parametrize("neox", [True, False])
    def test_qk_fwd_vjp_parity(self, dtype, B, S, H, Hkv, D, neox):
        from paddle_tpu.ops.pallas.fused_rope import apply_fused_rope

        ftol, gtol = _TOLS[dtype]
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.dtype(dtype))
        k = jnp.asarray(rng.standard_normal((B, S, Hkv, D)), jnp.dtype(dtype))
        c, s = _tables(S, D)
        oq, ok = apply_fused_rope((q, k), c, s, interleaved=not neox)
        np.testing.assert_allclose(_f32(oq), _f32(_ref_rope(q, c, s, neox)),
                                   rtol=ftol, atol=ftol * 4)
        np.testing.assert_allclose(_f32(ok), _f32(_ref_rope(k, c, s, neox)),
                                   rtol=ftol, atol=ftol * 4)

        g = jnp.asarray(rng.standard_normal(q.shape), jnp.dtype(dtype))
        gq = jax.grad(lambda a: (
            apply_fused_rope((a, k), c, s, interleaved=not neox)[0]
            .astype(jnp.float32) * g.astype(jnp.float32)).sum())(q)
        rq = jax.grad(lambda a: (
            _ref_rope(a, c, s, neox).astype(jnp.float32)
            * g.astype(jnp.float32)).sum())(q)
        np.testing.assert_allclose(_f32(gq), _f32(rq), rtol=gtol,
                                   atol=gtol * 4)

    def test_per_batch_position_tables(self):
        """position_ids path: per-batch [B, S, D/2] tables (not broadcast)."""
        from paddle_tpu.ops.pallas.fused_rope import apply_fused_rope

        rng = np.random.default_rng(2)
        B, S, H, D = 2, 24, 2, 32
        q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        pid = jnp.asarray(rng.integers(0, 64, (B, S)), jnp.int32)
        c, s = _tables(S, D, batched=pid)
        assert c.shape == (B, S, D // 2)
        (out,) = apply_fused_rope((q,), c, s, interleaved=False)
        np.testing.assert_allclose(_f32(out), _f32(_ref_rope(q, c, s, True)),
                                   rtol=2e-6, atol=1e-5)

    def test_three_tensor_pass(self):
        """q, k AND v rotated in the one kernel sweep (reference rotates
        every given tensor)."""
        from paddle_tpu.ops.pallas.fused_rope import apply_fused_rope

        rng = np.random.default_rng(3)
        B, S, D = 1, 16, 16
        ts = tuple(
            jnp.asarray(rng.standard_normal((B, S, h, D)), jnp.float32)
            for h in (4, 2, 2))
        c, s = _tables(S, D)
        outs = apply_fused_rope(ts, c, s, interleaved=True)
        assert len(outs) == 3
        for o, t in zip(outs, ts):
            np.testing.assert_allclose(_f32(o), _f32(_ref_rope(t, c, s, False)),
                                       rtol=2e-6, atol=1e-5)


# --------------------------------------------------------------------------- #
# functional dispatch + toggles
# --------------------------------------------------------------------------- #


class TestFunctionalDispatch:
    def test_rms_norm_kernel_matches_composite(self, monkeypatch):
        rng = np.random.default_rng(0)
        xv = rng.standard_normal((2, 50, 96)).astype(np.float32)
        wv = rng.standard_normal(96).astype(np.float32)
        x = paddle.to_tensor(xv, stop_gradient=False)
        w = paddle.to_tensor(wv, stop_gradient=False)
        out = F.rms_norm(x, w)
        out.sum().backward()
        gx, gw = x.grad.numpy(), w.grad.numpy()

        monkeypatch.setenv("PADDLE_TPU_FUSED_NORM", "0")
        x2 = paddle.to_tensor(xv, stop_gradient=False)
        w2 = paddle.to_tensor(wv, stop_gradient=False)
        out2 = F.rms_norm(x2, w2)
        out2.sum().backward()
        np.testing.assert_allclose(out.numpy(), out2.numpy(), rtol=2e-6,
                                   atol=2e-6)
        np.testing.assert_allclose(gx, x2.grad.numpy(), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(gw, w2.grad.numpy(), rtol=1e-4, atol=1e-3)

    def test_layer_norm_kernel_matches_composite(self, monkeypatch):
        rng = np.random.default_rng(1)
        xv = rng.standard_normal((3, 40, 64)).astype(np.float32)
        wv = rng.standard_normal(64).astype(np.float32)
        bv = rng.standard_normal(64).astype(np.float32)
        on = F.layer_norm(paddle.to_tensor(xv), 64, paddle.to_tensor(wv),
                          paddle.to_tensor(bv))
        monkeypatch.setenv("PADDLE_TPU_FUSED_NORM", "0")
        off = F.layer_norm(paddle.to_tensor(xv), 64, paddle.to_tensor(wv),
                           paddle.to_tensor(bv))
        np.testing.assert_allclose(on.numpy(), off.numpy(), rtol=2e-6,
                                   atol=1e-5)

    def test_incubate_fused_rms_norm_residual_path(self, monkeypatch):
        from paddle_tpu.incubate.nn.functional import fused_rms_norm

        rng = np.random.default_rng(2)
        xv = rng.standard_normal((2, 30, 96)).astype(np.float32)
        wv = rng.standard_normal(96).astype(np.float32)
        nbv = rng.standard_normal(96).astype(np.float32)
        rv = rng.standard_normal((2, 30, 96)).astype(np.float32)
        on, ron = fused_rms_norm(
            paddle.to_tensor(xv), paddle.to_tensor(wv),
            norm_bias=paddle.to_tensor(nbv), residual=paddle.to_tensor(rv))
        monkeypatch.setenv("PADDLE_TPU_FUSED_NORM", "0")
        off, roff = fused_rms_norm(
            paddle.to_tensor(xv), paddle.to_tensor(wv),
            norm_bias=paddle.to_tensor(nbv), residual=paddle.to_tensor(rv))
        np.testing.assert_allclose(on.numpy(), off.numpy(), rtol=2e-6,
                                   atol=1e-5)
        np.testing.assert_allclose(ron.numpy(), roff.numpy(), rtol=0,
                                   atol=0)

    def test_fused_rope_matches_composite(self, monkeypatch):
        from paddle_tpu.incubate.nn.functional import (
            fused_rotary_position_embedding)

        rng = np.random.default_rng(3)
        qv = rng.standard_normal((2, 37, 4, 32)).astype(np.float32)
        kv = rng.standard_normal((2, 37, 2, 32)).astype(np.float32)
        pid = rng.integers(0, 37, (2, 37)).astype(np.int32)
        for neox in (True, False):
            on_q, on_k, _ = fused_rotary_position_embedding(
                paddle.to_tensor(qv), paddle.to_tensor(kv),
                position_ids=paddle.to_tensor(pid),
                use_neox_rotary_style=neox)
            monkeypatch.setenv("PADDLE_TPU_FUSED_ROPE", "0")
            off_q, off_k, _ = fused_rotary_position_embedding(
                paddle.to_tensor(qv), paddle.to_tensor(kv),
                position_ids=paddle.to_tensor(pid),
                use_neox_rotary_style=neox)
            monkeypatch.delenv("PADDLE_TPU_FUSED_ROPE")
            np.testing.assert_allclose(on_q.numpy(), off_q.numpy(),
                                       rtol=2e-5, atol=2e-5)
            np.testing.assert_allclose(on_k.numpy(), off_k.numpy(),
                                       rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------------- #
# A/B toggles through the llama model: loss + grads, trace-time capture
# --------------------------------------------------------------------------- #


def _llama_loss_and_grads(flip_env_between_fwd_bwd=None, monkeypatch=None):
    """Build a seeded tiny llama, run one fwd+bwd, return (loss, grads).
    flip_env_between_fwd_bwd: dict of env vars flipped AFTER the forward
    (trace) but BEFORE backward — the PR-7 capture contract says this must
    be inert."""
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
    from paddle_tpu.models.llama import llama_tiny

    paddle.seed(0)
    cfg = llama_tiny()
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 33)))
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 33)))
    loss = crit(model(ids), labels)
    if flip_env_between_fwd_bwd:
        for k, v in flip_env_between_fwd_bwd.items():
            monkeypatch.setenv(k, v)
    loss.backward()
    layer = model.gpt.layers[0]
    grads = {
        "norm_w": layer.input_layernorm.weight.grad.numpy(),
        "q_proj_w": layer.self_attn.q_proj.weight.grad.numpy(),
        "gate_w": layer.mlp.gate_proj.weight.grad.numpy(),
    }
    return float(loss.numpy()), grads


class TestLlamaToggleAB:
    """Tier-1 A/B parity: the fused-norm/fused-rope toggles change the
    kernels, not the math — llama loss and grads agree both ways, and an
    env flip between forward and backward cannot corrupt gradients (the
    toggle is captured at forward trace time into the custom-VJP pair,
    like the PR-7 safe-softmax fix)."""

    def test_toggles_on_vs_off_loss_and_grads(self, monkeypatch):
        loss_on, grads_on = _llama_loss_and_grads()
        monkeypatch.setenv("PADDLE_TPU_FUSED_NORM", "0")
        monkeypatch.setenv("PADDLE_TPU_FUSED_ROPE", "0")
        loss_off, grads_off = _llama_loss_and_grads()
        assert loss_on == pytest.approx(loss_off, rel=1e-5, abs=1e-5)
        for name in grads_on:
            np.testing.assert_allclose(grads_on[name], grads_off[name],
                                       rtol=1e-3, atol=1e-4)

    def test_env_flip_between_fwd_and_bwd_is_inert(self, monkeypatch):
        _, grads_ref = _llama_loss_and_grads()
        _, grads_flip = _llama_loss_and_grads(
            flip_env_between_fwd_bwd={"PADDLE_TPU_FUSED_NORM": "0",
                                      "PADDLE_TPU_FUSED_ROPE": "0"},
            monkeypatch=monkeypatch)
        for name in grads_ref:
            np.testing.assert_allclose(grads_ref[name], grads_flip[name],
                                       rtol=0, atol=0)

    def test_default_is_fused_and_kernels_consulted(self):
        """Default-on acceptance: a llama step with no env overrides routes
        through the fused kernels, visible in the autotune tile registry."""
        from paddle_tpu.framework.core import clear_dispatch_cache
        from paddle_tpu.ops.pallas import autotune

        autotune.clear_cache()
        # tile recording happens at trace time — drop cached dispatch
        # entries or the replayed traces never re-consult the tuner
        clear_dispatch_cache()
        assert os.environ.get("PADDLE_TPU_FUSED_NORM") is None
        assert os.environ.get("PADDLE_TPU_FUSED_ROPE") is None
        _llama_loss_and_grads()
        tiles = autotune.chosen_tiles()
        assert "fused_rms_norm" in tiles
        assert "fused_rope" in tiles
        assert "flash_fwd" in tiles
