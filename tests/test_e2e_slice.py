"""End-to-end slice tests: DataLoader -> Model.fit -> checkpoint
(the reference's test/book + hapi test pattern). Training uses the jitted
TrainStep engine — the real TPU execution path."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io import DataLoader, Dataset, TensorDataset
from paddle_tpu.vision.datasets import FakeData


class TestDataLoader:
    def test_basic_batching(self):
        class Sq(Dataset):
            def __len__(self):
                return 10

            def __getitem__(self, i):
                return np.float32([i]), np.int32(i % 2)

        dl = DataLoader(Sq(), batch_size=4)
        batches = list(dl)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == [4, 1] and y.shape == [4]
        dl2 = DataLoader(Sq(), batch_size=4, drop_last=True)
        assert len(list(dl2)) == 2

    def test_shuffle_and_workers(self):
        class Idx(Dataset):
            def __len__(self):
                return 32

            def __getitem__(self, i):
                return np.float32([i])

        dl = DataLoader(Idx(), batch_size=8, shuffle=True, num_workers=2)
        seen = np.concatenate([b.numpy().ravel() for b in dl])
        np.testing.assert_array_equal(np.sort(seen), np.arange(32))

    def test_distributed_batch_sampler(self):
        from paddle_tpu.io import DistributedBatchSampler

        class Idx(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return np.float32([i])

        s0 = DistributedBatchSampler(Idx(), 4, num_replicas=2, rank=0)
        s1 = DistributedBatchSampler(Idx(), 4, num_replicas=2, rank=1)
        i0 = [i for b in s0 for i in b]
        i1 = [i for b in s1 for i in b]
        assert set(i0) | set(i1) == set(range(16))
        assert not (set(i0) & set(i1))


class TestMetrics:
    def test_accuracy(self):
        m = paddle.metric.Accuracy()
        pred = paddle.to_tensor([[0.1, 0.9], [0.8, 0.2]])
        label = paddle.to_tensor([[1], [1]])
        m.update(m.compute(pred, label))
        assert abs(m.accumulate() - 0.5) < 1e-6

    def test_precision_recall(self):
        p = paddle.metric.Precision()
        r = paddle.metric.Recall()
        preds = np.array([0.9, 0.8, 0.1, 0.7])
        labels = np.array([1, 0, 1, 1])
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-6
        assert abs(r.accumulate() - 2 / 3) < 1e-6


class TestJit:
    def test_to_static_function(self):
        calls = []

        @paddle.jit.to_static
        def f(x, y):
            calls.append(1)
            return paddle.matmul(x, y) + 1

        a = paddle.ones([2, 3])
        b = paddle.ones([3, 2])
        out1 = f(a, b)
        out2 = f(a, b)  # cached: no retrace
        np.testing.assert_allclose(out1.numpy(), np.full((2, 2), 4.0))
        assert len(calls) == 1

    def test_to_static_layer_forward(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = paddle.randn([3, 4])
        ref = net(x).numpy()
        paddle.jit.to_static(net)
        out = net(x).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_train_step_matches_eager(self):
        paddle.seed(0)
        rng = np.random.RandomState(0)
        X = rng.rand(16, 4).astype(np.float32)
        y = (X @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32))

        def build():
            paddle.seed(42)
            return nn.Linear(4, 1)

        # eager reference
        net_e = build()
        opt_e = paddle.optimizer.SGD(learning_rate=0.1, parameters=net_e.parameters())
        for _ in range(5):
            loss = F.mse_loss(net_e(paddle.to_tensor(X)), paddle.to_tensor(y))
            loss.backward()
            opt_e.step()
            opt_e.clear_grad()

        # jitted TrainStep
        net_j = build()
        opt_j = paddle.optimizer.SGD(learning_rate=0.1, parameters=net_j.parameters())
        step = paddle.jit.TrainStep(net_j, F.mse_loss, opt_j)
        for _ in range(5):
            jloss = step(paddle.to_tensor(X), paddle.to_tensor(y))
        step.sync_weights()
        np.testing.assert_allclose(net_j.weight.numpy(), net_e.weight.numpy(), rtol=1e-4, atol=1e-5)

    def test_train_step_adam_with_clip(self):
        paddle.seed(1)
        net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = paddle.optimizer.AdamW(
            learning_rate=0.01, parameters=net.parameters(), weight_decay=0.01,
            grad_clip=nn.ClipGradByGlobalNorm(1.0),
        )
        step = paddle.jit.TrainStep(net, F.mse_loss, opt)
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.rand(32, 4).astype(np.float32))
        y = paddle.to_tensor(rng.rand(32, 1).astype(np.float32))
        losses = [float(step(X, y).numpy()) for _ in range(30)]
        assert losses[-1] < losses[0] * 0.5

    def test_train_step_updates_bn_buffers(self):
        net = nn.Sequential(nn.Conv2D(1, 2, 3, padding=1), nn.BatchNorm2D(2), nn.Flatten(), nn.Linear(2 * 16, 1))
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=net.parameters())
        step = paddle.jit.TrainStep(net, F.mse_loss, opt)
        x = paddle.randn([4, 1, 4, 4])
        y = paddle.randn([4, 1])
        step(x, y)
        step.sync_weights()
        bn = net[1]
        assert not np.allclose(bn._mean.numpy(), 0)  # running stats updated in-graph


class TestModelFit:
    def test_fit_lenet_on_fake_mnist(self, capsys):
        paddle.seed(0)

        class FakeMnist(Dataset):
            def __len__(self):
                return 64

            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                label = i % 10
                img = rng.rand(1, 28, 28).astype(np.float32) * 0.1
                img[0, label * 2:label * 2 + 3, :] += 1.0  # learnable signal
                return img, np.int64(label)

        from paddle_tpu.vision.models import LeNet

        model = paddle.Model(LeNet())
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss(), paddle.metric.Accuracy())
        model.fit(FakeMnist(), epochs=3, batch_size=16, verbose=0)
        logs = model.evaluate(FakeMnist(), batch_size=16, verbose=0)
        assert logs["acc"] > 0.5, logs

    def test_fit_small_resnet(self):
        """The ResNet-50-config slice at toy scale: ResNet-18 arch, tiny inputs."""
        paddle.seed(0)
        from paddle_tpu.vision.models import resnet18

        net = resnet18(num_classes=4)
        model = paddle.Model(net)
        opt = paddle.optimizer.Momentum(learning_rate=0.01, parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        data = FakeData(size=8, image_shape=(3, 32, 32), num_classes=4)
        model.fit(data, epochs=1, batch_size=4, verbose=0)
        out = model.predict_batch([np.random.rand(2, 3, 32, 32).astype(np.float32)])
        assert out[0].shape == (2, 4)

    def test_model_save_load(self, tmp_path):
        net = nn.Linear(3, 2)
        model = paddle.Model(net)
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
        model.prepare(opt, nn.MSELoss())
        X = paddle.randn([8, 3])
        y = paddle.randn([8, 2])
        model.train_batch([X], [y])
        p = str(tmp_path / "ckpt")
        model.save(p)
        w_saved = net.weight.numpy().copy()
        net.weight._value = net.weight._value * 0
        model2 = paddle.Model(net)
        model2.prepare(paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters()), nn.MSELoss())
        model2.load(p)
        np.testing.assert_allclose(net.weight.numpy(), w_saved)

    def test_summary(self, capsys):
        net = nn.Linear(4, 2)
        info = paddle.summary(net)
        assert info["total_params"] == 4 * 2 + 2


class TestReviewRegressions2:
    def test_metric_compute_tuple_unpacked_in_evaluate(self):
        net = nn.Sequential(nn.Linear(4, 1), nn.Sigmoid())
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.SGD(0.1, parameters=net.parameters()),
            nn.MSELoss(),
            [paddle.metric.Precision()],
        )
        ds = TensorDataset([paddle.randn([8, 4]), paddle.ones([8, 1])])
        logs = model.evaluate(ds, batch_size=4, verbose=0)
        assert "precision" in logs

    def test_dataloader_worker_error_propagates(self):
        class Bad(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("corrupt sample")
                return np.float32([i])

        dl = DataLoader(Bad(), batch_size=2, num_workers=2)
        with pytest.raises(ValueError, match="corrupt"):
            list(dl)

    def test_optimizer_state_synced_on_save(self, tmp_path):
        net = nn.Linear(3, 1)
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
        model = paddle.Model(net)
        model.prepare(opt, nn.MSELoss())
        model.train_batch([paddle.randn([4, 3])], [paddle.randn([4, 1])])
        model.save(str(tmp_path / "ck"))
        opt_state = paddle.load(str(tmp_path / "ck") + ".pdopt")
        assert opt_state["_step_count"] == 1
        assert any(k.startswith("param_") for k in opt_state)

    def test_bilinear_resize(self):
        from paddle_tpu.vision.transforms import Resize

        img = np.arange(16, dtype=np.float32).reshape(4, 4)
        out = Resize((2, 2), interpolation="bilinear")(img)
        np.testing.assert_allclose(out, [[2.5, 4.5], [10.5, 12.5]])
        out_n = Resize((2, 2), interpolation="nearest")(img)
        np.testing.assert_array_equal(out_n, [[0, 2], [8, 10]])
