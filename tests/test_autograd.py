"""Eager autograd tests — tape backward, grad accumulation, hooks, PyLayer
(reference: test/legacy_test/test_imperative_* and test/legacy_test/test_pylayer_op.py),
with finite-difference/NumPy oracles."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def tensor(a, sg=False):
    t = paddle.to_tensor(np.asarray(a, np.float32))
    t.stop_gradient = sg
    return t


class TestBackward:
    def test_simple_chain(self):
        x = tensor([2.0, 3.0])
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0], rtol=1e-6)

    def test_branching_graph(self):
        x = tensor([1.0, 2.0])
        a = x * 2
        b = x * 3
        y = (a * b).sum()  # y = 6x^2, dy/dx = 12x
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0, 24.0], rtol=1e-6)

    def test_matmul_grad(self):
        rng = np.random.RandomState(0)
        a = rng.rand(3, 4).astype(np.float32)
        b = rng.rand(4, 2).astype(np.float32)
        ta, tb = tensor(a), tensor(b)
        loss = paddle.matmul(ta, tb).sum()
        loss.backward()
        np.testing.assert_allclose(ta.grad.numpy(), np.ones((3, 2)) @ b.T, rtol=1e-4)
        np.testing.assert_allclose(tb.grad.numpy(), a.T @ np.ones((3, 2)), rtol=1e-4)

    def test_grad_accumulation(self):
        x = tensor([1.0, 1.0])
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
        x.clear_grad()
        assert x.grad is None

    def test_stop_gradient(self):
        x = tensor([1.0])
        y = tensor([2.0], sg=True)
        (x * y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])
        assert y.grad is None

    def test_detach(self):
        x = tensor([3.0])
        d = (x * 2).detach()
        assert d.stop_gradient
        z = x * d
        z.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_no_grad(self):
        x = tensor([1.0])
        with paddle.no_grad():
            y = x * 5
        assert y._grad_node is None
        assert y.stop_gradient

    def test_multi_output_op(self):
        x = tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        a, b = paddle.split(x, 2, axis=0)
        (a.sum() * 2 + b.sum() * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [[2, 2, 2], [3, 3, 3]])

    def test_backward_nonscalar_raises(self):
        x = tensor([1.0, 2.0])
        with pytest.raises(RuntimeError):
            (x * 2).backward()

    def test_backward_with_grad_tensor(self):
        x = tensor([1.0, 2.0])
        y = x * x
        y.backward(paddle.to_tensor([1.0, 0.5]))
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_hook(self):
        x = tensor([1.0])
        seen = []
        x.register_hook(lambda g: seen.append(g.numpy()[0]))
        (x * 4).sum().backward()
        assert seen == [4.0]

    def test_hook_modifies_grad(self):
        x = tensor([1.0])
        x.register_hook(lambda g: g * 10)
        (x * 2).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [20.0])

    def test_nonlinear_vs_fd(self):
        rng = np.random.RandomState(1)
        a = rng.rand(5).astype(np.float32) + 0.5

        def f(v):
            return float(np.sum(np.tanh(v) * np.exp(v * 0.5)))

        x = tensor(a)
        (paddle.tanh(x) * paddle.exp(x * 0.5)).sum().backward()
        eps = 1e-3
        for i in range(5):
            ap, am = a.copy(), a.copy()
            ap[i] += eps
            am[i] -= eps
            fd = (f(ap) - f(am)) / (2 * eps)
            np.testing.assert_allclose(x.grad.numpy()[i], fd, rtol=1e-2)


class TestGradAPI:
    def test_paddle_grad(self):
        x = tensor([2.0])
        y = x * x * x
        (gx,) = paddle.grad(y, [x])
        np.testing.assert_allclose(gx.numpy(), [12.0], rtol=1e-5)
        assert x.grad is None  # paddle.grad must not pollute .grad

    def test_grad_unused(self):
        x = tensor([1.0])
        z = tensor([1.0])
        y = x * 2
        gx, gz = paddle.grad(y.sum(), [x, z], allow_unused=True)
        assert gz is None


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, g):
                return g * 2

        x = tensor([3.0])
        y = Double.apply(x)
        np.testing.assert_allclose(y.numpy(), [6.0])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_custom_grad_override(self):
        class FakeGrad(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return paddle.exp(x)

            @staticmethod
            def backward(ctx, g):
                return g * 0 + 7

        x = tensor([0.0])
        FakeGrad.apply(x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_multi_io(self):
        class MulAdd(PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                ctx.save_for_backward(a, b)
                return a * b, a + b

            @staticmethod
            def backward(ctx, ga, gb):
                a, b = ctx.saved_tensor()
                return ga * b + gb, ga * a + gb

        a, b = tensor([2.0]), tensor([5.0])
        p, s = MulAdd.apply(a, b)
        (p.sum() + s.sum()).backward()
        np.testing.assert_allclose(a.grad.numpy(), [6.0])
        np.testing.assert_allclose(b.grad.numpy(), [3.0])


class TestFunctionalAD:
    def test_vjp(self):
        x = tensor([1.0, 2.0])
        out, g = paddle.autograd.vjp(lambda v: (v * v).sum(), x)
        np.testing.assert_allclose(g.numpy(), [2.0, 4.0])

    def test_jvp(self):
        x = tensor([1.0, 2.0])
        out, t = paddle.autograd.jvp(lambda v: (v * v).sum(), x)
        np.testing.assert_allclose(t.numpy(), 6.0, rtol=1e-6)

    def test_jacobian(self):
        x = tensor([1.0, 2.0])
        j = paddle.autograd.jacobian(lambda v: v * v, x)
        np.testing.assert_allclose(j.numpy(), np.diag([2.0, 4.0]))

    def test_hessian(self):
        x = tensor([1.0, 2.0])
        h = paddle.autograd.hessian(lambda v: (v * v * v).sum(), x)
        np.testing.assert_allclose(h.numpy(), np.diag([6.0, 12.0]), atol=1e-5)


class TestSaveLoad:
    def test_save_load_roundtrip(self, tmp_path):
        obj = {"w": paddle.randn([3, 3]), "step": 7, "nested": {"b": paddle.ones([2])}}
        p = str(tmp_path / "ckpt.pdparams")
        paddle.save(obj, p)
        back = paddle.load(p)
        np.testing.assert_array_equal(back["w"].numpy(), obj["w"].numpy())
        assert back["step"] == 7
        np.testing.assert_array_equal(back["nested"]["b"].numpy(), [1, 1])


class TestReviewRegressions:
    """Regressions from code review: in-place tape cycles, intermediate grads."""

    def test_setitem_on_intermediate_keeps_grad(self):
        x = tensor([1.0, 2.0, 3.0])
        y = x * 2
        y[0] = 5.0  # in-place on non-leaf must keep the graph acyclic
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [0.0, 2.0, 2.0])

    def test_setitem_on_leaf_requiring_grad_raises(self):
        x = tensor([1.0, 2.0, 3.0])
        with pytest.raises(RuntimeError):
            x[0] = 5.0

    def test_grad_wrt_intermediate(self):
        a = tensor([2.0])
        h = a * 3
        y = h * h
        gh = paddle.grad(y.sum(), h)
        np.testing.assert_allclose(gh.numpy(), [12.0])

    def test_hook_on_intermediate_fires_and_modifies(self):
        a = tensor([1.0])
        h = a * 2
        h.register_hook(lambda g: g * 10)
        (h * 3).sum().backward()
        # dh = 3, hook -> 30, da = 30 * 2 = 60
        np.testing.assert_allclose(a.grad.numpy(), [60.0])

    def test_retain_grads(self):
        a = tensor([1.0])
        h = a * 2
        h.retain_grads()
        (h * 3).sum().backward()
        np.testing.assert_allclose(h.grad.numpy(), [3.0])


class TestDoubleGrad:
    """create_graph=True: grads carry tape nodes (VERDICT r3 missing #6;
    reference: test/legacy_test/test_imperative_double_grad.py)."""

    def test_second_order_parity_with_jax(self):
        import jax
        import jax.numpy as jnp

        x = tensor([1.0, 2.0, 3.0])
        w = np.array([0.5, -1.0, 2.0], np.float32)
        y = (x * x * x * tensor(w)).sum()
        (gx,) = paddle.autograd.grad(y, [x], create_graph=True)
        assert not gx.stop_gradient
        gx.sum().backward()
        ref = jax.grad(lambda xv: jax.grad(
            lambda a: (a ** 3 * jnp.asarray(w)).sum())(xv).sum())(
            jnp.array([1.0, 2.0, 3.0]))
        np.testing.assert_allclose(x.grad.numpy(), np.asarray(ref), rtol=1e-5)

    def test_gradient_penalty_reaches_weights(self):
        paddle.seed(0)
        lin = paddle.nn.Linear(2, 1)
        x = tensor([[1.0, 2.0]])
        out = paddle.tanh(lin(x)).sum()
        (g,) = paddle.autograd.grad(out, [x], create_graph=True)
        (g * g).sum().backward()
        assert lin.weight.grad is not None
        assert np.isfinite(np.asarray(lin.weight.grad.numpy())).all()

    def test_grad_wrt_intermediate(self):
        a = tensor([2.0])
        b = a * 3.0
        (gb,) = paddle.autograd.grad((b * b).sum(), [b], create_graph=True)
        np.testing.assert_allclose(gb.numpy(), [12.0], rtol=1e-6)

    def test_multi_input_second_order(self):
        p = tensor([1.0])
        q = tensor([2.0])
        r = (p * p * q).sum()
        gp, gq = paddle.autograd.grad(r, [p, q], create_graph=True)
        np.testing.assert_allclose(gp.numpy(), [4.0])
        np.testing.assert_allclose(gq.numpy(), [1.0])
        (gp * gq).sum().backward()  # loss = 2p^3 q
        np.testing.assert_allclose(p.grad.numpy(), [12.0], rtol=1e-5)
        np.testing.assert_allclose(q.grad.numpy(), [2.0], rtol=1e-5)

    def test_unused_input_raises_unless_allowed(self):
        x = tensor([1.0])
        z = tensor([1.0])
        y = (x * x).sum()
        with pytest.raises(RuntimeError):
            paddle.autograd.grad(y, [z], create_graph=True)
        gs = paddle.autograd.grad(y, [x, z], create_graph=True,
                                  allow_unused=True)
        assert gs[1] is None
        np.testing.assert_allclose(gs[0].numpy(), [2.0])
