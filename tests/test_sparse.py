"""paddle.sparse subset (reference: python/paddle/sparse/ creation/binary/
matmul + sparse/nn; kernels paddle/phi/kernels/sparse/)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import sparse


def _coo():
    indices = [[0, 1, 2], [1, 0, 2]]
    values = [1.0, 2.0, 3.0]
    return sparse.sparse_coo_tensor(indices, values, shape=[3, 3])


def test_coo_create_dense_roundtrip():
    s = _coo()
    assert s.shape == [3, 3] and s.nnz == 3
    dense = s.to_dense().numpy()
    ref = np.zeros((3, 3), np.float32)
    ref[0, 1], ref[1, 0], ref[2, 2] = 1, 2, 3
    np.testing.assert_allclose(dense, ref)
    np.testing.assert_array_equal(s.indices().numpy(),
                                  [[0, 1, 2], [1, 0, 2]])
    np.testing.assert_allclose(s.values().numpy(), [1, 2, 3])


def test_csr_create():
    s = sparse.sparse_csr_tensor(
        crows=[0, 1, 2, 3], cols=[1, 0, 2], values=[1.0, 2.0, 3.0],
        shape=[3, 3])
    np.testing.assert_allclose(s.to_dense().numpy(), _coo().to_dense().numpy())


def test_add_sub_mul():
    a, b = _coo(), _coo()
    np.testing.assert_allclose((a + b).to_dense().numpy(),
                               2 * a.to_dense().numpy())
    np.testing.assert_allclose((a - b).to_dense().numpy(), 0.0)
    np.testing.assert_allclose((a * 2.0).to_dense().numpy(),
                               2 * a.to_dense().numpy())
    dense = paddle.to_tensor(np.full((3, 3), 2.0, np.float32))
    np.testing.assert_allclose((a * dense).to_dense().numpy(),
                               2 * a.to_dense().numpy())


def test_matmul_and_masked_matmul():
    s = _coo()
    d = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
    out = sparse.matmul(s, d)
    np.testing.assert_allclose(out.numpy(),
                               s.to_dense().numpy() @ d.numpy())
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal((3, 4))
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.default_rng(1).standard_normal((4, 3))
                         .astype(np.float32))
    mm = sparse.masked_matmul(x, y, s)
    full = x.numpy() @ y.numpy()
    mask = (s.to_dense().numpy() != 0)
    np.testing.assert_allclose(mm.to_dense().numpy(), full * mask, rtol=1e-5)


def test_relu_and_softmax():
    idx = [[0, 0, 1], [0, 1, 2]]
    s = sparse.sparse_coo_tensor(idx, [-1.0, 2.0, -3.0], shape=[2, 3])
    r = sparse.nn.functional.relu(s)
    np.testing.assert_allclose(r.values().numpy(), [0.0, 2.0, 0.0])

    sm = sparse.nn.functional.softmax(_coo())
    dense = sm.to_dense().numpy()
    # each row has ONE stored value -> softmax over stored entries = 1
    np.testing.assert_allclose(dense[dense != 0], 1.0)

    s2 = sparse.sparse_coo_tensor([[0, 0], [0, 1]], [1.0, 2.0], shape=[1, 3])
    sm2 = sparse.nn.functional.softmax(s2).values().numpy()
    e = np.exp(np.array([1.0, 2.0]) - 2.0)
    np.testing.assert_allclose(sm2, e / e.sum(), rtol=1e-5)


def test_softmax_3d_lanes_independent():
    """ndim > 2: entries normalize per (batch, row) lane, never across."""
    idx = [[0, 0], [0, 1], [0, 0]]  # two different rows of batch 0
    s = sparse.sparse_coo_tensor(idx, [1.0, 5.0], shape=[2, 2, 3])
    sm = sparse.nn.functional.softmax(s)
    # each lane has a single entry -> softmax = 1, NOT mixed across rows
    np.testing.assert_allclose(sm.values().numpy(), [1.0, 1.0])


def test_transpose():
    t = sparse.transpose(_coo(), [1, 0])
    np.testing.assert_allclose(t.to_dense().numpy(),
                               _coo().to_dense().numpy().T)
