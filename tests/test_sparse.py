"""paddle.sparse subset (reference: python/paddle/sparse/ creation/binary/
matmul + sparse/nn; kernels paddle/phi/kernels/sparse/)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import sparse


def _coo():
    indices = [[0, 1, 2], [1, 0, 2]]
    values = [1.0, 2.0, 3.0]
    return sparse.sparse_coo_tensor(indices, values, shape=[3, 3])


def test_coo_create_dense_roundtrip():
    s = _coo()
    assert s.shape == [3, 3] and s.nnz == 3
    dense = s.to_dense().numpy()
    ref = np.zeros((3, 3), np.float32)
    ref[0, 1], ref[1, 0], ref[2, 2] = 1, 2, 3
    np.testing.assert_allclose(dense, ref)
    np.testing.assert_array_equal(s.indices().numpy(),
                                  [[0, 1, 2], [1, 0, 2]])
    np.testing.assert_allclose(s.values().numpy(), [1, 2, 3])


def test_csr_create():
    s = sparse.sparse_csr_tensor(
        crows=[0, 1, 2, 3], cols=[1, 0, 2], values=[1.0, 2.0, 3.0],
        shape=[3, 3])
    np.testing.assert_allclose(s.to_dense().numpy(), _coo().to_dense().numpy())


def test_add_sub_mul():
    a, b = _coo(), _coo()
    np.testing.assert_allclose((a + b).to_dense().numpy(),
                               2 * a.to_dense().numpy())
    np.testing.assert_allclose((a - b).to_dense().numpy(), 0.0)
    np.testing.assert_allclose((a * 2.0).to_dense().numpy(),
                               2 * a.to_dense().numpy())
    dense = paddle.to_tensor(np.full((3, 3), 2.0, np.float32))
    np.testing.assert_allclose((a * dense).to_dense().numpy(),
                               2 * a.to_dense().numpy())


def test_matmul_and_masked_matmul():
    s = _coo()
    d = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
    out = sparse.matmul(s, d)
    np.testing.assert_allclose(out.numpy(),
                               s.to_dense().numpy() @ d.numpy())
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal((3, 4))
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.default_rng(1).standard_normal((4, 3))
                         .astype(np.float32))
    mm = sparse.masked_matmul(x, y, s)
    full = x.numpy() @ y.numpy()
    mask = (s.to_dense().numpy() != 0)
    np.testing.assert_allclose(mm.to_dense().numpy(), full * mask, rtol=1e-5)


def test_relu_and_softmax():
    idx = [[0, 0, 1], [0, 1, 2]]
    s = sparse.sparse_coo_tensor(idx, [-1.0, 2.0, -3.0], shape=[2, 3])
    r = sparse.nn.functional.relu(s)
    np.testing.assert_allclose(r.values().numpy(), [0.0, 2.0, 0.0])

    sm = sparse.nn.functional.softmax(_coo())
    dense = sm.to_dense().numpy()
    # each row has ONE stored value -> softmax over stored entries = 1
    np.testing.assert_allclose(dense[dense != 0], 1.0)

    s2 = sparse.sparse_coo_tensor([[0, 0], [0, 1]], [1.0, 2.0], shape=[1, 3])
    sm2 = sparse.nn.functional.softmax(s2).values().numpy()
    e = np.exp(np.array([1.0, 2.0]) - 2.0)
    np.testing.assert_allclose(sm2, e / e.sum(), rtol=1e-5)


def test_softmax_3d_lanes_independent():
    """ndim > 2: entries normalize per (batch, row) lane, never across."""
    idx = [[0, 0], [0, 1], [0, 0]]  # two different rows of batch 0
    s = sparse.sparse_coo_tensor(idx, [1.0, 5.0], shape=[2, 2, 3])
    sm = sparse.nn.functional.softmax(s)
    # each lane has a single entry -> softmax = 1, NOT mixed across rows
    np.testing.assert_allclose(sm.values().numpy(), [1.0, 1.0])


def test_transpose():
    t = sparse.transpose(_coo(), [1, 0])
    np.testing.assert_allclose(t.to_dense().numpy(),
                               _coo().to_dense().numpy().T)


# --------------------------------------------------------------------------- #
# CSR format (reference: sparse_csr_tensor, phi/kernels/sparse/ mv/matmul)
# --------------------------------------------------------------------------- #


def _csr():
    return sparse.sparse_csr_tensor(
        crows=[0, 2, 3, 5], cols=[0, 2, 1, 0, 2],
        values=[1.0, 2.0, 3.0, 4.0, 5.0], shape=[3, 3])


def test_csr_is_real_csr():
    s = _csr()
    assert s.is_sparse_csr() and not s.is_sparse_coo()
    assert s.nnz == 5
    np.testing.assert_array_equal(s.crows().numpy(), [0, 2, 3, 5])
    np.testing.assert_array_equal(s.cols().numpy(), [0, 2, 1, 0, 2])
    np.testing.assert_allclose(s.values().numpy(), [1, 2, 3, 4, 5])
    ref = np.array([[1, 0, 2], [0, 3, 0], [4, 0, 5]], np.float32)
    np.testing.assert_allclose(s.to_dense().numpy(), ref)


def test_csr_coo_roundtrip():
    s = _csr()
    coo = s.to_sparse_coo()
    assert coo.is_sparse_coo()
    np.testing.assert_allclose(coo.to_dense().numpy(), s.to_dense().numpy())
    back = coo.to_sparse_csr()
    np.testing.assert_array_equal(back.crows().numpy(), s.crows().numpy())
    np.testing.assert_array_equal(back.cols().numpy(), s.cols().numpy())
    np.testing.assert_allclose(back.values().numpy(), s.values().numpy())


def test_dense_to_sparse_methods():
    ref = np.array([[1, 0, 2], [0, 3, 0], [4, 0, 5]], np.float32)
    t = paddle.to_tensor(ref)
    coo = t.to_sparse_coo()
    csr = t.to_sparse_csr()
    np.testing.assert_allclose(coo.to_dense().numpy(), ref)
    np.testing.assert_array_equal(csr.crows().numpy(), [0, 2, 3, 5])
    np.testing.assert_allclose(csr.to_dense().numpy(), ref)


def test_csr_spmm_spmv():
    s = _csr()
    dense = s.to_dense().numpy()
    d = np.arange(12, dtype=np.float32).reshape(3, 4)
    np.testing.assert_allclose(
        sparse.matmul(s, paddle.to_tensor(d)).numpy(), dense @ d, rtol=1e-6)
    v = np.asarray([1.0, -2.0, 0.5], np.float32)
    np.testing.assert_allclose(
        sparse.mv(s, paddle.to_tensor(v)).numpy(), dense @ v, rtol=1e-6)
    # COO spmv too
    np.testing.assert_allclose(
        sparse.mv(_coo(), paddle.to_tensor(v)).numpy(),
        _coo().to_dense().numpy() @ v, rtol=1e-6)


def test_csr_spmm_gradients():
    import jax
    import jax.numpy as jnp

    s = _csr()
    dense = s.to_dense().numpy()
    d = np.arange(12, dtype=np.float32).reshape(3, 4)

    def loss(vals):
        s2 = sparse.SparseCsrTensor(s.crows(), s.cols(),
                                    paddle.to_tensor(vals), s.shape)
        return sparse.matmul(s2, paddle.to_tensor(d))._value.sum()

    g = jax.grad(lambda v: loss(v))(jnp.asarray(s.values().numpy()))
    # d(sum)/d(val_e) = sum of dense row d[cols[e]]
    expect = d[[0, 2, 1, 0, 2]].sum(axis=1)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-6)


def test_csr_binary_and_relu():
    s = _csr()
    two = (s + s).to_dense().numpy()
    np.testing.assert_allclose(two, 2 * s.to_dense().numpy())
    neg = sparse.sparse_csr_tensor([0, 1, 1, 1], [1], [-7.0], [3, 3])
    r = sparse.nn.functional.relu(neg)
    assert r.is_sparse_csr()
    np.testing.assert_allclose(r.to_dense().numpy(), 0.0)
    t = sparse.transpose(s, [1, 0])
    np.testing.assert_allclose(t.to_dense().numpy(),
                               s.to_dense().numpy().T)


def test_spmv_spmm_eager_autograd():
    """mv/matmul go through the tape: backward() reaches the dense operand."""
    s = _csr()
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    sparse.mv(s, x).sum().backward()
    # d(sum(Ax))/dx = column sums of A
    np.testing.assert_allclose(x.grad.numpy(),
                               _csr().to_dense().numpy().sum(0), rtol=1e-6)
    W = paddle.to_tensor(np.ones((3, 2), np.float32), stop_gradient=False)
    sparse.matmul(s, W).sum().backward()
    np.testing.assert_allclose(
        W.grad.numpy(), np.tile(s.to_dense().numpy().sum(0)[:, None], (1, 2)),
        rtol=1e-6)


# --------------------------------------------------------------------------- #
# sparse.nn layers (reference: python/paddle/sparse/nn/)
# --------------------------------------------------------------------------- #


def _point_cloud(seed=0, N=1, D=6, H=6, W=6, C=3, n_pts=10):
    rng = np.random.default_rng(seed)
    dense = np.zeros((N, D, H, W, C), np.float32)
    for _ in range(n_pts):
        n, d, h, w = (rng.integers(0, s) for s in (N, D, H, W))
        dense[n, d, h, w] = rng.normal(size=C).astype(np.float32) + 0.1
    return dense


def test_sparse_conv3d_matches_dense():
    import jax
    import paddle_tpu as pd

    pd.seed(0)
    dense = _point_cloud()
    st = paddle.to_tensor(dense).to_sparse_coo(sparse_dim=4)
    conv = sparse.nn.Conv3D(3, 5, kernel_size=3, padding=1)
    out = conv(st)
    ref = np.asarray(jax.lax.conv_general_dilated(
        dense, np.asarray(conv.weight.numpy()), (1, 1, 1),
        [(1, 1)] * 3, dimension_numbers=("NDHWC", "DHWIO", "NDHWC")))
    ref = ref + np.asarray(conv.bias.numpy())
    np.testing.assert_allclose(out.to_dense().numpy(), ref,
                               rtol=1e-4, atol=1e-5)


def test_sparse_submconv3d_preserves_sparsity():
    import paddle_tpu as pd

    pd.seed(0)
    dense = _point_cloud(seed=1)
    st = paddle.to_tensor(dense).to_sparse_coo(sparse_dim=4)
    conv = sparse.nn.SubmConv3D(3, 4, kernel_size=3, padding=1)
    out = conv(st)
    in_mask = np.any(dense != 0, axis=-1)
    out_mask = np.any(out.to_dense().numpy() != 0, axis=-1)
    # output active sites are a subset of the input's (submanifold semantic)
    assert not np.any(out_mask & ~in_mask)


def test_sparse_batchnorm_relu_pool():
    import paddle_tpu as pd

    pd.seed(0)
    dense = _point_cloud(seed=2)
    st = paddle.to_tensor(dense).to_sparse_coo(sparse_dim=4)
    bn = sparse.nn.BatchNorm(3)
    out = bn(st)
    vals = out.values().numpy()
    active = dense[np.any(dense != 0, axis=-1)]
    # normalized over ACTIVE sites only
    np.testing.assert_allclose(vals.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(vals.std(0), 1.0, atol=1e-2)
    r = sparse.nn.ReLU()(out)
    assert (r.values().numpy() >= 0).all()
    lr = sparse.nn.LeakyReLU(0.1)(out)
    assert np.isfinite(lr.values().numpy()).all()
    p = sparse.nn.MaxPool3D(2)(st)
    assert p.shape[1] == dense.shape[1] // 2
    ref_pool = dense.reshape(1, 3, 2, 3, 2, 3, 2, 3).max((2, 4, 6))
    np.testing.assert_allclose(p.to_dense().numpy(),
                               np.maximum(ref_pool, 0.0) + np.minimum(ref_pool, 0.0),
                               rtol=1e-5)


def test_sparse_conv_grads_flow():
    import paddle_tpu as pd

    pd.seed(0)
    dense = _point_cloud(seed=3)
    st = paddle.to_tensor(dense).to_sparse_coo(sparse_dim=4)
    conv = sparse.nn.SubmConv3D(3, 4, kernel_size=3, padding=1)
    out = conv(st)
    out.values().sum().backward()
    assert conv.weight.grad is not None
    assert np.abs(conv.weight.grad.numpy()).sum() > 0
