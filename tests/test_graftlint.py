"""graftlint tier-1 suite: rule fixtures (true positives AND false-positive
guards for each of GL001-GL005), suppression comments, baseline round-trip,
CLI exit codes / --stats, the self-lint of paddle_tpu against the checked-in
baseline, and the runtime cross-check proving GL001's static reachability
matches what the sync-observer hook actually sees under tracing."""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

from tools.graftlint import baseline as baseline_mod  # noqa: E402
from tools.graftlint import lint_paths  # noqa: E402
from tools.graftlint.__main__ import main as cli_main  # noqa: E402
from tools.graftlint.rules import RULES  # noqa: E402


def lint_src(tmp_path, src, rules=None, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return lint_paths([p], root=tmp_path, rules=rules)


def rule_ids(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------------------- #
# GL001 host-sync-in-trace
# --------------------------------------------------------------------------- #


class TestGL001:
    def test_numpy_and_cast_in_jitted_fn(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            @jax.jit
            def step(x):
                return float(x.numpy())
        """, rules=["GL001"])
        assert len(fs) == 2  # float() cast + .numpy() sync
        assert all(f.rule == "GL001" for f in fs)

    def test_if_on_traced_param(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            @jax.jit
            def step(x):
                if x:
                    return x + 1
                return x
        """, rules=["GL001"])
        assert rule_ids(fs) == ["GL001"]
        assert "if x:" in fs[0].message

    def test_transitive_callee_flagged(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def helper(t):
                return t.item()

            @jax.jit
            def step(x):
                return helper(x)
        """, rules=["GL001"])
        assert rule_ids(fs) == ["GL001"]
        assert ".item()" in fs[0].message

    def test_transform_arg_and_guard_body(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax
            from paddle_tpu.framework.core import tracing_guard

            def loss_fn(t):
                return t.tolist()

            g = jax.grad(loss_fn)

            def replay(fn, t):
                with tracing_guard(True):
                    return int(t)
        """, rules=["GL001"])
        msgs = " | ".join(f.message for f in fs)
        assert len(fs) == 2
        assert ".tolist()" in msgs and "int()" in msgs

    def test_eager_code_not_flagged(self, tmp_path):
        # the same syncs OUTSIDE any traced region are legitimate
        fs = lint_src(tmp_path, """
            def log_loss(t):
                return float(t.numpy())

            def fetch(t):
                if t:
                    return t.item()
        """, rules=["GL001"])
        assert fs == []

    def test_safe_casts_and_python_flags_not_flagged(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def helper(x, training):
                # Python config flag of a transitively-traced helper: a
                # static branch, not a tracer bool
                if training:
                    x = x * 2
                return float(len([x]))

            @jax.jit
            def step(x):
                return helper(x, True)
        """, rules=["GL001"])
        assert fs == []

    def test_cross_file_calls_not_followed(self, tmp_path):
        # call-graph edges are per-file by design (see rule rationale)
        (tmp_path / "helpers.py").write_text(textwrap.dedent("""
            def helper(t):
                return t.numpy()
        """))
        fs = lint_src(tmp_path, """
            import jax
            from helpers import helper

            @jax.jit
            def step(x):
                return helper(x)
        """, rules=["GL001"])
        assert fs == []

    def test_fixed_hot_path_sites_stay_clean(self):
        # regression for the .numpy() hot-path audit: the hapi fit loop and
        # the LR schedulers must stay free of traced host syncs
        fs = lint_paths(
            [REPO / "paddle_tpu/hapi/model.py",
             REPO / "paddle_tpu/optimizer/lr.py"],
            root=REPO, rules=["GL001"])
        assert fs == []

    def test_old_hapi_pattern_would_be_flagged(self, tmp_path):
        # the pre-audit idiom — per-step float(loss.numpy()) — placed where
        # it would run under trace is exactly what GL001 exists to stop
        fs = lint_src(tmp_path, """
            import jax

            @jax.jit
            def train_batch(loss):
                return [float(loss.numpy())]
        """, rules=["GL001"])
        assert len(fs) == 2


# --------------------------------------------------------------------------- #
# GL002 rank-conditional collective
# --------------------------------------------------------------------------- #


class TestGL002:
    def test_collective_under_rank_if(self, tmp_path):
        fs = lint_src(tmp_path, """
            import paddle_tpu.distributed as dist

            def sync(rank, t):
                if rank == 0:
                    dist.all_reduce(t)
        """, rules=["GL002"])
        assert rule_ids(fs) == ["GL002"]
        assert "all_reduce" in fs[0].message

    def test_else_branch_and_get_rank_call(self, tmp_path):
        fs = lint_src(tmp_path, """
            import paddle_tpu.distributed as dist

            def sync(t):
                if dist.get_rank() == 0:
                    pass
                else:
                    dist.broadcast(t, src=0)
        """, rules=["GL002"])
        assert rule_ids(fs) == ["GL002"]

    def test_unconditional_collective_ok(self, tmp_path):
        fs = lint_src(tmp_path, """
            import paddle_tpu.distributed as dist

            def sync(rank, t):
                dist.all_reduce(t)
                if rank == 0:
                    print("rank0 saw", t.shape)
        """, rules=["GL002"])
        assert fs == []

    def test_p2p_and_stdlib_reduce_ok(self, tmp_path):
        # send/recv are legitimately rank-conditional; bare `reduce` is
        # functools, not a collective
        fs = lint_src(tmp_path, """
            from functools import reduce
            import paddle_tpu.distributed as dist

            def route(rank, t, xs):
                if rank == 0:
                    dist.send(t, dst=1)
                    return reduce(lambda a, b: a + b, xs)
                dist.recv(t, src=0)
        """, rules=["GL002"])
        assert fs == []

    def test_nested_rank_if_reported_once(self, tmp_path):
        fs = lint_src(tmp_path, """
            import paddle_tpu.distributed as dist

            def sync(rank, t):
                if rank < 4:
                    if rank == 0:
                        dist.all_reduce(t)
        """, rules=["GL002"])
        assert len(fs) == 1

    def test_rank_conditional_expert_dispatch(self, tmp_path):
        """ISSUE-14 fixture: per-rank expert counts gating the MoE
        all-to-all — the canonical expert-parallel deadlock (a rank with no
        routed tokens skips the exchange while its peers park in it).
        The count-shaped guard mentions the rank, so GL002 must fire; the
        fixed form (exchange unconditionally, counts steer only payload
        layout) must stay clean."""
        fs = lint_src(tmp_path, """
            from paddle_tpu.distributed.utils import global_scatter

            def dispatch(x, local_count, global_count, rank):
                if local_count[rank] > 0:
                    return global_scatter(x, local_count, global_count)
                return x
        """, rules=["GL002"])
        assert rule_ids(fs) == ["GL002"]
        assert "global_scatter" in fs[0].message

        fs = lint_src(tmp_path, """
            from paddle_tpu.distributed.utils import global_scatter

            def dispatch(x, local_count, global_count, rank):
                out = global_scatter(x, local_count, global_count)
                if local_count[rank] == 0:
                    return x
                return out
        """, rules=["GL002"])
        assert fs == []

    def test_moe_fast_path_files_clean(self):
        """ISSUE-14 satellite: the new moe/grouped-gemm/a2a-accounting
        files lint clean with NO new baseline entries (the deadlock-shaped
        patterns above must never ship in the real dispatch path)."""
        fs = lint_paths([
            REPO / "paddle_tpu/incubate/distributed/models/moe",
            REPO / "paddle_tpu/ops/pallas/grouped_gemm.py",
            REPO / "paddle_tpu/distributed/moe_comm.py",
            REPO / "paddle_tpu/distributed/utils/moe_utils.py",
        ], root=REPO)
        assert fs == [], "\n".join(f.format() for f in fs)


# --------------------------------------------------------------------------- #
# GL003 swallowed exception
# --------------------------------------------------------------------------- #


class TestGL003:
    def test_pass_and_continue_bodies_flagged(self, tmp_path):
        fs = lint_src(tmp_path, """
            def probe(store, keys):
                try:
                    store.get("k")
                except Exception:
                    pass
                for k in keys:
                    try:
                        store.get(k)
                    except:
                        continue
        """, rules=["GL003"])
        assert rule_ids(fs) == ["GL003", "GL003"]
        assert "bare `except:`" in fs[1].message

    def test_logging_narrow_raise_ok(self, tmp_path):
        fs = lint_src(tmp_path, """
            def probe(store, log):
                try:
                    store.get("a")
                except KeyError:
                    pass
                try:
                    store.get("b")
                except Exception as e:
                    log.warning("probe failed: %r", e)
                try:
                    store.get("c")
                except Exception:
                    raise RuntimeError("store gone")
        """, rules=["GL003"])
        assert fs == []

    def test_del_allowlisted(self, tmp_path):
        fs = lint_src(tmp_path, """
            class Holder:
                def __del__(self):
                    try:
                        self.close()
                    except Exception:
                        pass
        """, rules=["GL003"])
        assert fs == []

    def test_distributed_layer_fixed_sites(self):
        # the PR-1 leftovers named in the issue: now narrowed, logging, or
        # carrying an explicit in-source disable — zero raw findings
        fs = lint_paths(
            [REPO / "paddle_tpu/distributed/eager_multiproc.py",
             REPO / "paddle_tpu/distributed/store.py",
             REPO / "paddle_tpu/distributed/fleet/elastic/manager.py"],
            root=REPO, rules=["GL003"])
        assert fs == []


# --------------------------------------------------------------------------- #
# GL004 retrace hazard
# --------------------------------------------------------------------------- #


class TestGL004:
    def test_mutable_defaults_flagged(self, tmp_path):
        fs = lint_src(tmp_path, """
            def op(x, axes=[], opts={}):
                return x
        """, rules=["GL004"])
        assert rule_ids(fs) == ["GL004", "GL004"]

    def test_scalar_default_on_jitted_fn(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            @jax.jit
            def step(x, lr=0.1):
                return x * lr
        """, rules=["GL004"])
        assert rule_ids(fs) == ["GL004"]
        assert "lr=0.1" in fs[0].message

    def test_safe_defaults_ok(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def eager(x, lr=0.1, name=None, shape=(2, 3)):
                return x

            @jax.jit
            def step(x, axis=None, mode="mean"):
                return x
        """, rules=["GL004"])
        assert fs == []


# --------------------------------------------------------------------------- #
# GL005 RNG key reuse
# --------------------------------------------------------------------------- #


class TestGL005:
    def test_straight_line_reuse(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def init(key, shape):
                a = jax.random.normal(key, shape)
                b = jax.random.uniform(key, shape)
                return a + b
        """, rules=["GL005"])
        assert rule_ids(fs) == ["GL005"]
        assert "already consumed" in fs[0].message

    def test_loop_reuse(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def layers(key, n):
                return [jax.random.normal(key, (4,)) for _ in range(n)] and [
                    jax.random.normal(key, (4,)) for _ in range(n)]
        """, rules=["GL005"])
        # two comprehension uses of the same key in one statement
        assert rule_ids(fs) == ["GL005"]

    def test_for_loop_without_split(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def noise(key, n):
                out = []
                for _ in range(n):
                    out.append(jax.random.normal(key, (3,)))
                return out
        """, rules=["GL005"])
        assert rule_ids(fs) == ["GL005"]
        assert "loop" in fs[0].message

    def test_split_between_uses_ok(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def init(key, shape):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1, shape)
                b = jax.random.uniform(k2, shape)
                return a + b

            def loop(key, n):
                out = []
                for _ in range(n):
                    key, sub = jax.random.split(key)
                    out.append(jax.random.normal(sub, (3,)))
                return out
        """, rules=["GL005"])
        assert fs == []

    def test_exclusive_branches_ok(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def sample(key, flag, shape):
                if flag:
                    return jax.random.normal(key, shape)
                return jax.random.uniform(key, shape)
        """, rules=["GL005"])
        assert fs == []

    def test_split_inside_with_body_ok(self, tmp_path):
        # the in-tree idiom: RNG code under `with tracing_guard(True):`.
        # The body must be scanned sequentially — a flat scan would see the
        # second sampler before the split reassignment and false-positive
        fs = lint_src(tmp_path, """
            import jax
            from paddle_tpu.framework.core import tracing_guard

            def sample(key, ctx, shape):
                with tracing_guard(True):
                    a = jax.random.normal(key, shape)
                    key = jax.random.split(key)[0]
                    b = jax.random.normal(key, shape)
                return a + b
        """, rules=["GL005"])
        assert fs == []

    def test_reuse_inside_with_body_flagged(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def sample(key, ctx, shape):
                with ctx:
                    a = jax.random.normal(key, shape)
                    b = jax.random.uniform(key, shape)
                return a + b
        """, rules=["GL005"])
        assert rule_ids(fs) == ["GL005"]

    def test_numpy_stateful_api_ok(self, tmp_path):
        # np.random.normal(loc, scale) has no key argument — positional
        # Name reuse there must not be mistaken for key reuse
        fs = lint_src(tmp_path, """
            import numpy as np

            def jitter(mu, sigma):
                a = np.random.normal(mu, sigma)
                b = np.random.normal(mu, sigma)
                return a + b
        """, rules=["GL005"])
        assert fs == []


# --------------------------------------------------------------------------- #
# hot-path audit regressions (satellite: per-step host syncs in hapi fit)
# --------------------------------------------------------------------------- #


class TestHotPathAudit:
    def test_recorder_callback_accepts_device_loss(self, tmp_path):
        # between log points the fit loop hands callbacks the 0-d device
        # Tensor; the jsonl/VisualDL recorder must still capture every step
        import paddle_tpu as paddle
        from paddle_tpu.hapi.callbacks import VisualDL

        cb = VisualDL(str(tmp_path / "vdl"))
        cb.epoch = 0
        cb.on_train_batch_end(0, {"loss": 0.5})
        cb.on_train_batch_end(1, {"loss": paddle.to_tensor(0.25)})
        cb.on_train_batch_end(2, {"loss": "not-a-number"})
        recorded = (tmp_path / "vdl" / "train.jsonl").read_text().splitlines()
        assert [json.loads(l)["value"] for l in recorded] == [0.5, 0.25]

    def test_fit_passes_float_at_log_boundaries(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi.callbacks import Callback

        seen = {}

        class Spy(Callback):
            def on_train_batch_end(self, step, logs=None):
                seen[step] = logs["loss"]

        net = nn.Linear(2, 1)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.01,
                                           parameters=net.parameters()),
            loss=nn.MSELoss())
        x = np.ones((4, 2), "float32")
        y = np.ones((4, 1), "float32")
        batches = [(paddle.to_tensor(x), paddle.to_tensor(y))] * 4
        model.fit(batches, epochs=1, log_freq=2, verbose=0,
                  callbacks=[Spy()])
        assert isinstance(seen[0], float) and isinstance(seen[2], float)
        # non-log steps carry the device scalar, float()-able on demand
        assert float(seen[1]) >= 0.0

    def test_fit_honors_train_batch_override(self):
        # subclassing train_batch is the paddle.Model extension point; the
        # async fast path must defer to it, not silently bypass it
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        calls = []

        class Custom(paddle.Model):
            def train_batch(self, inputs, labels=None, update=True):
                calls.append(1)
                return super().train_batch(inputs, labels, update)

        net = nn.Linear(2, 1)
        model = Custom(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.01,
                                           parameters=net.parameters()),
            loss=nn.MSELoss())
        b = (paddle.to_tensor(np.ones((4, 2), "float32")),
             paddle.to_tensor(np.ones((4, 1), "float32")))
        model.fit([b, b], epochs=1, verbose=0)
        assert len(calls) == 2


# --------------------------------------------------------------------------- #
# suppression comments
# --------------------------------------------------------------------------- #


class TestSuppression:
    SRC = """
        def probe(store):
            try:
                store.get("k")
            except Exception:{comment}
                pass
    """

    def test_matching_rule_suppressed(self, tmp_path):
        fs = lint_src(tmp_path, self.SRC.format(
            comment="  # graftlint: disable=GL003 best-effort probe"))
        assert fs == []

    def test_all_and_multi_rule_lists(self, tmp_path):
        fs = lint_src(tmp_path, self.SRC.format(
            comment="  # graftlint: disable=all"))
        assert fs == []
        fs = lint_src(tmp_path, self.SRC.format(
            comment="  # graftlint: disable=GL001, GL003"))
        assert fs == []

    def test_wrong_rule_does_not_suppress(self, tmp_path):
        fs = lint_src(tmp_path, self.SRC.format(
            comment="  # graftlint: disable=GL001"))
        assert rule_ids(fs) == ["GL003"]


# --------------------------------------------------------------------------- #
# baseline round-trip
# --------------------------------------------------------------------------- #

_VIOLATION = """
    def probe(store):
        try:
            store.get("k")
        except Exception:
            pass
"""
_VIOLATION_TWICE = _VIOLATION + """
    def probe2(store):
        try:
            store.get("j")
        except Exception:
            pass
"""


class TestBaseline:
    def test_round_trip_add_fix_shrink(self, tmp_path):
        src_file = tmp_path / "mod.py"
        bl_file = tmp_path / "baseline.json"

        # 1. two violations, baselined: clean
        src_file.write_text(textwrap.dedent(_VIOLATION_TWICE))
        findings = lint_paths([src_file], root=tmp_path)
        assert len(findings) == 2
        baseline_mod.save(bl_file, findings)
        new, known = baseline_mod.partition(findings, baseline_mod.load(bl_file))
        assert new == [] and len(known) == 2

        # 2. a third identical violation appears: exactly one NEW finding
        #    (fingerprints are count-aware, not just set membership)
        src3 = textwrap.dedent(_VIOLATION_TWICE) + textwrap.dedent(_VIOLATION).replace("probe", "probe3")
        src_file.write_text(src3)
        new, known = baseline_mod.partition(
            lint_paths([src_file], root=tmp_path), baseline_mod.load(bl_file))
        assert len(new) == 1 and len(known) == 2

        # 3. fix all but one and rewrite: the baseline shrinks
        src_file.write_text(textwrap.dedent(_VIOLATION))
        remaining = lint_paths([src_file], root=tmp_path)
        baseline_mod.save(bl_file, remaining)
        entries = json.loads(bl_file.read_text())["entries"]
        assert sum(entries.values()) == 1

    def test_line_moves_do_not_invalidate(self, tmp_path):
        src_file = tmp_path / "mod.py"
        bl_file = tmp_path / "baseline.json"
        src_file.write_text(textwrap.dedent(_VIOLATION))
        baseline_mod.save(bl_file, lint_paths([src_file], root=tmp_path))
        # unrelated code added above: line numbers shift, fingerprint stays
        src_file.write_text("x = 1\ny = 2\n" + textwrap.dedent(_VIOLATION))
        new, known = baseline_mod.partition(
            lint_paths([src_file], root=tmp_path), baseline_mod.load(bl_file))
        assert new == [] and len(known) == 1

    def test_parse_errors_never_baselined(self, tmp_path):
        # GL000 fingerprints carry no snippet — baselining one would absorb
        # every future parse error in the file (truncated checkouts included)
        src_file = tmp_path / "broken.py"
        bl_file = tmp_path / "baseline.json"
        src_file.write_text("def oops(:\n")
        findings = lint_paths([src_file], root=tmp_path)
        assert rule_ids(findings) == ["GL000"]
        baseline_mod.save(bl_file, findings)
        assert json.loads(bl_file.read_text())["entries"] == {}
        new, known = baseline_mod.partition(
            findings, baseline_mod.load(bl_file))
        assert rule_ids(new) == ["GL000"] and known == []

    def test_corrupt_baseline_rejected(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text('{"version": 99, "entries": {}}')
        with pytest.raises(ValueError):
            baseline_mod.load(bad)
        bad.write_text('{"no_entries": true}')
        with pytest.raises(ValueError):
            baseline_mod.load(bad)


# --------------------------------------------------------------------------- #
# CLI: exit codes, --stats, self-lint
# --------------------------------------------------------------------------- #


class TestCLI:
    def _fixture_dir(self, tmp_path):
        (tmp_path / "clean.py").write_text("def ok(x):\n    return x\n")
        (tmp_path / "dirty.py").write_text(textwrap.dedent(_VIOLATION))
        return tmp_path

    def test_exit_codes_in_process(self, tmp_path, capsys):
        d = self._fixture_dir(tmp_path)
        assert cli_main([str(d / "clean.py"), "--root", str(d)]) == 0
        assert cli_main([str(d / "dirty.py"), "--root", str(d)]) == 1
        out = capsys.readouterr().out
        assert "dirty.py" in out and "GL003" in out
        # internal errors: missing path / unknown rule / unreadable baseline
        assert cli_main([str(d / "missing.py")]) == 2
        assert cli_main([str(d), "--rules", "GL999"]) == 2
        assert cli_main([]) == 2

    def test_stats_exact_counts(self, tmp_path, capsys):
        d = self._fixture_dir(tmp_path)
        assert cli_main([str(d), "--root", str(d), "--stats"]) == 1
        lines = capsys.readouterr().out.strip().splitlines()
        stats = dict(l.split(" ", 1) for l in lines)
        assert stats["GL003"] == "total=1 new=1"
        assert stats["GL001"] == "total=0 new=0"
        assert stats["TOTAL"] == "total=1 new=1"

    def test_baseline_flag_and_write(self, tmp_path, capsys):
        d = self._fixture_dir(tmp_path)
        bl = d / "bl.json"
        assert cli_main([str(d), "--root", str(d),
                         "--write-baseline", str(bl)]) == 0
        assert cli_main([str(d), "--root", str(d), "--baseline", str(bl)]) == 0
        out = capsys.readouterr().out
        assert "clean" in out

    def test_subprocess_entry_point(self, tmp_path):
        # the documented invocation: `python -m tools.graftlint <path>`
        d = self._fixture_dir(tmp_path)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", str(d / "dirty.py")],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1, proc.stderr
        assert "GL003" in proc.stdout


class TestSelfLint:
    @pytest.fixture(scope="class")
    def tree_findings(self):
        return lint_paths([REPO / "paddle_tpu"], root=REPO)

    def test_no_findings_above_baseline(self, tree_findings):
        baseline = baseline_mod.load(REPO / "tools/graftlint/baseline.json")
        new, known = baseline_mod.partition(tree_findings, baseline)
        assert new == [], "new graftlint findings:\n" + "\n".join(
            f.format() for f in new)

    def test_baseline_not_stale(self, tree_findings):
        # every baselined entry still corresponds to a real finding — fixed
        # violations must be removed (--write-baseline) so the ratchet
        # tightens instead of leaving headroom for regressions
        baseline = baseline_mod.load(REPO / "tools/graftlint/baseline.json")
        current = baseline_mod.aggregate(tree_findings)
        stale = {k: n - current.get(k, 0) for k, n in baseline.items()
                 if n > current.get(k, 0)}
        assert stale == {}, f"stale baseline entries: {stale}"

    def test_every_rule_registered(self):
        assert list(RULES) == ["GL001", "GL002", "GL003", "GL004", "GL005",
                               "GL006"]


# --------------------------------------------------------------------------- #
# runtime cross-check: GL001 static and dynamic analyses agree
# --------------------------------------------------------------------------- #

# one snippet, linted statically AND executed dynamically
_HOST_SYNC_SNIPPET = textwrap.dedent("""
    import paddle_tpu

    def traced_loss(t):
        return float(t.numpy())

    step = paddle_tpu.jit.to_static(traced_loss)
""")


@pytest.fixture
def runtime_checks():
    from tools.graftlint import runtime as rt

    rt.install_runtime_checks("raise")
    try:
        yield rt
    finally:
        rt.uninstall_runtime_checks()
        rt.reset_runtime_events()


class TestRuntimeCrossCheck:
    def test_static_and_dynamic_agree(self, tmp_path, runtime_checks):
        import paddle_tpu as paddle

        # static: GL001 flags the deliberate host-sync-under-trace
        f = tmp_path / "snippet.py"
        f.write_text(_HOST_SYNC_SNIPPET)
        static = lint_paths([f], root=tmp_path, rules=["GL001"])
        assert {fi.rule for fi in static} == {"GL001"}
        flagged_lines = {fi.line for fi in static}

        # dynamic: executing the same snippet raises at trace time
        ns: dict = {}
        exec(compile(_HOST_SYNC_SNIPPET, str(f), "exec"), ns)
        with pytest.raises(runtime_checks.HostSyncInTraceError):
            ns["step"](paddle.to_tensor(2.5))
        events = runtime_checks.runtime_report()["host_syncs_in_trace"]
        assert events and events[0]["kind"] == "array"
        # the sync the observer caught is on a line the static pass flagged
        assert any("float(t.numpy())" in fi.snippet for fi in static)
        assert flagged_lines  # non-empty: both analyses located the sync

    def test_without_checks_sot_fallback_is_silent(self):
        # baseline behavior the runtime mode exists to surface: the same
        # sync silently degrades to SOT graph-break capture (perf loss, no
        # error) when enforcement is off
        import paddle_tpu as paddle

        ns: dict = {}
        exec(_HOST_SYNC_SNIPPET, ns)
        out = ns["step"](paddle.to_tensor(2.5))
        assert float(out) == 2.5
        assert ns["step"]._sot_fallen_back[0] is True

    def test_tracing_guard_direct(self, runtime_checks):
        import paddle_tpu as paddle
        from paddle_tpu.framework.core import tracing_guard

        t = paddle.to_tensor(1.0)
        assert float(t) == 1.0  # outside tracing: observer passes through
        with tracing_guard(True):
            with pytest.raises(runtime_checks.HostSyncInTraceError):
                t.numpy()
        assert t.tolist() == 1.0  # guard restored

    def test_warn_mode(self):
        import paddle_tpu as paddle
        from paddle_tpu.framework.core import tracing_guard
        from tools.graftlint import runtime as rt

        rt.install_runtime_checks("warn")
        try:
            t = paddle.to_tensor(3.0)
            with tracing_guard(True):
                with pytest.warns(rt.GraftlintRuntimeWarning):
                    v = t.numpy()
            assert float(v) == 3.0
        finally:
            rt.uninstall_runtime_checks()
            rt.reset_runtime_events()

    def test_report_surfaces_dispatch_cache_stats(self, runtime_checks):
        import paddle_tpu as paddle

        a = paddle.to_tensor([1.0, 2.0])
        _ = a + a  # at least one dispatched op
        rep = runtime_checks.runtime_report()
        assert set(rep) >= {"host_syncs_in_trace", "traced_op_census",
                            "dispatch_cache", "uncacheable_ops",
                            "bypassed_ops"}
        assert {"hits", "misses", "bypass"} <= set(rep["dispatch_cache"])
        assert isinstance(rep["uncacheable_ops"], list)
        text = runtime_checks.format_report()
        assert "dispatch cache" in text

    def test_op_census_counts_traced_ops(self, runtime_checks):
        import paddle_tpu as paddle

        def f(t):
            return t + t

        stepped = paddle.jit.to_static(f)
        stepped(paddle.to_tensor([1.0, 2.0]))
        census = runtime_checks.runtime_report()["traced_op_census"]
        assert census, "expected ops dispatched under tracing to be counted"

    def test_env_activation(self, monkeypatch):
        import paddle_tpu
        from tools.graftlint import runtime as rt

        assert not rt._state["installed"]
        # the conventional disable spellings must NOT arm strict raise mode
        for off in ("0", "false", "OFF", ""):
            monkeypatch.setenv("GRAFTLINT_RUNTIME", off)
            paddle_tpu._maybe_install_graftlint_runtime()
            assert not rt._state["installed"], f"GRAFTLINT_RUNTIME={off!r}"
        monkeypatch.setenv("GRAFTLINT_RUNTIME", "1")
        paddle_tpu._maybe_install_graftlint_runtime()
        try:
            assert rt._state["installed"] and rt._state["mode"] == "raise"
        finally:
            rt.uninstall_runtime_checks()
            rt.reset_runtime_events()


# --------------------------------------------------------------------------- #
# GL006 unlabeled hot-path metric
# --------------------------------------------------------------------------- #


class TestGL006:
    def test_emission_in_jitted_fn(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            @jax.jit
            def step(x, STEP_TOTAL, LAT):
                STEP_TOTAL.inc()
                LAT.observe(0.1)
                return x * 2
        """, rules=["GL006"])
        assert rule_ids(fs) == ["GL006", "GL006"]
        assert ".inc()" in fs[0].message
        assert "host callback" in fs[0].message

    def test_metricish_set_in_tracing_guard(self, tmp_path):
        fs = lint_src(tmp_path, """
            def run(fn, x, hb_gauge):
                with tracing_guard(True):
                    out = fn(x)
                    hb_gauge.set(1.0)
                return out
        """, rules=["GL006"])
        assert rule_ids(fs) == ["GL006"]

    def test_transitive_callee_flagged(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            def bump(counter):
                counter.inc(1, op="fwd")

            def loss(x, counter):
                bump(counter)
                return x.sum()

            grad_fn = jax.grad(loss)
        """, rules=["GL006"])
        assert rule_ids(fs) == ["GL006"]
        assert "bump" in fs[0].message

    def test_stdlib_set_add_and_eager_emission_not_flagged(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            @jax.jit
            def step(x, seen, cfg):
                seen.add(3)          # builtin set, not a metric
                cfg.set("k", "v")    # non-metric receiver
                return x * 2

            def eager_loop(STEP_TOTAL, LAT):
                # emission OUTSIDE any traced region is the sanctioned
                # pattern (the fit loop / StepTimeline.step_end)
                STEP_TOTAL.inc()
                LAT.observe(0.5)
        """, rules=["GL006"])
        assert fs == []

    def test_suppression_comment(self, tmp_path):
        fs = lint_src(tmp_path, """
            import jax

            @jax.jit
            def step(x, C):
                C.inc()  # graftlint: disable=GL006 trace-time once is intended
                return x
        """, rules=["GL006"])
        assert fs == []

    def test_repo_hot_paths_stay_clean(self):
        """The shipped emitters (fit loop, collectives, trainer, timer) all
        emit outside traces — GL006 over the package must not regress."""
        fs = lint_paths([REPO / "paddle_tpu"], root=REPO, rules=["GL006"])
        assert fs == []
