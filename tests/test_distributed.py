"""Distributed stack tests on the 8-device virtual CPU mesh — the analog of
the reference's spawn-on-localhost fake cluster
(test/legacy_test/test_parallel_dygraph_dataparallel.py:30)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.collective import primitives


@pytest.fixture(autouse=True)
def reset_groups():
    yield
    dist.destroy_process_group()
    dist.env.set_global_mesh(None)


class TestTopology:
    def test_mesh_axes(self):
        mesh = dist.build_mesh(dp=2, mp=4)
        assert mesh.shape == {"dp": 2, "pp": 1, "sharding": 1, "sep": 1,
                              "ep": 1, "mp": 4}
        assert mesh.devices.size == 8

    def test_communicate_topology(self):
        from paddle_tpu.distributed.fleet.base.topology import CommunicateTopology

        topo = CommunicateTopology(dims=(2, 1, 1, 1, 4))
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, pipe=0, sharding=0, sep=0, model=2) == 6
        assert topo.get_comm_list("model") == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert topo.get_comm_list("data") == [[0, 4], [1, 5], [2, 6], [3, 7]]

    def test_fleet_init_and_hcg(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_parallel_mode() == "tensor_parallel"
        assert hcg.mesh.shape["mp"] == 4


class TestEagerCollectives:
    def test_all_reduce_stacked(self):
        g = dist.new_group(list(range(4)))
        t = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(4, 2))
        dist.all_reduce(t, group=g)
        ref = np.broadcast_to(np.arange(8, dtype=np.float32).reshape(4, 2).sum(0), (4, 2))
        np.testing.assert_allclose(t.numpy(), ref)

    def test_all_gather(self):
        g = dist.new_group(list(range(4)))
        t = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(4, 1))
        out = []
        dist.all_gather(out, t, group=g)
        assert len(out) == 4
        np.testing.assert_allclose(out[2].numpy(), [2.0])

    def test_broadcast(self):
        g = dist.new_group(list(range(4)))
        t = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(4, 1))
        dist.broadcast(t, src=1, group=g)
        np.testing.assert_allclose(t.numpy(), np.ones((4, 1)))

    def test_alltoall(self):
        g = dist.new_group(list(range(2)))
        # in_list[j][i] = what rank i sends to slot j
        a = paddle.to_tensor(np.array([[0.0], [10.0]], np.float32))
        b = paddle.to_tensor(np.array([[1.0], [11.0]], np.float32))
        out = []
        dist.alltoall(out, [a, b], group=g)
        np.testing.assert_allclose(out[0].numpy(), [[0.0], [1.0]])
        np.testing.assert_allclose(out[1].numpy(), [[10.0], [11.0]])

    def test_reduce_op_variants(self):
        g = dist.new_group(list(range(2)))
        t = paddle.to_tensor(np.array([[1.0], [3.0]], np.float32))
        dist.all_reduce(t, op=dist.ReduceOp.MAX, group=g)
        np.testing.assert_allclose(t.numpy(), [[3.0], [3.0]])


class TestPrimitives:
    def test_psum_inside_shard_map(self):
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        mesh = dist.build_mesh(dp=8)
        x = jnp.arange(8.0)

        def body(v):
            return primitives.all_reduce(v, axis="dp")

        f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        out = f(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_ppermute_ring(self):
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        mesh = dist.build_mesh(pp=8)
        x = jnp.arange(8.0)
        perm = [(i, (i + 1) % 8) for i in range(8)]

        def body(v):
            return primitives.ppermute(v, "pp", perm)

        out = shard_map(body, mesh=mesh, in_specs=P("pp"), out_specs=P("pp"))(x)
        np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


class TestTensorParallelLayers:
    def test_column_row_match_dense(self):
        paddle.seed(0)
        fleet_strategy = fleet.DistributedStrategy()
        fleet_strategy.hybrid_configs = {"mp_degree": 4, "dp_degree": 2}
        fleet.init(is_collective=True, strategy=fleet_strategy)
        from paddle_tpu.distributed.fleet.layers.mpu import (
            ColumnParallelLinear,
            RowParallelLinear,
        )

        col = ColumnParallelLinear(8, 16, gather_output=False)
        row = RowParallelLinear(16, 8, input_is_parallel=True)
        x = paddle.randn([2, 8])
        out = row(col(x))
        # dense oracle with the same weights
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
        # sharding metadata present for the compiled path
        from jax.sharding import PartitionSpec as P

        assert col.weight.dist_attr == P(None, "mp")
        assert row.weight.dist_attr == P("mp", None)

    def test_vocab_parallel_embedding(self):
        from paddle_tpu.distributed.fleet.layers.mpu import VocabParallelEmbedding

        dist.build_mesh(mp=4, dp=2)
        emb = VocabParallelEmbedding(16, 8)
        ids = paddle.to_tensor([[1, 5], [7, 3]], dtype="int32")
        out = emb(ids)
        assert out.shape == [2, 2, 8]
        np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1], rtol=1e-6)


class TestDistributedTrainStep:
    def _mlp_with_tp(self):
        from paddle_tpu.distributed.fleet.layers.mpu import (
            ColumnParallelLinear,
            RowParallelLinear,
        )

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = ColumnParallelLinear(8, 32, gather_output=False)
                self.fc2 = RowParallelLinear(32, 8, input_is_parallel=True)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        return MLP()

    def test_dp_mp_train_step_runs_sharded(self):
        paddle.seed(0)
        mesh = dist.build_mesh(dp=2, mp=4)
        net = self._mlp_with_tp()
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
        step = dist.DistributedTrainStep(net, F.mse_loss, opt, mesh=mesh)
        rng = np.random.RandomState(0)
        X = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        losses = [float(step(X, y).numpy()) for _ in range(10)]
        assert losses[-1] < losses[0]
        # fc1 weight must actually be sharded over mp
        sh = step.params["fc1.weight"].sharding
        assert "mp" in str(sh.spec)

    def test_matches_single_device_training(self):
        """Numeric parity: dp=2 x mp=4 vs single-device, same seeds/data —
        the hybrid_parallel_mp_model.py test pattern."""
        rng = np.random.RandomState(1)
        X = rng.rand(8, 8).astype(np.float32)
        y = rng.rand(8, 8).astype(np.float32)

        def run(distributed):
            paddle.seed(7)
            if distributed:
                mesh = dist.build_mesh(dp=2, mp=4)
            else:
                dist.env.set_global_mesh(None)
            net = self._mlp_with_tp()
            opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
            if distributed:
                step = dist.DistributedTrainStep(net, F.mse_loss, opt, mesh=mesh)
            else:
                step = paddle.jit.TrainStep(net, F.mse_loss, opt)
            out = [float(step(paddle.to_tensor(X), paddle.to_tensor(y)).numpy()) for _ in range(5)]
            step.sync_weights()
            return out, net.fc1.weight.numpy()

        dist_losses, dist_w = run(True)
        single_losses, single_w = run(False)
        np.testing.assert_allclose(dist_losses, single_losses, rtol=1e-4)
        np.testing.assert_allclose(dist_w, single_w, rtol=1e-4, atol=1e-5)

    def test_sharding_stage1_opt_states_sharded(self):
        paddle.seed(0)
        mesh = dist.build_mesh(sharding=8)
        net = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 16))
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
        step = dist.DistributedTrainStep(net, F.mse_loss, opt, mesh=mesh, sharding_stage=1)
        m_state = step.opt_states["0.weight"]["m"]
        assert "sharding" in str(m_state.sharding.spec)
        X = paddle.randn([8, 16])
        y = paddle.randn([8, 16])
        l0 = float(step(X, y).numpy())
        l1 = float(step(X, y).numpy())
        assert np.isfinite(l1)

    def test_sharding_stage3_params_sharded(self):
        paddle.seed(0)
        mesh = dist.build_mesh(sharding=8)
        net = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 16))
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
        step = dist.DistributedTrainStep(net, F.mse_loss, opt, mesh=mesh, sharding_stage=3)
        assert "sharding" in str(step.params["0.weight"].sharding.spec)
        l0 = float(step(paddle.randn([8, 16]), paddle.randn([8, 16])).numpy())
        assert np.isfinite(l0)


class TestGroupShardedAPI:
    def test_levels(self):
        dist.build_mesh(sharding=8)
        net = nn.Linear(8, 8)
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
        m, o, s = dist.group_sharded_parallel(net, opt, "p_g_os")
        assert o._sharding_stage == 3
        from jax.sharding import PartitionSpec as P

        assert net.weight.dist_attr is not None

    def test_bad_level_raises(self):
        net = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        with pytest.raises(ValueError):
            dist.group_sharded_parallel(net, opt, "bogus")


class TestRecompute:
    def test_eager_recompute_grads_match(self):
        from paddle_tpu.distributed.fleet.utils import recompute

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 4))
        x = paddle.randn([2, 4])

        loss1 = net(x).sum()
        loss1.backward()
        g_ref = net[0].weight.grad.numpy().copy()
        net.clear_gradients()

        out = recompute(net, x)
        out.sum().backward()
        np.testing.assert_allclose(net[0].weight.grad.numpy(), g_ref, rtol=1e-4, atol=1e-5)

    def test_jit_recompute_in_train_step(self):
        from paddle_tpu.distributed.fleet.utils import recompute

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 1)

            def forward(self, x):
                h = recompute(lambda v: F.relu(self.fc1(v)), x)
                return self.fc2(h)

        net = Net()
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        step = paddle.jit.TrainStep(net, F.mse_loss, opt)
        loss = step(paddle.randn([4, 4]), paddle.randn([4, 1]))
        assert np.isfinite(float(loss.numpy()))


class TestDataParallel:
    def test_wrapper_api(self):
        net = nn.Linear(4, 2)
        dp = paddle.DataParallel(net)
        out = dp(paddle.ones([2, 4]))
        assert out.shape == [2, 2]
        assert len(dp.parameters()) == 2
        assert "weight" in dict(dp.named_parameters())


class TestCollectiveRegressions:
    """Fixes from review: p2p mailbox routing, alltoall_single transpose,
    reduce_scatter non-SUM axis, fused dp-sep group."""

    def test_send_recv_nonzero_dst(self):
        import paddle_tpu.distributed as dist

        g = dist.new_group(list(range(4)))
        t = paddle.to_tensor(np.arange(4, dtype="float32"))
        dist.send(t, dst=1, group=g)
        out = paddle.zeros([4])
        dist.recv(out, src=0, group=g)
        np.testing.assert_allclose(out.numpy(), t.numpy())

    def test_alltoall_single_transpose(self):
        import paddle_tpu.distributed as dist

        dist.init_parallel_env()
        n = dist.get_world_size()
        g = dist.new_group(list(range(2)))
        # stacked [src=2, dst=2, per=1] rows: a0 b0 / a1 b1 -> a0 a1 / b0 b1
        src = paddle.to_tensor(np.array([[0.0], [1.0], [2.0], [3.0]], "float32"))
        out = paddle.zeros([4, 1])
        dist.alltoall_single(out, src, group=g)
        np.testing.assert_allclose(out.numpy().ravel(), [0.0, 2.0, 1.0, 3.0])

    def test_reduce_scatter_max(self):
        import paddle_tpu.distributed as dist

        g = dist.new_group(list(range(2)))
        # entry j = per-source contributions for destination j
        t0 = paddle.to_tensor(np.array([[1.0], [8.0]], "float32"))
        t1 = paddle.to_tensor(np.array([[3.0], [2.0]], "float32"))
        out = paddle.zeros([2, 1])
        dist.reduce_scatter(out, [t0, t1], op=dist.ReduceOp.MAX, group=g)
        np.testing.assert_allclose(out.numpy().ravel(), [8.0, 3.0])

    def test_dp_sep_group_ranks(self):
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology,
            HybridCommunicateGroup,
        )

        topo = CommunicateTopology(dims=(2, 1, 1, 2, 2))  # dp=2, sep=2, mp=2
        hcg = HybridCommunicateGroup(topo)
        # rank 0's dp-sep peers: all ranks with the same mp coordinate
        ranks = hcg.get_dp_sep_parallel_group().ranks
        assert len(ranks) == 4
        assert 0 in ranks


class TestMixPrecisionUtils:
    def test_main_grad_accumulation_and_step(self):
        """fleet.utils.mix_precision_utils: bf16 grads accumulate into f32
        main_grad via hooks; the wrapped optimizer steps on them (reference
        mix_precision_utils.py MixPrecisionLayer :35 / MixPrecisionOptimizer
        :97)."""
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        from paddle_tpu.distributed.fleet.utils.mix_precision_utils import (
            MixPrecisionLayer,
            MixPrecisionOptimizer,
        )

        paddle.seed(0)
        inner = nn.Linear(8, 4)
        for _, p in inner.named_parameters():
            p._value = p._value.astype("bfloat16")
        model = MixPrecisionLayer(inner, dtype="bfloat16")
        o = MixPrecisionOptimizer(
            opt.SGD(learning_rate=0.1, parameters=inner.parameters()))
        losses = []
        for _ in range(5):
            x = paddle.to_tensor(
                np.ones((4, 8), np.float32)).astype("bfloat16")
            loss = (model(x).astype("float32") ** 2).mean()
            loss.backward()
            assert str(inner.weight.main_grad._value.dtype) == "float32"
            o.step()
            o.clear_grad()
            assert inner.weight.main_grad is None  # cleared with grads
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


class TestHybridParallelOptimizer:
    def test_distributed_clip_single_controller_matches_plain(self):
        """On a single-controller 2-mp mesh params hold global values, so the
        distributed clip must equal the plain ClipGradByGlobalNorm result
        (mp reduction is a placement no-op; replicated params counted once)."""
        from paddle_tpu.framework.core import Parameter
        import paddle_tpu.optimizer as opt

        strat = fleet.DistributedStrategy()
        strat.hybrid_configs = {"dp_degree": 1, "mp_degree": 2}
        fleet.init(is_collective=True, strategy=strat)

        def build():
            wd = Parameter(jnp.zeros((4,), jnp.float32))
            wd.is_distributed = True
            wr = Parameter(jnp.zeros((2,), jnp.float32))
            wd.grad = paddle.to_tensor(np.arange(4, dtype=np.float32))
            wr.grad = paddle.to_tensor(np.asarray([6.0, 8.0], np.float32))
            return wd, wr

        wd1, wr1 = build()
        inner = opt.SGD(learning_rate=1.0, parameters=[wd1, wr1],
                        grad_clip=nn.ClipGradByGlobalNorm(1.0))
        hpo = fleet.distributed_optimizer(inner)
        assert hpo._dist_clip is not None, "global-norm clip not wrapped"
        hpo.step()

        wd2, wr2 = build()
        plain = opt.SGD(learning_rate=1.0, parameters=[wd2, wr2],
                        grad_clip=nn.ClipGradByGlobalNorm(1.0))
        plain.step()
        np.testing.assert_allclose(wd1.numpy(), wd2.numpy(), rtol=1e-6)
        np.testing.assert_allclose(wr1.numpy(), wr2.numpy(), rtol=1e-6)

    def test_param_list_dedup(self):
        from paddle_tpu.framework.core import Parameter
        import paddle_tpu.optimizer as opt

        strat = fleet.DistributedStrategy()
        strat.hybrid_configs = {"dp_degree": 1, "mp_degree": 2}
        fleet.init(is_collective=True, strategy=strat)
        shared = Parameter(jnp.zeros((2,), jnp.float32))
        other = Parameter(jnp.zeros((2,), jnp.float32))
        inner = opt.SGD(learning_rate=1.0,
                        parameters=[shared, other, shared])
        hpo = fleet.distributed_optimizer(inner)
        assert len(hpo._obtain_optimizer_parameters_list()) == 2
        # the twice-listed (tied) param is updated exactly ONCE per step
        shared.grad = paddle.to_tensor(np.ones(2, np.float32))
        other.grad = paddle.to_tensor(np.ones(2, np.float32))
        hpo.step()
        np.testing.assert_allclose(shared.numpy(), -1.0)
        np.testing.assert_allclose(other.numpy(), -1.0)


class TestStoreKeyCleanup:
    """ADVICE round-3: group-communicator store keys must not leak for the
    job's life — destroy_process_group sweeps this rank's residual gar/
    keys (eager_multiproc.cleanup_group_keys)."""

    def test_rolling_and_destroy_cleanup(self, monkeypatch):
        from paddle_tpu.distributed import eager_multiproc as mp
        from paddle_tpu.distributed.store import TCPStore

        store = TCPStore(is_master=True, port=0)
        try:
            monkeypatch.setattr(mp, "rank", lambda: 0)
            monkeypatch.setattr(mp, "nprocs", lambda: 2)
            mp._group_seq.clear()
            for _ in range(4):
                out = mp.store_allreduce_group(
                    store, np.array([2.0]), [0], gid=7)
                assert float(out[0]) == 2.0
            tag = "0#g7"
            live = [s for s in range(4)
                    if store.tryget(f"gar/{tag}/{s}/0") is not None]
            # rolling cleanup keeps only the last two rounds
            assert live == [2, 3], live

            # destroy_process_group sweeps the rest
            import paddle_tpu.distributed as dist
            from paddle_tpu.distributed import store as store_mod

            monkeypatch.setattr(store_mod,
                                "create_or_get_global_tcp_store",
                                lambda *a, **k: store)
            dist.destroy_process_group()
            live = [s for s in range(4)
                    if store.tryget(f"gar/{tag}/{s}/0") is not None]
            assert live == [], live
            assert tag not in mp._group_seq
        finally:
            mp._group_seq.clear()
            store.close()
