"""Flagship decoder LM tests: GPT/LLaMA variants, fused incubate ops,
hybrid-parallel parity, decode cache (reference test model:
test/collective/fleet/hybrid_parallel_mp_model.py — parallel-vs-single
numeric parity as the oracle)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.optimizer as opt
from paddle_tpu import nn
from paddle_tpu.incubate.nn import functional as IF
from paddle_tpu.models import (
    GPTForCausalLM,
    GPTPretrainingCriterion,
    gpt3_tiny,
    gpt3_1p3b,
    llama_tiny,
    llama_7b,
)


class TestIncubateFunctional:
    def test_swiglu(self):
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        y = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        out = IF.swiglu(x, y)
        ref = (x.numpy() / (1 + np.exp(-x.numpy()))) * y.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)
        # single-input form splits in half
        out2 = IF.swiglu(paddle.to_tensor(np.concatenate([x.numpy(), y.numpy()], -1)))
        np.testing.assert_allclose(out2.numpy(), ref, rtol=1e-5)

    def test_fused_rms_norm_residual(self):
        x = np.random.randn(2, 4, 8).astype("float32")
        r = np.random.randn(2, 4, 8).astype("float32")
        w = np.random.rand(8).astype("float32") + 0.5
        out, res = IF.fused_rms_norm(
            paddle.to_tensor(x), paddle.to_tensor(w), residual=paddle.to_tensor(r)
        )
        h = x + r
        ref = h / np.sqrt((h ** 2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(res.numpy(), h, rtol=1e-5)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_fused_layer_norm(self):
        x = np.random.randn(2, 6, 8).astype("float32")
        w = np.random.rand(8).astype("float32") + 0.5
        b = np.random.randn(8).astype("float32")
        out, _ = IF.fused_layer_norm(paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(b))
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mu) / np.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_fused_rope_roundtrip_neox_vs_gptj(self):
        q = np.random.randn(2, 6, 4, 8).astype("float32")
        for neox in (True, False):
            out, _, _ = IF.fused_rotary_position_embedding(
                paddle.to_tensor(q), use_neox_rotary_style=neox
            )
            assert out.shape == [2, 6, 4, 8]
            # position 0 is identity rotation
            np.testing.assert_allclose(out.numpy()[:, 0], q[:, 0], rtol=1e-5, atol=1e-6)
            # norms preserved (rotation)
            np.testing.assert_allclose(
                np.linalg.norm(out.numpy(), axis=-1), np.linalg.norm(q, axis=-1),
                rtol=1e-4,
            )

    def test_fused_rope_position_ids(self):
        q = np.random.randn(1, 4, 2, 8).astype("float32")
        pid = np.array([[0, 1, 2, 3]])
        out1, _, _ = IF.fused_rotary_position_embedding(paddle.to_tensor(q))
        out2, _, _ = IF.fused_rotary_position_embedding(
            paddle.to_tensor(q), position_ids=paddle.to_tensor(pid)
        )
        np.testing.assert_allclose(out1.numpy(), out2.numpy(), rtol=1e-5)

    def test_fused_bias_act(self):
        x = np.random.randn(3, 8).astype("float32")
        b = np.random.randn(8).astype("float32")
        out = IF.fused_bias_act(paddle.to_tensor(x), paddle.to_tensor(b), act_method="relu")
        np.testing.assert_allclose(out.numpy(), np.maximum(x + b, 0), rtol=1e-6)
        out2 = IF.fused_bias_act(paddle.to_tensor(x), act_method="swiglu")
        assert out2.shape == [3, 4]

    def test_fused_dropout_add(self):
        x = np.random.randn(4, 8).astype("float32")
        y = np.random.randn(4, 8).astype("float32")
        out = IF.fused_dropout_add(paddle.to_tensor(x), paddle.to_tensor(y), p=0.5, training=False)
        np.testing.assert_allclose(out.numpy(), x + y, rtol=1e-6)


class TestGPTModel:
    def test_forward_backward_gpt(self):
        paddle.seed(0)
        cfg = gpt3_tiny()
        m = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 16)))
        labels = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 16)))
        logits = m(ids)
        assert logits.shape == [2, 16, cfg.vocab_size]
        loss = crit(logits, labels)
        loss.backward()
        assert m.gpt.embed_tokens.weight.grad is not None
        assert np.isfinite(float(loss))

    def test_forward_backward_llama_gqa(self):
        paddle.seed(0)
        cfg = llama_tiny()
        assert cfg.kv_heads == 2 and cfg.num_heads == 4
        m = GPTForCausalLM(cfg)
        ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 8)))
        logits = m(ids)
        loss = logits.mean()
        loss.backward()
        assert m.lm_head.weight.grad is not None

    def test_flashmask_variant_matches_flash(self):
        """attn_variant="flashmask" with no document mask == plain causal."""
        paddle.seed(0)
        cfg = gpt3_tiny()
        m = GPTForCausalLM(cfg)
        ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 16)))
        ref = m(ids).numpy()
        m.config.attn_variant = "flashmask"
        for layer in m.gpt.layers:
            layer.self_attn.config = m.config
        out = m(ids).numpy()
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
        # document mask (block-diagonal over two 8-token docs) differs from
        # plain causal and still trains
        idx = np.full((2, 1, 16, 1), 16, np.int32)
        idx[:, :, :8] = 8  # keys in doc 0 masked for rows >= 8
        logits = m(ids, attn_startend_row_indices=paddle.to_tensor(idx))
        assert not np.allclose(logits.numpy(), ref, atol=1e-3)
        loss = logits.mean()
        loss.backward()
        assert m.gpt.embed_tokens.weight.grad is not None

    def test_loss_mask(self):
        paddle.seed(0)
        cfg = gpt3_tiny()
        m = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 8)))
        labels = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 8)))
        mask = np.ones((2, 8), "float32")
        mask[:, 4:] = 0
        l1 = crit(m(ids), labels, paddle.to_tensor(mask))
        assert np.isfinite(float(l1))

    def test_decode_cache_matches_full_forward(self):
        """Prefill+decode through the static KV cache == full causal forward."""
        paddle.seed(0)
        cfg = llama_tiny()
        m = GPTForCausalLM(cfg)
        m.eval()
        ids_np = np.random.randint(0, cfg.vocab_size, (1, 8))
        full = m(paddle.to_tensor(ids_np)).numpy()

        caches = m.init_kv_caches(1, 16)
        # prefill first 4 (position_ids default derives from cache_offset)
        lg, caches = m(paddle.to_tensor(ids_np[:, :4]),
                       caches=caches, cache_offset=paddle.to_tensor(0))
        np.testing.assert_allclose(lg.numpy(), full[:, :4], rtol=1e-4, atol=1e-4)
        # decode one token at a time
        for t in range(4, 8):
            lg, caches = m(paddle.to_tensor(ids_np[:, t:t + 1]),
                           caches=caches, cache_offset=paddle.to_tensor(t))
            np.testing.assert_allclose(lg.numpy()[:, 0], full[:, t], rtol=1e-4, atol=1e-4)

    def test_param_counts(self):
        assert abs(gpt3_1p3b().num_params() / 1e9 - 1.3) < 0.1
        assert abs(llama_7b().num_params() / 1e9 - 6.74) < 0.15


class TestGPTHybridParallel:
    def _build(self, seed, sp=False):
        paddle.seed(seed)
        cfg = gpt3_tiny(sequence_parallel=sp)
        m = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        return m, crit, o

    def test_hybrid_parity_dp_sharding_mp(self):
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 1024, (8, 16))
        labels = rng.integers(0, 1024, (8, 16))

        m1, c1, o1 = self._build(7)
        step1 = dist.DistributedTrainStep(m1, lambda lg, lb: c1(lg, lb), o1,
                                          mesh=dist.build_mesh())
        l1 = [float(step1(paddle.to_tensor(ids), paddle.to_tensor(labels)))
              for _ in range(3)]

        m2, c2, o2 = self._build(7, sp=True)
        mesh = dist.build_mesh(dp=2, sharding=2, mp=2)
        step2 = dist.DistributedTrainStep(m2, lambda lg, lb: c2(lg, lb), o2,
                                          mesh=mesh, sharding_stage=1)
        l2 = [float(step2(paddle.to_tensor(ids), paddle.to_tensor(labels)))
              for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-5)

    def test_mp_weight_shardings_applied(self):
        paddle.seed(0)
        dist.build_mesh(mp=4)
        cfg = gpt3_tiny()
        m = GPTForCausalLM(cfg)
        from jax.sharding import PartitionSpec as P

        attn = m.gpt.layers[0].self_attn
        assert attn.q_proj.weight.dist_attr == P(None, "mp")
        assert attn.out_proj.weight.dist_attr == P("mp", None)
        assert m.gpt.embed_tokens.weight.dist_attr == P("mp", None)
        dist.build_mesh()  # reset


class TestGraftEntry:
    def test_entry_jittable(self):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "__graft_entry__",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "__graft_entry__.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        import jax

        fn, args = mod.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (2, 16, 1024)
        mod.dryrun_multichip(8)
