"""Continuous-batching generation engine (reference L13 serving depth:
dynamic batching scheduler; here admit-while-decoding over a slotted KV
cache with one fixed-shape compiled decode program)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models import GPTForCausalLM, gpt3_tiny
from paddle_tpu.models.generation import generate


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return GPTForCausalLM(gpt3_tiny())


class TestContinuousBatching:
    def test_single_request_matches_generate(self, model):
        eng = ContinuousBatchingEngine(model, max_batch_size=4,
                                       max_seq_len=64)
        prompt = np.array([5, 7, 11, 13], np.int32)
        eng.add_request(prompt, max_new_tokens=8, temperature=0.0)
        done = eng.run()
        ref = generate(model, prompt[None], max_new_tokens=8,
                       temperature=0.0).numpy()[0]
        np.testing.assert_array_equal(done[0].output_ids,
                                      ref[: len(done[0].output_ids)])

    def test_staggered_admission_parity(self, model):
        """More requests than slots, different prompt lengths and budgets:
        every output equals its standalone greedy generation."""
        eng = ContinuousBatchingEngine(model, max_batch_size=4,
                                       max_seq_len=64)
        prompts = [np.arange(2 + i, dtype=np.int32) + 3 for i in range(6)]
        ids = [eng.add_request(p, max_new_tokens=4 + i % 3,
                               temperature=0.0)
               for i, p in enumerate(prompts)]
        done = eng.run()
        assert len(done) == 6
        by_id = {r.req_id: r for r in done}
        for p, rid in zip(prompts, ids):
            got = by_id[rid]
            ref = generate(model, p[None],
                           max_new_tokens=len(got.generated),
                           temperature=0.0).numpy()[0]
            np.testing.assert_array_equal(got.output_ids, ref)

    def test_eos_stops_request(self, model):
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=64)
        prompt = np.array([5, 7, 11, 13], np.int32)
        ref = generate(model, prompt[None], max_new_tokens=8,
                       temperature=0.0).numpy()[0]
        eos = int(ref[len(prompt)])  # first generated token acts as EOS
        eng.add_request(prompt, max_new_tokens=8, eos_token_id=eos,
                        temperature=0.0)
        done = eng.run()
        assert done[0].generated == [eos]

    def test_prompt_too_long_rejected(self, model):
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=16)
        with pytest.raises(ValueError):
            eng.add_request(np.zeros(16, np.int32))

    def test_admission_is_online(self, model):
        """step() output only contains live requests; new arrivals join
        later ticks without recompilation (same decode program)."""
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=64)
        a = eng.add_request(np.array([3, 4], np.int32), max_new_tokens=6,
                            temperature=0.0)
        first = eng.step()
        assert set(first) == {a}
        b = eng.add_request(np.array([9, 8, 7], np.int32),
                            max_new_tokens=3, temperature=0.0)
        second = eng.step()
        assert b in second and a in second
        done = eng.run()
        assert {r.req_id for r in done} == {a, b}


class TestServingSatellites:
    def test_sampled_rows_leave_greedy_rows_untouched(self, model):
        """One sampled-temperature request must not perturb the greedy
        requests batched with it (the old path materialized the whole
        [B, vocab] logits on host for everyone; now each sampled row
        gathers only its own slice, and greedy stays on device)."""
        prompts = [np.array([5, 7, 11], np.int32),
                   np.array([2, 3], np.int32)]
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=64, seed=0)
        g_only = eng.add_request(prompts[0], max_new_tokens=4,
                                 temperature=0.0)
        ref = {r.req_id: r.generated for r in eng.run()}[g_only]

        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=64, seed=0)
        g = eng.add_request(prompts[0], max_new_tokens=4, temperature=0.0)
        eng.add_request(prompts[1], max_new_tokens=4, temperature=0.9)
        out = {r.req_id: r.generated for r in eng.run()}
        assert out[g] == ref

    def test_sampled_stream_deterministic_per_seed_and_arrival(self, model):
        """Per-request sampling keys fold (engine seed, arrival index):
        the same workload on the same seed reproduces exactly."""
        prompt = np.array([9, 8, 7], np.int32)

        def run_once():
            eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                           max_seq_len=64, seed=5)
            eng.add_request(prompt, max_new_tokens=5, temperature=0.8)
            return eng.run()[0].generated

        assert run_once() == run_once()

    def test_truncated_flag_on_capacity_retirement(self, model):
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=16)
        eng.add_request(np.arange(1, 11, dtype=np.int32),
                        max_new_tokens=100)
        done = eng.run()
        assert done[0].truncated and len(done[0].generated) == 6
        # a request that finishes inside its budget is NOT flagged
        eng.add_request(np.array([1, 2, 3], np.int32), max_new_tokens=2)
        assert not eng.run()[0].truncated

    def test_prefill_compile_cache_capped(self, model):
        """Live prefill buckets are capped (oldest evicted) and every real
        compile — including a re-compile after eviction — lands in
        serving_prefill_compiles_total{engine=,bucket=}."""
        from paddle_tpu.observability.metrics import default_registry

        def compiles(bucket):
            m = default_registry().get("serving_prefill_compiles_total")
            return m.value(engine="dense", bucket=bucket) if m else 0.0

        c16 = compiles("16")
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=128,
                                       max_prefill_buckets=2)
        for n in (5, 20, 40):  # buckets 16, 32, 64 -> 16 evicted
            eng.add_request(np.arange(1, n + 1, dtype=np.int32),
                            max_new_tokens=1)
            eng.run()
        assert len(eng._prefill_cache) == 2
        assert 16 not in eng._prefill_cache and 64 in eng._prefill_cache
        eng.add_request(np.arange(1, 6, dtype=np.int32), max_new_tokens=1)
        eng.run()
        assert compiles("16") == c16 + 2  # eviction made the recompile visible


class TestQuantizedServing:
    def test_weight_only_generation_and_serving(self):
        """quantize_for_inference converts Linear (incl. degenerate
        parallel Linear) layers to int8 weight-only buffers; generation and
        the batching engine keep working with near-identical tokens."""
        from paddle_tpu.nn.quant import quantize_for_inference

        paddle.seed(0)
        m = GPTForCausalLM(gpt3_tiny())
        prompt = np.array([5, 7, 11, 13], np.int32)
        ref = generate(m, prompt[None], max_new_tokens=8,
                       temperature=0.0).numpy()[0]
        n = quantize_for_inference(m)
        assert n > 0
        # the fp weight params are gone from state (HBM saving is real)
        assert not any(k.endswith("q_proj.weight")
                       for k, _ in m.named_parameters())
        got = generate(m, prompt[None], max_new_tokens=8,
                       temperature=0.0).numpy()[0]
        assert (ref == got).mean() >= 0.7
        eng = ContinuousBatchingEngine(m, max_batch_size=2, max_seq_len=48)
        eng.add_request(prompt, max_new_tokens=5, temperature=0.0)
        done = eng.run()
        np.testing.assert_array_equal(
            done[0].output_ids, got[: len(done[0].output_ids)])


class TestLlamaServing:
    def test_llama_gqa_through_engine(self):
        """GQA models (kv_heads < num_heads) run through the slotted cache
        and match plain generate()."""
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        paddle.seed(0)
        m = LlamaForCausalLM(llama_tiny())
        prompt = np.array([3, 5, 7], np.int32)
        ref = generate(m, prompt[None], max_new_tokens=6,
                       temperature=0.0).numpy()[0]
        eng = ContinuousBatchingEngine(m, max_batch_size=2, max_seq_len=48)
        eng.add_request(prompt, max_new_tokens=6, temperature=0.0)
        done = eng.run()
        np.testing.assert_array_equal(
            done[0].output_ids, ref[: len(done[0].output_ids)])
