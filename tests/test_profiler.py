"""Profiler subsystem tests (reference test model:
test/legacy_test/test_profiler.py + python/paddle/profiler scheduler docs).
"""

import json
import os

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (
    Profiler,
    ProfilerState,
    RecordEvent,
    benchmark,
    export_chrome_tracing,
    make_scheduler,
)


def test_make_scheduler_state_machine():
    """skip_first=1, closed=1, ready=1, record=4, repeat=1: batches 0 skipped,
    1 closed, 2 ready, [3,6] record with 6 RECORD_AND_RETURN — the reference
    docstring example (profiler.py:129)."""
    sched = make_scheduler(closed=1, ready=1, record=4, repeat=1, skip_first=1)
    want = [
        ProfilerState.CLOSED,   # 0 skipped
        ProfilerState.CLOSED,   # 1
        ProfilerState.READY,    # 2
        ProfilerState.RECORD,   # 3
        ProfilerState.RECORD,
        ProfilerState.RECORD,
        ProfilerState.RECORD_AND_RETURN,  # 6
        ProfilerState.CLOSED,   # repeat exhausted
    ]
    assert [sched(i) for i in range(8)] == want


def test_profiler_records_ops_and_exports(tmp_path):
    """Op dispatch spans + RecordEvent annotations land in a loadable
    chrome trace, and summary() aggregates them."""
    traces = []

    def on_ready(prof):
        path = os.path.join(tmp_path, f"trace_{prof.step_num}.json")
        prof.export(path)
        traces.append(path)

    x = paddle.to_tensor(np.ones((8, 8), np.float32))
    with Profiler(scheduler=make_scheduler(closed=0, ready=1, record=2,
                                           repeat=1),
                  on_trace_ready=on_ready) as p:
        for i in range(4):
            with RecordEvent("train_iter"):
                y = (paddle.matmul(x, x) + 1.0).sum()
            p.step()

    assert traces, "on_trace_ready never fired"
    doc = json.load(open(traces[0]))
    names = {e["name"] for e in doc["traceEvents"]}
    assert "train_iter" in names
    assert any(n in names for n in ("matmul", "add", "sum")), names
    cats = {e["cat"] for e in doc["traceEvents"]}
    assert "operator" in cats and "user_defined" in cats
    for e in doc["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] >= 0

    s = p.summary()
    assert "train_iter" in s and "Calls" in s


def test_profiler_closed_state_records_nothing():
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    p = Profiler(scheduler=make_scheduler(closed=10, ready=0, record=1))
    p.start()
    _ = (x + x).sum()
    p.step()
    p.stop()
    assert p.events() == []
    # the op-event hook must be uninstalled after stop
    from paddle_tpu.framework import core

    assert core._op_event_hook is None


def test_export_chrome_tracing_handler(tmp_path):
    d = os.path.join(tmp_path, "log")
    handler = export_chrome_tracing(d)
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    with Profiler(scheduler=(1, 2), on_trace_ready=handler) as p:
        for _ in range(3):
            _ = x * 2.0
            p.step()
    files = os.listdir(d)
    assert any(f.endswith(".paddle_trace.json") for f in files), files


def test_step_info_and_benchmark():
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    with Profiler(timer_only=True) as p:
        for _ in range(3):
            _ = x + 1.0
            p.step()
    info = p.step_info()
    assert "ips" in info and "batch_cost" in info

    b = benchmark()
    b.begin()
    b.after_reader()
    b.after_step(num_samples=32)
    b.end()
    assert "ips" in b.step_info()
    assert b.ips > 0


def test_summary_overview_and_tables():
    """Overview Summary (per-category totals) + per-op table with calls,
    total/avg/min/max and ratio (reference profiler_statistic.py)."""
    import paddle_tpu.profiler as profiler

    p = profiler.Profiler(scheduler=(0, 1))
    p.start()
    with profiler.RecordEvent("userstep"):
        a = paddle.to_tensor(np.ones((4, 4), np.float32))
        for _ in range(3):
            a = paddle.matmul(a, a)
    p.stop()
    s = p.summary()
    assert "Overview Summary" in s
    assert "Category: operator" in s
    # matmul row: 3 calls
    row = [ln for ln in s.splitlines() if ln.startswith("matmul")]
    assert row and "3" in row[0].split()[1], row
    assert "%" in row[0]


def test_device_kernel_summary_from_trace(tmp_path):
    """Kernel Summary parses device tracks out of a chrome trace (the
    jax.profiler capture analog of the reference's CUPTI kernel records)."""
    import gzip
    import json

    from paddle_tpu.profiler.statistic import (build_device_summary,
                                               parse_device_trace)

    trace = {
        "traceEvents": [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 9, "name": "process_name",
             "args": {"name": "python host"}},
            {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1",
             "ts": 0, "dur": 500.0},
            {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1",
             "ts": 600, "dur": 700.0},
            {"ph": "X", "pid": 1, "tid": 1, "name": "copy.2",
             "ts": 1400, "dur": 100.0},
            # host event must NOT appear in the kernel table
            {"ph": "X", "pid": 9, "tid": 1, "name": "hostop",
             "ts": 0, "dur": 9999.0},
        ]
    }
    d = tmp_path / "plugins" / "profile" / "2026"
    d.mkdir(parents=True)
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump(trace, f)

    agg = parse_device_trace(str(d / "host.trace.json.gz"))
    assert agg["fusion.1"]["calls"] == 2
    assert agg["fusion.1"]["total"] == 1200.0 * 1e3  # us -> ns
    assert "hostop" not in agg

    lines = build_device_summary(str(tmp_path), time_unit="us")
    text = "\n".join(lines)
    assert "Kernel Summary" in text
    assert "fusion.1" in text and "hostop" not in text
    # top row is the biggest total and carries its ratio of device time
    assert "92.3%" in text  # 1200/1300

    # summary() composes it when device_trace_dir is set
    import paddle_tpu.profiler as profiler

    p = profiler.Profiler(scheduler=(0, 1), device_trace_dir=str(tmp_path))
    p._events = []
    s = p.summary()
    assert "Kernel Summary" in s


def test_make_scheduler_repeat_zero_wraps_forever():
    """repeat=0 cycles indefinitely: the pattern at steps [0, period) must
    repeat verbatim at [k*period, (k+1)*period) for any k — no CLOSED
    tail-off like the exhausted-repeat case."""
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=0)
    period = [sched(i) for i in range(4)]
    assert period == [
        ProfilerState.CLOSED,
        ProfilerState.READY,
        ProfilerState.RECORD,
        ProfilerState.RECORD_AND_RETURN,
    ]
    for k in (1, 2, 25):
        assert [sched(k * 4 + i) for i in range(4)] == period


def test_make_scheduler_closed_ready_boundaries():
    """closed=0/ready=0 boundaries: record=1 makes EVERY cycle step a
    RECORD_AND_RETURN; skip_first shifts the whole cycle, not just the
    first period."""
    sched = make_scheduler(closed=0, ready=0, record=1, repeat=0)
    assert [sched(i) for i in range(3)] == [ProfilerState.RECORD_AND_RETURN] * 3

    sched = make_scheduler(closed=2, ready=0, record=1, repeat=0, skip_first=3)
    assert [sched(i) for i in range(9)] == [
        ProfilerState.CLOSED, ProfilerState.CLOSED, ProfilerState.CLOSED,  # skip
        ProfilerState.CLOSED, ProfilerState.CLOSED,                        # closed
        ProfilerState.RECORD_AND_RETURN,
        ProfilerState.CLOSED, ProfilerState.CLOSED,
        ProfilerState.RECORD_AND_RETURN,
    ]

    # record is the only mandatory phase
    import pytest

    with pytest.raises(AssertionError):
        make_scheduler(closed=1, ready=1, record=0)


def test_export_chrome_tracing_contains_observability_spans(tmp_path):
    """Spans from the observability layer ride the same record window and
    land in the exported chrome trace with their own category."""
    from paddle_tpu.observability import span

    d = os.path.join(tmp_path, "log")
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    with Profiler(scheduler=make_scheduler(closed=0, ready=0, record=2,
                                           repeat=1),
                  on_trace_ready=export_chrome_tracing(d)) as p:
        for _ in range(2):
            with span("bench_step"):
                with span("matmul_block"):
                    _ = paddle.matmul(x, x)
            p.step()
    traces = [f for f in os.listdir(d) if f.endswith(".paddle_trace.json")]
    assert traces
    doc = json.load(open(os.path.join(d, traces[0])))
    by_cat = {}
    for e in doc["traceEvents"]:
        by_cat.setdefault(e["cat"], set()).add(e["name"])
    assert "bench_step" in by_cat["observability"]
    assert "bench_step/matmul_block" in by_cat["observability"]
    assert "matmul" in by_cat["operator"]
