"""hapi callback zoo tail + vision transforms tail (round-5: VERDICT
missing #5/#6 — reference python/paddle/hapi/callbacks.py and
python/paddle/vision/transforms/)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.hapi import Model
from paddle_tpu.hapi.callbacks import ReduceLROnPlateau, VisualDL
from paddle_tpu.vision import transforms as T


class _Const:
    """Reusable tiny dataset: x -> 2x."""

    def __iter__(self):
        rng = np.random.default_rng(0)
        for _ in range(4):
            x = rng.normal(size=(8, 4)).astype(np.float32)
            yield paddle.to_tensor(x), paddle.to_tensor(2 * x)


class TestCallbacksTail:
    def _model(self, lr=0.1):
        paddle.seed(0)
        net = nn.Linear(4, 4)
        m = Model(net)
        optimizer = opt.SGD(learning_rate=lr, parameters=net.parameters())
        m.prepare(optimizer, nn.MSELoss())
        return m, optimizer

    def test_reduce_lr_on_plateau_cuts_lr(self):
        m, optimizer = self._model(lr=0.1)
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                               verbose=0)
        cb.set_model(m)
        # flat loss: first epoch sets best; each later epoch waits, reduce
        # fires when wait hits patience. Train-log observations are deferred
        # to the next epoch boundary (eval logs would take precedence).
        for e, loss in [(0, 1.0), (1, 1.0)]:
            cb.on_epoch_begin(e)
            cb.on_epoch_end(e, {"loss": loss})
        cb.on_epoch_begin(2)
        assert optimizer.get_lr() == pytest.approx(0.05)
        # improvement resets the wait counter
        cb.on_epoch_end(2, {"loss": 0.5})
        cb.on_epoch_begin(3)
        cb.on_epoch_end(3, {"loss": 0.5})
        cb.on_train_end()
        assert optimizer.get_lr() == pytest.approx(0.025)

    def test_reduce_lr_respects_min_lr(self):
        m, optimizer = self._model(lr=0.1)
        cb = ReduceLROnPlateau(monitor="loss", factor=0.1, patience=0,
                               min_lr=0.05, verbose=0)
        cb.set_model(m)
        for e in range(3):
            cb.on_epoch_begin(e)
            cb.on_epoch_end(e, {"loss": 1.0})
        cb.on_train_end()
        assert optimizer.get_lr() == pytest.approx(0.05)

    def test_visualdl_writes_scalars(self, tmp_path):
        m, _ = self._model()
        cb = VisualDL(log_dir=str(tmp_path))
        cb.set_model(m)
        cb.on_train_batch_end(0, {"loss": 1.5})
        cb.on_train_batch_end(1, {"loss": [1.25]})
        cb.on_epoch_end(0, {"loss": 1.0, "non_scalar": "skip-me"})
        cb.on_eval_end({"loss": 0.75})
        train = [json.loads(l) for l in
                 open(os.path.join(tmp_path, "train.jsonl"))]
        assert [r["value"] for r in train] == [1.5, 1.25]
        ep = [json.loads(l) for l in
              open(os.path.join(tmp_path, "train_epoch.jsonl"))]
        assert ep[0]["value"] == 1.0 and len(ep) == 1  # non-scalar skipped
        ev = [json.loads(l) for l in
              open(os.path.join(tmp_path, "eval.jsonl"))]
        assert ev[0]["value"] == 0.75

    def test_fit_with_tail_callbacks(self, tmp_path):
        """The new callbacks survive a real Model.fit loop."""
        m, optimizer = self._model(lr=0.05)
        cbs = [ReduceLROnPlateau(monitor="loss", patience=100, verbose=0),
               VisualDL(log_dir=str(tmp_path))]
        m.fit(_Const(), epochs=2, callbacks=cbs, verbose=0)
        assert os.path.exists(os.path.join(tmp_path, "train.jsonl"))


class TestTransformsTail:
    def _img(self, h=16, w=20):
        return (np.random.RandomState(0).rand(h, w, 3) * 255).astype(np.uint8)

    def test_affine_identity_and_rotate_conventions(self):
        img = self._img()
        assert np.array_equal(T.affine(img, angle=0), img)
        sq = self._img(17, 17)
        # positive angle = counter-clockwise (torchvision/paddle convention)
        assert np.abs(T.rotate(sq, 90).astype(int)
                      - np.rot90(sq, 1).astype(int)).max() <= 1
        assert np.abs(T.rotate(sq, 180).astype(int)
                      - sq[::-1, ::-1].astype(int)).max() <= 1

    def test_affine_translate_scale(self):
        img = self._img()
        # translate by (2, 3): out[y, x] == img[y-3, x-2]
        out = T.affine(img, translate=(2, 3))
        assert np.array_equal(out[5:, 4:], img[2:-3, 2:-2])

    def test_perspective_identity_and_warp(self):
        img = self._img()
        H, W = img.shape[:2]
        corners = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        assert np.array_equal(T.perspective(img, corners, corners), img)
        # a real distortion changes pixels but stays in range
        end = [(2, 1), (W - 2, 2), (W - 1, H - 2), (1, H - 1)]
        out = T.perspective(img, corners, end)
        assert out.shape == img.shape and not np.array_equal(out, img)

    def test_color_ops(self):
        img = self._img()
        assert np.array_equal(T.adjust_brightness(img, 1.0), img)
        bright = T.adjust_brightness(img, 2.0)
        assert bright.astype(int).sum() > img.astype(int).sum()
        # saturation 0 == grayscale
        gray = T.adjust_saturation(img, 0.0)
        g3 = T.to_grayscale(img, 3)
        assert np.abs(gray.astype(int) - g3.astype(int)).max() <= 1
        # hue round-trips
        h2 = T.adjust_hue(T.adjust_hue(img, 0.3), -0.3)
        assert np.abs(h2.astype(int) - img.astype(int)).max() <= 3
        with pytest.raises(ValueError):
            T.adjust_hue(img, 0.7)

    def test_random_transforms_shapes_and_determinism(self):
        img = self._img()
        np.random.seed(0)
        for t in (T.ColorJitter(0.4, 0.4, 0.4, 0.4), T.RandomRotation(30),
                  T.RandomAffine(10, translate=(0.1, 0.1), scale=(0.8, 1.2),
                                 shear=5.0),
                  T.RandomPerspective(prob=1.0), T.Grayscale(3)):
            out = t(img)
            assert out.shape == img.shape, type(t).__name__

    def test_random_erasing(self):
        chw = np.ones((3, 16, 16), np.float32)
        np.random.seed(1)
        out = T.RandomErasing(prob=1.0, value=0.0)(chw)
        assert out.shape == chw.shape
        assert (out == 0).any() and (out == 1).any()
        # functional erase on HWC
        hwc = self._img()
        er = T.erase(hwc, 2, 3, 4, 5, 0)
        assert (er[2:6, 3:8] == 0).all()
        assert np.array_equal(er[:2], hwc[:2])


class TestReviewRegressions:
    """Round-5 review findings pinned."""

    def test_random_erasing_random_value_chw(self):
        chw = np.ones((3, 16, 16), np.float32)
        np.random.seed(2)
        out = T.RandomErasing(prob=1.0, value="random")(chw)
        assert out.shape == chw.shape
        changed = out != 1
        assert changed.any()
        # per-channel noise fills along C, not smeared along width
        assert not np.isnan(out).any()

    def test_rotate_expand(self):
        sq = (np.random.RandomState(3).rand(17, 17, 3) * 255).astype(np.uint8)
        r = T.rotate(sq, 90, expand=True)
        assert r.shape == sq.shape
        assert np.abs(r.astype(int) - np.rot90(sq, 1).astype(int)).max() <= 1
        rect = (np.random.RandomState(4).rand(10, 20, 3) * 255).astype(np.uint8)
        r = T.rotate(rect, 90, expand=True)
        assert r.shape[:2] == (20, 10)
        assert np.abs(r.astype(int)
                      - np.rot90(rect, 1).astype(int)).max() <= 1
        # 45 deg expands the canvas to cover all corners
        r45 = T.rotate(rect, 45, expand=True)
        assert r45.shape[0] > 10 and r45.shape[1] > 10

    def test_rotate_nearest_interpolation(self):
        sq = (np.random.RandomState(5).rand(9, 9, 3) * 255).astype(np.uint8)
        out = T.rotate(sq, 90, interpolation="nearest")
        # nearest on a multiple-of-90 rotation is exact
        assert np.array_equal(out, np.rot90(sq, 1))

    def test_contrast_transform_matches_functional(self):
        img = (np.random.RandomState(6).rand(8, 8, 3) * 255).astype(np.uint8)
        np.random.seed(3)
        f = 1 + np.random.uniform(-0.4, 0.4)
        np.random.seed(3)
        out = T.ContrastTransform(0.4)(img)
        assert np.array_equal(out, T.adjust_contrast(img, f))

    def test_reduce_lr_single_step_per_epoch(self):
        """Monitored key in BOTH epoch and eval logs must count once."""
        import paddle_tpu.optimizer as opt
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import ReduceLROnPlateau

        paddle.seed(0)
        net = nn.Linear(4, 4)
        m = Model(net)
        optimizer = opt.SGD(learning_rate=0.1, parameters=net.parameters())
        m.prepare(optimizer, nn.MSELoss())
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                               verbose=0)
        cb.set_model(m)
        for epoch in range(3):
            cb.on_epoch_begin(epoch)
            # TRAIN loss improves every epoch; EVAL loss is flat — the
            # plateau must be tracked on the EVAL metric (reference
            # semantics), so the lr still reduces
            cb.on_epoch_end(epoch, {"loss": 1.0 / (epoch + 1)})
            cb.on_eval_end({"loss": 1.0})
        assert optimizer.get_lr() == pytest.approx(0.05)

    def test_reduce_lr_scheduler_scales_base(self):
        import paddle_tpu.optimizer as opt
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import ReduceLROnPlateau
        from paddle_tpu.optimizer.lr import ExponentialDecay

        paddle.seed(0)
        net = nn.Linear(4, 4)
        m = Model(net)
        sched = ExponentialDecay(learning_rate=0.1, gamma=0.9)
        optimizer = opt.SGD(learning_rate=sched, parameters=net.parameters())
        m.prepare(optimizer, nn.MSELoss())
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=0,
                               verbose=0)
        cb.set_model(m)
        for e in range(2):
            cb.on_epoch_begin(e)
            cb.on_epoch_end(e, {"loss": 1.0})
        cb.on_train_end()
        # base lr halved once; schedule multiplier NOT applied twice
        assert sched.base_lr == pytest.approx(0.05)

    def test_hue_grayscale_passthrough(self):
        g = (np.random.RandomState(8).rand(8, 8) * 255).astype(np.uint8)
        assert np.array_equal(T.adjust_hue(g, 0.3), g)
        assert np.array_equal(T.adjust_hue(g[..., None], 0.3), g[..., None])

    def test_float_color_ops_stay_nonnegative(self):
        img = np.random.RandomState(9).rand(8, 8, 3).astype(np.float32)
        out = T.adjust_contrast(img, 3.0)
        assert (out >= 0).all()
        out = T.adjust_brightness(img, 0.5)
        assert (out >= 0).all()
        # warps, by contrast, must NOT clip normalized (negative) values
        norm = img - 0.5
        w = T.affine(norm, translate=(1, 0))
        assert (w < 0).any()

    def test_perspective_nearest_preserves_label_values(self):
        mask = np.random.RandomState(10).randint(0, 5, (12, 12, 1)).astype(np.float32)
        np.random.seed(4)
        out = T.RandomPerspective(prob=1.0, interpolation="nearest")(mask)
        assert set(np.unique(out)).issubset(set(np.unique(mask)) | {0.0})
