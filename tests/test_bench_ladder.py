"""bench.py rung-ladder robustness + the 1.3B low-memory recipe.

Round-4 postmortem: the 1.3B rung OOMed at *construction* (params +
optimizer-state allocation), outside the warmup-only try/except, so the
350M/125M fallback never ran and the driver recorded `mfu_failed`. These
tests pin (a) the fallback fires no matter where in the rung the failure
happens, (b) failed rungs free their device buffers, (c) the bf16-moment
AdamW recipe the 1.3B rung uses trains correctly.
"""

import json

import numpy as np
import pytest

import bench
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.optimizer as opt


@pytest.fixture(autouse=True)
def _clear_mesh():
    yield
    dist.env.set_global_mesh(None)


def _tiny_cfg():
    from paddle_tpu.models import GPTConfig

    return GPTConfig(hidden_size=64, num_layers=2, num_heads=2,
                     vocab_size=512, max_position_embeddings=64)


def test_ladder_falls_back_on_construction_failure(monkeypatch, capsys):
    """Failures during model/optimizer ALLOCATION (not just warmup) must
    fall through to the next rung."""
    real = bench._decoder_step
    calls = []

    def fake(cfg, batch, seq, on_tpu, low_mem=False, **kw):
        calls.append(cfg)
        if len(calls) < 3:
            raise RuntimeError("RESOURCE_EXHAUSTED: fake construction OOM")
        return real(_tiny_cfg(), 2, 32, False)

    monkeypatch.setattr(bench, "_decoder_step", fake)
    line = bench.run_gpt_rung(None, True, None)
    assert len(calls) == 3  # 1.3b failed, 350m failed, 125m ran
    assert "fell back" in line.get("note", "")
    assert np.isfinite(line["value"]) and line["value"] > 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(out)["metric"].startswith("mfu_")


def test_ladder_raises_if_all_rungs_fail(monkeypatch):
    def fake(cfg, batch, seq, on_tpu, low_mem=False, **kw):
        raise RuntimeError("RESOURCE_EXHAUSTED")

    monkeypatch.setattr(bench, "_decoder_step", fake)
    with pytest.raises(RuntimeError):
        bench.run_gpt_rung(None, True, None)


def test_free_rung_drops_trainstep_state():
    import gc
    import weakref

    step, ids, labels = bench._decoder_step(_tiny_cfg(), 2, 16, False)
    assert step.params
    # a param's device buffer must become unreachable after _free_rung even
    # while the caller still holds `step` (round-4 failure mode: params were
    # pinned through step.model/_state/optimizer during the fallback rung)
    ref = weakref.ref(next(iter(step._state.params.values())))
    bench._free_rung(step, ids, labels)
    assert step.params == {} and step.opt_states == {}
    assert step.model is None and step._state is None
    gc.collect()
    assert ref() is None, "Parameter still reachable after _free_rung"


def test_low_mem_recipe_trains():
    """bf16 params (amp.decorate O2) + bf16 AdamW moments + recompute —
    the 1.3B-fits-one-v5e recipe, on a tiny config."""
    import jax.numpy as jnp

    cfg = _tiny_cfg()
    step, ids, labels = bench._decoder_step(cfg, 2, 16, False, low_mem=True)
    # params stored bf16, moments stored bf16
    dts = {str(v.dtype) for v in step.params.values()}
    assert "bfloat16" in dts, dts
    mdts = {str(st["m"].dtype) for st in step.opt_states.values()
            if "m" in st}
    assert mdts == {"bfloat16"}, mdts
    assert cfg.use_recompute
    losses = [float(step(ids, labels)) for _ in range(4)]
    assert all(np.isfinite(v) for v in losses), losses
    assert losses[-1] < losses[0], losses


def test_adamw_moment_dtype_matches_f32_compute():
    """bf16-stored moments with f32 update compute should track the all-f32
    AdamW closely on an f32 param."""
    rng = np.random.default_rng(0)
    w0 = rng.normal(size=(32, 32)).astype(np.float32)

    def run(moment_dtype):
        w = paddle.to_tensor(w0.copy())
        w.stop_gradient = False
        o = opt.AdamW(learning_rate=1e-2, parameters=[w],
                      moment_dtype=moment_dtype)
        for i in range(5):
            ((w * w).sum()).backward()
            o.step()
            o.clear_grad()
        return w.numpy()

    ref = run(None)
    low = run("bfloat16")
    assert np.max(np.abs(ref - low)) < 1e-2, np.max(np.abs(ref - low))


def test_timed_steps_emits_overlap_metrics(tmp_path):
    """--emit-metrics acceptance: every step-timeline JSONL record carries
    overlap_fraction, the perf line aggregates it, and
    tools/overlap_report.py reads the file back."""
    from paddle_tpu.observability import disable_step_timeline, \
        enable_step_timeline

    path = str(tmp_path / "bench_metrics.jsonl")
    step, ids, labels = bench._decoder_step(_tiny_cfg(), 2, 16, False)
    enable_step_timeline(jsonl_path=path)
    try:
        dt, info = bench._timed_steps(lambda: step(ids, labels), steps=3,
                                      warmup=1, rung="cpu_smoke")
    finally:
        disable_step_timeline()
    assert dt > 0
    assert "overlap_fraction" in info
    assert 0.0 <= info["overlap_fraction"] <= 1.0
    assert "comm_exposed_s_per_step" in info

    recs = [json.loads(ln) for ln in open(path)]
    assert len(recs) == 3
    assert all("overlap_fraction" in r for r in recs)
    assert all(r["rung"] == "cpu_smoke" for r in recs)
    # the distributed step instruments its input placement as comm
    assert all(any(t["desc"] == "h2d/inputs" for t in r["comm_tasks"])
               for r in recs)

    from tools import overlap_report
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = overlap_report.main([path, "--json"])
    assert rc == 0
    summary = json.loads(buf.getvalue().strip())
    assert summary["steps"] == 3
    assert summary["overlap_fraction"] == pytest.approx(
        info["overlap_fraction"], abs=1e-3)
    assert "h2d/inputs" in summary["exposed_by_desc"] or \
        summary["exposed_s"] == 0.0
