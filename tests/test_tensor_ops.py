"""Op unit tests vs NumPy oracle — the OpTest pattern
(reference: test/legacy_test/op_test.py:418)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def check(t, ref, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(t.numpy(), np.float64), ref, rtol=rtol, atol=atol)


class TestCreation:
    def test_to_tensor(self):
        t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.shape == [2, 2]
        assert t.dtype == np.float32
        np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])

    def test_zeros_ones_full(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3]).numpy().sum() == 6
        np.testing.assert_array_equal(paddle.full([2], 7, "int32").numpy(), [7, 7])

    def test_arange_linspace(self):
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(
            paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6
        )

    def test_eye_tril_triu(self):
        np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3, dtype=np.float32))
        x = paddle.ones([3, 3])
        np.testing.assert_array_equal(paddle.tril(x).numpy(), np.tril(np.ones((3, 3))))
        np.testing.assert_array_equal(paddle.triu(x).numpy(), np.triu(np.ones((3, 3))))

    def test_like_variants(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        assert paddle.zeros_like(x).shape == [2, 3]
        assert paddle.ones_like(x).numpy().sum() == 6
        assert paddle.full_like(x, 3).numpy().sum() == 18


class TestMath:
    def setup_method(self, _):
        self.rng = np.random.RandomState(0)

    def test_binary_ops(self):
        a = self.rng.rand(3, 4).astype(np.float32)
        b = self.rng.rand(3, 4).astype(np.float32) + 0.5
        ta, tb = paddle.to_tensor(a), paddle.to_tensor(b)
        check(paddle.add(ta, tb), a + b)
        check(paddle.subtract(ta, tb), a - b)
        check(paddle.multiply(ta, tb), a * b)
        check(paddle.divide(ta, tb), a / b, rtol=1e-5)
        check(paddle.maximum(ta, tb), np.maximum(a, b))
        check(paddle.pow(ta, 2.0), a**2, rtol=1e-5)

    def test_operators(self):
        a = self.rng.rand(3, 4).astype(np.float32)
        b = self.rng.rand(3, 4).astype(np.float32) + 0.5
        ta, tb = paddle.to_tensor(a), paddle.to_tensor(b)
        check(ta + tb, a + b)
        check(ta - tb, a - b)
        check(ta * 2, a * 2)
        check(2 / tb, 2 / b, rtol=1e-5)
        check(-ta, -a)
        assert bool((ta > tb).numpy()[0, 0]) == bool(a[0, 0] > b[0, 0])

    def test_unary_ops(self):
        a = self.rng.rand(4, 5).astype(np.float32) + 0.1
        t = paddle.to_tensor(a)
        check(paddle.exp(t), np.exp(a), rtol=1e-4)
        check(paddle.log(t), np.log(a), rtol=1e-3, atol=1e-4)
        check(paddle.sqrt(t), np.sqrt(a), rtol=1e-5)
        check(paddle.tanh(t), np.tanh(a), rtol=1e-4, atol=1e-5)
        check(paddle.sigmoid(t), 1 / (1 + np.exp(-a)), rtol=1e-4)
        check(paddle.abs(paddle.to_tensor(-a)), a)
        check(paddle.rsqrt(t), 1 / np.sqrt(a), rtol=1e-4)

    def test_reductions(self):
        a = self.rng.rand(3, 4, 5).astype(np.float32)
        t = paddle.to_tensor(a)
        check(paddle.sum(t), a.sum(), rtol=1e-4)
        check(paddle.sum(t, axis=1), a.sum(1), rtol=1e-4)
        check(paddle.mean(t, axis=[0, 2]), a.mean((0, 2)), rtol=1e-4)
        check(paddle.max(t, axis=-1, keepdim=True), a.max(-1, keepdims=True))
        check(paddle.min(t), a.min())
        check(paddle.prod(t, axis=0), a.prod(0), rtol=1e-4)

    def test_method_chaining(self):
        a = self.rng.rand(3, 4).astype(np.float32)
        t = paddle.to_tensor(a)
        check(t.exp().log(), a, rtol=1e-3, atol=1e-4)
        check(t.sum(axis=0), a.sum(0), rtol=1e-5)
        assert t.reshape([4, 3]).shape == [4, 3]

    def test_cumsum_clip(self):
        a = self.rng.randn(3, 4).astype(np.float32)
        t = paddle.to_tensor(a)
        check(paddle.cumsum(t, axis=1), np.cumsum(a, 1), rtol=1e-5)
        check(paddle.clip(t, -0.5, 0.5), np.clip(a, -0.5, 0.5))

    def test_scale(self):
        a = self.rng.rand(3).astype(np.float32)
        check(paddle.scale(paddle.to_tensor(a), 2.0, 1.0), a * 2 + 1, rtol=1e-6)


class TestManipulation:
    def setup_method(self, _):
        self.rng = np.random.RandomState(1)

    def test_reshape_transpose(self):
        a = self.rng.rand(2, 3, 4).astype(np.float32)
        t = paddle.to_tensor(a)
        check(paddle.reshape(t, [6, 4]), a.reshape(6, 4))
        check(paddle.transpose(t, [2, 0, 1]), a.transpose(2, 0, 1))
        check(paddle.flatten(t, 1, 2), a.reshape(2, 12))

    def test_squeeze_unsqueeze(self):
        a = self.rng.rand(2, 1, 3).astype(np.float32)
        t = paddle.to_tensor(a)
        assert paddle.squeeze(t, 1).shape == [2, 3]
        assert paddle.unsqueeze(t, 0).shape == [1, 2, 1, 3]
        assert paddle.unsqueeze(t, [0, 4]).shape == [1, 2, 1, 3, 1]

    def test_concat_stack_split(self):
        a = self.rng.rand(2, 3).astype(np.float32)
        b = self.rng.rand(2, 3).astype(np.float32)
        ta, tb = paddle.to_tensor(a), paddle.to_tensor(b)
        check(paddle.concat([ta, tb], axis=0), np.concatenate([a, b], 0))
        check(paddle.stack([ta, tb], axis=1), np.stack([a, b], 1))
        parts = paddle.split(paddle.concat([ta, tb], axis=0), 2, axis=0)
        assert len(parts) == 2
        check(parts[0], a)
        parts = paddle.split(ta, [1, 2], axis=1)
        check(parts[1], a[:, 1:])

    def test_gather_scatter(self):
        a = self.rng.rand(5, 3).astype(np.float32)
        t = paddle.to_tensor(a)
        idx = paddle.to_tensor([0, 2], dtype="int32")
        check(paddle.gather(t, idx, axis=0), a[[0, 2]])
        upd = np.ones((2, 3), np.float32)
        out = paddle.scatter(t, idx, paddle.to_tensor(upd))
        ref = a.copy()
        ref[[0, 2]] = 1
        check(out, ref)

    def test_indexing(self):
        a = self.rng.rand(4, 5).astype(np.float32)
        t = paddle.to_tensor(a)
        check(t[1], a[1])
        check(t[1:3, ::2], a[1:3, ::2])
        check(t[:, -1], a[:, -1])
        t2 = paddle.to_tensor(a.copy())
        t2[0] = 0.0
        ref = a.copy()
        ref[0] = 0
        check(t2, ref)

    def test_tile_expand_pad(self):
        a = self.rng.rand(2, 3).astype(np.float32)
        t = paddle.to_tensor(a)
        check(paddle.tile(t, [2, 1]), np.tile(a, (2, 1)))
        check(paddle.expand(paddle.to_tensor(a[:1]), [4, 3]), np.broadcast_to(a[:1], (4, 3)))
        check(paddle.pad(t, [1, 1], value=0.0), np.pad(a, [(0, 0), (1, 1)]))

    def test_take_put_along_axis(self):
        a = self.rng.rand(3, 4).astype(np.float32)
        idx = np.argsort(a, axis=1).astype(np.int32)
        t, ti = paddle.to_tensor(a), paddle.to_tensor(idx)
        check(paddle.take_along_axis(t, ti, 1), np.take_along_axis(a, idx, 1))

    def test_masked_select_where(self):
        a = self.rng.randn(3, 4).astype(np.float32)
        t = paddle.to_tensor(a)
        m = t > 0
        check(paddle.masked_select(t, m), a[a > 0])
        check(paddle.where(m, t, paddle.zeros_like(t)), np.where(a > 0, a, 0))

    def test_flip_roll(self):
        a = self.rng.rand(3, 4).astype(np.float32)
        t = paddle.to_tensor(a)
        check(paddle.flip(t, [0]), a[::-1])
        check(paddle.roll(t, 1, axis=0), np.roll(a, 1, 0))


class TestLinalg:
    def setup_method(self, _):
        self.rng = np.random.RandomState(2)

    def test_matmul(self):
        a = self.rng.rand(3, 4).astype(np.float32)
        b = self.rng.rand(4, 5).astype(np.float32)
        check(paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b)), a @ b, rtol=1e-4)
        check(
            paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b.T), transpose_y=True),
            a @ b,
            rtol=1e-4,
        )

    def test_batched_matmul(self):
        a = self.rng.rand(2, 3, 4).astype(np.float32)
        b = self.rng.rand(2, 4, 5).astype(np.float32)
        check(paddle.bmm(paddle.to_tensor(a), paddle.to_tensor(b)), a @ b, rtol=1e-4)

    def test_norm_det_inv(self):
        a = self.rng.rand(3, 3).astype(np.float32) + np.eye(3, dtype=np.float32) * 3
        t = paddle.to_tensor(a)
        check(paddle.linalg.norm(t), np.linalg.norm(a), rtol=1e-4)
        check(paddle.linalg.det(t), np.linalg.det(a), rtol=1e-4)
        check(paddle.linalg.inv(t), np.linalg.inv(a), rtol=1e-3, atol=1e-5)

    def test_einsum(self):
        a = self.rng.rand(3, 4).astype(np.float32)
        b = self.rng.rand(4, 5).astype(np.float32)
        check(paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b)), a @ b, rtol=1e-4)


class TestSearchLogic:
    def setup_method(self, _):
        self.rng = np.random.RandomState(3)

    def test_argmax_topk_sort(self):
        a = self.rng.rand(3, 5).astype(np.float32)
        t = paddle.to_tensor(a)
        np.testing.assert_array_equal(paddle.argmax(t, axis=1).numpy(), a.argmax(1))
        vals, idx = paddle.topk(t, 2, axis=1)
        np.testing.assert_allclose(vals.numpy(), np.sort(a, 1)[:, ::-1][:, :2], rtol=1e-6)
        check(paddle.sort(t, axis=1), np.sort(a, 1))

    def test_comparisons(self):
        a = np.array([1.0, 2.0, 3.0], np.float32)
        b = np.array([2.0, 2.0, 2.0], np.float32)
        ta, tb = paddle.to_tensor(a), paddle.to_tensor(b)
        np.testing.assert_array_equal(paddle.equal(ta, tb).numpy(), a == b)
        np.testing.assert_array_equal(paddle.less_than(ta, tb).numpy(), a < b)
        assert bool(paddle.allclose(ta, ta).numpy())
        assert not bool(paddle.equal_all(ta, tb).numpy())

    def test_nonzero(self):
        a = np.array([[0, 1], [2, 0]], np.float32)
        out = paddle.nonzero(paddle.to_tensor(a))
        np.testing.assert_array_equal(out.numpy(), np.stack(np.nonzero(a), 1))


class TestStat:
    def test_std_var_median(self):
        rng = np.random.RandomState(4)
        a = rng.rand(4, 6).astype(np.float32)
        t = paddle.to_tensor(a)
        check(paddle.std(t), a.std(ddof=1), rtol=1e-4)
        check(paddle.var(t, axis=1), a.var(1, ddof=1), rtol=1e-4)
        check(paddle.median(t), np.median(a), rtol=1e-5)


class TestRandom:
    def test_seed_reproducible(self):
        paddle.seed(42)
        a = paddle.randn([4, 4])
        paddle.seed(42)
        b = paddle.randn([4, 4])
        np.testing.assert_array_equal(a.numpy(), b.numpy())

    def test_shapes_ranges(self):
        u = paddle.uniform([100], min=0.0, max=1.0)
        assert u.numpy().min() >= 0 and u.numpy().max() <= 1
        r = paddle.randint(0, 10, [50])
        assert r.numpy().min() >= 0 and r.numpy().max() < 10
        p = paddle.randperm(10)
        np.testing.assert_array_equal(np.sort(p.numpy()), np.arange(10))


class TestCast:
    def test_astype(self):
        t = paddle.to_tensor([1.7, 2.3])
        assert t.astype("int32").numpy().tolist() == [1, 2]
        assert t.astype("float16").dtype == np.float16
        assert paddle.to_tensor([1, 2]).dtype in (np.int32, np.int64)


class TestReviewRegressions:
    def test_split_non_divisible_raises(self):
        with pytest.raises(ValueError):
            paddle.split(paddle.arange(7), 3)

    def test_chunk_uneven(self):
        parts = paddle.chunk(paddle.arange(7), 3)
        assert [p.shape[0] for p in parts] == [3, 3, 1]
        np.testing.assert_array_equal(parts[2].numpy(), [6])

    def test_bitwise_operators(self):
        a = paddle.to_tensor([3], dtype="int32")
        b = paddle.to_tensor([5], dtype="int32")
        assert (a & b).numpy().tolist() == [1]
        assert (a | b).numpy().tolist() == [7]
        assert (a ^ b).numpy().tolist() == [6]
        assert (~a).numpy().tolist() == [-4]
        t = paddle.to_tensor([True, False])
        np.testing.assert_array_equal((~t).numpy(), [False, True])

    def test_cummax_cummin(self):
        a = np.array([[1.0, 3.0, 2.0], [4.0, 0.0, 5.0]], np.float32)
        vals, idx = paddle.cummax(paddle.to_tensor(a), axis=1)
        np.testing.assert_array_equal(vals.numpy(), np.maximum.accumulate(a, 1))
        np.testing.assert_array_equal(idx.numpy(), [[0, 1, 1], [0, 0, 2]])
        vals, idx = paddle.cummin(paddle.to_tensor(a), axis=1)
        np.testing.assert_array_equal(vals.numpy(), np.minimum.accumulate(a, 1))

    def test_argmax_dtype_honored(self):
        x = paddle.to_tensor([[1.0, 5.0]])
        assert paddle.argmax(x, axis=1, dtype="int32").dtype == np.int32


class TestTensorTo:
    def test_to_device_dtype_tensor(self):
        """Tensor.to accepts device strings (placement no-op), dtypes, and
        Tensors; anything else raises instead of silently returning self
        (reference Tensor.to, python/paddle/base/dygraph/tensor_patch_methods.py)."""
        import pytest

        t = paddle.to_tensor(np.ones(3, np.float32))
        assert "float16" in str(t.to("float16")._value.dtype)
        assert t.to("cpu")._value.dtype == t._value.dtype
        assert t.to("gpu:0") is not None  # device strings are accepted
        assert "int32" in str(
            t.to(paddle.to_tensor(np.ones(1, np.int32)))._value.dtype)
        with pytest.raises(ValueError, match="cannot interpret"):
            t.to("floaty32")
