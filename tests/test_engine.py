"""Auto-parallel Engine (reference:
python/paddle/distributed/auto_parallel/static/engine.py fit/evaluate/
predict/save/load over a parallelized program)."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.auto_parallel import Engine, Strategy


@pytest.fixture(autouse=True)
def _clear_global_mesh():
    """Engine.prepare sets the sticky global mesh; tests must not leak it
    into later test files (jit.save would export for 8 devices)."""
    yield
    dist.env.set_global_mesh(None)


def _setup():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    crit = nn.MSELoss()
    optimizer = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    return model, crit, optimizer


def _data(n=32):
    rng = np.random.default_rng(0)
    x = np.asarray(rng.normal(size=(n, 16)), np.float32)
    w = np.asarray(rng.normal(size=(16, 4)), np.float32)
    return x, x @ w * 0.1


def test_engine_fit_evaluate_predict():
    model, crit, optimizer = _setup()
    strategy = Strategy()
    strategy.sharding.enable = True
    strategy.sharding.degree = 2
    strategy.mp_degree = 1
    engine = Engine(model=model, loss=crit, optimizer=optimizer,
                    strategy=strategy)
    x, y = _data()
    hist = engine.fit(train_data=(x, y), batch_size=8, epochs=3)
    assert hist["loss"][-1] < hist["loss"][0]
    ev = engine.evaluate(valid_data=(x, y), batch_size=8)
    assert np.isfinite(ev["loss"])
    preds = engine.predict(test_data=(x, y), batch_size=8)
    assert preds and preds[0].shape == (8, 4)


def test_engine_save_load(tmp_path):
    model, crit, optimizer = _setup()
    engine = Engine(model=model, loss=crit, optimizer=optimizer)
    x, y = _data(16)
    engine.fit(train_data=(x, y), batch_size=8, epochs=1)
    p = str(tmp_path / "ckpt")
    engine.save(p)

    model2, crit2, opt2 = _setup()
    engine2 = Engine(model=model2, loss=crit2, optimizer=opt2)
    engine2.load(p)
    xa = paddle.to_tensor(x[:4])
    np.testing.assert_allclose(model2(xa).numpy(), model(xa).numpy(),
                               atol=1e-6)


def test_engine_rejects_oversized_mesh():

    model, crit, optimizer = _setup()
    strategy = Strategy()
    strategy.mp_degree = 64
    engine = Engine(model=model, loss=crit, optimizer=optimizer,
                    strategy=strategy)
    with pytest.raises(ValueError, match="exceeds"):
        engine.prepare()


def test_engine_predict_without_optimizer_and_partial_batch():
    """Inference-only Engine (no optimizer/loss step build) + trailing
    partial batches are not dropped."""
    model, crit, _ = _setup()
    engine = Engine(model=model, loss=crit, optimizer=None)
    x, y = _data(10)  # 10 % 8 != 0
    preds = engine.predict(test_data=(x, y), batch_size=8)
    assert sum(p.shape[0] for p in preds) == 10
    ev = engine.evaluate(valid_data=(x, y), batch_size=8)
    assert np.isfinite(ev["loss"])


def test_engine_save_carries_optimizer_state(tmp_path):
    model, crit, optimizer = _setup()
    engine = Engine(model=model, loss=crit, optimizer=optimizer)
    x, y = _data(16)
    engine.fit(train_data=(x, y), batch_size=8, epochs=2)
    p = str(tmp_path / "ck")
    engine.save(p)
    from paddle_tpu.framework.io import load as fload

    opt_sd = fload(p + ".pdopt")
    param_states = [v for k, v in opt_sd.items() if k.startswith("param_")]
    assert param_states, f"no param states in checkpoint: {list(opt_sd)}"
    # Adam moments must be non-zero after real training steps
    leaves = [np.asarray(t.numpy() if hasattr(t, "numpy") else t)
              for st in param_states for t in st.values()]
    assert any(np.abs(l).max() > 0 for l in leaves), \
        "optimizer checkpoint holds only init state"


def test_engine_cost_model():
    from paddle_tpu.models import GPTForCausalLM, gpt3_tiny

    cfg = gpt3_tiny()
    model = GPTForCausalLM(cfg)
    engine = Engine(model=model, loss=lambda a, b: a, optimizer=None)
    assert engine.cost() > 0


def test_engine_plan_search():
    """auto_mode="full" plan search (round-3 VERDICT missing #5): the
    auto_tuner memory model prunes infeasible factorizations, the cost
    model ranks the rest."""
    from paddle_tpu.distributed.auto_parallel.engine import Engine

    e = Engine(model=None)
    # small model, no cap: pure DP wins (no comm, no bubble)
    plan = e.plan(8, model_cfg={"hidden_size": 768, "num_layers": 12,
                                "vocab_size": 50304, "seq_length": 1024,
                                "micro_batch_size": 8})
    assert plan == (8, 1, 1, 1)
    # big model under a tight memory cap: must split the model
    plan2 = e.plan(8, model_cfg={"hidden_size": 2048, "num_layers": 24,
                                 "vocab_size": 50304, "seq_length": 2048,
                                 "micro_batch_size": 8,
                                 "max_mem_usage_bytes": int(4e9)})
    dp, pp, shard, mp = plan2
    assert pp * shard * mp > 1
    assert dp * pp * shard * mp == 8
    # impossible cap: explicit failure, not a silent bad plan
    with pytest.raises(RuntimeError):
        e.plan(2, model_cfg={"hidden_size": 8192, "num_layers": 96,
                             "vocab_size": 50304, "seq_length": 4096,
                             "micro_batch_size": 8,
                             "max_mem_usage_bytes": int(1e9)})


def test_engine_full_mode_fit():
    """fit() under auto_mode='full' plans a dpxmp split for GPT-tiny on the
    8-device CPU mesh and trains (VERDICT done-criterion)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel.engine import Engine, Strategy
    from paddle_tpu.models import GPTForCausalLM, gpt3_tiny

    paddle.seed(0)
    cfg = gpt3_tiny()
    model = GPTForCausalLM(cfg)
    crit = paddle.nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        return crit(logits.reshape([-1, cfg.vocab_size]),
                    labels.reshape([-1]))

    s = Strategy()
    s.auto_mode = "full"
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    e = Engine(model=model, loss=loss_fn, optimizer=opt, strategy=s)
    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.vocab_size, (8, 16)).astype("int64")
    y = rng.integers(0, cfg.vocab_size, (8, 16)).astype("int64")
    hist = e.fit(train_data=(x, y), batch_size=8, epochs=1)
    assert np.isfinite(e.history["loss"]).all()
    assert s.dp_degree * s.pp_degree * s.mp_degree * s.sharding.degree == 8


_PLAN_MODEL_CFG = dict(hidden_size=64, num_layers=2, seq_length=32,
                       vocab_size=1024, micro_batch_size=8, microbatches=2)


def test_cost_model_analytic_ordering():
    """Always-on deterministic half: the analytic cost model must rank
    pure-dp above a pipeline split (bubble) and above wide-mp (per-layer
    collectives), and plan() must pick it."""
    from paddle_tpu.distributed.auto_parallel.engine import Engine

    eng = Engine.__new__(Engine)
    costs = eng.candidate_costs(8, _PLAN_MODEL_CFG)
    assert costs[(8, 1, 1, 1)] < costs[(4, 2, 1, 1)], costs
    assert costs[(8, 1, 1, 1)] < costs[(1, 1, 1, 8)], costs
    assert eng.plan(8, _PLAN_MODEL_CFG) == (8, 1, 1, 1)


def test_cost_model_ranking_matches_measured_steps():
    """Round-5 (VERDICT round-4 missing #4): the planner's analytic cost
    model had never been validated against MEASURED runs. Time three
    clearly-separated factorizations of the 8-device mesh on a real
    compiled train step and require the cost model's ranking to agree on
    the compute-structure facts it claims to capture: pure-dp beats a
    pipeline split (bubble), and beats wide-mp (per-layer collectives).
    Skips on a saturated host, where mesh timings are scheduler noise."""
    import time

    import jax

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.models import (GPTForCausalLM, GPTForCausalLMPipe,
                                   GPTPretrainingCriterion, gpt3_tiny)

    model_cfg = _PLAN_MODEL_CFG
    eng = Engine.__new__(Engine)  # cost model needs no prepared engine
    costs = eng.candidate_costs(8, model_cfg)

    def measure(dp, pp, sharding, mp):
        paddle.seed(0)
        cfg = gpt3_tiny(sequence_parallel=(mp > 1))
        cfg.num_layers = 2
        mesh = dist.build_mesh(dp=dp, pp=pp, sharding=sharding, sep=1,
                               mp=mp, devices=jax.devices()[:8])
        if pp > 1:
            model = GPTForCausalLMPipe(cfg, num_microbatches=2,
                                       pp_schedule="1f1b")
        else:
            model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        o = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
        step = dist.DistributedTrainStep(model, lambda lg, lb: crit(lg, lb),
                                         o, mesh=mesh)
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32)))
        lb = paddle.to_tensor(
            np.random.default_rng(1).integers(0, cfg.vocab_size, (8, 32)))
        for _ in range(2):  # compile + settle
            float(step(ids, lb))
        # MIN over batches: noise-robust on a shared CPU (a single loaded
        # 5-step mean flaked under concurrent test load)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(3):
                last = step(ids, lb)
            float(last)
            best = min(best, (time.perf_counter() - t0) / 3)
        dist.env.set_global_mesh(None)
        return best

    configs = [(8, 1, 1, 1), (4, 2, 1, 1), (1, 1, 1, 8)]
    # wall-clock agreement needs a quiet host: on a saturated machine the
    # 8-way virtual mesh timings are scheduler noise, not compute. Use the
    # AVAILABLE cpu budget (cgroup/affinity aware), not the machine's.
    load = os.getloadavg()[0]
    try:
        ncpu = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        ncpu = os.cpu_count() or 1
    if load > 0.75 * ncpu:
        pytest.skip(f"host too loaded for timing validation "
                    f"(load {load:.1f} on {ncpu} cpus)")
    measured = {c: measure(*c) for c in configs}
    # 10% slack for residual scheduler noise
    assert measured[(8, 1, 1, 1)] < measured[(4, 2, 1, 1)] * 1.1, measured
    assert measured[(8, 1, 1, 1)] < measured[(1, 1, 1, 8)] * 1.1, measured
