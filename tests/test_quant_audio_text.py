"""Quantization, audio, text subsystems (reference:
python/paddle/quantization/ (PTQ/QAT/observers), python/paddle/audio/
(functional + feature layers vs librosa-identical formulas),
python/paddle/text/viterbi_decode.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


# --------------------------------------------------------------------------- #
# quantization
# --------------------------------------------------------------------------- #


class TestQuantization:
    def _model(self):
        paddle.seed(0)
        return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))

    def test_ptq_weight_only_int8(self):
        from paddle_tpu.quantization import PTQ, QuantizedLinear

        m = self._model()
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32))
        ref = m(x).numpy()
        ptq = PTQ()
        ptq.quantize(m)
        _ = m(x)  # calibration pass
        qm = ptq.convert(m)
        layers = [s for _, s in qm.named_sublayers()]
        assert any(isinstance(s, QuantizedLinear) for s in layers)
        out = qm(x).numpy()
        # int8 weight-only: small quantization error, same predictions-ish
        assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.05
        # int8 storage really used
        ql = [s for s in layers if isinstance(s, QuantizedLinear)][0]
        assert str(ql.weight_quant._value.dtype) == "int8"
        # calibration observed real activations -> nonzero act scale
        assert ql.activation_scale > 0
        scales = ptq.activation_scales()
        assert scales and all(v > 0 for v in scales.values())

    def test_quantize_weight_roundtrip(self):
        from paddle_tpu.quantization import quantize_weight

        w = paddle.to_tensor(
            np.random.default_rng(1).normal(size=(8, 4)).astype(np.float32))
        q, s = quantize_weight(w, axis=1)
        deq = q.numpy().astype(np.float32) * s.numpy()
        assert np.abs(deq - w.numpy()).max() < np.abs(w.numpy()).max() / 100

    def test_qat_straight_through(self):
        from paddle_tpu.quantization import QAT, fake_quant

        x = paddle.to_tensor(np.linspace(-1, 1, 16).astype(np.float32),
                             stop_gradient=False)
        y = fake_quant(x)
        (y * 2).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 2.0)  # STE identity grad

        m = self._model()
        QAT().quantize(m)
        xin = paddle.to_tensor(
            np.random.default_rng(2).normal(size=(4, 16)).astype(np.float32))
        out = m(xin)
        out.sum().backward()
        g = m[0].weight.grad
        assert g is not None and np.isfinite(g.numpy()).all()


# --------------------------------------------------------------------------- #
# audio
# --------------------------------------------------------------------------- #


class TestAudio:
    def test_mel_conversions(self):
        from paddle_tpu.audio import functional as AF

        assert abs(AF.mel_to_hz(AF.hz_to_mel(440.0)) - 440.0) < 1e-6
        assert abs(AF.mel_to_hz(AF.hz_to_mel(4000.0)) - 4000.0) < 1e-3
        assert abs(AF.hz_to_mel(0.0)) < 1e-9

    def test_fbank_and_dct_shapes(self):
        from paddle_tpu.audio import functional as AF

        fb = AF.compute_fbank_matrix(16000, 512, n_mels=40)
        assert tuple(fb.shape) == (40, 257)
        assert fb.numpy().min() >= 0
        dct = AF.create_dct(13, 40)
        assert tuple(dct.shape) == (40, 13)
        # ortho DCT columns are orthonormal
        d = dct.numpy()
        np.testing.assert_allclose(d.T @ d, np.eye(13), atol=1e-5)

    def test_spectrogram_parity_with_numpy_stft(self):
        from paddle_tpu.audio import Spectrogram

        sr, n_fft, hop = 8000, 256, 128
        t = np.arange(sr // 4) / sr
        sig = np.sin(2 * np.pi * 1000 * t).astype(np.float32)
        spec = Spectrogram(n_fft=n_fft, hop_length=hop, center=False)(
            paddle.to_tensor(sig[None]))
        out = spec.numpy()[0]
        # numpy reference stft (hann, power 2)
        w = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(n_fft) / n_fft)
        n_frames = (len(sig) - n_fft) // hop + 1
        frames = np.stack([sig[i * hop:i * hop + n_fft] * w
                           for i in range(n_frames)])
        ref = np.abs(np.fft.rfft(frames, axis=-1)).T ** 2
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)
        # the 1 kHz bin dominates
        assert abs(np.argmax(out.mean(-1)) - round(1000 * n_fft / sr)) <= 1

    def test_logmel_and_mfcc_shapes(self):
        from paddle_tpu.audio import LogMelSpectrogram, MFCC

        sig = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(2, 4000)).astype(np.float32))
        lm = LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32)(sig)
        assert lm.shape[0] == 2 and lm.shape[1] == 32
        mf = MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)(sig)
        assert mf.shape[1] == 13
        assert np.isfinite(mf.numpy()).all()


# --------------------------------------------------------------------------- #
# text
# --------------------------------------------------------------------------- #


class TestText:
    def test_viterbi_matches_bruteforce(self):
        from paddle_tpu.text import ViterbiDecoder

        rng = np.random.default_rng(0)
        B, T, N = 2, 5, 4
        pot = rng.normal(size=(B, T, N)).astype(np.float32)
        trans = rng.normal(size=(N, N)).astype(np.float32)
        dec = ViterbiDecoder(paddle.to_tensor(trans),
                             include_bos_eos_tag=False)
        scores, paths = dec(paddle.to_tensor(pot))

        # brute force over all N^T paths
        import itertools

        for b in range(B):
            best, best_path = -np.inf, None
            for path in itertools.product(range(N), repeat=T):
                s = pot[b, 0, path[0]]
                for t in range(1, T):
                    s += trans[path[t - 1], path[t]] + pot[b, t, path[t]]
                if s > best:
                    best, best_path = s, path
            np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                       rtol=1e-5)
            assert tuple(paths.numpy()[b]) == best_path
