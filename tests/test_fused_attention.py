"""fused_multi_head_attention / fused_feedforward + layers: parity vs
composed nn ops (reference test:
test/legacy_test/test_fused_attention_op_api.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.incubate.nn.functional as IF
from paddle_tpu.incubate.nn import (FusedBiasDropoutResidualLayerNorm,
                                    FusedFeedForward, FusedMultiHeadAttention,
                                    FusedTransformerEncoderLayer)

B, S, E, H = 2, 8, 32, 4
D = E // H


def _ln_np(x, scale, bias, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    out = (x - mu) / np.sqrt(var + eps)
    return out * scale + bias


def _ref_attention_block(x, qkv_w, qkv_b, lin_w, lin_b, pre_ln, ln_s, ln_b,
                         mask=None):
    h = _ln_np(x, ln_s, ln_b) if pre_ln else x
    qkv = np.einsum("bse,jhde->bsjhd", h, qkv_w) + qkv_b
    q, k, v = (np.moveaxis(qkv[:, :, i], 2, 1) for i in range(3))
    s = np.einsum("bhsd,bhtd->bhst", q / np.sqrt(D), k)
    if mask is not None:
        s = s + mask
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    ctx = np.einsum("bhst,bhtd->bhsd", p, v)
    ctx = np.moveaxis(ctx, 1, 2).reshape(B, S, E)
    out = ctx @ lin_w + lin_b
    out = x + out
    if not pre_ln:
        out = _ln_np(out, ln_s, ln_b)
    return out


@pytest.mark.parametrize("pre_ln", [False, True])
def test_fused_mha_matches_reference_math(pre_ln):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(B, S, E)).astype(np.float32)
    qkv_w = rng.normal(size=(3, H, D, E)).astype(np.float32) * 0.2
    qkv_b = rng.normal(size=(3, H, D)).astype(np.float32) * 0.1
    lin_w = rng.normal(size=(E, E)).astype(np.float32) * 0.2
    lin_b = rng.normal(size=(E,)).astype(np.float32) * 0.1
    ln_s = rng.normal(size=(E,)).astype(np.float32) * 0.1 + 1.0
    ln_b = rng.normal(size=(E,)).astype(np.float32) * 0.1
    mask = np.where(rng.random((B, 1, S, S)) > 0.2, 0.0, -1e9).astype(
        np.float32)

    out = IF.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(qkv_w), paddle.to_tensor(lin_w),
        pre_layer_norm=pre_ln,
        pre_ln_scale=paddle.to_tensor(ln_s) if pre_ln else None,
        pre_ln_bias=paddle.to_tensor(ln_b) if pre_ln else None,
        ln_scale=None if pre_ln else paddle.to_tensor(ln_s),
        ln_bias=None if pre_ln else paddle.to_tensor(ln_b),
        qkv_bias=paddle.to_tensor(qkv_b), linear_bias=paddle.to_tensor(lin_b),
        attn_mask=paddle.to_tensor(mask),
        dropout_rate=0.0, attn_dropout_rate=0.0)
    ref = _ref_attention_block(x, qkv_w, qkv_b, lin_w, lin_b, pre_ln,
                               ln_s, ln_b, mask)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)


def test_fused_mha_bool_mask_and_cache():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(B, S, E)).astype(np.float32)
    qkv_w = rng.normal(size=(3, H, D, E)).astype(np.float32) * 0.2
    lin_w = rng.normal(size=(E, E)).astype(np.float32) * 0.2
    bool_mask = rng.random((B, 1, S, S)) > 0.2
    out_b = IF.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(qkv_w), paddle.to_tensor(lin_w),
        attn_mask=paddle.to_tensor(bool_mask),
        dropout_rate=0.0, attn_dropout_rate=0.0)
    add_mask = np.where(bool_mask, 0.0, -1e30).astype(np.float32)
    out_f = IF.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(qkv_w), paddle.to_tensor(lin_w),
        attn_mask=paddle.to_tensor(add_mask),
        dropout_rate=0.0, attn_dropout_rate=0.0)
    np.testing.assert_allclose(out_b.numpy(), out_f.numpy(), rtol=1e-5)

    # cache path: prefix cache + new tokens == full-sequence attention rows
    cache = paddle.to_tensor(np.zeros((2, B, H, 0, D), np.float32))
    out_c, new_cache = IF.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(qkv_w), paddle.to_tensor(lin_w),
        cache_kv=cache, dropout_rate=0.0, attn_dropout_rate=0.0)
    assert tuple(new_cache.shape) == (2, B, H, S, D)
    out_nc = IF.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(qkv_w), paddle.to_tensor(lin_w),
        dropout_rate=0.0, attn_dropout_rate=0.0)
    np.testing.assert_allclose(out_c.numpy(), out_nc.numpy(), rtol=1e-5)


def test_fused_feedforward_matches_composition():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(B, S, E)).astype(np.float32)
    w1 = rng.normal(size=(E, 4 * E)).astype(np.float32) * 0.2
    b1 = rng.normal(size=(4 * E,)).astype(np.float32) * 0.1
    w2 = rng.normal(size=(4 * E, E)).astype(np.float32) * 0.2
    b2 = rng.normal(size=(E,)).astype(np.float32) * 0.1
    ln_s = np.ones(E, np.float32)
    ln_b = np.zeros(E, np.float32)
    out = IF.fused_feedforward(
        paddle.to_tensor(x), paddle.to_tensor(w1), paddle.to_tensor(w2),
        linear1_bias=paddle.to_tensor(b1), linear2_bias=paddle.to_tensor(b2),
        ln1_scale=paddle.to_tensor(ln_s), ln1_bias=paddle.to_tensor(ln_b),
        dropout1_rate=0.0, dropout2_rate=0.0, activation="gelu",
        pre_layer_norm=True)
    h = _ln_np(x, ln_s, ln_b)
    from scipy.special import erf

    g = h @ w1 + b1
    g = g * 0.5 * (1 + erf(g / np.sqrt(2)))
    ref = x + (g @ w2 + b2)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)


def test_fused_layers_train_and_dropout_behaves():
    paddle.seed(0)
    layer = FusedTransformerEncoderLayer(E, H, 4 * E, dropout_rate=0.0,
                                         normalize_before=True)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=layer.parameters())
    rng = np.random.default_rng(3)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    tgt = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    losses = []
    for _ in range(10):
        loss = ((layer(x) - tgt) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]

    # dropout active in train (stochastic), identity in eval
    mha = FusedMultiHeadAttention(E, H, dropout_rate=0.5,
                                  attn_dropout_rate=0.5)
    y1 = mha(x).numpy()
    y2 = mha(x).numpy()
    assert not np.allclose(y1, y2)
    mha.eval()
    e1 = mha(x).numpy()
    e2 = mha(x).numpy()
    np.testing.assert_allclose(e1, e2)


def test_fused_mha_layer_parity_with_functional():
    paddle.seed(0)
    mha = FusedMultiHeadAttention(E, H, dropout_rate=0.0,
                                  attn_dropout_rate=0.0)
    rng = np.random.default_rng(4)
    x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
    out = mha(x)
    ref = IF.fused_multi_head_attention(
        x, mha.qkv_weight, mha.linear_weight, qkv_bias=mha.qkv_bias,
        linear_bias=mha.linear_bias, ln_scale=mha.ln_scale,
        ln_bias=mha.ln_bias, dropout_rate=0.0, attn_dropout_rate=0.0)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)


def test_transpose_qkv_wb_variant():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(B, S, E)).astype(np.float32)
    qkv_w4 = rng.normal(size=(3, H, D, E)).astype(np.float32) * 0.2
    lin_w = rng.normal(size=(E, E)).astype(np.float32) * 0.2
    out4 = IF.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(qkv_w4),
        paddle.to_tensor(lin_w), dropout_rate=0.0, attn_dropout_rate=0.0)
    # same weights in [E, 3E] layout: w2[e, j*E + h*D + d] = w4[j, h, d, e]
    qkv_w2 = np.moveaxis(qkv_w4.reshape(3, E, E), 1, 2).reshape(
        3, E, E).transpose(1, 0, 2).reshape(E, 3 * E)
    out2 = IF.fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(qkv_w2),
        paddle.to_tensor(lin_w), dropout_rate=0.0, attn_dropout_rate=0.0,
        num_heads=H, transpose_qkv_wb=True)
    np.testing.assert_allclose(out2.numpy(), out4.numpy(), rtol=2e-4,
                               atol=2e-4)


def test_bias_dropout_residual_ln():
    rng = np.random.default_rng(6)
    x = rng.normal(size=(B, S, E)).astype(np.float32)
    res = rng.normal(size=(B, S, E)).astype(np.float32)
    b = rng.normal(size=(E,)).astype(np.float32)
    out = IF.fused_bias_dropout_residual_layer_norm(
        paddle.to_tensor(x), paddle.to_tensor(res), bias=paddle.to_tensor(b),
        ln_scale=paddle.to_tensor(np.ones(E, np.float32)),
        ln_bias=paddle.to_tensor(np.zeros(E, np.float32)), dropout_rate=0.0)
    ref = _ln_np(res + x + b, np.ones(E), np.zeros(E))
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)
    paddle.seed(0)
    layer = FusedBiasDropoutResidualLayerNorm(E, dropout_rate=0.0)
    out_l = layer(paddle.to_tensor(x), paddle.to_tensor(res))
    assert out_l.shape == [B, S, E]


class TestFusedMiscOps:
    def test_fused_matmul_bias(self):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(3, 4)).astype(np.float32)
        w = rng.normal(size=(4, 5)).astype(np.float32)
        b = rng.normal(size=(5,)).astype(np.float32)
        out = IF.fused_matmul_bias(paddle.to_tensor(a), paddle.to_tensor(w),
                                   paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ w + b, rtol=1e-5)
        out2 = IF.fused_matmul_bias(paddle.to_tensor(a.T),
                                    paddle.to_tensor(w),
                                    transpose_x=True)
        np.testing.assert_allclose(out2.numpy(), a @ w, rtol=1e-5)

    def test_fused_dot_product_attention_matches_sdpa(self):
        rng = np.random.default_rng(1)
        q = rng.normal(size=(2, 6, 4, 8)).astype(np.float32)
        out = IF.fused_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            is_causal=True, dropout_p=0.0)
        ref = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            is_causal=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)
        # custom scaling factor changes the result
        out2 = IF.fused_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            is_causal=True, scaling_factor=1.0)
        assert not np.allclose(out2.numpy(), ref.numpy())

    def test_fused_gate_attention_oracle(self):
        """AlphaFold gate-attention pseudo-code oracle (reference
        fused_gate_attention.py:49-68), merged-qkv + separate-weight paths."""
        rng = np.random.default_rng(2)
        n, b, q_len, a, h, d = 2, 3, 5, 8, 2, 4
        q_data = rng.normal(size=(n, b, q_len, a)).astype(np.float32)
        qw = rng.normal(size=(a, h, d)).astype(np.float32) * 0.3
        kw = rng.normal(size=(a, h, d)).astype(np.float32) * 0.3
        vw = rng.normal(size=(a, h, d)).astype(np.float32) * 0.3
        gw = rng.normal(size=(a, h, d)).astype(np.float32) * 0.3
        gb = rng.normal(size=(h, d)).astype(np.float32) * 0.1
        ow = rng.normal(size=(h, d, a)).astype(np.float32) * 0.3
        ob = rng.normal(size=(a,)).astype(np.float32) * 0.1
        nb_bias = rng.normal(size=(n, h, q_len, q_len)).astype(np.float32)

        def oracle():
            c = d ** -0.5
            qq = np.einsum("nbqa,ahc->nbqhc", q_data, qw) * c
            kk = np.einsum("nbka,ahc->nbkhc", q_data, kw)
            vv = np.einsum("nbka,ahc->nbkhc", q_data, vw)
            logits = np.einsum("nbqhc,nbkhc->nbhqk", qq, kk)
            logits = logits + nb_bias[:, None]
            w = np.exp(logits - logits.max(-1, keepdims=True))
            w = w / w.sum(-1, keepdims=True)
            avg = np.einsum("nbhqk,nbkhc->nbqhc", w, vv)
            gate = 1 / (1 + np.exp(-(np.einsum("nbqc,chv->nbqhv", q_data, gw)
                                     + gb)))
            avg = avg * gate
            return np.einsum("nbqhc,hco->nbqo", avg, ow) + ob

        got = IF.fused_gate_attention(
            paddle.to_tensor(q_data),
            query_weight=paddle.to_tensor(qw), key_weight=paddle.to_tensor(kw),
            value_weight=paddle.to_tensor(vw),
            gate_linear_weight=paddle.to_tensor(gw),
            gate_linear_bias=paddle.to_tensor(gb),
            out_linear_weight=paddle.to_tensor(ow),
            out_linear_bias=paddle.to_tensor(ob),
            nonbatched_bias=paddle.to_tensor(nb_bias),
            has_gating=True, merge_qkv=False)
        np.testing.assert_allclose(got.numpy(), oracle(), rtol=2e-4,
                                   atol=2e-4)
        # merged-qkv layout [3, H, D, A] must agree with the separate path
        qkv_w = np.stack([np.transpose(qw, (1, 2, 0)),
                          np.transpose(kw, (1, 2, 0)),
                          np.transpose(vw, (1, 2, 0))])
        got2 = IF.fused_gate_attention(
            paddle.to_tensor(q_data), qkv_weight=paddle.to_tensor(qkv_w),
            gate_linear_weight=paddle.to_tensor(gw),
            gate_linear_bias=paddle.to_tensor(gb),
            out_linear_weight=paddle.to_tensor(ow),
            out_linear_bias=paddle.to_tensor(ob),
            nonbatched_bias=paddle.to_tensor(nb_bias),
            has_gating=True, merge_qkv=True)
        np.testing.assert_allclose(got2.numpy(), got.numpy(), rtol=2e-4,
                                   atol=2e-4)

    def test_fused_gate_attention_validation_and_bool_mask(self):
        rng = np.random.default_rng(3)
        q_data = paddle.to_tensor(
            rng.normal(size=(1, 2, 4, 8)).astype(np.float32))
        qkv_w = paddle.to_tensor(
            rng.normal(size=(3, 2, 4, 8)).astype(np.float32) * 0.3)
        ow = paddle.to_tensor(
            rng.normal(size=(2, 4, 8)).astype(np.float32) * 0.3)
        with pytest.raises(ValueError):
            IF.fused_gate_attention(q_data, qkv_weight=qkv_w)  # no out weight
        with pytest.raises(ValueError):
            IF.fused_gate_attention(q_data, qkv_weight=qkv_w,
                                    out_linear_weight=ow)  # gating w missing
        # bool keep-mask masks keys out (parity with the additive -inf form)
        keep = np.ones((1, 2, 2, 4, 4), bool)
        keep[..., -1] = False
        out_b = IF.fused_gate_attention(
            q_data, qkv_weight=qkv_w, out_linear_weight=ow,
            attn_mask=paddle.to_tensor(keep), has_gating=False)
        add = np.where(keep, 0.0, -1e30).astype(np.float32)
        out_f = IF.fused_gate_attention(
            q_data, qkv_weight=qkv_w, out_linear_weight=ow,
            attn_mask=paddle.to_tensor(add), has_gating=False)
        np.testing.assert_allclose(out_b.numpy(), out_f.numpy(), rtol=1e-5)
