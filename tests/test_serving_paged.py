"""Paged-KV serving subsystem (paddle_tpu/inference/paged/): block pool,
two-queue scheduler, and the PagedServingEngine — including the acceptance
properties: per-token parity with the dense ContinuousBatchingEngine on
mixed greedy/sampled workloads (prefix sharing on and off), strictly more
concurrency than dense at equal HBM page budget, and preemption under an
undersized pool that recovers every request with no lost tokens."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.inference.paged import (
    BlockPool,
    PagedServingEngine,
    SpilledRequest,
    TwoQueueScheduler,
    prefix_page_key,
)
from paddle_tpu.models import GPTForCausalLM, gpt3_tiny
from paddle_tpu.observability.metrics import default_registry


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret_unless_hw):
    pass


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return GPTForCausalLM(gpt3_tiny())


def _counter(name, **labels):
    m = default_registry().get(name)
    return m.value(**labels) if m is not None else 0.0


def _drive(eng, prompts, temps=None, max_new=None, priorities=None):
    ids = [eng.add_request(
        p,
        max_new_tokens=6 if max_new is None else max_new[i],
        temperature=0.0 if temps is None else temps[i],
        priority=0 if priorities is None else priorities[i])
        for i, p in enumerate(prompts)]
    done = eng.run()
    by = {r.req_id: r for r in done}
    return [by[i] for i in ids]


# --------------------------------------------------------------------------- #
# block pool
# --------------------------------------------------------------------------- #


class TestBlockPool:
    def _pool(self, **kw):
        kw.setdefault("num_layers", 1)
        kw.setdefault("kv_heads", 1)
        kw.setdefault("head_dim", 4)
        kw.setdefault("page_size", 4)
        kw.setdefault("num_pages", 5)
        return BlockPool(**kw)

    def test_alloc_free_cycle_never_hands_out_null_page(self):
        pool = self._pool()
        assert pool.pages_total == 4
        got = [pool.alloc() for _ in range(4)]
        assert 0 not in got and pool.alloc() is None
        for p in got:
            pool.release(p)
        assert pool.pages_free == 4

    def test_refcounted_prefix_sharing_and_unregister(self):
        pool = self._pool()
        key = prefix_page_key(np.arange(4, dtype=np.int32), 0, 4)
        p = pool.alloc()
        pool.register_prefix(key, p)
        assert pool.lookup_prefix(key) == p and pool.is_shared(p)
        pool.release(p)            # one holder left
        assert not pool.is_shared(p) and pool.is_registered(p)
        pool.unregister_page(p)    # first divergent write would do this
        assert pool.lookup_prefix(key) is None
        pool.release(p)
        assert pool.pages_free == 4  # freed page left the prefix map too

    def test_release_to_zero_unregisters(self):
        pool = self._pool()
        key = b"k" * 16
        p = pool.alloc()
        pool.register_prefix(key, p)
        pool.release(p)
        assert pool.lookup_prefix(key) is None  # no dangling shared page

    def test_copy_page_copies_content(self):
        pool = self._pool()
        src, dst = pool.alloc(), pool.alloc()
        k, v = pool.kv[0]
        pool.kv[0] = (k.at[src].set(1.5), v.at[src].set(2.5))
        pool.copy_page(src, dst)
        k, v = pool.kv[0]
        np.testing.assert_array_equal(np.asarray(k[dst]), np.asarray(k[src]))
        np.testing.assert_array_equal(np.asarray(v[dst]), np.asarray(v[src]))

    def test_spill_roundtrip(self):
        pool = self._pool()
        pages = [pool.alloc(), pool.alloc()]
        k, v = pool.kv[0]
        pool.kv[0] = (k.at[pages[0]].set(3.0), v.at[pages[1]].set(4.0))
        host = pool.read_pages(pages)
        for p in pages:
            pool.release(p)
        fresh = [pool.alloc(), pool.alloc()]
        pool.restore_pages(fresh, host, [0, 1])
        k, v = pool.kv[0]
        assert float(k[fresh[0]].sum()) == pytest.approx(3.0 * 4 * 4)
        assert float(v[fresh[1]].sum()) == pytest.approx(4.0 * 4 * 4)


# --------------------------------------------------------------------------- #
# scheduler
# --------------------------------------------------------------------------- #


class TestTwoQueueScheduler:
    def _req(self, n):
        from paddle_tpu.inference.serving import GenerationRequest

        return GenerationRequest(np.arange(n, dtype=np.int32))

    def test_watermark_blocks_head_of_line(self):
        sched = TwoQueueScheduler(page_size=16, watermark_pages=2)
        a, b = self._req(20), self._req(20)  # 2 pages each
        sched.enqueue_prefill(a)
        sched.enqueue_prefill(b)
        picked = sched.pick(free_rows=4, pages_free=5, live=0)
        # a fits (5-2 >= 2); b would leave 1 < watermark 2 -> blocked
        assert picked == [a] and sched.waiting_prefill == 1

    def test_fifo_across_buckets(self):
        """Arrival order wins over bucket grouping — the property that keeps
        the sampling-key stream identical to the dense engine's."""
        sched = TwoQueueScheduler(page_size=16, watermark_pages=0)
        big, small, big2 = self._req(30), self._req(4), self._req(30)
        for r in (big, small, big2):
            sched.enqueue_prefill(r)
        assert sched.pick(3, 100, 0) == [big, small, big2]

    def test_resume_queue_preempts_fresh_prefills(self):
        sched = TwoQueueScheduler(page_size=16, watermark_pages=0)
        fresh = self._req(4)
        sched.enqueue_prefill(fresh)
        spilled = SpilledRequest(self._req(4), 5, 1, [], [None])
        sched.enqueue_resume(spilled)
        assert sched.pick(2, 100, 0) == [spilled, fresh]

    def test_idle_engine_admits_whole_pool_request(self):
        """A request whose prompt needs every pool page must not deadlock
        behind the watermark when nothing is live: the head request admits
        whenever it fits at all on an idle engine."""
        sched = TwoQueueScheduler(page_size=16, watermark_pages=1)
        big = self._req(32)  # 2 pages
        sched.enqueue_prefill(big)
        assert sched.pick(free_rows=1, pages_free=2, live=0) == [big]
        # ...but not when other requests are live (reserve holds)
        sched.enqueue_prefill(self._req(32))
        assert sched.pick(free_rows=1, pages_free=2, live=1) == []

    def test_dynamic_watermark_reserves_per_live_row(self):
        sched = TwoQueueScheduler(page_size=16)  # watermark = max(1, live)
        a = self._req(16)  # 1 page
        sched.enqueue_prefill(a)
        assert sched.pick(1, 2, live=3) == []      # 2 - 1 < 3
        assert sched.pick(1, 5, live=3) == [a]     # 5 - 1 >= 3


# --------------------------------------------------------------------------- #
# engine
# --------------------------------------------------------------------------- #


class TestPagedServingEngine:
    def test_mixed_workload_parity_with_dense(self, model):
        """Mixed greedy/sampled, staggered lengths, shared prefixes:
        per-token output identical to the dense engine, prefix sharing on
        AND off; sharing shows hits and allocates fewer pages. (Parity vs
        plain generate() is transitive: test_serving.py pins dense ==
        generate.)"""
        rng = np.random.default_rng(42)
        shared = rng.integers(1, 1000, 20).astype(np.int32)
        prompts, temps = [], []
        for i in range(5):
            tail = rng.integers(1, 1000, 3 + i).astype(np.int32)
            prompts.append(np.concatenate([shared, tail]) if i % 2 == 0
                           else rng.integers(1, 1000, 4 + i).astype(np.int32))
            temps.append(0.0 if i % 3 else 0.7)
        max_new = [4 + i % 3 for i in range(5)]

        dense = _drive(ContinuousBatchingEngine(
            model, max_batch_size=4, max_seq_len=64, seed=3),
            prompts, temps, max_new)
        d_tokens = [r.generated for r in dense]

        hits0 = _counter("serving_prefix_hits_total")
        share_on = PagedServingEngine(model, max_batch_size=4, max_seq_len=64,
                                      page_size=16, seed=3)
        p_tokens = [r.generated
                    for r in _drive(share_on, prompts, temps, max_new)]
        assert p_tokens == d_tokens
        assert _counter("serving_prefix_hits_total") > hits0

        share_off = PagedServingEngine(model, max_batch_size=4,
                                       max_seq_len=64, page_size=16, seed=3,
                                       prefix_sharing=False)
        p2_tokens = [r.generated
                     for r in _drive(share_off, prompts, temps, max_new)]
        assert p2_tokens == d_tokens
        assert share_on.pool.allocs_total < share_off.pool.allocs_total

    def test_admits_more_concurrency_than_dense_hbm(self, model):
        """At the dense engine's exact HBM budget (max_batch_size=4 x
        max_seq_len=64 token slots), the paged engine runs 8 concurrent
        requests — pages are allocated per token actually cached, not per
        slot capacity."""
        dense_budget_pages = (4 * 64) // 16
        eng = PagedServingEngine(model, max_batch_size=8, max_seq_len=64,
                                 page_size=16,
                                 num_pages=dense_budget_pages + 1)
        rng = np.random.default_rng(0)
        for _ in range(8):
            eng.add_request(rng.integers(1, 1000, 6).astype(np.int32),
                            max_new_tokens=4)
        eng.step()
        assert eng.live_count == 8  # strictly more than dense's 4 slots
        assert all(len(r.generated) == 4 for r in eng.run())

    def test_cow_on_first_divergent_write(self, model):
        """Two identical prompts share every page including the partial
        tail; the first decode write must copy-on-write, and both requests
        still produce identical (correct) greedy tokens."""
        cow0 = _counter("serving_cow_copies_total")
        eng = PagedServingEngine(model, max_batch_size=4, max_seq_len=64,
                                 page_size=16, seed=3)
        prompt = np.random.default_rng(1).integers(1, 1000, 10).astype(np.int32)
        eng.add_request(prompt, max_new_tokens=4)
        eng.add_request(prompt, max_new_tokens=4)
        out = eng.run()
        assert out[0].generated == out[1].generated
        assert _counter("serving_cow_copies_total") > cow0

    def test_preemption_recovers_all_requests(self, model):
        """Deliberately undersized pool: decode growth across page
        boundaries must preempt (spill to host) and later resume, with
        per-token output still identical to the dense engine — no lost or
        recomputed tokens."""
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, 1000, 14).astype(np.int32)
                   for _ in range(4)]
        prios = [0, -1, -2, -3]
        dense = _drive(ContinuousBatchingEngine(
            model, max_batch_size=4, max_seq_len=64, seed=3),
            prompts, max_new=[6] * 4, priorities=prios)
        pre0 = _counter("serving_preemptions_total")
        res0 = _counter("serving_resumes_total")
        # 4 x 14-token prompts = 4 pages; growth wants 4 more; pool holds 5
        eng = PagedServingEngine(model, max_batch_size=4, max_seq_len=64,
                                 page_size=16, seed=3, num_pages=6,
                                 watermark_pages=0, prefix_sharing=False)
        paged = _drive(eng, prompts, max_new=[6] * 4, priorities=prios)
        assert [r.generated for r in paged] == [r.generated for r in dense]
        assert _counter("serving_preemptions_total") > pre0
        assert _counter("serving_resumes_total") > res0

    def test_truncation_is_flagged_and_counted(self, model):
        """A request whose prompt + budget exceeds max_seq_len retires at
        capacity with truncated=True and a counter bump (the dense engine's
        variant lives in test_serving.py)."""
        prompt = np.arange(1, 11, dtype=np.int32)  # 10 tokens, S=16
        eng = PagedServingEngine(model, max_batch_size=2, max_seq_len=16,
                                 page_size=8)
        t0 = _counter("serving_truncations_total", engine="paged")
        eng.add_request(prompt, max_new_tokens=100)
        done = eng.run()
        assert done[0].truncated
        assert len(done[0].generated) == 6  # 16 - 10
        assert _counter("serving_truncations_total", engine="paged") == t0 + 1

    def test_add_request_validation(self, model):
        eng = PagedServingEngine(model, max_batch_size=2, max_seq_len=16,
                                 page_size=8, num_pages=2)  # 1 usable page
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.add_request(np.zeros(16, np.int32))
        with pytest.raises(ValueError, match="pages"):
            eng.add_request(np.zeros(10, np.int32), max_new_tokens=4)

    # the bounded prefill compile cache is the shared BoundedCompileCache;
    # its cap/eviction/counter behavior is pinned on the dense engine in
    # test_serving.py::TestServingSatellites::test_prefill_compile_cache_capped


@pytest.mark.slow
class TestPagedDrainEndToEnd:
    def test_large_mixed_drain_under_pressure(self, model):
        """End-to-end: 16 mixed greedy/sampled requests with shared
        prefixes through an undersized pool — everything drains, outputs
        match the dense engine, and the SLO series are populated."""
        rng = np.random.default_rng(11)
        shared = rng.integers(1, 1000, 16).astype(np.int32)
        prompts, temps, max_new, prios = [], [], [], []
        for i in range(16):
            tail = rng.integers(1, 1000, 2 + i % 7).astype(np.int32)
            prompts.append(np.concatenate([shared, tail]) if i % 3 == 0
                           else rng.integers(1, 1000, 3 + i % 9).astype(np.int32))
            temps.append(0.6 if i % 4 == 0 else 0.0)
            max_new.append(4 + i % 6)
            prios.append(-(i % 5))
        dense = _drive(ContinuousBatchingEngine(
            model, max_batch_size=4, max_seq_len=64, seed=9),
            prompts, temps, max_new, prios)
        eng = PagedServingEngine(model, max_batch_size=4, max_seq_len=64,
                                 page_size=16, seed=9, num_pages=8,
                                 watermark_pages=1)
        paged = _drive(eng, prompts, temps, max_new, prios)
        assert [r.generated for r in paged] == [r.generated for r in dense]
        reg = default_registry()
        ttft = reg.get("serving_ttft_seconds")
        assert ttft is not None and ttft.count(engine="paged") >= 16
        assert reg.get("serving_tokens_total").value(engine="paged") >= \
            sum(len(r.generated) for r in paged)
