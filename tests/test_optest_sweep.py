"""Systematic OpTest sweep (reference: test/legacy_test/op_test.py:418).

One registry of (op, numpy-ref, input-specs); every entry is checked
fwd-vs-NumPy (f32 + bf16), fwd under jax.jit, and VJP-vs-finite-difference
(f32, plus bf16-vs-f32 drift) by the generic harness in
paddle_tpu.utils.op_test. Seeded from the Tensor-method surface.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.utils.op_test import (InSpec, OpSpec, check_grad,
                                      check_forward, run_all_checks)

S = InSpec  # shorthand


def _sp(*args, **kw):
    return OpSpec(*args, **kw)


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _erf_np(x):
    from scipy.special import erf

    return erf(x)




def _put_np(x, v):
    out = x.copy()
    for r in range(3):
        out[r, r] = v[r, 0]
    return out


def _renorm_np(x):
    norms = np.linalg.norm(x, axis=1, keepdims=True)
    scale = np.minimum(1.0 / np.maximum(norms, 1e-7), 1.0)
    return x * scale


def _smooth_l1_np(a, b, delta=1.0):
    d = np.abs(a - b)
    return np.mean(np.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta))


_NAN_MASK = np.zeros((3, 4), bool)
_NAN_MASK[0, 0] = _NAN_MASK[1, 2] = _NAN_MASK[2, 3] = True
_NAN_FILL = np.where(
    np.arange(12).reshape(3, 4) % 2 == 0, np.nan, np.inf).astype(np.float32)

POS = S(low=0.1, high=3.0)
UNIT = S(low=-0.9, high=0.9)
NZ = S(avoid_zero=True)
INT8 = S(dtype="int", low=0, high=8)

REGISTRY = [
    # ---- unary math (Tensor methods) ---------------------------------- #
    _sp("abs", paddle.abs, np.abs, [NZ]),
    _sp("acos", paddle.acos, np.arccos, [UNIT]),
    _sp("acosh", paddle.acosh, np.arccosh, [S(low=1.1, high=3.0)]),
    _sp("asin", paddle.asin, np.arcsin, [UNIT]),
    _sp("asinh", paddle.asinh, np.arcsinh),
    _sp("atan", paddle.atan, np.arctan),
    _sp("atanh", paddle.atanh, np.arctanh, [UNIT]),
    _sp("ceil", paddle.ceil, np.ceil, [NZ], check_grad=False),
    _sp("cos", paddle.cos, np.cos),
    _sp("cosh", paddle.cosh, np.cosh),
    _sp("sin", paddle.sin, np.sin),
    _sp("sinh", paddle.sinh, np.sinh),
    _sp("tan", paddle.tan, np.tan, [UNIT]),
    _sp("tanh", paddle.tanh, np.tanh),
    _sp("exp", paddle.exp, np.exp),
    _sp("expm1", paddle.expm1, np.expm1),
    _sp("log", paddle.log, np.log, [POS]),
    _sp("log1p", paddle.log1p, np.log1p, [POS]),
    _sp("log2", paddle.log2, np.log2, [POS]),
    _sp("log10", paddle.log10, np.log10, [POS]),
    _sp("sqrt", paddle.sqrt, np.sqrt, [POS]),
    _sp("rsqrt", paddle.rsqrt, lambda x: 1.0 / np.sqrt(x), [POS]),
    _sp("square", paddle.square, np.square),
    _sp("sign", paddle.sign, np.sign, [NZ], check_grad=False),
    _sp("floor", paddle.floor, np.floor, [NZ], check_grad=False),
    _sp("round", paddle.round, np.round, [NZ], check_grad=False),
    _sp("trunc", paddle.trunc, np.trunc, [NZ], check_grad=False),
    _sp("erf", paddle.erf, _erf_np),
    _sp("reciprocal", paddle.reciprocal, np.reciprocal, [NZ]),
    _sp("neg", paddle.neg, np.negative),
    _sp("sigmoid", F.sigmoid, lambda x: 1 / (1 + np.exp(-x))),
    _sp("angle", paddle.angle, np.angle, [NZ], check_grad=False),
    _sp("deg2rad", paddle.deg2rad, np.deg2rad),
    _sp("rad2deg", paddle.rad2deg, np.rad2deg),
    _sp("digamma", paddle.digamma,
        lambda x: __import__("scipy.special", fromlist=["digamma"]).digamma(x),
        [POS]),
    _sp("lgamma", paddle.lgamma,
        lambda x: __import__("scipy.special", fromlist=["gammaln"]).gammaln(x),
        [POS]),
    _sp("sinc", paddle.sinc, np.sinc, [NZ]),
    _sp("i0", paddle.i0,
        lambda x: __import__("scipy.special", fromlist=["i0"]).i0(x), [POS]),
    _sp("logit", paddle.logit,
        lambda x: np.log(x / (1 - x)), [S(low=0.15, high=0.85)]),
    # ---- binary ------------------------------------------------------- #
    _sp("add", paddle.add, np.add, [S(), S()]),
    _sp("subtract", paddle.subtract, np.subtract, [S(), S()]),
    _sp("multiply", paddle.multiply, np.multiply, [S(), S()]),
    _sp("divide", paddle.divide, np.divide, [S(), S(low=0.5, high=2.0)]),
    _sp("maximum", paddle.maximum, np.maximum, [S(), S()]),
    _sp("minimum", paddle.minimum, np.minimum, [S(), S()]),
    _sp("pow", paddle.pow, np.power, [POS, S(low=0.5, high=2.0)]),
    _sp("atan2", paddle.atan2, np.arctan2, [NZ, NZ]),
    _sp("hypot", paddle.hypot, np.hypot, [NZ, NZ]),
    _sp("remainder", paddle.remainder, np.remainder,
        [S(low=0.5, high=4.0), S(low=1.0, high=3.0)], check_grad=False),
    _sp("fmax", paddle.fmax, np.fmax, [S(), S()]),
    _sp("fmin", paddle.fmin, np.fmin, [S(), S()]),
    _sp("logaddexp", paddle.logaddexp, np.logaddexp, [S(), S()]),
    _sp("nextafter", paddle.nextafter, np.nextafter, [S(), S()],
        check_grad=False),
    _sp("copysign", paddle.copysign, np.copysign, [NZ, NZ],
        check_grad=False),
    # ---- reductions --------------------------------------------------- #
    _sp("sum", paddle.sum, np.sum),
    _sp("mean", paddle.mean, np.mean),
    _sp("max", lambda x: paddle.max(x), lambda x: np.max(x)),
    _sp("min", lambda x: paddle.min(x), lambda x: np.min(x)),
    _sp("prod", paddle.prod, np.prod, [S(low=0.5, high=1.5)]),
    _sp("std", paddle.std,
        lambda x: np.std(x, ddof=1), fd_rtol=0.12),
    _sp("var", paddle.var, lambda x: np.var(x, ddof=1)),
    _sp("logsumexp", paddle.logsumexp,
        lambda x: np.log(np.exp(x).sum())),
    _sp("cumsum", paddle.cumsum, lambda x: np.cumsum(x)),
    _sp("cumprod", lambda x: paddle.cumprod(x, dim=0),
        lambda x: np.cumprod(x, axis=0), [S(shape=(12,), low=0.5, high=1.5)]),
    _sp("median", paddle.median, np.median, [S(shape=(3, 5))],
        check_grad=False),
    _sp("nanmean", paddle.nanmean, np.nanmean),
    _sp("count_nonzero", paddle.count_nonzero,
        lambda x: np.count_nonzero(x), [NZ], check_grad=False),
    # ---- linalg ------------------------------------------------------- #
    _sp("matmul", paddle.matmul, np.matmul, [S((3, 4)), S((4, 5))]),
    _sp("bmm", paddle.bmm, np.matmul, [S((2, 3, 4)), S((2, 4, 3))]),
    _sp("dot", paddle.dot, np.dot, [S((6,)), S((6,))]),
    _sp("outer", paddle.outer, np.outer, [S((3,)), S((4,))]),
    _sp("cross", lambda a, b: paddle.cross(a, b, axis=-1),
        lambda a, b: np.cross(a, b, axis=-1), [S((4, 3)), S((4, 3))]),
    _sp("trace", paddle.trace, np.trace, [S((4, 4))]),
    _sp("diag", paddle.diag, np.diag, [S((5,))]),
    _sp("tril", paddle.tril, np.tril, [S((4, 4))]),
    _sp("triu", paddle.triu, np.triu, [S((4, 4))]),
    _sp("kron", paddle.kron, np.kron, [S((2, 2)), S((3, 2))]),
    _sp("t", paddle.t, np.transpose, [S((3, 4))]),
    _sp("cholesky",
        lambda a: paddle.linalg.cholesky(
            paddle.matmul(a, paddle.t(a)) + 3.0 * paddle.eye(3)),
        lambda a: np.linalg.cholesky(a @ a.T + 3.0 * np.eye(3)),
        [S((3, 3))], fd_rtol=0.12),
    _sp("norm", lambda x: paddle.linalg.norm(x),
        lambda x: np.linalg.norm(x.reshape(-1)), [S((3, 4))]),
    _sp("matrix_power", lambda x: paddle.linalg.matrix_power(x, 2),
        lambda x: np.linalg.matrix_power(x, 2), [S((3, 3))]),
    _sp("inverse", paddle.inverse,
        np.linalg.inv, [S((3, 3), low=1.0, high=2.0)], check_grad=False,
        check_bf16=False),
    _sp("pinv", lambda x: paddle.linalg.pinv(x), np.linalg.pinv,
        [S((4, 3))], check_grad=False, rtol=1e-4, atol=1e-4,
        check_bf16=False),
    _sp("slogdet",
        lambda x: paddle.linalg.slogdet(
            paddle.matmul(x, paddle.t(x)) + 3.0 * paddle.eye(3))[1],
        lambda x: np.linalg.slogdet(x @ x.T + 3.0 * np.eye(3))[1],
        [S((3, 3))], fd_rtol=0.12),
    # ---- manipulation ------------------------------------------------- #
    _sp("reshape", lambda x: paddle.reshape(x, [4, 3]),
        lambda x: np.reshape(x, (4, 3))),
    _sp("squeeze", lambda x: paddle.squeeze(x, 0),
        lambda x: np.squeeze(x, 0), [S((1, 3, 4))]),
    _sp("unsqueeze", lambda x: paddle.unsqueeze(x, 1),
        lambda x: np.expand_dims(x, 1)),
    _sp("flatten", paddle.flatten, np.ravel),
    _sp("concat", lambda a, b: paddle.concat([a, b]),
        lambda a, b: np.concatenate([a, b]), [S(), S()]),
    _sp("stack", lambda a, b: paddle.stack([a, b]),
        lambda a, b: np.stack([a, b]), [S(), S()]),
    _sp("flip", lambda x: paddle.flip(x, axis=0), lambda x: np.flip(x, 0)),
    _sp("roll", lambda x: paddle.roll(x, 2), lambda x: np.roll(x, 2)),
    _sp("tile", lambda x: paddle.tile(x, [2, 1]),
        lambda x: np.tile(x, (2, 1))),
    _sp("broadcast_to", lambda x: paddle.broadcast_to(x, [5, 3, 4]),
        lambda x: np.broadcast_to(x, (5, 3, 4))),
    _sp("clip", lambda x: paddle.clip(x, -1.0, 1.0),
        lambda x: np.clip(x, -1.0, 1.0), [S(low=-3, high=3)]),
    _sp("transpose", lambda x: paddle.transpose(x, [1, 0]),
        lambda x: np.transpose(x, (1, 0))),
    _sp("split", lambda x: paddle.split(x, 2, axis=1)[0],
        lambda x: np.split(x, 2, axis=1)[0], [S((3, 4))]),
    _sp("chunk", lambda x: paddle.chunk(x, 2, axis=1)[0],
        lambda x: np.array_split(x, 2, axis=1)[0], [S((3, 4))]),
    _sp("gather", lambda x, i: paddle.gather(x, i),
        lambda x, i: x[i], [S((6, 3)), S((4,), dtype="int", low=0, high=6)]),
    _sp("index_select", lambda x, i: paddle.index_select(x, i),
        lambda x, i: x[i], [S((6, 3)), S((4,), dtype="int", low=0, high=6)]),
    _sp("where", lambda c, a, b: paddle.where(c, a, b),
        lambda c, a, b: np.where(c, a, b),
        [S(dtype="bool"), S(), S()]),
    _sp("masked_select",
        lambda x: paddle.masked_select(x, paddle.to_tensor(
            np.tile([True, False], 6).reshape(3, 4))),
        lambda x: x[np.tile([True, False], 6).reshape(3, 4)],
        check_jit=False, check_grad=False),  # value-dependent output shape
    _sp("take_along_axis",
        lambda x, i: paddle.take_along_axis(x, i, axis=1),
        lambda x, i: np.take_along_axis(x, i, axis=1),
        [S((3, 4)), S((3, 2), dtype="int", low=0, high=4)]),
    _sp("sort", lambda x: paddle.sort(x, axis=-1),
        lambda x: np.sort(x, axis=-1)),
    _sp("argsort", lambda x: paddle.argsort(x, axis=-1),
        lambda x: np.argsort(x, axis=-1), check_grad=False),
    _sp("argmax", paddle.argmax, np.argmax, check_grad=False),
    _sp("argmin", paddle.argmin, np.argmin, check_grad=False),
    _sp("topk", lambda x: paddle.topk(x, 2)[0],
        lambda x: np.sort(x, axis=-1)[..., ::-1][..., :2]),
    _sp("unbind", lambda x: paddle.unbind(x)[1], lambda x: x[1],
        [S((3, 4))]),
    _sp("rot90", lambda x: paddle.rot90(x), lambda x: np.rot90(x)),
    _sp("moveaxis", lambda x: paddle.moveaxis(x, 0, 1),
        lambda x: np.moveaxis(x, 0, 1)),
    _sp("repeat_interleave", lambda x: paddle.repeat_interleave(x, 2, axis=0),
        lambda x: np.repeat(x, 2, axis=0)),
    _sp("diff", paddle.diff, lambda x: np.diff(x), [S((12,))]),
    _sp("searchsorted",
        lambda s, v: paddle.searchsorted(s, v),
        lambda s, v: np.searchsorted(s, v),
        [S((8,), low=0, high=0.0001), S((4,))], check_grad=False),
    # ---- comparisons / logic (no grads) -------------------------------- #
    _sp("equal", paddle.equal, np.equal, [INT8, INT8], check_grad=False),
    _sp("less_than", paddle.less_than, np.less, [S(), S()],
        check_grad=False),
    _sp("greater_than", paddle.greater_than, np.greater, [S(), S()],
        check_grad=False),
    _sp("logical_and", paddle.logical_and, np.logical_and,
        [S(dtype="bool"), S(dtype="bool")], check_grad=False),
    _sp("logical_not", paddle.logical_not, np.logical_not,
        [S(dtype="bool")], check_grad=False),
    _sp("isnan", paddle.isnan, np.isnan, check_grad=False),
    _sp("isinf", paddle.isinf, np.isinf, check_grad=False),
    _sp("isfinite", paddle.isfinite, np.isfinite, check_grad=False),
    _sp("bitwise_and", paddle.bitwise_and, np.bitwise_and, [INT8, INT8],
        check_grad=False),
    _sp("bitwise_xor", paddle.bitwise_xor, np.bitwise_xor, [INT8, INT8],
        check_grad=False),
    # ---- activations / nn.functional ----------------------------------- #
    _sp("softmax", lambda x: F.softmax(x, axis=-1), _softmax_np),
    _sp("log_softmax", lambda x: F.log_softmax(x, axis=-1),
        lambda x: np.log(_softmax_np(x))),
    _sp("relu", F.relu, lambda x: np.maximum(x, 0), [NZ]),
    _sp("leaky_relu", F.leaky_relu,
        lambda x: np.where(x > 0, x, 0.01 * x), [NZ]),
    _sp("elu", F.elu, lambda x: np.where(x > 0, x, np.expm1(x)), [NZ]),
    _sp("silu", F.silu, lambda x: x / (1 + np.exp(-x))),
    _sp("softplus", F.softplus, lambda x: np.log1p(np.exp(x))),
    _sp("hardtanh", F.hardtanh, lambda x: np.clip(x, -1, 1),
        [S(low=-3, high=3, avoid_zero=True)]),
    _sp("gelu", F.gelu, lambda x: x * 0.5 * (1 + _erf_np(x / np.sqrt(2)))),
    _sp("mish", F.mish, lambda x: x * np.tanh(np.log1p(np.exp(x)))),
    _sp("swish", F.swish, lambda x: x / (1 + np.exp(-x))),
    _sp("tanhshrink", F.tanhshrink, lambda x: x - np.tanh(x)),
    _sp("softsign", F.softsign, lambda x: x / (1 + np.abs(x)), [NZ]),
    _sp("relu6", F.relu6, lambda x: np.minimum(np.maximum(x, 0), 6), [NZ]),
    _sp("hardswish", F.hardswish,
        lambda x: x * np.clip(x + 3, 0, 6) / 6,
        [S(low=-5, high=5, avoid_zero=True)], fd_rtol=0.12),
    _sp("normalize", lambda x: F.normalize(x, axis=-1),
        lambda x: x / np.maximum(
            np.sqrt((x ** 2).sum(-1, keepdims=True)), 1e-12)),
    _sp("mse_loss", F.mse_loss, lambda a, b: np.mean((a - b) ** 2),
        [S(), S()]),
    _sp("l1_loss", F.l1_loss, lambda a, b: np.mean(np.abs(a - b)),
        [S(), S(low=3.0, high=5.0)]),
    # ---- round-3 breadth batch ----------------------------------------- #
    _sp("lerp", lambda a, b: paddle.lerp(a, b, 0.3),
        lambda a, b: a + 0.3 * (b - a), [S(), S()]),
    _sp("addmm", lambda c, a, b: paddle.addmm(c, a, b, beta=0.5, alpha=2.0),
        lambda c, a, b: 0.5 * c + 2.0 * (a @ b),
        [S((3, 5)), S((3, 4)), S((4, 5))]),
    _sp("diag_embed", paddle.diag_embed,
        lambda x: np.stack([np.diag(r) for r in x]), [S((3, 4))]),
    _sp("diagonal", lambda x: paddle.diagonal(x),
        lambda x: np.diagonal(x), [S((4, 4))]),
    _sp("kthvalue", lambda x: paddle.kthvalue(x, 2)[0],
        lambda x: np.sort(x, axis=-1)[..., 1]),
    _sp("mode", lambda x: paddle.mode(x)[0],
        lambda x: __import__("scipy.stats", fromlist=["mode"]).mode(
            x, axis=-1, keepdims=False).mode,
        [S((3, 8), dtype="int", low=0, high=3)], check_grad=False,
        check_jit=False),  # host-side bincount path
    _sp("masked_fill",
        lambda x: paddle.masked_fill(x, paddle.to_tensor(
            np.tile([True, False], 6).reshape(3, 4)), 7.0),
        lambda x: np.where(np.tile([True, False], 6).reshape(3, 4), 7.0, x)),
    _sp("index_fill",
        lambda x: paddle.index_fill(
            x, paddle.to_tensor(np.asarray([1], np.int32)), 0, 9.0),
        lambda x: np.concatenate([x[:1], np.full((1, 4), 9.0), x[2:]])),
    _sp("put_along_axis",
        lambda x, v: paddle.put_along_axis(
            x, paddle.to_tensor(np.asarray([[0], [1], [2]], np.int32)), v,
            axis=1),
        lambda x, v: _put_np(x, v), [S((3, 4)), S((3, 1))]),
    _sp("gather_nd",
        lambda x: paddle.gather_nd(x, paddle.to_tensor(
            np.asarray([[0, 1], [2, 3]], np.int32))),
        lambda x: x[[0, 2], [1, 3]]),
    _sp("tensordot", lambda a, b: paddle.tensordot(a, b, axes=1),
        lambda a, b: np.tensordot(a, b, axes=1), [S((3, 4)), S((4, 5))]),
    _sp("dist", lambda a, b: paddle.dist(a, b, p=2),
        lambda a, b: np.linalg.norm((a - b).ravel()), [S(), S()]),
    _sp("det",
        lambda x: paddle.linalg.det(
            paddle.matmul(x, paddle.t(x)) + 3.0 * paddle.eye(3)),
        lambda x: np.linalg.det(x @ x.T + 3.0 * np.eye(3)),
        [S((3, 3))], fd_rtol=0.15),
    _sp("solve",
        lambda a, b: paddle.linalg.solve(
            paddle.matmul(a, paddle.t(a)) + 3.0 * paddle.eye(3), b),
        lambda a, b: np.linalg.solve(a @ a.T + 3.0 * np.eye(3), b),
        [S((3, 3)), S((3, 2))], check_bf16=False, fd_rtol=0.12),
    _sp("triangular_solve",
        lambda a, b: paddle.linalg.triangular_solve(
            paddle.tril(a) + 3.0 * paddle.eye(3), b, upper=False),
        lambda a, b: np.linalg.solve(np.tril(a) + 3.0 * np.eye(3), b),
        [S((3, 3)), S((3, 2))], check_bf16=False, fd_rtol=0.12),
    _sp("bucketize",
        lambda x: paddle.bucketize(x, paddle.to_tensor(
            np.asarray([-1.0, 0.0, 1.0], np.float32))),
        lambda x: np.searchsorted([-1.0, 0.0, 1.0], x.ravel()).reshape(
            x.shape), check_grad=False),
    _sp("histogram", lambda x: paddle.histogram(x, bins=4, min=-2, max=2),
        lambda x: np.histogram(x, bins=4, range=(-2, 2))[0],
        check_grad=False, check_bf16=False, check_jit=False),
    _sp("nanmedian", paddle.nanmedian, np.nanmedian, [S((3, 5))],
        check_grad=False),
    _sp("frac", paddle.frac, lambda x: x - np.trunc(x), [NZ],
        check_grad=False),
    _sp("nan_to_num",
        lambda x: paddle.nan_to_num(paddle.where(
            paddle.to_tensor(_NAN_MASK), paddle.to_tensor(_NAN_FILL), x)),
        lambda x: np.nan_to_num(np.where(_NAN_MASK, _NAN_FILL, x)),
        check_grad=False),
    _sp("heaviside", paddle.heaviside, np.heaviside, [NZ, S()],
        check_grad=False),
    _sp("ldexp", paddle.ldexp, np.ldexp,
        [S(), S(dtype="int", low=0, high=3)], check_grad=False),
    _sp("trapezoid", lambda y: paddle.trapezoid(y, dx=0.5),
        lambda y: np.trapezoid(y, dx=0.5) if hasattr(np, "trapezoid")
        else np.trapz(y, dx=0.5), [S((12,))]),
    _sp("vander", lambda x: paddle.vander(x, 4),
        lambda x: np.vander(x, 4), [S((5,))]),
    _sp("expand_as", lambda a, b: paddle.expand_as(a, b),
        lambda a, b: np.broadcast_to(a, b.shape), [S((1, 4)), S((3, 4))],
        grad_args=[0]),
    _sp("renorm", lambda x: paddle.renorm(x, 2.0, 0, 1.0),
        _renorm_np, [S((3, 4))], fd_rtol=0.12),
    _sp("logcumsumexp", paddle.logcumsumexp,
        lambda x: np.log(np.cumsum(np.exp(x))), [S((10,))]),
    _sp("cosine_similarity",
        lambda a, b: F.cosine_similarity(a, b, axis=-1),
        lambda a, b: (a * b).sum(-1)
        / np.maximum(np.linalg.norm(a, axis=-1)
                     * np.linalg.norm(b, axis=-1), 1e-8),
        [S(), S()]),
    _sp("pairwise_distance",
        lambda a, b: F.pairwise_distance(a, b),
        lambda a, b: np.linalg.norm(a - b + 1e-6, axis=-1),
        [S(), S(low=3.0, high=5.0)], rtol=1e-4, atol=1e-4),
    _sp("one_hot",
        lambda i: F.one_hot(i, 6),
        lambda i: np.eye(6, dtype=np.float32)[i],
        [S((5,), dtype="int", low=0, high=6)], check_grad=False),
    _sp("label_smooth",
        lambda x: F.label_smooth(x, epsilon=0.1),
        lambda x: x * 0.9 + 0.1 / x.shape[-1], [S((3, 4), low=0, high=1)]),
    _sp("nll_loss",
        lambda lp: F.nll_loss(lp, paddle.to_tensor(
            np.asarray([0, 2, 1], np.int64))),
        lambda lp: -np.mean([lp[0, 0], lp[1, 2], lp[2, 1]]),
        [S((3, 4), low=-3, high=-0.1)]),
    _sp("kl_div",
        lambda lp, t: F.kl_div(lp, t, reduction="mean"),
        lambda lp, t: np.mean(t * (np.log(t) - lp)),
        [S((3, 4), low=-3, high=-0.5), S((3, 4), low=0.1, high=1.0)]),
    _sp("smooth_l1_loss",
        lambda a, b: F.smooth_l1_loss(a, b),
        lambda a, b: _smooth_l1_np(a, b), [S(), S(low=3.0, high=5.0)]),
    _sp("linear_fn",
        lambda x, w, b: F.linear(x, w, b),
        lambda x, w, b: x @ w + b, [S((3, 4)), S((4, 5)), S((5,))]),
    _sp("log_sigmoid", F.log_sigmoid,
        lambda x: -np.log1p(np.exp(-x))),
    _sp("celu", lambda x: F.celu(x, alpha=1.5),
        lambda x: np.where(x > 0, x, 1.5 * np.expm1(x / 1.5)), [NZ]),
    _sp("thresholded_relu", lambda x: F.thresholded_relu(x, threshold=0.25),
        lambda x: np.where(x > 0.25, x, 0.0), [NZ]),
    # threshold 0.25 keeps the |x| >= 0.3 inputs clear of the kink for FD
    _sp("softshrink", lambda x: F.softshrink(x, threshold=0.25),
        lambda x: np.where(x > 0.25, x - 0.25,
                           np.where(x < -0.25, x + 0.25, 0)), [NZ]),
    _sp("hardshrink", lambda x: F.hardshrink(x, threshold=0.25),
        lambda x: np.where(np.abs(x) > 0.25, x, 0.0), [NZ]),
    # ---- round-4 growth: activations -------------------------------- #
    _sp("hardsigmoid", F.hardsigmoid,
        lambda x: np.clip(x / 6.0 + 0.5, 0, 1), [NZ]),
    _sp("selu", F.selu,
        lambda x: 1.0507009873554805 * np.where(
            x > 0, x, 1.6732632423543772 * np.expm1(x)), [NZ]),
    _sp("prelu", F.prelu,
        lambda x, w: np.where(x > 0, x, w.reshape(1, -1, 1) * x),
        [S(shape=(2, 4, 3)), S(shape=(4,), low=0.1, high=0.5)]),
    _sp("glu", F.glu,
        lambda x: x[:, :2] / (1 + np.exp(-x[:, 2:])),
        [S(shape=(3, 4))]),
    _sp("maxout", lambda x: F.maxout(x, 2),
        lambda x: x.reshape(3, 2, 2, 4).max(2), [S(shape=(3, 4, 4))]),
    _sp("rrelu_eval", lambda x: F.rrelu(x, training=False),
        lambda x: np.where(x >= 0, x, x * (1 / 8 + 1 / 3) / 2), [NZ]),
    # ---- binary / comparison / bitwise ------------------------------- #
    _sp("floor_divide", paddle.floor_divide, np.floor_divide,
        [S(), S(low=0.5, high=2.0)], check_grad=False),
    _sp("mod", paddle.mod, np.mod, [S(), S(low=0.5, high=2.0)],
        check_grad=False),
    _sp("gcd", paddle.gcd, np.gcd, [INT8, INT8], check_grad=False),
    _sp("lcm", paddle.lcm, np.lcm, [INT8, INT8], check_grad=False),
    _sp("not_equal", paddle.not_equal, np.not_equal, [INT8, INT8],
        check_grad=False, check_bf16=False),
    _sp("greater_equal", paddle.greater_equal, np.greater_equal,
        [INT8, INT8], check_grad=False, check_bf16=False),
    _sp("less_equal", paddle.less_equal, np.less_equal, [INT8, INT8],
        check_grad=False, check_bf16=False),
    _sp("logical_or", paddle.logical_or,
        lambda a, b: np.logical_or(a != 0, b != 0),
        [S(), S()], check_grad=False, check_bf16=False),
    _sp("logical_xor", paddle.logical_xor,
        lambda a, b: np.logical_xor(a != 0, b != 0),
        [S(), S()], check_grad=False, check_bf16=False),
    _sp("bitwise_or", paddle.bitwise_or, np.bitwise_or, [INT8, INT8],
        check_grad=False, check_bf16=False),
    _sp("bitwise_not", paddle.bitwise_not, np.bitwise_not, [INT8],
        check_grad=False, check_bf16=False),
    _sp("bitwise_left_shift", paddle.bitwise_left_shift, np.left_shift,
        [INT8, S(dtype="int", low=0, high=3)],
        check_grad=False, check_bf16=False),
    _sp("bitwise_right_shift", paddle.bitwise_right_shift, np.right_shift,
        [INT8, S(dtype="int", low=0, high=3)],
        check_grad=False, check_bf16=False),
    _sp("isclose", paddle.isclose, np.isclose, [S(), S()],
        check_grad=False, check_bf16=False),
    _sp("equal_all", paddle.equal_all,
        lambda a, b: np.asarray(np.array_equal(a, b)), [INT8, INT8],
        check_grad=False, check_bf16=False),
    _sp("signbit", paddle.signbit, np.signbit, [NZ], check_grad=False,
        check_bf16=False),
    _sp("isneginf", paddle.isneginf, np.isneginf, [NZ],
        check_grad=False, check_bf16=False),
    _sp("isposinf", paddle.isposinf, np.isposinf, [NZ],
        check_grad=False, check_bf16=False),
    # ---- reductions / scans ------------------------------------------ #
    _sp("amax", paddle.amax, np.max, [S()]),
    _sp("amin", paddle.amin, np.min, [S()]),
    _sp("nansum", paddle.nansum, np.nansum, [S()]),
    _sp("cummax", lambda x: paddle.cummax(x, axis=0)[0],
        lambda x: np.maximum.accumulate(x, axis=0), [S()]),
    _sp("cummin", lambda x: paddle.cummin(x, axis=0)[0],
        lambda x: np.minimum.accumulate(x, axis=0), [S()]),
    _sp("p_norm_c", lambda x: paddle._C_ops.p_norm(x, 2.0, -1),
        lambda x: np.linalg.norm(x, axis=-1), [S()]),
    _sp("frobenius_norm_c", paddle._C_ops.frobenius_norm,
        lambda x: np.sqrt((x * x).sum()), [S()]),
    _sp("l1_norm_c", paddle._C_ops.l1_norm,
        lambda x: np.abs(x).sum(), [NZ]),
    _sp("squared_l2_norm_c", paddle._C_ops.squared_l2_norm,
        lambda x: (x * x).sum().reshape(1), [S()]),
    # ---- special functions ------------------------------------------- #
    _sp("polygamma", lambda x: paddle.polygamma(x, 1),
        lambda x: __import__("scipy.special",
                             fromlist=["polygamma"]).polygamma(1, x),
        [POS]),
    _sp("erfinv", paddle.erfinv,
        lambda x: __import__("scipy.special", fromlist=["erfinv"]).erfinv(x),
        [UNIT]),
    _sp("i0e", paddle.i0e,
        lambda x: __import__("scipy.special", fromlist=["i0e"]).i0e(x),
        [POS]),
    _sp("i1", paddle.i1,
        lambda x: __import__("scipy.special", fromlist=["i1"]).i1(x),
        [POS]),
    _sp("i1e", paddle.i1e,
        lambda x: __import__("scipy.special", fromlist=["i1e"]).i1e(x),
        [POS]),
    _sp("gammaln", paddle.gammaln,
        lambda x: __import__("scipy.special", fromlist=["gammaln"]).gammaln(x),
        [POS]),
    # ---- linalg tail ------------------------------------------------- #
    _sp("multi_dot", lambda a, b: paddle.linalg.multi_dot([a, b]),
        lambda a, b: a @ b, [S(shape=(3, 4)), S(shape=(4, 2))]),
    _sp("svdvals", paddle.linalg.svdvals,
        lambda x: np.linalg.svd(x, compute_uv=False),
        [S(shape=(4, 3))], check_bf16=False, check_grad=False),
    _sp("matrix_exp", paddle.linalg.matrix_exp,
        lambda x: __import__("scipy.linalg",
                             fromlist=["expm"]).expm(x),
        [S(shape=(3, 3), low=-0.3, high=0.3)], check_bf16=False,
        check_grad=False),
    _sp("cov", lambda x: paddle.linalg.cov(x),
        lambda x: np.cov(x), [S(shape=(3, 8))], check_bf16=False),
    _sp("corrcoef", lambda x: paddle.linalg.corrcoef(x),
        lambda x: np.corrcoef(x), [S(shape=(3, 8))], check_bf16=False,
        check_grad=False),
    # ---- manipulation tail ------------------------------------------- #
    _sp("unstack", lambda x: paddle.unstack(x, axis=0)[0],
        lambda x: x[0], [S()]),
    _sp("tensor_split", lambda x: paddle.tensor_split(x, 2, axis=1)[0],
        lambda x: x[:, :2], [S()]),
    _sp("hsplit", lambda x: paddle.hsplit(x, 2)[1],
        lambda x: x[:, 2:], [S()]),
    _sp("vsplit", lambda x: paddle.vsplit(x, 3)[0],
        lambda x: x[:1], [S()]),
    _sp("hstack", lambda a, b: paddle.hstack([a, b]),
        lambda a, b: np.hstack([a, b]),
        [S(), S()]),
    _sp("vstack", lambda a, b: paddle.vstack([a, b]),
        lambda a, b: np.vstack([a, b]),
        [S(), S()]),
    _sp("dstack", lambda a, b: paddle.dstack([a, b]),
        lambda a, b: np.dstack([a, b]),
        [S(), S()]),
    _sp("atleast_1d", lambda x: paddle.atleast_1d(x),
        lambda x: np.atleast_1d(x), [S()]),
    _sp("unflatten", lambda x: paddle.unflatten(x, 1, [2, 2]),
        lambda x: x.reshape(3, 2, 2), [S()]),
    _sp("as_strided", lambda x: paddle.as_strided(x, [2, 2], [4, 1]),
        lambda x: np.lib.stride_tricks.as_strided(
            x, (2, 2), (x.strides[0], x.strides[1])), [S()],
        check_grad=False, check_jit=False),
    _sp("diagflat", paddle.diagflat,
        lambda x: np.diagflat(x), [S(shape=(4,))]),
    _sp("conj_real", paddle.conj, np.conj, [S()]),
    _sp("real", paddle.real, np.real, [S()], check_grad=False),
    _sp("take", lambda x: paddle.take(x, paddle.to_tensor(
        np.array([0, 3, 5], np.int64))),
        lambda x: x.reshape(-1)[[0, 3, 5]], [S()]),
    _sp("index_add",
        lambda x, v: paddle.index_add(
            x, paddle.to_tensor(np.array([0, 2], np.int64)), 0, v),
        lambda x, v: _index_add_np(x, v),
        [S(shape=(3, 4)), S(shape=(2, 4))]),
    _sp("crop", lambda x: paddle.crop(x, shape=[2, 2], offsets=[1, 1]),
        lambda x: x[1:3, 1:3], [S()]),
    _sp("strided_slice",
        lambda x: paddle.strided_slice(x, [0], [0], [3], [2]),
        lambda x: x[0:3:2], [S()]),
    _sp("multiplex",
        lambda a, b: paddle.multiplex(
            [a, b], paddle.to_tensor(np.array([[0], [1], [0]], np.int32))),
        lambda a, b: np.stack([a[0], b[1], a[2]]), [S(), S()]),
    # ---- dynamic-shape ops (eager only) ------------------------------ #
    _sp("unique", lambda x: paddle.unique(x)[0] if isinstance(
        paddle.unique(x), (tuple, list)) else paddle.unique(x),
        lambda x: np.unique(x), [INT8], check_grad=False,
        check_jit=False, check_bf16=False),
    _sp("nonzero", lambda x: paddle.nonzero(x),
        lambda x: np.stack(np.nonzero(x), axis=1), [INT8],
        check_grad=False, check_jit=False, check_bf16=False),
    _sp("unique_consecutive",
        lambda x: paddle.unique_consecutive(x)[0] if isinstance(
            paddle.unique_consecutive(x), (tuple, list))
        else paddle.unique_consecutive(x),
        lambda x: x[np.concatenate([[True], x[1:] != x[:-1]])],
        [S(shape=(8,), dtype="int", low=0, high=3)], check_grad=False,
        check_jit=False, check_bf16=False),
    _sp("bincount", lambda x: paddle.bincount(x, minlength=8),
        lambda x: np.bincount(x, minlength=8),
        [S(shape=(12,), dtype="int", low=0, high=8)], check_grad=False,
        check_bf16=False, check_jit=False),
    # ---- round-4 new ops --------------------------------------------- #
    _sp("reduce_as", lambda x: paddle.reduce_as(
        x, paddle.to_tensor(np.zeros((4,), np.float32))),
        lambda x: x.sum(0), [S()]),
    _sp("clip_by_norm", lambda x: paddle.clip_by_norm(x, 1.0),
        lambda x: x * min(1.0, 1.0 / np.linalg.norm(x)), [S()]),
    _sp("hinge_loss_c", paddle._C_ops.hinge_loss,
        lambda lg, lb: np.maximum(0.0, 1.0 - lb * lg),
        [NZ, S(low=0.5, high=1.5)]),
    _sp("affine_channel_c",
        lambda x, s, b: paddle._C_ops.affine_channel(x, s, b),
        lambda x, s, b: x * s.reshape(1, -1, 1) + b.reshape(1, -1, 1),
        [S(shape=(2, 3, 4)), S(shape=(3,)), S(shape=(3,))]),
    _sp("segment_sum",
        lambda x: paddle.geometric.segment_sum(x, paddle.to_tensor(
            np.array([0, 0, 1], np.int32))),
        lambda x: np.stack([x[0] + x[1], x[2]]), [S(shape=(3, 4))],
        check_jit=False),
    _sp("segment_mean",
        lambda x: paddle.geometric.segment_mean(x, paddle.to_tensor(
            np.array([0, 0, 1], np.int32))),
        lambda x: np.stack([(x[0] + x[1]) / 2, x[2]]), [S(shape=(3, 4))],
        check_jit=False),
    _sp("segment_max",
        lambda x: paddle.geometric.segment_max(x, paddle.to_tensor(
            np.array([0, 0, 1], np.int32))),
        lambda x: np.stack([np.maximum(x[0], x[1]), x[2]]),
        [S(shape=(3, 4))], check_jit=False),
    _sp("segment_min",
        lambda x: paddle.geometric.segment_min(x, paddle.to_tensor(
            np.array([0, 0, 1], np.int32))),
        lambda x: np.stack([np.minimum(x[0], x[1]), x[2]]),
        [S(shape=(3, 4))], check_jit=False),
    _sp("send_u_recv",
        lambda x: paddle.geometric.send_u_recv(
            x, paddle.to_tensor(np.array([0, 1, 2, 0], np.int32)),
            paddle.to_tensor(np.array([1, 2, 1, 0], np.int32)), "sum"),
        lambda x: np.stack([x[0], x[0] + x[2], x[1]]), [S(shape=(3, 4))],
        check_jit=False),
    _sp("send_uv",
        lambda x: paddle.geometric.send_uv(
            x, x, paddle.to_tensor(np.array([0, 1], np.int32)),
            paddle.to_tensor(np.array([1, 2], np.int32)), "mul"),
        lambda x: np.stack([x[0] * x[1], x[1] * x[2]]), [S(shape=(3, 4))]),
    _sp("softmax_mask_fuse",
        lambda x, m: paddle.incubate.softmax_mask_fuse(x, m * 100.0),
        lambda x, m: _softmax_np(x + m * 100.0),
        [S(shape=(1, 2, 3, 4)), S(shape=(1, 1, 3, 4), low=-1, high=0)]),
    _sp("softmax_mask_fuse_ut",
        paddle.incubate.softmax_mask_fuse_upper_triangle,
        lambda x: _softmax_np(np.where(
            np.tril(np.ones((4, 4), bool)), x, -np.inf)),
        [S(shape=(1, 2, 4, 4))]),
    _sp("lp_pool2d", lambda x: F.lp_pool2d(x, 2.0, 2, 2),
        lambda x: np.sqrt(
            (x ** 2).reshape(1, 1, 2, 2, 2, 2).transpose(
                0, 1, 2, 4, 3, 5).reshape(1, 1, 2, 2, 4).sum(-1)),
        [S(shape=(1, 1, 4, 4), low=0.2, high=2.0)]),
    _sp("weight_dequant_roundtrip",
        lambda x: paddle.nn.quant.weight_dequantize(
            *paddle.nn.quant.weight_quantize(x), out_dtype="float32"),
        lambda x: x, [S(shape=(8, 4))], rtol=2e-2, atol=2e-2,
        check_grad=False, check_jit=False, check_bf16=False),
    _sp("mean_all_c", paddle._C_ops.mean_all, np.mean, [S()]),
    _sp("complex_abs",
        lambda a, b: paddle.abs(paddle.complex(a, b)),
        lambda a, b: np.abs(a + 1j * b), [NZ, NZ], check_grad=False,
        check_bf16=False),
    _sp("tanh_shrink_c", paddle._C_ops.tanh_shrink,
        lambda x: x - np.tanh(x), [S()]),
    _sp("logsigmoid_c", paddle._C_ops.logsigmoid,
        lambda x: -np.log1p(np.exp(-x)), [S()]),
    _sp("box_clip_c",
        lambda b: paddle._C_ops.box_clip(
            b, paddle.to_tensor(np.array([10.0, 10.0], np.float32))),
        lambda b: np.clip(b, 0, 9), [S(shape=(3, 4), low=-2, high=12)],
        check_grad=False),
]


def _index_add_np(x, v):
    out = x.copy()
    out[0] += v[0]
    out[2] += v[1]
    return out

_IDS = [s.name for s in REGISTRY]
assert len(_IDS) == len(set(_IDS)), "duplicate registry ids"


@pytest.mark.parametrize("spec", REGISTRY, ids=_IDS)
def test_op_sweep(spec):
    run_all_checks(spec)


def test_registry_breadth():
    """The sweep must stay seeded across the Tensor-method surface."""
    assert len(REGISTRY) >= 250
    with_grad = [s for s in REGISTRY if s.check_grad]
    assert len(with_grad) >= 100


def test_harness_catches_planted_wrong_grad():
    """A deliberately wrong VJP must fail the finite-difference check."""
    import jax

    @jax.custom_vjp
    def bad_sin(x):
        return jnp.sin(x)

    bad_sin.defvjp(lambda x: (jnp.sin(x), x),
                   lambda x, g: (g * jnp.cos(x) * 1.5,))  # 1.5x too big

    spec = OpSpec("bad_sin", lambda x: bad_sin(x), np.sin)
    with pytest.raises(AssertionError):
        check_grad(spec)


def test_harness_catches_planted_wrong_forward():
    spec = OpSpec("bad_exp", lambda x: jnp.exp(x) * 1.01, np.exp)
    with pytest.raises(AssertionError):
        check_forward(spec, np.float32)
