"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the analog of the reference's
spawn-on-localhost fake cluster, test/legacy_test/test_parallel_dygraph_dataparallel.py:30)
so multi-chip sharding logic is exercised without TPU hardware. These env vars
must be set before jax is imported anywhere in the process.
"""

import os
import sys

# PADDLE_TPU_HW=1: run on the real TPU chip (hardware-validation sessions —
# tools/hw_session.sh). Default: virtual 8-device CPU mesh. Interpret-mode
# Pallas provably hides Mosaic layout bugs (round-2 finding), so kernel tests
# honor this flag too (see tests/test_pallas_kernels.py::_interpret_mode).
_ON_HW = os.environ.get("PADDLE_TPU_HW") == "1"

if not _ON_HW:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Numeric-parity oracle tests need full-precision GEMMs (the TPU bf16-pass
# default is a perf choice, not a correctness one) — same stance as the
# reference's FLAGS_cudnn_deterministic test mode.
import jax  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _flight_file_in_tmp(tmp_path, monkeypatch):
    """The flight recorder's default dump path is the cwd (production: the
    launcher points it at the worker log dir). Tests that legitimately
    crash a trainer (hold timeout, injected faults) must not litter the
    repo root — default every test's post-mortems into its tmp dir."""
    monkeypatch.setenv("PADDLE_FLIGHT_FILE",
                       str(tmp_path / "flight_recorder.json"))


@pytest.fixture
def fault_injector(monkeypatch):
    """Resilience fault harness (tools/fault_inject.py + distributed/faults):
    arm in-process fault points via env, corrupt/truncate checkpoint files.

        def test_x(fault_injector, tmp_path):
            fault_injector.arm("ckpt.before_commit", "exc")   # or kill/sleep
            fault_injector.corrupt(ckpt_dir)                  # flip bytes
            fault_injector.truncate(ckpt_dir, frac=0.3)
    """
    from paddle_tpu.distributed import faults
    from tools import fault_inject as fi

    class _Injector:
        def arm(self, point, action, arg=None, nth=None):
            spec = f"{point}:{action}" + (f":{arg}" if arg is not None else "")
            if nth is not None:
                spec += f"@{nth}"
            prev = os.environ.get("PADDLE_FAULT_INJECT", "")
            faults.reset()  # fresh @n counters even for an identical spec
            monkeypatch.setenv("PADDLE_FAULT_INJECT",
                               f"{prev},{spec}" if prev else spec)

        def disarm(self):
            monkeypatch.delenv("PADDLE_FAULT_INJECT", raising=False)
            faults.reset()

        corrupt = staticmethod(fi.corrupt_file)
        truncate = staticmethod(fi.truncate_file)

    return _Injector()


@pytest.fixture
def pallas_interpret_unless_hw(monkeypatch):
    """Interpret-mode Pallas hides Mosaic layout bugs (round-2 finding); under
    PADDLE_TPU_HW=1 (tools/hw_session.sh) kernels must compile on the real
    chip, so clear any leftover interpret var instead of setting it."""
    if _ON_HW:
        monkeypatch.delenv("PADDLE_TPU_PALLAS_INTERPRET", raising=False)
    else:
        monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")


jax.config.update("jax_default_matmul_precision", "highest")
# The environment's axon sitecustomize force-sets jax_platforms="axon,cpu"
# programmatically (overriding the env var). Re-override to cpu BEFORE any
# backend initializes so tests never touch the TPU tunnel — unless this is a
# hardware-validation session.
if not _ON_HW:
    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; slow marks the fault-injection tests that
    # fork full worker pods and wait out real watchdog deadlines
    config.addinivalue_line(
        "markers", "slow: multi-process fault-injection/recovery tests "
                   "excluded from tier-1 (`-m 'not slow'`)")


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Tier-1 runs under a hard wall-clock budget (ROADMAP 870 s timeout);
    print the session's total wall time so budget creep shows up in CI logs
    as a number, not as a surprise rc=124."""
    import time

    start = getattr(terminalreporter, "_sessionstarttime", None)
    if start is not None:
        terminalreporter.write_sep(
            "-", f"session wall time: {time.time() - start:.1f}s "
                 "(tier-1 budget: 870s)")


def pytest_collection_modifyitems(config, items):
    """PADDLE_TPU_HW=1 runs on the real chip, where the virtual 8-device CPU
    mesh is NOT configured — multi-device tests would all fail on a 1-chip
    host. Only the hardware-validation subsets (tools/hw_session.sh: Pallas
    kernels, masked flash, RNN scan) are meant for that flag; skip the rest
    instead of failing them."""
    if not _ON_HW:
        return
    n = len(jax.devices())
    if n >= 8:
        return
    hw_safe = {
        "test_pallas_kernels.py", "test_masked_flash.py", "test_rnn.py",
        "test_autotune.py", "test_fused_attention.py", "test_amp_conv.py",
    }
    skip = pytest.mark.skip(
        reason=f"PADDLE_TPU_HW=1 with {n} device(s): needs the 8-device "
               "virtual CPU mesh (run without the flag, or use the "
               "tools/hw_session.sh subsets)")
    for item in items:
        if item.fspath.basename not in hw_safe:
            item.add_marker(skip)
