"""API-tail subsystems: fft, distribution, vision zoo, paddle.static
(reference: python/paddle/fft.py, python/paddle/distribution/,
python/paddle/vision/models/vgg.py + mobilenetv*.py,
python/paddle/base/framework.py Program / executor.py Executor).
OpTest-style numpy parity per addition (test/legacy_test/op_test.py:418)."""

import numpy as np
import pytest

import jax

import paddle_tpu as paddle


# --------------------------------------------------------------------------- #
# fft
# --------------------------------------------------------------------------- #


class TestFFT:
    def test_fft_roundtrip_and_numpy_parity(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal(32).astype(np.float32)
        t = paddle.to_tensor(x)
        out = paddle.fft.fft(t)
        np.testing.assert_allclose(out.numpy(), np.fft.fft(x), rtol=1e-4,
                                   atol=1e-4)
        back = paddle.fft.ifft(out)
        np.testing.assert_allclose(back.numpy().real, x, rtol=1e-4, atol=1e-4)

    def test_rfft_irfft(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal(64).astype(np.float32)
        out = paddle.fft.rfft(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), np.fft.rfft(x), rtol=1e-4,
                                   atol=1e-4)
        back = paddle.fft.irfft(out, n=64)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-4, atol=1e-4)

    def test_fft2_and_shift(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((8, 8)).astype(np.float32)
        out = paddle.fft.fft2(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), np.fft.fft2(x), rtol=1e-4,
                                   atol=1e-4)
        sh = paddle.fft.fftshift(out)
        np.testing.assert_allclose(sh.numpy(), np.fft.fftshift(np.fft.fft2(x)),
                                   rtol=1e-4, atol=1e-4)

    def test_fftfreq(self):
        np.testing.assert_allclose(paddle.fft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, d=0.5))

    def test_rfft_grad_flows(self):
        x = paddle.to_tensor(np.ones(16, np.float32), stop_gradient=False)
        y = paddle.fft.rfft(x)
        loss = (y.real() ** 2).sum() if hasattr(y, "real") else None
        # abs() is the portable path
        loss = paddle.abs(y).sum()
        loss.backward()
        assert x.grad is not None and np.isfinite(x.grad.numpy()).all()


# --------------------------------------------------------------------------- #
# distribution
# --------------------------------------------------------------------------- #


class TestDistribution:
    def test_normal_log_prob_entropy_kl(self):
        from paddle_tpu.distribution import Normal, kl_divergence

        n1 = Normal(0.0, 1.0)
        n2 = Normal(1.0, 2.0)
        v = 0.5
        ref_lp = -0.5 * v * v - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(float(n1.log_prob(v).numpy()), ref_lp,
                                   rtol=1e-5)
        np.testing.assert_allclose(float(n1.entropy().numpy()),
                                   0.5 * np.log(2 * np.pi * np.e), rtol=1e-5)
        # closed-form KL(N(0,1) || N(1,2))
        ref_kl = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(float(kl_divergence(n1, n2).numpy()),
                                   ref_kl, rtol=1e-5)

    def test_normal_rsample_stats_and_grad(self):
        from paddle_tpu.distribution import Normal

        paddle.seed(7)
        loc = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        scale = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
        d = Normal(loc, scale)
        s = d.rsample((20000,))
        assert abs(float(s.numpy().mean()) - 2.0) < 0.02
        assert abs(float(s.numpy().std()) - 0.5) < 0.02
        # reparameterized: gradient flows to loc
        s.mean().backward()
        np.testing.assert_allclose(float(loc.grad.numpy()), 1.0, rtol=1e-5)

    def test_categorical_and_bernoulli(self):
        from paddle_tpu.distribution import Bernoulli, Categorical

        logits = paddle.to_tensor(np.log(np.array([0.2, 0.3, 0.5], np.float32)))
        c = Categorical(logits)
        np.testing.assert_allclose(float(c.log_prob(2).numpy()), np.log(0.5),
                                   rtol=1e-5)
        ent = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
        np.testing.assert_allclose(float(c.entropy().numpy()), ent, rtol=1e-5)
        paddle.seed(1)
        samp = c.sample((4000,)).numpy()
        assert abs((samp == 2).mean() - 0.5) < 0.05
        # log_prob over sampled values (sample dims + batch dims broadcast)
        lp = c.log_prob(c.sample((16,)))
        assert tuple(lp.shape) == (16,)
        cb = Categorical(paddle.to_tensor(np.zeros((4, 5), np.float32)))
        assert tuple(cb.log_prob(cb.sample((7,))).shape) == (7, 4)

        b = Bernoulli(0.25)
        np.testing.assert_allclose(float(b.log_prob(1.0).numpy()),
                                   np.log(0.25), rtol=1e-4)
        np.testing.assert_allclose(float(b.mean.numpy()), 0.25)

    def test_uniform(self):
        from paddle_tpu.distribution import Uniform

        u = Uniform(1.0, 3.0)
        np.testing.assert_allclose(float(u.log_prob(2.0).numpy()),
                                   -np.log(2.0), rtol=1e-5)
        assert float(u.log_prob(5.0).numpy()) == -np.inf
        paddle.seed(2)
        s = u.sample((5000,)).numpy()
        assert s.min() >= 1.0 and s.max() < 3.0
        assert abs(s.mean() - 2.0) < 0.05


# --------------------------------------------------------------------------- #
# vision zoo
# --------------------------------------------------------------------------- #


class TestVisionZoo:
    @pytest.mark.parametrize("ctor,kw", [
        ("vgg11", {}),
        ("vgg16", {"batch_norm": True}),
        ("mobilenet_v1", {"scale": 0.25}),
        ("mobilenet_v2", {"scale": 0.25}),
        ("mobilenet_v3_small", {"scale": 0.5}),
        ("mobilenet_v3_large", {"scale": 0.35}),
    ])
    def test_forward_shapes(self, ctor, kw):
        from paddle_tpu.vision import models

        paddle.seed(0)
        m = getattr(models, ctor)(num_classes=10, **kw)
        m.eval()
        x = paddle.to_tensor(
            np.random.default_rng(0).standard_normal((2, 3, 64, 64))
            .astype(np.float32))
        out = m(x)
        assert tuple(out.shape) == (2, 10)
        assert np.isfinite(out.numpy()).all()

    @pytest.mark.parametrize("ctor,kw,hw", [
        ("alexnet", {}, 224),
        ("squeezenet1_1", {}, 64),
        ("densenet121", {}, 64),
        ("shufflenet_v2_x0_25", {}, 64),
        ("shufflenet_v2_swish", {}, 64),
        ("inception_v3", {}, 299),
    ])
    def test_new_zoo_forward_shapes(self, ctor, kw, hw):
        from paddle_tpu.vision import models

        paddle.seed(0)
        m = getattr(models, ctor)(num_classes=10, **kw)
        m.eval()
        x = paddle.to_tensor(
            np.random.default_rng(0).standard_normal((2, 3, hw, hw))
            .astype(np.float32))
        out = m(x)
        assert tuple(out.shape) == (2, 10)
        assert np.isfinite(out.numpy()).all()

    def test_googlenet_aux_heads(self):
        from paddle_tpu.vision.models import googlenet

        paddle.seed(0)
        m = googlenet(num_classes=7)
        m.eval()
        x = paddle.to_tensor(
            np.random.default_rng(0).standard_normal((2, 3, 64, 64))
            .astype(np.float32))
        out, aux1, aux2 = m(x)
        for o in (out, aux1, aux2):
            assert tuple(o.shape) == (2, 7)
            assert np.isfinite(o.numpy()).all()

    def test_new_zoo_train_step(self):
        from paddle_tpu.vision.models import densenet121, shufflenet_v2_x0_25
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        for build in (densenet121, shufflenet_v2_x0_25):
            paddle.seed(0)
            m = build(num_classes=4)
            m.train()
            ce = nn.CrossEntropyLoss()
            o = opt.Adam(learning_rate=1e-3, parameters=m.parameters())
            x = paddle.to_tensor(
                np.random.default_rng(1).standard_normal((4, 3, 32, 32))
                .astype(np.float32))
            y = paddle.to_tensor(np.array([0, 1, 2, 3]))
            losses = []
            for _ in range(8):
                loss = ce(m(x), y)
                loss.backward()
                o.step()
                o.clear_grad()
                losses.append(float(loss.numpy()))
            assert losses[-1] < losses[0], build.__name__

    def test_mobilenet_trains(self):
        from paddle_tpu.vision.models import mobilenet_v2
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        paddle.seed(0)
        m = mobilenet_v2(scale=0.25, num_classes=4)
        m.train()
        ce = nn.CrossEntropyLoss()
        o = opt.SGD(learning_rate=0.01, parameters=m.parameters())
        x = paddle.to_tensor(
            np.random.default_rng(1).standard_normal((4, 3, 32, 32))
            .astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 2, 3]))
        losses = []
        for _ in range(8):
            loss = ce(m(x), y)
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


# --------------------------------------------------------------------------- #
# paddle.static
# --------------------------------------------------------------------------- #


class TestStatic:
    def test_program_build_and_run(self):
        import paddle_tpu.static as static

        paddle.seed(0)
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 8], "float32")
            h = static.nn.fc(x, 16, activation="relu")
            y = static.nn.fc(h, 4)
            loss = paddle.mean(y * y)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.default_rng(0)
        feed_x = rng.standard_normal((6, 8)).astype(np.float32)
        out, lval = exe.run(main, feed={"x": feed_x},
                            fetch_list=[y, loss])
        assert out.shape == (6, 4)
        assert np.isfinite(lval).all()
        # replay matches an eager recomputation through the same params
        w1, b1 = main._holders[0].weight, main._holders[0].bias
        w2, b2 = main._holders[1].weight, main._holders[1].bias
        ref_h = np.maximum(feed_x @ w1.numpy() + b1.numpy(), 0)
        ref_y = ref_h @ w2.numpy() + b2.numpy()
        np.testing.assert_allclose(out, ref_y, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(lval, (ref_y * ref_y).mean(), rtol=1e-4)

    def test_executor_sees_param_updates(self):
        """Replay reads live parameter values — mutating a param between
        runs changes the fetched result (the reference's scope semantics)."""
        import paddle_tpu.static as static

        paddle.seed(1)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 4], "float32")
            y = static.nn.fc(x, 3)
        exe = static.Executor()
        feed = np.ones((2, 4), np.float32)
        (a,) = exe.run(main, feed={"x": feed}, fetch_list=[y])
        layer = main._holders[0]
        layer.weight.set_value(np.zeros_like(layer.weight.numpy()))
        (b,) = exe.run(main, feed={"x": feed}, fetch_list=[y])
        assert not np.allclose(a, b)
        np.testing.assert_allclose(b, np.broadcast_to(layer.bias.numpy(), b.shape),
                                   atol=1e-6)

    def test_variable_batch_dim(self):
        """None dims capture as 1 but replay binds the real fed shape."""
        import paddle_tpu.static as static

        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4], "float32")
            y = paddle.sum(x * 2.0, axis=1)
        exe = static.Executor()
        for bs in (3, 7):
            arr = np.ones((bs, 4), np.float32)
            (out,) = exe.run(main, feed={"x": arr}, fetch_list=[y])
            np.testing.assert_allclose(out, np.full(bs, 8.0))

    def test_enable_static_records_default_program(self):
        import paddle_tpu.static as static

        paddle.enable_static()
        try:
            x = static.data("xs", [2, 2], "float32")
            y = x + 1.0
            exe = static.Executor()
            (out,) = exe.run(static.default_main_program(),
                             feed={"xs": np.zeros((2, 2), np.float32)},
                             fetch_list=[y])
            np.testing.assert_allclose(out, 1.0)
        finally:
            paddle.disable_static()


class TestSignal:
    def test_stft_istft_roundtrip(self):
        """reference signal.py stft/istft: hann-window roundtrip recovers
        the waveform (COLA)."""
        from paddle_tpu.audio.functional import get_window

        sr = 4096  # hop-divisible so no trailing partial frame drops
        t = np.arange(sr) / sr
        sig = np.sin(2 * np.pi * 440 * t).astype(np.float32)
        n_fft, hop = 256, 64
        w = get_window("hann", n_fft)
        spec = paddle.signal.stft(paddle.to_tensor(sig), n_fft,
                                  hop_length=hop, window=w)
        assert spec.shape[0] == n_fft // 2 + 1
        back = paddle.signal.istft(spec, n_fft, hop_length=hop, window=w,
                                   length=len(sig))
        np.testing.assert_allclose(back.numpy(), sig, atol=1e-4)

    def test_stft_numpy_parity(self):
        rng = np.random.default_rng(0)
        sig = rng.standard_normal(512).astype(np.float32)
        n_fft, hop = 128, 32
        spec = paddle.signal.stft(paddle.to_tensor(sig), n_fft,
                                  hop_length=hop, center=False).numpy()
        n = (len(sig) - n_fft) // hop + 1
        frames = np.stack([sig[i * hop:i * hop + n_fft] for i in range(n)])
        ref = np.fft.rfft(frames, axis=-1).T
        np.testing.assert_allclose(spec, ref, rtol=1e-4, atol=1e-4)

    def test_frame_overlap_add_inverse(self):
        x = paddle.to_tensor(np.arange(32, dtype=np.float32))
        fr = paddle.signal.frame(x, 8, 8)  # non-overlapping
        assert tuple(fr.shape) == (8, 4)
        back = paddle.signal.overlap_add(fr, 8)
        np.testing.assert_allclose(back.numpy(), x.numpy())


class TestFlops:
    def test_flops_counts_linear_chain(self):
        """paddle.flops (reference hapi/dynamic_flops.py): per-layer hook
        counting on a zeros forward."""
        import paddle_tpu.nn as nn

        m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        total = paddle.flops(m, [2, 16])
        assert total == 2 * 2 * 16 * 32 + 2 * 2 * 32 * 4 + 2 * 32

    def test_flops_conv_model(self):
        from paddle_tpu.vision.models import LeNet

        total = paddle.flops(LeNet(), [1, 1, 28, 28])
        assert total > 1e5


class TestLinalgTail:
    def test_norms_and_cond(self):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(4, 5)).astype(np.float32)
        t = paddle.to_tensor(a)
        np.testing.assert_allclose(
            float(paddle.linalg.vector_norm(t, 2).numpy()),
            np.linalg.norm(a.ravel()), rtol=1e-5)
        np.testing.assert_allclose(
            float(paddle.linalg.vector_norm(t, float("inf")).numpy()),
            np.abs(a).max(), rtol=1e-6)
        np.testing.assert_allclose(
            float(paddle.linalg.matrix_norm(t, "fro").numpy()),
            np.linalg.norm(a, "fro"), rtol=1e-5)
        np.testing.assert_allclose(
            float(paddle.linalg.matrix_norm(t, "nuc").numpy()),
            np.linalg.norm(a, "nuc"), rtol=1e-4)
        sq = paddle.to_tensor(a[:4, :4] + 4 * np.eye(4, dtype=np.float32))
        np.testing.assert_allclose(
            float(paddle.linalg.cond(sq).numpy()),
            np.linalg.cond(np.asarray(sq.numpy())), rtol=1e-4)

    def test_matrix_exp_and_vecdot(self):
        from scipy.linalg import expm

        rng = np.random.default_rng(1)
        a = (rng.normal(size=(3, 3)) * 0.3).astype(np.float32)
        np.testing.assert_allclose(
            paddle.linalg.matrix_exp(paddle.to_tensor(a)).numpy(),
            expm(a), rtol=1e-4, atol=1e-5)
        x = rng.normal(size=(2, 5)).astype(np.float32)
        y = rng.normal(size=(2, 5)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.linalg.vecdot(paddle.to_tensor(x),
                                 paddle.to_tensor(y)).numpy(),
            (x * y).sum(-1), rtol=1e-5)

    def test_householder_product_and_ormqr(self):
        rng = np.random.default_rng(2)
        a = rng.normal(size=(5, 3)).astype(np.float32)
        # LAPACK geqrf output via scipy: reflectors in qr_mat's lower part
        from scipy.linalg import qr as _sqr

        (qr_mat, tau), _r = _sqr(a, mode="raw")
        q_econ = _sqr(a, mode="economic")[0]
        got = paddle.linalg.householder_product(
            paddle.to_tensor(np.asarray(qr_mat, np.float32)),
            paddle.to_tensor(np.asarray(tau, np.float32))).numpy()
        np.testing.assert_allclose(got, q_econ, rtol=1e-4, atol=1e-4)
        # ormqr applies the FULL Q to other [m, k]
        other = rng.normal(size=(5, 2)).astype(np.float32)
        om = paddle.linalg.ormqr(
            paddle.to_tensor(np.asarray(qr_mat, np.float32)),
            paddle.to_tensor(np.asarray(tau, np.float32)),
            paddle.to_tensor(other)).numpy()
        q_full = _sqr(a, mode="full")[0]
        np.testing.assert_allclose(om, q_full @ other, rtol=1e-4, atol=1e-4)

    def test_lowrank(self):
        rng = np.random.default_rng(3)
        # rank-2 matrix + tiny noise: lowrank svd recovers it
        u = rng.normal(size=(20, 2)).astype(np.float32)
        v = rng.normal(size=(2, 15)).astype(np.float32)
        a = u @ v
        paddle.seed(0)
        U, s, V = paddle.linalg.svd_lowrank(paddle.to_tensor(a), q=4)
        rec = (U.numpy() * s.numpy()) @ V.numpy().T
        np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-3)
        U2, s2, V2 = paddle.linalg.pca_lowrank(paddle.to_tensor(a), q=3)
        assert s2.shape[-1] == 3

    def test_linalg_aliases(self):
        assert paddle.linalg.matrix_transpose is not None
        assert paddle.linalg.multi_dot is not None
        assert paddle.linalg.lu_unpack is not None

    def test_cond_orders_and_matrix_norm_axes(self):
        rng = np.random.default_rng(5)
        a = (rng.normal(size=(3, 3)) + 3 * np.eye(3)).astype(np.float32)
        t = paddle.to_tensor(a)
        for p in (1, np.inf, "fro", None):
            np.testing.assert_allclose(
                float(paddle.linalg.cond(t, p).numpy()),
                np.linalg.cond(a, p if p is not None else 2), rtol=1e-4)
        x = rng.normal(size=(2, 3, 4)).astype(np.float32)
        got = paddle.linalg.matrix_norm(paddle.to_tensor(x), p=1,
                                        axis=(0, 1)).numpy()
        ref = np.abs(x).sum(0).max(0)
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_householder_partial_tau(self):
        rng = np.random.default_rng(6)
        a = rng.normal(size=(6, 4)).astype(np.float32)
        from scipy.linalg import qr as _sqr

        (qr_mat, tau), _ = _sqr(a, mode="raw")
        # only k=2 reflectors: Q accumulates H_0 H_1 only
        got = paddle.linalg.householder_product(
            paddle.to_tensor(np.asarray(qr_mat, np.float32)),
            paddle.to_tensor(np.asarray(tau[:2], np.float32))).numpy()
        ident = np.eye(6, dtype=np.float64)
        q_ref = ident.copy()
        for i in range(2):
            v = np.zeros(6)
            v[i] = 1.0
            v[i + 1:] = qr_mat[i + 1:, i]
            q_ref = q_ref @ (ident - tau[i] * np.outer(v, v))
        np.testing.assert_allclose(got, q_ref[:, :4], rtol=1e-4, atol=1e-4)


class TestIncubateOptimizers:
    def test_lookahead_converges_and_interpolates(self):
        paddle.seed(0)
        w = paddle.to_tensor(np.asarray([4.0], np.float32),
                             stop_gradient=False)
        inner = paddle.optimizer.SGD(learning_rate=0.2, parameters=[w])
        opt = paddle.incubate.LookAhead(inner, alpha=0.5, k=3)
        vals = []
        for _ in range(12):
            ((w ** 2).sum()).backward()
            opt.step()
            opt.clear_grad()
            vals.append(float(w.numpy()[0]))
        assert abs(vals[-1]) < abs(vals[0])
        # after a sync step (k=3), w jumped toward the slow weights —
        # the value after step 3 is NOT the pure-SGD trajectory value
        pure = 4.0 * (0.6 ** 3)
        assert abs(vals[2] - pure) > 1e-4
        with pytest.raises(ValueError):
            paddle.incubate.LookAhead(inner, alpha=2.0)

    def test_model_average_apply_restore(self):
        paddle.seed(0)
        v = paddle.to_tensor(np.asarray([0.0], np.float32),
                             stop_gradient=False)
        ma = paddle.incubate.ModelAverage(0.5, parameters=[v],
                                          min_average_window=10,
                                          max_average_window=50)
        for x in (1.0, 2.0, 3.0):
            v.set_value(np.asarray([x], np.float32))
            ma.step()
        with ma.apply():
            np.testing.assert_allclose(v.numpy(), [2.0], rtol=1e-6)
        np.testing.assert_allclose(v.numpy(), [3.0], rtol=1e-6)  # restored
        # rate-scaled window: a tiny min window restarts the accumulation
        w2 = paddle.to_tensor(np.asarray([0.0], np.float32),
                              stop_gradient=False)
        ma2 = paddle.incubate.ModelAverage(0.5, parameters=[w2],
                                           min_average_window=2,
                                           max_average_window=50)
        for x in (1.0, 2.0, 3.0):
            w2.set_value(np.asarray([x], np.float32))
            ma2.step()
        with ma2.apply():
            np.testing.assert_allclose(w2.numpy(), [3.0], rtol=1e-6)
