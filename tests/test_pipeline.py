"""Pipeline-parallelism tests.

Model: the reference validates pp by numeric parity between the 1F1B
multi-process run and a single-process run
(test/collective/fleet/hybrid_parallel_pp_*.py); here the compiled
collective-permute pipeline (paddle_tpu.parallel.pipeline) is checked against
sequential execution on the 8-device CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.parallel.pipeline import (
    microbatch,
    pack_chunked,
    pipeline_1f1b,
    pipeline_interleaved,
    pipeline_spmd,
    stack_pytrees,
    unmicrobatch,
    unstack_leading,
)

AXES = ("dp", "pp", "sharding", "sep", "mp")


def _pp_mesh(S):
    return Mesh(np.array(jax.devices()[:S]).reshape(1, S, 1, 1, 1), AXES)


class TestPipelineSpmd:
    def test_forward_parity(self):
        S, M, mb, H = 4, 8, 2, 16
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(0)
        Ws = jnp.asarray(rng.normal(size=(S, H, H)), jnp.float32) * 0.3
        x = jnp.asarray(rng.normal(size=(M * mb, H)), jnp.float32)

        def stage_fn(W, inp):
            h, tag = inp
            return (jnp.tanh(h @ W), tag)

        tags = jnp.arange(M * mb, dtype=jnp.int32)
        out, otags = unmicrobatch(
            pipeline_spmd(stage_fn, Ws, microbatch((x, tags), M), mesh=mesh)
        )
        ref = x
        for i in range(S):
            ref = jnp.tanh(ref @ Ws[i])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        # constants ride the pipeline unchanged and in order
        np.testing.assert_array_equal(np.asarray(otags), np.asarray(tags))

    def test_grad_parity(self):
        S, M, mb, H = 2, 4, 2, 8
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(1)
        Ws = jnp.asarray(rng.normal(size=(S, H, H)), jnp.float32) * 0.3
        x = jnp.asarray(rng.normal(size=(M * mb, H)), jnp.float32)
        xmb = microbatch((x,), M)

        def stage_fn(W, inp):
            (h,) = inp
            return (jnp.tanh(h @ W),)

        def loss_pipe(Ws):
            (o,) = pipeline_spmd(stage_fn, Ws, xmb, mesh=mesh)
            return (o ** 2).sum()

        def loss_ref(Ws):
            h = x
            for i in range(S):
                h = jnp.tanh(h @ Ws[i])
            return (h ** 2).sum()

        g1 = jax.jit(jax.grad(loss_pipe))(Ws)
        g2 = jax.grad(loss_ref)(Ws)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)

    def test_1f1b_loss_and_grad_parity(self):
        """1F1B computes the same loss and grads (stage params, loss params,
        inputs) as plain autodiff of the sequential chain — including int
        riders flowing through the pipeline untouched (reference parity
        test: hybrid_parallel_pp_1f1b)."""
        S, M, mb, H = 4, 8, 2, 16
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(2)
        Ws = jnp.asarray(rng.normal(size=(S, H, H)), jnp.float32) * 0.4
        Wl = jnp.asarray(rng.normal(size=(H, 1)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(M, mb, H)), jnp.float32)
        tags = jnp.arange(M * mb, dtype=jnp.int32).reshape(M, mb)

        def stage_fn(W, inp):
            h, tag = inp
            return (jnp.tanh(h @ W), tag)

        def loss_fn(lp, out):
            h, tag = out
            # rider participates (non-differentiably) so mis-sequencing shows
            return jnp.mean((h @ lp) ** 2 * (1.0 + 0.01 * tag[:, None]))

        def loss_pipe(Ws, Wl, x):
            return pipeline_1f1b(stage_fn, loss_fn, Ws, Wl, (x, tags),
                                 mesh=mesh)

        def loss_ref(Ws, Wl, x):
            total = 0.0
            for m in range(M):
                h = x[m]
                for i in range(S):
                    h = jnp.tanh(h @ Ws[i])
                total = total + loss_fn(Wl, (h, tags[m])) / M
            return total

        l1 = loss_pipe(Ws, Wl, x)
        l2 = loss_ref(Ws, Wl, x)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)

        g1 = jax.jit(jax.grad(loss_pipe, (0, 1, 2)))(Ws, Wl, x)
        g2 = jax.grad(loss_ref, (0, 1, 2))(Ws, Wl, x)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_1f1b_degenerate_single_stage(self):
        mesh = _pp_mesh(1)
        rng = np.random.default_rng(3)
        Ws = jnp.asarray(rng.normal(size=(1, 8, 8)), jnp.float32) * 0.4
        Wl = jnp.asarray(rng.normal(size=(8, 1)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(4, 2, 8)), jnp.float32)

        def stage_fn(W, inp):
            return (jnp.tanh(inp[0] @ W),)

        def loss_fn(lp, out):
            return jnp.mean((out[0] @ lp) ** 2)

        l = pipeline_1f1b(stage_fn, loss_fn, Ws, Wl, (x,), mesh=mesh)
        ref = jnp.mean(jnp.stack([
            loss_fn(Wl, (jnp.tanh(x[m] @ Ws[0]),)) for m in range(4)]))
        np.testing.assert_allclose(float(l), float(ref), rtol=1e-5)

    def test_1f1b_peak_memory_below_gpipe(self):
        """The 1F1B ring buffer (W = 2S-1 stage inputs) must beat the
        autodiff'd GPipe scan's T = M + S - 1 stashed residuals (reference
        claim: pipeline_parallel.py 1F1B memory motivation)."""
        S, M, mb, H = 4, 32, 4, 256
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(4)
        Ws = jnp.asarray(rng.normal(size=(S, H, H)), jnp.float32) * 0.3
        Wl = jnp.asarray(rng.normal(size=(H, 1)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(M, mb, H)), jnp.float32)

        def stage_fn(W, inp):
            return (jnp.tanh(inp[0] @ W),)

        def loss_fn(lp, out):
            return jnp.mean((out[0] @ lp) ** 2)

        def loss_1f1b(Ws, Wl, x):
            return pipeline_1f1b(stage_fn, loss_fn, Ws, Wl, (x,), mesh=mesh)

        def loss_gpipe(Ws, Wl, x):
            (o,) = pipeline_spmd(stage_fn, Ws, (x,), mesh=mesh)
            return jnp.mean(
                jnp.stack([loss_fn(Wl, (o[m],)) for m in range(M)]))

        def peak(f):
            c = jax.jit(jax.grad(f, (0, 1, 2))).lower(Ws, Wl, x).compile()
            ma = c.memory_analysis()
            return ma.temp_size_in_bytes

        p_1f1b, p_gpipe = peak(loss_1f1b), peak(loss_gpipe)
        assert p_1f1b < p_gpipe, (p_1f1b, p_gpipe)

    def test_interleaved_forward_and_grad_parity(self):
        """VPP circular schedule == sequential chain of S*V virtual stages
        (reference interleaved 1F1B parity, hybrid_parallel_pp_vpp)."""
        S, V, M, mb, H = 2, 3, 4, 2, 16
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(5)
        Ws = jnp.asarray(rng.normal(size=(S * V, H, H)), jnp.float32) * 0.4
        x = jnp.asarray(rng.normal(size=(M, mb, H)), jnp.float32)

        def stage_fn(W, inp):
            (h,) = inp
            return (jnp.tanh(h @ W),)

        def run_vpp(Ws, x):
            (o,) = pipeline_interleaved(
                stage_fn, pack_chunked(Ws, S, V), (x,), mesh=mesh,
                num_chunks=V)
            return o

        def run_ref(Ws, x):
            h = x
            for u in range(S * V):
                h = jnp.tanh(h @ Ws[u])
            return h

        np.testing.assert_allclose(
            np.asarray(run_vpp(Ws, x)), np.asarray(run_ref(Ws, x)),
            atol=1e-5)

        g1 = jax.jit(jax.grad(lambda W: (run_vpp(W, x) ** 2).sum()))(Ws)
        g2 = jax.grad(lambda W: (run_ref(W, x) ** 2).sum())(Ws)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-4)

    def test_stack_unstack_roundtrip(self):
        trees = [{"w": jnp.ones((2,)) * i} for i in range(3)]
        stacked = stack_pytrees(trees)
        assert stacked["w"].shape == (3, 2)
        back = unstack_leading(stacked, 3)
        np.testing.assert_allclose(np.asarray(back[2]["w"]), 2.0)


class TestGPTPipe:
    def _models(self, num_layers=4):
        from paddle_tpu.models import gpt3_tiny, GPTForCausalLMPipe

        paddle.seed(0)
        cfg = gpt3_tiny()
        cfg.num_layers = num_layers
        return cfg, GPTForCausalLMPipe(cfg, num_microbatches=2)

    def test_scan_vs_pipeline_exact(self):
        cfg, pipe = self._models()
        pipe.eval()
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16))
        )
        dist.env.build_mesh(dp=1, devices=jax.devices()[:1])
        out_scan = pipe(ids).numpy()
        dist.env.build_mesh(pp=4, devices=jax.devices()[:4])
        out_pipe = pipe(ids).numpy()
        dist.env.set_global_mesh(None)
        np.testing.assert_allclose(out_scan, out_pipe, atol=1e-4)

    def test_layered_state_dict_parity(self):
        from paddle_tpu.models import GPTForCausalLM, stack_layered_state_dict

        cfg, pipe = self._models()
        layered = GPTForCausalLM(cfg)
        layered.eval()
        pipe.eval()
        pipe.set_state_dict(stack_layered_state_dict(layered.state_dict(), cfg.num_layers))
        dist.env.set_global_mesh(None)
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16))
        )
        np.testing.assert_allclose(
            layered(ids).numpy(), pipe(ids).numpy(), atol=1e-4
        )

    def test_vpp_forward_matches_scan(self):
        from paddle_tpu.models import gpt3_tiny, GPTForCausalLMPipe

        paddle.seed(0)
        cfg = gpt3_tiny()
        cfg.num_layers = 4
        pipe = GPTForCausalLMPipe(cfg, num_microbatches=4,
                                  pp_schedule="vpp", vpp_degree=2)
        pipe.eval()
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16)))
        dist.env.build_mesh(dp=1, devices=jax.devices()[:1])
        out_scan = pipe(ids).numpy()
        dist.env.build_mesh(pp=2, devices=jax.devices()[:2])
        out_vpp = pipe(ids).numpy()
        dist.env.set_global_mesh(None)
        np.testing.assert_allclose(out_scan, out_vpp, atol=1e-4)

    def test_1f1b_train_step_matches_gpipe(self):
        """Same init, same data: the 1F1B train step must follow the same
        loss trajectory as the GPipe-autodiff step (reference parity between
        schedule_mode settings, hybrid_parallel_pp_1f1b)."""
        from paddle_tpu.models import (
            GPTForCausalLMPipe, GPTPretrainingCriterion, gpt3_tiny)
        import paddle_tpu.optimizer as opt

        def run(schedule):
            paddle.seed(0)
            cfg = gpt3_tiny()
            cfg.num_layers = 4
            cfg.hidden_dropout_prob = 0.0
            cfg.attention_dropout_prob = 0.0
            pipe = GPTForCausalLMPipe(cfg, num_microbatches=2,
                                      pp_schedule=schedule)
            crit = GPTPretrainingCriterion(cfg)
            pipe.train()
            mesh = dist.build_mesh(pp=2)
            optimizer = opt.AdamW(learning_rate=1e-3,
                                  parameters=pipe.parameters())
            step = dist.DistributedTrainStep(
                pipe, lambda lg, lb: crit(lg, lb), optimizer, mesh=mesh)
            rng = np.random.default_rng(7)
            ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)))
            labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)))
            losses = [float(step(ids, labels)) for _ in range(4)]
            dist.env.set_global_mesh(None)
            return losses

        l_gpipe = run("gpipe")
        l_1f1b = run("1f1b")
        np.testing.assert_allclose(l_1f1b, l_gpipe, rtol=2e-3, atol=2e-4)
        assert l_1f1b[-1] < l_1f1b[0]

    def test_1f1b_full_hybrid_mesh(self):
        """1F1B under pp x sharding x mp with sequence parallel — the combo
        that exposed the cond-wrapped-collective rendezvous deadlock (auto-
        axis collectives inside pp-divergent control flow). Must train."""
        from paddle_tpu.models import (
            GPTForCausalLMPipe, GPTPretrainingCriterion, gpt3_tiny)
        import paddle_tpu.optimizer as opt

        paddle.seed(0)
        cfg = gpt3_tiny(sequence_parallel=True)
        cfg.num_layers = 4
        mesh = dist.build_mesh(pp=2, sharding=2, mp=2)
        pipe = GPTForCausalLMPipe(cfg, num_microbatches=2, pp_schedule="1f1b")
        crit = GPTPretrainingCriterion(cfg)
        pipe.train()
        step = dist.DistributedTrainStep(
            pipe, lambda lg, lb: crit(lg, lb),
            opt.AdamW(learning_rate=1e-4, parameters=pipe.parameters()),
            mesh=mesh, sharding_stage=1)
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)))
        labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)))
        losses = [float(step(ids, labels)) for _ in range(3)]
        dist.env.set_global_mesh(None)
        assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses

    def test_vpp_hybrid_mesh_forward(self):
        """VPP with mp sharing the mesh (the cond-removal covers this
        schedule too): forward parity against the single-device scan."""
        from paddle_tpu.models import gpt3_tiny, GPTForCausalLMPipe

        paddle.seed(0)
        cfg = gpt3_tiny(sequence_parallel=False)
        cfg.num_layers = 4
        pipe = GPTForCausalLMPipe(cfg, num_microbatches=4,
                                  pp_schedule="vpp", vpp_degree=2)
        pipe.eval()
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16)))
        dist.env.build_mesh(dp=1, devices=jax.devices()[:1])
        ref = pipe(ids).numpy()
        dist.env.build_mesh(pp=2, mp=2)
        out = pipe(ids).numpy()
        dist.env.set_global_mesh(None)
        np.testing.assert_allclose(ref, out, atol=1e-4)

    def test_hybrid_train_step_dp_pp_mp(self):
        from paddle_tpu.models import GPTPretrainingCriterion
        import paddle_tpu.optimizer as opt

        cfg, pipe = self._models()
        crit = GPTPretrainingCriterion(cfg)
        pipe.train()
        mesh = dist.build_mesh(dp=2, pp=2, mp=2)
        optimizer = opt.AdamW(learning_rate=1e-3, parameters=pipe.parameters())
        step = dist.DistributedTrainStep(
            pipe, lambda lg, lb: crit(lg, lb), optimizer, mesh=mesh
        )
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)))
        labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)))
        losses = [float(step(ids, labels)) for _ in range(5)]
        dist.env.set_global_mesh(None)
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


class TestPipelineLayerWrapper:
    def test_pipeline_layer_partition_and_train_batch(self):
        """Eager PipelineLayer/PipelineParallel wrapper parity (reference
        hybrid_parallel_pp_layer.py API)."""
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (
            LayerDesc,
            PipelineLayer,
        )

        paddle.seed(0)
        descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(4)]
        pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.MSELoss())
        assert pl.get_num_stages() == 2
        assert len(pl.get_stage_layers(0)) == 2
        x = paddle.to_tensor(np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32))
        out = pl(x)
        assert tuple(out.shape) == (4, 8)

    def test_train_batch_compiled_1f1b_route(self):
        """With schedule_mode 1F1B and uniform stages, train_batch must run
        the compiled pipeline (reference: PipelineParallel selects 1F1B in
        fleet/model.py:160-185) and match the sequential loop numerically."""
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        from paddle_tpu.distributed.fleet.base.distributed_strategy import (
            DistributedStrategy,
        )
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
            PipelineParallel,
        )
        from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (
            LayerDesc,
            PipelineLayer,
        )

        rng = np.random.default_rng(1)
        x = np.asarray(rng.normal(size=(8, 16)), np.float32)
        y = np.asarray(rng.normal(size=(8, 16)), np.float32)

        def run(schedule):
            paddle.seed(0)
            descs = [LayerDesc(nn.Linear, 16, 16) for _ in range(4)]
            pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.MSELoss())
            strat = DistributedStrategy()
            strat.hybrid_configs = {
                "pp_configs": {"micro_batch_size": 2,
                               "schedule_mode": schedule},
            }
            pp = PipelineParallel(pl, None, strat)
            optimizer = opt.SGD(learning_rate=0.05,
                                parameters=pl.parameters())
            losses = [
                float(pp.train_batch(
                    (paddle.to_tensor(x), paddle.to_tensor(y)),
                    optimizer).numpy())
                for _ in range(3)
            ]
            return pp, losses

        dist.env.build_mesh(pp=2, devices=jax.devices()[:2])
        pp1, l_1f1b = run("1F1B")
        assert pp1._compiled_state == 1, "compiled 1F1B path not engaged"
        pp2, l_seq = run("FThenB")
        assert pp2._compiled_state == 0, "FThenB must not build compiled path"
        dist.env.set_global_mesh(None)
        np.testing.assert_allclose(l_1f1b, l_seq, rtol=1e-4, atol=1e-5)
        assert l_1f1b[-1] < l_1f1b[0]

    def test_compiled_route_rejects_nonuniform_stages(self):
        """Stages with identical param shapes but different construction
        must fall back to the eager loop (stage-0's layer objects would
        otherwise execute every stage's weights)."""
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        from paddle_tpu.distributed.fleet.base.distributed_strategy import (
            DistributedStrategy,
        )
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
            PipelineParallel,
        )
        from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (
            LayerDesc,
            PipelineLayer,
        )

        paddle.seed(0)
        descs = [
            LayerDesc(nn.Linear, 16, 16),
            LayerDesc(nn.Linear, 16, 16),
            LayerDesc(nn.Linear, 16, 16, bias_attr=False),  # differs
            LayerDesc(nn.Linear, 16, 16, bias_attr=False),
        ]
        pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.MSELoss())
        strat = DistributedStrategy()
        strat.hybrid_configs = {
            "pp_configs": {"micro_batch_size": 2, "schedule_mode": "1F1B"},
        }
        dist.env.build_mesh(pp=2, devices=jax.devices()[:2])
        pp = PipelineParallel(pl, None, strat)
        optimizer = opt.SGD(learning_rate=0.05, parameters=pl.parameters())
        rng = np.random.default_rng(2)
        x = paddle.to_tensor(np.asarray(rng.normal(size=(4, 16)), np.float32))
        y = paddle.to_tensor(np.asarray(rng.normal(size=(4, 16)), np.float32))
        import warnings

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            loss = pp.train_batch((x, y), optimizer)
        dist.env.set_global_mesh(None)
        assert pp._compiled_state == -1, "nonuniform stages must not compile"
        # the downgrade to the sequential loop must be announced, not silent
        assert any("falling back" in str(w.message)
                   and issubclass(w.category, RuntimeWarning) for w in caught)
        assert np.isfinite(float(loss.numpy()))


class TestDoubleBufferedSchedules:
    """The overlap PR's double-buffered ppermute (prefetch carry slot):
    per-microbatch math is identical to the single-buffered schedule — only
    the tick mapping changes — so values and grads must match exactly."""

    def test_spmd_double_buffer_value_and_grad_parity(self):
        S, M, mb, H = 4, 8, 2, 16
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(0)
        Ws = jnp.asarray(rng.normal(size=(S, H, H)), jnp.float32) * 0.3
        x = jnp.asarray(rng.normal(size=(M * mb, H)), jnp.float32)
        xmb = microbatch((x,), M)

        def stage_fn(W, inp):
            (h,) = inp
            return (jnp.tanh(h @ W),)

        def loss(Ws, db):
            (o,) = pipeline_spmd(stage_fn, Ws, xmb, mesh=mesh,
                                 double_buffer=db)
            return (o ** 2).sum()

        l0, g0 = jax.value_and_grad(loss)(Ws, False)
        l1, g1 = jax.value_and_grad(loss)(Ws, True)
        np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), rtol=0)
        np.testing.assert_allclose(np.asarray(g0), np.asarray(g1), rtol=0)

    def test_spmd_double_buffer_rider_order_preserved(self):
        S, M, mb, H = 2, 4, 2, 8
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(2)
        Ws = jnp.asarray(rng.normal(size=(S, H, H)), jnp.float32) * 0.3
        x = jnp.asarray(rng.normal(size=(M * mb, H)), jnp.float32)
        tags = jnp.arange(M * mb, dtype=jnp.int32)

        def stage_fn(W, inp):
            h, tag = inp
            return (jnp.tanh(h @ W), tag)

        out, otags = unmicrobatch(
            pipeline_spmd(stage_fn, Ws, microbatch((x, tags), M), mesh=mesh,
                          double_buffer=True))
        np.testing.assert_array_equal(np.asarray(otags), np.asarray(tags))

    def test_interleaved_double_buffer_parity(self):
        S, V, M, mb, H = 2, 2, 4, 2, 8
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(3)
        Ws = jnp.asarray(rng.normal(size=(S * V, H, H)), jnp.float32) * 0.3
        x = jnp.asarray(rng.normal(size=(M * mb, H)), jnp.float32)
        xmb = microbatch((x,), M)

        def stage_fn(W, inp):
            (h,) = inp
            return (jnp.tanh(h @ W),)

        def loss(Ws, db):
            (o,) = pipeline_interleaved(
                stage_fn, pack_chunked(Ws, S, V), xmb,
                mesh=mesh, num_chunks=V, double_buffer=db)
            return (o ** 2).sum()

        l0, g0 = jax.value_and_grad(loss)(Ws, False)
        l1, g1 = jax.value_and_grad(loss)(Ws, True)
        np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), rtol=0)
        np.testing.assert_allclose(np.asarray(g0), np.asarray(g1), rtol=0)

    def test_interleaved_double_buffer_needs_enough_microbatches(self):
        S, V, M, H = 4, 2, 4, 8  # M=4 < 2S-1=7
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(4)
        Ws = jnp.asarray(rng.normal(size=(S * V, H, H)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(M * 2, H)), jnp.float32)

        def stage_fn(W, inp):
            (h,) = inp
            return (h @ W,)

        with pytest.raises(ValueError, match="2\\*pp-1"):
            pipeline_interleaved(stage_fn, pack_chunked((Ws,), S, V), 
                                 microbatch((x,), M), mesh=mesh,
                                 num_chunks=V, double_buffer=True)

    def test_env_default_controls_spmd(self, monkeypatch):
        # PADDLE_TPU_PP_DOUBLE_BUFFER=1 flips the default; parity holds
        S, M, mb, H = 2, 4, 1, 8
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(5)
        Ws = jnp.asarray(rng.normal(size=(S, H, H)), jnp.float32) * 0.3
        x = jnp.asarray(rng.normal(size=(M * mb, H)), jnp.float32)
        xmb = microbatch((x,), M)

        def stage_fn(W, inp):
            (h,) = inp
            return (jnp.tanh(h @ W),)

        (base,) = pipeline_spmd(stage_fn, Ws, xmb, mesh=mesh,
                                double_buffer=False)
        monkeypatch.setenv("PADDLE_TPU_PP_DOUBLE_BUFFER", "1")
        (flipped,) = pipeline_spmd(stage_fn, Ws, xmb, mesh=mesh)
        np.testing.assert_allclose(np.asarray(base), np.asarray(flipped),
                                   atol=0)
