"""Pipeline-parallelism tests.

Model: the reference validates pp by numeric parity between the 1F1B
multi-process run and a single-process run
(test/collective/fleet/hybrid_parallel_pp_*.py); here the compiled
collective-permute pipeline (paddle_tpu.parallel.pipeline) is checked against
sequential execution on the 8-device CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.parallel.pipeline import (
    microbatch,
    pipeline_spmd,
    stack_pytrees,
    unmicrobatch,
    unstack_leading,
)

AXES = ("dp", "pp", "sharding", "sep", "mp")


def _pp_mesh(S):
    return Mesh(np.array(jax.devices()[:S]).reshape(1, S, 1, 1, 1), AXES)


class TestPipelineSpmd:
    def test_forward_parity(self):
        S, M, mb, H = 4, 8, 2, 16
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(0)
        Ws = jnp.asarray(rng.normal(size=(S, H, H)), jnp.float32) * 0.3
        x = jnp.asarray(rng.normal(size=(M * mb, H)), jnp.float32)

        def stage_fn(W, inp):
            h, tag = inp
            return (jnp.tanh(h @ W), tag)

        tags = jnp.arange(M * mb, dtype=jnp.int32)
        out, otags = unmicrobatch(
            pipeline_spmd(stage_fn, Ws, microbatch((x, tags), M), mesh=mesh)
        )
        ref = x
        for i in range(S):
            ref = jnp.tanh(ref @ Ws[i])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        # constants ride the pipeline unchanged and in order
        np.testing.assert_array_equal(np.asarray(otags), np.asarray(tags))

    def test_grad_parity(self):
        S, M, mb, H = 2, 4, 2, 8
        mesh = _pp_mesh(S)
        rng = np.random.default_rng(1)
        Ws = jnp.asarray(rng.normal(size=(S, H, H)), jnp.float32) * 0.3
        x = jnp.asarray(rng.normal(size=(M * mb, H)), jnp.float32)
        xmb = microbatch((x,), M)

        def stage_fn(W, inp):
            (h,) = inp
            return (jnp.tanh(h @ W),)

        def loss_pipe(Ws):
            (o,) = pipeline_spmd(stage_fn, Ws, xmb, mesh=mesh)
            return (o ** 2).sum()

        def loss_ref(Ws):
            h = x
            for i in range(S):
                h = jnp.tanh(h @ Ws[i])
            return (h ** 2).sum()

        g1 = jax.jit(jax.grad(loss_pipe))(Ws)
        g2 = jax.grad(loss_ref)(Ws)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)

    def test_stack_unstack_roundtrip(self):
        trees = [{"w": jnp.ones((2,)) * i} for i in range(3)]
        stacked = stack_pytrees(trees)
        assert stacked["w"].shape == (3, 2)
        back = unstack_leading(stacked, 3)
        np.testing.assert_allclose(np.asarray(back[2]["w"]), 2.0)


class TestGPTPipe:
    def _models(self, num_layers=4):
        from paddle_tpu.models import gpt3_tiny, GPTForCausalLMPipe

        paddle.seed(0)
        cfg = gpt3_tiny()
        cfg.num_layers = num_layers
        return cfg, GPTForCausalLMPipe(cfg, num_microbatches=2)

    def test_scan_vs_pipeline_exact(self):
        cfg, pipe = self._models()
        pipe.eval()
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16))
        )
        dist.env.build_mesh(dp=1, devices=jax.devices()[:1])
        out_scan = pipe(ids).numpy()
        dist.env.build_mesh(pp=4, devices=jax.devices()[:4])
        out_pipe = pipe(ids).numpy()
        dist.env.set_global_mesh(None)
        np.testing.assert_allclose(out_scan, out_pipe, atol=1e-4)

    def test_layered_state_dict_parity(self):
        from paddle_tpu.models import GPTForCausalLM, stack_layered_state_dict

        cfg, pipe = self._models()
        layered = GPTForCausalLM(cfg)
        layered.eval()
        pipe.eval()
        pipe.set_state_dict(stack_layered_state_dict(layered.state_dict(), cfg.num_layers))
        dist.env.set_global_mesh(None)
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16))
        )
        np.testing.assert_allclose(
            layered(ids).numpy(), pipe(ids).numpy(), atol=1e-4
        )

    def test_hybrid_train_step_dp_pp_mp(self):
        from paddle_tpu.models import GPTPretrainingCriterion
        import paddle_tpu.optimizer as opt

        cfg, pipe = self._models()
        crit = GPTPretrainingCriterion(cfg)
        pipe.train()
        mesh = dist.build_mesh(dp=2, pp=2, mp=2)
        optimizer = opt.AdamW(learning_rate=1e-3, parameters=pipe.parameters())
        step = dist.DistributedTrainStep(
            pipe, lambda lg, lb: crit(lg, lb), optimizer, mesh=mesh
        )
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)))
        labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)))
        losses = [float(step(ids, labels)) for _ in range(5)]
        dist.env.set_global_mesh(None)
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


class TestPipelineLayerWrapper:
    def test_pipeline_layer_partition_and_train_batch(self):
        """Eager PipelineLayer/PipelineParallel wrapper parity (reference
        hybrid_parallel_pp_layer.py API)."""
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (
            LayerDesc,
            PipelineLayer,
        )

        paddle.seed(0)
        descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(4)]
        pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.MSELoss())
        assert pl.get_num_stages() == 2
        assert len(pl.get_stage_layers(0)) == 2
        x = paddle.to_tensor(np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32))
        out = pl(x)
        assert tuple(out.shape) == (4, 8)
