"""Headline benchmark: GPT-3 decoder training step on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric is model FLOPs utilization (MFU) of the full train step
(fwd+bwd+AdamW) — the BASELINE.md north star is >=45% MFU, so
vs_baseline = mfu / 0.45.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import numpy as np

# chip kind -> peak bf16 FLOP/s (public spec sheets)
_PEAK = {
    "TPU v2": 22.5e12,
    "TPU v3": 61.0e12,  # per chip (2 cores)
    "TPU v4": 137.5e12,  # per chip (megacore)
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 229.5e12,
    "TPU v5p": 229.5e12,
    "TPU v6 lite": 459e12,
    "TPU v6e": 459e12,
    "TPU7x": 2307e12,
}


def _peak_flops(device):
    kind = getattr(device, "device_kind", "")
    for k, v in _PEAK.items():
        if kind.startswith(k) or k in kind:
            return v, kind
    # CPU smoke runs / unknown chips: assume v4-class so the line still prints
    return 137.5e12, kind or "unknown"


def main():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import gpt3_1p3b, gpt3_125m, GPTForCausalLM, GPTPretrainingCriterion

    on_tpu = jax.default_backend() not in ("cpu",)
    cfg_name = os.environ.get("BENCH_CONFIG", "gpt3_1p3b" if on_tpu else "gpt3_125m_cpu")
    if cfg_name == "gpt3_1p3b":
        cfg = gpt3_1p3b(max_position_embeddings=2048)
        batch, seq, steps = 4, 2048, 10
    elif cfg_name == "gpt3_125m":
        cfg = gpt3_125m(max_position_embeddings=2048)
        batch, seq, steps = 8, 2048, 10
    else:  # tiny CPU smoke
        from paddle_tpu.models import GPTConfig
        cfg = GPTConfig(hidden_size=256, num_layers=4, num_heads=4, vocab_size=8192,
                        max_position_embeddings=512)
        batch, seq, steps = 2, 256, 3

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    step = dist.DistributedTrainStep(model, lambda lg, lb: crit(lg, lb), optimizer, mesh=mesh)

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))

    loss = step(ids, labels)  # compile + warmup
    _ = float(loss)
    t0 = time.perf_counter()
    for _i in range(steps):
        loss = step(ids, labels)
    _ = float(loss)
    dt = (time.perf_counter() - t0) / steps

    n_params = cfg.num_params(include_embeddings=False) + cfg.vocab_size * cfg.hidden_size
    tokens = batch * seq
    # 6ND fwd+bwd + attention quadratic term (12*L*h*T^2 per token batch)
    flops = 6.0 * n_params * tokens + 12.0 * cfg.num_layers * cfg.hidden_size * seq * tokens
    peak, kind = _peak_flops(jax.devices()[0])
    mfu = flops / dt / peak
    print(json.dumps({
        "metric": f"mfu_{cfg_name}_bs{batch}x{seq}_{kind.replace(' ', '_')}",
        "value": round(mfu, 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(mfu / 0.45, 4),
        "tokens_per_sec_per_chip": round(tokens / dt, 1),
        "step_time_s": round(dt, 4),
    }))


if __name__ == "__main__":
    main()
