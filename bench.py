"""Benchmarks against the BASELINE.md matrix.

Default (driver mode): the headline GPT-3 decoder train-step ladder — prints
ONE JSON line {"metric", "value", "unit", "vs_baseline"} (MFU; north star
>=45% so vs_baseline = mfu / 0.45).

BENCH_CONFIG=<rung> runs a single named rung. BENCH_MATRIX=1 runs the
BASELINE.md matrix (gpt3 headline + llama flashmask + bert-base +
resnet50 + SD-scale unet),
one JSON line per rung, headline line LAST so drivers reading the final line
still get the headline.

`--emit-metrics[=path]` (default path: $BENCH_METRICS_PATH or
bench_metrics.jsonl) installs an observability StepTimeline over the timed
loops, appending one JSON step record per timed step — host-sync counts,
dispatch-cache hit/miss/bypass deltas, comm_task intervals — so BENCH_*.json
rounds can be read next to the per-step telemetry that produced them, not
just the wall-time headline.

Rungs: gpt3_1p3b gpt3_350m gpt3_125m llama_7bshape bert_base resnet50
unet_sd serving serving_quant cpu_smoke. `serving` drives the paged-KV
engine (docs/SERVING.md) and reports tokens/sec at the p99 token latency it
measured, plus TTFT percentiles; with --emit-metrics the serving SLO
registry series is appended to the JSONL once per scheduler tick.
`serving_quant` A/Bs the int8-KV + weight-only-int8 fast path against the
full-precision engine at an equal KV HBM byte budget (tokens/s, p99, peak
concurrency, kv bytes/token per leg).

`--plan` prints the mesh planner's analytic top-K shortlist + cost
breakdown for the selected rung config (docs/PLANNER.md) without timing
anything — BENCH_PLAN_DEVICES sizes the grid, and
PADDLE_TPU_PLAN_OVERLAP_JSONL feeds measured overlap history into the
hybrid cost model.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import numpy as np

def _peak_flops(device):
    """Chip kind -> peak bf16 FLOP/s, resolved through the mesh planner's
    chip spec table (paddle_tpu/distributed/planner/cost_model.py) so the
    bench MFU denominator and the planner's compute term can never disagree
    about what a chip can do. Imported lazily — paddle_tpu must not load
    before _probe_backend() decides whether to pin jax_platforms=cpu."""
    from paddle_tpu.distributed.planner.cost_model import PEAK_BF16_FLOPS

    kind = getattr(device, "device_kind", "")
    for k, v in PEAK_BF16_FLOPS.items():
        if kind.startswith(k) or k in kind:
            return v, kind
    # CPU smoke runs / unknown chips: assume v4-class so the line still prints
    return PEAK_BF16_FLOPS["TPU v4"], kind or "unknown"


def _probe_backend(max_tries=2, timeout_s=180.0):
    """Probe accelerator init in a subprocess so a wedged tunnel cannot hang us.

    Round-1 failure modes: (a) 'Unable to initialize backend axon' raised and
    the uncaught exception meant no perf line shipped; (b) the tunnel can also
    simply HANG in init, which no in-process try/except survives. So the probe
    runs `jax.default_backend()` in a child process under a hard timeout; on
    failure the parent forces jax_platforms=cpu BEFORE any in-process backend
    init and degrades to the smoke config.
    Returns (backend_name_or_None, error_or_None).
    """
    import subprocess

    err = None
    for i in range(max_tries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            out = r.stdout.strip().splitlines()
            if r.returncode == 0 and out:
                return out[-1], None
            err = (r.stderr or "").strip()[-300:] or f"probe rc={r.returncode}"
        except subprocess.TimeoutExpired:
            err = f"backend init timed out after {timeout_s:.0f}s (tunnel wedged)"
    return None, err


def _timed_steps(step_fn, steps, trace_dir=None, warmup=3, rung=None):
    """Warmed-up timed loop; returns (seconds/step, timeline_info).
    step_fn() must return a device value whose float() forces completion.
    timeline_info carries the overlap aggregate over the timed steps when
    --emit-metrics installed a StepTimeline ({} otherwise).

    warmup: executions AFTER compile before the clock starts — the first few
    runs of a fresh executable through the axon tunnel pay settling costs
    (measured round 5: ~2x on the first timed batch), which inflated the
    125M rung from 192 to 272 ms/step when only one warmup call ran."""
    # warmup BEFORE the profiler starts so the trace holds only timed steps
    last = None
    for _ in range(warmup):
        last = step_fn()
    if last is not None:
        _ = float(last)
    prof = None
    if trace_dir:
        import paddle_tpu.profiler as profiler

        prof = profiler.Profiler(
            device_trace_dir=trace_dir,
            on_trace_ready=profiler.export_chrome_tracing(trace_dir))
        prof.start()
    from paddle_tpu.observability import spans as _obs_spans

    tl = _obs_spans.active_timeline()  # installed by --emit-metrics
    timed_records = []
    t0 = time.perf_counter()
    last = None
    for i in range(steps):
        if tl is not None:
            tl.step_begin(i)
        last = step_fn()
        if tl is not None:
            # rung tag: a BENCH_MATRIX run interleaves several rungs'
            # step sequences in one JSONL — untagged records with repeating
            # step indices would be unattributable
            timed_records.append(
                tl.step_end(extra={"rung": rung} if rung else None))
        if prof is not None:
            prof.step()
    _ = float(last)
    dt = (time.perf_counter() - t0) / steps
    if prof is not None:
        prof.stop()
    info = {}
    if timed_records:
        agg = _obs_spans.aggregate_overlap(
            r.get("overlap") or {} for r in timed_records if r)
        n = max(len(timed_records), 1)
        info = {
            "overlap_fraction": round(agg["fraction"], 4),
            "comm_exposed_s_per_step": round(agg["exposed_s"] / n, 6),
        }
        info.update(_kernel_ladder_info())
    return dt, info


def _kernel_ladder_info():
    """Pallas-kernel attribution for the perf line (under --emit-metrics):
    which fused kernels were live (toggle x backend) and the autotuned tile
    + hit/miss/fallback counts per kernel — so a BENCH round's MFU movement
    can be attributed to tile choices, not guessed at."""
    try:
        from paddle_tpu.nn.functional.flash_attention import _use_pallas_kernel
        from paddle_tpu.ops.pallas import autotune as _autotune
        from paddle_tpu.ops.pallas.fused_norm import fused_norm_on
        from paddle_tpu.ops.pallas.fused_rope import fused_rope_on

        pallas = _use_pallas_kernel()
        return {
            "fused_norm": bool(pallas and fused_norm_on()),
            "fused_rope": bool(pallas and fused_rope_on()),
            "autotuned_tiles": _autotune.chosen_tiles(),
        }
    except Exception:
        return {}


def _emit(name, dt, flops, tokens=None, extra=None):
    peak, kind = _peak_flops(jax.devices()[0])
    mfu = flops / dt / peak
    line = {
        "metric": f"mfu_{name}_{kind.replace(' ', '_')}",
        "value": round(mfu, 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(mfu / 0.45, 4),
        "step_time_s": round(dt, 4),
    }
    if tokens is not None:
        line["tokens_per_sec_per_chip"] = round(tokens / dt, 1)
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)
    return line


# --------------------------------------------------------------------------- #
# rungs
# --------------------------------------------------------------------------- #


def _cpu_smoke_cfg():
    """The degraded-run model shape, shared by the gpt ladder's fallback
    rung and `--plan` so the planned config is always the config the
    cpu_smoke rung actually measures."""
    from paddle_tpu.models import GPTConfig

    return GPTConfig(hidden_size=256, num_layers=4, num_heads=4,
                     vocab_size=8192, max_position_embeddings=512)


def _decoder_flops(cfg, batch, seq):
    """6ND fwd+bwd + attention quadratic term (12*L*h*T^2 per token batch)."""
    n_params = (cfg.num_params(include_embeddings=False)
                + cfg.vocab_size * cfg.hidden_size)
    tokens = batch * seq
    return (6.0 * n_params * tokens
            + 12.0 * cfg.num_layers * cfg.hidden_size * seq * tokens)


def _free_rung(*objs):
    """Release a failed/finished rung's device buffers before the next one
    allocates (round-4 lesson: the 1.3B OOM left 15GB of params+states live
    while the 350M fallback tried to allocate)."""
    import gc

    for o in objs:
        try:
            if hasattr(o, "params"):  # TrainStep: drop device state dicts
                o.params = {}
                o.opt_states = {}
                o.buffers = {}
                # the same buffers stay live through model Parameters
                # (_ModuleState) — null those refs too or nothing is freed
                o.model = None
                o._state = None
                o._compiled = None
                # optimizer._parameter_list also pins the Parameters
                if getattr(o, "optimizer", None) is not None:
                    o.optimizer._parameter_list = None
                    o.optimizer = None
        except Exception:
            pass
    del objs
    gc.collect()
    try:
        jax.clear_caches()
    except Exception:
        pass


def _decoder_step(cfg, batch, seq, on_tpu, low_mem=False, **step_kw):
    """Shared scaffold: seeded model + criterion + AdamW + single-device mesh
    + DistributedTrainStep + random token batch. Returns (step, ids, labels).

    low_mem (the 1.3B-on-one-16GB-chip recipe): bf16 params via amp.decorate
    + bf16 AdamW moments (f32 update compute) + per-layer recompute. Steady
    HBM for 1.3B drops 15.6GB -> ~7.8GB; the f32-master recipe needs >1 chip
    (that path is exercised by the sharded dryrun/tests instead)."""
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion

    paddle.seed(0)
    if low_mem:
        cfg.use_recompute = True
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    if low_mem:
        amp.decorate(model, level="O2", dtype="bfloat16")
        optimizer = opt.AdamW(learning_rate=1e-4, moment_dtype="bfloat16",
                              parameters=model.parameters())
    else:
        optimizer = opt.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    # bf16 compute with f32 master weights — the production TPU recipe
    step = dist.DistributedTrainStep(
        model, lambda lg, lb: crit(lg, lb), optimizer, mesh=mesh,
        amp_level="O2" if on_tpu else None, amp_dtype="bfloat16", **step_kw)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))
    return step, ids, labels


def run_gpt_rung(cfg_name, on_tpu, init_error, trace_dir=None):
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import gpt3_1p3b, gpt3_125m, gpt3_350m

    def build(name):
        if name == "gpt3_1p3b":
            return gpt3_1p3b(max_position_embeddings=2048), 4, 2048, 10
        if name == "gpt3_350m":
            return gpt3_350m(max_position_embeddings=2048), 8, 2048, 10
        if name == "gpt3_125m":
            return gpt3_125m(max_position_embeddings=2048), 8, 2048, 10
        return _cpu_smoke_cfg(), 2, 256, 3

    ladder = [cfg_name] if cfg_name else (
        ["gpt3_1p3b", "gpt3_350m", "gpt3_125m"] if on_tpu else ["cpu_smoke"])

    fallback_note = None
    step = ids = labels = None
    for idx, name in enumerate(ladder):
        # the WHOLE rung — model/optimizer/state allocation included — is
        # inside the try: round 4's 1.3B run OOMed at construction, outside
        # the old warmup-only try, so the fallback never ran
        try:
            cfg, batch, seq, steps = build(name)
            low_mem = name == "gpt3_1p3b"
            step, ids, labels = _decoder_step(cfg, batch, seq, on_tpu,
                                              low_mem=low_mem)
            _ = float(step(ids, labels))  # compile + warmup
            break
        except Exception as e:
            if idx + 1 >= len(ladder):
                raise
            fallback_note = f"{name} failed ({type(e).__name__}), fell back"
            _free_rung(step, ids, labels)
            step = ids = labels = None
            dist.env.set_global_mesh(None)
            continue

    dt, tl_info = _timed_steps(lambda: step(ids, labels), steps, trace_dir,
                               rung=name)
    flops = _decoder_flops(cfg, batch, seq)
    extra = dict(tl_info)
    if name == "gpt3_1p3b":
        extra["recipe"] = "bf16_params+bf16_moments+recompute"
    if init_error:
        extra["error"] = f"degraded to cpu: {init_error}"[:400]
    if fallback_note:
        extra["note"] = fallback_note
    return _emit(f"{name}_bs{batch}x{seq}", dt, flops, batch * seq, extra)


def run_llama_rung(on_tpu):
    """LLaMA-7B-shape (h=4096, GQA, SwiGLU, RoPE) scaled in depth to fit one
    chip's optimizer states; flashmask Pallas attention; sharding stage-2 code
    path (degenerate on 1 chip); BASELINE.md row 'LLaMA-7B/13B sharding +
    flash_attn'."""
    from paddle_tpu.models.llama import LlamaConfig, llama_tiny

    if on_tpu:
        # 7B's matmul shapes (h=4096, f=11008, heads 32/kv 8) at depth 3:
        # ~0.9B params => ~12.5GB AdamW f32 states on one v5e
        cfg = LlamaConfig(hidden_size=4096, num_layers=3, num_heads=32,
                          num_kv_heads=8, intermediate_size=11008,
                          max_position_embeddings=2048,
                          attn_variant="flashmask")
        batch, seq, steps = 4, 2048, 10
    else:
        cfg = llama_tiny(attn_variant="flashmask")
        batch, seq, steps = 2, 128, 3
    step, ids, labels = _decoder_step(cfg, batch, seq, on_tpu,
                                      sharding_stage=2)
    _ = float(step(ids, labels))
    dt, tl_info = _timed_steps(lambda: step(ids, labels), steps,
                               rung="llama_7bshape")
    return _emit(f"llama_7bshape_flashmask_bs{batch}x{seq}", dt,
                 _decoder_flops(cfg, batch, seq), batch * seq,
                 extra=tl_info or None)


def run_bert_rung(on_tpu):
    """BERT-base MLM+NSP pretraining step (BASELINE.md 'BERT-base / ERNIE-1.0
    pretraining, fleet data-parallel' — DP collectives are a no-op on one
    chip; the dp axis is exercised in tests/dryrun)."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.bert import (BertForPretraining,
                                        BertPretrainingCriterion, bert_base,
                                        bert_tiny)

    if on_tpu:
        cfg = bert_base()
        batch, seq, n_mask, steps = 32, 512, 80, 10
    else:
        cfg = bert_tiny()
        batch, seq, n_mask, steps = 2, 128, 8, 3
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_dropout_prob = 0.0
    paddle.seed(0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    step = dist.DistributedTrainStep(
        model, lambda mlm, nsp, ml, nl: crit(mlm, nsp, ml, nl), optimizer,
        mesh=mesh, amp_level="O2" if on_tpu else None, amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))
    tt = paddle.to_tensor(np.zeros((batch, seq), np.int32))
    am = paddle.to_tensor(np.ones((batch, seq), np.float32))
    mpos = paddle.to_tensor(rng.integers(0, seq, (batch, n_mask)))
    mlab = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, n_mask)))
    nlab = paddle.to_tensor(rng.integers(0, 2, (batch,)))
    _ = float(step([ids, tt, am, mpos], [mlab, nlab]))
    dt, tl_info = _timed_steps(lambda: step([ids, tt, am, mpos], [mlab, nlab]),
                               steps, rung="bert_base")
    h, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    # encoder 12h^2/layer params, attention quadratic, + MLM head on n_mask
    n_enc = 12 * L * h * h
    flops = (6.0 * n_enc * batch * seq
             + 12.0 * L * h * seq * batch * seq
             + 6.0 * batch * n_mask * h * V)
    return _emit(f"bert_base_bs{batch}x{seq}", dt, flops, batch * seq,
                 extra=tl_info or None)


def run_unet_rung(on_tpu):
    """Stable-Diffusion-style UNet denoising step (BASELINE.md 'Stable
    Diffusion UNet: conv + cross-attn' row). SD-scale channel stack
    (320/640/1280, cross-attn context 768) at the 64x64x4 latent shape;
    throughput metric is latents/sec (MFU for a conv+attn hybrid is not
    comparable to the decoder rungs)."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import UNetConfig, UNetModel, unet_tiny

    if on_tpu:
        cfg = UNetConfig(in_channels=4, out_channels=4, base_channels=320,
                         channel_mult=(1, 2, 4), num_res_blocks=2,
                         attention_levels=(1, 2), num_heads=8,
                         context_dim=768)
        batch, hw, ctx_len, steps = 8, 64, 77, 10
    else:
        cfg = unet_tiny()
        batch, hw, ctx_len, steps = 2, 8, 4, 3
    paddle.seed(0)
    model = UNetModel(cfg)
    mse = nn.MSELoss()
    optimizer = opt.AdamW(learning_rate=1e-4, moment_dtype="bfloat16",
                          parameters=model.parameters())
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    step = dist.DistributedTrainStep(
        model, lambda pred, target: mse(pred, target), optimizer, mesh=mesh,
        amp_level="O2" if on_tpu else None, amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    noisy = paddle.to_tensor(
        rng.normal(size=(batch, cfg.in_channels, hw, hw)).astype(np.float32))
    t = paddle.to_tensor(rng.integers(0, 1000, (batch,)))
    ctx = paddle.to_tensor(
        rng.normal(size=(batch, ctx_len, cfg.context_dim)).astype(np.float32))
    noise = paddle.to_tensor(
        rng.normal(size=(batch, cfg.out_channels, hw, hw)).astype(np.float32))
    _ = float(step([noisy, t, ctx], noise))
    dt, tl_info = _timed_steps(lambda: step([noisy, t, ctx], noise), steps,
                               rung="unet_sd")
    peak, kind = _peak_flops(jax.devices()[0])
    line = {
        "metric": f"unet_sd_bs{batch}x{hw}_{kind.replace(' ', '_')}",
        "value": round(batch / dt, 2),
        "unit": "latents_per_sec",
        "vs_baseline": 0.0,  # reference publishes no UNet number
        "step_time_s": round(dt, 4),
        **tl_info,
    }
    print(json.dumps(line), flush=True)
    return line


def run_resnet_rung(on_tpu):
    """ResNet-50 ImageNet train step (BASELINE.md first-slice row)."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50, resnet18

    if on_tpu:
        model, batch, hw, steps, fwd_flops = resnet50(), 128, 224, 10, 4.1e9
    else:
        model, batch, hw, steps, fwd_flops = resnet18(), 2, 32, 3, 0.04e9
    paddle.seed(0)
    optimizer = opt.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    step = dist.DistributedTrainStep(
        model, lambda lg, lb: F.cross_entropy(lg, lb), optimizer, mesh=mesh,
        amp_level="O2" if on_tpu else None, amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    img = paddle.to_tensor(rng.normal(size=(batch, 3, hw, hw)).astype(np.float32))
    lab = paddle.to_tensor(rng.integers(0, 1000, (batch, 1)))
    _ = float(step(img, lab))
    dt, tl_info = _timed_steps(lambda: step(img, lab), steps, rung="resnet50")
    flops = 3.0 * fwd_flops * batch  # fwd + ~2x bwd
    return _emit(f"resnet50_bs{batch}" if on_tpu else f"resnet18_bs{batch}",
                 dt, flops,
                 extra={"images_per_sec": round(batch / dt, 1), **tl_info})


def run_moe_rung(on_tpu, metrics_path=None):
    """Expert-parallel MoE train step (BASELINE.md 'gpt3_moe' row;
    ISSUE-14): decoder embedding + L pre-norm MoE-FFN residual blocks
    (8 experts, GShard top-2) + tied-size LM head — attention-free, so the
    measured fast-vs-einsum delta is the MoE dispatch/GEMM path itself,
    not attention noise. Experts shard over the `ep` mesh axis (as many
    devices as divide the expert count); the batch shards over ep too, so
    the dispatch/combine reshards are REAL all-to-all traffic.

    A/B knobs (the recorded bench delta, not a claim): PADDLE_TPU_MOE_FAST
    =0 runs the dense einsum oracle, PADDLE_TPU_MOE_A2A_CHUNKS sets the
    a2a chunk schedule. The perf line carries fast=/a2a_chunks=/ep= and,
    over the timed loop, the collective_bytes_total{op="all_to_all"} delta
    (all_to_all_bytes=) next to overlap_fraction under --emit-metrics.
    On CPU the sorted fast path runs its batched-einsum grouped-GEMM
    fallback (the Pallas kernel needs tpu/axon or interpret mode, which
    tier-1 kernel tests cover)."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu.incubate.distributed.models.moe import (ExpertFFN,
                                                            MoELayer,
                                                            moe_a2a_chunks,
                                                            moe_fast_on)
    from paddle_tpu.observability.metrics import default_registry

    E, topk = 8, 2
    if on_tpu:
        M, H, L, V = 1024, 4096, 4, 32000
        batch, seq, steps = 8, 1024, 10
    else:
        M, H, L, V = 64, 128, 2, 1024
        batch, seq, steps = 8, 128, 3
    ndev = len(jax.devices())
    ep = next((c for c in (8, 4, 2) if E % c == 0 and ndev >= c
               and batch % c == 0), 1)
    ep_axis = "ep" if ep > 1 else None
    paddle.seed(0)
    mesh = dist.build_mesh(ep=ep, devices=jax.devices()[:ep])

    class MoEDecoder(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(V, M)
            self.norms = nn.LayerList([nn.LayerNorm(M) for _ in range(L)])
            self.moes = nn.LayerList([
                MoELayer(M, ExpertFFN(E, M, H, ep_axis=ep_axis),
                         gate={"type": "gshard", "top_k": topk},
                         ep_axis=ep_axis)
                for _ in range(L)])
            self.head = nn.Linear(M, V)

        def forward(self, ids):
            x = self.embed(ids)
            for norm, moe in zip(self.norms, self.moes):
                x = x + moe(norm(x))
            return self.head(x)

    model = MoEDecoder()
    optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = dist.DistributedTrainStep(
        model, lambda lg, lb: F.cross_entropy(
            lg.reshape([-1, V]), lb.reshape([-1, 1])), optimizer, mesh=mesh,
        batch_axes=("dp", "ep"),
        amp_level="O2" if on_tpu else None, amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, V, (batch, seq)))
    labels = paddle.to_tensor(rng.integers(0, V, (batch, seq)))
    for _ in range(4):  # compile + the settle warmups _timed_steps would run
        last = step(ids, labels)
    _ = float(last)
    # snapshot AFTER warmup so the a2a byte delta covers exactly the timed
    # steps the dt covers (every executed step re-emits its volume)
    reg = default_registry()
    base = reg.snapshot()
    dt, tl_info = _timed_steps(lambda: step(ids, labels), steps,
                               rung="gpt3_moe", warmup=0)
    a2a_bytes = reg.delta(base).get("collective_bytes_total{op=all_to_all}", 0)
    if metrics_path:
        # the counter registry next to the step-timeline records, like the
        # serving rung — a standalone gpt3_moe run leaves the a2a series on
        # disk, not only in the perf line
        reg.export_jsonl(metrics_path)
    tokens = batch * seq
    cap = int(np.ceil(1.2 * tokens / E))
    routed = min(topk * tokens, E * cap)
    # fwd FLOPs: expert GEMMs over ROUTED rows (the fast-path work model;
    # the einsum oracle burns strictly more) + router + LM head; *3 fwd+bwd
    fwd = (L * routed * 4.0 * M * H + L * tokens * 2.0 * M * E
           + tokens * 2.0 * M * V)
    return _emit(
        f"gpt3_moe_e{E}top{topk}_bs{batch}x{seq}", dt, 3.0 * fwd, tokens,
        extra={"fast": moe_fast_on(), "a2a_chunks": moe_a2a_chunks(),
               "ep": ep, "experts": E, "top_k": topk,
               "all_to_all_bytes": int(a2a_bytes), **tl_info})


def _serving_workload(cfg, S, n_req):
    """The serving rungs' shared request mix: every third prompt extends one
    long common prefix (exercises prefix sharing), lengths staggered, every
    fourth request sampled at T=0.7 and the rest greedy. One definition so
    `serving` and `serving_quant` numbers stay comparable — returns
    [(prompt, temperature), ...]."""
    rng = np.random.default_rng(0)
    shared = rng.integers(1, cfg.vocab_size, S // 4).astype(np.int32)
    out = []
    for i in range(n_req):
        tail = rng.integers(1, cfg.vocab_size,
                            2 + i % (S // 8)).astype(np.int32)
        prompt = (np.concatenate([shared, tail]) if i % 3 == 0
                  else rng.integers(1, cfg.vocab_size,
                                    4 + i % (S // 4)).astype(np.int32))
        out.append((prompt, 0.7 if i % 4 == 0 else 0.0))
    return out


def _drain_serving_engine(eng, reg, metrics_path=None, timeline=None,
                          rung=None):
    """Drain a serving engine, timing every scheduler tick. Ticks that paid
    a one-time XLA compile (a prefill bucket or the decode program) are
    warmup, not steady-state token latency — excluding them keeps p99/slo
    honest on cold runs; throughput still counts every token and all wall
    time. One definition shared by the `serving` and `serving_quant` rungs
    so their latency-exclusion semantics cannot drift apart. With
    `metrics_path` the registry is appended to the JSONL once per tick."""
    step_lat, tokens, tick, compile_ticks, peak_live = [], 0, 0, 0, 0
    t_start = time.perf_counter()
    while eng.has_work():
        if timeline is not None:
            timeline.step_begin(tick)
        compiles0 = eng._prefill_cache.compiles_total
        decode_cold = eng._decode_jit is None
        t0 = time.perf_counter()
        out = eng.step()
        dt = time.perf_counter() - t0
        if timeline is not None:
            timeline.step_end(extra={"rung": rung})
        peak_live = max(peak_live, eng.live_count)
        if out:
            if (eng._prefill_cache.compiles_total > compiles0
                    or decode_cold):
                compile_ticks += 1
            else:
                step_lat.append(dt)
            tokens += len(out)
        if metrics_path:
            reg.export_jsonl(metrics_path)
        tick += 1
    return {"step_lat": step_lat, "tokens": tokens,
            "compile_ticks": compile_ticks, "peak_live": peak_live,
            "total_s": time.perf_counter() - t_start}


def run_serving_rung(on_tpu, metrics_path=None):
    """Paged-KV serving throughput at a fixed p99 token-latency SLO
    (docs/SERVING.md; BASELINE.md 'inference' row). Drives the
    PagedServingEngine over a mixed greedy/sampled workload with shared
    prefixes, reporting tokens/sec alongside the p99 per-step token latency
    it was measured at (SLO target: SERVING_SLO_MS env, default 200) and the
    TTFT distribution. With --emit-metrics the full serving registry
    (TTFT/tokens-per-second histograms, queue-depth/pages-free gauges,
    preemption/prefix counters) is appended to the JSONL once per scheduler
    tick — a time series, not just the final line."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.paged import PagedServingEngine
    from paddle_tpu.models import GPTForCausalLM, gpt3_tiny, gpt3_125m
    from paddle_tpu.observability import spans as _obs_spans
    from paddle_tpu.observability.metrics import default_registry

    interp_prev = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")
    if not on_tpu:
        # the paged decode kernel needs the Pallas interpreter off-TPU
        os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
    try:
        paddle.seed(0)
        if on_tpu:
            cfg, B, S, ps, n_req, max_new = gpt3_125m(), 16, 512, 32, 64, 32
        else:
            cfg, B, S, ps, n_req, max_new = gpt3_tiny(), 8, 96, 16, 24, 8
        model = GPTForCausalLM(cfg)
        eng = PagedServingEngine(model, max_batch_size=B, max_seq_len=S,
                                 page_size=ps)
        for prompt, temp in _serving_workload(cfg, S, n_req):
            eng.add_request(prompt, max_new_tokens=max_new, temperature=temp)
        reg = default_registry()
        base = reg.snapshot()
        st = _drain_serving_engine(eng, reg, metrics_path,
                                   timeline=_obs_spans.active_timeline(),
                                   rung="serving")
        total_s, step_lat = st["total_s"], st["step_lat"]
        compile_ticks = st["compile_ticks"]
        done = eng.finished
        delta = reg.delta(base)
        # step() returns only decode-advance tokens; each request's FIRST
        # token is emitted at admission and never appears in `out`. The
        # registry counter saw every token, so it is the honest numerator.
        tokens = delta.get("serving_tokens_total{engine=paged}",
                           st["tokens"])
        ttfts = sorted(r._t_first - r._t_arrival for r in done
                       if r._t_first is not None)
        slo_s = float(os.environ.get("SERVING_SLO_MS", "200")) / 1e3
        p99 = float(np.percentile(step_lat, 99)) if step_lat else 0.0
        peak, kind = _peak_flops(jax.devices()[0])
        line = {
            "metric": f"serving_paged_{('gpt3_125m' if on_tpu else 'gpt3_tiny')}"
                      f"_bs{B}x{S}_{kind.replace(' ', '_')}",
            "value": round(tokens / total_s, 2),
            "unit": "tokens_per_sec",
            "vs_baseline": 0.0,  # reference publishes no serving number
            "requests": len(done),
            "p99_token_latency_s": round(p99, 4),
            "slo_p99_s": slo_s,
            "slo_met": p99 <= slo_s,
            "compile_ticks_excluded": compile_ticks,
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 4),
            "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 4),
            "preemptions": delta.get("serving_preemptions_total", 0),
            "prefix_hits": delta.get("serving_prefix_hits_total", 0),
            "truncations": delta.get("serving_truncations_total"
                                     "{engine=paged}", 0),
            "pages_total": eng.pool.pages_total,
        }
        print(json.dumps(line), flush=True)
        return line
    finally:
        if interp_prev is None:
            os.environ.pop("PADDLE_TPU_PALLAS_INTERPRET", None)
        else:
            os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = interp_prev


def run_serving_quant_rung(on_tpu, metrics_path=None):
    """Quantized serving A/B at EQUAL KV HBM budget (docs/SERVING.md
    "Quantized KV cache"; BASELINE.md row). Leg A: the full-precision paged
    engine. Leg B: `PADDLE_TPU_KV_QUANT=1` + `PADDLE_TPU_SERVE_W8=1` — int8
    pages with per-(page, head) scales through the dequant-fused Pallas
    decode kernel, plus weight-only int8 projections. Both legs get the
    same pool bytes; the int8 pool fits ~4x the pages, so at a page-starved
    budget the quantized leg sustains strictly more concurrent requests
    (and the line records tokens/s + p99 for both so the throughput side of
    the trade is visible too)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.paged import BlockPool, PagedServingEngine
    from paddle_tpu.models import GPTForCausalLM, gpt3_tiny, gpt3_125m
    from paddle_tpu.observability.metrics import default_registry

    interp_prev = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")
    if not on_tpu:
        os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
    try:
        if on_tpu:
            cfg_f, B, S, ps, n_req, max_new = gpt3_125m, 16, 512, 32, 64, 32
            pages_budget = (B * S) // (2 * ps)  # page-starved on purpose
        else:
            cfg_f, B, S, ps, n_req, max_new = gpt3_tiny, 8, 96, 16, 16, 6
            pages_budget = 13
        cfg = cfg_f()
        budget = pages_budget * BlockPool.page_nbytes(
            cfg.num_layers, cfg.kv_heads, cfg.head_dim, ps)
        workload = _serving_workload(cfg, S, n_req)

        def drive(kv_quant, w8):
            # fresh model per leg: the serve_w8 convert pass mutates in
            # place, and the A/B must compare equal starting weights
            paddle.seed(0)
            model = GPTForCausalLM(cfg_f())
            eng = PagedServingEngine(
                model, max_batch_size=B, max_seq_len=S, page_size=ps,
                kv_budget_bytes=budget, kv_quant=kv_quant, serve_w8=w8)
            for prompt, temp in workload:
                eng.add_request(prompt, max_new_tokens=max_new,
                                temperature=temp)
            reg = default_registry()
            base = reg.snapshot()
            st = _drain_serving_engine(eng, reg, metrics_path)
            delta = reg.delta(base)
            tokens = delta.get("serving_tokens_total{engine=paged}", 0)
            step_lat = st["step_lat"]
            return {
                "tokens_per_sec": round(tokens / st["total_s"], 2),
                "p99_token_latency_s": round(
                    float(np.percentile(step_lat, 99)) if step_lat else 0.0,
                    4),
                "peak_concurrent": st["peak_live"],
                "pages_total": eng.pool.pages_total,
                "kv_bytes_per_token": round(eng.pool.bytes_per_token, 1),
                "preemptions": delta.get("serving_preemptions_total", 0),
                "quant_pages": delta.get("serving_kv_quant_pages_total", 0),
                "compile_ticks_excluded": st["compile_ticks"],
            }

        a = drive(kv_quant=False, w8=False)
        b = drive(kv_quant=True, w8=True)
        peak, kind = _peak_flops(jax.devices()[0])
        line = {
            "metric": f"serving_quant_ab_"
                      f"{('gpt3_125m' if on_tpu else 'gpt3_tiny')}"
                      f"_bs{B}x{S}_{kind.replace(' ', '_')}",
            "value": b["tokens_per_sec"],
            "unit": "tokens_per_sec",
            "vs_baseline": 0.0,  # reference publishes no serving number
            "equal_kv_budget_bytes": budget,
            "requests": n_req,
            "dense": a,
            "int8_kv_w8": b,
            "concurrency_gain": (round(b["peak_concurrent"]
                                       / a["peak_concurrent"], 2)
                                 if a["peak_concurrent"] else 0.0),
        }
        print(json.dumps(line), flush=True)
        return line
    finally:
        if interp_prev is None:
            os.environ.pop("PADDLE_TPU_PALLAS_INTERPRET", None)
        else:
            os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = interp_prev


def run_plan(on_tpu, top_k=None):
    """`--plan`: the mesh planner's analytic shortlist + cost breakdown for
    the current rung config — one JSON line per shortlisted candidate and a
    final mesh_plan_shortlist line. Pure analytic: nothing is measured, so
    this exits 0 on the CPU smoke config and the bench harness can gate it.

    Env: BENCH_PLAN_DEVICES (default: live device count), BENCH_PLAN_TOP_K,
    BENCH_PLAN_GBS, BENCH_CONFIG picks the model shape (cpu_smoke default
    off-TPU), PADDLE_TPU_PLAN_OVERLAP_JSONL feeds the measured
    overlap_fraction half of the hybrid cost model."""
    from paddle_tpu.distributed.planner import CostModel, rank_candidates
    from paddle_tpu.models import gpt3_1p3b, gpt3_125m, gpt3_350m

    cfg_name = os.environ.get("BENCH_CONFIG") or (
        "gpt3_1p3b" if on_tpu else "cpu_smoke")
    builders = {"gpt3_1p3b": gpt3_1p3b, "gpt3_350m": gpt3_350m,
                "gpt3_125m": gpt3_125m}
    if cfg_name in builders:
        c = builders[cfg_name](max_position_embeddings=2048)
        seq = 2048
    else:
        c = _cpu_smoke_cfg()
        seq = 256
    ndev = int(os.environ.get("BENCH_PLAN_DEVICES", "0")) or len(jax.devices())
    top_k = top_k or int(os.environ.get("BENCH_PLAN_TOP_K", "5"))
    tuner_cfg = {
        "num_devices": ndev,
        "global_batch_size": int(os.environ.get("BENCH_PLAN_GBS", "0"))
        or max(8, ndev),
        "model_cfg": {"hidden_size": c.hidden_size,
                      "num_layers": c.num_layers,
                      "num_heads": c.num_heads,
                      "vocab_size": c.vocab_size,
                      "seq_length": seq},
    }
    cm = CostModel(device=jax.devices()[0])
    ranked, pruned = rank_candidates(tuner_cfg, cm)
    for rank, (cfg, bd) in enumerate(ranked[:top_k], 1):
        print(json.dumps({
            "metric": "plan_candidate", "rank": rank,
            "dp": cfg["dp_degree"], "mp": cfg["mp_degree"],
            "pp": cfg["pp_degree"], "sharding": cfg["sharding_degree"],
            "sharding_stage": cfg.get("sharding_stage", 1)
            if cfg["sharding_degree"] > 1 else 0,
            "micro_batch_size": cfg["micro_batch_size"],
            "use_recompute": cfg["use_recompute"],
            "predicted_step_time_s": bd["total_s"],
            "compute_s": bd["compute_s"], "bubble_s": bd["bubble_s"],
            "exposed_comm_s": bd["exposed_comm_s"],
            "comm_s_by_axis": bd["comm_s_by_axis"],
            "mem_estimate_gb": round(bd["mem_estimate_bytes"] / 1e9, 3),
            "n_micro": bd["n_micro"],
        }), flush=True)
    top = ranked[0][0] if ranked else None
    line = {
        "metric": f"mesh_plan_shortlist_{cfg_name}",
        "value": len(ranked[:top_k]),
        "unit": "candidates",
        "vs_baseline": 0.0,
        "num_devices": ndev,
        "candidates_ranked": len(ranked),
        "candidates_pruned": len(pruned),
        "overlap_fraction": cm.overlap_fraction,
        "overlap_source": cm.overlap_source,
        "chip": cm.chip,
        "top": (None if top is None else
                f"dp{top['dp_degree']}xpp{top['pp_degree']}"
                f"xsharding{top['sharding_degree']}xmp{top['mp_degree']}"
                f"/mbs{top['micro_batch_size']}"),
    }
    print(json.dumps(line), flush=True)
    return line


def main():
    # --emit-metrics[=path]: step-timeline JSONL alongside the perf line
    # (env-var style config everywhere else; this one is a flag so BENCH
    # driver scripts can toggle it without touching the environment block)
    metrics_path = None
    for a in sys.argv[1:]:
        if a == "--emit-metrics":
            metrics_path = os.environ.get("BENCH_METRICS_PATH",
                                          "bench_metrics.jsonl")
        elif a.startswith("--emit-metrics="):
            metrics_path = a.split("=", 1)[1]
    if metrics_path:
        from paddle_tpu.observability import enable_step_timeline

        enable_step_timeline(jsonl_path=metrics_path)
        print(json.dumps({"metric": "step_timeline_jsonl",
                          "path": metrics_path}), file=sys.stderr)

    backend, init_error = _probe_backend()
    if backend is None:
        # Nothing initialized in this process yet; pin to CPU so the smoke
        # config below cannot touch the wedged tunnel.
        jax.config.update("jax_platforms", "cpu")
        backend = "cpu"
    on_tpu = backend not in ("cpu",)
    if "--plan" in sys.argv[1:]:
        # analytic-only: nothing is measured, so a degraded (wedged-tunnel)
        # run still plans — on CPU, with the v4-class spec fallback
        run_plan(on_tpu and not init_error)
        return
    trace_dir = os.environ.get("BENCH_TRACE_DIR")
    cfg_name = os.environ.get("BENCH_CONFIG")
    matrix = os.environ.get("BENCH_MATRIX")
    if os.environ.get("BENCH_NO_PALLAS"):
        # model-level A/B: force the XLA-composite attention instead of the
        # Pallas kernels (perf attribution on hardware). importlib, because
        # both `from ... import` AND `import ... as` resolve through the
        # package attribute, which the star-import rebound to the
        # same-named FUNCTION.
        import importlib

        _fa_mod = importlib.import_module(
            "paddle_tpu.nn.functional.flash_attention")
        _fa_mod._USE_PALLAS = False

    if matrix:
        import paddle_tpu.distributed as dist

        results = []
        for rung_name, rung in (
                ("llama", run_llama_rung),
                ("bert", run_bert_rung),
                ("resnet", run_resnet_rung),
                ("unet", run_unet_rung),
                ("moe", lambda t: run_moe_rung(t, metrics_path)),
                ("serving", lambda t: run_serving_rung(t, metrics_path)),
                ("serving_quant",
                 lambda t: run_serving_quant_rung(t, metrics_path))):
            try:
                results.append(rung(on_tpu))
            except Exception as e:
                print(json.dumps({"metric": f"{rung_name}_failed",
                                  "error": f"{type(e).__name__}: {e}"[:300]}),
                      flush=True)
            dist.env.set_global_mesh(None)
            _free_rung()  # gc + clear_caches between rungs
        # headline GPT line LAST (drivers read the final line); a degraded
        # (wedged-tunnel) run must never build a TPU-sized config on host
        run_gpt_rung("cpu_smoke" if init_error else cfg_name, on_tpu,
                     init_error, trace_dir)
        return

    if init_error:
        cfg_name = "cpu_smoke"  # degraded: never run a TPU-sized config on host
    if cfg_name == "llama_7bshape":
        run_llama_rung(on_tpu)
    elif cfg_name == "bert_base":
        run_bert_rung(on_tpu)
    elif cfg_name == "resnet50":
        run_resnet_rung(on_tpu)
    elif cfg_name == "unet_sd":
        run_unet_rung(on_tpu)
    elif cfg_name == "gpt3_moe":
        run_moe_rung(on_tpu, metrics_path)
    elif cfg_name == "serving":
        run_serving_rung(on_tpu, metrics_path)
    elif cfg_name == "serving_quant":
        run_serving_quant_rung(on_tpu, metrics_path)
    else:
        run_gpt_rung(cfg_name, on_tpu, init_error, trace_dir)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never exit without the JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "mfu_failed",
            "value": 0.0,
            "unit": "mfu_fraction",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:400],
        }))
        sys.exit(1)
