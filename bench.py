"""Headline benchmark: GPT-3 decoder training step on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric is model FLOPs utilization (MFU) of the full train step
(fwd+bwd+AdamW) — the BASELINE.md north star is >=45% MFU, so
vs_baseline = mfu / 0.45.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import numpy as np

# chip kind -> peak bf16 FLOP/s (public spec sheets)
_PEAK = {
    "TPU v2": 22.5e12,
    "TPU v3": 61.0e12,  # per chip (2 cores)
    "TPU v4": 137.5e12,  # per chip (megacore)
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 229.5e12,
    "TPU v5p": 229.5e12,
    "TPU v6 lite": 459e12,
    "TPU v6e": 459e12,
    "TPU7x": 2307e12,
}


def _peak_flops(device):
    kind = getattr(device, "device_kind", "")
    for k, v in _PEAK.items():
        if kind.startswith(k) or k in kind:
            return v, kind
    # CPU smoke runs / unknown chips: assume v4-class so the line still prints
    return 137.5e12, kind or "unknown"


def _probe_backend(max_tries=2, timeout_s=180.0):
    """Probe accelerator init in a subprocess so a wedged tunnel cannot hang us.

    Round-1 failure modes: (a) 'Unable to initialize backend axon' raised and
    the uncaught exception meant no perf line shipped; (b) the tunnel can also
    simply HANG in init, which no in-process try/except survives. So the probe
    runs `jax.default_backend()` in a child process under a hard timeout; on
    failure the parent forces jax_platforms=cpu BEFORE any in-process backend
    init and degrades to the smoke config.
    Returns (backend_name_or_None, error_or_None).
    """
    import subprocess

    err = None
    for i in range(max_tries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            out = r.stdout.strip().splitlines()
            if r.returncode == 0 and out:
                return out[-1], None
            err = (r.stderr or "").strip()[-300:] or f"probe rc={r.returncode}"
        except subprocess.TimeoutExpired:
            err = f"backend init timed out after {timeout_s:.0f}s (tunnel wedged)"
    return None, err


def main():
    backend, init_error = _probe_backend()
    if backend is None:
        # Nothing initialized in this process yet; pin to CPU so the smoke
        # config below cannot touch the wedged tunnel.
        jax.config.update("jax_platforms", "cpu")
        backend = "cpu"

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import gpt3_1p3b, gpt3_125m, GPTForCausalLM, GPTPretrainingCriterion

    from paddle_tpu.models import gpt3_350m

    on_tpu = backend not in ("cpu",)
    if init_error:
        ladder = ["cpu_smoke"]  # degraded: never run a TPU-sized config on host
    elif os.environ.get("BENCH_CONFIG"):
        ladder = [os.environ["BENCH_CONFIG"]]
    elif on_tpu:
        # try biggest first; a config that cannot compile/fit on this chip
        # (e.g. 1.3B f32 states > v5e HBM) falls through to the next rung
        ladder = ["gpt3_1p3b", "gpt3_350m", "gpt3_125m"]
    else:
        ladder = ["cpu_smoke"]

    def build(cfg_name):
        if cfg_name == "gpt3_1p3b":
            return gpt3_1p3b(max_position_embeddings=2048), 4, 2048, 10
        if cfg_name == "gpt3_350m":
            return gpt3_350m(max_position_embeddings=2048), 8, 2048, 10
        if cfg_name == "gpt3_125m":
            return gpt3_125m(max_position_embeddings=2048), 8, 2048, 10
        from paddle_tpu.models import GPTConfig
        return (GPTConfig(hidden_size=256, num_layers=4, num_heads=4,
                          vocab_size=8192, max_position_embeddings=512),
                2, 256, 3)

    fallback_note = None
    for idx, cfg_name in enumerate(ladder):
        cfg, batch, seq, steps = build(cfg_name)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
        mesh = dist.build_mesh(devices=jax.devices()[:1])
        # bf16 compute with f32 master weights — the production TPU recipe
        step = dist.DistributedTrainStep(
            model, lambda lg, lb: crit(lg, lb), optimizer, mesh=mesh,
            amp_level="O2" if on_tpu else None, amp_dtype="bfloat16")

        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))
        labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))
        try:
            loss = step(ids, labels)  # compile + warmup
            _ = float(loss)
            break
        except Exception as e:
            if idx + 1 >= len(ladder):
                raise
            fallback_note = f"{cfg_name} failed ({type(e).__name__}), fell back"
            dist.env.set_global_mesh(None)
            continue

    # BENCH_TRACE_DIR=<dir>: bracket the timed steps with the profiler so
    # the run ships an XLA device trace + host chrome-trace for analysis
    trace_dir = os.environ.get("BENCH_TRACE_DIR")
    prof = None
    if trace_dir:
        import paddle_tpu.profiler as profiler

        prof = profiler.Profiler(
            device_trace_dir=trace_dir,
            on_trace_ready=profiler.export_chrome_tracing(trace_dir))
        prof.start()

    t0 = time.perf_counter()
    for _i in range(steps):
        loss = step(ids, labels)
        if prof is not None:
            prof.step()
    _ = float(loss)
    dt = (time.perf_counter() - t0) / steps
    if prof is not None:
        prof.stop()

    n_params = cfg.num_params(include_embeddings=False) + cfg.vocab_size * cfg.hidden_size
    tokens = batch * seq
    # 6ND fwd+bwd + attention quadratic term (12*L*h*T^2 per token batch)
    flops = 6.0 * n_params * tokens + 12.0 * cfg.num_layers * cfg.hidden_size * seq * tokens
    peak, kind = _peak_flops(jax.devices()[0])
    mfu = flops / dt / peak
    line = {
        "metric": f"mfu_{cfg_name}_bs{batch}x{seq}_{kind.replace(' ', '_')}",
        "value": round(mfu, 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(mfu / 0.45, 4),
        "tokens_per_sec_per_chip": round(tokens / dt, 1),
        "step_time_s": round(dt, 4),
    }
    if init_error:
        line["error"] = f"degraded to cpu: {init_error}"[:400]
    if fallback_note:
        line["note"] = fallback_note
    print(json.dumps(line))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never exit without the JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "mfu_failed",
            "value": 0.0,
            "unit": "mfu_fraction",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:400],
        }))
        sys.exit(1)
