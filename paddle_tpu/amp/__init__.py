"""AMP: auto_cast, GradScaler, decorate (reference: python/paddle/amp/).

TPU stance: bf16 is the native mixed-precision dtype (no loss scaling needed —
bf16 has f32's exponent range), so GradScaler defaults to a functional no-op
that keeps the reference API (scale/unscale/step/update, dynamic scaling
still implemented for fp16 parity). auto_cast installs a run_op input
interceptor — the analog of the AMP branch in every generated ad_func
(paddle/fluid/imperative/amp_auto_cast.cc).
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.core import Tensor, no_grad, set_op_input_interceptor
from .amp_lists import BLACK_LIST, WHITE_LIST

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate", "GradScaler", "is_float16_supported", "is_bfloat16_supported"]

_amp_state = {"enable": False, "dtype": "bfloat16", "level": "O1",
              "custom_white_list": set(), "custom_black_list": set()}


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


def _interceptor(op_name, values):
    if not _amp_state["enable"]:
        return values
    target = jnp.bfloat16 if _amp_state["dtype"] == "bfloat16" else jnp.float16
    white = (WHITE_LIST | _amp_state["custom_white_list"]) - _amp_state["custom_black_list"]
    black = BLACK_LIST | _amp_state["custom_black_list"]
    level = _amp_state["level"]

    def cast_to(v, d):
        if hasattr(v, "dtype") and v.dtype in (jnp.float32, jnp.bfloat16, jnp.float16) and v.dtype != d:
            return v.astype(d)
        return v

    if op_name in black:
        return [cast_to(v, jnp.float32) for v in values]
    if level == "O2":
        # cast everything float to target except black list
        return [cast_to(v, target) for v in values]
    if op_name in white:
        return [cast_to(v, target) for v in values]
    return values


class auto_cast(contextlib.ContextDecorator):
    """paddle.amp.auto_cast (reference: python/paddle/amp/auto_cast.py:1018)."""

    def __init__(self, enable=True, custom_white_list=None, custom_black_list=None,
                 level="O1", dtype="bfloat16", use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = dtype
        self.white = set(custom_white_list or [])
        self.black = set(custom_black_list or [])

    def __enter__(self):
        self._saved = dict(_amp_state)
        _amp_state.update(
            enable=self.enable, dtype=self.dtype, level=self.level,
            custom_white_list=self.white, custom_black_list=self.black,
        )
        set_op_input_interceptor(_interceptor if self.enable else None)
        return self

    def __exit__(self, *exc):
        _amp_state.update(self._saved)
        set_op_input_interceptor(_interceptor if _amp_state["enable"] else None)
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """paddle.amp.decorate (reference: auto_cast.py:1103) — O2 casts model
    params to the AMP dtype, keeping norm layers in f32."""
    from ..nn.layer.norm import LayerNorm, _BatchNormBase

    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        target = dtype
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if isinstance(layer, (_BatchNormBase, LayerNorm)):
                    continue
                if excluded_layers and isinstance(layer, tuple(excluded_layers)):
                    continue
                for p in layer._parameters.values():
                    if p is not None and p.dtype == np.float32:
                        p._value = p._value.astype(
                            jnp.bfloat16 if target == "bfloat16" else jnp.float16
                        )
    if optimizers is None:
        return models if single else model_list
    opt_single = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if opt_single else list(optimizers)
    for o in opt_list:
        o._multi_precision = True
    return (models if single else model_list), (optimizers if opt_single else opt_list)


amp_decorate = decorate


import jax as _jax


@_jax.jit
def _unscale_and_check(grads, inv):
    scaled = [g * inv.astype(g.dtype) for g in grads]
    found = jnp.any(jnp.stack(
        [jnp.any(~jnp.isfinite(g.astype(jnp.float32))) for g in scaled]))
    return scaled, found


class GradScaler:
    """reference: python/paddle/amp/grad_scaler.py:657. With bf16 (TPU default)
    scaling is the identity; with fp16 the full dynamic-loss-scale state
    machine runs (init_loss_scaling, incr/decr ratios, skip-on-inf)."""

    def __init__(self, enable=True, init_loss_scaling=2.0**16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # Per-optimizer state machine (reference grad_scaler.py:354-373):
        # INIT -> UNSCALED (explicit unscale_) -> STEPPED (step) -> INIT
        # (update). step() skips unscaling when the user already called
        # unscale_(opt); unscale_ after unscale_ or step raises; the
        # finite-check result is tracked per optimizer, not shared.
        self._opt_states = {}  # id(opt) -> [stage, found_inf]

    def scale(self, var):
        if not self._enable or self._scale == 1.0:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        """Fused on-device unscale + finite check: ONE compiled program over
        all grads and ONE device→host sync at the step decision (the
        reference's check_finite_and_unscale kernel,
        paddle/phi/kernels/gpu/check_finite_and_unscale_kernel.cu — NOT a
        per-tensor host round-trip)."""
        if not self._enable:
            return
        st = self._opt_states.setdefault(id(optimizer), [0, False])
        if st[0] != 0:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update().")
        st[0] = 1
        holders = []
        for p in optimizer._parameter_list or []:
            params = p["params"] if isinstance(p, dict) else [p]
            holders.extend(q for q in params if q.grad is not None)
        if not holders:
            st[1] = self._found_inf = False
            return
        grads = [q.grad._value for q in holders]
        scaled, found = _unscale_and_check(
            grads, jnp.float32(1.0 / self._scale))
        if self._scale != 1.0:
            for q, g in zip(holders, scaled):
                q.grad._value = g
        st[1] = self._found_inf = found  # device scalar; synced in step()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        st = self._opt_states.setdefault(id(optimizer), [0, False])
        if st[0] == 2:
            raise RuntimeError(
                "step() has already been called on this optimizer since "
                "the last update().")
        if st[0] == 0:
            self.unscale_(optimizer)
        st[0] = 2
        if bool(st[1]):  # this optimizer's finite check; single host sync
            self._found_inf = True
            self._update_on_inf()
            return
        self._found_inf = False
        optimizer.step()
        self._update_on_good()

    def update(self):
        # paddle's separate update(); scale state already advanced in step()
        self._opt_states.clear()
        return

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def _update_on_good(self):
        if not self._dynamic:
            return
        self._good_steps += 1
        self._bad_steps = 0
        if self._good_steps >= self._incr_every:
            self._scale *= self._incr_ratio
            self._good_steps = 0

    def _update_on_inf(self):
        if not self._dynamic:
            return
        self._bad_steps += 1
        self._good_steps = 0
        if self._bad_steps >= self._decr_every:
            self._scale = max(self._scale * self._decr_ratio, 1.0)
            self._bad_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
            "use_dynamic_loss_scaling": self._dynamic,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)

from . import debugging  # noqa: E402
__all__.append("debugging")
