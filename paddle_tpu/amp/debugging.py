"""Numeric debugging (reference: python/paddle/amp/debugging.py —
TensorCheckerConfig/enable_tensor_checker :56, check_numerics :321,
DebugMode, collect_operator_stats; kernel-side nan/inf scan
paddle/fluid/eager/nan_inf_utils.cc and FLAGS_check_nan_inf).

TPU formulation: the eager dispatcher exposes an op-result hook
(framework.core.set_op_check_hook); enabling the checker installs a
device-side isfinite reduction over every op's outputs and raises (or
logs) with the op name on the first non-finite value. Inside compiled
programs use `check_numerics` directly (it is jit-traceable via
jax.lax.cond-free arithmetic and debug_callback)."""

from __future__ import annotations

import contextlib
from collections import defaultdict
from enum import Enum

import jax
import jax.numpy as jnp

from ..framework import core as _core
from ..framework.core import Tensor

__all__ = [
    "DebugMode",
    "TensorCheckerConfig",
    "enable_tensor_checker",
    "disable_tensor_checker",
    "check_numerics",
    "enable_operator_stats_collection",
    "disable_operator_stats_collection",
    "collect_operator_stats",
]


class DebugMode(Enum):
    """reference debugging.py DebugMode."""

    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


class TensorCheckerConfig:
    """reference debugging.py:56 — enable_check, debug_mode, op black/white
    lists (checked_op_list / skipped_op_list)."""

    def __init__(self, enable, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None, skipped_op_list=None):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])


class NumericError(RuntimeError):
    pass


_findings: list[str] = []


def _iter_values(result):
    if isinstance(result, Tensor):
        yield result._value
    elif isinstance(result, (list, tuple)):
        for r in result:
            yield from _iter_values(r)


def _make_hook(config: TensorCheckerConfig):
    def hook(op_name, result):
        if config.checked_op_list and op_name not in config.checked_op_list:
            return
        if op_name in config.skipped_op_list:
            return
        for v in _iter_values(result):
            if not jnp.issubdtype(v.dtype, jnp.inexact):
                continue
            if isinstance(v, jax.core.Tracer):
                # compiled paths must use check_numerics explicitly — an
                # eager host sync cannot run inside a trace
                continue
            finite = bool(jnp.all(jnp.isfinite(v)))
            if not finite:
                n_nan = int(jnp.sum(jnp.isnan(v)))
                n_inf = int(jnp.sum(jnp.isinf(v)))
                msg = (f"[check_nan_inf] op `{op_name}` produced "
                       f"{n_nan} NaN / {n_inf} Inf values "
                       f"(shape {tuple(v.shape)}, dtype {v.dtype})")
                _findings.append(msg)
                if config.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
                    raise NumericError(msg)
                import warnings

                warnings.warn(msg)

    return hook


_active_config: TensorCheckerConfig | None = None

# The core exposes one op-check hook slot; the checker and the stats
# collector each own a sub-slot here so enabling one never uninstalls the
# other.
_hooks: dict[str, object] = {}


def _sync_hooks():
    if not _hooks:
        _core.set_op_check_hook(None)
        return

    def dispatch(op_name, result):
        for fn in list(_hooks.values()):
            fn(op_name, result)

    _core.set_op_check_hook(dispatch)


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """reference debugging.py enable_tensor_checker (and the
    FLAGS_check_nan_inf runtime flag)."""
    global _active_config
    _active_config = checker_config
    if checker_config.enable:
        _hooks["checker"] = _make_hook(checker_config)
    else:
        _hooks.pop("checker", None)
    _sync_hooks()


def disable_tensor_checker():
    global _active_config
    _active_config = None
    _hooks.pop("checker", None)
    _sync_hooks()


@jax.jit
def _count_stats(v):
    return (jnp.sum(jnp.isnan(v)), jnp.sum(jnp.isinf(v)), jnp.sum(v == 0))


def check_numerics(tensor, op_type="", var_name="",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """One-shot numeric scan of a tensor (reference debugging.py:321).
    Returns (num_nan, num_inf, num_zero) like the reference's stats path —
    one fused device reduction, one host sync."""
    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    n_nan, n_inf, n_zero = _count_stats(v)
    if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT and int(n_nan + n_inf):
        raise NumericError(
            f"[check_numerics] {op_type}:{var_name} has {int(n_nan)} NaN / "
            f"{int(n_inf)} Inf")
    return (n_nan, n_inf, n_zero)


# --------------------------------------------------------------------------- #
# operator stats (reference collect_operator_stats / low-precision op list)
# --------------------------------------------------------------------------- #

_op_stats: defaultdict | None = None


def _stats_hook(op_name, result):
    dtypes = {str(v.dtype) for v in _iter_values(result)}
    for dt in dtypes or {"-"}:
        _op_stats[op_name][dt] += 1


def enable_operator_stats_collection():
    """Count eager op calls per output dtype (reference
    debugging.py enable_operator_stats_collection — used to audit which ops
    ran in fp16/bf16 under AMP)."""
    global _op_stats
    _op_stats = defaultdict(lambda: defaultdict(int))
    _hooks["stats"] = _stats_hook
    _sync_hooks()


def disable_operator_stats_collection():
    _hooks.pop("stats", None)
    _sync_hooks()
    stats = _op_stats
    if stats:
        print("<------------------- op list ------------------->")
        for op, by_dt in sorted(stats.items()):
            counts = ", ".join(f"{dt}: {c}" for dt, c in sorted(by_dt.items()))
            print(f"  {op:<40} {counts}")
        print("<----------------- op list end ----------------->")
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def operator_stats():
    return {k: dict(v) for k, v in (_op_stats or {}).items()}
