"""paddle_tpu: a TPU-native deep-learning framework.

Brand-new design with the capability surface of the PaddlePaddle reference
(/root/reference), built on JAX/XLA/Pallas:

- Tensors wrap jax.Array; XLA owns kernels, layouts, memory (replacing the phi
  kernel registry / allocator stack).
- Eager autograd is a VJP tape (framework/core.py); functional/jit training
  uses jax.grad through paddle_tpu.jit.
- Distributed = named mesh axes + compiled ICI/DCN collectives (paddle_tpu.distributed).
"""

from __future__ import annotations

from . import autograd, framework, tensor
from .autograd import PyLayer, enable_grad, grad, no_grad, set_grad_enabled
from .framework import (
    Parameter,
    Tensor,
    get_default_dtype,
    get_flags,
    load,
    save,
    seed,
    set_default_dtype,
    set_flags,
    to_tensor,
)
from .framework.core import is_grad_enabled
from .framework.dtype import (  # noqa: F401
    bfloat16,
    bool_ as bool,  # noqa: A001
    complex64,
    complex128,
    float16,
    float32,
    float64,
    int8,
    int16,
    int32,
    int64,
    uint8,
)
from .framework.random import get_rng_state, set_rng_state
from .tensor import *  # noqa: F401,F403
from .tensor import linalg  # namespace: paddle.linalg.*
from .tensor.logic import is_tensor


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_custom_device(name: str) -> bool:
    return name in ("tpu",)


def device_count() -> int:
    import jax

    return jax.device_count()


def set_device(device: str):
    # single-controller JAX owns placement; accepted for API parity
    return device


def get_device() -> str:
    import jax

    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


class CPUPlace:
    pass


class TPUPlace:
    def __init__(self, idx: int = 0):
        self.idx = idx


CUDAPlace = TPUPlace  # scripts that name CUDAPlace get the accelerator

# subpackages added as they are built (M2+)
from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import amp  # noqa: E402
from .nn.layer.layers import ParamAttr  # noqa: E402
from . import io  # noqa: E402
from . import metric  # noqa: E402
from . import vision  # noqa: E402
from . import jit  # noqa: E402
from . import hapi  # noqa: E402
from .hapi import Model, flops, summary  # noqa: E402
from . import distributed  # noqa: E402
from .distributed import DataParallel  # noqa: E402
from . import incubate  # noqa: E402
from . import inference  # noqa: E402
from . import profiler  # noqa: E402
from . import observability  # noqa: E402
from . import device  # noqa: E402
from . import fft  # noqa: E402
from . import distribution  # noqa: E402
from . import static  # noqa: E402
from .static import disable_static, enable_static  # noqa: E402
from . import utils  # noqa: E402
from . import sparse  # noqa: E402
from . import quantization  # noqa: E402
from . import audio  # noqa: E402
from . import text  # noqa: E402
from . import onnx  # noqa: E402
from . import signal  # noqa: E402
from . import geometric  # noqa: E402
from . import _C_ops  # noqa: E402  (kernel-level op surface, reference paddle._C_ops)
from . import regularizer  # noqa: E402
from . import sysconfig  # noqa: E402
from . import reader  # noqa: E402
from . import hub  # noqa: E402
from .reader import batch  # noqa: E402
from .hapi import callbacks  # noqa: E402


def in_dynamic_mode():
    """reference paddle.in_dynamic_mode — True outside static building."""
    from . import static as _static

    return not _static.in_static_mode()


def disable_signal_handler():
    """reference paddle.disable_signal_handler — the reference installs
    C++ signal handlers that can conflict with other runtimes; this build
    installs none, so there is nothing to disable (documented no-op)."""


class version:  # noqa: N801 — reference paddle.version module shape
    full_version = "0.4.0"
    major, minor, patch = "0", "4", "0"
    rc = "0"
    cuda_version = "False"
    cudnn_version = "False"
    xpu_version = "False"
    istaged = True
    commit = "tpu-native"

    @staticmethod
    def show():
        print(f"paddle_tpu {version.full_version} (tpu-native; XLA/PJRT)")

    @staticmethod
    def cuda():
        return "False"

    @staticmethod
    def cudnn():
        return "False"


__version__ = version.full_version


def _maybe_install_graftlint_runtime():
    """GRAFTLINT_RUNTIME=1 (raise) / =warn: enforce no-host-sync-under-trace
    at runtime via the sync-observer hook — the dynamic cross-check for the
    static GL001 rule (tools/graftlint, docs/LINTING.md)."""
    import os as _os

    # "0"/"false"/"off" must mean OFF (the conventional env idiom), not
    # "truthy string → strict raise mode"
    if _os.environ.get("GRAFTLINT_RUNTIME", "").strip().lower() in (
            "", "0", "false", "off", "no"):
        return
    try:
        from tools.graftlint import runtime as _glrt
    except ImportError:
        # installed without the repo's tools/ tree alongside — the static
        # linter is a dev-time tool, its absence must not break the package
        import warnings as _warnings

        _warnings.warn(
            "GRAFTLINT_RUNTIME is set but tools.graftlint is not importable; "
            "runtime host-sync checks disabled", RuntimeWarning)
        return
    _glrt.install_runtime_checks()


_maybe_install_graftlint_runtime()
