"""Autograd user API (reference: python/paddle/autograd/).

backward / PyLayer / functional vjp-jvp-jacobian-hessian, re-expressed on the
tape in framework/core.py (the reference's C++ engine: paddle/fluid/eager/).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import (
    GradNode,
    Tensor,
    no_grad,
    enable_grad,
    is_grad_enabled,
    set_grad_enabled,
    run_op,
    to_tensor,
)
from ..framework.core import backward as _backward_impl

__all__ = [
    "backward",
    "grad",
    "PyLayer",
    "PyLayerContext",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "vjp",
    "jvp",
    "jacobian",
    "hessian",
]


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (reference: python/paddle/autograd/autograd.py)."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    for t, g in zip(tensors, grad_tensors):
        _backward_impl(t, g, retain_graph=True)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad: returns grads of outputs wrt inputs without touching .grad
    (reference: python/paddle/base/dygraph/base.py:grad)."""
    single_out = isinstance(outputs, Tensor)
    outs = [outputs] if single_out else list(outputs)
    single_in = isinstance(inputs, Tensor)
    ins = [inputs] if single_in else list(inputs)
    saved = [(t.grad, t.stop_gradient, t._retain_grads) for t in ins]
    for t in ins:
        t.grad = None
        t.stop_gradient = False
        t._retain_grads = True  # deliver grads to intermediates too
    gouts = grad_outputs
    if gouts is None:
        gouts = [None] * len(outs)
    elif isinstance(gouts, Tensor):
        gouts = [gouts]
    try:
        for o, g in zip(outs, gouts):
            _backward_impl(o, g, retain_graph=True)
        results = []
        for t in ins:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        "One of the differentiated tensors appears unused; "
                        "pass allow_unused=True to return None for it"
                    )
                results.append(None)
            else:
                results.append(t.grad)
    finally:
        for t, (g, sg, rg) in zip(ins, saved):
            t.grad = g
            t.stop_gradient = sg
            t._retain_grads = rg
    return results[0] if single_in else results


# --------------------------------------------------------------------------- #
# PyLayer
# --------------------------------------------------------------------------- #


class PyLayerContext:
    """ctx passed to PyLayer.forward/backward
    (reference: python/paddle/autograd/py_layer.py PyLayerContext)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved

    saved_tensors = property(lambda self: self._saved)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined differentiable op (reference: python/paddle/autograd/py_layer.py:PyLayer).

    The recompute / sequence-parallel / MoE-dispatch machinery all build on this,
    exactly as in the reference.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()

        tensor_inputs = []
        for a in args:
            if isinstance(a, Tensor):
                tensor_inputs.append(a)
        for v in kwargs.values():
            if isinstance(v, Tensor):
                tensor_inputs.append(v)

        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]
        out_tensors = [o for o in outs if isinstance(o, Tensor)]

        need_grad = is_grad_enabled() and any(
            (not t.stop_gradient) or t._grad_node is not None for t in tensor_inputs
        )
        if not need_grad:
            return outputs if multi else outs[0]

        avals = [jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype) for o in out_tensors]

        def vjp_fn(cots):
            if not isinstance(cots, tuple):
                cots = (cots,)
            cot_tensors = [Tensor(c) for c in cots]
            with no_grad():
                gin = cls.backward(ctx, *cot_tensors)
            if not isinstance(gin, (tuple, list)):
                gin = (gin,)
            raw = []
            for g in gin:
                if g is None:
                    raw.append(None)
                elif isinstance(g, Tensor):
                    raw.append(g._value)
                else:
                    raw.append(jnp.asarray(g))
            # align with tensor_inputs
            raw = [r for r in raw]
            if len(raw) < len(tensor_inputs):
                raw += [None] * (len(tensor_inputs) - len(raw))
            return tuple(raw[: len(tensor_inputs)])

        node = GradNode(cls.__name__, vjp_fn, tensor_inputs, avals)
        new_outs = []
        node_outs = []
        ti = 0
        for o in outs:
            if isinstance(o, Tensor):
                t = Tensor(o._value, stop_gradient=False)
                t._grad_node = node
                t._out_index = ti
                ti += 1
                new_outs.append(t)
                node_outs.append(t)
            else:
                new_outs.append(o)
        node.set_outputs(node_outs)
        if multi:
            return type(outputs)(new_outs) if isinstance(outputs, tuple) else new_outs
        return new_outs[0]


# --------------------------------------------------------------------------- #
# functional AD (reference: python/paddle/autograd/autograd.py jacobian/hessian,
# python/paddle/incubate/autograd/functional.py vjp/jvp)
# --------------------------------------------------------------------------- #


def _wrap_fn(func):
    def raw(*vals):
        ts = [Tensor(v, stop_gradient=False) for v in vals]
        out = func(*ts)
        if isinstance(out, (tuple, list)):
            return tuple(o._value for o in out)
        return out._value

    return raw


def vjp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    raw = _wrap_fn(func)
    out, f_vjp = jax.vjp(raw, *[x._value for x in xs_l])
    if v is None:
        seed = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(jnp.ones_like(o) for o in out)
    else:
        if isinstance(v, Tensor):
            seed = v._value
        elif isinstance(v, (tuple, list)):
            seed = tuple(t._value for t in v)
        else:
            seed = v
    grads = f_vjp(seed)
    outs = Tensor(out) if not isinstance(out, tuple) else tuple(Tensor(o) for o in out)
    gts = [Tensor(g) for g in grads]
    return outs, (gts[0] if single else gts)


def jvp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    raw = _wrap_fn(func)
    primals = tuple(x._value for x in xs_l)
    if v is None:
        tangents = tuple(jnp.ones_like(p) for p in primals)
    elif isinstance(v, Tensor):
        tangents = (v._value,)
    else:
        tangents = tuple(t._value for t in v)
    out, tang = jax.jvp(raw, primals, tangents)
    outs = Tensor(out) if not isinstance(out, tuple) else tuple(Tensor(o) for o in out)
    tangs = Tensor(tang) if not isinstance(tang, tuple) else tuple(Tensor(t) for t in tang)
    return outs, tangs


def jacobian(func, xs, batch_axis=None):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    raw = _wrap_fn(func)
    jac = jax.jacobian(raw, argnums=tuple(range(len(xs_l))))(*[x._value for x in xs_l])
    if single:
        j = jac[0] if isinstance(jac, tuple) else jac
        return Tensor(j)
    return tuple(Tensor(j) for j in jac)


def hessian(func, xs, batch_axis=None):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    raw = _wrap_fn(func)
    hes = jax.hessian(raw, argnums=tuple(range(len(xs_l))))(*[x._value for x in xs_l])
    if single:
        h = hes[0][0] if isinstance(hes, tuple) else hes
        return Tensor(h)
    return tuple(tuple(Tensor(c) for c in row) for row in hes)
