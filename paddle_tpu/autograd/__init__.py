"""Autograd user API (reference: python/paddle/autograd/).

backward / PyLayer / functional vjp-jvp-jacobian-hessian, re-expressed on the
tape in framework/core.py (the reference's C++ engine: paddle/fluid/eager/).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import (
    GradNode,
    Tensor,
    no_grad,
    enable_grad,
    is_grad_enabled,
    set_grad_enabled,
    run_op,
    to_tensor,
)
from ..framework.core import backward as _backward_impl

__all__ = [
    "backward",
    "grad",
    "PyLayer",
    "PyLayerContext",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "vjp",
    "jvp",
    "jacobian",
    "hessian",
]


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (reference: python/paddle/autograd/autograd.py)."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    for t, g in zip(tensors, grad_tensors):
        _backward_impl(t, g, retain_graph=True)


def _grad_create_graph(outs, ins, gouts, allow_unused):
    """Differentiable grads: replay the recorded forward subgraph as a pure
    jax function of the inputs and take jax.vjp THROUGH one run_op, so the
    returned grads carry their own tape nodes (grad-of-grad works exactly
    like the reference's double_grad ops,
    test/legacy_test/test_imperative_double_grad.py).

    The in-place tape walk (framework.core.backward) computes raw values —
    it cannot record itself; this functional path is the TPU-native
    equivalent of the reference's generated higher-order GradNodes."""
    # collect the forward subgraph
    nodes = {}
    stack = [o._grad_node for o in outs if o._grad_node is not None]
    if not stack:
        raise RuntimeError("create_graph=True requires outputs on the tape")
    while stack:
        node = stack.pop()
        if node.id in nodes:
            continue
        nodes[node.id] = node
        if node.fwd_fn is None:
            raise RuntimeError(
                f"op '{node.name}' recorded no forward fn; cannot build a "
                "differentiable grad graph through it")
        for t in node.inputs:
            if t._grad_node is not None and t._grad_node.id not in nodes:
                stack.append(t._grad_node)
    order = sorted(nodes)  # ascending creation id = forward order

    produced_ids = set()
    for node in nodes.values():
        for wref in node.weak_outputs:
            t = wref()
            if t is not None:
                produced_ids.add(id(t))
    in_ids = {id(t) for t in ins}
    # leaves: subgraph inputs not produced inside it and not differentiated
    leaves, seen = [], set()
    for nid in order:
        for t in nodes[nid].inputs:
            if (id(t) not in produced_ids and id(t) not in in_ids
                    and id(t) not in seen):
                leaves.append(t)
                seen.add(id(t))
    connected = {id(t) for n in nodes.values() for t in n.inputs}
    connected |= produced_ids
    for t in ins:
        if id(t) not in connected and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears unused; "
                "pass allow_unused=True to return None for it")

    nb, ni = len(leaves), len(ins)
    node_list = [nodes[nid] for nid in order]

    def fn(*vals):
        base_vals = vals[:nb]
        in_vals = vals[nb:nb + ni]
        gout_vals = vals[nb + ni:]

        def inner(iv):
            env = {id(t): v for t, v in zip(leaves, base_vals)}
            for t, v in zip(ins, iv):
                env[id(t)] = v
            for node in node_list:
                ivals = [env[id(t)] for t in node.inputs]
                res = node.fwd_fn(*ivals)
                rl = res if isinstance(res, tuple) else (res,)
                for i, wref in enumerate(node.weak_outputs):
                    t = wref()
                    # injected ins keep their independent value even when
                    # re-produced (grad w.r.t. an intermediate holds its
                    # producer fixed)
                    if t is not None and id(t) not in in_ids:
                        env[id(t)] = rl[i]
            return tuple(env[id(o)] for o in outs)

        _, vjp_fn = jax.vjp(inner, tuple(in_vals))
        (gs,) = vjp_fn(tuple(gout_vals))
        # the tape normalizes single outputs to a bare value (run_op's
        # 1-tuple and scalar paths must agree for the second backward)
        return tuple(gs) if len(gs) > 1 else gs[0]

    gout_tensors = [
        g if g is not None else Tensor(jnp.ones_like(o._value))
        for o, g in zip(outs, gouts)]
    res = run_op("grad_replay", fn, list(leaves) + list(ins) + gout_tensors)
    res = list(res) if isinstance(res, tuple) else [res]
    return [r if id(t) in connected else None
            for t, r in zip(ins, res)]


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad: returns grads of outputs wrt inputs without touching .grad
    (reference: python/paddle/base/dygraph/base.py:grad)."""
    single_out = isinstance(outputs, Tensor)
    outs = [outputs] if single_out else list(outputs)
    single_in = isinstance(inputs, Tensor)
    ins = [inputs] if single_in else list(inputs)
    if create_graph:
        gouts_n = grad_outputs
        if gouts_n is None:
            gouts_n = [None] * len(outs)
        elif isinstance(gouts_n, Tensor):
            gouts_n = [gouts_n]
        results = _grad_create_graph(outs, ins, gouts_n, allow_unused)
        return results[0] if single_in else results
    saved = [(t.grad, t.stop_gradient, t._retain_grads) for t in ins]
    for t in ins:
        t.grad = None
        t.stop_gradient = False
        t._retain_grads = True  # deliver grads to intermediates too
    gouts = grad_outputs
    if gouts is None:
        gouts = [None] * len(outs)
    elif isinstance(gouts, Tensor):
        gouts = [gouts]
    try:
        for o, g in zip(outs, gouts):
            _backward_impl(o, g, retain_graph=True)
        results = []
        for t in ins:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        "One of the differentiated tensors appears unused; "
                        "pass allow_unused=True to return None for it"
                    )
                results.append(None)
            else:
                results.append(t.grad)
    finally:
        for t, (g, sg, rg) in zip(ins, saved):
            t.grad = g
            t.stop_gradient = sg
            t._retain_grads = rg
    return results[0] if single_in else results


# --------------------------------------------------------------------------- #
# PyLayer
# --------------------------------------------------------------------------- #


class PyLayerContext:
    """ctx passed to PyLayer.forward/backward
    (reference: python/paddle/autograd/py_layer.py PyLayerContext)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved

    saved_tensors = property(lambda self: self._saved)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined differentiable op (reference: python/paddle/autograd/py_layer.py:PyLayer).

    The recompute / sequence-parallel / MoE-dispatch machinery all build on this,
    exactly as in the reference.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()

        tensor_inputs = []
        for a in args:
            if isinstance(a, Tensor):
                tensor_inputs.append(a)
        for v in kwargs.values():
            if isinstance(v, Tensor):
                tensor_inputs.append(v)

        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]
        out_tensors = [o for o in outs if isinstance(o, Tensor)]

        need_grad = is_grad_enabled() and any(
            (not t.stop_gradient) or t._grad_node is not None for t in tensor_inputs
        )
        if not need_grad:
            return outputs if multi else outs[0]

        avals = [jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype) for o in out_tensors]

        def vjp_fn(cots):
            if not isinstance(cots, tuple):
                cots = (cots,)
            cot_tensors = [Tensor(c) for c in cots]
            with no_grad():
                gin = cls.backward(ctx, *cot_tensors)
            if not isinstance(gin, (tuple, list)):
                gin = (gin,)
            raw = []
            for g in gin:
                if g is None:
                    raw.append(None)
                elif isinstance(g, Tensor):
                    raw.append(g._value)
                else:
                    raw.append(jnp.asarray(g))
            # align with tensor_inputs
            raw = [r for r in raw]
            if len(raw) < len(tensor_inputs):
                raw += [None] * (len(tensor_inputs) - len(raw))
            return tuple(raw[: len(tensor_inputs)])

        node = GradNode(cls.__name__, vjp_fn, tensor_inputs, avals)
        new_outs = []
        node_outs = []
        ti = 0
        for o in outs:
            if isinstance(o, Tensor):
                t = Tensor(o._value, stop_gradient=False)
                t._grad_node = node
                t._out_index = ti
                ti += 1
                new_outs.append(t)
                node_outs.append(t)
            else:
                new_outs.append(o)
        node.set_outputs(node_outs)
        if multi:
            return type(outputs)(new_outs) if isinstance(outputs, tuple) else new_outs
        return new_outs[0]


# --------------------------------------------------------------------------- #
# functional AD (reference: python/paddle/autograd/autograd.py jacobian/hessian,
# python/paddle/incubate/autograd/functional.py vjp/jvp)
# --------------------------------------------------------------------------- #


def _wrap_fn(func):
    def raw(*vals):
        ts = [Tensor(v, stop_gradient=False) for v in vals]
        out = func(*ts)
        if isinstance(out, (tuple, list)):
            return tuple(o._value for o in out)
        return out._value

    return raw


def vjp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    raw = _wrap_fn(func)
    out, f_vjp = jax.vjp(raw, *[x._value for x in xs_l])
    if v is None:
        seed = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(jnp.ones_like(o) for o in out)
    else:
        if isinstance(v, Tensor):
            seed = v._value
        elif isinstance(v, (tuple, list)):
            seed = tuple(t._value for t in v)
        else:
            seed = v
    grads = f_vjp(seed)
    outs = Tensor(out) if not isinstance(out, tuple) else tuple(Tensor(o) for o in out)
    gts = [Tensor(g) for g in grads]
    return outs, (gts[0] if single else gts)


def jvp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    raw = _wrap_fn(func)
    primals = tuple(x._value for x in xs_l)
    if v is None:
        tangents = tuple(jnp.ones_like(p) for p in primals)
    elif isinstance(v, Tensor):
        tangents = (v._value,)
    else:
        tangents = tuple(t._value for t in v)
    out, tang = jax.jvp(raw, primals, tangents)
    outs = Tensor(out) if not isinstance(out, tuple) else tuple(Tensor(o) for o in out)
    tangs = Tensor(tang) if not isinstance(tang, tuple) else tuple(Tensor(t) for t in tang)
    return outs, tangs


def jacobian(func, xs, batch_axis=None):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    raw = _wrap_fn(func)
    jac = jax.jacobian(raw, argnums=tuple(range(len(xs_l))))(*[x._value for x in xs_l])
    if single:
        j = jac[0] if isinstance(jac, tuple) else jac
        return Tensor(j)
    return tuple(Tensor(j) for j in jac)


def hessian(func, xs, batch_axis=None):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    raw = _wrap_fn(func)
    hes = jax.hessian(raw, argnums=tuple(range(len(xs_l))))(*[x._value for x in xs_l])
    if single:
        h = hes[0][0] if isinstance(hes, tuple) else hes
        return Tensor(h)
    return tuple(tuple(Tensor(c) for c in row) for row in hes)
