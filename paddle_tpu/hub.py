"""Model hub over LOCAL repositories (reference: python/paddle/hub.py).

The reference resolves github/gitee sources by downloading; zero-egress
here, so `source="local"` (a directory containing ``hubconf.py``) is the
supported path and remote sources raise with a clear message."""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location(
        f"paddle_tpu_hubconf_{abs(hash(repo_dir))}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access (zero-egress "
            "build); clone the repo and use source='local'")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exported by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n, v in vars(mod).items()
            if callable(v) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"no entrypoint {model!r} in {repo_dir}/hubconf.py")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"no entrypoint {model!r} in {repo_dir}/hubconf.py")
    return fn(**kwargs)
