"""paddle.inference — the serving API.

Reference: paddle/fluid/inference/ (90 k LoC AnalysisPredictor with IR passes,
TensorRT/ONNX sub-engines) + python wrappers python/paddle/inference/.

TPU-native collapse: a saved model is a serialized StableHLO program
(jit.save) — deserialization + XLA compilation replaces the analysis/pass
pipeline, and the TPU is the only execution provider. The Predictor keeps the
reference's handle-based API (get_input_names/get_input_handle/run) so
serving scripts port unchanged.
"""

from __future__ import annotations

import numpy as np

from ..framework.core import Tensor
from .. import jit as _jit

__all__ = ["Config", "Predictor", "create_predictor", "PlaceType", "DataType",
           "create_serving_engine"]


class PlaceType:
    CPU = "cpu"
    GPU = "tpu"  # scripts selecting "GPU" get the accelerator
    TPU = "tpu"


class DataType:
    FLOAT32 = "float32"
    INT64 = "int64"
    INT32 = "int32"


class Config:
    """reference: paddle.inference.Config (analysis config). Only the model
    path plumbing is meaningful on TPU; enable_* toggles are accepted no-ops
    (XLA always compiles/fuses)."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._flags = {}

    def set_prog_file(self, path):
        self._prefix = path[: -len(".pdmodel")] if path.endswith(".pdmodel") else path

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def _noop(self, knob):
        # parity shims must not be SILENT no-ops (they mask user error):
        # one debug line per knob, once
        if knob not in self._flags:
            self._flags[knob] = True
            import logging

            logging.getLogger(__name__).info(
                "inference.Config.%s is a no-op on TPU: device placement, "
                "memory planning and graph optimization are owned by "
                "XLA/PJRT", knob)

    def enable_use_gpu(self, *a, **kw):
        self._noop("enable_use_gpu")

    def enable_memory_optim(self, *a, **kw):
        self._noop("enable_memory_optim")

    def switch_ir_optim(self, *a, **kw):
        self._noop("switch_ir_optim")

    def disable_glog_info(self):
        pass


class _Handle:
    def __init__(self):
        self._data = None

    def copy_from_cpu(self, arr):
        self._data = np.asarray(arr)

    def copy_to_cpu(self):
        return self._data

    def reshape(self, shape):
        if self._data is not None:
            self._data = self._data.reshape(shape)

    def share_external_data(self, arr):
        self.copy_from_cpu(arr)


class Predictor:
    """reference: paddle.inference.Predictor (AnalysisPredictor binding)."""

    def __init__(self, config: Config):
        self._layer = _jit.load(config._prefix)
        if not isinstance(self._layer, _jit.TranslatedLayer):
            raise ValueError(
                f"no saved program at {config.prog_file()}; jit.save with "
                "input_spec produces one")
        n_in = len(self._layer._exported.in_avals)
        self._in_names = [f"x{i}" for i in range(n_in)]
        self._inputs = {n: _Handle() for n in self._in_names}
        self._out_names = []
        self._outputs = {}

    def get_input_names(self):
        return list(self._in_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs=None):
        """Either pass a list of ndarrays, or pre-fill input handles."""
        if inputs is None:
            inputs = [self._inputs[n].copy_to_cpu() for n in self._in_names]
        outs = self._layer(*inputs)
        if isinstance(outs, Tensor):
            outs = [outs]
        outs = [o.numpy() if isinstance(o, Tensor) else np.asarray(o) for o in outs]
        self._out_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {}
        for n, o in zip(self._out_names, outs):
            h = _Handle()
            h.copy_from_cpu(o)
            self._outputs[n] = h
        return outs

    def get_output_names(self):
        return list(self._out_names)

    def get_output_handle(self, name):
        return self._outputs[name]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)

from . import serving  # noqa: E402
from .serving import ContinuousBatchingEngine, GenerationRequest  # noqa: E402


def create_serving_engine(model, paged=True, **kw):
    """Generation engine factory. paged=True (default) builds the
    block-pool `PagedServingEngine` (docs/SERVING.md); paged=False the
    dense-cache `ContinuousBatchingEngine` fallback. Keyword args pass
    through to the chosen engine."""
    if paged:
        from .paged import PagedServingEngine

        return PagedServingEngine(model, **kw)
    return ContinuousBatchingEngine(model, **kw)
