"""Continuous-batching generation engine (the serving-engine depth of
reference L13 — fastdeploy/llm serving's dynamic batching scheduler — on
top of the decode path in models/generation.py).

TPU-first design: ONE compiled decode program of fixed shape
[max_batch_size, 1] runs every step regardless of how many requests are
live — slots hold per-row cache offsets (models/gpt.py _dyn_update /
_decode_mask vector-offset path), so admission/retirement never
recompiles. Prefill pads prompts to power-of-two length buckets to bound
compile count. This is the vLLM/fastdeploy scheduling idea expressed as
static shapes + masking instead of dynamic batch reshaping — the form XLA
wants.
"""

from __future__ import annotations

import collections
import itertools

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["GenerationRequest", "ContinuousBatchingEngine"]


class GenerationRequest:
    """One prompt in flight."""

    _ids = itertools.count()

    def __init__(self, prompt_ids, max_new_tokens=32, temperature=0.0,
                 eos_token_id=None):
        self.req_id = next(self._ids)
        self.prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        self.generated: list[int] = []
        self.done = False

    @property
    def output_ids(self):
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])


def _bucket(n):
    b = 16
    while b < n:
        b *= 2
    return b


class ContinuousBatchingEngine:
    """Admit-while-decoding scheduler over a slotted KV cache.

    add_request() enqueues; step() admits waiting requests into free slots
    (prefill) and advances every live slot by one token (single fixed-shape
    decode). run() drains everything and returns finished requests.
    """

    def __init__(self, model, max_batch_size=8, max_seq_len=512, seed=0):
        model.eval()
        self.model = model
        self.cfg = model.config
        self.B = int(max_batch_size)
        self.S = int(max_seq_len)
        self.params = {k: p._value for k, p in model.named_parameters()}
        self.buffers = {k: b._value for k, b in model.named_buffers()}
        cfg = self.cfg
        self.caches = [
            (jnp.zeros((self.B, self.S, cfg.kv_heads, cfg.head_dim),
                       jnp.float32),) * 2
            for _ in range(cfg.num_layers)]
        self.lengths = np.zeros(self.B, np.int32)   # tokens in each slot
        self.active: list[GenerationRequest | None] = [None] * self.B
        self.last_tok = np.zeros(self.B, np.int32)
        self.waiting: collections.deque = collections.deque()
        self.finished: list[GenerationRequest] = []
        self._key = jax.random.PRNGKey(seed)
        self._prefill_cache = {}
        self._decode_jit = None

    # ------------------------------------------------------------------ #

    def add_request(self, prompt_ids, **kw):
        req = GenerationRequest(prompt_ids, **kw)
        if len(req.prompt) >= self.S:
            raise ValueError(
                f"prompt length {len(req.prompt)} >= max_seq_len {self.S}")
        self.waiting.append(req)
        return req.req_id

    def _functional_forward(self, p, b, tok, pos, caches, off):
        from ..jit import functional_call

        c = [(Tensor(k_), Tensor(v_)) for k_, v_ in caches]
        (logits, new_c), _ = functional_call(
            self.model, p, b, [Tensor(tok), Tensor(pos), c, Tensor(off)],
            train=False)
        return logits, new_c

    # ------------------------------------------------------------------ #

    def _admit(self):
        free = [i for i in range(self.B) if self.active[i] is None]
        while free and self.waiting:
            slot = free.pop(0)
            req = self.waiting.popleft()
            n = len(req.prompt)
            Sp = _bucket(n)
            pf = self._prefill_cache.get(Sp)
            if pf is None:
                def prefill(p, b, tok, pos, caches):
                    # batch-1 prefill with a fresh (zero) cache view
                    logits, new_c = self._functional_forward(
                        p, b, tok, pos, caches, jnp.int32(0))
                    return logits, new_c

                pf = jax.jit(prefill)
                self._prefill_cache[Sp] = pf
            tok = np.zeros((1, Sp), np.int32)
            tok[0, :n] = req.prompt
            pos = np.arange(Sp, dtype=np.int32)[None]
            cfg = self.cfg
            zero_c = [(jnp.zeros((1, Sp, cfg.kv_heads, cfg.head_dim),
                                 jnp.float32),) * 2
                      for _ in range(cfg.num_layers)]
            logits, new_c = pf(self.params, self.buffers,
                               jnp.asarray(tok), jnp.asarray(pos), zero_c)
            # scatter the prompt's kv into this slot's cache rows [0, n)
            for li, (k_, v_) in enumerate(new_c):
                bk, bv = self.caches[li]
                bk = bk.at[slot, :n].set(k_[0, :n])
                bv = bv.at[slot, :n].set(v_[0, :n])
                self.caches[li] = (bk, bv)
            first = self._pick_token(
                np.asarray(logits)[0, n - 1], req)
            self.active[slot] = req
            self.lengths[slot] = n
            self.last_tok[slot] = first
            self._emit(slot, first)

    def _pick_token(self, logits_row, req):
        if req.temperature == 0.0:
            return int(np.argmax(logits_row))
        self._key, sub = jax.random.split(self._key)
        return int(jax.random.categorical(
            sub, jnp.asarray(logits_row) / req.temperature))

    def _emit(self, slot, tok):
        req = self.active[slot]
        req.generated.append(int(tok))
        hit_eos = (req.eos_token_id is not None
                   and int(tok) == req.eos_token_id)
        if (hit_eos or len(req.generated) >= req.max_new_tokens
                or self.lengths[slot] + 1 >= self.S):
            req.done = True
            self.finished.append(req)
            self.active[slot] = None
            self.lengths[slot] = 0

    # ------------------------------------------------------------------ #

    def step(self):
        """One scheduler tick: admit then decode-advance all live slots.
        Returns {req_id: new_token} for tokens produced this tick."""
        self._admit()
        live = [i for i in range(self.B) if self.active[i] is not None]
        if not live:
            return {}
        if self._decode_jit is None:
            def decode(p, b, tok, offs, caches):
                pos = offs[:, None]
                logits, new_c = self._functional_forward(
                    p, b, tok[:, None], pos, caches, offs)
                last = logits[:, -1]
                # greedy tokens picked ON DEVICE: the [B, vocab] logits
                # only cross to host when a sampled-temperature request
                # needs them (jax arrays materialize lazily)
                return jnp.argmax(last, axis=-1).astype(jnp.int32), \
                    last, new_c

            self._decode_jit = jax.jit(decode, donate_argnums=(4,))

        offs = jnp.asarray(self.lengths)  # per-slot write offset
        greedy_tok, logits, self.caches = self._decode_jit(
            self.params, self.buffers, jnp.asarray(self.last_tok), offs,
            self.caches)
        need_logits = any(self.active[i].temperature != 0.0 for i in live)
        greedy_np = np.asarray(greedy_tok)
        logits_np = np.asarray(logits) if need_logits else None
        out = {}
        for i in live:
            req = self.active[i]
            if req.temperature == 0.0:
                tok = int(greedy_np[i])
            else:
                tok = self._pick_token(logits_np[i], req)
            self.lengths[i] += 1
            self.last_tok[i] = tok
            out[req.req_id] = tok
            self._emit(i, tok)
        return out

    def run(self):
        """Drain: step until every queued/live request finishes; returns
        the finished requests in completion order."""
        while self.waiting or any(r is not None for r in self.active):
            self.step()
        done, self.finished = self.finished, []
        return done
