"""Continuous-batching generation engine (the serving-engine depth of
reference L13 — fastdeploy/llm serving's dynamic batching scheduler — on
top of the decode path in models/generation.py).

TPU-first design: ONE compiled decode program of fixed shape
[max_batch_size, 1] runs every step regardless of how many requests are
live — slots hold per-row cache offsets (models/gpt.py _dyn_update /
_decode_mask vector-offset path), so admission/retirement never
recompiles. Prefill pads prompts to power-of-two length buckets to bound
compile count. This is the vLLM/fastdeploy scheduling idea expressed as
static shapes + masking instead of dynamic batch reshaping — the form XLA
wants.

Two engines share the scaffolding in `_ServingEngineBase`:

- `ContinuousBatchingEngine` (this module) — dense per-slot KV caches,
  every slot reserves max_seq_len rows of HBM. Simple, and the fallback
  (`inference.create_serving_engine(..., paged=False)`).
- `PagedServingEngine` (`paddle_tpu.inference.paged`) — block-pool paged
  KV cache with prefix sharing, preemption and a two-queue scheduler; HBM
  is allocated per page actually used, not per slot capacity. See
  docs/SERVING.md.
"""

from __future__ import annotations

import collections
import itertools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from .slo import BoundedCompileCache, serving_metrics

__all__ = ["GenerationRequest", "ContinuousBatchingEngine"]


class GenerationRequest:
    """One prompt in flight."""

    _ids = itertools.count()

    def __init__(self, prompt_ids, max_new_tokens=32, temperature=0.0,
                 eos_token_id=None, priority=0):
        self.req_id = next(self._ids)
        self.prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        # scheduling weight: higher survives preemption longer (paged engine)
        self.priority = int(priority)
        self.generated: list[int] = []
        self.done = False
        # True iff the engine retired this request because the KV cache hit
        # max_seq_len before max_new_tokens/EOS — the output is shorter than
        # asked for (previously this truncation was silent)
        self.truncated = False
        self._t_arrival = time.perf_counter()
        self._t_first: float | None = None
        self._sample_key = None  # set by the admitting engine

    @property
    def output_ids(self):
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])


def _bucket(n):
    b = 16
    while b < n:
        b *= 2
    return b


class _ServingEngineBase:
    """Model state, bucketed prefill compilation, sampling and SLO
    bookkeeping shared by the dense and paged engines. Subclasses own the
    KV representation and the admission policy."""

    engine_label = "base"

    def __init__(self, model, max_batch_size=8, max_seq_len=512, seed=0,
                 max_prefill_buckets=None, serve_w8=None):
        model.eval()
        # weight-only int8 serving (PADDLE_TPU_SERVE_W8, captured HERE —
        # construction is trace time for every program this engine compiles,
        # the PR-7/12/14 toggle rule): swap the model's Linear-family
        # projections for QuantizedLinear before the param/buffer snapshot,
        # so the decode/prefill programs carry int8 weights + f32 scales
        # instead of full-precision weight HBM. In-place on `model`
        # (idempotent) — build a fresh model per engine when A/B-ing.
        if serve_w8 is None:
            serve_w8 = os.environ.get("PADDLE_TPU_SERVE_W8", "0") == "1"
        self.serve_w8 = bool(serve_w8)
        if self.serve_w8:
            from ..quantization import ptq_convert_for_serving

            ptq_convert_for_serving(model)
        self.model = model
        self.cfg = model.config
        self.B = int(max_batch_size)
        self.S = int(max_seq_len)
        if max_prefill_buckets is None:
            # default: room for EVERY bucket this max_seq_len can produce
            # (16, 32, ..., >=S) — a flat cap smaller than the bucket count
            # would thrash full prefill recompiles on a spread-out prompt
            # mix; pass an explicit cap to bound compiled-program memory
            max_prefill_buckets = 1
            while 16 << (max_prefill_buckets - 1) < self.S:
                max_prefill_buckets += 1
        self.params = {k: p._value for k, p in model.named_parameters()}
        self.buffers = {k: b._value for k, b in model.named_buffers()}
        # KV cache dtype flows from the model: a bf16 model gets bf16 pages
        # instead of silently paying 2x KV bytes through a hardcoded f32
        # default (embeddings stay full precision under serve_w8, so this
        # reads the pre-quantization compute dtype)
        self.kv_dtype = next(
            (jnp.dtype(v.dtype) for v in self.params.values()
             if jnp.issubdtype(v.dtype, jnp.floating)),
            jnp.dtype(jnp.float32))
        self.last_logits = None  # last decode tick's [B, vocab] device array
        self.finished: list[GenerationRequest] = []
        self._key = jax.random.PRNGKey(seed)
        self._req_seq = 0  # arrival index, keys each request's sample stream
        self._prefill_cache = BoundedCompileCache(max_prefill_buckets,
                                                  self.engine_label)
        self._decode_jit = None
        m = serving_metrics()
        for name in ("tokens", "requests", "truncations"):
            m[name].inc(0, engine=self.engine_label)  # series exists from t0

    def _make_request(self, prompt_ids, **kw):
        """Construct a request with its own sampling key, folded from the
        engine seed and the ARRIVAL index: sampled output is a function of
        (seed, arrival order, logits) only — invariant to slot assignment,
        batch composition and preemption/resume timing, so the paged and
        dense engines produce identical tokens for the same workload."""
        req = GenerationRequest(prompt_ids, **kw)
        req._sample_key = jax.random.fold_in(self._key, self._req_seq)
        self._req_seq += 1
        return req

    # -- shared forward plumbing ---------------------------------------- #

    def _functional_forward(self, p, b, tok, pos, caches, off, tables=None):
        from ..jit import functional_call

        # per-layer cache entries are (k, v) — or (k, v, k_scale, v_scale)
        # for the quantized paged layout; pass tuples through structurally
        c = [tuple(Tensor(x) for x in layer_c) for layer_c in caches]
        kwargs = {}
        if tables is not None:
            kwargs["block_tables"] = Tensor(tables)
        (logits, new_c), _ = functional_call(
            self.model, p, b, [Tensor(tok), Tensor(pos), c, Tensor(off)],
            kwargs=kwargs, train=False)
        return logits, new_c

    def _run_prefill(self, req):
        """Batch-1 prefill over a zeroed bucket-length dense cache. Returns
        (logits [1, Sp, V] device, new_caches per layer [1, Sp, Hkv, D],
        n, Sp)."""
        n = len(req.prompt)
        Sp = _bucket(n)

        def compile_prefill():
            def prefill(p, b, tok, pos, caches):
                logits, new_c = self._functional_forward(
                    p, b, tok, pos, caches, jnp.int32(0))
                return logits, new_c

            return jax.jit(prefill)

        pf = self._prefill_cache.get_or_compile(Sp, compile_prefill)
        tok = np.zeros((1, Sp), np.int32)
        tok[0, :n] = req.prompt
        pos = np.arange(Sp, dtype=np.int32)[None]
        cfg = self.cfg
        zero_c = [(jnp.zeros((1, Sp, cfg.kv_heads, cfg.head_dim),
                             self.kv_dtype),) * 2
                  for _ in range(cfg.num_layers)]
        logits, new_c = pf(self.params, self.buffers,
                           jnp.asarray(tok), jnp.asarray(pos), zero_c)
        return logits, new_c, n, Sp

    # -- sampling -------------------------------------------------------- #

    def _pick_token(self, logits_row, req):
        """logits_row may be a DEVICE array: greedy argmax and categorical
        sampling both run on device and only the chosen token id crosses to
        host — never the [vocab] row, and never the whole [B, vocab] batch
        (one sampled request used to force that transfer for everyone)."""
        if req.temperature == 0.0:
            return int(jnp.argmax(jnp.asarray(logits_row)))
        req._sample_key, sub = jax.random.split(req._sample_key)
        return int(jax.random.categorical(
            sub, jnp.asarray(logits_row) / req.temperature))

    # -- SLO bookkeeping ------------------------------------------------- #

    def _note_token(self, req, tok):
        m = serving_metrics()
        m["tokens"].inc(engine=self.engine_label)
        if req._t_first is None:
            req._t_first = time.perf_counter()
            m["ttft"].observe(req._t_first - req._t_arrival,
                              engine=self.engine_label)

    def _retire_decision(self, req, tok, row_len):
        """(done, truncated) after appending `tok` with `row_len` tokens
        already in the cache. Capacity retirement that cut the request short
        is surfaced as truncation instead of silently ending it."""
        hit_eos = (req.eos_token_id is not None
                   and int(tok) == req.eos_token_id)
        budget_done = len(req.generated) >= req.max_new_tokens
        cap_hit = row_len + 1 >= self.S
        done = hit_eos or budget_done or cap_hit
        truncated = cap_hit and not hit_eos and not budget_done
        return done, truncated

    def _note_finished(self, req, truncated):
        req.done = True
        m = serving_metrics()
        m["requests"].inc(engine=self.engine_label)
        if truncated:
            req.truncated = True
            m["truncations"].inc(engine=self.engine_label)
        if req._t_first is not None and len(req.generated) > 1:
            dt = time.perf_counter() - req._t_first
            if dt > 0:
                m["request_tps"].observe(len(req.generated) / dt,
                                         engine=self.engine_label)
        self.finished.append(req)

    def run(self):
        """Drain: step until every queued/live request finishes; returns
        the finished requests in completion order."""
        while self.has_work():
            self.step()
        done, self.finished = self.finished, []
        return done

    # subclass contract
    def has_work(self) -> bool:
        raise NotImplementedError

    def step(self) -> dict:
        raise NotImplementedError


class ContinuousBatchingEngine(_ServingEngineBase):
    """Admit-while-decoding scheduler over a slotted DENSE KV cache.

    add_request() enqueues; step() admits waiting requests into free slots
    (prefill) and advances every live slot by one token (single fixed-shape
    decode). run() drains everything and returns finished requests.
    """

    engine_label = "dense"

    def __init__(self, model, max_batch_size=8, max_seq_len=512, seed=0,
                 max_prefill_buckets=None, serve_w8=None):
        super().__init__(model, max_batch_size, max_seq_len, seed,
                         max_prefill_buckets, serve_w8=serve_w8)
        cfg = self.cfg
        self.caches = [
            (jnp.zeros((self.B, self.S, cfg.kv_heads, cfg.head_dim),
                       self.kv_dtype),) * 2
            for _ in range(cfg.num_layers)]
        self.lengths = np.zeros(self.B, np.int32)   # tokens in each slot
        self.active: list[GenerationRequest | None] = [None] * self.B
        self.last_tok = np.zeros(self.B, np.int32)
        self.waiting: collections.deque = collections.deque()

    # ------------------------------------------------------------------ #

    def add_request(self, prompt_ids, **kw):
        req = self._make_request(prompt_ids, **kw)
        if len(req.prompt) >= self.S:
            raise ValueError(
                f"prompt length {len(req.prompt)} >= max_seq_len {self.S}")
        self.waiting.append(req)
        return req.req_id

    def has_work(self):
        return bool(self.waiting) or any(r is not None for r in self.active)

    # ------------------------------------------------------------------ #

    def _admit(self):
        free = [i for i in range(self.B) if self.active[i] is None]
        while free and self.waiting:
            slot = free.pop(0)
            req = self.waiting.popleft()
            logits, new_c, n, _ = self._run_prefill(req)
            # scatter the prompt's kv into this slot's cache rows [0, n)
            for li, (k_, v_) in enumerate(new_c):
                bk, bv = self.caches[li]
                bk = bk.at[slot, :n].set(k_[0, :n])
                bv = bv.at[slot, :n].set(v_[0, :n])
                self.caches[li] = (bk, bv)
            # device row gather: only [vocab] of THIS row ever materializes
            first = self._pick_token(logits[0, n - 1], req)
            self.active[slot] = req
            self.lengths[slot] = n
            self.last_tok[slot] = first
            self._emit(slot, first)

    def _emit(self, slot, tok):
        req = self.active[slot]
        req.generated.append(int(tok))
        self._note_token(req, tok)
        done, truncated = self._retire_decision(req, tok, self.lengths[slot])
        if done:
            self._note_finished(req, truncated)
            self.active[slot] = None
            self.lengths[slot] = 0

    # ------------------------------------------------------------------ #

    def step(self):
        """One scheduler tick: admit then decode-advance all live slots.
        Returns {req_id: new_token} for the decode advance only — each
        request's FIRST token is emitted at admission (onto req.generated
        and serving_tokens_total), not in this dict."""
        t_tick = time.perf_counter()
        self._admit()
        m = serving_metrics()
        live = [i for i in range(self.B) if self.active[i] is not None]
        m["queue_depth"].set(len(self.waiting),
                             engine=self.engine_label, queue="prefill")
        m["queue_depth"].set(len(live),
                             engine=self.engine_label, queue="decode")
        if not live:
            return {}
        if self._decode_jit is None:
            def decode(p, b, tok, offs, caches):
                pos = offs[:, None]
                logits, new_c = self._functional_forward(
                    p, b, tok[:, None], pos, caches, offs)
                last = logits[:, -1]
                # greedy tokens picked ON DEVICE: the [B, vocab] logits
                # only cross to host when a sampled-temperature request
                # needs them (jax arrays materialize lazily)
                return jnp.argmax(last, axis=-1).astype(jnp.int32), \
                    last, new_c

            self._decode_jit = jax.jit(decode, donate_argnums=(4,))

        offs = jnp.asarray(self.lengths)  # per-slot write offset
        greedy_tok, logits, self.caches = self._decode_jit(
            self.params, self.buffers, jnp.asarray(self.last_tok), offs,
            self.caches)
        self.last_logits = logits  # device array; tests probe divergence
        greedy_np = np.asarray(greedy_tok)
        out = {}
        for i in live:
            req = self.active[i]
            if req.temperature == 0.0:
                tok = int(greedy_np[i])
            else:
                # per-row device gather + on-device categorical: only the
                # sampled token id is transferred, not [B, vocab]
                tok = self._pick_token(logits[i], req)
            self.lengths[i] += 1
            self.last_tok[i] = tok
            out[req.req_id] = tok
            self._emit(i, tok)
        m["step_seconds"].observe(time.perf_counter() - t_tick,
                                  engine=self.engine_label)
        return out
