"""Serving SLO instrumentation shared by both generation engines.

Two pieces, both engine-agnostic (an `engine` label distinguishes the dense
`ContinuousBatchingEngine` from the paged `PagedServingEngine`):

- `serving_metrics()` — the serving metric families, declared through the
  PR-3 observability registry via a `HandleCache` so handles survive
  `reset_default_registry()` (tests) without re-taking the declaration lock
  on the hot scheduler path. The catalog lives in docs/OBSERVABILITY.md and
  docs/SERVING.md.
- `BoundedCompileCache` — the per-bucket prefill program cache. Prompts pad
  to power-of-two length buckets so compile count is bounded *per mix*, but
  a pathological prompt-length distribution could still grow one compiled
  program per bucket forever; the cache caps live buckets (oldest-inserted
  evicted — deliberately FIFO, not LRU: an evicted bucket that comes back
  recompiles and the counter shows it) and emits
  `serving_prefill_compiles_total{engine=,bucket=}` on every real compile so
  that growth is visible in the metrics, never silent.
"""

from __future__ import annotations

import collections

from ..observability.metrics import DEFAULT_BUCKETS, HandleCache

__all__ = ["serving_metrics", "BoundedCompileCache"]

# tokens/s per finished request: 0.5 .. 4096, x2 per bucket
_TPS_BUCKETS = tuple(0.5 * 2 ** i for i in range(14))


def _build(reg):
    return {
        "ttft": reg.histogram(
            "serving_ttft_seconds",
            "Time from add_request to the request's first generated token",
            labelnames=("engine",)),
        "request_tps": reg.histogram(
            "serving_request_tokens_per_second",
            "Per finished request: generated tokens / (finish - first token)",
            labelnames=("engine",), buckets=_TPS_BUCKETS),
        "step_seconds": reg.histogram(
            "serving_step_seconds",
            "Wall time of one scheduler tick (admit + decode advance)",
            labelnames=("engine",), buckets=DEFAULT_BUCKETS),
        "tokens": reg.counter(
            "serving_tokens_total", "Generated tokens", ("engine",)),
        "requests": reg.counter(
            "serving_requests_total", "Finished requests", ("engine",)),
        "truncations": reg.counter(
            "serving_truncations_total",
            "Requests retired by KV-cache capacity before max_new_tokens/EOS",
            ("engine",)),
        "queue_depth": reg.gauge(
            "serving_queue_depth",
            "Requests waiting (queue=prefill|resume) or live (queue=decode)",
            ("engine", "queue")),
        "pages_free": reg.gauge(
            "serving_pages_free", "Free physical KV pages in the block pool"),
        "pages_total": reg.gauge(
            "serving_pages_total",
            "Allocatable physical KV pages (excludes the reserved null page)"),
        "kv_bytes_per_token": reg.gauge(
            "serving_kv_bytes_per_token",
            "KV-cache HBM bytes per cached token across all layers and both "
            "K/V sides (int8 payload + amortized per-page scales when the "
            "pool is quantized)"),
        "kv_quant_pages": reg.counter(
            "serving_kv_quant_pages_total",
            "KV pages written through the int8 quantized path (prefill "
            "scatters; decode appends requantize in place)"),
        "prefix_lookups": reg.counter(
            "serving_prefix_lookups_total",
            "Prompt-page hash lookups against the shared-prefix map"),
        "prefix_hits": reg.counter(
            "serving_prefix_hits_total",
            "Prompt pages served by an existing shared page (no new page)"),
        "cow_copies": reg.counter(
            "serving_cow_copies_total",
            "Copy-on-write page copies on first divergent write"),
        "preemptions": reg.counter(
            "serving_preemptions_total",
            "Requests evicted to the host spill buffer when the pool ran dry"),
        "preempted_pages": reg.counter(
            "serving_preempted_pages_total",
            "Pages released by preemption"),
        "resumes": reg.counter(
            "serving_resumes_total",
            "Spilled requests re-admitted from the host buffer"),
        "prefill_compiles": reg.counter(
            "serving_prefill_compiles_total",
            "Prefill program compiles, one per live length bucket",
            ("engine", "bucket")),
    }


_HANDLES = HandleCache(_build)


def serving_metrics() -> dict:
    """Current-registry serving metric handles (rebuilt after registry
    resets; a two-attribute read steady-state)."""
    return _HANDLES.get()


class BoundedCompileCache:
    """{bucket -> compiled program} with an explicit max and FIFO eviction.

    get_or_compile() counts every real compile in
    serving_prefill_compiles_total{engine=,bucket=} — including recompiles of
    a previously evicted bucket, which is exactly the signal that the cap is
    too small for the traffic's prompt-length mix.
    """

    def __init__(self, max_entries: int, engine: str):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_entries = int(max_entries)
        self.engine = engine
        self.compiles_total = 0  # lifetime compiles (bench warmup detection)
        self._programs: collections.OrderedDict = collections.OrderedDict()

    def __len__(self):
        return len(self._programs)

    def __contains__(self, bucket):
        return bucket in self._programs

    def get_or_compile(self, bucket, compile_fn):
        prog = self._programs.get(bucket)
        if prog is not None:
            return prog
        prog = compile_fn()
        self.compiles_total += 1
        serving_metrics()["prefill_compiles"].inc(
            engine=self.engine, bucket=str(bucket))
        self._programs[bucket] = prog
        while len(self._programs) > self.max_entries:
            self._programs.popitem(last=False)  # oldest bucket out
        return prog
