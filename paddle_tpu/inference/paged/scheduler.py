"""Two-queue admission scheduler for the paged serving engine.

Queues:

- **prefill** — waiting `GenerationRequest`s, held in power-of-two length
  buckets (the same buckets the prefill compile cache is keyed by, so queue
  depth per bucket reads directly against
  `serving_prefill_compiles_total{bucket=}`).
- **resume** — preempted requests whose pages were spilled to host; they
  already produced tokens, so they re-admit ahead of fresh prefills.

Admission decisions are made against a **page-budget watermark**: a request
is admitted only if, after taking its (upper-bound) page need, the pool
would still hold `watermark` free pages. The default watermark is one page
per live request — every live row can cross at most one page boundary per
`page_size` decode steps, so this reserve makes same-tick pool exhaustion
(and therefore preemption) the exception rather than the steady state.

Ordering is strict arrival FIFO across buckets, with head-of-line blocking
when the head doesn't fit the budget. Two deliberate consequences: no
starvation (a big request is never overtaken forever by small ones), and
admission order equals the dense engine's — which keeps the sampling-key
stream identical across engines for the same workload, the property the
parity tests pin. Bucket structure is for compile management and
observability, not reordering.
"""

from __future__ import annotations

import collections

from ..serving import _bucket
from ..slo import serving_metrics

__all__ = ["TwoQueueScheduler"]


def _pages_for_prompt(n_tokens: int, page_size: int) -> int:
    return -(-n_tokens // page_size)  # ceil


class TwoQueueScheduler:
    def __init__(self, page_size: int, watermark_pages: int | None = None):
        self.page_size = int(page_size)
        # None -> dynamic: one reserved page per live request (min 1)
        self.watermark_pages = watermark_pages
        self._seq = 0
        # bucket -> deque[(seq, req)]; FIFO within, arrival-merged across
        self.prefill: dict[int, collections.deque] = {}
        self.resume: collections.deque = collections.deque()

    # -- enqueue --------------------------------------------------------- #

    def enqueue_prefill(self, req):
        b = _bucket(len(req.prompt))
        self.prefill.setdefault(b, collections.deque()).append(
            (self._seq, req))
        self._seq += 1

    def enqueue_resume(self, spilled):
        self.resume.append(spilled)

    # -- introspection --------------------------------------------------- #

    @property
    def waiting_prefill(self) -> int:
        return sum(len(d) for d in self.prefill.values())

    @property
    def waiting_resume(self) -> int:
        return len(self.resume)

    def has_waiting(self) -> bool:
        return bool(self.resume) or any(self.prefill.values())

    def update_gauges(self, engine: str, live: int):
        g = serving_metrics()["queue_depth"]
        g.set(self.waiting_prefill, engine=engine, queue="prefill")
        g.set(self.waiting_resume, engine=engine, queue="resume")
        g.set(live, engine=engine, queue="decode")

    # -- admission ------------------------------------------------------- #

    def _watermark(self, live: int) -> int:
        if self.watermark_pages is not None:
            return self.watermark_pages
        return max(1, live)

    def _head_bucket(self):
        """Bucket holding the earliest-arrived waiting request."""
        best = None
        for b, d in self.prefill.items():
            if d and (best is None or d[0][0] < self.prefill[best][0][0]):
                best = b
        return best

    def pick(self, free_rows: int, pages_free: int, live: int) -> list:
        """Admissions for this tick, in order: resumes (FIFO), then prefill
        arrivals (FIFO across buckets). Page needs are charged at their
        upper bound (prefix-sharing hits only under-run the budget). Stops
        at the first request that would dip below the watermark —
        head-of-line blocking by design (see module docstring)."""
        out = []
        budget = pages_free

        def fits(need):
            # live + 1: the reserve must cover the candidate itself once
            # admitted, or the pool runs one page short of the documented
            # one-reserved-page-per-live-request invariant
            if budget - need >= self._watermark(live + 1):
                return True
            # idle-engine fallback: with nothing live and nothing admitted
            # yet, the head request admits whenever it fits AT ALL — a
            # request needing the whole pool must not deadlock an empty
            # engine behind its own watermark
            return live == 0 and not out and budget >= need

        while free_rows and self.resume:
            need = self.resume[0].n_pages
            if not fits(need):
                return out
            sp = self.resume.popleft()
            out.append(sp)
            free_rows -= 1
            live += 1
            budget -= need

        while free_rows:
            b = self._head_bucket()
            if b is None:
                break
            need = _pages_for_prompt(len(self.prefill[b][0][1].prompt),
                                     self.page_size)
            if not fits(need):
                return out
            _, req = self.prefill[b].popleft()
            out.append(req)
            free_rows -= 1
            live += 1
            budget -= need
        return out
