"""Block-pool KV cache manager: fixed-size physical pages, free-list
allocation, refcounted prefix sharing, copy-on-write.

The physical layout is `[n_pages, Hkv, page_size, D]` per layer — exactly
the shape `ops.pallas.decode_attention.paged_decode_attention` consumes, so
the decode program DMAs pages straight from their physical slots (the block
table is a scalar-prefetch operand resolved in the BlockSpec index_map; no
gathered copy of the cache ever materializes).

Host-side metadata (free list, refcounts, prefix map) is plain Python/numpy:
it is touched once per admission / page-boundary crossing / preemption, never
per token, and never inside a trace. Device arrays are immutable jnp values;
every mutation (`.at[...]`) swaps in a fresh array, which composes with the
engine's donated decode program.

Prefix sharing: a prompt page is keyed by the hash of the ENTIRE token
prefix through that page's end — K/V at position i depends on every token
<= i (attention mixes the prefix into the hidden state before the
projections), so two pages are interchangeable iff their full prefixes
match. Partial tail pages therefore only share between prompts with
identical full prefixes of the same length; extending a shorter prompt's
tail page in place is deliberately out of scope (vLLM's partial-block
dedup), see docs/SERVING.md. A shared page is immutable: the engine must
copy-on-write (`copy_page`) before the first divergent write, and a page
that stops being shared (refcount 1) must be unregistered before an
in-place write so a later identical prompt cannot adopt a page that now
holds generated tokens.

Physical page 0 is the reserved NULL page: never allocated, never referenced
by a live block table. Parked decode rows (batch padding) route their
per-step K/V writes there, so the fixed-shape decode program needs no
conditional writes.

Quantized layout (`quantized=True`, the `PADDLE_TPU_KV_QUANT` serving fast
path): page payloads are int8 with one f32 dequant scale per (page, head)
stored alongside (`scales[layer] = (k_scale, v_scale)`, each
[n_pages, Hkv]); dequant is `payload * scale`, fused into the Pallas decode
kernel's page load. Prefill pages quantize with abs-max per (page, head);
decode appends keep a running abs-max per page
(`ops.pallas.decode_attention.paged_kv_write_q8`). Prefix sharing keeps the
SAME full-prefix blake2b keys: quantization is a deterministic function of
page content, so two identical prefixes produce bit-identical int8 payloads
AND scales — a shared page is interchangeable exactly as in the f32 layout,
and COW/spill/restore move payload + scales together, bit-exactly.
"""

from __future__ import annotations

import collections
import hashlib

import jax.numpy as jnp
import numpy as np

from ..slo import serving_metrics

__all__ = ["BlockPool", "prefix_page_key"]


def _quantize_pages(x):
    """[m, Hkv, ps, D] float pages -> (int8 payload, f32 [m, Hkv] scales):
    symmetric abs-max per (page, head), matching paged_kv_write_q8 (±127 so
    running-max rescales never overflow)."""
    from ...ops.pallas.decode_attention import KV_QMAX

    x32 = jnp.asarray(x).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=(2, 3))
    scale = absmax / KV_QMAX
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(x32 / safe[:, :, None, None]),
                 -KV_QMAX, KV_QMAX).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def prefix_page_key(prompt: np.ndarray, page_index: int, page_size: int):
    """Sharing key for prompt page `page_index`: hash of the full token
    prefix through the page's end (clipped to the prompt length)."""
    end = min(len(prompt), (page_index + 1) * page_size)
    return hashlib.blake2b(
        np.ascontiguousarray(prompt[:end], np.int32).tobytes(),
        digest_size=16).digest()


class BlockPool:
    """Fixed pool of physical KV pages shared by every layer's cache."""

    def __init__(self, num_layers, kv_heads, head_dim, page_size, num_pages,
                 dtype=jnp.float32, prefix_sharing=True, quantized=False):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.num_layers = int(num_layers)
        self.kv_heads = int(kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)  # unquantized payload dtype
        self.prefix_sharing = bool(prefix_sharing)
        self.quantized = bool(quantized)
        shape = (self.num_pages, kv_heads, self.page_size, head_dim)
        pay_dtype = jnp.dtype(jnp.int8) if self.quantized else self.dtype
        # immutable jnp zeros: (z,)*2 aliasing is safe, .at[] copies
        self.kv = [(jnp.zeros(shape, pay_dtype),) * 2
                   for _ in range(num_layers)]
        # per-(page, head) f32 dequant scales beside the int8 payloads
        self.scales = ([(jnp.zeros((self.num_pages, kv_heads),
                                   jnp.float32),) * 2
                        for _ in range(num_layers)]
                       if self.quantized else None)
        self.free: collections.deque = collections.deque(
            range(1, self.num_pages))
        self.ref = np.zeros(self.num_pages, np.int32)
        self._prefix: dict[bytes, int] = {}   # key -> page
        self._page_key: dict[int, bytes] = {}  # page -> key (registered only)
        self.allocs_total = 0  # lifetime allocations (tests/introspection)

    # -- accounting ------------------------------------------------------ #

    @staticmethod
    def page_nbytes(num_layers, kv_heads, head_dim, page_size,
                    dtype=jnp.float32, quantized=False) -> int:
        """HBM bytes one physical page costs across all layers and both K/V
        sides — payload plus, when quantized, the per-(page, head) f32
        scales. The unit of the equal-budget serving A/B."""
        if quantized:
            per_side = kv_heads * page_size * head_dim + kv_heads * 4
        else:
            per_side = (kv_heads * page_size * head_dim
                        * jnp.dtype(dtype).itemsize)
        return int(num_layers) * 2 * per_side

    @property
    def bytes_per_page(self) -> int:
        return self.page_nbytes(self.num_layers, self.kv_heads,
                                self.head_dim, self.page_size, self.dtype,
                                self.quantized)

    @property
    def bytes_per_token(self) -> float:
        """KV HBM bytes one cached token costs (all layers, K+V, amortized
        scale overhead) — the `serving_kv_bytes_per_token` series."""
        return self.bytes_per_page / self.page_size

    @property
    def pages_total(self) -> int:
        return self.num_pages - 1  # null page is not allocatable

    @property
    def pages_free(self) -> int:
        return len(self.free)

    def update_gauges(self):
        m = serving_metrics()
        m["pages_free"].set(self.pages_free)
        m["pages_total"].set(self.pages_total)
        m["kv_bytes_per_token"].set(self.bytes_per_token)

    # -- allocation / refcounts ------------------------------------------ #

    def alloc(self) -> int | None:
        """One free page with refcount 1, or None when the pool is dry."""
        if not self.free:
            return None
        page = self.free.popleft()
        self.ref[page] = 1
        self.allocs_total += 1
        return page

    def incref(self, page: int):
        assert self.ref[page] > 0, f"incref on unallocated page {page}"
        self.ref[page] += 1

    def release(self, page: int):
        """Drop one reference; a page at zero is unregistered and freed."""
        assert self.ref[page] > 0, f"release of unallocated page {page}"
        self.ref[page] -= 1
        if self.ref[page] == 0:
            self.unregister_page(page)
            self.free.append(page)

    def is_shared(self, page: int) -> bool:
        return self.ref[page] > 1

    # -- prefix sharing -------------------------------------------------- #

    def lookup_prefix(self, key: bytes | None) -> int | None:
        """Shared page for `key` (increfs on hit), else None."""
        if not self.prefix_sharing or key is None:
            return None
        m = serving_metrics()
        m["prefix_lookups"].inc()
        page = self._prefix.get(key)
        if page is None:
            return None
        self.incref(page)
        m["prefix_hits"].inc()
        return page

    def register_prefix(self, key: bytes, page: int):
        if not self.prefix_sharing or key in self._prefix:
            return
        self._prefix[key] = page
        self._page_key[page] = key

    def is_registered(self, page: int) -> bool:
        return page in self._page_key

    def page_key(self, page: int) -> bytes | None:
        return self._page_key.get(page)

    def unregister_page(self, page: int):
        """Remove a page from the prefix map (before an in-place write, or
        on free) so future lookups cannot adopt diverged content."""
        key = self._page_key.pop(page, None)
        if key is not None:
            self._prefix.pop(key, None)

    # -- device page data ------------------------------------------------ #

    def write_prompt_pages(self, pages, write_mask, k_layers, v_layers):
        """Scatter a prefilled prompt into its pages, all layers.

        pages: the request's m physical pages in logical order; write_mask[j]
        False for shared pages (content already present — identical by key
        construction, so it is never rewritten). k_layers/v_layers: per layer
        [m, Hkv, page_size, D] page-stacked prompt K/V. One batched scatter
        per layer per side. A quantized pool quantizes here (abs-max per
        (page, head)) and scatters payload + scales together."""
        idx = [j for j, w in enumerate(write_mask) if w]
        if not idx:
            return
        tgt = jnp.asarray([pages[j] for j in idx], jnp.int32)
        sel = jnp.asarray(idx, jnp.int32)
        for li in range(self.num_layers):
            k, v = self.kv[li]
            if self.quantized:
                kq, ks = _quantize_pages(k_layers[li][sel])
                vq, vs = _quantize_pages(v_layers[li][sel])
                sk, sv = self.scales[li]
                self.kv[li] = (k.at[tgt].set(kq), v.at[tgt].set(vq))
                self.scales[li] = (sk.at[tgt].set(ks), sv.at[tgt].set(vs))
            else:
                self.kv[li] = (k.at[tgt].set(k_layers[li][sel]),
                               v.at[tgt].set(v_layers[li][sel]))
        if self.quantized:
            serving_metrics()["kv_quant_pages"].inc(len(idx))

    def copy_page(self, src: int, dst: int):
        """Copy-on-write body: duplicate src's content into dst (all
        layers; payload + scales for a quantized pool). Caller owns
        refcount/table updates."""
        for li in range(self.num_layers):
            k, v = self.kv[li]
            self.kv[li] = (k.at[dst].set(k[src]), v.at[dst].set(v[src]))
            if self.quantized:
                sk, sv = self.scales[li]
                self.scales[li] = (sk.at[dst].set(sk[src]),
                                   sv.at[dst].set(sv[src]))
        serving_metrics()["cow_copies"].inc()

    def read_pages(self, pages) -> list[tuple]:
        """Host copies of the given pages, per layer — the preemption spill
        buffer. Unquantized: [(k, v), ...] each [m, Hkv, page_size, D];
        quantized: [(k, v, k_scale, v_scale), ...] with [m, Hkv] scales
        (int8 payload + f32 scales round-trip the host bit-exactly, so a
        spilled quantized request resumes with zero extra error)."""
        idx = jnp.asarray(list(pages), jnp.int32)
        if self.quantized:
            return [(np.asarray(k[idx]), np.asarray(v[idx]),
                     np.asarray(sk[idx]), np.asarray(sv[idx]))
                    for (k, v), (sk, sv) in zip(self.kv, self.scales)]
        return [(np.asarray(k[idx]), np.asarray(v[idx]))
                for k, v in self.kv]

    def restore_pages(self, pages, kv_host, rows):
        """Write spilled host pages back: kv_host is read_pages() output for
        the request's full logical page list; `rows` selects which logical
        indices need restoring (prefix-shared hits don't), `pages` the
        freshly allocated physical destinations, aligned with `rows`."""
        if not pages:
            return
        tgt = jnp.asarray(list(pages), jnp.int32)
        sel = np.asarray(list(rows), np.int32)
        for li in range(self.num_layers):
            k, v = self.kv[li]
            k_h, v_h = kv_host[li][0], kv_host[li][1]
            self.kv[li] = (k.at[tgt].set(jnp.asarray(k_h[sel])),
                           v.at[tgt].set(jnp.asarray(v_h[sel])))
            if self.quantized:
                sk, sv = self.scales[li]
                sk_h, sv_h = kv_host[li][2], kv_host[li][3]
                self.scales[li] = (sk.at[tgt].set(jnp.asarray(sk_h[sel])),
                                   sv.at[tgt].set(jnp.asarray(sv_h[sel])))
