"""Paged-KV serving subsystem (docs/SERVING.md).

- `BlockPool` — fixed-size physical KV pages in the layout the Pallas
  `paged_decode_attention` kernel consumes, with free-list allocation,
  refcounted prefix sharing and copy-on-write; `quantized=True` stores int8
  payloads + per-(page, head) f32 scales for the dequant-fused kernel
  (`PADDLE_TPU_KV_QUANT`).
- `TwoQueueScheduler` — power-of-two prefill length buckets + decode/resume
  queues, admitting against a page-budget watermark.
- `PagedServingEngine` — the continuous-batching engine over both, with
  preemption to a host spill buffer and SLO metrics through the
  observability registry.

The dense `ContinuousBatchingEngine` remains the fallback:
`paddle_tpu.inference.create_serving_engine(model, paged=False)`.
"""

from .block_pool import BlockPool, prefix_page_key
from .engine import PagedServingEngine, SpilledRequest
from .scheduler import TwoQueueScheduler

__all__ = [
    "BlockPool",
    "PagedServingEngine",
    "SpilledRequest",
    "TwoQueueScheduler",
    "prefix_page_key",
]
