"""PagedServingEngine: continuous batching over a block-pool paged KV cache.

The dense `ContinuousBatchingEngine` reserves `max_seq_len` cache rows per
slot, so HBM — not compute — caps concurrent users. Here a slot (decode
program row) holds only a block table; physical pages come from the shared
`BlockPool` on demand. Admission is by *pages available* against the
scheduler's watermark, not by slots free, so at equal HBM budget the engine
runs strictly more concurrent requests whenever prompts are shorter than
`max_seq_len` (and more again when they share prefixes).

Fixed shapes throughout, like the dense engine: ONE compiled decode program
of shape [max_batch_size, 1] runs every tick; the block tables and lengths
are data inputs, so admission/retirement/preemption/COW never recompile.
Page-table maintenance (allocation at page boundaries, copy-on-write off
shared pages, preemption spills) happens on host BETWEEN steps — it is per
page-boundary-crossing, never per token.

Preemption: when the pool runs dry mid-decode, the lowest-priority live
request (newest arrival among equals, never the row that triggered the
allocation) has its pages copied to a host spill buffer and released; the
request re-enters through the scheduler's resume queue and continues
decoding from exactly where it stopped — no tokens are lost or recomputed.
Spilled pages that were prefix-shared re-attach by hash on resume when the
shared copy still exists, and are restored from host otherwise.
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..serving import GenerationRequest, _ServingEngineBase
from ..slo import serving_metrics
from .block_pool import BlockPool, prefix_page_key
from .scheduler import TwoQueueScheduler, _pages_for_prompt

__all__ = ["PagedServingEngine", "SpilledRequest"]


class SpilledRequest:
    """A preempted request parked on host: generation state plus page
    contents, enough to resume without recomputing anything."""

    __slots__ = ("req", "length", "last_tok", "kv_host", "keys")

    def __init__(self, req, length, last_tok, kv_host, keys):
        self.req = req
        self.length = int(length)
        self.last_tok = int(last_tok)
        self.kv_host = kv_host   # per layer (k, v) np [m, Hkv, ps, D]
        self.keys = keys         # per logical page: prefix key or None

    @property
    def n_pages(self) -> int:
        return len(self.keys)


class PagedServingEngine(_ServingEngineBase):
    """Admit-while-decoding over paged KV with prefix sharing + preemption.

    Same surface as the dense engine (`add_request` / `step` / `run`), plus:
    `page_size`, `num_pages` (default: the dense engine's HBM budget,
    `max_batch_size * max_seq_len` tokens worth of pages), `prefix_sharing`,
    `watermark_pages`, `preemption`, and the quantized fast path:
    `kv_quant` (default: the `PADDLE_TPU_KV_QUANT` env toggle, captured at
    construction — trace time for the decode program) stores int8 pages +
    per-(page, head) f32 scales and decodes through the dequant-fused Pallas
    kernel; `kv_budget_bytes` sizes the pool by HBM bytes instead of page
    count (the equal-budget A/B knob — an int8 pool fits ~4x the pages of
    an f32 one in the same budget).
    """

    engine_label = "paged"

    def __init__(self, model, max_batch_size=8, max_seq_len=512, seed=0,
                 page_size=16, num_pages=None, prefix_sharing=True,
                 watermark_pages=None, preemption=True,
                 max_prefill_buckets=None, kv_quant=None,
                 kv_budget_bytes=None, serve_w8=None):
        super().__init__(model, max_batch_size, max_seq_len, seed,
                         max_prefill_buckets, serve_w8=serve_w8)
        cfg = self.cfg
        self.ps = int(page_size)
        self.P = _pages_for_prompt(self.S, self.ps)  # block-table width
        if kv_quant is None:
            kv_quant = os.environ.get("PADDLE_TPU_KV_QUANT", "0") == "1"
        self.kv_quant = bool(kv_quant)
        if num_pages is not None and kv_budget_bytes is not None:
            raise ValueError(
                "pass num_pages OR kv_budget_bytes, not both — a page count "
                "would silently override the byte budget and break the "
                "equal-budget A/B contract")
        if num_pages is None:
            if kv_budget_bytes is not None:
                page_b = BlockPool.page_nbytes(
                    cfg.num_layers, cfg.kv_heads, cfg.head_dim, self.ps,
                    self.kv_dtype, self.kv_quant)
                # budget covers the whole pool, reserved null page included
                num_pages = int(kv_budget_bytes) // page_b
                if num_pages < 2:
                    raise ValueError(
                        f"kv_budget_bytes={int(kv_budget_bytes)} fits "
                        f"{num_pages} pages at {page_b} bytes/page; need >= 2 "
                        "(the reserved null page plus one allocatable) — a "
                        "silently enlarged pool would break the equal-budget "
                        "A/B contract")
            else:
                num_pages = (self.B * self.S) // self.ps + 1  # +1: null page
        self.pool = BlockPool(cfg.num_layers, cfg.kv_heads, cfg.head_dim,
                              self.ps, num_pages, dtype=self.kv_dtype,
                              prefix_sharing=prefix_sharing,
                              quantized=self.kv_quant)
        self.sched = TwoQueueScheduler(self.ps, watermark_pages)
        self.preemption = bool(preemption)
        self.tables = np.full((self.B, self.P), -1, np.int32)
        self.lengths = np.zeros(self.B, np.int32)
        self.active: list[GenerationRequest | None] = [None] * self.B
        self.last_tok = np.zeros(self.B, np.int32)
        self.pool.update_gauges()
        # materialize the pool/preemption series at zero so --emit-metrics
        # JSONL carries them from the first tick, not only after the first
        # event (a dashboard must distinguish "no preemptions" from
        # "no data")
        m = serving_metrics()
        for name in ("preemptions", "resumes", "preempted_pages",
                     "prefix_hits", "prefix_lookups", "cow_copies",
                     "kv_quant_pages"):
            m[name].inc(0)

    # ------------------------------------------------------------------ #

    def add_request(self, prompt_ids, **kw):
        req = self._make_request(prompt_ids, **kw)
        n = len(req.prompt)
        if n >= self.S:
            raise ValueError(
                f"prompt length {n} >= max_seq_len {self.S}")
        # lifetime page need (capacity retirement caps a row at S tokens)
        worst = _pages_for_prompt(min(self.S, n + req.max_new_tokens),
                                  self.ps)
        if worst > self.pool.pages_total:
            raise ValueError(
                f"request needs up to {worst} pages but the pool only has "
                f"{self.pool.pages_total}; grow num_pages or shrink the "
                "request")
        self.sched.enqueue_prefill(req)
        return req.req_id

    def has_work(self):
        return (self.sched.has_waiting()
                or any(r is not None for r in self.active))

    @property
    def live_count(self) -> int:
        return sum(r is not None for r in self.active)

    # -- allocation / preemption ---------------------------------------- #

    def _alloc_or_preempt(self, requester_row=None) -> int:
        while True:
            page = self.pool.alloc()
            if page is not None:
                return page
            if not self.preemption or not self._preempt_lowest(requester_row):
                raise RuntimeError(
                    "KV page pool exhausted with no preemptible request; "
                    "pool is too small for the admitted working set")

    def _preempt_lowest(self, exclude_row) -> bool:
        """Spill the lowest-priority live request (newest arrival among
        equals; never `exclude_row`, whose allocation triggered this)."""
        candidates = [i for i in range(self.B)
                      if self.active[i] is not None and i != exclude_row]
        if not candidates:
            return False
        victim = min(candidates,
                     key=lambda i: (self.active[i].priority,
                                    -self.active[i].req_id))
        self._spill_row(victim)
        return True

    def _spill_row(self, row):
        req = self.active[row]
        pages = [int(p) for p in self.tables[row] if p >= 0]
        kv_host = self.pool.read_pages(pages)
        keys = [self.pool.page_key(p) for p in pages]
        for p in pages:
            self.pool.release(p)
        self.sched.enqueue_resume(SpilledRequest(
            req, self.lengths[row], self.last_tok[row], kv_host, keys))
        self.tables[row, :] = -1
        self.active[row] = None
        self.lengths[row] = 0
        m = serving_metrics()
        m["preemptions"].inc()
        m["preempted_pages"].inc(len(pages))

    def _release_row(self, row):
        for p in self.tables[row]:
            if p >= 0:
                self.pool.release(int(p))
        self.tables[row, :] = -1
        self.active[row] = None
        self.lengths[row] = 0

    # -- admission ------------------------------------------------------- #

    def _admit(self):
        free_rows = [i for i in range(self.B) if self.active[i] is None]
        if not free_rows:
            return
        work = self.sched.pick(len(free_rows), self.pool.pages_free,
                               self.live_count)
        for item in work:
            row = free_rows.pop(0)
            if isinstance(item, SpilledRequest):
                self._resume_into(row, item)
            else:
                self._prefill_into(row, item)

    def _stack_pages(self, arr, n, m):
        """[1, Sp, Hkv, D] prefill K/V -> [m, Hkv, ps, D] page-stacked."""
        a = arr[0, :n]
        pad = m * self.ps - n
        if pad:
            a = jnp.pad(a, ((0, pad), (0, 0), (0, 0)))
        return a.reshape(m, self.ps, a.shape[1], a.shape[2]).transpose(
            0, 2, 1, 3)

    def _prefill_into(self, row, req):
        logits, new_c, n, _ = self._run_prefill(req)
        m = _pages_for_prompt(n, self.ps)
        pages, write_mask = [], []
        for j in range(m):
            key = prefix_page_key(req.prompt, j, self.ps)
            page = self.pool.lookup_prefix(key)
            if page is not None:
                pages.append(page)
                write_mask.append(False)
                continue
            page = self._alloc_or_preempt()
            self.pool.register_prefix(key, page)
            pages.append(page)
            write_mask.append(True)
        if any(write_mask):
            k_layers = [self._stack_pages(k_, n, m) for k_, _ in new_c]
            v_layers = [self._stack_pages(v_, n, m) for _, v_ in new_c]
            self.pool.write_prompt_pages(pages, write_mask,
                                         k_layers, v_layers)
        self.tables[row, :m] = pages
        first = self._pick_token(logits[0, n - 1], req)
        self.active[row] = req
        self.lengths[row] = n
        self.last_tok[row] = first
        self._emit(row, first)

    def _resume_into(self, row, sp: SpilledRequest):
        pages, restore_rows, restore_pages = [], [], []
        for j, key in enumerate(sp.keys):
            page = self.pool.lookup_prefix(key)
            if page is None:
                page = self._alloc_or_preempt()
                if key is not None:
                    self.pool.register_prefix(key, page)
                restore_rows.append(j)
                restore_pages.append(page)
            pages.append(page)
        self.pool.restore_pages(restore_pages, sp.kv_host, restore_rows)
        self.tables[row, :len(pages)] = pages
        self.active[row] = sp.req
        self.lengths[row] = sp.length
        self.last_tok[row] = sp.last_tok
        serving_metrics()["resumes"].inc()

    # -- decode write-target maintenance -------------------------------- #

    def _ensure_write_target(self, row):
        """Guarantee this row can scatter its next K/V: allocate at page
        boundaries, copy-on-write off shared pages, unregister a private
        page before its first divergent write."""
        L = int(self.lengths[row])
        j = L // self.ps
        page = int(self.tables[row, j])
        if page < 0:
            self.tables[row, j] = self._alloc_or_preempt(requester_row=row)
        elif self.pool.is_shared(page):
            dst = self._alloc_or_preempt(requester_row=row)
            self.pool.copy_page(page, dst)
            self.pool.release(page)
            self.tables[row, j] = dst
        elif self.pool.is_registered(page):
            self.pool.unregister_page(page)

    # -- token emission -------------------------------------------------- #

    def _emit(self, row, tok):
        req = self.active[row]
        req.generated.append(int(tok))
        self._note_token(req, tok)
        done, truncated = self._retire_decision(req, tok, self.lengths[row])
        if done:
            self._note_finished(req, truncated)
            self._release_row(row)

    # ------------------------------------------------------------------ #

    def step(self):
        """One scheduler tick: admit (resumes then prefills), ensure every
        live row has a writable page, advance all live rows by one token
        with the single compiled paged-decode program. Returns
        {req_id: new_token} for the decode advance only — each request's
        FIRST token is emitted at admission (onto req.generated and
        serving_tokens_total), not in this dict."""
        t_tick = time.perf_counter()
        self._admit()
        live = [i for i in range(self.B) if self.active[i] is not None]
        self.sched.update_gauges(self.engine_label, len(live))
        self.pool.update_gauges()
        if not live:
            return {}
        for i in live:
            if self.active[i] is not None:  # an earlier COW may have spilled i
                self._ensure_write_target(i)
        live = [i for i in range(self.B) if self.active[i] is not None]
        if not live:
            return {}
        if self._decode_jit is None:
            def decode(p, b, tok, offs, tables, caches):
                pos = offs[:, None]
                logits, new_c = self._functional_forward(
                    p, b, tok[:, None], pos, caches, offs, tables=tables)
                last = logits[:, -1]
                # greedy picked ON DEVICE; [B, vocab] logits stay on device
                # unless a sampled row gathers its own [vocab] slice
                return jnp.argmax(last, axis=-1).astype(jnp.int32), \
                    last, new_c

            self._decode_jit = jax.jit(decode, donate_argnums=(5,))

        # quantized pool: each layer's cache rides as (k, v, k_scale,
        # v_scale) so the int8 append + dequant-fused attention see payload
        # and scales together inside the one compiled program
        caches = ([kv + sc for kv, sc in zip(self.pool.kv, self.pool.scales)]
                  if self.kv_quant else self.pool.kv)
        greedy_tok, logits, new_kv = self._decode_jit(
            self.params, self.buffers, jnp.asarray(self.last_tok),
            jnp.asarray(self.lengths), jnp.asarray(self.tables), caches)
        if self.kv_quant:
            self.pool.kv = [tuple(c[:2]) for c in new_kv]
            self.pool.scales = [tuple(c[2:]) for c in new_kv]
        else:
            self.pool.kv = [tuple(c) for c in new_kv]
        self.last_logits = logits  # device array; tests probe divergence
        greedy_np = np.asarray(greedy_tok)
        out = {}
        for i in live:
            req = self.active[i]
            if req.temperature == 0.0:
                tok = int(greedy_np[i])
            else:
                tok = self._pick_token(logits[i], req)
            self.lengths[i] += 1
            self.last_tok[i] = tok
            out[req.req_id] = tok
            self._emit(i, tok)
        m = serving_metrics()
        m["step_seconds"].observe(time.perf_counter() - t_tick,
                                  engine=self.engine_label)
        self.pool.update_gauges()
        return out
