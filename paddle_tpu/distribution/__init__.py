"""Probability distributions (reference: python/paddle/distribution/ —
distribution.py Distribution base, normal.py, uniform.py, categorical.py,
bernoulli.py, kl.py kl_divergence registry).

TPU formulation: sampling draws keys from the framework RNG
(framework.random) and every density/statistic is a differentiable run_op
over jnp — distributions compose with autograd, jit, and shard_map like any
other op. Reparameterized sampling (rsample) is native: samples are pure
functions of (key, params), so gradients flow to the parameters."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework import random as rnd
from ..framework.core import Tensor, run_op, to_tensor

__all__ = [
    "Distribution",
    "Normal",
    "Uniform",
    "Categorical",
    "Bernoulli",
    "Exponential",
    "kl_divergence",
    "register_kl",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _f32(x):
    t = _t(x)
    if not jnp.issubdtype(t._value.dtype, jnp.floating):
        t = Tensor(t._value.astype(jnp.float32))
    return t


class Distribution:
    """reference: distribution/distribution.py Distribution."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return run_op("dist_prob", lambda lp: jnp.exp(lp),
                      [self.log_prob(value)])

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    """reference: distribution/normal.py Normal (loc/scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _f32(loc)
        self.scale = _f32(scale)
        shape = jnp.broadcast_shapes(self.loc._value.shape,
                                     self.scale._value.shape)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return run_op("normal_var", lambda s: s * s, [self.scale])

    @property
    def stddev(self):
        return self.scale

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(loc, scale):
            eps = jax.random.normal(key, shp, dtype=loc.dtype)
            return loc + scale * eps

        return run_op("normal_rsample", fn, [self.loc, self.scale])

    def log_prob(self, value):
        def fn(v, loc, scale):
            var = scale * scale
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))

        return run_op("normal_log_prob", fn,
                      [_f32(value), self.loc, self.scale])

    def entropy(self):
        def fn(loc, scale):
            return jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale),
                jnp.broadcast_shapes(loc.shape, scale.shape))

        return run_op("normal_entropy", fn, [self.loc, self.scale])

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Uniform(Distribution):
    """reference: distribution/uniform.py Uniform (low/high)."""

    def __init__(self, low, high, name=None):
        self.low = _f32(low)
        self.high = _f32(high)
        shape = jnp.broadcast_shapes(self.low._value.shape,
                                     self.high._value.shape)
        super().__init__(batch_shape=shape)

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(low, high):
            u = jax.random.uniform(key, shp, dtype=low.dtype)
            return low + (high - low) * u

        return run_op("uniform_rsample", fn, [self.low, self.high])

    def log_prob(self, value):
        def fn(v, low, high):
            inside = (v >= low) & (v < high)
            return jnp.where(inside, -jnp.log(high - low), -jnp.inf)

        return run_op("uniform_log_prob", fn,
                      [_f32(value), self.low, self.high])

    def entropy(self):
        return run_op("uniform_entropy",
                      lambda low, high: jnp.log(high - low),
                      [self.low, self.high])


class Categorical(Distribution):
    """reference: distribution/categorical.py Categorical(logits)."""

    def __init__(self, logits, name=None):
        self.logits = _f32(logits)
        super().__init__(batch_shape=self.logits._value.shape[:-1])

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(logits):
            return jax.random.categorical(key, logits, shape=shp)

        return run_op("categorical_sample", fn, [self.logits])

    @staticmethod
    def _gather_last(table, v):
        """table [*B, K] gathered at v [*S, *B] -> [*S, *B] (sample dims
        broadcast against the batch dims)."""
        v = v.astype(jnp.int32)
        tb = jnp.broadcast_to(table, v.shape + table.shape[-1:])
        return jnp.take_along_axis(tb, v[..., None], axis=-1)[..., 0]

    def log_prob(self, value):
        def fn(v, logits):
            return self._gather_last(jax.nn.log_softmax(logits, axis=-1), v)

        return run_op("categorical_log_prob", fn, [_t(value), self.logits])

    def probs(self, value=None):
        p = run_op("categorical_probs",
                   lambda l: jax.nn.softmax(l, axis=-1), [self.logits])
        if value is None:
            return p
        return run_op("categorical_probs_at",
                      lambda pr, v: self._gather_last(pr, v), [p, _t(value)])

    def entropy(self):
        def fn(logits):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

        return run_op("categorical_entropy", fn, [self.logits])


class Bernoulli(Distribution):
    """reference: distribution/bernoulli.py Bernoulli(probs)."""

    def __init__(self, probs, name=None):
        self.probs_t = _f32(probs)
        super().__init__(batch_shape=self.probs_t._value.shape)

    @property
    def mean(self):
        return self.probs_t

    @property
    def variance(self):
        return run_op("bernoulli_var", lambda p: p * (1 - p), [self.probs_t])

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(p):
            return jax.random.bernoulli(key, p, shape=shp).astype(p.dtype)

        return run_op("bernoulli_sample", fn, [self.probs_t])

    def log_prob(self, value):
        def fn(v, p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

        return run_op("bernoulli_log_prob", fn, [_f32(value), self.probs_t])

    def entropy(self):
        def fn(p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

        return run_op("bernoulli_entropy", fn, [self.probs_t])


class Exponential(Distribution):
    """reference: distribution/exponential.py Exponential(rate)."""

    def __init__(self, rate, name=None):
        self.rate = _f32(rate)
        super().__init__(batch_shape=self.rate._value.shape)

    @property
    def mean(self):
        return run_op("exp_mean", lambda r: 1.0 / r, [self.rate])

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(rate):
            u = jax.random.uniform(key, shp, dtype=rate.dtype,
                                   minval=1e-7, maxval=1.0)
            return -jnp.log(u) / rate

        return run_op("exp_rsample", fn, [self.rate])

    def log_prob(self, value):
        def fn(v, rate):
            return jnp.where(v >= 0, jnp.log(rate) - rate * v, -jnp.inf)

        return run_op("exp_log_prob", fn, [_f32(value), self.rate])

    def entropy(self):
        return run_op("exp_entropy", lambda r: 1.0 - jnp.log(r), [self.rate])


# --------------------------------------------------------------------------- #
# KL divergence registry (reference: distribution/kl.py register_kl)
# --------------------------------------------------------------------------- #

_KL_REGISTRY: dict = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    """reference: paddle.distribution.kl_divergence."""
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def fn(l1, s1, l2, s2):
        var1, var2 = s1 * s1, s2 * s2
        return (jnp.log(s2 / s1) + (var1 + (l1 - l2) ** 2) / (2 * var2) - 0.5)

    return run_op("kl_normal", fn, [p.loc, p.scale, q.loc, q.scale])


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    def fn(lp, lq):
        a = jax.nn.log_softmax(lp, axis=-1)
        b = jax.nn.log_softmax(lq, axis=-1)
        return jnp.sum(jnp.exp(a) * (a - b), axis=-1)

    return run_op("kl_categorical", fn, [p.logits, q.logits])


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def fn(al, ah, bl, bh):
        covered = (bl <= al) & (ah <= bh)
        return jnp.where(covered, jnp.log((bh - bl) / (ah - al)), jnp.inf)

    return run_op("kl_uniform", fn, [p.low, p.high, q.low, q.high])


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    def fn(a, b):
        eps = 1e-7
        a = jnp.clip(a, eps, 1 - eps)
        b = jnp.clip(b, eps, 1 - eps)
        return a * (jnp.log(a) - jnp.log(b)) + (1 - a) * (
            jnp.log1p(-a) - jnp.log1p(-b))

    return run_op("kl_bernoulli", fn, [p.probs_t, q.probs_t])


# zoo tail + transforms (import at the end: they subclass Distribution and
# register KLs against the classes above)
from .extras import (  # noqa: E402,F401
    Beta, Gamma, Dirichlet, Laplace, LogNormal, Multinomial, Geometric,
    Gumbel, Cauchy, Poisson, StudentT, Binomial, Independent,
    MultivariateNormal,
)
from . import transform  # noqa: E402,F401
from .transform import (  # noqa: E402,F401
    Transform, AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
    TransformedDistribution,
)

__all__ += [
    "Beta", "Gamma", "Dirichlet", "Laplace", "LogNormal", "Multinomial",
    "Geometric", "Gumbel", "Cauchy", "Poisson", "StudentT", "Binomial",
    "Independent", "MultivariateNormal",
    "transform", "Transform", "AbsTransform", "AffineTransform",
    "ChainTransform", "ExpTransform", "IndependentTransform",
    "PowerTransform", "ReshapeTransform", "SigmoidTransform",
    "SoftmaxTransform", "StackTransform", "StickBreakingTransform",
    "TanhTransform", "TransformedDistribution",
]
