"""Bijective transforms + TransformedDistribution (reference:
python/paddle/distribution/transform.py — Transform base :96,
AffineTransform :418, ChainTransform :482, ExpTransform :556,
PowerTransform :700, SigmoidTransform :1176, SoftmaxTransform :1243,
StackTransform, StickBreakingTransform :1391, TanhTransform :1460,
transformed_distribution.py TransformedDistribution).

TPU formulation: transforms are pure jnp maps, so forward/inverse and both
log-det-Jacobians are differentiable and jit-safe; TransformedDistribution
composes them with any base distribution's log_prob/sample."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op
from . import Distribution, _f32, _t

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "TransformedDistribution",
]


class Transform:
    """reference: transform.py:96. Subclasses implement _forward, _inverse,
    _forward_log_det_jacobian over jnp arrays."""

    _codomain_event_rank = 0
    _domain_event_rank = 0

    def forward(self, x):
        return run_op(f"{type(self).__name__}_fwd",
                      lambda v: self._forward(v), [_f32(x)])

    def inverse(self, y):
        return run_op(f"{type(self).__name__}_inv",
                      lambda v: self._inverse(v), [_f32(y)])

    def forward_log_det_jacobian(self, x):
        return run_op(f"{type(self).__name__}_fldj",
                      lambda v: self._forward_log_det_jacobian(v), [_f32(x)])

    def inverse_log_det_jacobian(self, y):
        return run_op(
            f"{type(self).__name__}_ildj",
            lambda v: -self._forward_log_det_jacobian(self._inverse(v)),
            [_f32(y)])

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # -- jnp-level implementations -------------------------------------- #
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    """y = loc + scale * x (reference :418)."""

    def __init__(self, loc, scale):
        self.loc = _f32(loc)
        self.scale = _f32(scale)

    def _forward(self, x):
        return self.loc._value + self.scale._value * x

    def _inverse(self, y):
        return (y - self.loc._value) / self.scale._value

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(
            jnp.log(jnp.abs(self.scale._value)), jnp.shape(x))


class ExpTransform(Transform):
    """y = exp(x) (reference :556)."""

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power on x > 0 (reference :700)."""

    def __init__(self, power):
        self.power = _f32(power)

    def _forward(self, x):
        return jnp.power(x, self.power._value)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power._value)

    def _forward_log_det_jacobian(self, x):
        p = self.power._value
        return jnp.log(jnp.abs(p * jnp.power(x, p - 1)))


class SigmoidTransform(Transform):
    """y = sigmoid(x) (reference :1176)."""

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    """y = tanh(x) (reference :1460)."""

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2 (log2 - x - softplus(-2x)) — stable form
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    """y = |x| (reference AbsTransform; inverse returns the positive
    branch)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (reference :1243). Not bijective on
    R^k (softmax is shift-invariant); inverse returns log(y) like the
    reference."""

    _codomain_event_rank = 1
    _domain_event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError(
            "SoftmaxTransform is not bijective; no log-det")


class StickBreakingTransform(Transform):
    """R^k -> open (k+1)-simplex by stick breaking (reference :1391)."""

    _codomain_event_rank = 1
    _domain_event_rank = 1

    def _forward(self, x):
        offset = x.shape[-1] - jnp.cumsum(
            jnp.ones_like(x), axis=-1) + 1.0
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zcum = jnp.cumprod(1 - z, axis=-1)
        pad = jnp.ones(x.shape[:-1] + (1,), x.dtype)
        return jnp.concatenate([z, pad], -1) * jnp.concatenate(
            [pad, zcum], -1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        offset = y_crop.shape[-1] - jnp.cumsum(
            jnp.ones_like(y_crop), axis=-1) + 1.0
        sf = 1.0 - jnp.cumsum(y_crop, axis=-1)
        z = y_crop / jnp.concatenate(
            [jnp.ones(y_crop.shape[:-1] + (1,), y.dtype), sf[..., :-1]], -1)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        # triangular Jacobian: dy_i/dx_i = z_i (1-z_i) prod_{j<i} (1-z_j)
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), axis=-1) + 1.0
        xo = x - jnp.log(offset)
        z = jax.nn.sigmoid(xo)
        detail = -jax.nn.softplus(-xo) - jax.nn.softplus(xo)  # log z(1-z)
        csum = jnp.cumsum(jnp.log1p(-z), axis=-1)
        prev = jnp.concatenate(
            [jnp.zeros(x.shape[:-1] + (1,), x.dtype), csum[..., :-1]], -1)
        return (detail + prev).sum(-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ChainTransform(Transform):
    """Composition t_n(...t_1(x)) (reference :482)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ldj = t._forward_log_det_jacobian(x)
            if total is not None:
                # an event-rank-reducing step (e.g. StickBreaking) returns a
                # log-det summed over its event dims; fold the accumulated
                # per-element terms over those dims before adding
                while jnp.ndim(total) > jnp.ndim(ldj):
                    total = total.sum(-1)
                ldj = ldj + total
            total = ldj
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape

    # composed event ranks (reference ChainTransform._domain/_codomain):
    # walking the chain, each step consumes its domain rank and produces
    # its codomain rank; excess rank passes through.
    @property
    def _domain_event_rank(self):
        rank = 0
        for t in reversed(self.transforms):
            rank = t._domain_event_rank + max(
                rank - t._codomain_event_rank, 0)
        return rank

    @property
    def _codomain_event_rank(self):
        rank = 0
        for t in self.transforms:
            rank = t._codomain_event_rank + max(
                rank - t._domain_event_rank, 0)
        return rank


class IndependentTransform(Transform):
    """Reinterprets the rightmost `reinterpreted_batch_rank` dims as event
    dims: the log-det sums over them (reference IndependentTransform)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    @property
    def _domain_event_rank(self):
        return self.base._domain_event_rank + self.rank

    @property
    def _codomain_event_rank(self):
        return self.base._codomain_event_rank + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self.base._forward_log_det_jacobian(x)
        return ldj.sum(axis=tuple(range(-self.rank, 0)))


class ReshapeTransform(Transform):
    """Event reshape (reference ReshapeTransform)."""

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    @property
    def _domain_event_rank(self):
        return len(self.in_event_shape)

    @property
    def _codomain_event_rank(self):
        return len(self.out_event_shape)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        if tuple(shape[len(shape) - n:]) != self.in_event_shape:
            raise ValueError(
                f"expected trailing dims {self.in_event_shape}, got {shape}")
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        if tuple(shape[len(shape) - n:]) != self.out_event_shape:
            raise ValueError(
                f"expected trailing dims {self.out_event_shape}, got {shape}")
        return tuple(shape[:len(shape) - n]) + self.in_event_shape

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)


class StackTransform(Transform):
    """Applies transforms[i] to slice i along `axis` (reference
    StackTransform)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, x, method):
        parts = [
            getattr(t, method)(xi)
            for t, xi in zip(self.transforms,
                             jnp.moveaxis(x, self.axis, 0))
        ]
        return jnp.moveaxis(jnp.stack(parts), 0, self.axis)

    def _forward(self, x):
        return self._map(x, "_forward")

    def _inverse(self, y):
        return self._map(y, "_inverse")

    def _forward_log_det_jacobian(self, x):
        return self._map(x, "_forward_log_det_jacobian")


def _sum_rightmost(t, n):
    """Sum a Tensor over its rightmost n dims (reference
    transformed_distribution.py _sum_rightmost)."""
    if n <= 0:
        return t
    return run_op("sum_rightmost",
                  lambda v: v.sum(axis=tuple(range(-n, 0))), [t])


class TransformedDistribution(Distribution):
    """reference: distribution/transformed_distribution.py — base sample
    pushed through the transform; log_prob via the inverse + log-det,
    with each transform's per-element log-det summed over the event dims
    it is responsible for (the reference's _sum_rightmost bookkeeping)."""

    def __init__(self, base, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self._transforms = list(transforms)
        self.transform = (transforms[0] if len(transforms) == 1
                          else ChainTransform(transforms))
        chain = self.transform
        base_shape = tuple(base.batch_shape) + tuple(base.event_shape)
        if len(base_shape) < chain._domain_event_rank:
            raise ValueError(
                f"base distribution needs at least "
                f"{chain._domain_event_rank} dims, got shape {base_shape}")
        transformed_shape = chain.forward_shape(base_shape)
        event_rank = chain._codomain_event_rank + max(
            len(base.event_shape) - chain._domain_event_rank, 0)
        cut = len(transformed_shape) - event_rank
        super().__init__(batch_shape=tuple(transformed_shape[:cut]),
                         event_shape=tuple(transformed_shape[cut:]))

    def sample(self, shape=()):
        return self.transform.forward(self.base.sample(shape))

    def rsample(self, shape=()):
        return self.transform.forward(self.base.rsample(shape))

    def log_prob(self, value):
        event_rank = len(self.event_shape)
        lp = None
        y = _f32(value)
        for t in reversed(self._transforms):
            x = t.inverse(y)
            event_rank += t._domain_event_rank - t._codomain_event_rank
            term = _sum_rightmost(t.forward_log_det_jacobian(x),
                                  event_rank - t._domain_event_rank)
            lp = term if lp is None else lp + term
            y = x
        base_lp = _sum_rightmost(
            self.base.log_prob(y),
            event_rank - len(self.base.event_shape))
        return base_lp - lp if lp is not None else base_lp
