"""Distribution-zoo tail (reference: python/paddle/distribution/ beta.py,
gamma.py, dirichlet.py, laplace.py, lognormal.py, multinomial.py,
geometric.py, gumbel.py, cauchy.py, poisson.py, binomial.py, student_t.py).

Same TPU formulation as the core zoo: sampling is a pure function of
(framework-RNG key, params) so rsample is reparameterized where the math
allows (jax.random's gamma/beta/dirichlet implement implicit
reparameterization), and every density is a differentiable run_op."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma, gammainc, gammaln, xlogy

from ..framework import random as rnd
from ..framework.core import Tensor, run_op
from . import Distribution, Normal, _f32, _t, register_kl

__all__ = [
    "Beta", "Gamma", "Dirichlet", "Laplace", "LogNormal", "Multinomial",
    "Geometric", "Gumbel", "Cauchy", "Poisson", "StudentT", "Binomial",
    "Independent", "MultivariateNormal",
]


class MultivariateNormal(Distribution):
    """reference: distribution/multivariate_normal.py
    MultivariateNormal(loc, covariance_matrix | precision_matrix |
    scale_tril)."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _f32(loc)
        given = [a for a in (covariance_matrix, precision_matrix, scale_tril)
                 if a is not None]
        if len(given) != 1:
            raise ValueError(
                "exactly one of covariance_matrix / precision_matrix / "
                "scale_tril must be given")
        if scale_tril is not None:
            self.scale_tril = _f32(scale_tril)
        elif covariance_matrix is not None:
            cov = _f32(covariance_matrix)
            self.scale_tril = Tensor(jnp.linalg.cholesky(cov._value))
        else:
            prec = _f32(precision_matrix)
            self.scale_tril = Tensor(
                jnp.linalg.cholesky(jnp.linalg.inv(prec._value)))
        d = self.scale_tril._value.shape[-1]
        batch = jnp.broadcast_shapes(self.loc._value.shape[:-1],
                                     self.scale_tril._value.shape[:-2])
        super().__init__(batch_shape=batch, event_shape=(d,))

    @property
    def mean(self):
        return self.loc

    @property
    def covariance_matrix(self):
        def fn(L):
            return L @ jnp.swapaxes(L, -2, -1)

        return run_op("mvn_cov", fn, [self.scale_tril])

    @property
    def variance(self):
        return run_op("mvn_var",
                      lambda L: jnp.sum(L * L, axis=-1), [self.scale_tril])

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape + self.event_shape

        def fn(loc, L):
            eps = jax.random.normal(key, shp, dtype=loc.dtype)
            return loc + jnp.einsum("...ij,...j->...i", L, eps)

        return run_op("mvn_rsample", fn, [self.loc, self.scale_tril])

    def log_prob(self, value):
        def fn(v, loc, L):
            d = L.shape[-1]
            diff = v - loc
            # broadcast BOTH operands to the common batch shape (value may
            # have sample dims, scale_tril may carry batch dims)
            batch = jnp.broadcast_shapes(diff.shape[:-1], L.shape[:-2])
            diff = jnp.broadcast_to(diff, batch + diff.shape[-1:])
            Lb = jnp.broadcast_to(L, batch + L.shape[-2:])
            z = jax.scipy.linalg.solve_triangular(
                Lb, diff[..., None], lower=True)[..., 0]
            maha = jnp.sum(z * z, axis=-1)
            logdet = jnp.sum(
                jnp.log(jnp.diagonal(Lb, axis1=-2, axis2=-1)), axis=-1)
            return (-0.5 * maha - logdet
                    - 0.5 * d * math.log(2 * math.pi))

        return run_op("mvn_log_prob", fn,
                      [_f32(value), self.loc, self.scale_tril])

    def entropy(self):
        def fn(L):
            d = L.shape[-1]
            logdet = jnp.sum(
                jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)
            return 0.5 * d * (1 + math.log(2 * math.pi)) + logdet

        return run_op("mvn_entropy", fn, [self.scale_tril])


class Independent(Distribution):
    """reference: distribution/independent.py — reinterprets the rightmost
    `reinterpreted_batch_rank` batch dims as event dims (log_prob sums over
    them)."""

    def __init__(self, base, reinterpreted_batch_rank=1):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bshape = tuple(base.batch_shape)
        if self.rank > len(bshape):
            raise ValueError(
                f"reinterpreted_batch_rank {self.rank} exceeds base batch "
                f"rank {len(bshape)}")
        split = len(bshape) - self.rank
        super().__init__(batch_shape=bshape[:split],
                         event_shape=bshape[split:] + tuple(base.event_shape))

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)

        def fn(v):
            return v.sum(axis=tuple(range(-self.rank, 0)))

        return run_op("independent_log_prob", fn, [lp])

    def entropy(self):
        ent = self.base.entropy()

        def fn(v):
            return v.sum(axis=tuple(range(-self.rank, 0)))

        return run_op("independent_entropy", fn, [ent])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance


class Beta(Distribution):
    """reference: distribution/beta.py Beta(alpha, beta)."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _f32(alpha)
        self.beta = _f32(beta)
        shape = jnp.broadcast_shapes(self.alpha._value.shape,
                                     self.beta._value.shape)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return run_op("beta_mean", lambda a, b: a / (a + b),
                      [self.alpha, self.beta])

    @property
    def variance(self):
        def fn(a, b):
            t = a + b
            return a * b / (t * t * (t + 1))

        return run_op("beta_var", fn, [self.alpha, self.beta])

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(a, b):
            return jax.random.beta(key, a, b, shape=shp)

        return run_op("beta_rsample", fn, [self.alpha, self.beta])

    def log_prob(self, value):
        def fn(v, a, b):
            return (xlogy(a - 1, v) + xlogy(b - 1, 1 - v) - betaln(a, b))

        return run_op("beta_log_prob", fn,
                      [_f32(value), self.alpha, self.beta])

    def entropy(self):
        def fn(a, b):
            t = a + b
            return (betaln(a, b) - (a - 1) * digamma(a)
                    - (b - 1) * digamma(b) + (t - 2) * digamma(t))

        return run_op("beta_entropy", fn, [self.alpha, self.beta])


class Gamma(Distribution):
    """reference: distribution/gamma.py Gamma(concentration, rate)."""

    def __init__(self, concentration, rate, name=None):
        self.concentration = _f32(concentration)
        self.rate = _f32(rate)
        shape = jnp.broadcast_shapes(self.concentration._value.shape,
                                     self.rate._value.shape)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return run_op("gamma_mean", lambda c, r: c / r,
                      [self.concentration, self.rate])

    @property
    def variance(self):
        return run_op("gamma_var", lambda c, r: c / (r * r),
                      [self.concentration, self.rate])

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(c, r):
            return jax.random.gamma(key, c, shape=shp) / r

        return run_op("gamma_rsample", fn, [self.concentration, self.rate])

    def log_prob(self, value):
        def fn(v, c, r):
            return (xlogy(c, r) + xlogy(c - 1, v) - r * v - gammaln(c))

        return run_op("gamma_log_prob", fn,
                      [_f32(value), self.concentration, self.rate])

    def entropy(self):
        def fn(c, r):
            return c - jnp.log(r) + gammaln(c) + (1 - c) * digamma(c)

        return run_op("gamma_entropy", fn, [self.concentration, self.rate])

    def cdf(self, value):
        return run_op("gamma_cdf",
                      lambda v, c, r: gammainc(c, r * v),
                      [_f32(value), self.concentration, self.rate])


class Dirichlet(Distribution):
    """reference: distribution/dirichlet.py Dirichlet(concentration)."""

    def __init__(self, concentration, name=None):
        self.concentration = _f32(concentration)
        shape = self.concentration._value.shape
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        return run_op("dirichlet_mean",
                      lambda c: c / c.sum(-1, keepdims=True),
                      [self.concentration])

    @property
    def variance(self):
        def fn(c):
            a0 = c.sum(-1, keepdims=True)
            m = c / a0
            return m * (1 - m) / (a0 + 1)

        return run_op("dirichlet_var", fn, [self.concentration])

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(c):
            return jax.random.dirichlet(key, c, shape=shp)

        return run_op("dirichlet_rsample", fn, [self.concentration])

    def log_prob(self, value):
        def fn(v, c):
            return (xlogy(c - 1, v).sum(-1)
                    + gammaln(c.sum(-1)) - gammaln(c).sum(-1))

        return run_op("dirichlet_log_prob", fn,
                      [_f32(value), self.concentration])

    def entropy(self):
        def fn(c):
            k = c.shape[-1]
            a0 = c.sum(-1)
            lb = gammaln(c).sum(-1) - gammaln(a0)
            return (lb + (a0 - k) * digamma(a0)
                    - ((c - 1) * digamma(c)).sum(-1))

        return run_op("dirichlet_entropy", fn, [self.concentration])


class Laplace(Distribution):
    """reference: distribution/laplace.py Laplace(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _f32(loc)
        self.scale = _f32(scale)
        shape = jnp.broadcast_shapes(self.loc._value.shape,
                                     self.scale._value.shape)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return run_op("laplace_var", lambda s: 2.0 * s * s, [self.scale])

    @property
    def stddev(self):
        return run_op("laplace_std",
                      lambda s: math.sqrt(2.0) * s, [self.scale])

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(loc, scale):
            return loc + scale * jax.random.laplace(key, shp, dtype=loc.dtype)

        return run_op("laplace_rsample", fn, [self.loc, self.scale])

    def log_prob(self, value):
        def fn(v, loc, scale):
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)

        return run_op("laplace_log_prob", fn,
                      [_f32(value), self.loc, self.scale])

    def entropy(self):
        return run_op("laplace_entropy",
                      lambda loc, s: jnp.broadcast_to(
                          1 + jnp.log(2 * s),
                          jnp.broadcast_shapes(loc.shape, s.shape)),
                      [self.loc, self.scale])

    def cdf(self, value):
        def fn(v, loc, s):
            z = (v - loc) / s
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))

        return run_op("laplace_cdf", fn, [_f32(value), self.loc, self.scale])

    def icdf(self, value):
        def fn(p, loc, s):
            a = p - 0.5
            return loc - s * jnp.sign(a) * jnp.log1p(-2 * jnp.abs(a))

        return run_op("laplace_icdf", fn, [_f32(value), self.loc, self.scale])


class LogNormal(Distribution):
    """reference: distribution/lognormal.py LogNormal(loc, scale) — exp of a
    Normal; equals TransformedDistribution(Normal, ExpTransform)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _f32(loc)
        self.scale = _f32(scale)
        self._base = Normal(loc, scale)
        super().__init__(batch_shape=self._base.batch_shape)

    @property
    def mean(self):
        return run_op("lognormal_mean",
                      lambda m, s: jnp.exp(m + s * s / 2),
                      [self.loc, self.scale])

    @property
    def variance(self):
        def fn(m, s):
            s2 = s * s
            return jnp.expm1(s2) * jnp.exp(2 * m + s2)

        return run_op("lognormal_var", fn, [self.loc, self.scale])

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        z = self._base.rsample(shape)
        return run_op("lognormal_rsample", lambda v: jnp.exp(v), [z])

    def log_prob(self, value):
        def fn(v, m, s):
            lv = jnp.log(v)
            return (-((lv - m) ** 2) / (2 * s * s) - jnp.log(s)
                    - 0.5 * math.log(2 * math.pi) - lv)

        return run_op("lognormal_log_prob", fn,
                      [_f32(value), self.loc, self.scale])

    def entropy(self):
        def fn(m, s):
            return m + 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s)

        return run_op("lognormal_entropy", fn, [self.loc, self.scale])


class Multinomial(Distribution):
    """reference: distribution/multinomial.py Multinomial(total_count,
    probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_t = _f32(probs)
        shape = self.probs_t._value.shape
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        return run_op("multinomial_mean",
                      lambda p: self.total_count * p / p.sum(-1, keepdims=True),
                      [self.probs_t])

    @property
    def variance(self):
        def fn(p):
            p = p / p.sum(-1, keepdims=True)
            return self.total_count * p * (1 - p)

        return run_op("multinomial_var", fn, [self.probs_t])

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape
        n = self.total_count

        def fn(p):
            k = p.shape[-1]
            logits = jnp.log(p / p.sum(-1, keepdims=True))
            draws = jax.random.categorical(
                key, logits, axis=-1, shape=(n,) + shp)  # [n, *shp]
            onehot = jax.nn.one_hot(draws, k, dtype=p.dtype)
            return onehot.sum(0)

        return run_op("multinomial_sample", fn, [self.probs_t])

    def log_prob(self, value):
        def fn(v, p):
            logp = jnp.log(p / p.sum(-1, keepdims=True))
            coeff = gammaln(jnp.asarray(self.total_count + 1.0)) - gammaln(
                v + 1.0).sum(-1)
            return coeff + (v * logp).sum(-1)

        return run_op("multinomial_log_prob", fn,
                      [_f32(value), self.probs_t])


class Geometric(Distribution):
    """reference: distribution/geometric.py Geometric(probs) — counts k in
    {0, 1, ...} of failures before the first success."""

    def __init__(self, probs, name=None):
        self.probs_t = _f32(probs)
        super().__init__(batch_shape=self.probs_t._value.shape)

    @property
    def mean(self):
        return run_op("geometric_mean", lambda p: (1 - p) / p, [self.probs_t])

    @property
    def variance(self):
        return run_op("geometric_var", lambda p: (1 - p) / (p * p),
                      [self.probs_t])

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(p):
            u = jax.random.uniform(key, shp, dtype=p.dtype,
                                   minval=1e-7, maxval=1.0)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))

        return run_op("geometric_sample", fn, [self.probs_t])

    def log_prob(self, value):
        def fn(v, p):
            return xlogy(v, 1 - p) + jnp.log(p)

        return run_op("geometric_log_prob", fn, [_f32(value), self.probs_t])

    def entropy(self):
        def fn(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p

        return run_op("geometric_entropy", fn, [self.probs_t])


class Gumbel(Distribution):
    """reference: distribution/gumbel.py Gumbel(loc, scale)."""

    _EULER = 0.57721566490153286060

    def __init__(self, loc, scale, name=None):
        self.loc = _f32(loc)
        self.scale = _f32(scale)
        shape = jnp.broadcast_shapes(self.loc._value.shape,
                                     self.scale._value.shape)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return run_op("gumbel_mean",
                      lambda m, s: m + self._EULER * s,
                      [self.loc, self.scale])

    @property
    def variance(self):
        return run_op("gumbel_var",
                      lambda s: (math.pi ** 2 / 6.0) * s * s, [self.scale])

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(m, s):
            return m + s * jax.random.gumbel(key, shp, dtype=m.dtype)

        return run_op("gumbel_rsample", fn, [self.loc, self.scale])

    def log_prob(self, value):
        def fn(v, m, s):
            z = (v - m) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)

        return run_op("gumbel_log_prob", fn,
                      [_f32(value), self.loc, self.scale])

    def entropy(self):
        return run_op("gumbel_entropy",
                      lambda m, s: jnp.broadcast_to(
                          jnp.log(s) + 1 + self._EULER,
                          jnp.broadcast_shapes(m.shape, s.shape)),
                      [self.loc, self.scale])


class Cauchy(Distribution):
    """reference: distribution/cauchy.py Cauchy(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _f32(loc)
        self.scale = _f32(scale)
        shape = jnp.broadcast_shapes(self.loc._value.shape,
                                     self.scale._value.shape)
        super().__init__(batch_shape=shape)

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(m, s):
            return m + s * jax.random.cauchy(key, shp, dtype=m.dtype)

        return run_op("cauchy_rsample", fn, [self.loc, self.scale])

    def log_prob(self, value):
        def fn(v, m, s):
            z = (v - m) / s
            return -jnp.log(math.pi * s * (1 + z * z))

        return run_op("cauchy_log_prob", fn,
                      [_f32(value), self.loc, self.scale])

    def entropy(self):
        return run_op("cauchy_entropy",
                      lambda m, s: jnp.broadcast_to(
                          jnp.log(4 * math.pi * s),
                          jnp.broadcast_shapes(m.shape, s.shape)),
                      [self.loc, self.scale])

    def cdf(self, value):
        def fn(v, m, s):
            return jnp.arctan((v - m) / s) / math.pi + 0.5

        return run_op("cauchy_cdf", fn, [_f32(value), self.loc, self.scale])


class Poisson(Distribution):
    """reference: distribution/poisson.py Poisson(rate)."""

    def __init__(self, rate, name=None):
        self.rate = _f32(rate)
        super().__init__(batch_shape=self.rate._value.shape)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(r):
            return jax.random.poisson(key, r, shape=shp).astype(r.dtype)

        return run_op("poisson_sample", fn, [self.rate])

    def log_prob(self, value):
        def fn(v, r):
            return xlogy(v, r) - r - gammaln(v + 1.0)

        return run_op("poisson_log_prob", fn, [_f32(value), self.rate])


class StudentT(Distribution):
    """reference: distribution/student_t.py StudentT(df, loc, scale)."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _f32(df)
        self.loc = _f32(loc)
        self.scale = _f32(scale)
        shape = jnp.broadcast_shapes(self.df._value.shape,
                                     self.loc._value.shape,
                                     self.scale._value.shape)
        super().__init__(batch_shape=shape)

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape

        def fn(df, m, s):
            return m + s * jax.random.t(key, df, shape=shp)

        return run_op("studentt_rsample", fn, [self.df, self.loc, self.scale])

    def log_prob(self, value):
        def fn(v, df, m, s):
            z = (v - m) / s
            return (gammaln((df + 1) / 2) - gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))

        return run_op("studentt_log_prob", fn,
                      [_f32(value), self.df, self.loc, self.scale])


class Binomial(Distribution):
    """reference: distribution/binomial.py Binomial(total_count, probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_t = _f32(probs)
        super().__init__(batch_shape=self.probs_t._value.shape)

    @property
    def mean(self):
        return run_op("binomial_mean",
                      lambda p: self.total_count * p, [self.probs_t])

    @property
    def variance(self):
        return run_op("binomial_var",
                      lambda p: self.total_count * p * (1 - p),
                      [self.probs_t])

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = tuple(shape) + self.batch_shape
        n = self.total_count

        def fn(p):
            draws = jax.random.bernoulli(
                key, p, shape=(n,) + shp)
            return draws.astype(p.dtype).sum(0)

        return run_op("binomial_sample", fn, [self.probs_t])

    def log_prob(self, value):
        def fn(v, p):
            n = float(self.total_count)
            coeff = (gammaln(jnp.asarray(n + 1.0)) - gammaln(v + 1.0)
                     - gammaln(n - v + 1.0))
            return coeff + xlogy(v, p) + xlogy(n - v, 1 - p)

        return run_op("binomial_log_prob", fn, [_f32(value), self.probs_t])


# --------------------------------------------------------------------------- #
# KLs for the new zoo (reference: distribution/kl.py)
# --------------------------------------------------------------------------- #


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def fn(a1, b1, a2, b2):
        t1 = a1 + b1
        return (betaln(a2, b2) - betaln(a1, b1)
                + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
                + (a2 - a1 + b2 - b1) * digamma(t1))

    return run_op("kl_beta", fn, [p.alpha, p.beta, q.alpha, q.beta])


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    def fn(c1, r1, c2, r2):
        return (gammaln(c2) - gammaln(c1) + (c1 - c2) * digamma(c1)
                + c2 * (jnp.log(r1) - jnp.log(r2)) + c1 * (r2 - r1) / r1)

    return run_op("kl_gamma", fn,
                  [p.concentration, p.rate, q.concentration, q.rate])


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def fn(c1, c2):
        a0 = c1.sum(-1)
        return (gammaln(a0) - gammaln(c1).sum(-1)
                - gammaln(c2.sum(-1)) + gammaln(c2).sum(-1)
                + ((c1 - c2) * (digamma(c1)
                                - digamma(a0)[..., None])).sum(-1))

    return run_op("kl_dirichlet", fn, [p.concentration, q.concentration])


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    def fn(m1, s1, m2, s2):
        d = jnp.abs(m1 - m2)
        return (jnp.log(s2 / s1) + d / s2
                + s1 / s2 * jnp.exp(-d / s1) - 1)

    return run_op("kl_laplace", fn, [p.loc, p.scale, q.loc, q.scale])
