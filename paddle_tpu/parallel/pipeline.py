"""Compiled pipeline parallelism over a `pp` mesh axis.

Reference analog: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py — the hand-scheduled 1F1B loop (forward_backward_pipeline
:684) where pp ranks are processes exchanging activations via batched NCCL
send/recv (pp_utils/p2p_communication.py).

TPU-native redesign: the schedule is *compiled into one XLA program*. Stages
live on the `pp` axis of the device mesh; each tick of a `lax.scan` applies
the local stage to its current microbatch and `ppermute`s the activations one
stage forward over ICI. Stage 0 injects a fresh microbatch per tick; the last
stage's outputs are collected from the scan ys. With `jax.checkpoint` around
the stage body the backward pass recomputes stage activations per microbatch,
which gives 1F1B's peak-memory behavior while XLA owns the overlap of
compute and collective-permute DMA — the steady-state overlap the reference
schedules by hand in Python.

Schedule shape: GPipe-style fill/drain over T = M + S - 1 ticks (M
microbatches, S stages) — bubble fraction (S-1)/T, identical to 1F1B; choose
M >= 4*S to keep the bubble small. Interleaved/VPP parity note: virtual
stages would add a chunk dimension to the stacked params and V inner
applications per tick; the memory win it buys the reference is already
covered here by remat.

Only the `pp` axis is manual (shard_map axis_names={'pp'}); dp/mp/sharding
remain auto axes, so GSPMD still inserts TP/DP collectives inside the stage
body from the usual sharding constraints.
"""

from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def stack_pytrees(trees):
    """Stack a list of identical-structure pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def unstack_leading(tree, n):
    """Inverse of stack_pytrees: one pytree per leading index."""
    return [jax.tree.map(lambda a, i=i: a[i], tree) for i in range(n)]


def pipeline_spmd(stage_fn, stacked_params, inputs_mb, *, mesh, axis="pp",
                  remat=True):
    """Run microbatches through a compiled stage pipeline.

    Args:
      stage_fn: (stage_params, inputs) -> outputs. `stage_params` is
        `stacked_params` with the leading stage dim removed. `outputs` must
        have the same pytree structure/shapes/dtypes as `inputs` (they feed
        the next stage); constants that later stages need (position ids,
        masks) should ride along inside `inputs` and be returned unchanged.
      stacked_params: pytree whose leaves have leading dim S (= pp size),
        leaf i holding stage i's params.
      inputs_mb: pytree whose leaves have leading dim M (microbatches).
      mesh: the hybrid jax.sharding.Mesh containing `axis`.
      remat: wrap stage_fn in jax.checkpoint (recompute activations in bwd).

    Returns outputs pytree with leading dim M, replicated over `axis`.
    """
    S = mesh.shape[axis]
    if S <= 1:
        # degenerate pipeline: sequential scan over the single stage's params
        def apply_one(mb):
            p = jax.tree.map(lambda a: a[0], stacked_params)
            return stage_fn(p, mb)

        return _vmap_microbatches(apply_one, inputs_mb)

    leaves = jax.tree.leaves(inputs_mb)
    pad = [jnp.zeros((S - 1,) + l.shape[1:], l.dtype) for l in leaves]
    inputs_pad = jax.tree.unflatten(
        jax.tree.structure(inputs_mb),
        [jnp.concatenate([l, p], axis=0) for l, p in zip(leaves, pad)],
    )
    pipelined = _build_pipelined(
        stage_fn, mesh, axis, remat,
        jax.tree.structure(stacked_params), jax.tree.structure(inputs_pad),
    )
    # the shard_map must go through jit: jax 0.9's un-jitted partial-manual
    # spec-matching path (_unmatch) builds full-axes specs and rejects the
    # manual subset — this bites in eager AND inside vjp traces. Under an
    # outer jit the nested jit inlines; eagerly the cache above makes repeat
    # calls with a stable stage_fn hit the compiled program.
    return _jitted(pipelined)(stacked_params, inputs_pad)


# jitted-wrapper caches. Keyed so repeated eager calls with a STABLE stage_fn
# (models memoize theirs, e.g. GPTForCausalLMPipe) hit the jit cache instead
# of retracing per call; fresh-closure callers just pay what they paid before.
_BUILD_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_JIT_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _jitted(pipelined):
    jitted = _JIT_CACHE.get(pipelined)
    if jitted is None:
        jitted = jax.jit(pipelined)
        _JIT_CACHE[pipelined] = jitted
    return jitted


def _build_pipelined(stage_fn, mesh, axis, remat, ptreedef, xtreedef):
    per_fn = _BUILD_CACHE.setdefault(stage_fn, {})
    key = (mesh, axis, remat, ptreedef, xtreedef)
    if key in per_fn:
        return per_fn[key]

    S = mesh.shape[axis]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(params_block, xs_pad):
        # manual over pp only: each leaf arrives as [1, ...] — stage-local slice
        p_local = jax.tree.map(lambda a: a[0], params_block)
        idx = jax.lax.axis_index(axis)
        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        recv0 = jax.tree.map(lambda a: jnp.zeros(a.shape[1:], a.dtype), xs_pad)

        def step(recv, x_t):
            inp = jax.tree.map(lambda a, b: jnp.where(idx == 0, a, b), x_t, recv)
            out = fn(p_local, inp)
            send = jax.tree.map(
                lambda a: jax.lax.ppermute(a, axis, fwd_perm), out
            )
            return send, out

        _, ys = jax.lax.scan(step, recv0, xs_pad)
        # outputs are valid on the last stage at ticks t >= S-1
        outs = jax.tree.map(lambda a: a[S - 1:], ys)
        # replicate the last stage's outputs over pp (everyone else adds zeros)
        mask = (idx == S - 1)
        outs = jax.tree.map(
            lambda a: jax.lax.psum(
                jnp.where(mask, a, jnp.zeros_like(a)), axis
            ),
            outs,
        )
        return outs

    n_p = ptreedef.num_leaves
    n_x = xtreedef.num_leaves
    pspecs = jax.tree.unflatten(ptreedef, [P(axis)] * n_p)
    xspecs = jax.tree.unflatten(xtreedef, [P()] * n_x)
    ospecs = jax.tree.unflatten(xtreedef, [P()] * n_x)
    pipelined = jax.shard_map(
        body, mesh=mesh, in_specs=(pspecs, xspecs), out_specs=ospecs,
        axis_names=frozenset({axis}), check_vma=False,
    )
    per_fn[key] = pipelined
    return pipelined


def _vmap_microbatches(apply_one, inputs_mb):
    """Sequential microbatch application (scan keeps memory flat like the
    pipelined path so pp=1 vs pp>1 behave alike)."""
    def step(carry, mb):
        return carry, apply_one(mb)

    _, ys = jax.lax.scan(step, 0, inputs_mb)
    return ys


def microbatch(tree, num_microbatches):
    """Split leading batch dim B into [M, B/M, ...] on every leaf."""
    def split(a):
        B = a.shape[0]
        if B % num_microbatches != 0:
            raise ValueError(
                f"batch {B} not divisible by {num_microbatches} microbatches"
            )
        return a.reshape((num_microbatches, B // num_microbatches) + a.shape[1:])

    return jax.tree.map(split, tree)


def unmicrobatch(tree):
    """Inverse of microbatch: [M, mb, ...] -> [M*mb, ...]."""
    return jax.tree.map(
        lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), tree
    )
