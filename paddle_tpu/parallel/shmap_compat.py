"""shard_map across jax generations.

The manual-axes pipeline/ring code targets the jax >= 0.6 surface
(`jax.shard_map(..., axis_names=..., check_vma=...)`). Older jaxlibs (0.4.x,
still common on dev containers) only ship `jax.experimental.shard_map` with
the inverse parameterization: `auto=` names the NON-manual axes and
`check_rep` is the replication checker. One adapter keeps every call site on
the new spelling so the compiled schedules don't fork per jax version.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=False):
    """`jax.shard_map` when available, else the 0.4.x experimental form.

    `axis_names` is the MANUAL subset (new-jax semantics); None means every
    mesh axis is manual. `check_vma` maps onto `check_rep` on old jax —
    both gate the replication/varying-manual-axes checker.
    """
    if hasattr(jax, "shard_map"):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(f, mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=bool(check_vma), auto=auto)
