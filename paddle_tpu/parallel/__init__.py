"""Compiled SPMD parallelism primitives.

This package holds the schedules that don't fall out of plain GSPMD
annotation — pipeline parallelism (collective-permute microbatch loop) and
ring attention (paddle_tpu.parallel.ring) — expressed as shard_map programs
over the hybrid mesh built by paddle_tpu.distributed.env.build_mesh.
"""

from .pipeline import pipeline_spmd, stack_pytrees, unstack_leading
from .ring import ring_attention_spmd

__all__ = ["pipeline_spmd", "stack_pytrees", "unstack_leading", "ring_attention_spmd"]
