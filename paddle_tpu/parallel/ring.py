"""Ring attention: context parallelism over the `sep` mesh axis.

The reference snapshot has NO ring attention (SURVEY §5.7 — its long-context
story is the bare SEP mesh axis, segment_parallel.py:26, with attention
resharding left to user model code). This module is the TPU-native upgrade:
sequence-sharded exact attention where K/V blocks rotate around the ICI ring
(`ppermute`) while each device keeps a running online-softmax accumulator —
so peak memory is O(L_local) and the ring hop overlaps with the block GEMMs.

Math (online softmax, identical to flash attention's outer loop):
  per incoming block: m' = max(m, rowmax(S)); acc = acc*e^{m-m'} + e^{S-m'}V;
  l = l*e^{m-m'} + rowsum(e^{S-m'}); out = acc / l.

Causal masking is by GLOBAL chunk position: a device holding query chunk i
attends fully to K/V chunks j<i, diagonally (tril) to j==i, not at all to
j>i. Shapes follow the paddle layout [B, S, H, D], S sharded over `sep`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

_NEG = -1e30


def _local_ring_attention(q, k, v, *, axis, n, causal, scale):
    """shard_map body: q [B, L, H, D], k/v [B, L, Hkv, D] (seq-sharded over
    `axis`). K/V rotate UNEXPANDED — GQA groups broadcast in the einsums, so
    each ppermute hop moves Hkv (not H) heads of bytes."""
    B, L, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv  # query heads per kv head; head order matches jnp.repeat
    idx = jax.lax.axis_index(axis)
    qf = q.astype(jnp.float32).reshape(B, L, Hkv, G, D)
    rows = jnp.arange(L)
    perm = [(i, (i + 1) % n) for i in range(n)]

    @jax.checkpoint
    def block_update(carry_mla, ks, vs, s):
        """Online-softmax update with the K/V block that came from chunk
        (idx - s) mod n."""
        m, l, acc = carry_mla
        src = (idx - s) % n
        logits = jnp.einsum("bihgd,bjhd->bhgij", qf, ks.astype(jnp.float32)) * scale
        if causal:
            grow = idx * L + rows[:, None]   # global query row
            gcol = src * L + rows[None, :]   # global key col
            logits = jnp.where(gcol <= grow, logits, _NEG)
        m_new = jnp.maximum(m, logits.max(-1))
        p = jnp.exp(logits - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhgij,bjhd->bhgid", p, vs.astype(jnp.float32))
        return m_new, l_new, acc_new

    def step(carry, s):
        ks, vs, mla = carry
        # permute FIRST: n-1 hops total, the last block is consumed in place
        ks = jax.lax.ppermute(ks, axis, perm)
        vs = jax.lax.ppermute(vs, axis, perm)
        return (ks, vs, block_update(mla, ks, vs, s)), None

    m0 = jnp.full((B, Hkv, G, L), _NEG, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, L), jnp.float32)
    a0 = jnp.zeros((B, Hkv, G, L, D), jnp.float32)
    mla = block_update((m0, l0, a0), k, v, jnp.int32(0))  # local block, no hop
    if n > 1:
        (_, _, mla), _ = jax.lax.scan(step, (k, v, mla), jnp.arange(1, n))
    m, l, acc = mla
    out = acc / jnp.maximum(l, 1e-30)[..., None]        # [B, Hkv, G, L, D]
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(B, L, H, D)
    return out.astype(q.dtype)


@functools.lru_cache(maxsize=32)
def _build(mesh, axis, causal, scale, jit):
    n = mesh.shape[axis]
    body = functools.partial(_local_ring_attention, axis=axis, n=n,
                             causal=causal, scale=scale)
    spec = P(None, axis, None, None)
    from .shmap_compat import shard_map as _shard_map

    mapped = _shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis}), check_vma=False,
    )
    # through jit for the same partial-manual reason as pipeline_spmd
    return jax.jit(mapped) if jit else mapped


def ring_attention_spmd(q, k, v, mesh, axis="sep", causal=True, scale=None):
    """Raw-array entry: q/k/v [B, S, H, D] with S divisible by mesh.shape[axis]."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    # nested inside another partial-manual shard_map region (e.g. the pp
    # pipeline body): shard_map must be built on the CONTEXT abstract mesh,
    # and without a jit wrapper (the trace is already inside one)
    try:
        ctx = jax.sharding.get_abstract_mesh()
    except Exception:
        ctx = None
    if ctx is not None and not ctx.empty and ctx.manual_axes:
        if axis in ctx.manual_axes:
            raise ValueError(f"ring attention axis {axis!r} is already manual here")
        return _build(ctx, axis, bool(causal), float(scale), False)(q, k, v)
    return _build(mesh, axis, bool(causal), float(scale), True)(q, k, v)


__all__ = ["ring_attention_spmd"]
