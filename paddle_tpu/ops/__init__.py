"""TPU op library: Pallas kernels for the reference's hand-written CUDA
fusion kernels (SURVEY §2.2), plus jnp fallbacks for CPU testing."""
